"""Two-process jax.distributed test of the multi-host worker path.

The reference tests its distributed path with localhost TCP workers
(examples/n-workers.sh, macbeth.sh); the SPMD equivalent spawns two python
processes (1 virtual CPU device each, gloo collectives), process 1 running the
real ``worker`` CLI mode and process 0 driving InferenceEngine in multihost
mode. The root's transcript must match the committed reference-binary golden —
cross-process AND cross-implementation parity in one test.
"""

from __future__ import annotations

import os
import subprocess
import sys
import textwrap
import threading
import time
from pathlib import Path

import numpy as np
import pytest

import golden_assets
from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model

REPO = Path(__file__).resolve().parent.parent
PORT = 19917

ROOT_SCRIPT = textwrap.dedent("""
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    sys.path.insert(0, sys.argv[1])
    from dllama_tpu.parallel.multihost import init_distributed
    init_distributed(sys.argv[2], 2, 0, platform="cpu")
    from dllama_tpu.formats.quants import Q80
    from dllama_tpu.runtime.engine import InferenceEngine
    m, t, prompt, n_gen, seed = (sys.argv[3], sys.argv[4], sys.argv[5],
                                 int(sys.argv[6]), int(sys.argv[7]))
    eng = InferenceEngine(m, t, tp=2, sync_type=Q80, compute_dtype="float32",
                          temperature=0.0, seed=seed, multihost=True)
    ids = eng.tokenizer.encode(prompt, is_start=True)
    drive = ids[:-1] + [0]  # reference CLI seed-token quirk (dllama.cpp:54)
    res = eng.generate(drive, max_tokens=n_gen, stop_on_eos=False)
    eng.tokenizer.reset_decoder()
    pieces = [p if (p := eng.tokenizer.decode(tok)) is not None else "~"
              for tok in res.tokens]
    print("PIECES=" + "|".join(pieces), flush=True)
    # Eval/Sync split over a REAL 2-process mesh: the scratch dispatches
    # mirror to the worker (CTRL_GREEDY) and the tp=2 program carries
    # collectives, so traffic accounting and the measured split must both
    # see sync (engine.measure_split, runtime/profiling.py)
    sp = eng.measure_split()
    print(f"SPLIT= colls={eng.traffic.n_collectives} "
          f"sync_pos={int(sp.sync_ms > 0.0)}", flush=True)
    eng.close()
""")


@pytest.mark.slow
def test_two_process_worker_matches_golden(tmp_path):
    golden = golden_assets.load_golden("llama_q40")
    if golden is None:
        pytest.skip("no golden (run tools/golden_reference.py)")
    m, t, m_sha, _ = golden_assets.build_assets("llama_q40", tmp_path)
    if m_sha != golden["m_sha256"]:
        pytest.skip("assets no longer match golden hashes")

    env = _two_proc_env()
    coord = f"127.0.0.1:{PORT}"
    n_gen = min(8, len(golden["pieces"]))  # keep the 2-process run short

    root = subprocess.Popen(
        [sys.executable, "-c", ROOT_SCRIPT, str(REPO), coord, str(m), str(t),
         golden["prompt"], str(n_gen), str(golden["sampler_seed"])],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    worker = subprocess.Popen(
        [sys.executable, "-m", "dllama_tpu", "worker",
         "--coordinator", coord, "--nprocs", "2", "--procid", "1",
         "--model", str(m), "--tokenizer", str(t), "--tp", "2",
         "--temperature", "0.0"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    try:
        root_out, _ = root.communicate(timeout=600)
        worker_out, _ = worker.communicate(timeout=120)
    except subprocess.TimeoutExpired:
        root.kill()
        worker.kill()
        raise
    root_txt = root_out.decode(errors="replace")
    worker_txt = worker_out.decode(errors="replace")
    assert root.returncode == 0, f"root failed:\n{root_txt[-3000:]}"
    assert worker.returncode == 0, f"worker failed:\n{worker_txt[-3000:]}"

    pieces_line = [ln for ln in root_txt.splitlines() if ln.startswith("PIECES=")]
    assert pieces_line, root_txt[-2000:]
    got = pieces_line[0][len("PIECES="):].split("|")
    assert got == golden["pieces"][:n_gen]
    # the worker must have actually co-executed dispatches
    assert "served" in worker_txt and "served 0" not in worker_txt, worker_txt[-1000:]
    # the eval/sync machinery ran over the real 2-process mesh and the
    # compiled-HLO traffic accounting saw collectives. The TIMED split is
    # asserted only softly (sync_pos may be 0 if all of measure_split's
    # empty-capture retries lose — the intermittent profiler behavior
    # engine.measure_split documents); the deterministic half (colls>0)
    # is the hard assertion.
    split_line = [ln for ln in root_txt.splitlines() if ln.startswith("SPLIT=")]
    assert split_line, root_txt[-2000:]
    import re as _re

    colls = int(_re.search(r"colls=(\d+)", split_line[0]).group(1))
    assert colls > 0, split_line[0]


class _FakeKVClient:
    """Dict-backed stand-in for the coordination-service client."""

    def __init__(self):
        self.store: dict = {}

    def key_value_set_bytes(self, k, v):
        if k in self.store:  # coordination-service semantics
            raise RuntimeError("ALREADY_EXISTS")
        self.store[k] = v

    def key_value_set(self, k, v, allow_overwrite=False):
        if k in self.store and not allow_overwrite:
            raise RuntimeError("ALREADY_EXISTS")
        self.store[k] = v

    def blocking_key_value_get_bytes(self, k, ms):
        if k not in self.store:
            raise RuntimeError("DEADLINE_EXCEEDED: key never arrived")
        return self.store[k]

    def key_value_try_get(self, k):
        if k not in self.store:
            raise RuntimeError("NOT_FOUND")
        return self.store[k]

    def key_value_delete(self, k):
        self.store.pop(k, None)


def test_ctrl_gc_never_outruns_a_silent_worker(monkeypatch):
    """A RESET/STOP storm carries no collective backpressure: with no worker
    watermark published, the root must keep EVERY packet (code-review
    finding: blind lag-based GC deleted keys a stalled worker hadn't read)."""
    from dllama_tpu.parallel import multihost as mh

    import jax

    fake = _FakeKVClient()
    monkeypatch.setattr(mh.ControlCodec, "_client", staticmethod(lambda: fake))
    monkeypatch.setattr(jax, "process_count", lambda: 2)  # 1 silent worker
    codec = mh.ControlCodec(4)
    for _ in range(3 * mh._ACK_EVERY):
        codec.send(codec.encode(mh.CTRL_RESET))
    ctrl_keys = [k for k in fake.store if k.startswith("dllama/ctrl/")]
    assert len(ctrl_keys) == 3 * mh._ACK_EVERY  # nothing GC'd


def test_ctrl_gc_respects_watermark(monkeypatch):
    """With a worker watermark published, only consumed packets are deleted
    and a lagging worker can still read everything above its watermark."""
    import jax

    from dllama_tpu.parallel import multihost as mh

    fake = _FakeKVClient()
    monkeypatch.setattr(mh.ControlCodec, "_client", staticmethod(lambda: fake))
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    root = mh.ControlCodec(4)
    n = 2 * mh._ACK_EVERY
    fake.store["dllama/ack/1"] = str(mh._ACK_EVERY)  # worker consumed 256
    for _ in range(n):
        root.send(root.encode(mh.CTRL_GREEDY, [[7]], 3))
    kept = sorted(int(k.rsplit("/", 1)[1]) for k in fake.store
                  if k.startswith("dllama/ctrl/"))
    assert kept[0] == mh._ACK_EVERY  # everything below the watermark GC'd
    assert kept[-1] == n - 1         # everything above intact

    # a worker resuming at the watermark can replay every surviving packet
    worker = mh.ControlCodec(4)
    worker.seq = mh._ACK_EVERY
    kind, tokens, pos, _ = worker.decode(worker.recv(timeout_s=1))
    assert (kind, tokens.tolist(), pos) == (mh.CTRL_GREEDY, [[7]], 3)


def test_worker_watermark_advances_past_first_publish(monkeypatch):
    """The ack key is OVERWRITTEN on every publish: the coordination service
    raises ALREADY_EXISTS without allow_overwrite=True, which would silently
    freeze the watermark at its first value (code-review finding)."""
    import jax

    from dllama_tpu.parallel import multihost as mh

    fake = _FakeKVClient()
    monkeypatch.setattr(mh.ControlCodec, "_client", staticmethod(lambda: fake))
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    n = 2 * mh._ACK_EVERY
    root = mh.ControlCodec(4)
    worker = mh.ControlCodec(4)
    monkeypatch.setattr(mh.ControlCodec, "_gc", lambda self: None)  # keep keys
    for _ in range(n):
        root.send(root.encode(mh.CTRL_GREEDY, [[1]], 0))
    for _ in range(n):
        worker.recv(timeout_s=1)
    assert fake.store["dllama/ack/1"] == str(n)  # advanced, not frozen at 256


# root that exercises sp=2 ring attention AND fused sampled decode over the
# control channel in one 2-process run (VERDICT round-2 weak #5 coverage)
SP_SAMPLED_ROOT_SCRIPT = textwrap.dedent("""
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    sys.path.insert(0, sys.argv[1])
    from dllama_tpu.parallel.multihost import init_distributed
    init_distributed(sys.argv[2], 2, 0, platform="cpu")
    from dllama_tpu.runtime.engine import InferenceEngine
    eng = InferenceEngine(sys.argv[3], sys.argv[4], tp=1, sp=2,
                          temperature=0.8, topp=0.9, seed=77, multihost=True)
    res = eng.generate([1, 2, 3], max_tokens=6, stop_on_eos=False)
    print("TOKENS=" + ",".join(map(str, res.tokens)), flush=True)
    eng.close()
""")


@pytest.mark.slow
def test_two_process_sp_sampled_decode(tiny_files):
    """2-process run with sp=2 (ring attention across processes) and
    temperature>0 (CTRL_SAMPLED packets carry the coin): root tokens must
    match a single-process engine with the same seed, and the worker must
    co-execute every dispatch."""
    m, t = tiny_files
    from dllama_tpu.runtime.engine import InferenceEngine

    local = InferenceEngine(m, t, tp=1, sp=1, temperature=0.8, topp=0.9,
                            seed=77)
    expect = local.generate([1, 2, 3], max_tokens=6, stop_on_eos=False).tokens

    got, _, wtxt = _run_two_proc_tokens(
        SP_SAMPLED_ROOT_SCRIPT, 3, m, t,
        ("--sp", "2", "--tp", "1", "--buffer-float-type", "f32"))
    assert got == expect
    assert "served" in wtxt and "served 0" not in wtxt, wtxt[-1000:]


# root driving chunked sampled decode over the control channel: one packet
# per K tokens, coins riding the packet
CHUNK_ROOT_SCRIPT = textwrap.dedent("""
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    sys.path.insert(0, sys.argv[1])
    from dllama_tpu.parallel.multihost import init_distributed
    init_distributed(sys.argv[2], 2, 0, platform="cpu")
    from dllama_tpu.runtime.engine import InferenceEngine
    eng = InferenceEngine(sys.argv[3], sys.argv[4], tp=2, temperature=0.8,
                          topp=0.9, seed=31, decode_chunk=4, multihost=True)
    res = eng.generate([1, 2, 3], max_tokens=9, stop_on_eos=False)
    print("TOKENS=" + ",".join(map(str, res.tokens)), flush=True)
    eng.close()
""")


@pytest.mark.slow
def test_two_process_chunked_decode(tiny_files):
    """decode_chunk=4 under multihost: the root ships one packet per chunk
    (coins included), the worker replays the fused K-step program, and the
    tokens equal a single-process decode_chunk=1 run with the same seed."""
    m, t = tiny_files
    from dllama_tpu.runtime.engine import InferenceEngine

    local = InferenceEngine(m, t, tp=1, temperature=0.8, topp=0.9, seed=31)
    expect = local.generate([1, 2, 3], max_tokens=9, stop_on_eos=False).tokens

    got, _, wtxt = _run_two_proc_tokens(
        CHUNK_ROOT_SCRIPT, 5, m, t,
        ("--buffer-float-type", "f32", "--decode-chunk", "4"))
    assert got == expect
    # 9 tokens = 2 chunk packets (4+4) + 1 single-step tail + prefill, so
    # far fewer dispatches than tokens
    served = int(wtxt.split("served ")[-1].split()[0])
    assert served < 9, wtxt[-500:]


@pytest.mark.slow
def test_fingerprint_mismatch_fails_fast_both_sides(tiny_files):
    """Root and worker started with different program-selecting flags
    (weight_mode auto vs bf16) must BOTH exit with the mismatch diagnostic
    instead of deadlocking at the first divergent collective."""
    m, t = tiny_files
    coord = f"127.0.0.1:{PORT + 4}"
    root = _spawn_root(CLEAN_ROOT_SCRIPT, coord, m, t)
    worker = _spawn_worker(coord, m, t, "--weight-mode", "bf16")
    try:
        root_out, _ = root.communicate(timeout=240)
        worker_out, _ = worker.communicate(timeout=60)
    except subprocess.TimeoutExpired:
        root.kill()
        worker.kill()
        raise
    rtxt = root_out.decode(errors="replace")
    wtxt = worker_out.decode(errors="replace")
    assert worker.returncode != 0 and "config mismatch" in wtxt, wtxt[-2500:]
    assert root.returncode != 0 and "config mismatch" in rtxt, rtxt[-2500:]


# ---------------------------------------------------------------------------
# worker resilience (reference: runWorkerApp outer re-serve loop,
# src/app.cpp:299-358 — a worker survives root death)
# ---------------------------------------------------------------------------

# root that generates a few tokens, signals READY, then hangs (the test then
# kills it — "root death mid-run" from the worker's point of view)
HANG_ROOT_SCRIPT = textwrap.dedent("""
    import os, sys, time
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    sys.path.insert(0, sys.argv[1])
    from dllama_tpu.parallel.multihost import init_distributed
    init_distributed(sys.argv[2], 2, 0, platform="cpu")
    from dllama_tpu.formats.quants import Q80
    from dllama_tpu.runtime.engine import InferenceEngine
    eng = InferenceEngine(sys.argv[3], sys.argv[4], tp=2, temperature=0.0,
                          sync_type=Q80, multihost=True)
    eng.generate([1, 2, 3], max_tokens=2, stop_on_eos=False)
    print("READY", flush=True)
    time.sleep(600)
""")

# root that runs a complete generation + clean STOP (for the re-serve cycle)
CLEAN_ROOT_SCRIPT = textwrap.dedent("""
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    sys.path.insert(0, sys.argv[1])
    from dllama_tpu.parallel.multihost import init_distributed
    init_distributed(sys.argv[2], 2, 0, platform="cpu")
    from dllama_tpu.formats.quants import Q80
    from dllama_tpu.runtime.engine import InferenceEngine
    eng = InferenceEngine(sys.argv[3], sys.argv[4], tp=2, temperature=0.0,
                          sync_type=Q80, multihost=True)
    res = eng.generate([1, 2, 3], max_tokens=2, stop_on_eos=False)
    print("TOKENS=" + ",".join(map(str, res.tokens)), flush=True)
    eng.close()
""")


SPEC_ROOT_SCRIPT = textwrap.dedent("""
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    sys.path.insert(0, sys.argv[1])
    from dllama_tpu.parallel.multihost import init_distributed
    init_distributed(sys.argv[2], 2, 0, platform="cpu")
    from dllama_tpu.formats.quants import Q80
    from dllama_tpu.runtime.engine import InferenceEngine
    eng = InferenceEngine(sys.argv[3], sys.argv[4], tp=2, temperature=0.0,
                          sync_type=Q80, multihost=True)
    plain = eng.generate([1, 2, 3, 1, 2], max_tokens=8, stop_on_eos=False)
    eng.close()
    print("PLAIN=" + ",".join(map(str, plain.tokens)), flush=True)
""")

SPEC2_ROOT_SCRIPT = textwrap.dedent("""
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    sys.path.insert(0, sys.argv[1])
    from dllama_tpu.parallel.multihost import init_distributed
    init_distributed(sys.argv[2], 2, 0, platform="cpu")
    from dllama_tpu.formats.quants import Q80
    from dllama_tpu.runtime.engine import InferenceEngine
    eng = InferenceEngine(sys.argv[3], sys.argv[4], tp=2, temperature=0.0,
                          sync_type=Q80, multihost=True, spec_lookup=2)
    spec = eng.generate([1, 2, 3, 1, 2], max_tokens=8, stop_on_eos=False)
    eng.close()
    print("SPEC=" + ",".join(map(str, spec.tokens)), flush=True)
""")


def test_two_process_speculative_decode(tiny_files):
    """Speculative verify packets (CTRL_SPEC_VERIFY) across the control
    channel: the worker co-executes the verify dispatches and the transcript
    matches the plain-greedy 2-process run."""
    m, t = tiny_files
    coord = f"127.0.0.1:{PORT + 6}"
    tokens = {}
    for script, key, extra in [(SPEC_ROOT_SCRIPT, "PLAIN=", ()),
                               (SPEC2_ROOT_SCRIPT, "SPEC=",
                                ("--spec-lookup", "2"))]:
        root = _spawn_root(script, coord, m, t)
        worker = _spawn_worker(coord, m, t, *extra)
        try:
            root_out, _ = root.communicate(timeout=300)
            worker_out, _ = worker.communicate(timeout=120)
        finally:
            for p in (root, worker):
                if p.poll() is None:
                    p.kill()
        rtxt = root_out.decode(errors="replace")
        wtxt = worker_out.decode(errors="replace")
        assert root.returncode == 0, f"root failed:\n{rtxt[-3000:]}"
        assert worker.returncode == 0, f"worker failed:\n{wtxt[-3000:]}"
        line = [ln for ln in rtxt.splitlines() if ln.startswith(key)]
        assert line, rtxt[-2000:]
        tokens[key] = line[0][len(key):]
    assert tokens["PLAIN="] == tokens["SPEC="], tokens


@pytest.fixture(scope="module")
def tiny_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("resilience")
    m, t = d / "m.m", d / "t.t"
    write_tiny_model(m, tiny_header_params(vocab_size=268, seq_len=32),
                     np.random.default_rng(3))
    from dllama_tpu.formats import tfile

    tfile.write_tfile(t, byte_vocab_tokenizer())
    return str(m), str(t)


def _two_proc_env():
    import getpass
    import tempfile

    # persistent compile cache: the 2-process tests re-jit the same tiny
    # programs in every subprocess; cache hits keep the whole multihost suite
    # inside the CI window. User-scoped path: a world-shared one breaks
    # silently (cache disabled) for the second user on a machine.
    cache = os.path.join(tempfile.gettempdir(),
                         f"dllama-xla-cache-{getpass.getuser()}")
    return dict(os.environ, JAX_PLATFORMS="cpu",
                XLA_FLAGS="--xla_force_host_platform_device_count=1",
                JAX_COMPILATION_CACHE_DIR=cache,
                JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="0.5",
                PYTHONPATH=str(REPO) + os.pathsep + os.environ.get("PYTHONPATH", ""))


def _spawn_root(script: str, coord: str, m: str, t: str) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, "-c", script, str(REPO), coord, m, t],
        env=_two_proc_env(), stdout=subprocess.PIPE, stderr=subprocess.STDOUT)


def _spawn_worker(coord: str, m: str, t: str, *extra: str, nprocs: int = 2,
                  procid: int = 1, tp: int = 2) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, "-m", "dllama_tpu", "worker",
         "--coordinator", coord, "--nprocs", str(nprocs),
         "--procid", str(procid),
         "--model", m, "--tokenizer", t, "--tp", str(tp), *extra],
        env=_two_proc_env(), stdout=subprocess.PIPE, stderr=subprocess.STDOUT)


def _run_two_proc_tokens(script, port_offset, m, t, worker_args,
                         root_timeout=420):
    """Spawn root(script) + a worker, wait for both, assert clean exits,
    and return ``(tokens, root_text, worker_text)`` parsed from the root's
    TOKENS= line — the shared protocol of every 2-process decode test."""
    coord = f"127.0.0.1:{PORT + port_offset}"
    root = _spawn_root(script, coord, m, t)
    worker = _spawn_worker(coord, m, t, *worker_args)
    try:
        root_out, _ = root.communicate(timeout=root_timeout)
        worker_out, _ = worker.communicate(timeout=120)
    except subprocess.TimeoutExpired:
        root.kill()
        worker.kill()
        raise
    rtxt = root_out.decode(errors="replace")
    wtxt = worker_out.decode(errors="replace")
    assert root.returncode == 0, f"root failed:\n{rtxt[-3000:]}"
    assert worker.returncode == 0, f"worker failed:\n{wtxt[-3000:]}"
    line = [ln for ln in rtxt.splitlines() if ln.startswith("TOKENS=")]
    assert line, rtxt[-2000:]
    got = [int(x) for x in line[0][len("TOKENS="):].split(",")]
    return got, rtxt, wtxt


def _wait_for_line(proc: subprocess.Popen, needle: str, timeout: float) -> str:
    """Wait until ``needle`` appears on proc's stdout; returns all output so
    far. Reads on a thread so a silent process can't block the test."""
    lines: list = []
    done = threading.Event()

    def reader():
        for raw in proc.stdout:
            lines.append(raw.decode(errors="replace"))
            if needle in lines[-1]:
                done.set()
        done.set()

    threading.Thread(target=reader, daemon=True).start()
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if done.is_set():
            break
        time.sleep(0.2)
    out = "".join(lines)
    assert needle in out, f"never saw {needle!r} in:\n{out[-3000:]}"
    return out


@pytest.mark.slow
def test_worker_exits_within_bound_when_root_dies(tiny_files):
    """Kill the root mid-run: the worker's bounded control-packet wait must
    turn the silent hang into a clean, diagnosed exit (VERDICT round-2 #3)."""
    m, t = tiny_files
    coord = f"127.0.0.1:{PORT + 1}"
    root = _spawn_root(HANG_ROOT_SCRIPT, coord, m, t)
    worker = _spawn_worker(coord, m, t, "--worker-timeout", "20")
    try:
        _wait_for_line(root, "READY", timeout=300)
        root.kill()
        root.wait(timeout=30)
        t0 = time.monotonic()
        worker_out, _ = worker.communicate(timeout=90)  # 20s timeout + slack
        waited = time.monotonic() - t0
    finally:
        for p in (root, worker):
            if p.poll() is None:
                p.kill()
    txt = worker_out.decode(errors="replace")
    # the worker prints the diagnosis and exits rc=3; the jax client's own
    # coordinator-loss abort can win the race — either way the worker is down
    # within the bound with a root-death diagnostic on its output
    assert worker.returncode != 0, txt[-3000:]
    assert ("root presumed dead" in txt or "control channel failed" in txt
            or "JAX distributed service detected fatal errors" in txt
            or "coordination service" in txt), txt[-2000:]
    assert waited < 90


@pytest.mark.slow
def test_worker_reserves_new_root_after_root_death(tiny_files):
    """Full re-serve cycle: root 1 dies, the --worker-reserve worker re-execs,
    joins root 2 at the same coordinator, co-executes its run, and exits
    cleanly on STOP — the reference worker's outer loop behavior."""
    m, t = tiny_files
    coord = f"127.0.0.1:{PORT + 2}"
    root1 = _spawn_root(HANG_ROOT_SCRIPT, coord, m, t)
    worker = _spawn_worker(coord, m, t, "--worker-timeout", "20",
                           "--worker-reserve")
    root2 = None
    try:
        _wait_for_line(root1, "READY", timeout=300)
        root1.kill()
        root1.wait(timeout=30)
        time.sleep(25)  # let the worker hit its timeout and re-exec
        root2 = _spawn_root(CLEAN_ROOT_SCRIPT, coord, m, t)
        root2_out, _ = root2.communicate(timeout=300)
        worker_out, _ = worker.communicate(timeout=120)
    finally:
        for p in (root1, worker, root2):
            if p is not None and p.poll() is None:
                p.kill()
    r2txt = root2_out.decode(errors="replace")
    wtxt = worker_out.decode(errors="replace")
    assert root2.returncode == 0, f"root2 failed:\n{r2txt[-3000:]}"
    assert "TOKENS=" in r2txt
    assert worker.returncode == 0, f"worker rc={worker.returncode}\n{wtxt[-3000:]}"
    assert "re-serving" in wtxt and "worker done" in wtxt, wtxt[-2000:]


FOUR_PROC_ROOT = textwrap.dedent("""
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    sys.path.insert(0, sys.argv[1])
    from dllama_tpu.parallel.multihost import init_distributed
    init_distributed(sys.argv[2], 4, 0, platform="cpu")
    from dllama_tpu.formats.quants import Q80
    from dllama_tpu.runtime.engine import InferenceEngine
    eng = InferenceEngine(sys.argv[3], sys.argv[4], tp=4, temperature=0.0,
                          sync_type=Q80, multihost=True)
    res = eng.generate([1, 2, 3, 1, 2], max_tokens=6, stop_on_eos=False)
    eng.close()
    print("TOKENS4=" + ",".join(map(str, res.tokens)), flush=True)
""")


@pytest.mark.slow
def test_four_process_cluster_matches_solo(tiny_files):
    """A 4-process cluster (tp=4, one device per process) produces the same
    tokens as a solo single-device run — node-count invariance at real
    multi-process scale (the reference's 4-node localhost cluster,
    examples/n-workers.sh)."""
    m, t = tiny_files
    from dllama_tpu.formats.quants import Q80
    from dllama_tpu.runtime.engine import InferenceEngine

    solo = InferenceEngine(m, t, tp=1, temperature=0.0, sync_type=Q80)
    want = solo.generate([1, 2, 3, 1, 2], max_tokens=6,
                         stop_on_eos=False).tokens
    solo.close()

    coord = f"127.0.0.1:{PORT + 9}"
    root = subprocess.Popen(
        [sys.executable, "-c", FOUR_PROC_ROOT, str(REPO), coord, m, t],
        env=_two_proc_env(), stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    workers = [_spawn_worker(coord, m, t, "--worker-timeout", "120",
                             nprocs=4, procid=p, tp=4)
               for p in (1, 2, 3)]
    try:
        out, _ = root.communicate(timeout=600)
        txt = out.decode(errors="replace")
        # assert on the root FIRST: if it crashed, the workers would block
        # until their timeout and bury the root traceback (review finding)
        assert root.returncode == 0, f"root failed:\n{txt[-3000:]}"
        wouts = [w.communicate(timeout=180)[0] for w in workers]
    finally:
        for p in [root, *workers]:
            if p.poll() is None:
                p.kill()
    tok4 = [ln for ln in txt.splitlines() if ln.startswith("TOKENS4=")]
    assert tok4, txt[-2000:]
    for i, w in enumerate(workers):
        wtxt = wouts[i].decode(errors="replace")
        assert w.returncode == 0, f"worker {i + 1} failed:\n{wtxt[-2000:]}"
        assert "served" in wtxt and "served 0" not in wtxt, wtxt[-1000:]
    got = [int(x) for x in tok4[0].split("=")[1].split(",")]
    assert got == want, (got, want)


BATCHED_SCRIPT = textwrap.dedent("""
    import os, sys
    os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                               + sys.argv[8])
    sys.path.insert(0, sys.argv[1])
    multihost = sys.argv[2] != "-"
    nprocs = int(sys.argv[10]) if len(sys.argv) > 10 else 2
    dp = int(sys.argv[11]) if len(sys.argv) > 11 else 1
    if multihost:
        from dllama_tpu.parallel.multihost import init_distributed
        init_distributed(sys.argv[2], nprocs, 0, platform="cpu")
    else:
        # single-host run: re-pin cpu past the axon sitecustomize override
        # (init_distributed does this on the multihost side)
        import jax
        jax.config.update("jax_platforms", "cpu")
    m, t, p1, p2 = sys.argv[3], sys.argv[4], sys.argv[5], sys.argv[6]
    spec = int(sys.argv[7])
    from dllama_tpu.runtime.engine import InferenceEngine
    from dllama_tpu.runtime.serving import BatchedGenerator, Request
    eng = InferenceEngine(m, t, tp=2, dp=dp, compute_dtype="float32",
                          temperature=0.0, seed=3, multihost=multihost,
                          spec_lookup=spec)
    gen = BatchedGenerator(eng, n_slots=2)
    ids1 = eng.tokenizer.encode(p1, is_start=True)
    ids2 = eng.tokenizer.encode(p2, is_start=True)
    r1 = Request(rid=0, prompt_ids=ids1, max_tokens=6, temperature=0.0,
                 stop_on_eos=False)
    r2 = Request(rid=1, prompt_ids=ids2, max_tokens=6, temperature=0.8,
                 topp=0.9, seed=11, stop_on_eos=False)
    gen.admit(r1, 0)
    gen.admit(r2, 1)
    chunk = int(sys.argv[9]) if len(sys.argv) > 9 else 0
    while gen.n_active:
        if chunk > 1:
            gen.step_chunk(chunk)
        else:
            gen.step()
    print("TOK0=" + ",".join(map(str, r1.tokens)), flush=True)
    print("TOK1=" + ",".join(map(str, r2.tokens)), flush=True)
    eng.close()
""")


def _run_batched_cluster(tmp_path, m, t, spec: int = 0, chunk: int = 0):
    """2-process multihost batched serving; returns the two token lists."""
    env = _two_proc_env()
    coord = f"127.0.0.1:{PORT + 4 + spec + 2 * chunk}"
    root = subprocess.Popen(
        [sys.executable, "-c", BATCHED_SCRIPT, str(REPO), coord, str(m),
         str(t), "hello world", "the quick brown", str(spec), "1",
         str(chunk)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    worker_cmd = [sys.executable, "-m", "dllama_tpu", "worker",
                  "--coordinator", coord, "--nprocs", "2", "--procid", "1",
                  "--model", str(m), "--tokenizer", str(t), "--tp", "2",
                  "--temperature", "0.0", "--buffer-float-type", "f32"]
    if spec:
        worker_cmd += ["--spec-lookup", str(spec)]
    worker = subprocess.Popen(worker_cmd, env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT)
    try:
        root_out, _ = root.communicate(timeout=600)
        worker_out, _ = worker.communicate(timeout=120)
    except subprocess.TimeoutExpired:
        root.kill()
        worker.kill()
        raise
    root_txt = root_out.decode(errors="replace")
    worker_txt = worker_out.decode(errors="replace")
    assert root.returncode == 0, f"root failed:\n{root_txt[-3000:]}"
    assert worker.returncode == 0, f"worker failed:\n{worker_txt[-3000:]}"
    toks = {}
    for ln in root_txt.splitlines():
        if ln.startswith("TOK0="):
            toks[0] = ln[5:]
        elif ln.startswith("TOK1="):
            toks[1] = ln[5:]
    assert 0 in toks and 1 in toks, root_txt[-2000:]
    assert "served" in worker_txt and "served 0" not in worker_txt, \
        worker_txt[-1000:]
    return toks


def _run_batched_single(tmp_path, m, t, spec: int = 0, chunk: int = 0):
    """Same request set, single process, tp=2 over 2 virtual devices."""
    env = _two_proc_env()
    proc = subprocess.run(
        [sys.executable, "-c", BATCHED_SCRIPT, str(REPO), "-", str(m),
         str(t), "hello world", "the quick brown", str(spec), "2",
         str(chunk)],
        env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
    toks = {}
    for ln in proc.stdout.splitlines():
        if ln.startswith("TOK0="):
            toks[0] = ln[5:]
        elif ln.startswith("TOK1="):
            toks[1] = ln[5:]
    return toks


@pytest.mark.slow
def test_multihost_batched_serving_matches_single_host(tmp_path):
    """VERDICT r3 next #5: a batched (greedy + sampled mix) request set over
    a 2-process worker mesh reproduces the single-host batched output —
    the CTRL_SRV_* mirror protocol keeps every device-state mutation
    identical across hosts."""
    m, t = tmp_path / "m.m", tmp_path / "t.t"
    rng = np.random.default_rng(88)
    write_tiny_model(m, tiny_header_params(vocab_size=268, seq_len=96), rng)
    from dllama_tpu.formats import tfile
    tfile.write_tfile(t, byte_vocab_tokenizer())

    single = _run_batched_single(tmp_path, m, t)
    multi = _run_batched_cluster(tmp_path, m, t)
    assert multi == single


@pytest.mark.slow
def test_multihost_batched_serving_with_speculation(tmp_path):
    """The ragged verify dispatch (--spec-lookup) also mirrors across hosts."""
    m, t = tmp_path / "m.m", tmp_path / "t.t"
    rng = np.random.default_rng(89)
    write_tiny_model(m, tiny_header_params(vocab_size=268, seq_len=96), rng)
    from dllama_tpu.formats import tfile
    tfile.write_tfile(t, byte_vocab_tokenizer())

    single = _run_batched_single(tmp_path, m, t, spec=2)
    multi = _run_batched_cluster(tmp_path, m, t, spec=2)
    assert multi == single


@pytest.mark.slow
def test_multihost_batched_serving_chunked(tmp_path):
    """K fused ragged steps mirror across hosts (CTRL_SRV_STEP_CHUNK)."""
    m, t = tmp_path / "m.m", tmp_path / "t.t"
    rng = np.random.default_rng(90)
    write_tiny_model(m, tiny_header_params(vocab_size=268, seq_len=96), rng)
    from dllama_tpu.formats import tfile
    tfile.write_tfile(t, byte_vocab_tokenizer())

    single = _run_batched_single(tmp_path, m, t, chunk=3)
    multi = _run_batched_cluster(tmp_path, m, t, chunk=3)
    assert multi == single


@pytest.mark.slow
def test_multihost_api_server_batched_end_to_end(tmp_path):
    """The reference's exact deployment shape (dllama-api.cpp:599-613): the
    HTTP API server runs on the ROOT and drives the whole worker mesh —
    here with --batch-slots continuous batching riding the CTRL_SRV_*
    mirror protocol. Two sequential requests with the same body must get
    identical replies (determinism + the 2nd admission prefix-reuses)."""
    import json as _json
    import urllib.request

    m, t = tmp_path / "m.m", tmp_path / "t.t"
    rng = np.random.default_rng(91)
    write_tiny_model(m, tiny_header_params(vocab_size=268, seq_len=96), rng)
    from dllama_tpu.formats import tfile
    data = byte_vocab_tokenizer()
    data.chat_template = (
        "{% set content = '<|start_header_id|>' + message['role'] + "
        "'<|end_header_id|>\n\n' + message['content'] | trim + "
        "'<|eot_id|>' %}")  # autodetects as llama3 (test_cli's snippet)
    tfile.write_tfile(t, data)

    env = _two_proc_env()
    coord = f"127.0.0.1:{PORT + 30}"
    api_port = PORT + 31
    root = subprocess.Popen(
        [sys.executable, "-m", "dllama_tpu", "api",
         "--coordinator", coord, "--nprocs", "2", "--procid", "0",
         "--model", str(m), "--tokenizer", str(t), "--tp", "2",
         "--buffer-float-type", "f32", "--batch-slots", "2",
         "--port", str(api_port), "--host", "127.0.0.1"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    worker = subprocess.Popen(
        [sys.executable, "-m", "dllama_tpu", "worker",
         "--coordinator", coord, "--nprocs", "2", "--procid", "1",
         "--model", str(m), "--tokenizer", str(t), "--tp", "2",
         "--buffer-float-type", "f32"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    body = _json.dumps({
        "model": "m", "max_tokens": 6, "temperature": 0.0,
        "messages": [{"role": "user", "content": "hello world"}],
    }).encode()

    def post():
        req = urllib.request.Request(
            f"http://127.0.0.1:{api_port}/v1/chat/completions", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            return _json.loads(r.read())

    try:
        reply1 = reply2 = None
        deadline = time.time() + 420
        while time.time() < deadline:
            try:
                reply1 = post()
                break
            except Exception:
                if root.poll() is not None:
                    break
                time.sleep(3)
        assert reply1 is not None, "api never came up"
        reply2 = post()
        c1 = reply1["choices"][0]["message"]["content"]
        c2 = reply2["choices"][0]["message"]["content"]
        assert c1 == c2 and isinstance(c1, str)
    finally:
        import signal as _signal

        root.send_signal(_signal.SIGINT)
        try:
            root_out, _ = root.communicate(timeout=60)
        except subprocess.TimeoutExpired:
            root.kill()
            root_out, _ = root.communicate()
        try:
            worker_out, _ = worker.communicate(timeout=60)
        except subprocess.TimeoutExpired:
            worker.kill()
            worker_out, _ = worker.communicate()
    worker_txt = worker_out.decode(errors="replace")
    assert "served" in worker_txt, worker_txt[-1000:]
    assert root.returncode in (0, -2, 130), root_out.decode(errors="replace")[-2000:]


@pytest.mark.slow
def test_four_process_dp_tp_batched_serving(tiny_files):
    """The flagship serving topology at real multi-process scale: a dp=2 ×
    tp=2 mesh over FOUR processes (one device each), slot pool dp-sharded,
    with the CTRL_SRV_* mirror protocol driving all four. Must reproduce
    the single-process dp×tp run of the same request set."""
    m, t = tiny_files

    env = _two_proc_env()
    args = ["hello world", "the quick brown", "0"]  # p1, p2, spec
    single = subprocess.run(
        [sys.executable, "-c", BATCHED_SCRIPT, str(REPO), "-", m, t,
         *args, "4", "0", "4", "2"], env=env, capture_output=True,
        text=True, timeout=600)
    assert single.returncode == 0, single.stdout[-3000:] + single.stderr[-2000:]
    want = {ln.split("=")[0]: ln.split("=")[1]
            for ln in single.stdout.splitlines() if ln.startswith("TOK")}
    assert set(want) == {"TOK0", "TOK1"}, single.stdout[-2000:]

    coord = f"127.0.0.1:{PORT + 40}"
    root = subprocess.Popen(
        [sys.executable, "-c", BATCHED_SCRIPT, str(REPO), coord, m, t,
         *args, "1", "0", "4", "2"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    workers = [_spawn_worker(coord, m, t, "--dp", "2",
                             "--buffer-float-type", "f32",
                             "--worker-timeout", "120",
                             nprocs=4, procid=p, tp=2)
               for p in (1, 2, 3)]
    try:
        out, _ = root.communicate(timeout=600)
        txt = out.decode(errors="replace")
        assert root.returncode == 0, f"root failed:\n{txt[-3000:]}"
        wouts = [w.communicate(timeout=180)[0] for w in workers]
    finally:
        for p in [root, *workers]:
            if p.poll() is None:
                p.kill()
    got = {ln.split("=")[0]: ln.split("=")[1]
           for ln in txt.splitlines() if ln.startswith("TOK")}
    assert got == want, (got, want)
    for i, w in enumerate(workers):
        wtxt = wouts[i].decode(errors="replace")
        assert w.returncode == 0, f"worker {i + 1} failed:\n{wtxt[-2000:]}"
        assert "served" in wtxt and "served 0" not in wtxt, wtxt[-1000:]


# root driving turbo integer-dot planes over the worker mesh: both
# processes derive identical TurboWeights from the same file + env (the
# quant mode is cluster-fingerprinted), and the s8 dot's int32 partials
# make the tp split exact
TURBO_ROOT_SCRIPT = textwrap.dedent("""
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    os.environ["DLLAMA_TPU_QUANT_MODE"] = "turbo"
    sys.path.insert(0, sys.argv[1])
    from dllama_tpu.parallel.multihost import init_distributed
    init_distributed(sys.argv[2], 2, 0, platform="cpu")
    from dllama_tpu.runtime.engine import InferenceEngine
    eng = InferenceEngine(sys.argv[3], sys.argv[4], tp=2, temperature=0.0,
                          seed=5, multihost=True, compute_dtype="bfloat16")
    from dllama_tpu.ops.turbo import TurboWeight
    assert isinstance(eng.params.layers.wq, TurboWeight)
    res = eng.generate([1, 2, 3], max_tokens=6, stop_on_eos=False)
    print("TOKENS=" + ",".join(map(str, res.tokens)), flush=True)
    eng.close()
""")


@pytest.mark.slow
def test_two_process_turbo_decode(tmp_path, monkeypatch):
    """Turbo composes with multihost: a 2-process tp=2 cluster under the
    knob reproduces the solo turbo transcript (the mode is part of the
    cluster fingerprint; each process derives its own shard)."""
    from dllama_tpu.formats import quants, tfile
    from dllama_tpu.runtime.engine import InferenceEngine

    m, t = tmp_path / "m.m", tmp_path / "t.t"
    write_tiny_model(m, tiny_header_params(vocab_size=268, seq_len=32,
                                           weight_type=quants.Q40),
                     np.random.default_rng(3))
    tfile.write_tfile(t, byte_vocab_tokenizer())
    m, t = str(m), str(t)

    monkeypatch.setenv("DLLAMA_TPU_QUANT_MODE", "turbo")
    local = InferenceEngine(m, t, tp=1, temperature=0.0, seed=5,
                            compute_dtype="bfloat16")
    expect = local.generate([1, 2, 3], max_tokens=6, stop_on_eos=False).tokens

    got, _, _ = _run_two_proc_tokens(
        TURBO_ROOT_SCRIPT, 50, m, t,
        ("--compute-dtype", "bf16", "--buffer-float-type", "f32"))
    assert got == expect


# root driving PIPELINE stages across processes: pp is the DCN-friendly
# axis (per-forward activation traffic independent of depth), so a
# 2-process pp=2 cluster is the distributed deployment it exists for
PP_ROOT_SCRIPT = textwrap.dedent("""
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    sys.path.insert(0, sys.argv[1])
    from dllama_tpu.parallel.multihost import init_distributed
    init_distributed(sys.argv[2], 2, 0, platform="cpu")
    from dllama_tpu.runtime.engine import InferenceEngine
    eng = InferenceEngine(sys.argv[3], sys.argv[4], tp=1, pp=2,
                          temperature=0.0, multihost=True)
    res = eng.generate([1, 2, 3], max_tokens=6, stop_on_eos=False)
    print("TOKENS=" + ",".join(map(str, res.tokens)), flush=True)
    eng.close()
""")


@pytest.mark.slow
def test_two_process_pp_decode(tiny_files):
    """2-process run with pp=2: each process holds ONE pipeline stage (half
    the layer stack + its KV slice) and the activation ppermutes between
    processes — the distributed deployment pp exists for. Root tokens must
    match a single-process engine."""
    m, t = tiny_files
    from dllama_tpu.runtime.engine import InferenceEngine

    local = InferenceEngine(m, t, tp=1, temperature=0.0)
    expect = local.generate([1, 2, 3], max_tokens=6, stop_on_eos=False).tokens
    local.close()

    got, _, wtxt = _run_two_proc_tokens(
        PP_ROOT_SCRIPT, 11, m, t,
        ("--pp", "2", "--tp", "1", "--buffer-float-type", "f32"))
    assert got == expect
    assert "served" in wtxt and "served 0" not in wtxt, wtxt[-1000:]
