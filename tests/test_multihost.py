"""Two-process jax.distributed test of the multi-host worker path.

The reference tests its distributed path with localhost TCP workers
(examples/n-workers.sh, macbeth.sh); the SPMD equivalent spawns two python
processes (1 virtual CPU device each, gloo collectives), process 1 running the
real ``worker`` CLI mode and process 0 driving InferenceEngine in multihost
mode. The root's transcript must match the committed reference-binary golden —
cross-process AND cross-implementation parity in one test.
"""

from __future__ import annotations

import os
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

import golden_assets

REPO = Path(__file__).resolve().parent.parent
PORT = 19917

ROOT_SCRIPT = textwrap.dedent("""
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    sys.path.insert(0, sys.argv[1])
    from dllama_tpu.parallel.multihost import init_distributed
    init_distributed(sys.argv[2], 2, 0, platform="cpu")
    from dllama_tpu.formats.quants import Q80
    from dllama_tpu.runtime.engine import InferenceEngine
    m, t, prompt, n_gen, seed = (sys.argv[3], sys.argv[4], sys.argv[5],
                                 int(sys.argv[6]), int(sys.argv[7]))
    eng = InferenceEngine(m, t, tp=2, sync_type=Q80, compute_dtype="float32",
                          temperature=0.0, seed=seed, multihost=True)
    ids = eng.tokenizer.encode(prompt, is_start=True)
    drive = ids[:-1] + [0]  # reference CLI seed-token quirk (dllama.cpp:54)
    res = eng.generate(drive, max_tokens=n_gen, stop_on_eos=False)
    eng.tokenizer.reset_decoder()
    pieces = [p if (p := eng.tokenizer.decode(tok)) is not None else "~"
              for tok in res.tokens]
    print("PIECES=" + "|".join(pieces), flush=True)
    eng.close()
""")


@pytest.mark.slow
def test_two_process_worker_matches_golden(tmp_path):
    golden = golden_assets.load_golden("llama_q40")
    if golden is None:
        pytest.skip("no golden (run tools/golden_reference.py)")
    m, t, m_sha, _ = golden_assets.build_assets("llama_q40", tmp_path)
    if m_sha != golden["m_sha256"]:
        pytest.skip("assets no longer match golden hashes")

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=1",
               PYTHONPATH=str(REPO) + os.pathsep + os.environ.get("PYTHONPATH", ""))
    coord = f"127.0.0.1:{PORT}"
    n_gen = min(8, len(golden["pieces"]))  # keep the 2-process run short

    root = subprocess.Popen(
        [sys.executable, "-c", ROOT_SCRIPT, str(REPO), coord, str(m), str(t),
         golden["prompt"], str(n_gen), str(golden["sampler_seed"])],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    worker = subprocess.Popen(
        [sys.executable, "-m", "dllama_tpu", "worker",
         "--coordinator", coord, "--nprocs", "2", "--procid", "1",
         "--model", str(m), "--tokenizer", str(t), "--tp", "2",
         "--temperature", "0.0"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    try:
        root_out, _ = root.communicate(timeout=600)
        worker_out, _ = worker.communicate(timeout=120)
    except subprocess.TimeoutExpired:
        root.kill()
        worker.kill()
        raise
    root_txt = root_out.decode(errors="replace")
    worker_txt = worker_out.decode(errors="replace")
    assert root.returncode == 0, f"root failed:\n{root_txt[-3000:]}"
    assert worker.returncode == 0, f"worker failed:\n{worker_txt[-3000:]}"

    pieces_line = [ln for ln in root_txt.splitlines() if ln.startswith("PIECES=")]
    assert pieces_line, root_txt[-2000:]
    got = pieces_line[0][len("PIECES="):].split("|")
    assert got == golden["pieces"][:n_gen]
    # the worker must have actually co-executed dispatches
    assert "served" in worker_txt and "served 0" not in worker_txt, worker_txt[-1000:]
