"""Seeded property sweeps — cheap randomized coverage of invariants the
hand-picked cases can't span (deterministic seeds, so failures reproduce).

The reference relies on exactly these invariance properties without testing
them broadly: chunk-size-invariant prefill (positions-as-batch semantics,
SURVEY §4) and byte-exact tokenizer round-trips (tokenizer-test.cpp)."""

import os

import numpy as np
import pytest

from dllama_tpu.formats import tfile
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.tokenizer.bpe import Tokenizer

from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model

FIXTURE_T = os.path.join(os.path.dirname(__file__), "goldens", "fixture_bpe.t")


@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("fuzz")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(99)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=192), rng)
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    return str(mpath), str(tpath)


def test_prefill_bucketing_invariant_over_random_lengths(model_files):
    """Adaptive 128/64/32 bucketing must produce the same tokens as pinned
    tiny chunks for prompts of ARBITRARY length — the boundary cases (just
    below/above a bucket edge, tail of 1) are where off-by-ones live."""
    m, t = model_files
    adaptive = InferenceEngine(m, t, temperature=0.0, seed=7)
    pinned = InferenceEngine(m, t, temperature=0.0, seed=7, n_batches=5)
    rng = np.random.default_rng(123)
    lengths = [2, 31, 32, 33, 63, 64, 65, 127, 128, 129, 150]
    for n in lengths:
        prompt = [int(x) for x in rng.integers(4, 260, size=n)]
        ra = adaptive.generate(prompt, 3, stop_on_eos=False)
        rp = pinned.generate(prompt, 3, stop_on_eos=False)
        assert ra.tokens == rp.tokens, n
        adaptive.reset()
        pinned.reset()


def test_fixture_tokenizer_roundtrip_fuzz():
    """Random multilingual strings through the production-shape BPE fixture:
    encode→streaming-decode must reproduce the input byte-for-byte."""
    tok = Tokenizer.load(FIXTURE_T)
    rng = np.random.default_rng(7)
    pools = [
        "abcdefghijklmnopqrstuvwxyz THE MODEL tokenize 0123456789.,!?-",
        "éüßñçàøæœ€αβγδεζКНИГАшщъыь",
        "素早い茶色の狐犬を飛び越える中文文本日本語",
        "🦊🐕🎉🚀👩‍💻",
    ]
    for trial in range(60):
        pool = pools[trial % len(pools)]
        chars = [pool[i] for i in rng.integers(0, len(pool),
                                               size=rng.integers(1, 80))]
        s = "".join(chars)
        ids = tok.encode(s, is_start=False)
        tok.reset_decoder()
        rt = "".join(p for t in ids if (p := tok.decode(t)) is not None)
        assert rt == s, repr(s)


def test_chat_body_validation_fuzz_rejects_cleanly():
    """Randomly-typed /v1/chat/completions bodies through the schema
    check: the ONLY acceptable failure is ValueError (HTTP 400). Any
    other exception is the 500-from-a-typed-field bug class the
    fault-tolerance contract forbids (ISSUE 2 satellite)."""
    from dllama_tpu.serve.api import _validate_body

    rng = np.random.default_rng(5)
    junk = [None, True, False, 0, -1, 7, 3.5, -0.1, float("nan"),
            float("inf"), 1e308, "x", "🦊", b"bytes", [], [1, "a"], {},
            {"a": 1}, [{"role": 1}], [{"content": []}]]
    keys = ["messages", "max_tokens", "temperature", "top_p", "seed",
            "timeout", "stop", "stream", "unknown_extra"]
    n_ok = n_rejected = 0
    for _ in range(300):
        body = {}
        for k in keys:
            if rng.random() < 0.4:
                body[k] = junk[int(rng.integers(0, len(junk)))]
        if rng.random() < 0.4:  # sometimes a valid messages list rides along
            body["messages"] = [{"role": "user", "content": "hi"}]
        try:
            _validate_body(body)
            n_ok += 1
        except ValueError:
            n_rejected += 1  # 400: the contract
    assert n_ok + n_rejected == 300
    assert n_rejected > 0  # the sweep actually exercised rejections


def test_native_python_merge_fuzz_on_fixture():
    """Random byte soup (valid UTF-8) through native vs Python mergers."""
    from dllama_tpu import native

    if not native.available():
        pytest.skip("native library unavailable")
    tok_nat = Tokenizer.load(FIXTURE_T)
    tok_py = Tokenizer.load(FIXTURE_T)
    tok_py._bpe_native = False
    rng = np.random.default_rng(11)
    corpus = ("the model writes tokens Résumé café Быстрая 素早い 🦊 "
              "def f(x):\n  return x  # 42\n")
    for _ in range(40):
        i = int(rng.integers(0, len(corpus) - 1))
        j = int(rng.integers(i + 1, len(corpus) + 1))
        s = corpus[i:j] * int(rng.integers(1, 4))
        assert tok_nat.encode(s, is_start=False) == \
            tok_py.encode(s, is_start=False), repr(s)
