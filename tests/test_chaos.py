"""Chaos tier: fault-injection driven coverage of the serving stack's
failure semantics (runtime/failpoints.py + the ISSUE-2 fault-tolerance
layer). Every behavior README's "Failure semantics" promises is DRIVEN
here, not assumed: scheduler crash → fail-all → supervised restart →
unready; load shedding (429); deadlines (queued and in-flight); graceful
drain (/readyz flip + explicit failure of the remainder); SSE client
disconnect accounting — all asserted through the telemetry registry.

Scheduler-level tests drive ``_tick`` by hand (``_start_thread=False``)
where determinism matters; thread-level tests use the real loop."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from dllama_tpu.formats import mfile, tfile
from dllama_tpu.runtime import failpoints as fp
from dllama_tpu.runtime import telemetry as tm
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.runtime.serving import (BatchScheduler, HbmAdmissionError,
                                        QueueFullError,
                                        SchedulerUnavailableError)
from dllama_tpu.runtime.weights import (WeightIntegrityError, WeightLoadError)

from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model


@pytest.fixture(autouse=True)
def _clean_failpoints():
    """A leaked armed failpoint would crash unrelated schedulers."""
    fp.registry().clear()
    yield
    fp.registry().clear()


@pytest.fixture(scope="module")
def engine(tmp_path_factory):
    d = tmp_path_factory.mktemp("chaos")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(17)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96), rng)
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    return InferenceEngine(str(mpath), str(tpath), tp=1, temperature=0.0,
                           seed=3)


def _enc(engine, text="hello"):
    return engine.tokenizer.encode(text, is_start=True)


# -- failpoint registry ------------------------------------------------------


def test_failpoint_registry_arm_fire_times():
    reg = fp.registry()
    fired = tm.registry().counter(tm.FAILPOINTS_FIRED)
    before = fired.total(name="chaos.x")
    reg.arm("chaos.x", "raise", times=2)
    for _ in range(2):
        with pytest.raises(fp.FailpointError):
            reg.fire("chaos.x")
    reg.fire("chaos.x")  # exhausted: no-op
    assert reg.fired("chaos.x") == 2
    assert fired.total(name="chaos.x") == before + 2
    assert not reg.armed("chaos.x")


def test_short_read_and_sleep_actions():
    reg = fp.registry()
    reg.arm("x", "short_read")
    with pytest.raises(fp.ShortReadError) as e:
        reg.fire("x")
    assert isinstance(e.value, OSError)  # classified transient by the loader
    reg.arm("y", "sleep", times=1, delay_s=0.15)
    t0 = time.monotonic()
    reg.fire("y")  # blocks, does NOT raise
    assert time.monotonic() - t0 >= 0.14
    assert reg.fired("y") == 1 and not reg.armed("y")
    reg.fire("y")  # exhausted: no-op, no sleep
    reg.clear()


def test_failpoint_actions_and_spec_grammar(monkeypatch):
    reg = fp.registry()
    reg.configure("a:broken_pipe,b:conn_reset:1, c:oserror")
    with pytest.raises(BrokenPipeError):
        reg.fire("a")
    with pytest.raises(ConnectionResetError):
        reg.fire("b")
    reg.fire("b")  # times=1: disarmed
    with pytest.raises(OSError):
        reg.fire("c")
    with pytest.raises(ValueError, match="unknown failpoint action"):
        reg.arm("x", "explode")
    with pytest.raises(ValueError, match="bad failpoint spec"):
        reg.configure("justaname")
    reg.clear()
    monkeypatch.setenv("DLLAMA_FAILPOINTS", "step:raise")
    assert fp.configure_from_env()
    assert reg.armed("step")
    reg.clear()
    monkeypatch.delenv("DLLAMA_FAILPOINTS")
    assert not fp.configure_from_env()


# -- satellite: close() must not leak waiters --------------------------------


def test_close_fails_queued_waiters_instead_of_hanging(engine):
    sched = BatchScheduler(engine, n_slots=2, _start_thread=False)
    reqs = [sched.submit(_enc(engine), 8) for _ in range(3)]
    sched.close()
    for r in reqs:
        assert r.done.is_set()  # the old close() left these waiting forever
        assert r.error is not None and "shutting down" in r.error
    with pytest.raises(SchedulerUnavailableError):
        sched.submit(_enc(engine), 4)


def test_drain_close_lets_active_work_finish(engine):
    sched = BatchScheduler(engine, n_slots=2)
    req = sched.submit(_enc(engine), 4, stop_on_eos=False)
    sched.close(drain_s=60.0)
    assert req.done.is_set()
    assert req.error is None, req.error  # drained, not failed
    assert len(req.tokens) == 4


def test_drain_emits_flight_lifecycle_bracket(engine):
    """ISSUE-12 satellite: begin_drain/close leave a drain_begin →
    drain_end pair in the flight recorder's lifecycle ring (not just a
    stdout banner), so a postmortem can classify a death as a drain, not
    a crash — including whether the drain finished clean."""
    from dllama_tpu.runtime import flightrec

    flightrec.recorder().reset()
    try:
        sched = BatchScheduler(engine, n_slots=2)
        req = sched.submit(_enc(engine), 4, stop_on_eos=False)
        sched.begin_drain()
        sched.close(drain_s=60.0)
        assert req.done.is_set() and req.error is None
        events = flightrec.recorder().snapshot()["events"]
        begins = [e for e in events if e["event"] == "drain_begin"]
        ends = [e for e in events if e["event"] == "drain_end"]
        assert len(begins) == 1  # idempotent: close()'s begin_drain is a no-op
        assert len(ends) == 1
        assert ends[0]["reason"] == "clean"  # active work drained, not failed
        assert ends[0]["n_failed"] == 0
        # the pair brackets: begin strictly before end in ring order
        assert events.index(begins[0]) < events.index(ends[0])
        # a second close() must not double-close the bracket
        sched.close()
        events = flightrec.recorder().snapshot()["events"]
        assert len([e for e in events if e["event"] == "drain_end"]) == 1
    finally:
        flightrec.recorder().reset()


# -- load shedding -----------------------------------------------------------


def test_submit_sheds_beyond_max_queue(engine):
    shed = tm.registry().counter(tm.REQUESTS_SHED)
    before = shed.total()
    sched = BatchScheduler(engine, n_slots=2, max_queue=2,
                           _start_thread=False)
    try:
        sched.submit(_enc(engine), 4)
        sched.submit(_enc(engine), 4)
        assert sched.readiness() == (False, "queue full (shedding)",
                                     "queue_full")
        with pytest.raises(QueueFullError, match="queue full"):
            sched.submit(_enc(engine), 4)
        assert shed.total() == before + 1
    finally:
        sched.close()


# -- deadlines ---------------------------------------------------------------


def test_queued_request_past_deadline_fails_with_timeout(engine):
    timeouts = tm.registry().counter(tm.REQUEST_TIMEOUTS)
    before = timeouts.total()
    sched = BatchScheduler(engine, n_slots=2, _start_thread=False)
    try:
        req = sched.submit(_enc(engine), 8, timeout_s=1e-6)
        time.sleep(0.002)  # deadline long past
        sched._tick()
        assert req.done.is_set()
        assert req.timed_out and not req.tokens
        assert timeouts.total() == before + 1
    finally:
        sched.close()


def test_inflight_deadline_cancels_at_next_step_boundary(engine):
    timeouts = tm.registry().counter(tm.REQUEST_TIMEOUTS)
    before = timeouts.total()
    sched = BatchScheduler(engine, n_slots=2, _start_thread=False)
    try:
        req = sched.submit(_enc(engine), 50, stop_on_eos=False,
                           timeout_s=3600.0)
        for _ in range(20):
            sched._tick()
            if len(req.tokens) >= 2:
                break
        assert len(req.tokens) >= 2 and not req.done.is_set()
        n_before = len(req.tokens)
        req.deadline_ns = tm.now_ns() - 1  # deadline just expired
        sched._tick()  # cancel marked + slot retired this boundary
        assert req.done.is_set()
        assert req.timed_out
        assert len(req.tokens) == n_before  # partial output preserved
        assert timeouts.total() == before + 1
    finally:
        sched.close()


# -- scheduler supervision ---------------------------------------------------


def test_scheduler_crash_fails_all_pending_then_restarts(engine):
    crashes = tm.registry().counter(tm.SCHEDULER_CRASHES)
    restarts = tm.registry().counter(tm.SCHEDULER_RESTARTS)
    c0, r0 = crashes.total(), restarts.total()
    fp.arm("step", "raise", times=1)
    sched = BatchScheduler(engine, n_slots=2)
    try:
        reqs = [sched.submit(_enc(engine, p), 30, stop_on_eos=False)
                for p in ("hello", " world")]
        for r in reqs:
            assert r.done.wait(timeout=60)  # NOT a hung done.wait()
            assert r.error is not None and "scheduler crashed" in r.error
            assert "failpoint" in r.error
            assert r.server_error  # maps to HTTP 503, not 400
        assert crashes.total() == c0 + 1
        assert restarts.total() == r0 + 1
        # the restarted loop serves fresh work on a fresh pool
        req = sched.submit(_enc(engine), 4, stop_on_eos=False)
        assert req.done.wait(timeout=60)
        assert req.error is None and len(req.tokens) == 4
        assert sched.readiness()[0]
    finally:
        sched.close()


def test_scheduler_crash_budget_exhausted_marks_unready(engine):
    fp.arm("step", "raise")  # every dispatch crashes
    sched = BatchScheduler(engine, n_slots=2, max_restarts=1)
    try:
        r1 = sched.submit(_enc(engine), 8)
        assert r1.done.wait(timeout=60) and r1.error
        # crash #1 consumed the whole restart budget's headroom; the next
        # crash (still armed) exceeds it
        deadline = time.monotonic() + 60
        while sched.is_alive() and time.monotonic() < deadline:
            try:
                r = sched.submit(_enc(engine), 8)
            except SchedulerUnavailableError:
                break
            assert r.done.wait(timeout=60)
        fp.registry().clear()
        ready, reason, code = sched.readiness()
        assert not ready and "crash" in reason
        assert code == "crashed"  # the machine-readable /readyz code
        with pytest.raises(SchedulerUnavailableError):
            sched.submit(_enc(engine), 4)
    finally:
        fp.registry().clear()
        sched.close()


def test_admit_failpoint_rejects_one_request_without_crashing(engine):
    crashes = tm.registry().counter(tm.SCHEDULER_CRASHES)
    c0 = crashes.total()
    fp.arm("admit", "raise", times=1)
    sched = BatchScheduler(engine, n_slots=2)
    try:
        bad = sched.submit(_enc(engine), 4)
        assert bad.done.wait(timeout=60)
        assert bad.error is not None and "FailpointError" in bad.error
        ok = sched.submit(_enc(engine), 4, stop_on_eos=False)
        assert ok.done.wait(timeout=60)
        assert ok.error is None and len(ok.tokens) == 4
        assert crashes.total() == c0  # a rejected admit is not a crash
    finally:
        sched.close()


# -- HTTP layer: drain/readyz, shed, timeout, client disconnect -------------


@pytest.fixture(scope="module")
def batched_server(tmp_path_factory):
    from http.server import ThreadingHTTPServer

    from dllama_tpu.serve.api import BatchedApiState, make_handler

    d = tmp_path_factory.mktemp("chaos_api")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(9)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96), rng)
    td = byte_vocab_tokenizer()
    td.chat_template = "<|start_header_id|>"  # detected as llama3
    tfile.write_tfile(tpath, td)
    eng = InferenceEngine(str(mpath), str(tpath), temperature=0.0, seed=3)
    state = BatchedApiState(eng, n_slots=2, max_queue=4)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(state))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{port}", state
    httpd.shutdown()
    state.close()
    eng.close()


def _post(url, payload, timeout=120):
    req = urllib.request.Request(
        url + "/v1/chat/completions",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=timeout)


def test_healthz_and_readyz_in_normal_operation(batched_server):
    url, _ = batched_server
    for path in ("/healthz", "/readyz"):
        with urllib.request.urlopen(url + path, timeout=30) as r:
            assert r.status == 200
            assert json.loads(r.read())["status"] == "ok"


def test_readyz_flips_to_503_during_drain(batched_server):
    url, state = batched_server
    draining = tm.registry().gauge(tm.SERVER_DRAINING)
    state.begin_drain()
    try:
        assert draining.value() == 1
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(url + "/readyz", timeout=30)
        assert e.value.code == 503
        # machine-readable body + the shared Retry-After (the 429 shed
        # path's header, unified via api.backpressure_headers)
        assert e.value.headers["Retry-After"] is not None
        body = json.loads(e.value.read())
        assert body["reason"] == "draining"
        assert body["code"] == "draining"
        # liveness stays green: a draining pod must not be restarted
        with urllib.request.urlopen(url + "/healthz", timeout=30) as r:
            assert r.status == 200
        # admissions are refused with an explicit 503
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(url, {"messages": [{"role": "user", "content": "hi"}],
                        "max_tokens": 3})
        assert e.value.code == 503
    finally:
        # un-drain: this server fixture is shared with the tests below
        state.sched._draining = False
        draining.set(0)
    with urllib.request.urlopen(url + "/readyz", timeout=30) as r:
        assert r.status == 200


def test_http_shed_returns_429_with_retry_after(batched_server, monkeypatch):
    url, state = batched_server

    def full(*a, **kw):
        raise QueueFullError("queue full (3 waiting, --max-queue 3)")

    monkeypatch.setattr(state.sched, "submit", full)
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(url, {"messages": [{"role": "user", "content": "hi"}],
                    "max_tokens": 3})
    assert e.value.code == 429
    assert e.value.headers["Retry-After"] is not None
    assert "queue full" in json.loads(e.value.read())["error"]


def test_http_request_timeout_bounded_and_counted(batched_server):
    url, _ = batched_server
    timeouts = tm.registry().counter(tm.REQUEST_TIMEOUTS)
    before = timeouts.total()
    t0 = time.monotonic()
    try:
        with _post(url, {"messages": [{"role": "user", "content": "hello"}],
                         "max_tokens": 80, "timeout": 0.02}) as r:
            out = json.loads(r.read())
        # deadline hit mid-generation: partial output, explicit reason
        assert out["choices"][0]["finish_reason"] == "timeout"
    except urllib.error.HTTPError as e:
        assert e.code == 408  # deadline expired before any output
    # "within timeout + one step": generous CI bound, but decisively below
    # an 80-token run that would otherwise be free to take forever
    assert time.monotonic() - t0 < 60
    assert timeouts.total() >= before + 1


def test_sse_client_disconnect_counted_not_500(batched_server):
    url, state = batched_server
    http = tm.registry().counter(tm.HTTP_REQUESTS)
    route = "/v1/chat/completions"
    dc0 = http.total(route=route, status="client_disconnect")
    e500 = http.total(route=route, status="500")
    fp.arm("emit", "broken_pipe", times=1)
    try:
        with _post(url, {"messages": [{"role": "user", "content": "hello"}],
                         "max_tokens": 6, "stream": True}, timeout=60) as r:
            raw = r.read().decode()
        assert "[DONE]" not in raw
    except (urllib.error.URLError, ConnectionError, OSError):
        pass  # server aborted before/while streaming: expected
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and \
            http.total(route=route, status="client_disconnect") == dc0:
        time.sleep(0.05)
    assert http.total(route=route, status="client_disconnect") == dc0 + 1
    assert http.total(route=route, status="500") == e500  # NOT a 500
    # the slot was cancelled and reclaimed: a fresh request still serves
    with _post(url, {"messages": [{"role": "user", "content": "again"}],
                     "max_tokens": 3}) as r:
        assert json.loads(r.read())["usage"]["completion_tokens"] >= 1


# -- numerics tripwire (ISSUE 5): logits failpoint → count / fail-fast -------


def test_logits_failpoint_counts_without_failfast(engine):
    """Armed `logits:nonfinite` → one batched dispatch's logits are
    poisoned in-graph; default mode counts the tripwire event
    (site=batch) and still emits the (garbage) tokens — observable, not
    behavior-changing."""
    nf = tm.registry().counter(tm.NONFINITE)
    fired = tm.registry().counter(tm.FAILPOINTS_FIRED)
    b0, f0 = nf.total(site="batch"), fired.total(name="logits")
    sched = BatchScheduler(engine, n_slots=2)
    try:
        fp.arm("logits", "nonfinite", times=1)
        req = sched.submit(_enc(engine), 4, stop_on_eos=False)
        assert req.done.wait(timeout=60)
        assert req.error is None and len(req.tokens) == 4
        assert nf.total(site="batch") == b0 + 1
        assert fired.total(name="logits") == f0 + 1
    finally:
        fp.registry().clear()
        sched.close()


def test_logits_failfast_fails_poisoned_request_503_shaped(tmp_path):
    """Fail-fast armed → the poisoned request dies with an explicit
    numerics error (server_error ⇒ HTTP 503-shaped) instead of garbage
    tokens, the slot is reclaimed, and the next clean request serves."""
    from dllama_tpu.runtime import numerics

    nf = tm.registry().counter(tm.NONFINITE)
    b0 = nf.total(site="batch")
    mpath, tpath = _fresh_model(tmp_path)
    eng = InferenceEngine(mpath, tpath, tp=1, temperature=0.0, seed=3,
                          numerics_failfast=True)
    sched = BatchScheduler(eng, n_slots=2)
    try:
        fp.arm("logits", "nonfinite", times=1)
        req = sched.submit(_enc(eng), 8, stop_on_eos=False)
        assert req.done.wait(timeout=60)
        assert req.error is not None and "non-finite" in req.error
        assert "site=batch" in req.error
        assert req.server_error  # maps to HTTP 503, not 400
        assert nf.total(site="batch") == b0 + 1
        # mid-request tripwires fail ONE request, not the scheduler
        ok = sched.submit(_enc(eng), 4, stop_on_eos=False)
        assert ok.done.wait(timeout=60)
        assert ok.error is None and len(ok.tokens) == 4
        assert isinstance(numerics.nonfinite_error("batch", 1),
                          numerics.NumericsError)
    finally:
        fp.registry().clear()
        sched.close()
        eng.close()


# -- runtime hardening (ISSUE 4): loader retries, corruption, watchdog, HBM --


def _fresh_model(tmp_path, seed=21, manifest=False):
    mpath, tpath = tmp_path / "m.m", tmp_path / "t.t"
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96),
                     np.random.default_rng(seed))
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    if manifest:
        mfile.write_manifest(mpath)
    return str(mpath), str(tpath)


def test_loader_retries_transient_reads_then_succeeds(tmp_path):
    """Armed load_read (transient, bounded times) → the loader retries at
    the read-callback level and the load completes; both the retry counter
    and the failpoint counter advance."""
    retries = tm.registry().counter(tm.WEIGHT_IO_RETRIES)
    fired = tm.registry().counter(tm.FAILPOINTS_FIRED)
    r0, f0 = retries.total(), fired.total(name="load_read")
    mpath, tpath = _fresh_model(tmp_path)
    fp.arm("load_read", "short_read", times=2)
    eng = InferenceEngine(mpath, tpath, temperature=0.0, seed=3)
    try:
        assert retries.total() == r0 + 2
        assert fired.total(name="load_read") == f0 + 2
        assert not fp.registry().armed("load_read")  # consumed, recovered
        # the engine is fully usable after the retried load
        logits, _ = eng.prefill(_enc(eng)[:2])
        assert np.all(np.isfinite(np.asarray(logits)))
    finally:
        eng.close()


def test_loader_retry_exhaustion_fails_atomically_naming_site(tmp_path,
                                                              monkeypatch):
    """Persistently armed load_read → bounded retries, then a clean,
    ATOMIC load failure: the error names the site, the engine never comes
    into existence, and its mmap/watchdog are torn down."""
    retries = tm.registry().counter(tm.WEIGHT_IO_RETRIES)
    r0 = retries.total()
    mpath, tpath = _fresh_model(tmp_path)
    opened = []
    orig_open = mfile.ModelFile.open.__func__

    def spy_open(cls, *a, **kw):
        mf = orig_open(cls, *a, **kw)
        opened.append(mf)
        return mf

    monkeypatch.setattr(mfile.ModelFile, "open", classmethod(spy_open))
    fp.arm("load_read", "oserror")
    with pytest.raises(WeightLoadError, match="load_read"):
        InferenceEngine(mpath, tpath, temperature=0.0, seed=3)
    fp.registry().clear()
    assert retries.total() == r0 + 3  # the loader's bounded retry budget
    assert opened and opened[-1]._mm is None  # teardown closed the mmap
    # atomic: nothing half-initialized lingers — a fresh engine just works
    eng = InferenceEngine(mpath, tpath, temperature=0.0, seed=3)
    try:
        assert len(eng.tokenizer.encode("ok")) > 0
    finally:
        eng.close()


def test_bit_flipped_tensor_fails_load_naming_tensor(tmp_path, monkeypatch):
    """A single flipped byte in one tensor of a manifested model → the
    load fails with WeightIntegrityError naming exactly that tensor, the
    corruption counter advances, and the failure is atomic."""
    corrupt = tm.registry().counter(tm.LOAD_CORRUPTION)
    c0 = corrupt.total()
    mpath, tpath = _fresh_model(tmp_path, manifest=True)
    with mfile.ModelFile.open(mpath) as mf:
        rec = mf.tensors["block_matmul_w2.1"]
    with open(mpath, "r+b") as f:
        f.seek(rec.offset + 5)
        b = f.read(1)
        f.seek(rec.offset + 5)
        f.write(bytes([b[0] ^ 0x10]))
    opened = []
    orig_open = mfile.ModelFile.open.__func__

    def spy_open(cls, *a, **kw):
        mf = orig_open(cls, *a, **kw)
        opened.append(mf)
        return mf

    monkeypatch.setattr(mfile.ModelFile, "open", classmethod(spy_open))
    with pytest.raises(WeightIntegrityError,
                       match=r"block_matmul_w2\.1.*corrupt|corrupt.*block_matmul_w2\.1"):
        InferenceEngine(mpath, tpath, temperature=0.0, seed=3)
    assert corrupt.total() == c0 + 1
    assert opened and opened[-1]._mm is None


def test_watchdog_trips_within_budget_and_routes_to_supervision(tmp_path):
    """Armed step_hang (sleep) → the watchdog trips within its budget
    (well before the injected hang would end), the in-flight request
    fails 503-shaped, /readyz-backing readiness flips, submits are
    refused, and the stall counter advances."""
    stalls = tm.registry().counter(tm.WATCHDOG_STALLS)
    s0 = stalls.total()
    mpath, tpath = _fresh_model(tmp_path)
    eng = InferenceEngine(mpath, tpath, temperature=0.0, seed=3)
    # tight test budget; production defaults are generous (floor 120s)
    eng.watchdog.min_budget_s = 0.3
    eng.watchdog.margin = 1.0
    eng.watchdog.min_samples = 2
    sched = BatchScheduler(eng, n_slots=2)
    try:
        warm = sched.submit(_enc(eng), 4, stop_on_eos=False)
        assert warm.done.wait(timeout=120) and warm.error is None
        assert eng.watchdog.budget_s() is not None  # EWMA trained, armed
        hang_s = 8.0
        fp.arm("step_hang", "sleep", times=1, delay_s=hang_s)
        t0 = time.monotonic()
        req = sched.submit(_enc(eng, "stall me"), 50, stop_on_eos=False)
        assert req.done.wait(timeout=60)
        elapsed = time.monotonic() - t0
        # tripped within budget: the waiter was failed while the dispatch
        # was still wedged, not after the hang resolved
        assert elapsed < hang_s - 1.0, elapsed
        assert req.error is not None and "watchdog" in req.error
        assert req.server_error  # maps to HTTP 503
        assert stalls.total() == s0 + 1
        ready, reason, code = sched.readiness()
        assert not ready and "watchdog" in reason
        assert code == "crashed"  # a wedged dispatch is crash-shaped
        with pytest.raises(SchedulerUnavailableError):
            sched.submit(_enc(eng), 4)
    finally:
        fp.registry().clear()
        sched.close()
        eng.close()


def test_hbm_admission_guard_rejects_over_budget_submit(tmp_path,
                                                        monkeypatch):
    """A device limit below the pool's needs → submit is rejected with a
    clear reason (503-shaped HbmAdmissionError) and the reject counter
    advances; the guard stands down when the limit is unknown."""
    rejects = tm.registry().counter(tm.HBM_ADMISSION_REJECTS)
    r0 = rejects.total()
    mpath, tpath = _fresh_model(tmp_path)
    eng = InferenceEngine(mpath, tpath, temperature=0.0, seed=3)
    sched = BatchScheduler(eng, n_slots=2, _start_thread=False)
    try:
        monkeypatch.setenv("DLLAMA_HBM_BYTES", "10000000")  # << pool need
        with pytest.raises(HbmAdmissionError, match="HBM admission guard"):
            sched.submit(_enc(eng), 4)
        assert rejects.total() == r0 + 1
        monkeypatch.delenv("DLLAMA_HBM_BYTES")
        req = sched.submit(_enc(eng), 4)  # limit unknown again: admits
        assert req in sched._queue
    finally:
        sched.close()
        eng.close()


def test_hbm_admission_guard_degrades_slot_pool(tmp_path, monkeypatch):
    """A limit that fits a 2-slot pool but not 4 → the generator degrades
    to 2 slots instead of refusing (and instead of OOM-crashing later)."""
    from dllama_tpu.runtime.hbm import estimate_device_bytes
    from dllama_tpu.runtime.serving import BatchedGenerator

    mpath, tpath = _fresh_model(tmp_path)
    # tp pinned to 1 so the estimate below (n_shards=1) matches the pool's
    eng = InferenceEngine(mpath, tpath, tp=1, temperature=0.0, seed=3)

    def need(batch):
        return estimate_device_bytes(
            eng.cfg, weight_repr=eng.hbm_weight_repr,
            kv_dtype_bytes=eng.kv_dtype.itemsize, batch=batch,
            n_shards=1)["need_per_device"]

    # between the 2-slot pool's need (batch=2+1) and the 4-slot's (4+1)
    monkeypatch.setenv("DLLAMA_HBM_BYTES", str((need(3) + need(5)) // 2))
    gen = BatchedGenerator(eng, n_slots=4)
    assert gen.n_slots == 2
    assert gen.kv.k.shape[1] == 2  # the pool really is smaller
    monkeypatch.delenv("DLLAMA_HBM_BYTES")
    eng.close()


# -- kv_alloc: paged block-pool exhaustion (ISSUE 6) -------------------------


@pytest.fixture(scope="module")
def paged_chaos_engine(tmp_path_factory):
    d = tmp_path_factory.mktemp("chaos_paged")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(23)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96),
                     rng)
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    return InferenceEngine(str(mpath), str(tpath), tp=1, temperature=0.0,
                           seed=3, kv_block_size=16)


def test_kv_alloc_exhaustion_degrades_to_queueing_then_recovers(
        paged_chaos_engine):
    """The ISSUE-6 chaos acceptance: injected block-pool exhaustion at
    admission DEGRADES TO QUEUEING — the request stays queued (not failed,
    not crashed), ``dllama_kv_block_exhaustion_total`` counts the event,
    and once blocks are allocatable again the same request admits and
    completes normally."""
    exhaustion = tm.registry().counter(tm.KV_BLOCK_EXHAUSTION)
    crashes = tm.registry().counter(tm.SCHEDULER_CRASHES)
    fired = tm.registry().counter(tm.FAILPOINTS_FIRED)
    e0, c0, f0 = exhaustion.total(), crashes.total(), fired.total(
        name="kv_alloc")
    fp.arm("kv_alloc", "raise", times=1)
    sched = BatchScheduler(paged_chaos_engine, n_slots=2,
                           _start_thread=False)
    try:
        req = sched.submit(_enc(paged_chaos_engine), 4, stop_on_eos=False)
        sched._tick()  # alloc raises: back-pressure, never a crash
        assert not req.done.is_set()
        assert req in sched._queue  # requeued at the head, FIFO preserved
        assert exhaustion.total() == e0 + 1
        assert fired.total(name="kv_alloc") == f0 + 1
        for _ in range(200):  # failpoint exhausted: admits + completes
            sched._tick()
            if req.done.is_set():
                break
        assert req.done.is_set()
        assert req.error is None and len(req.tokens) == 4
        assert crashes.total() == c0  # exhaustion is not a crash
    finally:
        sched.close()


def test_kv_alloc_sustained_exhaustion_sheds_429_shaped(paged_chaos_engine):
    """Sustained exhaustion back-pressures the queue until load shedding
    takes over: with the pool dry, queued work stays queued and the
    requests beyond ``max_queue`` are shed 429-shaped (QueueFullError +
    ``dllama_requests_shed_total``) — the crash-free degradation chain the
    README promises."""
    shed = tm.registry().counter(tm.REQUESTS_SHED)
    exhaustion = tm.registry().counter(tm.KV_BLOCK_EXHAUSTION)
    s0, e0 = shed.total(), exhaustion.total()
    fp.arm("kv_alloc", "raise")  # every alloc fails until cleared
    sched = BatchScheduler(paged_chaos_engine, n_slots=2, max_queue=1,
                           _start_thread=False)
    try:
        req = sched.submit(_enc(paged_chaos_engine), 4, stop_on_eos=False)
        for _ in range(3):
            sched._tick()  # pool dry: req keeps its place in the queue
        assert not req.done.is_set() and req in sched._queue
        assert exhaustion.total() > e0
        with pytest.raises(QueueFullError, match="queue full"):
            sched.submit(_enc(paged_chaos_engine), 4)
        assert shed.total() == s0 + 1
        fp.registry().clear()  # blocks allocatable again: queue drains
        for _ in range(200):
            sched._tick()
            if req.done.is_set():
                break
        assert req.error is None and len(req.tokens) == 4
    finally:
        sched.close()


def test_kv_alloc_exhaustion_dump_names_victim_and_tick_decisions(
        paged_chaos_engine, tmp_path, monkeypatch):
    """ISSUE-7 satellite: a kv_alloc-failpoint mid-decode exhaustion
    leaves a readable flight-recorder postmortem — the dump file names
    the victim request and carries the scheduler tick decisions leading
    in (telemetry- AND file-asserted)."""
    from dllama_tpu.runtime import flightrec

    monkeypatch.setenv("DLLAMA_FLIGHT_DIR", str(tmp_path))
    flightrec.recorder().reset()
    dumps = tm.registry().counter(tm.FLIGHT_DUMPS)
    d0 = dumps.total(reason="kv_block_exhaustion")
    sched = BatchScheduler(paged_chaos_engine, n_slots=2,
                           _start_thread=False)
    try:
        # rest = 9 ids -> one 16-row block; decode must grow at pos 16
        grower = sched.submit(_enc(paged_chaos_engine, "hello w"), 24,
                              stop_on_eos=False)
        bystander = sched.submit(_enc(paged_chaos_engine, "abc"), 4,
                                 stop_on_eos=False)
        for _ in range(20):  # admit + arm both
            sched._tick()
            if grower.t_decode and bystander.t_decode:
                break
        assert grower.t_decode and bystander.t_decode
        fp.arm("kv_alloc", "raise", times=1)
        for _ in range(200):
            sched._tick()
            if grower.done.is_set():
                break
        assert grower.server_error and "exhaustion" in grower.error
        assert bystander.done.is_set() and bystander.error is None
        assert dumps.total(reason="kv_block_exhaustion") == d0 + 1
        files = sorted(tmp_path.glob("dllama-flight-*kv_block_exhaustion*"))
        assert len(files) == 1
        doc = json.loads(files[0].read_text())
        assert doc["victims"] == [grower.rid]
        assert "exhaustion" in doc["info"]["error"]
        # the tick history leading in: the victim's admit decision and
        # its exhaustion retire are both on record
        decisions = [d for t in doc["ticks"] for d in t["decisions"]]
        assert any(d["event"] == "admit" and d["rid"] == grower.rid
                   for d in decisions)
        assert any(d["event"] == "retire" and d["rid"] == grower.rid
                   and d["reason"] == "kv_block_exhaustion"
                   for d in decisions)
        # block-pool occupancy rides every tick record
        assert any(t.get("blocks") for t in doc["ticks"])
    finally:
        fp.registry().clear()
        sched.close()
        flightrec.recorder().reset()


# -- spill / pagein: the tiered-KV failure contract (ISSUE 15) ---------------


PATHS_CHAOS = {}


@pytest.fixture(scope="module")
def tiered_chaos_engine(tmp_path_factory):
    d = tmp_path_factory.mktemp("chaos_tiered")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(29)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96),
                     rng)
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    PATHS_CHAOS["m"], PATHS_CHAOS["t"] = str(mpath), str(tpath)
    return InferenceEngine(str(mpath), str(tpath), tp=1, temperature=0.0,
                           seed=3, kv_block_size=16, kv_host_blocks=64)


def _session(i):
    return "".join(chr(97 + (i + j) % 26) for j in range(33))


def _tick_until(sched, req, n=800):
    for _ in range(n):
        sched._tick()
        if req.done.is_set():
            return
    raise AssertionError("request never finished")


def test_spill_failpoint_degrades_to_drop_evict_contract(
        tiered_chaos_engine):
    """A failing spill must DEGRADE to the pre-tier contract: the cold
    block is dropped instead of spilled, allocation proceeds, every
    request completes, nothing crashes — and once the failpoint clears,
    spilling resumes on the live scheduler."""
    reg = tm.registry()
    fired = reg.counter(tm.FAILPOINTS_FIRED)
    spill = reg.counter(tm.KV_SPILL_BLOCKS)
    crashes = reg.counter(tm.SCHEDULER_CRASHES)
    f0, s0, c0 = fired.total(name="spill"), spill.total(), crashes.total()
    fp.arm("spill", "raise")
    sched = BatchScheduler(tiered_chaos_engine, n_slots=2,
                           _start_thread=False)
    try:
        # enough idle sessions to overflow the 16-block device pool
        for i in range(10):
            r = sched.submit(_enc(tiered_chaos_engine, _session(i)), 4,
                             stop_on_eos=False)
            _tick_until(sched, r)
            assert r.error is None, r.error
        assert fired.total(name="spill") > f0, "pressure must hit the site"
        assert spill.total() == s0, "a failed spill must not count blocks"
        assert reg.gauge(tm.KV_BLOCKS_HOST_USED).value() == 0
        assert crashes.total() == c0  # degrade, never a crash
        # recovery: disarm -> the next pressure wave spills for real
        fp.registry().clear()
        for i in range(10, 16):
            r = sched.submit(_enc(tiered_chaos_engine, _session(i)), 4,
                             stop_on_eos=False)
            _tick_until(sched, r)
            assert r.error is None, r.error
        assert spill.total() > s0
        assert reg.gauge(tm.KV_BLOCKS_HOST_USED).value() > 0
    finally:
        fp.registry().clear()
        sched.close()


def test_pagein_failpoint_fails_only_resumer_503_shaped(
        tiered_chaos_engine):
    """A failing page-in fails ONLY the resuming request — 503-shaped
    (``server_error``), the error naming the page-in — while a bystander
    mid-decode keeps its exact transcript; the host copies stay intact,
    so the SAME resume succeeds once the failpoint clears."""
    sched = BatchScheduler(tiered_chaos_engine, n_slots=2,
                           _start_thread=False)
    try:
        # idle wave on fresh prompts for this test, forcing spills
        for i in range(20, 30):
            r = sched.submit(_enc(tiered_chaos_engine, _session(i)), 4,
                             stop_on_eos=False)
            _tick_until(sched, r)
        ids0 = _enc(tiered_chaos_engine, _session(20))
        assert any(sched.gen.pool.is_host(b)
                   for b in sched.gen.pool.match_prefix(ids0[:-1])[0]), \
            "the resumed session must have spilled"
        # oracles: ONE fresh engine per prompt (a reused engine's
        # NaiveCache shifts the second prompt's prefill chunking — the
        # documented ulp-flips-become-token-flips hazard)
        solo = InferenceEngine(PATHS_CHAOS["m"], PATHS_CHAOS["t"], tp=1)
        by_want = solo.generate("hello world", 8, stop_on_eos=False).tokens
        solo.close()
        resume_prompt = _session(20) + " back"
        solo = InferenceEngine(PATHS_CHAOS["m"], PATHS_CHAOS["t"], tp=1)
        res_want = solo.generate(resume_prompt, 6, stop_on_eos=False).tokens
        solo.close()

        bystander = sched.submit(_enc(tiered_chaos_engine, "hello world"),
                                 8, stop_on_eos=False)
        for _ in range(50):
            sched._tick()
            if bystander.t_decode:
                break
        assert bystander.t_decode and not bystander.done.is_set()

        fp.arm("pagein", "raise", times=1)
        resume = sched.submit(_enc(tiered_chaos_engine, resume_prompt), 6,
                              stop_on_eos=False)
        _tick_until(sched, resume)
        assert resume.error is not None and "page-in" in resume.error
        assert resume.server_error, "page-in failure must be 503-shaped"
        _tick_until(sched, bystander)
        assert bystander.error is None
        assert bystander.tokens == by_want, "bystander must be token-intact"

        # host copies survived the failed attempt: the retry succeeds
        # and stays bitwise equal to the never-spilled solo run
        fp.registry().clear()
        retry = sched.submit(_enc(tiered_chaos_engine, resume_prompt), 6,
                             stop_on_eos=False)
        _tick_until(sched, retry)
        assert retry.error is None, retry.error
        assert retry.tokens == res_want
    finally:
        fp.registry().clear()
        sched.close()


def test_step_hang_watchdog_trip_dumps_flight_recorder(tmp_path,
                                                       monkeypatch):
    """ISSUE-7 satellite: a step_hang watchdog trip writes the black-box
    postmortem (reason watchdog_stall) naming every in-flight victim,
    with the tick decisions leading into the wedged dispatch."""
    from dllama_tpu.runtime import flightrec

    monkeypatch.setenv("DLLAMA_FLIGHT_DIR", str(tmp_path))
    flightrec.recorder().reset()
    dumps = tm.registry().counter(tm.FLIGHT_DUMPS)
    d0 = dumps.total(reason="watchdog_stall")
    mpath, tpath = _fresh_model(tmp_path)
    eng = InferenceEngine(mpath, tpath, temperature=0.0, seed=3)
    eng.watchdog.min_budget_s = 0.3
    eng.watchdog.margin = 1.0
    eng.watchdog.min_samples = 2
    sched = BatchScheduler(eng, n_slots=2)
    try:
        warm = sched.submit(_enc(eng), 4, stop_on_eos=False)
        assert warm.done.wait(timeout=120) and warm.error is None
        assert eng.watchdog.budget_s() is not None
        fp.arm("step_hang", "sleep", times=1, delay_s=8.0)
        req = sched.submit(_enc(eng, "stall me"), 50, stop_on_eos=False)
        assert req.done.wait(timeout=60)
        assert req.error is not None and "watchdog" in req.error
        # the dump is written on the MONITOR thread after the fail-all
        # that set req.done — give it a moment
        for _ in range(100):
            if dumps.total(reason="watchdog_stall") == d0 + 1:
                break
            time.sleep(0.1)
        assert dumps.total(reason="watchdog_stall") == d0 + 1
        files = sorted(tmp_path.glob("dllama-flight-*watchdog_stall*"))
        assert len(files) == 1
        doc = json.loads(files[0].read_text())
        assert req.rid in doc["victims"]
        assert doc["info"]["label"] is not None  # the wedged dispatch
        assert doc["ticks"], "no tick history in the postmortem"
        decisions = [d for t in doc["ticks"] for d in t["decisions"]]
        assert any(d["event"] == "admit" and d["rid"] == req.rid
                   for d in decisions)
    finally:
        fp.registry().clear()
        sched.close()
        eng.close()
        flightrec.recorder().reset()


def test_kv_alloc_mid_decode_exhaustion_fails_one_request_503_shaped(
        paged_chaos_engine):
    """Exhaustion at mid-decode block growth fails THAT request explicitly
    (503-shaped: ``server_error`` + an error naming the exhaustion) and
    leaves the rest of the batch untouched — degraded service, never a
    crash or silent truncation."""
    from dllama_tpu.runtime.serving import PagedGenerator, Request

    exhaustion = tm.registry().counter(tm.KV_BLOCK_EXHAUSTION)
    e0 = exhaustion.total()
    gen = PagedGenerator(paged_chaos_engine, n_slots=2)
    # rest = 9 ids -> one 16-row block; decode must grow at position 16
    grower = Request(rid=0, prompt_ids=_enc(paged_chaos_engine, "hello w"),
                     max_tokens=24, stop_on_eos=False)
    bystander = Request(rid=1, prompt_ids=_enc(paged_chaos_engine, "abc"),
                        max_tokens=4, stop_on_eos=False)
    gen.admit(grower, 0)
    gen.admit(bystander, 1)
    fp.arm("kv_alloc", "raise", times=1)
    while gen.n_active:
        gen.step()
    assert grower.server_error and "exhaustion" in grower.error
    assert len(grower.tokens) < 24  # failed at the block boundary
    assert exhaustion.total() == e0 + 1
    assert bystander.error is None and len(bystander.tokens) == 4


def test_wire_failpoint_poisons_one_request_503_shaped(tmp_path):
    """Armed `wire:nonfinite` + --comm-overlap on a tp mesh: the next
    decode dispatch ships a corrupted ring-hop partial (batch row 0 only,
    in-graph — parallel/qcollectives._maybe_poison_partial), the
    downstream non-finite tripwire fails THAT request 503-shaped, and the
    bystander slot finishes untouched — a poisoned quantized hop's blast
    radius is one request, never the scheduler."""
    from dllama_tpu.runtime import numerics

    nf = tm.registry().counter(tm.NONFINITE)
    fired = tm.registry().counter(tm.FAILPOINTS_FIRED)
    b0, f0 = nf.total(site="batch"), fired.total(name="wire")
    mpath, tpath = _fresh_model(tmp_path, seed=29)
    eng = InferenceEngine(mpath, tpath, tp=2, comm_overlap="auto",
                          temperature=0.0, seed=3, numerics_failfast=True)
    assert eng.cfg.comm_overlap > 1  # the ring merges are in the trace
    sched = BatchScheduler(eng, n_slots=2)
    try:
        fp.arm("wire", "nonfinite", times=1)
        victim = sched.submit(_enc(eng), 8, stop_on_eos=False)
        bystander = sched.submit(_enc(eng, "world"), 4, stop_on_eos=False)
        assert victim.done.wait(timeout=120)
        assert victim.error is not None and "non-finite" in victim.error
        assert victim.server_error  # HTTP 503-shaped, not a client 400
        assert bystander.done.wait(timeout=120)
        assert bystander.error is None and len(bystander.tokens) == 4
        assert nf.total(site="batch") >= b0 + 1
        assert fired.total(name="wire") == f0 + 1
        # recovery: the slot is reclaimed, a clean request serves
        ok = sched.submit(_enc(eng), 4, stop_on_eos=False)
        assert ok.done.wait(timeout=120)
        assert ok.error is None and len(ok.tokens) == 4
        assert isinstance(numerics.nonfinite_error("batch", 1),
                          numerics.NumericsError)
    finally:
        fp.registry().clear()
        sched.close()
        eng.close()


# -- draft: speculative proposer poisoning (ISSUE 14) ------------------------


def test_draft_failpoint_degrades_slot_to_plain_decode(tmp_path):
    """Armed `draft:raise`: a poisoned/raising proposer DEGRADES that
    slot to plain decode for the step — the request completes with its
    exact spec-off transcript (a degraded greedy step emits exactly one
    verified token), ``dllama_spec_degraded_total`` counts every degrade,
    and bystanders are untouched. Disarming restores drafting on the
    same live scheduler."""
    mpath, tpath = _fresh_model(tmp_path, seed=31)
    plain = InferenceEngine(mpath, tpath, tp=1, temperature=0.0, seed=3,
                            kv_block_size=16)
    sched0 = BatchScheduler(plain, n_slots=2)
    try:
        want = sched0.generate(_enc(plain, "hello hello hello"), 10,
                               stop_on_eos=False)
    finally:
        sched0.close()
        plain.close()

    degraded = tm.registry().counter(tm.SPEC_DEGRADED)
    fired = tm.registry().counter(tm.FAILPOINTS_FIRED)
    drafted = tm.registry().counter(tm.SPEC_DRAFT_TOKENS)
    g0, f0 = degraded.total(), fired.total(name="draft")
    eng = InferenceEngine(mpath, tpath, tp=1, temperature=0.0, seed=3,
                          kv_block_size=16, spec_lookup=4)
    sched = BatchScheduler(eng, n_slots=2)
    try:
        fp.arm("draft", "raise")  # every draft call raises
        victim = sched.submit(_enc(eng, "hello hello hello"), 10,
                              stop_on_eos=False)
        bystander = sched.submit(_enc(eng, "world"), 4, stop_on_eos=False)
        assert victim.done.wait(timeout=300)
        assert bystander.done.wait(timeout=300)
        # the request COMPLETES — degraded means plain decode, not failure
        assert victim.error is None and victim.tokens == want
        assert bystander.error is None and len(bystander.tokens) == 4
        assert victim.spec_drafted == 0  # every step degraded
        assert degraded.total() > g0
        assert fired.total(name="draft") > f0
        # recovery on the SAME scheduler: disarm → drafting resumes
        fp.registry().clear()
        d0 = drafted.total(generator="paged")
        again = sched.submit(_enc(eng, "hello hello hello"), 10,
                             stop_on_eos=False)
        assert again.done.wait(timeout=300)
        assert again.error is None and again.tokens == want
        assert again.spec_drafted > 0
        assert drafted.total(generator="paged") > d0
    finally:
        fp.registry().clear()
        sched.close()
        eng.close()


# -- durable streams: resume-target death chaos -------------------------------


def test_resume_failpoint_kills_target_terminal_502_bystanders_intact():
    """The `resume` failpoint severs the mid-stream failover re-dispatch
    exactly where a dying resume target would: the attempt counts
    "failed", the --max-stream-resumes budget is found spent on the next
    pass ("exhausted"), and the victim stream ends with ONE explicit
    terminal 502 event + [DONE] — while a bystander stream riding the
    same fleet through the whole chaos window stays token-intact."""
    from test_router import (StubReplica, _body, _post, _resume_totals,
                             _sse_events, _stamp_indices, _wait, _up,
                             make_router)

    stubs = [StubReplica(f"r{i}") for i in range(3)]
    for s in stubs:
        s.behavior["stamp"] = True
        s.behavior["stream_chunks"] = ["c1 ", "c2 ", "c3 ", "c4 ", "c5"]
    stubs[0].behavior["die_after_chunks"] = 2
    # bystander chunks slow enough to span the victim's whole death +
    # failed resume + terminal abort
    stubs[2].behavior["chunk_delay_s"] = 0.15
    for s in (stubs[1], stubs[2]):
        s.behavior["queue_depth"] = 50  # first dispatch lands on r0
    for s in stubs:
        s.start()
    url, fleet, close = make_router(stubs)
    http = tm.registry().counter(tm.HTTP_REQUESTS)
    bystander: dict = {}

    def ride_along():
        with _post(url, _body("bystander", stream=True,
                              session_id="bystander-sess"),
                   timeout=60) as r:
            bystander["raw"] = r.read()

    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas)
              and fleet.replicas[1].load_score() >= 50,
              what="probes: up + load")
        # pin the bystander to r2 (sticky affinity), then start it
        with fleet._lock:
            fleet._affinity["sid:bystander-sess"] = fleet.replicas[2]
        t = threading.Thread(target=ride_along)
        t.start()
        t0 = _resume_totals()
        c0 = http.total(route="/v1/chat/completions", status="502")
        fired0 = fp.registry().fired("resume")
        fp.arm("resume", "conn_reset", times=1)
        with _post(url, _body("victim", stream=True,
                              session_id="victim-sess"), timeout=60) as r:
            raw = r.read()
        t.join(timeout=60)
        assert fp.registry().fired("resume") == fired0 + 1
        # victim: delivered prefix intact, then exactly one terminal 502
        events = _sse_events(raw)
        assert _stamp_indices(events) == [0, 1, 2]
        assert raw.count(b'"upstream_error"') == 1
        assert raw.rstrip().endswith(b"data: [DONE]")
        d = {k: v - t0[k] for k, v in _resume_totals().items()}
        assert d == {"resumed": 0, "exhausted": 1, "no_budget": 0,
                     "failed": 1}
        assert http.total(route="/v1/chat/completions",
                          status="502") == c0 + 1
        # bystander: full gapless transcript, normal finish
        bevents = _sse_events(bystander["raw"])
        assert _stamp_indices(bevents) == [0, 1, 2, 3, 4, 5]
        assert b'"upstream_error"' not in bystander["raw"]
        assert bevents[-1] == "[DONE]"
    finally:
        fp.registry().clear()
        close()
        for s in stubs:
            if s.httpd is not None:
                s.kill()
