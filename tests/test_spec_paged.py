"""Speculative decoding through the paged continuous-batching path.

The paged verify program family (models.llama.paged_verify_step_guarded +
runtime/serving.PagedGenerator._spec_step) must keep every serving
invariant: greedy spec output token-identical to spec-off through a
multi-request continuous stream with prefix sharing live, zero
post-steady compiles across varying per-slot draft lengths (the verify
program jits once per pool geometry — lens is traced), sampled requests
deterministic per request and independent of batch-mates, accept-rate
surfaced in /metrics and the opt-in ``timing`` response block, and the
spec-aware block-reservation formula pricing the verify frontier so
organic mid-verify exhaustion stays impossible.
"""

from __future__ import annotations

import numpy as np
import pytest

from dllama_tpu.formats import tfile
from dllama_tpu.runtime import introspection
from dllama_tpu.runtime import telemetry as tm
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.runtime.serving import BatchScheduler, PagedGenerator, Request

from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model


def _mk_model(d):
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(29)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96),
                     rng)
    td = byte_vocab_tokenizer()
    td.chat_template = "<|start_header_id|>"  # detected as llama3 (api tests)
    tfile.write_tfile(tpath, td)
    return str(mpath), str(tpath)


@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    return _mk_model(tmp_path_factory.mktemp("spec_paged"))


def _enc(eng, text):
    return eng.tokenizer.encode(text, is_start=True)


def _stream(m, t, spec, work, n_slots=3):
    """Run one continuous multi-request stream through the scheduler;
    returns (tokens per request, per-request (drafted, accepted))."""
    eng = InferenceEngine(m, t, tp=1, kv_block_size=16, spec_lookup=spec)
    sched = BatchScheduler(eng, n_slots=n_slots)
    try:
        reqs = [sched.submit(_enc(eng, p), max_tok, stop_on_eos=False,
                             **kw)
                for p, max_tok, kw in work]
        for r in reqs:
            assert r.done.wait(timeout=300) and r.error is None, r.error
        return ([r.tokens for r in reqs],
                [(r.spec_drafted, r.spec_accepted) for r in reqs])
    finally:
        sched.close()
        eng.close()


# -- the ISSUE acceptance criterion ------------------------------------------


def test_greedy_spec_token_exact_with_sharing_and_ledger_quiet(
        tmp_path_factory):
    """Greedy spec through PagedGenerator is token-exact vs spec-off on a
    multi-request continuous stream with shared prefixes, with zero
    post-steady compiles across varying per-slot draft lengths, and the
    accept-rate lands in /metrics."""
    m, t = _mk_model(tmp_path_factory.mktemp("spec_acc"))
    base = "abcdefghijklmnopqrstuvwxyz "  # > one 16-row block shared
    work = [(base + "hello hello hello", 12, {}),
            (base + "hello hello there", 12, {}),
            ("ababababababab", 16, {}),
            (base + "hello goodbye", 10, {}),
            ("the quick brown fox", 12, {})]
    want, _ = _stream(m, t, 0, work)

    eng = InferenceEngine(m, t, tp=1, kv_block_size=16, spec_lookup=4)
    scope = eng.introspection_scope
    sched = BatchScheduler(eng, n_slots=3)
    d0 = tm.registry().counter(tm.SPEC_DRAFT_TOKENS).total(generator="paged")
    a0 = tm.registry().counter(tm.SPEC_ACCEPTED_TOKENS).total(
        generator="paged")
    try:
        # warm wave: the program family (prefill buckets, paged verify,
        # CoW copy) compiles here; sharing is live (common base prefix)
        warm = [sched.submit(_enc(eng, p), n, stop_on_eos=False)
                for p, n, _kw in work[:3]]
        for r in warm:
            assert r.done.wait(timeout=300) and r.error is None, r.error
        c0 = introspection.ledger().compile_count(scope)

        # steady wave: same workload end to end — admit/retire churn,
        # shared prefixes, and PER-SLOT DRAFT LENGTHS that vary (near-done
        # slots clamp lens by their remaining budget) must not retrace
        reqs = [sched.submit(_enc(eng, p), n, stop_on_eos=False)
                for p, n, _kw in work]
        for r in reqs:
            assert r.done.wait(timeout=300) and r.error is None, r.error
        assert introspection.ledger().compile_count(scope) == c0, \
            "post-steady recompile on the paged verify path"
        assert [r.tokens for r in reqs] == want, \
            "greedy spec diverged from spec-off"
        # per-request accept accounting feeds the timing block
        assert all(r.spec_drafted > 0 for r in reqs)
        assert any(r.spec_accepted > 0 for r in reqs), \
            "repetitive greedy workload must show real acceptance"
    finally:
        sched.close()
        eng.close()

    # accept-rate in /metrics: the generator-labeled counters moved and
    # the Prometheus render carries the series
    drafted = tm.registry().counter(tm.SPEC_DRAFT_TOKENS).total(
        generator="paged") - d0
    accepted = tm.registry().counter(tm.SPEC_ACCEPTED_TOKENS).total(
        generator="paged") - a0
    assert drafted > 0 and accepted > 0
    text = tm.registry().render()
    assert 'dllama_spec_draft_tokens_total{generator="paged"}' in text
    assert 'dllama_spec_accepted_tokens_total{generator="paged"}' in text


# -- sampled traffic ----------------------------------------------------------


def test_sampled_spec_deterministic_and_batchmate_independent(model_files):
    """A sampled request under paged spec serving is deterministic (same
    seed → same tokens) and independent of what shares the batch with it
    — the coin-commit rule (speculative.spec_coins_consumed) consumes
    exactly the draws its own emitted tokens derived from."""
    m, t = model_files
    sampled = ("the quick brown fox", 14,
               dict(temperature=0.8, seed=11, topp=0.9))
    a, _ = _stream(m, t, 3, [sampled])
    b, _ = _stream(m, t, 3, [sampled,
                             ("hello hello hello hello", 16, {}),
                             ("zzzz yyyy xxxx", 12,
                              dict(temperature=0.5, seed=5))])
    assert a[0] == b[0], "batch-mates changed a sampled request's stream"


def test_sampled_spec_requests_accept_and_complete(model_files):
    """Sampled slots draft too (the whole point of speculative sampling):
    they complete to max_tokens and report drafted > 0."""
    m, t = model_files
    toks, stats = _stream(
        m, t, 4, [("hello hello hello hello", 16,
                   dict(temperature=0.7, seed=3))])
    assert len(toks[0]) == 16
    assert stats[0][0] > 0  # drafted


# -- serving-surface details --------------------------------------------------


def test_timing_block_carries_accept_rate(model_files):
    """The opt-in ``"timing": true`` response block gains the per-request
    accept-rate fields under paged spec serving."""
    from dllama_tpu.serve.api import BatchedApiState

    m, t = model_files
    eng = InferenceEngine(m, t, tp=1, temperature=0.0, seed=3,
                          kv_block_size=16, spec_lookup=4)
    state = BatchedApiState(eng, n_slots=2)
    try:
        out = state.complete({"messages": [{"role": "user",
                                            "content": "hello hello hello"}],
                              "max_tokens": 8, "timing": True})
        timing = out["timing"]
        assert timing["spec_drafted"] > 0
        assert 0.0 <= timing["spec_accept_rate"] <= 1.0
        assert timing["spec_accepted"] == round(
            timing["spec_accept_rate"] * timing["spec_drafted"])
        assert "verify_ms" in timing
    finally:
        state.close()
        eng.close()


def test_near_cap_slot_clamps_lens_instead_of_retiring(model_files):
    """A slot within spec+1 positions of seq_len keeps decoding at a
    clamped draft length (ragged lens) instead of retiring early — the
    paged path trades NO tail capacity for speculation, and the final
    tokens still match spec-off."""
    m, t = model_files
    eng0 = InferenceEngine(m, t, tp=1, kv_block_size=16)
    gen0 = PagedGenerator(eng0, n_slots=1)
    ids = _enc(eng0, "hello hello hello hello")
    cap = eng0.cfg.seq_len - len(ids) + 1  # decode to the very last row
    r0 = Request(rid=0, prompt_ids=list(ids), max_tokens=cap,
                 stop_on_eos=False)
    gen0.admit(r0, 0)
    while gen0.n_active:
        gen0.step()
    eng0.close()

    eng = InferenceEngine(m, t, tp=1, kv_block_size=16, spec_lookup=4)
    gen = PagedGenerator(eng, n_slots=1)
    r = Request(rid=0, prompt_ids=list(ids), max_tokens=cap,
                stop_on_eos=False)
    gen.admit(r, 0)
    while gen.n_active:
        gen.step()
    eng.close()
    assert r.tokens == r0.tokens
    # the context is filled to the cap — nothing was traded away
    assert len(r.tokens) == len(r0.tokens)


def test_reservation_prices_verify_frontier(model_files):
    """The spec-aware worst-case formula charges +spec rows: admission
    can never over-commit the pool into a mid-verify exhaustion."""
    m, t = model_files
    eng = InferenceEngine(m, t, tp=1, kv_block_size=16, spec_lookup=4)
    gen = PagedGenerator(eng, n_slots=2)
    try:
        plain = -(-(10 - 1 + 8) // gen.block_size)
        with_spec = gen._worst_case_blocks(10, 8)
        assert with_spec == -(-(10 - 1 + 8 + 4) // gen.block_size) >= plain
        # capped at seq_len: a request that could fill the context prices
        # the whole table, not more
        assert gen._worst_case_blocks(10, 10_000) == \
            -(-eng.cfg.seq_len // gen.block_size)
    finally:
        eng.close()


def test_paged_spec_width_past_decode_regime_refused(model_files):
    """Satellite: the blanket spec refusal is gone; the REAL remaining
    constraint (verify width past the decode regime) refuses with the
    limit named."""
    m, t = model_files
    with pytest.raises(ValueError, match="spec-lookup > 15"):
        InferenceEngine(m, t, tp=1, kv_block_size=16, spec_lookup=16)


def test_overlap_spec_refusal_names_limit(model_files):
    """Satellite: the --comm-overlap × spec refusal names the actual
    limit (_OVERLAP_MAX_WIDTH) and the flag that lifts it."""
    m, t = model_files
    if len(__import__("jax").devices()) < 2:
        pytest.skip("needs >= 2 devices for a tp mesh")
    with pytest.raises(ValueError) as ei:
        InferenceEngine(m, t, tp=2, comm_overlap="2", spec_lookup=16)
    msg = str(ei.value)
    assert "_OVERLAP_MAX_WIDTH" in msg and "16" in msg
    assert "--comm-overlap off" in msg
