"""On-device sampler parity: ops.sampling vs the host numpy oracle
(tokenizer.sampler), and the engine's fused sampled-decode path vs the
logits-download + host-sample path. Reference semantics: Sampler::sample,
src/tokenizer.cpp:424-510."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.formats import tfile
from dllama_tpu.ops.sampling import sampled_token
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.tokenizer.sampler import Sampler, softmax, xorshift_random_f32

from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model

VOCAB = 257  # odd size: exercises the cutoff denominator (n-1)


@pytest.fixture(scope="module")
def jit_sampled():
    return jax.jit(sampled_token)


def _draws(jit_sampled, logits_rows, temperature, topp, seed):
    """Run both samplers over the same xorshift stream; return (device, host)."""
    host = Sampler(VOCAB, temperature, topp, seed)
    state = seed
    dev_picks, host_picks = [], []
    for row in logits_rows:
        coin, state = xorshift_random_f32(state)
        tok = jit_sampled(jnp.asarray(row)[None, :], jnp.float32(temperature),
                          jnp.float32(topp), jnp.float32(coin))
        dev_picks.append(int(tok[0]))
        host_picks.append(host.sample(row))
    assert host.rng_state == state  # same stream consumed
    return dev_picks, host_picks


@pytest.mark.parametrize("temperature,topp", [
    (0.7, 0.9),    # nucleus path
    (1.3, 0.05),   # aggressive truncation (cutoff filter dominates)
    (0.9, 1.0),    # topp >= 1 -> multinomial path
    (1.0, 0.0),    # topp <= 0 -> multinomial path
])
def test_device_matches_host_oracle_500_draws(jit_sampled, temperature, topp):
    """>=500 draws on the oracle's RNG stream must agree exactly
    (VERDICT round-2 next #2)."""
    rng = np.random.default_rng(42)
    rows = rng.standard_normal((500, VOCAB)).astype(np.float32) * 3.0
    dev, host = _draws(jit_sampled, rows, temperature, topp, seed=0xB1A5)
    assert dev == host


def test_device_matches_host_on_peaked_logits(jit_sampled):
    """Near-one-hot rows: truncation keeps ~1 candidate; picks must agree."""
    rng = np.random.default_rng(7)
    rows = rng.standard_normal((100, VOCAB)).astype(np.float32)
    rows[np.arange(100), rng.integers(0, VOCAB, 100)] += 25.0
    dev, host = _draws(jit_sampled, rows, 0.8, 0.9, seed=99)
    assert dev == host


def test_sampled_token_is_distributionally_sane(jit_sampled):
    """Token frequencies track the softmax for a fixed small distribution."""
    logits = np.zeros(8, dtype=np.float32)
    logits[3] = 2.0
    logits[5] = 1.0
    p = softmax(logits / 1.0)
    state = 1234
    counts = np.zeros(8)
    for _ in range(2000):
        coin, state = xorshift_random_f32(state)
        tok = jit_sampled(jnp.asarray(logits)[None, :], jnp.float32(1.0),
                          jnp.float32(1.0), jnp.float32(coin))
        counts[int(tok[0])] += 1
    freq = counts / counts.sum()
    np.testing.assert_allclose(freq, p, atol=0.05)


# ---------------------------------------------------------------------------
# engine integration: the fused path is what next_token actually dispatches
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("sampling")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(5)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=64), rng)
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    return str(mpath), str(tpath)


def test_engine_fused_sampled_decode_matches_host_path(model_files):
    """generate() at temperature>0 via the fused on-device sampler must emit
    the same tokens as the host-sampler fallback on the same seed."""
    fused = InferenceEngine(*model_files, temperature=0.8, topp=0.9, seed=321)
    assert not fused.host_sampling
    rf = fused.generate("hello world", 16, stop_on_eos=False)

    host = InferenceEngine(*model_files, temperature=0.8, topp=0.9, seed=321,
                           host_sampling=True)
    rh = host.generate("hello world", 16, stop_on_eos=False)
    assert rf.tokens == rh.tokens
    # both consumed the same number of RNG steps
    assert fused.sampler.rng_state == host.sampler.rng_state


def test_engine_sampled_decode_under_tp(model_files):
    """The fused sampled step must survive a tp mesh plan (sharded logits
    feed the on-device sampler) and stay identical to tp=1."""
    base = InferenceEngine(*model_files, temperature=0.8, topp=0.9, seed=11, tp=1)
    rb = base.generate("hello world", 8, stop_on_eos=False)
    tp = InferenceEngine(*model_files, temperature=0.8, topp=0.9, seed=11, tp=4)
    rt = tp.generate("hello world", 8, stop_on_eos=False)
    assert rb.tokens == rt.tokens


def test_sampling_knob_change_does_not_recompile(model_files):
    """temperature/topp are traced scalars: changing them between calls must
    reuse the compiled sampled step. Asserted through the compile ledger
    (runtime/introspection), which counts real trace/compile events — the
    pjit wrapper's `_cache_size()` is NOT a compile signal: its fastpath
    cache also keys on input-sharding lineage, so entries appear across
    generations without any recompile."""
    from dllama_tpu.runtime import introspection

    e = InferenceEngine(*model_files, temperature=0.8, topp=0.9, seed=1)

    def sampled_compiles() -> int:
        return [p["compiles"]
                for p in introspection.ledger().snapshot()["programs"]
                if p["scope"] == e.introspection_scope
                and p["program"] == "sampled_step"][0]

    e.generate("hello", 2, stop_on_eos=False)
    before = sampled_compiles()
    assert before >= 1  # the first generation really compiled it
    e.sampler.set_temp(1.2)
    e.sampler.topp = 0.5
    e.generate("world", 2, stop_on_eos=False)
    assert sampled_compiles() == before
