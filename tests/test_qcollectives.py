"""Quantized-wire collectives (parallel/qcollectives.py) — the reference's
Q80 sync pipes (llm.cpp:167: each node ships its quantized partial,
OP_MERGE_ADD after dequant; report fig. 6 wire volume) realized as XLA
collectives."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from dllama_tpu.ops.linear import fake_quant_q80
from dllama_tpu.parallel.qcollectives import psum_q80_wire, wire_psum


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("tp",))


@pytest.mark.parametrize("n", [2, 4, 8])
def test_psum_q80_wire_equals_sum_of_fake_quant_partials(n):
    """The wire collective's numerics ARE the reference's merge: bit-equal
    to summing fake_quant_q80'd partials (quantize-each-partial-then-add,
    llm.cpp OP_MERGE_ADD semantics) — NOT quantize-after-sum."""
    rng = np.random.default_rng(5)
    parts = rng.standard_normal((n, 3, 64)).astype(np.float32)
    want = np.sum(np.asarray(jax.vmap(fake_quant_q80)(jnp.asarray(parts))),
                  axis=0)

    fn = jax.jit(jax.shard_map(
        lambda x: psum_q80_wire(x[0], "tp"), mesh=_mesh(n),
        in_specs=P("tp"), out_specs=P(), check_vma=False))
    got = np.asarray(fn(jnp.asarray(parts)))
    np.testing.assert_array_equal(got, want)


def test_psum_q80_wire_close_to_f32_psum():
    rng = np.random.default_rng(6)
    parts = rng.standard_normal((4, 2, 128)).astype(np.float32)
    exact = parts.sum(axis=0)
    fn = jax.jit(jax.shard_map(
        lambda x: psum_q80_wire(x[0], "tp"), mesh=_mesh(4),
        in_specs=P("tp"), out_specs=P(), check_vma=False))
    got = np.asarray(fn(jnp.asarray(parts)))
    # per-partial q80 rounding: ~|x|max/127 per term
    assert np.abs(got - exact).max() < 4 * np.abs(parts).max() / 127 + 1e-6


def test_wire_psum_dispatch(monkeypatch):
    """wire_psum routes by env knob and block divisibility."""
    rng = np.random.default_rng(7)
    parts = rng.standard_normal((2, 1, 64)).astype(np.float32)

    def run():
        fn = jax.jit(jax.shard_map(
            lambda x: wire_psum(x[0], "tp"), mesh=_mesh(2),
            in_specs=P("tp"), out_specs=P(), check_vma=False))
        return np.asarray(fn(jnp.asarray(parts)))

    monkeypatch.delenv("DLLAMA_TPU_WIRE", raising=False)
    f32 = run()
    np.testing.assert_allclose(f32, parts.sum(axis=0), rtol=1e-6)
    monkeypatch.setenv("DLLAMA_TPU_WIRE", "q80")
    q80 = run()
    assert not np.array_equal(q80, f32)  # quantization engaged
    np.testing.assert_allclose(q80, f32, atol=4 * np.abs(parts).max() / 127)
    # non-divisible trailing axis falls back to full precision
    odd = rng.standard_normal((2, 1, 48)).astype(np.float32)
    fn = jax.jit(jax.shard_map(
        lambda x: wire_psum(x[0], "tp"), mesh=_mesh(2),
        in_specs=P("tp"), out_specs=P(), check_vma=False))
    np.testing.assert_allclose(np.asarray(fn(jnp.asarray(odd))),
                               odd.sum(axis=0), rtol=1e-6)


def test_q80_wire_forward_drift_bounded(monkeypatch):
    """End-to-end: a tp=2 forward with --wire q80 on the Pallas col-split
    path stays close to the f32-wire logits (the wo/w2 partial merges are
    the only thing that changed)."""
    from dllama_tpu.formats import mfile
    from dllama_tpu.models import forward, init_random_params
    from dllama_tpu.models.config import ModelConfig
    from dllama_tpu.parallel.api import make_tp_mesh, use_plan
    from dllama_tpu.parallel.sharding import kv_cache_sharding, shard_params
    from dllama_tpu.runtime import KVCache

    cfg = ModelConfig(
        arch=mfile.ArchType.LLAMA, dim=64, hidden_dim=96, n_layers=2,
        n_heads=8, n_kv_heads=4, head_dim=8, vocab_size=128, seq_len=32,
        norm_epsilon=1e-5, rope_theta=10000.0, rope_type=mfile.RopeType.LLAMA)
    params = init_random_params(cfg, seed=41, quantized=True)
    tokens = jnp.asarray([[3, 1, 4, 1, 5]], dtype=jnp.int32)
    monkeypatch.setenv("DLLAMA_TPU_QUANT_KERNEL", "pallas")

    plan = make_tp_mesh(2)
    sharded = shard_params(plan, params)

    def run():
        kv0 = KVCache.create(cfg)
        kv = jax.device_put(kv0, kv_cache_sharding(plan, kv0))
        with use_plan(plan):
            # fresh lambda per run: jit wrappers around the SAME function
            # object share the global pjit executable cache, which would
            # silently reuse the first run's program and hide the env knob
            logits, _ = jax.jit(
                lambda p, c, t, s, k: forward(p, c, t, s, k),
                static_argnums=1)(sharded, cfg, tokens, jnp.int32(0), kv)
        return np.asarray(logits, np.float32)

    monkeypatch.delenv("DLLAMA_TPU_WIRE", raising=False)
    base = run()
    monkeypatch.setenv("DLLAMA_TPU_WIRE", "q80")
    wired = run()
    assert not np.array_equal(wired, base)  # the wire really quantized
    rms = float(np.sqrt(np.mean(base ** 2)))
    assert float(np.abs(wired - base).max()) / rms < 5e-2


def test_q80_wire_shrinks_collective_traffic(monkeypatch):
    """The point of the feature, measured by the compiled HLO: the q80-wire
    program's collective bytes are a fraction of the f32-wire program's
    (int8 codes + f16 scales vs f32 values)."""
    from dllama_tpu.runtime.profiling import collective_traffic

    def compiled_kb(env):
        for k, v in env.items():
            monkeypatch.setenv(k, v)
        fn = jax.jit(jax.shard_map(
            lambda x: wire_psum(x, "tp"), mesh=_mesh(4),
            in_specs=P(None, "tp"), out_specs=P(), check_vma=False))
        x = jnp.ones((8, 4 * 512), jnp.float32)
        txt = fn.lower(x).compile().as_text()
        return collective_traffic(txt, 4).sent_kb

    monkeypatch.delenv("DLLAMA_TPU_WIRE", raising=False)
    f32_kb = compiled_kb({})
    q80_kb = compiled_kb({"DLLAMA_TPU_WIRE": "q80"})
    assert f32_kb > 0 and q80_kb > 0
    # vs XLA's ring all-reduce (2(n-1)/n · 4B) the quantized all-gather
    # ((n-1)/n · n · 1.0625B) wins 8/(1.0625n)x — ~1.9x at n=4 (the full
    # ~3.8x of report fig. 6 is vs the reference's own all-gather+merge
    # formulation; see the qcollectives docstring for the crossover)
    assert q80_kb < f32_kb * 0.6, (q80_kb, f32_kb)


def test_wire_psum_crossover_guard(monkeypatch):
    """Past the all-gather crossover (n_parts > 7) the quantized wire would
    MOVE MORE bytes than the f32 ring all-reduce — wire_psum must fall back
    to full precision there."""
    monkeypatch.setenv("DLLAMA_TPU_WIRE", "q80")
    rng = np.random.default_rng(8)
    parts = rng.standard_normal((8, 1, 64)).astype(np.float32)
    fn = jax.jit(jax.shard_map(
        lambda x: wire_psum(x[0], "tp", n_parts=8), mesh=_mesh(8),
        in_specs=P("tp"), out_specs=P(), check_vma=False))
    got = np.asarray(fn(jnp.asarray(parts)))
    # exact f32 sum — no quantization happened
    np.testing.assert_allclose(got, parts.sum(axis=0), rtol=1e-6)


@pytest.mark.parametrize("n", [2, 4, 8])
def test_psum_q80_ring_close_to_f32(n):
    """The past-crossover ring variant: per-hop requantization error grows
    ~linearly in n but stays rounding-scale; result within n quantization
    steps of the exact sum, and every device sees the full vector."""
    rng = np.random.default_rng(13)
    parts = rng.standard_normal((n, 2, n * 64)).astype(np.float32)
    exact = parts.sum(axis=0)
    from dllama_tpu.parallel.qcollectives import psum_q80_ring

    fn = jax.jit(jax.shard_map(
        lambda x: psum_q80_ring(x[0], "tp", n)[None], mesh=_mesh(n),
        in_specs=P("tp"), out_specs=P("tp", None, None), check_vma=False))
    got = np.asarray(fn(jnp.asarray(parts)))  # [n, ...]: per-device results
    for dev in range(n):
        assert np.abs(got[dev] - exact).max() < \
            (2 * n) * np.abs(parts).max() / 127 + 1e-6, dev
    # all devices agree exactly (the all-gather hops are deterministic)
    for dev in range(1, n):
        np.testing.assert_array_equal(got[dev], got[0])


def test_wire_psum_routes_ring_past_crossover(monkeypatch):
    """n_parts > crossover with a ring-splittable axis routes to the ring
    (quantized — differs from exact), not the f32 fallback."""
    monkeypatch.setenv("DLLAMA_TPU_WIRE", "q80")
    rng = np.random.default_rng(14)
    parts = rng.standard_normal((8, 1, 8 * 32)).astype(np.float32)
    fn = jax.jit(jax.shard_map(
        lambda x: wire_psum(x[0], "tp", n_parts=8), mesh=_mesh(8),
        in_specs=P("tp"), out_specs=P(), check_vma=False))
    got = np.asarray(fn(jnp.asarray(parts)))
    exact = parts.sum(axis=0)
    assert not np.array_equal(got, exact)  # quantized path taken
    assert np.abs(got - exact).max() < 16 * np.abs(parts).max() / 127 + 1e-6


def test_wire_psum_unwraps_single_axis_tuple(monkeypatch):
    """The MoE caller passes red_axes as a 1-tuple — past the crossover it
    must still reach the quantized ring, not silently fall back to f32."""
    monkeypatch.setenv("DLLAMA_TPU_WIRE", "q80")
    rng = np.random.default_rng(15)
    parts = rng.standard_normal((8, 1, 8 * 32)).astype(np.float32)
    fn = jax.jit(jax.shard_map(
        lambda x: wire_psum(x[0], ("tp",), n_parts=8), mesh=_mesh(8),
        in_specs=P("tp"), out_specs=P(), check_vma=False))
    got = np.asarray(fn(jnp.asarray(parts)))
    assert not np.array_equal(got, parts.sum(axis=0))  # quantized ring ran


def test_wire_psum_multi_axis_past_crossover_decomposes(monkeypatch):
    """A 2-axis reduction whose PRODUCT exceeds the crossover (4x2=8) must
    decompose into sequential per-axis quantized reductions, not silently
    pay f32 wire (the large-mesh MoE ep x hidden regime)."""
    from jax.sharding import Mesh as _Mesh

    monkeypatch.setenv("DLLAMA_TPU_WIRE", "q80")
    mesh = _Mesh(np.array(jax.devices()).reshape(4, 2), ("a", "b"))
    rng = np.random.default_rng(16)
    parts = rng.standard_normal((4, 2, 1, 64)).astype(np.float32)

    fn = jax.jit(jax.shard_map(
        lambda x: wire_psum(x[0, 0], ("a", "b"), (4, 2)), mesh=mesh,
        in_specs=P("a", "b"), out_specs=P(), check_vma=False))
    got = np.asarray(fn(jnp.asarray(parts)))
    exact = parts.sum(axis=(0, 1))
    assert not np.array_equal(got, exact)  # quantized stages ran
    # two-stage quantization error: bounded by a few rounding steps of the
    # partial magnitudes
    assert np.abs(got - exact).max() < 12 * np.abs(parts).max() / 127 + 1e-6
