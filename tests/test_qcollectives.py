"""Quantized-wire collectives (parallel/qcollectives.py) — the reference's
Q80 sync pipes (llm.cpp:167: each node ships its quantized partial,
OP_MERGE_ADD after dequant; report fig. 6 wire volume) realized as XLA
collectives.

All manual-SPMD entry goes through the version-compat shim
(``parallel.api.shard_map``) — raw ``jax.shard_map`` does not exist on
0.4.x jax and ``jax.experimental.shard_map`` is gone on ≥0.5, so a direct
call can never trace on one of the two; tools/check_shard_map_shim.py
keeps this closed-world."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from dllama_tpu.ops.linear import fake_quant_q80
from dllama_tpu.parallel.api import shard_map
from dllama_tpu.parallel.qcollectives import psum_q80_wire, wire_psum


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("tp",))


@pytest.mark.parametrize("n", [2, 4, 8])
def test_psum_q80_wire_equals_sum_of_fake_quant_partials(n):
    """The wire collective's numerics ARE the reference's merge: bit-equal
    to summing fake_quant_q80'd partials (quantize-each-partial-then-add,
    llm.cpp OP_MERGE_ADD semantics) — NOT quantize-after-sum."""
    rng = np.random.default_rng(5)
    parts = rng.standard_normal((n, 3, 64)).astype(np.float32)
    want = np.sum(np.asarray(jax.vmap(fake_quant_q80)(jnp.asarray(parts))),
                  axis=0)

    fn = jax.jit(shard_map(
        lambda x: psum_q80_wire(x[0], "tp"), mesh=_mesh(n),
        in_specs=P("tp"), out_specs=P(), check_vma=False))
    got = np.asarray(fn(jnp.asarray(parts)))
    np.testing.assert_array_equal(got, want)


def test_psum_q80_wire_close_to_f32_psum():
    rng = np.random.default_rng(6)
    parts = rng.standard_normal((4, 2, 128)).astype(np.float32)
    exact = parts.sum(axis=0)
    fn = jax.jit(shard_map(
        lambda x: psum_q80_wire(x[0], "tp"), mesh=_mesh(4),
        in_specs=P("tp"), out_specs=P(), check_vma=False))
    got = np.asarray(fn(jnp.asarray(parts)))
    # per-partial q80 rounding: ~|x|max/127 per term
    assert np.abs(got - exact).max() < 4 * np.abs(parts).max() / 127 + 1e-6


def test_wire_psum_dispatch(monkeypatch):
    """wire_psum routes by env knob and block divisibility."""
    rng = np.random.default_rng(7)
    parts = rng.standard_normal((2, 1, 64)).astype(np.float32)

    def run():
        fn = jax.jit(shard_map(
            lambda x: wire_psum(x[0], "tp"), mesh=_mesh(2),
            in_specs=P("tp"), out_specs=P(), check_vma=False))
        return np.asarray(fn(jnp.asarray(parts)))

    monkeypatch.delenv("DLLAMA_TPU_WIRE", raising=False)
    f32 = run()
    np.testing.assert_allclose(f32, parts.sum(axis=0), rtol=1e-6)
    monkeypatch.setenv("DLLAMA_TPU_WIRE", "q80")
    q80 = run()
    assert not np.array_equal(q80, f32)  # quantization engaged
    np.testing.assert_allclose(q80, f32, atol=4 * np.abs(parts).max() / 127)
    # non-divisible trailing axis falls back to full precision
    odd = rng.standard_normal((2, 1, 48)).astype(np.float32)
    fn = jax.jit(shard_map(
        lambda x: wire_psum(x[0], "tp"), mesh=_mesh(2),
        in_specs=P("tp"), out_specs=P(), check_vma=False))
    np.testing.assert_allclose(np.asarray(fn(jnp.asarray(odd))),
                               odd.sum(axis=0), rtol=1e-6)


def test_q80_wire_forward_drift_bounded(monkeypatch):
    """End-to-end: a tp=2 forward with --wire q80 on the Pallas col-split
    path stays close to the f32-wire logits (the wo/w2 partial merges are
    the only thing that changed)."""
    from dllama_tpu.formats import mfile
    from dllama_tpu.models import forward, init_random_params
    from dllama_tpu.models.config import ModelConfig
    from dllama_tpu.parallel.api import make_tp_mesh, use_plan
    from dllama_tpu.parallel.sharding import kv_cache_sharding, shard_params
    from dllama_tpu.runtime import KVCache

    cfg = ModelConfig(
        arch=mfile.ArchType.LLAMA, dim=64, hidden_dim=96, n_layers=2,
        n_heads=8, n_kv_heads=4, head_dim=8, vocab_size=128, seq_len=32,
        norm_epsilon=1e-5, rope_theta=10000.0, rope_type=mfile.RopeType.LLAMA)
    params = init_random_params(cfg, seed=41, quantized=True)
    tokens = jnp.asarray([[3, 1, 4, 1, 5]], dtype=jnp.int32)
    monkeypatch.setenv("DLLAMA_TPU_QUANT_KERNEL", "pallas")

    plan = make_tp_mesh(2)
    sharded = shard_params(plan, params)

    def run():
        kv0 = KVCache.create(cfg)
        kv = jax.device_put(kv0, kv_cache_sharding(plan, kv0))
        with use_plan(plan):
            # fresh lambda per run: jit wrappers around the SAME function
            # object share the global pjit executable cache, which would
            # silently reuse the first run's program and hide the env knob
            logits, _ = jax.jit(
                lambda p, c, t, s, k: forward(p, c, t, s, k),
                static_argnums=1)(sharded, cfg, tokens, jnp.int32(0), kv)
        return np.asarray(logits, np.float32)

    monkeypatch.delenv("DLLAMA_TPU_WIRE", raising=False)
    base = run()
    monkeypatch.setenv("DLLAMA_TPU_WIRE", "q80")
    wired = run()
    assert not np.array_equal(wired, base)  # the wire really quantized
    rms = float(np.sqrt(np.mean(base ** 2)))
    assert float(np.abs(wired - base).max()) / rms < 5e-2


def test_q80_wire_shrinks_collective_traffic(monkeypatch):
    """The point of the feature, measured by the compiled HLO: the q80-wire
    program's collective bytes are a fraction of the f32-wire program's
    (int8 codes + f16 scales vs f32 values)."""
    from dllama_tpu.runtime.profiling import collective_traffic

    def compiled_kb(env):
        for k, v in env.items():
            monkeypatch.setenv(k, v)
        fn = jax.jit(shard_map(
            lambda x: wire_psum(x, "tp"), mesh=_mesh(4),
            in_specs=P(None, "tp"), out_specs=P(), check_vma=False))
        x = jnp.ones((8, 4 * 512), jnp.float32)
        txt = fn.lower(x).compile().as_text()
        return collective_traffic(txt, 4).sent_kb

    monkeypatch.delenv("DLLAMA_TPU_WIRE", raising=False)
    f32_kb = compiled_kb({})
    q80_kb = compiled_kb({"DLLAMA_TPU_WIRE": "q80"})
    assert f32_kb > 0 and q80_kb > 0
    # vs XLA's ring all-reduce (2(n-1)/n · 4B) the quantized all-gather
    # ((n-1)/n · n · 1.0625B) wins 8/(1.0625n)x — ~1.9x at n=4 (the full
    # ~3.8x of report fig. 6 is vs the reference's own all-gather+merge
    # formulation; see the qcollectives docstring for the crossover)
    assert q80_kb < f32_kb * 0.6, (q80_kb, f32_kb)


def test_wire_psum_crossover_guard(monkeypatch):
    """Past the all-gather crossover (n_parts > 7) the quantized wire would
    MOVE MORE bytes than the f32 ring all-reduce — wire_psum must fall back
    to full precision there."""
    monkeypatch.setenv("DLLAMA_TPU_WIRE", "q80")
    rng = np.random.default_rng(8)
    parts = rng.standard_normal((8, 1, 64)).astype(np.float32)
    fn = jax.jit(shard_map(
        lambda x: wire_psum(x[0], "tp", n_parts=8), mesh=_mesh(8),
        in_specs=P("tp"), out_specs=P(), check_vma=False))
    got = np.asarray(fn(jnp.asarray(parts)))
    # exact f32 sum — no quantization happened
    np.testing.assert_allclose(got, parts.sum(axis=0), rtol=1e-6)


@pytest.mark.parametrize("n", [2, 4, 8])
def test_psum_q80_ring_close_to_f32(n):
    """The past-crossover ring variant: per-hop requantization error grows
    ~linearly in n but stays rounding-scale; result within n quantization
    steps of the exact sum, and every device sees the full vector."""
    rng = np.random.default_rng(13)
    parts = rng.standard_normal((n, 2, n * 64)).astype(np.float32)
    exact = parts.sum(axis=0)
    from dllama_tpu.parallel.qcollectives import psum_q80_ring

    fn = jax.jit(shard_map(
        lambda x: psum_q80_ring(x[0], "tp", n)[None], mesh=_mesh(n),
        in_specs=P("tp"), out_specs=P("tp", None, None), check_vma=False))
    got = np.asarray(fn(jnp.asarray(parts)))  # [n, ...]: per-device results
    for dev in range(n):
        assert np.abs(got[dev] - exact).max() < \
            (2 * n) * np.abs(parts).max() / 127 + 1e-6, dev
    # all devices agree exactly (the all-gather hops are deterministic)
    for dev in range(1, n):
        np.testing.assert_array_equal(got[dev], got[0])


def test_wire_psum_routes_ring_past_crossover(monkeypatch):
    """n_parts > crossover with a ring-splittable axis routes to the ring
    (quantized — differs from exact), not the f32 fallback."""
    monkeypatch.setenv("DLLAMA_TPU_WIRE", "q80")
    rng = np.random.default_rng(14)
    parts = rng.standard_normal((8, 1, 8 * 32)).astype(np.float32)
    fn = jax.jit(shard_map(
        lambda x: wire_psum(x[0], "tp", n_parts=8), mesh=_mesh(8),
        in_specs=P("tp"), out_specs=P(), check_vma=False))
    got = np.asarray(fn(jnp.asarray(parts)))
    exact = parts.sum(axis=0)
    assert not np.array_equal(got, exact)  # quantized path taken
    assert np.abs(got - exact).max() < 16 * np.abs(parts).max() / 127 + 1e-6


def test_wire_psum_unwraps_single_axis_tuple(monkeypatch):
    """The MoE caller passes red_axes as a 1-tuple — past the crossover it
    must still reach the quantized ring, not silently fall back to f32."""
    monkeypatch.setenv("DLLAMA_TPU_WIRE", "q80")
    rng = np.random.default_rng(15)
    parts = rng.standard_normal((8, 1, 8 * 32)).astype(np.float32)
    fn = jax.jit(shard_map(
        lambda x: wire_psum(x[0], ("tp",), n_parts=8), mesh=_mesh(8),
        in_specs=P("tp"), out_specs=P(), check_vma=False))
    got = np.asarray(fn(jnp.asarray(parts)))
    assert not np.array_equal(got, parts.sum(axis=0))  # quantized ring ran


def test_wire_psum_multi_axis_past_crossover_decomposes(monkeypatch):
    """A 2-axis reduction whose PRODUCT exceeds the crossover (4x2=8) must
    decompose into sequential per-axis quantized reductions, not silently
    pay f32 wire (the large-mesh MoE ep x hidden regime)."""
    from jax.sharding import Mesh as _Mesh

    monkeypatch.setenv("DLLAMA_TPU_WIRE", "q80")
    mesh = _Mesh(np.array(jax.devices()).reshape(4, 2), ("a", "b"))
    rng = np.random.default_rng(16)
    parts = rng.standard_normal((4, 2, 1, 64)).astype(np.float32)

    fn = jax.jit(shard_map(
        lambda x: wire_psum(x[0, 0], ("a", "b"), (4, 2)), mesh=mesh,
        in_specs=P("a", "b"), out_specs=P(), check_vma=False))
    got = np.asarray(fn(jnp.asarray(parts)))
    exact = parts.sum(axis=(0, 1))
    assert not np.array_equal(got, exact)  # quantized stages ran
    # two-stage quantization error: bounded by a few rounding steps of the
    # partial magnitudes
    assert np.abs(got - exact).max() < 12 * np.abs(parts).max() / 127 + 1e-6


# -- overlapped (TokenWeave-shaped) ring reductions (ISSUE 8) ----------------


def _ring(fn_body, n, parts, out_specs=None):
    """Run ``fn_body(local_parts)`` under an n-way tp shard_map."""
    fn = jax.jit(shard_map(
        fn_body, mesh=_mesh(n), in_specs=P("tp"),
        out_specs=P() if out_specs is None else out_specs, check_vma=False))
    return np.asarray(fn(jnp.asarray(parts)))


@pytest.mark.parametrize("n_chunks", [2, 4])
def test_overlapped_f32_bitwise_equals_unchunked(n_chunks):
    """Chunking the trailing axis is elementwise-invariant: the overlapped
    merge must be BIT-identical to the single ring (n_chunks=1) — the
    invariant that makes --comm-overlap promotable without new goldens."""
    from dllama_tpu.parallel.qcollectives import (overlapped_wire_psum,
                                                  ring_wire_psum)

    rng = np.random.default_rng(21)
    parts = rng.standard_normal((4, 2, 256)).astype(np.float32)
    whole = _ring(lambda x: ring_wire_psum(x[0], "tp", 4), 4, parts)
    chunked = _ring(
        lambda x: overlapped_wire_psum(x[0], "tp", 4, n_chunks), 4, parts)
    np.testing.assert_array_equal(chunked, whole)
    # and the ring itself is an all-reduce: allclose to the exact f32 sum
    # (rank-order summation may differ from XLA's psum in the last ulp)
    np.testing.assert_allclose(whole, parts.sum(axis=0), rtol=1e-5,
                               atol=1e-5)


def test_ring_q80_bitwise_equals_reference_merge():
    """The quantized ring ships each partial's Q80 planes unchanged, so its
    result is BIT-identical to the reference's all-gather merge
    (psum_q80_wire == sum of fake_quant_q80 partials in rank order) —
    goldens and error bounds transfer to the overlapped path."""
    from dllama_tpu.parallel.qcollectives import _ring_rank_order_sum

    rng = np.random.default_rng(22)
    parts = rng.standard_normal((4, 2, 128)).astype(np.float32)
    got = _ring(
        lambda x: _ring_rank_order_sum(x[0], "tp", 4, quantized=True),
        4, parts)
    want = np.sum(np.asarray(jax.vmap(fake_quant_q80)(jnp.asarray(parts))),
                  axis=0)
    np.testing.assert_array_equal(got, want)
    ref = _ring(lambda x: psum_q80_wire(x[0], "tp"), 4, parts)
    np.testing.assert_array_equal(got, ref)


def test_ring_replicas_bit_identical_per_device():
    """Every device must compute the identical rank-order sum (fp addition
    is non-associative; replica drift would desync downstream SPMD
    decisions). Asserted for both wire formats."""
    from dllama_tpu.parallel.qcollectives import _ring_rank_order_sum

    rng = np.random.default_rng(23)
    parts = rng.standard_normal((8, 1, 64)).astype(np.float32)
    for quant in (False, True):
        per_dev = _ring(
            lambda x: _ring_rank_order_sum(x[0], "tp", 8,
                                           quantized=quant)[None],
            8, parts, out_specs=P("tp", None, None))
        for d in range(1, 8):
            np.testing.assert_array_equal(per_dev[d], per_dev[0])


def test_overlapped_q80_error_bounded_by_per_partial_roundtrip():
    """q80-wire error of the overlapped merge is the SUM of each partial's
    one quantization roundtrip — bounded by n x the per-partial Q80 step
    (absmax/127 per 32-block), the same bound the reference merge holds."""
    from dllama_tpu.parallel.qcollectives import overlapped_wire_psum

    rng = np.random.default_rng(24)
    parts = rng.standard_normal((4, 2, 256)).astype(np.float32)
    import os

    os.environ["DLLAMA_TPU_WIRE"] = "q80"
    try:
        got = _ring(
            lambda x: overlapped_wire_psum(x[0], "tp", 4, 4), 4, parts)
    finally:
        os.environ.pop("DLLAMA_TPU_WIRE", None)
    exact = parts.sum(axis=0)
    bound = 4 * (np.abs(parts).max() / 127.0) * 0.5 + 1e-6  # round-to-even
    assert np.abs(got - exact).max() <= 4 * bound
    # and it is exactly the fake-quant merge, not merely close
    want = np.sum(np.asarray(jax.vmap(fake_quant_q80)(jnp.asarray(parts))),
                  axis=0)
    np.testing.assert_array_equal(got, want)


def test_ring_wire_psum_routes_requantizing_ring_past_crossover(monkeypatch):
    """Past the all-gather crossover with a ring-splittable chunk the
    overlapped path delegates to psum_q80_ring (constant wire win) — the
    result then differs from the one-quantization-per-partial merge."""
    from dllama_tpu.parallel.qcollectives import ring_wire_psum

    monkeypatch.setenv("DLLAMA_TPU_WIRE", "q80")
    rng = np.random.default_rng(25)
    parts = rng.standard_normal((8, 1, 8 * 32)).astype(np.float32)
    got = _ring(lambda x: ring_wire_psum(x[0], "tp", 8), 8, parts)
    want_ref = np.sum(np.asarray(
        jax.vmap(fake_quant_q80)(jnp.asarray(parts))), axis=0)
    assert not np.array_equal(got, want_ref)  # requantizing ring ran
    np.testing.assert_allclose(got, parts.sum(axis=0),
                               atol=10 * np.abs(parts).max() / 127)


# -- overlap_chunks resolution (the --comm-overlap grammar) ------------------


def test_overlap_chunks_resolution_properties():
    from dllama_tpu.parallel.qcollectives import overlap_chunks

    # off spellings
    for off in (0, "0", "off", None, ""):
        assert overlap_chunks(off, 4096) == 0
    # auto: largest candidate <= 4 whose chunks stay Q80-block-divisible
    assert overlap_chunks("auto", 4096) == 4
    assert overlap_chunks("auto", 256) == 4      # 64-wide chunks, 32 | 64
    assert overlap_chunks("auto", 64) == 2       # 4 -> 16-wide (not 32|) -> 2
    assert overlap_chunks("auto", 33) == 0       # nothing fits: degrade
    # explicit N must divide; < 2 and non-dividing refuse loudly
    assert overlap_chunks(8, 4096) == 8
    assert overlap_chunks("8", 4096) == 8
    with pytest.raises(ValueError):
        overlap_chunks(3, 4096)
    with pytest.raises(ValueError):
        overlap_chunks(1, 4096)


def test_wire_traffic_model_prices_every_path():
    from dllama_tpu.parallel.qcollectives import wire_traffic_model

    dim, n = 4096, 4
    assert wire_traffic_model(dim, 1, 0, False) == []  # no wire, no bytes
    [(op, wire, b)] = wire_traffic_model(dim, n, 0, False)
    assert (op, wire) == ("all_reduce", "f32")
    assert b == pytest.approx(2 * (n - 1) / n * 4.0 * dim)
    [(op, wire, b)] = wire_traffic_model(dim, n, 4, False)
    assert (op, wire) == ("ppermute", "f32")
    assert b == pytest.approx((n - 1) * 4.0 * dim)
    [(op, wire, bq)] = wire_traffic_model(dim, n, 4, True)
    assert (op, wire) == ("ppermute", "q80")
    assert bq == pytest.approx((n - 1) * (1 + 2 / 32) * dim)
    assert b / bq == pytest.approx(4 / (1 + 2 / 32))  # the ~3.76x shrink
    # past the crossover with ring-splittable chunks: reduce-scatter halves
    [(op, wire, br)] = wire_traffic_model(8 * 32 * 8, 8, 1, True)
    assert (op, wire) == ("ppermute", "q80")
    assert br == pytest.approx(2 * 7 / 8 * (1 + 2 / 32) * 8 * 32 * 8)


# -- the `wire` failpoint's in-graph injection site --------------------------


def test_wire_poison_scope_poisons_row0_of_shipped_partial():
    """Inside a poison scope with code >= 3 the ring merge's row 0 goes
    non-finite on every device while other rows stay exact; codes < 3
    (the `logits` site's range) pass through clean. Outside any scope the
    injection code is never traced at all."""
    from dllama_tpu.parallel.qcollectives import (_maybe_poison_partial,
                                                  ring_wire_psum,
                                                  wire_poison_scope)

    rng = np.random.default_rng(26)
    parts = rng.standard_normal((2, 3, 2, 64)).astype(np.float32)

    def run(code):
        def body(x, p):
            with wire_poison_scope(p[0]):
                return ring_wire_psum(x[0], "tp", 2)
        fn = jax.jit(shard_map(
            body, mesh=_mesh(2), in_specs=(P("tp"), P()),
            out_specs=P(), check_vma=False))
        return np.asarray(fn(jnp.asarray(parts),
                             jnp.asarray([code], jnp.float32)))

    clean = run(0.0)
    np.testing.assert_allclose(clean, parts.sum(axis=0), rtol=1e-5,
                               atol=1e-5)
    for code in (1.0, 2.0):  # logits-site codes: wire stays clean
        np.testing.assert_array_equal(run(code), clean)
    nan_hit = run(3.0)
    assert np.all(np.isnan(nan_hit[0]))        # row 0 poisoned
    np.testing.assert_array_equal(nan_hit[1:], clean[1:])  # bystanders exact
    inf_hit = run(4.0)
    assert np.all(np.isinf(inf_hit[0]))
    np.testing.assert_array_equal(inf_hit[1:], clean[1:])
    # outside any scope: passthrough, no selector in the graph
    x = jnp.asarray(parts[0])
    assert _maybe_poison_partial(x) is x


def test_wire_traffic_model_q80_explicit_colsplit_pricing():
    """Overlap-off pricing must mirror what actually merges: the GSPMD
    psum is f32, but the EXPLICIT col-split (sharded Pallas kernel →
    wire_psum) ships q80 — all-gather below the crossover, the
    requantizing ring past it."""
    from dllama_tpu.parallel.qcollectives import wire_traffic_model

    dim = 4096
    [(op, wire, b)] = wire_traffic_model(dim, 4, 0, True, q80_explicit=True)
    assert (op, wire) == ("all_gather", "q80")
    assert b == pytest.approx(3 * (1 + 2 / 32) * dim)
    [(op, wire, b)] = wire_traffic_model(8 * 32 * 8, 8, 0, True,
                                         q80_explicit=True)
    assert (op, wire) == ("ppermute", "q80")  # past crossover: ring
    # q80 off, or a GSPMD merge, keeps the f32 all-reduce pricing
    [(op, wire, _)] = wire_traffic_model(dim, 4, 0, False, q80_explicit=True)
    assert (op, wire) == ("all_reduce", "f32")
    [(op, wire, _)] = wire_traffic_model(dim, 4, 0, True, q80_explicit=False)
    assert (op, wire) == ("all_reduce", "f32")


def test_overlap_chunks_rejects_garbage_with_grammar():
    from dllama_tpu.parallel.qcollectives import overlap_chunks

    with pytest.raises(ValueError, match="off.*auto.*integer"):
        overlap_chunks("bananas", 4096)


def test_wire_poison_dp_scope_pins_global_row0():
    """Under dp the shard-local row 0 exists once per dp group: with the
    dp axis named, only dp group 0's row 0 is poisoned — the global blast
    radius stays ONE request."""
    from jax.sharding import Mesh as _Mesh

    from dllama_tpu.parallel.qcollectives import (ring_wire_psum,
                                                  wire_poison_dp_scope,
                                                  wire_poison_scope)

    mesh = _Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))
    rng = np.random.default_rng(27)
    # global batch 4 over dp=2 (2 rows per shard), tp partials on axis 0
    parts = rng.standard_normal((2, 4, 1, 64)).astype(np.float32)

    def body(x, p):
        with wire_poison_scope(p[0]), wire_poison_dp_scope("dp"):
            return ring_wire_psum(x[0], "tp", 2)

    fn = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P("tp", "dp"), P()),
        out_specs=P("dp"), check_vma=False))
    got = np.asarray(fn(jnp.asarray(parts),
                        jnp.asarray([3.0], jnp.float32)))
    assert np.all(np.isnan(got[0]))              # global row 0: poisoned
    assert np.all(np.isfinite(got[1:]))          # rows 1-3 (incl. dp
    # group 1's local row 0, global row 2) untouched
    np.testing.assert_allclose(got[1:], parts.sum(axis=0)[1:], rtol=1e-5,
                               atol=1e-5)


def test_wire_poison_covers_requantizing_ring_past_crossover(monkeypatch):
    """The `wire` failpoint must also bite on the past-crossover route
    (psum_q80_ring): a fired fault that injects nothing would let chaos
    report coverage the large-mesh configs don't have."""
    from dllama_tpu.parallel.qcollectives import (psum_q80_ring,
                                                  wire_poison_scope)

    rng = np.random.default_rng(28)
    parts = rng.standard_normal((8, 2, 1, 8 * 32)).astype(np.float32)

    def body(x, p):
        with wire_poison_scope(p[0]):
            return psum_q80_ring(x[0], "tp", 8)

    fn = jax.jit(shard_map(
        body, mesh=_mesh(8), in_specs=(P("tp"), P()),
        out_specs=P(), check_vma=False))
    hit = np.asarray(fn(jnp.asarray(parts), jnp.asarray([3.0], jnp.float32)))
    assert not np.all(np.isfinite(hit[0]))       # row 0 poisoned
    assert np.all(np.isfinite(hit[1:]))          # bystander rows intact
