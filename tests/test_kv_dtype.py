"""fp8 (e4m3) KV cache — footprint/bandwidth mode for long-context decode.

No scale bookkeeping: both attention paths upcast cache reads to f32, so the
cache dtype is a storage choice (`--kv-dtype f8`). Beyond parity — the
reference's cache is always f32 (shiftForward, nn-cpu-ops.cpp:1304-1326).
These tests pin the three properties that make it shippable: it runs end to
end on every engine path, the numeric drift vs the f32 cache is bounded
(e4m3 has a ~6% max relative rounding step), and the flash kernel and XLA
oracle agree when reading the SAME f8-stored cache.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.formats import quants, tfile
from dllama_tpu.models import ModelConfig, forward, init_random_params
from dllama_tpu.runtime import KVCache
from dllama_tpu.runtime.engine import InferenceEngine

from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model


@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("kv8")
    tok = byte_vocab_tokenizer()
    hdr = tiny_header_params(vocab_size=tok.vocab_size, seq_len=96,
                             weight_type=quants.Q40)
    write_tiny_model(d / "m.m", hdr, np.random.default_rng(21))
    tfile.write_tfile(d / "t.t", tok)
    return str(d / "m.m"), str(d / "t.t")


def test_f8_cache_dtype_and_generation(model_files):
    m, t = model_files
    eng = InferenceEngine(m, t, temperature=0.0, kv_dtype="f8")
    try:
        assert eng.kv.k.dtype == jnp.float8_e4m3fn
        out = eng.generate("hello world", 24, stop_on_eos=False)
        assert len(out.tokens) == 24
    finally:
        eng.close()


def test_f8_logits_drift_bounded(model_files):
    """Prefill + one decode step with f8 vs f32 cache: the logits row must
    stay close (e4m3 rounds k/v entries within ~6%; a blowup here means the
    cache is being read without upcast or written twice-rounded)."""
    m, t = model_files
    rows = {}
    for kvd in ("f32", "f8"):
        eng = InferenceEngine(m, t, temperature=0.0, kv_dtype=kvd)
        try:
            ids = eng.tokenizer.encode("the quick brown fox jumps")
            logits, _ = eng.prefill(ids)
            rows[kvd] = np.asarray(logits, np.float32)
        finally:
            eng.close()
    diff = np.abs(rows["f8"] - rows["f32"]).max()
    ref = np.abs(rows["f32"]).max()
    assert diff < 0.15 * max(ref, 1.0), (diff, ref)
    assert diff > 0  # f8 genuinely engaged (identical rows = dtype ignored)


@pytest.mark.parametrize("kw", [
    {"tp": 2}, {"sp": 2}, {"spec_lookup": 3}, {"decode_chunk": 4},
])
def test_f8_cache_runs_on_every_engine_path(model_files, kw):
    m, t = model_files
    eng = InferenceEngine(m, t, temperature=0.0, kv_dtype="f8", **kw)
    try:
        out = eng.generate("hello hello hello", 16, stop_on_eos=False)
        assert len(out.tokens) == 16
    finally:
        eng.close()


def test_f8_flash_kernel_matches_oracle_same_cache():
    """Kernel and oracle read the same f8-stored cache: their outputs must
    agree to normal kernel tolerance (the f8 rounding happened at WRITE time,
    identically for both)."""
    from dllama_tpu.ops.attention import attention
    from dllama_tpu.ops.flash_attention import flash_attention

    rng = np.random.default_rng(31)
    B, T, H, KV, D, S = 1, 4, 8, 4, 32, 256
    q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    k8 = jnp.asarray(rng.standard_normal((B, KV, S, D)),
                     jnp.float32).astype(jnp.float8_e4m3fn)
    v8 = jnp.asarray(rng.standard_normal((B, KV, S, D)),
                     jnp.float32).astype(jnp.float8_e4m3fn)
    start = jnp.int32(21)
    positions = start + jnp.arange(T, dtype=jnp.int32)[None, :]
    got = np.asarray(flash_attention(q, k8, v8, start, D, interpret=True))
    want = np.asarray(attention(q, k8, v8, positions, D))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_bad_kv_dtype_rejected(model_files):
    m, t = model_files
    with pytest.raises(ValueError, match="kv_dtype"):
        InferenceEngine(m, t, kv_dtype="int8")
