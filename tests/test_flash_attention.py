"""Flash-attention kernel parity vs the XLA oracle (interpret mode on CPU).

Mirrors how the reference validates its GPU attention against CPU
expectations (reference: nn-vulkan-test.cpp multihead-att cases)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.ops.attention import attention
from dllama_tpu.ops.flash_attention import flash_attention, supports


def _mk(B, T, H, n_kv, D, S, start_pos, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    q = rng.standard_normal((B, T, H, D)).astype(np.float32)
    k = np.zeros((B, n_kv, S, D), np.float32)
    v = np.zeros((B, n_kv, S, D), np.float32)
    # fill cache up to and including the current rows' positions
    filled = start_pos + T
    k[:, :, :filled] = rng.standard_normal((B, n_kv, filled, D))
    v[:, :, :filled] = rng.standard_normal((B, n_kv, filled, D))
    return (jnp.asarray(q, dtype), jnp.asarray(k, dtype), jnp.asarray(v, dtype))


@pytest.mark.parametrize("B,T,H,n_kv,D,S,start_pos", [
    (1, 1, 8, 4, 64, 256, 0),       # decode at pos 0
    (1, 1, 8, 2, 64, 256, 200),     # decode deep into the cache
    (1, 16, 8, 4, 64, 256, 37),     # prefill chunk mid-sequence
    (2, 4, 4, 4, 128, 512, 5),      # MHA (kv_mul=1), batch>1, D=128
    (1, 8, 16, 2, 64, 128, 0),      # wide GQA group, single S block
])
def test_matches_oracle(B, T, H, n_kv, D, S, start_pos):
    q, k, v = _mk(B, T, H, n_kv, D, S, start_pos)
    assert supports(q.shape, n_kv, S)
    positions = start_pos + jnp.arange(T, dtype=jnp.int32)[None, :]
    positions = jnp.broadcast_to(positions, (B, T))

    want = attention(q, k, v, positions, D)
    got = flash_attention(q, k, v, jnp.int32(start_pos), D, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


def test_bf16_cache_matches_oracle():
    B, T, H, n_kv, D, S, start_pos = 1, 1, 8, 4, 64, 256, 100
    q, k, v = _mk(B, T, H, n_kv, D, S, start_pos, dtype=jnp.bfloat16)
    positions = jnp.full((B, T), start_pos, dtype=jnp.int32)
    want = attention(q, k, v, positions, D)
    got = flash_attention(q, k, v, jnp.int32(start_pos), D, interpret=True)
    np.testing.assert_allclose(np.asarray(got, dtype=np.float32),
                               np.asarray(want, dtype=np.float32),
                               rtol=2e-2, atol=2e-2)


def test_supports_gate():
    assert not supports((1, 1, 8, 64), 4, 100)      # S not tileable
    assert supports((1, 1, 8, 64), 4, 256)
    assert not supports((1, 2048, 8, 64), 1, 256)   # TQ too large


def test_ragged_positions_match_oracle():
    """Per-row start positions (batched serving): each batch row reads its
    own q_pos0 from the per-row position table."""
    B, T, H, n_kv, D, S = 4, 1, 8, 4, 64, 256
    starts = jnp.asarray([0, 57, 130, 255 - T], dtype=jnp.int32)
    rng = np.random.default_rng(7)
    q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, n_kv, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, n_kv, S, D)), jnp.float32)
    positions = starts[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]

    want = attention(q, k, v, positions, D)
    got = flash_attention(q, k, v, starts, D, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-6)


def test_ragged_forward_forced_flash_matches_oracle():
    """Full model forward with a [B] start_pos vector under attn_impl='flash'
    and a tp plan (the sharded kernel path threads interpret mode on CPU) vs
    the attn_impl='xla' oracle — the batched-serving decode step keeps flash
    on TPU."""
    from dataclasses import replace

    from dllama_tpu.formats import mfile
    from dllama_tpu.models import ModelConfig, forward, init_random_params
    from dllama_tpu.parallel import use_plan
    from dllama_tpu.parallel.api import make_tp_mesh
    from dllama_tpu.parallel.sharding import kv_cache_sharding, shard_params
    from dllama_tpu.runtime import KVCache

    cfg = ModelConfig(
        arch=mfile.ArchType.LLAMA, dim=64, hidden_dim=96, n_layers=2,
        n_heads=8, n_kv_heads=4, head_dim=8, vocab_size=128, seq_len=128,
        norm_epsilon=1e-5, rope_theta=10000.0, rope_type=mfile.RopeType.LLAMA,
        attn_impl="flash")
    params = init_random_params(cfg, seed=5)
    tokens = jnp.asarray([[3], [5], [7]], dtype=jnp.int32)
    starts = jnp.asarray([2, 40, 99], dtype=jnp.int32)
    kv0 = KVCache.create(cfg, batch_size=3)
    # seed the caches with history so positions differ meaningfully
    rng = np.random.default_rng(1)
    kv0 = KVCache(k=jnp.asarray(rng.standard_normal(kv0.k.shape), jnp.float32),
                  v=jnp.asarray(rng.standard_normal(kv0.v.shape), jnp.float32))

    ref, _ = jax.jit(forward, static_argnums=1)(
        params, replace(cfg, attn_impl="xla"), tokens, starts, kv0)

    plan = make_tp_mesh(2)
    sharded = shard_params(plan, params)
    kv = jax.device_put(kv0, kv_cache_sharding(plan, kv0))
    with use_plan(plan):
        got, _ = jax.jit(forward, static_argnums=1)(
            sharded, cfg, tokens, starts, kv)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


def test_non_128_seq_len_takes_flash():
    """A --max-seq-len that isn't a 128-multiple used to silently fall back
    to the XLA oracle (the kernel's block grid needs S % 128 == 0); the
    cache now allocates padded to the block grid (runtime.kvcache), so
    forced flash runs — and matches the oracle — at any logical length."""
    import jax
    import jax.numpy as jnp

    from dllama_tpu.formats import mfile
    from dllama_tpu.models import ModelConfig, forward, init_random_params
    from dllama_tpu.runtime import KVCache
    from dllama_tpu.runtime.kvcache import padded_cache_len

    assert padded_cache_len(100) == 128 and padded_cache_len(128) == 128
    cfg = ModelConfig(
        arch=mfile.ArchType.LLAMA, dim=64, hidden_dim=96, n_layers=2,
        n_heads=4, n_kv_heads=2, head_dim=16, vocab_size=128, seq_len=100,
        norm_epsilon=1e-5, rope_theta=10000.0, rope_type=mfile.RopeType.LLAMA,
        attn_impl="flash")
    params = init_random_params(cfg, seed=2)
    tokens = jnp.asarray([[3, 1, 4, 1, 5]], dtype=jnp.int32)
    kv = KVCache.create(cfg)
    assert kv.seq_len == 128  # physical rows padded; logical cap stays 100
    got, _ = jax.jit(forward, static_argnums=1)(
        params, cfg, tokens, jnp.int32(0), kv)

    from dataclasses import replace

    cfg_o = replace(cfg, attn_impl="xla")
    want, _ = jax.jit(forward, static_argnums=1)(
        params, cfg_o, tokens, jnp.int32(0), KVCache.create(cfg_o))
    import numpy as np
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
