"""Multi-step fused decode (decode_chunk): K tokens per dispatch must be
OUTPUT-IDENTICAL to single-step decode — greedy and sampled, including EOS
truncation mid-chunk and the sampler-RNG rewind that keeps the xorshift
stream bit-identical afterwards."""

import numpy as np
import pytest

from dllama_tpu.formats import tfile
from dllama_tpu.runtime.engine import InferenceEngine

from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model


@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("chunk")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(13)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96), rng)
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    return str(mpath), str(tpath)


@pytest.mark.parametrize("temperature,chunk", [
    (0.0, 8), (0.0, 5), (0.8, 8), (0.8, 3),
])
def test_chunked_matches_single_step(model_files, temperature, chunk):
    single = InferenceEngine(*model_files, temperature=temperature, seed=21)
    r1 = single.generate("hello world", 20, stop_on_eos=False)
    chunked = InferenceEngine(*model_files, temperature=temperature, seed=21,
                              decode_chunk=chunk)
    r2 = chunked.generate("hello world", 20, stop_on_eos=False)
    assert r1.tokens == r2.tokens
    assert single.pos == chunked.pos
    assert single.sampler.rng_state == chunked.sampler.rng_state
    # chunking actually reduced the number of dispatches
    preds = [s.n_tokens for s in r2.steps if s.kind == "pred"]
    assert len(preds) < len([s for s in r1.steps if s.kind == "pred"])
    # tails smaller than the chunk run single-step (no fresh compile of a
    # second chunk size): every multi-token dispatch is exactly `chunk` wide
    assert all(n == chunk or n == 1 for n in preds), preds


def _force_eos_on(engine, token_id):
    orig = engine.tokenizer.is_eos
    engine.tokenizer.is_eos = lambda t: t == token_id or orig(t)


def test_eos_mid_chunk_truncates_and_rewinds_rng(model_files):
    """EOS landing mid-chunk: kept tokens, position, and the sampler RNG
    state must all match the single-step run — and a CONTINUED generation
    after the EOS must also match (the rewind proof)."""
    probe = InferenceEngine(*model_files, temperature=0.8, seed=5)
    burn = probe.generate("hello world", 12, stop_on_eos=False)
    eos_tok = burn.tokens[6]  # a token known to appear mid-stream

    single = InferenceEngine(*model_files, temperature=0.8, seed=5)
    _force_eos_on(single, eos_tok)
    chunked = InferenceEngine(*model_files, temperature=0.8, seed=5,
                              decode_chunk=8)
    _force_eos_on(chunked, eos_tok)

    r1 = single.generate("hello world", 12, stop_on_eos=True)
    r2 = chunked.generate("hello world", 12, stop_on_eos=True)
    assert r1.tokens == r2.tokens and r1.tokens[-1] == eos_tok
    assert single.pos == chunked.pos
    assert single.sampler.rng_state == chunked.sampler.rng_state

    c1 = single.generate([r1.tokens[-1]], 6, stop_on_eos=False)
    c2 = chunked.generate([r2.tokens[-1]], 6, stop_on_eos=False)
    assert c1.tokens == c2.tokens


def test_greedy_eos_mid_chunk(model_files):
    probe = InferenceEngine(*model_files, temperature=0.0)
    burn = probe.generate("hello world", 12, stop_on_eos=False)
    eos_tok = burn.tokens[4]

    single = InferenceEngine(*model_files, temperature=0.0)
    _force_eos_on(single, eos_tok)
    chunked = InferenceEngine(*model_files, temperature=0.0, decode_chunk=8)
    _force_eos_on(chunked, eos_tok)
    r1 = single.generate("hello world", 12, stop_on_eos=True)
    r2 = chunked.generate("hello world", 12, stop_on_eos=True)
    assert r1.tokens == r2.tokens and r1.tokens[-1] == eos_tok
    assert single.pos == chunked.pos
    # overshoot rows beyond the EOS must be invisible: continue and compare
    c1 = single.generate([r1.tokens[-1]], 6, stop_on_eos=False)
    c2 = chunked.generate([r2.tokens[-1]], 6, stop_on_eos=False)
    assert c1.tokens == c2.tokens


def test_chunk_under_tp_matches(model_files):
    base = InferenceEngine(*model_files, temperature=0.0, tp=1)
    rb = base.generate("hello world", 12, stop_on_eos=False)
    tp = InferenceEngine(*model_files, temperature=0.0, tp=4, decode_chunk=4)
    rt = tp.generate("hello world", 12, stop_on_eos=False)
    assert rb.tokens == rt.tokens
