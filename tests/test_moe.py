"""Mixture-of-experts: format walk, router math, forward vs numpy golden,
expert parallelism, converter plan.

All of this is NEW capability: the reference parses N_EXPERTS and its
converter can emit expert weights, but its graph builder never reads
nExperts — an MoE model cannot run there at all (SURVEY.md §2.2). The .m MoE
layout here matches the reference converter's expert order (w3/w1/w2 per
expert) and adds the missing router tensor (block_moe_gate).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dllama_tpu.formats import mfile, quants
from dllama_tpu.models import ModelConfig, forward, init_random_params, load_params_from_mfile
from dllama_tpu.parallel import use_plan
from dllama_tpu.parallel.api import make_mesh
from dllama_tpu.parallel.sharding import kv_cache_sharding, shard_params, validate_ep
from dllama_tpu.runtime import KVCache

from helpers import tiny_header_params, write_tiny_model

E, K = 4, 2  # experts / active experts for the tiny configs


def _moe_params(arch=mfile.ArchType.LLAMA, **kw):
    return tiny_header_params(arch=arch, n_experts=E, n_active_experts=K,
                              weight_type=quants.F32, **kw)


def _golden_moe_ffn(cfg: ModelConfig, h: np.ndarray, gate_w: np.ndarray,
                    we1, we2, we3) -> np.ndarray:
    """Per-token loop reimplementation of the MoE FFN (no shared code)."""
    B, T, _ = h.shape
    y = np.zeros_like(h)
    logits = h @ gate_w.T  # [B,T,E]
    for b in range(B):
        for t in range(T):
            lg = logits[b, t]
            p = np.exp(lg - lg.max())
            p /= p.sum()
            idx = np.argsort(-p)[: cfg.n_active_experts]
            w = p[idx] / p[idx].sum() if cfg.moe_norm_topk else p[idx]
            acc = np.zeros(cfg.dim, np.float32)
            for wi, ei in zip(w, idx):
                g = h[b, t] @ we1[ei].T
                g = g / (1.0 + np.exp(-g))  # silu
                u = h[b, t] @ we3[ei].T
                acc += wi * ((g * u) @ we2[ei].T)
            y[b, t] = acc
    return y


def _golden_moe_forward(dense, cfg: ModelConfig, tokens: np.ndarray):
    """Full-model golden with the MoE FFN; attention mirrors
    test_model.golden_forward's math."""
    from test_model import golden_forward

    # run the dense golden with zeroed FFN contribution by giving it zero
    # w1/w3 (silu(0)*u = 0), then add MoE contributions layer by layer — not
    # possible layerwise from outside, so instead: reimplement inline.
    B, T = tokens.shape
    hd = cfg.head_dim
    x = dense["embedding"][tokens].astype(np.float32)

    def rms(v, w):
        inv = 1.0 / np.sqrt(np.mean(v * v, axis=-1, keepdims=True) + cfg.norm_epsilon)
        return v * inv * w

    def rope(v, positions):
        half = hd // 2
        freqs = 1.0 / cfg.rope_theta ** (2.0 * np.arange(half, dtype=np.float32) / hd)
        ang = positions[..., None] * freqs
        c, s = np.cos(ang)[:, :, None, :], np.sin(ang)[:, :, None, :]
        out = v.copy()
        a, b = v[..., 0::2], v[..., 1::2]
        out[..., 0::2] = a * c - b * s
        out[..., 1::2] = a * s + b * c
        return out

    positions = np.arange(T)[None, :] + np.zeros((B, 1), np.int32)
    for l in range(cfg.n_layers):
        h = rms(x, dense[f"block_norm_0.{l}"])
        q = (h @ dense[f"block_matmul_q.{l}"].T).reshape(B, T, cfg.n_heads, hd)
        k = (h @ dense[f"block_matmul_k.{l}"].T).reshape(B, T, cfg.n_kv_heads, hd)
        v = (h @ dense[f"block_matmul_v.{l}"].T).reshape(B, T, cfg.n_kv_heads, hd)
        q, k = rope(q, positions), rope(k, positions)
        att = np.zeros((B, T, cfg.n_heads, hd), np.float32)
        for hh in range(cfg.n_heads):
            kv_h = hh // (cfg.n_heads // cfg.n_kv_heads)
            for b in range(B):
                for t in range(T):
                    scores = np.einsum("sh,h->s", k[b, : t + 1, kv_h], q[b, t, hh]) / np.sqrt(hd)
                    e = np.exp(scores - scores.max())
                    p = e / e.sum()
                    att[b, t, hh] = p @ v[b, : t + 1, kv_h]
        x = x + att.reshape(B, T, -1) @ dense[f"block_matmul_wo.{l}"].T
        h = rms(x, dense[f"block_norm_1.{l}"])
        we1 = np.stack([dense[f"block_expert_w1.{l}.{e}"] for e in range(E)])
        we2 = np.stack([dense[f"block_expert_w2.{l}.{e}"] for e in range(E)])
        we3 = np.stack([dense[f"block_expert_w3.{l}.{e}"] for e in range(E)])
        x = x + _golden_moe_ffn(cfg, h, dense[f"block_moe_gate.{l}"], we1, we2, we3)
    x = rms(x, dense["final_norm"])
    return x @ dense["final_matmul_logits"].T


def test_mfile_walk_moe(tmp_path):
    p = _moe_params()
    write_tiny_model(tmp_path / "moe.m", p, np.random.default_rng(0))
    with mfile.ModelFile.open(tmp_path / "moe.m") as mf:
        assert mf.header.n_experts == E and mf.has_moe_router
        assert "block_moe_gate.0" in mf.tensors
        assert f"block_expert_w2.1.{E-1}" in mf.tensors
        assert "block_matmul_w1.0" not in mf.tensors
        # disk order within a layer: gate then w3/w1/w2 per expert
        o = mf.tensors
        assert (o["block_moe_gate.0"].offset < o["block_expert_w3.0.0"].offset
                < o["block_expert_w1.0.0"].offset < o["block_expert_w2.0.0"].offset
                < o["block_expert_w3.0.1"].offset)


def test_mfile_routerless_moe_file_detected(tmp_path):
    """A reference-converter-style MoE file (no router) parses with
    has_moe_router=False and refuses to load params."""
    p = _moe_params()
    # write with router, then excise the router bytes to fake the reference layout
    write_tiny_model(tmp_path / "a.m", p, np.random.default_rng(0))
    with mfile.ModelFile.open(tmp_path / "a.m") as mf:
        spans = sorted(
            (r.offset, r.n_bytes) for k, r in mf.tensors.items()
            if r.name == "block_moe_gate")
        raw = open(tmp_path / "a.m", "rb").read()
    out = bytearray()
    prev = 0
    for off, nb in spans:
        out += raw[prev:off]
        prev = off + nb
    out += raw[prev:]
    (tmp_path / "b.m").write_bytes(out)

    with mfile.ModelFile.open(tmp_path / "b.m") as mf:
        assert not mf.has_moe_router
        cfg = ModelConfig.from_header(mf.header)
        with pytest.raises(ValueError, match="router"):
            load_params_from_mfile(mf, cfg)


@pytest.mark.parametrize("norm_topk", [True, False])
def test_moe_forward_matches_golden(tmp_path, norm_topk):
    p = _moe_params()
    dense = write_tiny_model(tmp_path / "moe.m", p, np.random.default_rng(7))
    tokens = np.asarray([[5, 9, 2, 11, 3]], dtype=np.int32)

    from dataclasses import replace

    with mfile.ModelFile.open(tmp_path / "moe.m") as mf:
        cfg = replace(ModelConfig.from_header(mf.header), moe_norm_topk=norm_topk)
        assert cfg.is_moe
        params = load_params_from_mfile(mf, cfg)

    want = _golden_moe_forward(dense, cfg, tokens)
    logits, _ = jax.jit(forward, static_argnums=1)(
        params, cfg, jnp.asarray(tokens), jnp.int32(0), KVCache.create(cfg))
    np.testing.assert_allclose(np.asarray(logits)[0], want[0], rtol=2e-4, atol=2e-4)


def test_norm_topk_changes_outputs(tmp_path):
    """Renormalized vs raw top-k router weights genuinely differ (the only
    behavioral router knob: softmax-then-topk-renorm equals topk-then-softmax,
    so an arch-based 'flavor' would be a no-op)."""
    from dataclasses import replace

    write_tiny_model(tmp_path / "m.m", _moe_params(), np.random.default_rng(3))
    tokens = jnp.asarray([[1, 2, 3]], dtype=jnp.int32)
    with mfile.ModelFile.open(tmp_path / "m.m") as mf:
        cfg_norm = ModelConfig.from_header(mf.header)
        assert cfg_norm.moe_norm_topk  # header default
        params = load_params_from_mfile(mf, cfg_norm)
    cfg_raw = replace(cfg_norm, moe_norm_topk=False)
    a, _ = jax.jit(forward, static_argnums=1)(
        params, cfg_norm, tokens, jnp.int32(0), KVCache.create(cfg_norm))
    b, _ = jax.jit(forward, static_argnums=1)(
        params, cfg_raw, tokens, jnp.int32(0), KVCache.create(cfg_raw))
    assert not np.allclose(np.asarray(a), np.asarray(b))


def test_moe_norm_topk_header_round_trip(tmp_path):
    p = _moe_params()
    p["moe_norm_topk"] = 0
    write_tiny_model(tmp_path / "m.m", p, np.random.default_rng(1))
    with mfile.ModelFile.open(tmp_path / "m.m") as mf:
        assert mf.header.moe_norm_topk == 0
        assert not ModelConfig.from_header(mf.header).moe_norm_topk


@pytest.mark.parametrize("mesh_axes", [
    {"ep": 4},
    {"ep": 2, "tp": 2},
    {"dp": 2, "ep": 2, "tp": 2},
    {"tp": 4},  # hidden-sharded, no ep axis: sparse col-split path
])
def test_ep_sharded_forward_matches_unsharded(mesh_axes):
    cfg = ModelConfig(
        arch=mfile.ArchType.LLAMA, dim=64, hidden_dim=96, n_layers=2,
        n_heads=8, n_kv_heads=4, head_dim=8, vocab_size=128, seq_len=32,
        norm_epsilon=1e-5, rope_theta=10000.0, rope_type=mfile.RopeType.LLAMA,
        n_experts=E, n_active_experts=K)
    B = 2 if "dp" in mesh_axes else 1
    params = init_random_params(cfg, seed=31)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 128, (B, 6)), dtype=jnp.int32)

    ref, _ = jax.jit(forward, static_argnums=1)(
        params, cfg, tokens, jnp.int32(0), KVCache.create(cfg, batch_size=B))

    plan = make_mesh(mesh_axes)
    validate_ep(cfg, plan.axis_size("ep"))
    sharded = shard_params(plan, params)
    if "ep" in mesh_axes:
        assert sharded.layers.we1.sharding.spec[1] == "ep"
    kv0 = KVCache.create(cfg, batch_size=B)
    kv = jax.device_put(kv0, kv_cache_sharding(plan, kv0))
    with use_plan(plan):
        got, _ = jax.jit(forward, static_argnums=1)(
            sharded, cfg, tokens, jnp.int32(0), kv)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


def test_validate_ep():
    cfg = ModelConfig(
        arch=mfile.ArchType.LLAMA, dim=8, hidden_dim=16, n_layers=1,
        n_heads=2, n_kv_heads=2, head_dim=4, vocab_size=32, seq_len=8,
        norm_epsilon=1e-5, rope_theta=10000.0, rope_type=mfile.RopeType.LLAMA,
        n_experts=6, n_active_experts=2)
    validate_ep(cfg, 3)
    with pytest.raises(ValueError):
        validate_ep(cfg, 4)
    from dataclasses import replace
    with pytest.raises(ValueError):
        validate_ep(replace(cfg, n_experts=0, n_active_experts=0), 2)


def test_hf_plan_includes_router_and_dual_names():
    from dllama_tpu.convert.hf import hf_tensor_plan

    p = tiny_header_params(n_experts=2, n_active_experts=1)
    p["weight_float_type"] = quants.Q40
    plan = hf_tensor_plan(p)
    keys = [it.keys for it in plan]
    assert ("model.layers.0.block_sparse_moe.gate.weight",
            "model.layers.0.mlp.gate.weight") in keys
    assert ("model.layers.0.block_sparse_moe.experts.0.w3.weight",
            "model.layers.0.mlp.experts.0.up_proj.weight") in keys
    # dense mlp keys absent for MoE
    assert not any("mlp.gate_proj" in k for ks in keys for k in ks)


def test_hf_config_qwen3_moe_mapping(tmp_path):
    import json

    from dllama_tpu.convert.hf import load_hf_config

    (tmp_path / "config.json").write_text(json.dumps({
        "model_type": "qwen3_moe", "hidden_act": "silu", "hidden_size": 64,
        "intermediate_size": 96, "moe_intermediate_size": 48,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "num_key_value_heads": 2, "max_position_embeddings": 128,
        "vocab_size": 100, "num_experts": 8, "num_experts_per_tok": 2,
        "rope_theta": 10000, "rms_norm_eps": 1e-6, "head_dim": 16,
    }))
    params = load_hf_config(tmp_path, quants.Q40)
    assert params["n_experts"] == 8 and params["n_active_experts"] == 2
    assert params["hidden_dim"] == 48  # moe_intermediate_size wins
    assert params["moe_norm_topk"] == 0  # HF Qwen3MoeConfig default: False


# ---------------------------------------------------------------------------
# sparse (ragged_dot) dispatch vs the dense all-experts oracle
# ---------------------------------------------------------------------------

from dataclasses import replace as _replace

from dllama_tpu.models.llama import _moe_ffn, init_random_params
from dllama_tpu.parallel.api import make_mesh, use_plan
from dllama_tpu.parallel.sharding import shard_params


def _sparse_dense_cfg(**kw):
    base = dict(
        arch=mfile.ArchType.LLAMA, dim=64, hidden_dim=96, n_layers=1,
        n_heads=4, n_kv_heads=2, head_dim=16, vocab_size=128, seq_len=32,
        norm_epsilon=1e-5, rope_theta=10000.0, rope_type=mfile.RopeType.LLAMA,
        n_experts=8, n_active_experts=2)
    base.update(kw)
    return ModelConfig(**base)


@pytest.mark.parametrize("norm_topk", [True, False])
def test_sparse_matches_dense_oracle(norm_topk):
    cfg = _sparse_dense_cfg(moe_norm_topk=norm_topk)
    params = init_random_params(cfg, seed=21)
    lp = jax.tree.map(lambda a: None if a is None else a[0], params.layers,
                      is_leaf=lambda x: x is None)
    rng = np.random.default_rng(3)
    h = jnp.asarray(rng.standard_normal((2, 5, cfg.dim)), jnp.float32)

    dense = _moe_ffn(_replace(cfg, moe_impl="dense"), h, lp)
    sparse = _moe_ffn(_replace(cfg, moe_impl="sparse"), h, lp)
    np.testing.assert_allclose(np.asarray(sparse), np.asarray(dense),
                               rtol=2e-5, atol=2e-6)


def test_sparse_ep_sharded_matches_dense():
    """Sparse dispatch under an ep mesh (shard_map + psum combine)."""
    cfg = _sparse_dense_cfg()
    params = init_random_params(cfg, seed=22)
    lp = jax.tree.map(lambda a: None if a is None else a[0], params.layers,
                      is_leaf=lambda x: x is None)
    rng = np.random.default_rng(4)
    h = jnp.asarray(rng.standard_normal((1, 6, cfg.dim)), jnp.float32)

    dense = _moe_ffn(_replace(cfg, moe_impl="dense"), h, lp)
    plan = make_mesh({"ep": 4})
    with use_plan(plan):
        sparse = jax.jit(
            lambda hh: _moe_ffn(_replace(cfg, moe_impl="sparse"), hh, lp))(h)
    np.testing.assert_allclose(np.asarray(sparse), np.asarray(dense),
                               rtol=2e-5, atol=2e-6)


def test_sparse_flops_scale_with_k_not_E():
    """The point of sparse dispatch: FFN cost ~ k/E of dense (VERDICT #6).
    Measured on the decode-sized gather path, which is O(k) on every backend
    (ragged_dot's CPU fallback lowering is a masked dense over all groups, so
    the prefill path's savings only materialize on TPU)."""
    cfg = _sparse_dense_cfg(dim=128, hidden_dim=256, n_experts=8,
                            n_active_experts=2)
    params = init_random_params(cfg, seed=23)
    lp = jax.tree.map(lambda a: None if a is None else a[0], params.layers,
                      is_leaf=lambda x: x is None)
    h = jnp.ones((1, 8, cfg.dim), jnp.float32)  # N*k = 16 -> gather path

    def flops(impl):
        from dllama_tpu.runtime.introspection import cost_analysis_dict

        fn = jax.jit(lambda hh: _moe_ffn(_replace(cfg, moe_impl=impl), hh, lp))
        # cost_analysis() returns [dict] on this jax, a dict on newer —
        # the shared version-compat accessor owns that decision
        return cost_analysis_dict(fn.lower(h).compile())["flops"]

    dense, sparse = flops("dense"), flops("sparse")
    # dense FFN ~ N*E*3*D*H; sparse ~ N*k*3*D*H (+ routing/gather overhead).
    # E/k = 4 here; require at least 2x measured reduction.
    assert sparse < dense / 2, (sparse, dense)


def test_sparse_ragged_path_matches_dense():
    """Prefill-sized inputs take the sort+ragged_dot branch; same oracle."""
    cfg = _sparse_dense_cfg()
    params = init_random_params(cfg, seed=24)
    lp = jax.tree.map(lambda a: None if a is None else a[0], params.layers,
                      is_leaf=lambda x: x is None)
    rng = np.random.default_rng(9)
    h = jnp.asarray(rng.standard_normal((1, 40, cfg.dim)), jnp.float32)  # N*k=80

    dense = _moe_ffn(_replace(cfg, moe_impl="dense"), h, lp)
    sparse = _moe_ffn(_replace(cfg, moe_impl="sparse"), h, lp)
    np.testing.assert_allclose(np.asarray(sparse), np.asarray(dense),
                               rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("axes", [{"tp": 4}, {"ep": 2, "tp": 2}, {"tp": 8}])
def test_sparse_hidden_sharded_matches_dense(axes, monkeypatch):
    """tp shards the expert-hidden axis: the sparse path must RUN (col-split
    H-partials psum'd, composed with ep) rather than silently paying the
    dense all-experts O(E) fallback (VERDICT r3 weak #3). The dense impl is
    poisoned to prove which path executed; hidden_dim=96 divides by 2/4/8."""
    import dllama_tpu.models.llama as M

    cfg = _sparse_dense_cfg()
    params = init_random_params(cfg, seed=31)
    lp = jax.tree.map(lambda a: None if a is None else a[0], params.layers,
                      is_leaf=lambda x: x is None)
    rng = np.random.default_rng(6)
    h = jnp.asarray(rng.standard_normal((1, 6, cfg.dim)), jnp.float32)

    dense = _moe_ffn(_replace(cfg, moe_impl="dense"), h, lp)

    def _poisoned(*a, **k):
        raise AssertionError("dense fallback taken under a sharded mesh")

    monkeypatch.setattr(M, "_moe_ffn_dense", _poisoned)
    plan = make_mesh(axes)
    with use_plan(plan):
        sparse = jax.jit(
            lambda hh: _moe_ffn(_replace(cfg, moe_impl="auto"), hh, lp))(h)
    np.testing.assert_allclose(np.asarray(sparse), np.asarray(dense),
                               rtol=2e-5, atol=2e-6)


def test_sparse_hidden_sharded_ragged_branch_matches_dense():
    """Same property on the prefill-sized sort+ragged_dot branch."""
    cfg = _sparse_dense_cfg()
    params = init_random_params(cfg, seed=32)
    lp = jax.tree.map(lambda a: None if a is None else a[0], params.layers,
                      is_leaf=lambda x: x is None)
    rng = np.random.default_rng(7)
    h = jnp.asarray(rng.standard_normal((1, 40, cfg.dim)), jnp.float32)

    dense = _moe_ffn(_replace(cfg, moe_impl="dense"), h, lp)
    plan = make_mesh({"ep": 2, "tp": 4})
    with use_plan(plan):
        sparse = jax.jit(
            lambda hh: _moe_ffn(_replace(cfg, moe_impl="sparse"), hh, lp))(h)
    np.testing.assert_allclose(np.asarray(sparse), np.asarray(dense),
                               rtol=2e-5, atol=2e-6)


# ---------------------------------------------------------------------------
# quantized expert planes (VERDICT r4 next #5): Q40/Q80 MoE files keep their
# expert weights quantized on device — 1 B/weight resident — with the dequant
# fused into the consuming dot (gather regime) or expanded per local slice
# (ragged regime); turbo derivation covers the stacked expert axis too.
# ---------------------------------------------------------------------------


def _q40_moe_file(tmp_path, seed=7, **kw):
    p = tiny_header_params(n_experts=E, n_active_experts=K,
                           weight_type=quants.Q40, **kw)
    write_tiny_model(tmp_path / "moe_q40.m", p, np.random.default_rng(seed))
    return tmp_path / "moe_q40.m"


def _logits(params, cfg, tokens, plan=None):
    kv = KVCache.create(cfg, batch_size=tokens.shape[0])
    if plan is not None:
        kv = jax.device_put(kv, kv_cache_sharding(plan, kv))
    ctx = use_plan(plan) if plan is not None else None
    if ctx is not None:
        with ctx:
            out, _ = jax.jit(forward, static_argnums=1)(
                params, cfg, jnp.asarray(tokens), jnp.int32(0), kv)
    else:
        out, _ = jax.jit(forward, static_argnums=1)(
            params, cfg, jnp.asarray(tokens), jnp.int32(0), kv)
    return np.asarray(out)


@pytest.mark.parametrize("n_tokens", [5, 1])  # ragged regime / gather regime
def test_q40_experts_match_dense_load(tmp_path, n_tokens):
    """Quantized expert planes produce the same logits as dense-loading the
    SAME Q40 file (identical dequant values, different residency): both
    sparse regimes — ragged grouped matmul (prefill) and per-row gather
    (decode)."""
    from dllama_tpu.ops.linear import QuantizedWeight

    path = _q40_moe_file(tmp_path)
    tokens = np.asarray([[5, 9, 2, 11, 3][:n_tokens]], dtype=np.int32)
    with mfile.ModelFile.open(path) as mf:
        cfg = ModelConfig.from_header(mf.header)
        pq = load_params_from_mfile(mf, cfg, weight_mode="auto")
        pd = load_params_from_mfile(mf, cfg, weight_mode="f32")
    assert isinstance(pq.layers.we1, QuantizedWeight)
    assert isinstance(pq.layers.we2, QuantizedWeight)
    assert pq.layers.we1.codes.shape == (2, E, cfg.dim, cfg.hidden_dim)
    assert not isinstance(pd.layers.we1, QuantizedWeight)
    lq = _logits(pq, cfg, tokens)
    ld = _logits(pd, cfg, tokens)
    np.testing.assert_allclose(lq, ld, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("mesh_axes", [
    {"ep": 4},
    {"ep": 2, "tp": 2},
    {"tp": 4},  # hidden-sharded quantized planes: scale K/32 axis splits
])
def test_q40_experts_sharded_matches_unsharded(tmp_path, mesh_axes):
    path = _q40_moe_file(tmp_path, hidden_dim=128)  # 128/32=4 scale rows
    tokens = np.asarray([[5, 9, 2, 11, 3]], dtype=np.int32)
    with mfile.ModelFile.open(path) as mf:
        cfg = ModelConfig.from_header(mf.header)
        ref_params = load_params_from_mfile(mf, cfg)
        plan = make_mesh(mesh_axes)
        validate_ep(cfg, plan.axis_size("ep"))
        sharded = load_params_from_mfile(mf, cfg, plan=plan)
    if "ep" in mesh_axes:
        assert sharded.layers.we1.codes.sharding.spec[1] == "ep"
    ref = _logits(ref_params, cfg, tokens)
    got = _logits(sharded, cfg, tokens, plan=plan)
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)


def test_turbo_expert_planes(tmp_path, monkeypatch):
    """turbo/turbo16 derivation covers the stacked expert axis: expert
    leaves become TurboWeight [L, E, ...] and the forward drifts only within
    the per-column requant bound."""
    from dllama_tpu.ops.turbo import TurboWeight, turbo_params

    path = _q40_moe_file(tmp_path)
    tokens = np.asarray([[5, 9, 2, 11, 3]], dtype=np.int32)
    monkeypatch.setenv("DLLAMA_TPU_QUANT_MODE", "fast")
    with mfile.ModelFile.open(path) as mf:
        cfg = ModelConfig.from_header(mf.header, compute_dtype="bfloat16")
        params = load_params_from_mfile(mf, cfg)
    base = _logits(params, cfg, tokens)
    one = np.asarray([[5]], dtype=np.int32)
    base1 = _logits(params, cfg, one)
    for mode, a8 in (("turbo16", False), ("turbo", True)):
        monkeypatch.setenv("DLLAMA_TPU_QUANT_MODE", mode)
        with mfile.ModelFile.open(path) as mf:
            tparams = turbo_params(
                load_params_from_mfile(mf, cfg), a8=a8, free_source=False)
        assert isinstance(tparams.layers.we1, TurboWeight)
        assert tparams.layers.we1.a8 == a8
        assert tparams.layers.we1.w8.shape == (2, E, cfg.dim, cfg.hidden_dim)
        assert tparams.layers.we1.scale.shape == (2, E, cfg.hidden_dim)
        got = _logits(tparams, cfg, tokens)
        # bounded drift, not bit parity: requant + (for a8) activation quant
        rms = float(np.sqrt(np.mean((got - base) ** 2))
                    / (np.sqrt(np.mean(base ** 2)) + 1e-9))
        assert rms < 0.15, (mode, rms)
        # decode regime (per-row gather; a8 = integer dot, a16 = bf16 dot —
        # the a8 choice rides ON the weight): runs and stays close
        got1 = _logits(tparams, cfg, one)
        rms1 = float(np.sqrt(np.mean((got1 - base1) ** 2))
                     / (np.sqrt(np.mean(base1 ** 2)) + 1e-9))
        assert rms1 < 0.15, (mode, rms1)


def test_q40_expert_hbm_estimate_charges_quantized(tmp_path):
    """The budget estimator's q40 charge (1.125 B/weight) now matches what
    the loader actually keeps resident for expert planes."""
    from dllama_tpu.runtime.hbm import estimate_device_bytes, matmul_weight_count

    path = _q40_moe_file(tmp_path)
    with mfile.ModelFile.open(path) as mf:
        cfg = ModelConfig.from_header(mf.header)
        est_q = estimate_device_bytes(cfg, weight_repr="q40", kv_dtype_bytes=4)
        est_d = estimate_device_bytes(cfg, weight_repr="bf16", kv_dtype_bytes=4)
        params = load_params_from_mfile(mf, cfg)
    n_expert_w = 3 * cfg.n_layers * cfg.n_experts * cfg.dim * cfg.hidden_dim
    resident = (params.layers.we1.codes.nbytes + params.layers.we1.scales.nbytes
                + params.layers.we2.codes.nbytes + params.layers.we2.scales.nbytes
                + params.layers.we3.codes.nbytes + params.layers.we3.scales.nbytes)
    # loader keeps ~1.125 B/weight (codes + scales) for the expert planes
    assert resident <= n_expert_w * 1.5
    assert est_q["need_per_device"] < est_d["need_per_device"]
