"""Host-DRAM weight offload (weight_mode="offload", ModelConfig.offload).

The 70B/405B capability (BASELINE config 5, SURVEY.md §7.4 "new design
needed"): per-layer weight stacks live in pinned host memory and stream
through the forward scan, so HBM holds only ~2 layers of weights + KV +
activations at a time. The reference has no analogue (it mmaps shards
resident, nn-network.cpp:809-854).

CPU-tier tests prove placement (layer stacks in pinned_host, everything else
in device memory) and exact value parity with the resident path; the
tpu-marked test proves the device-memory claim on real hardware via the
compiled executable's memory analysis (device args exclude the layer stacks).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.formats import tfile
from dllama_tpu.runtime.engine import InferenceEngine

from helpers import (byte_vocab_tokenizer, require_pinned_host,
                     tiny_header_params, write_tiny_model)


@pytest.fixture(autouse=True)
def _needs_pinned_host():
    """Every test here places weights in pinned_host memory; on jaxlib/CPU
    builds that expose only unpinned_host the capability is absent — skip
    with the probe's reason instead of failing (the offload path itself is
    untouched; real TPU backends pass the probe and run the tests)."""
    require_pinned_host()


@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("offload")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(31)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=48,
                                               n_layers=4), rng)
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    return str(mpath), str(tpath)


def _mem_kinds(tree):
    return {leaf.sharding.memory_kind
            for leaf in jax.tree.leaves(tree) if hasattr(leaf, "sharding")}


def test_offload_places_layer_stacks_host_side(model_files):
    e = InferenceEngine(*model_files, weight_mode="offload", tp=1)
    assert e.cfg.offload
    assert _mem_kinds(e.params.layers) == {"pinned_host"}
    # non-scan params stay resident
    assert e.params.embedding.sharding.memory_kind != "pinned_host"
    assert _mem_kinds(e.params.logits) != {"pinned_host"}
    assert _mem_kinds(e.kv) != {"pinned_host"}


def test_offload_matches_resident_path_exactly(model_files):
    """Same Q40 planes, same math — the streamed forward must be
    value-identical to the resident forward (greedy tokens AND logits)."""
    res = InferenceEngine(*model_files, weight_mode="auto", tp=1)
    off = InferenceEngine(*model_files, weight_mode="offload", tp=1)

    ids = res.tokenizer.encode("hello world")
    la, _ = res.prefill(ids)
    lb, _ = off.prefill(ids)
    np.testing.assert_array_equal(la, lb)

    r1 = res.generate(ids[-1:], 8, stop_on_eos=False)
    r2 = off.generate(ids[-1:], 8, stop_on_eos=False)
    assert r1.tokens == r2.tokens


def test_offload_under_tp(model_files):
    """Offload composes with tensor parallelism: host-placed sharded stacks,
    same tokens as the resident tp run."""
    res = InferenceEngine(*model_files, weight_mode="auto", tp=4)
    off = InferenceEngine(*model_files, weight_mode="offload", tp=4)
    assert _mem_kinds(off.params.layers) == {"pinned_host"}
    ra = res.generate("hello world", 6, stop_on_eos=False)
    rb = off.generate("hello world", 6, stop_on_eos=False)
    assert ra.tokens == rb.tokens


def test_offload_sampled_decode(model_files):
    """The fused on-device sampler runs unchanged over streamed weights."""
    res = InferenceEngine(*model_files, weight_mode="auto", tp=1,
                          temperature=0.8, seed=5)
    off = InferenceEngine(*model_files, weight_mode="offload", tp=1,
                          temperature=0.8, seed=5)
    ra = res.generate("hello world", 8, stop_on_eos=False)
    rb = off.generate("hello world", 8, stop_on_eos=False)
    assert ra.tokens == rb.tokens


@pytest.mark.tpu
def test_offload_device_args_exclude_layer_weights_tpu():
    """On real hardware the compiled step's DEVICE argument bytes must
    exclude the host-resident layer stacks — the executable-level proof that
    a model bigger than HBM can run (its per-layer slices stream in)."""
    from dllama_tpu.formats.mfile import ArchType, RopeType
    from dllama_tpu.models import ModelConfig
    from dllama_tpu.models.llama import forward, init_random_params
    from dllama_tpu.runtime import KVCache

    cfg = ModelConfig(arch=ArchType.LLAMA, dim=1024, hidden_dim=2816,
                      n_layers=8, n_heads=16, n_kv_heads=8, head_dim=64,
                      vocab_size=4096, seq_len=256, norm_epsilon=1e-5,
                      rope_theta=10000.0, rope_type=RopeType.LLAMA,
                      offload=True)
    params = init_random_params(cfg, seed=0)
    dev = jax.devices()[0]
    from jax.sharding import SingleDeviceSharding

    host = SingleDeviceSharding(dev, memory_kind="pinned_host")
    params = params._replace(
        layers=jax.device_put(params.layers, host))
    kv = KVCache.create(cfg)
    tokens = jnp.zeros((1, 1), dtype=jnp.int32)

    compiled = (jax.jit(forward, static_argnums=1)
                .lower(params, cfg, tokens, jnp.int32(0), kv).compile())
    ma = compiled.memory_analysis()
    layer_bytes = sum(leaf.nbytes for leaf in jax.tree.leaves(params.layers))
    assert ma.host_argument_size_in_bytes >= layer_bytes * 0.9
    assert ma.argument_size_in_bytes < layer_bytes
