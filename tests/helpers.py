"""Shared test fixtures: synthetic tiny .m/.t files built with the format writers."""

from __future__ import annotations

import numpy as np

from dllama_tpu.formats import mfile, quants, tfile


def tiny_header_params(arch=mfile.ArchType.LLAMA, dim=64, n_layers=2, n_heads=4,
                       n_kv_heads=2, hidden_dim=96, vocab_size=128, seq_len=64,
                       head_dim=0, weight_type=quants.Q40, rope_type=mfile.RopeType.LLAMA,
                       n_experts=0, n_active_experts=0, **extra):
    """``extra`` adds/overrides raw header keys (e.g. rope_scaling_factor —
    the .m header stores them as ints, reference llm.cpp:85-88)."""
    params = {
        "version": 1,
        "arch_type": int(arch),
        "dim": dim,
        "hidden_dim": hidden_dim,
        "n_layers": n_layers,
        "n_heads": n_heads,
        "n_kv_heads": n_kv_heads,
        "vocab_size": vocab_size,
        "seq_len": seq_len,
        "hidden_act": int(mfile.HiddenAct.SILU),
        "rope_theta": 10000,
        "weight_float_type": weight_type,
        "rope_type": int(rope_type),
        "head_dim": head_dim,
        "norm_epsilon": 5,
        "n_experts": n_experts,
        "n_active_experts": n_active_experts,
    }
    params.update(extra)
    return params


def write_tensor(f, x: np.ndarray, float_type: int) -> None:
    flat = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    if float_type == quants.F32:
        f.write(flat.tobytes())
    elif float_type == quants.F16:
        f.write(flat.astype(np.float16).tobytes())
    elif float_type == quants.Q40:
        f.write(quants.quantize_q40(flat))
    elif float_type == quants.Q80:
        f.write(quants.quantize_q80(flat))
    else:
        raise ValueError(float_type)


def write_tiny_model(path, params: dict, rng: np.random.Generator, scale=0.05):
    """Write a synthetic .m file with random weights; returns the dense weights."""
    dim = params["dim"]
    n_layers = params["n_layers"]
    n_heads = params["n_heads"]
    n_kv_heads = params["n_kv_heads"]
    hidden_dim = params["hidden_dim"]
    vocab = params["vocab_size"]
    head_dim = params.get("head_dim") or dim // n_heads
    q_dim = head_dim * n_heads
    kv_dim = head_dim * n_kv_heads
    wt = params["weight_float_type"]
    qwen3 = params["arch_type"] == int(mfile.ArchType.QWEN3)

    def rand(*shape):
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    dense = {}
    with open(path, "wb") as f:
        mfile.write_header(f, params)

        def put(name, layer, x, ft):
            key = f"{name}.{layer}" if layer >= 0 else name
            dense[key] = x
            write_tensor(f, x, ft)

        put("embedding", -1, rand(vocab, dim), quants.F32)
        n_experts = params.get("n_experts", 0)
        for l in range(n_layers):
            put("block_matmul_q", l, rand(q_dim, dim), wt)
            put("block_matmul_k", l, rand(kv_dim, dim), wt)
            put("block_matmul_v", l, rand(kv_dim, dim), wt)
            put("block_matmul_wo", l, rand(dim, q_dim), wt)
            if n_experts > 0:
                put("block_moe_gate", l, rand(n_experts, dim), quants.F32)
                for e in range(n_experts):
                    put(f"block_expert_w3.{l}", e, rand(hidden_dim, dim), wt)
                    put(f"block_expert_w1.{l}", e, rand(hidden_dim, dim), wt)
                    put(f"block_expert_w2.{l}", e, rand(dim, hidden_dim), wt)
            else:
                put("block_matmul_w1", l, rand(hidden_dim, dim), wt)
                put("block_matmul_w2", l, rand(dim, hidden_dim), wt)
                put("block_matmul_w3", l, rand(hidden_dim, dim), wt)
            if qwen3:
                put("block_norm_q", l, 1.0 + rand(head_dim), quants.F32)
                put("block_norm_k", l, 1.0 + rand(head_dim), quants.F32)
            put("block_norm_0", l, 1.0 + rand(dim), quants.F32)
            put("block_norm_1", l, 1.0 + rand(dim), quants.F32)
        put("final_norm", -1, 1.0 + rand(dim), quants.F32)
        put("final_matmul_logits", -1, rand(vocab, dim), wt)
    return dense


def byte_vocab_tokenizer() -> tfile.TokenizerData:
    """A tokenizer whose regular vocab is all 256 bytes plus a few merges.

    Vocab layout mirrors the reference assumption: regular tokens first,
    bos at index `regular_vocab_size`, special tokens after.
    """
    vocab = [bytes([b]) for b in range(256)]
    scores = [0.0] * 256
    merges = [b"he", b"ll", b"llo", b"hello", b" w", b" wo", b" wor", b" worl",
              b" world", b"<|x|>"]
    for i, m in enumerate(merges[:-1]):
        vocab.append(m)
        scores.append(float(i + 1))
    bos_id = len(vocab)
    vocab += [b"<s>", b"</s>", merges[-1]]
    scores += [0.0, 0.0, 0.0]
    return tfile.TokenizerData(
        vocab=vocab, scores=scores, bos_id=bos_id, add_bos=True,
        eos_token_ids=[bos_id + 1],
        chat_template=None,
        max_token_length=max(len(t) for t in vocab),
    )


def pinned_host_probe():
    """Probe (once per process) which host memory kind this jaxlib can
    actually place arrays in: ``("pinned_host", "")`` when real pinned
    host memory works (the capability the offload weight path requires),
    falling back to ``("unpinned_host", reason)`` on builds that expose
    only that kind (CPU jaxlib — it IS host DRAM there, so the KV-tier
    spill/page-back tests exercise the real transfer path instead of
    capability-skipping), and ``(None, reason)`` when neither places.
    ``reason`` records why the stronger kind(s) failed. Delegates to the
    runtime's own CAPABILITY probe (``kvblocks.probe_host_memory_kind``
    — deliberately NOT the env-overridable ``host_memory_kind``: a
    forced serving knob like ``DLLAMA_KV_HOST_KIND=pinned_host`` must
    never flip capability-gated tests from skip to fail), so the tests
    and the serving tier can never disagree about what the backend can
    do."""
    from dllama_tpu.runtime.kvblocks import probe_host_memory_kind

    return probe_host_memory_kind()


def require_pinned_host():
    """``pytest.skip`` (with the probe's reason) when this jaxlib cannot
    place arrays in pinned_host memory specifically (the offload weight
    path's requirement — an unpinned fallback is not enough there)."""
    import pytest

    kind, reason = pinned_host_probe()
    if kind != "pinned_host":
        pytest.skip(f"jaxlib pinned_host unsupported on this backend: "
                    f"{reason}")


def require_host_memory() -> str:
    """``pytest.skip`` only when NO host memory kind places at all —
    the KV-tier tests run the real spill/page-back path on whatever kind
    the backend offers (``unpinned_host`` on the CPU tier). Returns the
    usable kind."""
    import pytest

    kind, reason = pinned_host_probe()
    if kind is None:
        pytest.skip(f"no jax host memory kind places on this backend: "
                    f"{reason}")
    return kind
