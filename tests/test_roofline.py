"""Roofline observatory (runtime/roofline) + per-op attribution
(profiling.op_attribution) + the perf-regression sentinel
(tools/perf_baseline.py, bench.py --baseline).

Acceptance tier (ISSUE 9): on the CPU mesh, ``GET /debug/roofline``
returns per-program entries whose achieved bytes/FLOPs are derived from
the compile ledger's measured values, with zero post-steady compiles
while the observatory is snapshotting — and a 20% synthetic step-time
regression makes ``bench.py --baseline check`` exit nonzero naming the
regressed metric."""

import json
import os
import subprocess
import sys
import threading
import urllib.request
from http.server import ThreadingHTTPServer

import numpy as np
import pytest

from dllama_tpu.formats import tfile
from dllama_tpu.runtime import introspection, profiling, roofline, telemetry
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.serve.api import _DEBUG_INDEX, _ROUTES, BatchedApiState, \
    make_handler

from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN_XPLANE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "goldens", "synthetic.xplane.pb")

CEIL = roofline.Ceilings(hbm_gbps=770.0, tflops=70.0, source="test")


# -- unit tier: the roofline math ---------------------------------------------


def test_attribute_memory_bound_program():
    # 8 GB streamed in 29 ms at a 770 GB/s ceiling ≈ 36% of roofline
    out = roofline.attribute(8.5e9, 16e9, 29.0, CEIL)
    assert out["bound"] == "memory"
    assert out["achieved_hbm_gbps"] == pytest.approx(8.5e9 / 0.029 / 1e9,
                                                     rel=1e-4)
    assert out["bw_fraction"] == pytest.approx(
        out["achieved_hbm_gbps"] / 770.0, abs=1e-3)
    assert out["roofline_fraction"] == out["bw_fraction"]
    assert 0.0 < out["roofline_fraction"] <= 1.0
    assert "raw_fraction" not in out
    # operational intensity + ridge ride along for plotting
    assert out["flops_per_byte"] == pytest.approx(16e9 / 8.5e9, abs=1e-3)
    assert out["ridge_flops_per_byte"] == pytest.approx(70e12 / 770e9,
                                                        abs=1e-3)


def test_attribute_compute_bound_program():
    # huge FLOPs over few bytes: compute fraction dominates
    out = roofline.attribute(1e6, 5e12, 100.0, CEIL)
    assert out["bound"] == "compute"
    assert out["roofline_fraction"] == out["compute_fraction"]


def test_attribute_zero_flop_program_is_memory_bound():
    # a pure gather/copy program (cost_analysis reports 0 FLOPs) is
    # legitimate: classified on its bandwidth fraction alone
    out = roofline.attribute(1e9, 0.0, 10.0, CEIL)
    assert out["bound"] == "memory"
    assert out["achieved_tflops"] == 0.0
    assert out["compute_fraction"] == 0.0
    assert out["roofline_fraction"] > 0.0
    assert "flops_per_byte" not in out


def test_attribute_fraction_clamped_to_unity():
    # over-counted bytes (e.g. aliased arguments) would put the raw
    # fraction above 1 — the published fraction clamps, the raw is kept
    out = roofline.attribute(770e9, 0.0, 100.0, CEIL)  # 7.7 TB/s "achieved"
    assert out["roofline_fraction"] == 1.0
    assert out["raw_fraction"] == pytest.approx(10.0, rel=1e-3)


def test_attribute_no_evidence_paths():
    assert "no_evidence" in roofline.attribute(1e9, 1e9, None, CEIL)
    assert "no_evidence" in roofline.attribute(1e9, 1e9, 0.0, CEIL)
    assert "no_evidence" in roofline.attribute(0, 0.0, 10.0, CEIL)


def test_snapshot_missing_memory_analysis_is_no_evidence():
    led = introspection.ledger()
    entry = led.register("rooftest-scope", "mystery_step")
    try:
        entry["compiles"] = 1  # compiled but never analyzed
        snap = roofline.snapshot(ceilings=CEIL, scope="rooftest-scope",
                                 publish=False)
        progs = {p["program"]: p for p in snap["programs"]}
        assert "mystery_step" in progs
        assert "no_evidence" in progs["mystery_step"]
        assert "roofline_fraction" not in progs["mystery_step"]
    finally:
        # surgical cleanup — a full ledger reset would wipe every other
        # engine's history from this process-global record
        with led._lock:
            led._programs.pop(("rooftest-scope", "mystery_step"), None)
            led._steady.pop("rooftest-scope", None)


# -- ceilings: probe file vs nameplate ----------------------------------------


def test_nameplate_ceilings_by_device_kind():
    c = roofline.nameplate_ceilings("TPU v5e chip")
    assert (c.tflops, c.hbm_gbps) == (197.0, 819.0)
    assert c.source == "nameplate:v5e"
    # "TPU v5 lite" (the real axon kind) has no v5e substring → default row
    c = roofline.nameplate_ceilings("TPU v5 lite")
    assert c.source == "nameplate:default"
    assert (c.tflops, c.hbm_gbps) == (197.0, 819.0)
    assert roofline.nameplate_ceilings("cpu").source == "nameplate:cpu"


def test_probe_ceilings_from_hw_probe_jsonl(tmp_path):
    p = tmp_path / "hw_probe.jsonl"
    p.write_text(
        json.dumps({"stage": "device", "platform": "tpu",
                    "kind": "TPU v5 lite"}) + "\n"
        + json.dumps({"stage": "hbm_bw", "gib": 2, "chain_gbps": 770.2,
                      "sync_gbps": 31.1}) + "\n"
        + json.dumps({"stage": "mxu", "tflops": 70.4}) + "\n")
    c = roofline.load_ceilings(probe_path=str(p))
    assert c.hbm_gbps == pytest.approx(770.2)
    assert c.tflops == pytest.approx(70.4)
    assert c.source.startswith("probe:")
    assert c.device_kind == "TPU v5 lite"


def test_probe_ceilings_plain_object_and_fallbacks(tmp_path):
    p = tmp_path / "HW_PROBE.json"
    p.write_text(json.dumps({"hbm_gbps": 765.0, "tflops": 69.0}))
    c = roofline.load_ceilings(probe_path=str(p))
    assert (c.hbm_gbps, c.tflops) == (765.0, 69.0)
    # a half-measured probe (no mxu stage) is NOT a ceiling claim: the
    # nameplate fallback applies instead
    half = tmp_path / "half.jsonl"
    half.write_text(json.dumps({"stage": "hbm_bw", "chain_gbps": 700.0}))
    assert roofline.probe_ceilings(str(half)) is None
    c = roofline.load_ceilings(device_kind="v5e", probe_path=str(half))
    assert c.source == "nameplate:v5e"
    # absent file → nameplate too
    c = roofline.load_ceilings(device_kind="v4",
                               probe_path=str(tmp_path / "nope.json"))
    assert c.source == "nameplate:v4"


# -- per-op attribution vs the checked-in xplane fixture ----------------------


def test_op_attribution_against_golden_xplane():
    xs = profiling._load_xplane(GOLDEN_XPLANE)
    out = profiling.op_attribution(xspace=xs, n_steps=1)
    # two device lanes; the primary (largest union) is TPU:0 with 7 ms busy
    assert out["n_lanes"] == 2
    assert out["device_busy_ms_per_step"] == pytest.approx(7.0, abs=1e-6)
    # primary-lane per-op sums: fusion.1(4) + all-reduce.1(2) +
    # wait:rendezvous(1) + fusion.2(2) = 9 ms; ExecuteHelper is noise
    assert out["total_ms_per_step"] == pytest.approx(9.0, abs=1e-6)
    assert not any(o["name"] == "ExecuteHelper" for o in out["top_ops"])
    # class rollup: the collective family (all-reduce + rendezvous wait)
    # is 3 ms of 9; the opaque fusions land honestly in "other"
    assert out["classes"]["collective"]["ms_per_step"] == pytest.approx(
        3.0, abs=1e-6)
    assert out["classes"]["collective"]["frac"] == pytest.approx(3 / 9,
                                                                 abs=1e-4)
    assert out["classes"]["other"]["ms_per_step"] == pytest.approx(6.0,
                                                                   abs=1e-6)
    # sum-vs-union reconcile: nested rows double-count in the sum
    assert out["sum_over_union"] == pytest.approx(9 / 7, abs=0.01)
    top = out["top_ops"][0]
    assert top["name"] == "fusion.1" and top["class"] == "other"


def test_op_attribution_class_regexes():
    cases = {
        "all-reduce.3": "collective",
        "ppermute.1": "collective",
        "dot_general.7": "gemv/matmul",
        "convert_element_type.2": "dequant",
        "top_k.1": "sampling",
        "sort.4": "sampling",
        "argmax.1": "sampling",
        "flash_attention_kernel": "attention",
        "softmax.2": "attention",
        "fusion.12": "other",
    }
    for name, want in cases.items():
        assert profiling.classify_op(name) == want, name


def test_op_attribution_empty_and_missing():
    with pytest.raises(RuntimeError):
        profiling.op_attribution(os.path.join(REPO, "tests", "goldens",
                                              "definitely-not-a-dir"))
    with pytest.raises(ValueError):
        profiling.op_attribution()


# -- acceptance tier: /debug/roofline on the CPU mesh -------------------------


@pytest.fixture(scope="module")
def roofline_server(tmp_path_factory):
    d = tmp_path_factory.mktemp("roofline")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(37)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=256),
                     rng)
    td = byte_vocab_tokenizer()
    td.chat_template = "<|start_header_id|>"  # detected as llama3
    tfile.write_tfile(tpath, td)

    led = introspection.ledger()
    prev_analyze = led.analyze
    led.analyze = True  # the observatory joins against the ledger analysis
    engine = InferenceEngine(str(mpath), str(tpath), temperature=0.0,
                             seed=3, tp=1)
    state = BatchedApiState(engine, n_slots=2)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(state))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        yield f"http://127.0.0.1:{httpd.server_address[1]}", engine
    finally:
        led.analyze = prev_analyze
        httpd.shutdown()
        state.close()
        engine.close()


def _get(url, timeout=60):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _chat(base, text, max_tokens=8):
    req = urllib.request.Request(
        base + "/v1/chat/completions",
        data=json.dumps({"messages": [{"role": "user", "content": text}],
                         "max_tokens": max_tokens,
                         "temperature": 0}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as r:
        return r.status, json.loads(r.read())


def test_debug_roofline_joins_ledger_measurements(roofline_server):
    base, engine = roofline_server
    led = introspection.ledger()
    scope = engine.introspection_scope
    # warm to steady state: same-shaped requests until the scheduler marks
    # the scope steady (two compile-quiet ticks)
    for _ in range(3):
        status, _ = _chat(base, "hello roofline")
        assert status == 200
    assert led.steady(scope)
    compiles_before = led.compile_count(scope)

    status, snap = _get(base + "/debug/roofline")
    assert status == 200
    assert snap["ceilings"]["hbm_gbps"] > 0
    assert snap["ceilings"]["source"].startswith(("probe:", "nameplate:"))
    mine = {p["program"]: p for p in snap["programs"]
            if p["scope"] == scope}
    assert mine, "no per-program entries for the serving engine"

    # every achieved number is DERIVED FROM the compile ledger's measured
    # values: the entry's bytes/FLOPs must equal the ledger analysis, and
    # achieved GB/s must be exactly bytes / wall
    led_snap = led.snapshot()
    led_mine = {p["program"]: p for p in led_snap["programs"]
                if p["scope"] == scope}
    attributed = {n: p for n, p in mine.items()
                  if "roofline_fraction" in p}
    assert attributed, f"no attributed programs in {list(mine)}"
    for name, p in attributed.items():
        analysis = led_mine[name]["analysis"]
        assert p["hbm_bytes"] == analysis["hbm_total_bytes"]
        assert p["flops"] == pytest.approx(analysis.get("flops", 0.0))
        # entries round to 3 decimals; tolerate that plus the rounding
        # of wall_ms itself
        assert p["achieved_hbm_gbps"] == pytest.approx(
            p["hbm_bytes"] / (p["wall_ms"] / 1e3) / 1e9, rel=0.02,
            abs=1e-3)
        assert 0.0 < p["roofline_fraction"] <= 1.0
        assert p["bound"] in ("memory", "compute")
    # the decode program is attributed (the ROADMAP #2 target) and the
    # summary names a decode-family program
    decode_named = [n for n, p in attributed.items()
                    if p["family"] == "decode"]
    assert decode_named
    assert snap.get("summary", {}).get("roofline_fraction", 0) > 0

    # the gauges published the same numbers
    reg = telemetry.registry()
    some = decode_named[0]
    assert reg.gauge(telemetry.ROOFLINE_FRACTION).value(
        scope=scope, program=some) == attributed[some]["roofline_fraction"]
    assert reg.gauge(telemetry.ACHIEVED_HBM_GBPS).value(
        scope=scope, program=some) > 0

    # the observatory is trace-invisible: snapshotting (HTTP + direct),
    # the stats fragment, and more steady traffic cause ZERO compiles
    roofline.snapshot(publish=True)
    telemetry.stats_line(reg)
    status, _ = _chat(base, "hello roofline")
    assert status == 200
    status, _ = _get(base + "/debug/roofline")
    assert status == 200
    assert led.compile_count(scope) == compiles_before, \
        "the roofline observatory caused a recompile"


def test_stats_line_carries_roofline_fraction(roofline_server):
    base, _engine = roofline_server
    _chat(base, "warm for stats")
    line = telemetry.stats_line(telemetry.registry())
    assert "roofline=" in line
    assert "%" in line


def test_debug_index_lists_every_debug_route(roofline_server):
    base, _engine = roofline_server
    status, out = _get(base + "/debug")
    assert status == 200
    eps = out["endpoints"]
    debug_routes = {r for r in _ROUTES if r.startswith("/debug/")}
    assert set(eps) == debug_routes == set(_DEBUG_INDEX)
    assert "/debug/roofline" in eps
    for path, desc in eps.items():
        assert isinstance(desc, str) and desc.strip(), path
    # the index route has its own metric label (not folded into "other")
    with urllib.request.urlopen(base + "/metrics", timeout=60) as r:
        text = r.read().decode()
    assert 'route="/debug",status="200"' in text
    assert 'route="/debug/roofline",status="200"' in text


# -- perf-regression sentinel -------------------------------------------------

sys.path.insert(0, os.path.join(REPO, "tools"))
import perf_baseline  # noqa: E402


def _sample_bench() -> dict:
    return {
        "metric": "decode_tok_per_s_llama8b_q40_1chip",
        "value": 34.54, "git": "abc1234", "device_kind": "TPU v5 lite",
        "roofline": {"roofline_fraction": 0.356},
        "stages": {
            "8b": {"decode_tok_per_s": 34.54, "decode_ms_per_step": 28.949,
                   "fetch_rtt_ms": 68.8},
            "1b": {"decode_tok_per_s": 181.03, "decode_ms_per_step": 5.524,
                   "fetch_rtt_ms": 66.4},
        },
    }


def test_noise_thresholds_are_rtt_floor_aware():
    m = perf_baseline.extract_metrics(_sample_bench())
    # 8b: rtt/(64×28.9 ms) ≈ 3.7% → the flat 10% floor dominates
    assert m["8b.decode_tok_per_s"]["noise_frac"] == pytest.approx(0.10)
    # 1b: rtt/(64×5.5 ms) ≈ 18.8% → the RTT floor dominates
    assert m["1b.decode_tok_per_s"]["noise_frac"] == pytest.approx(
        66.4 / (64 * 5.524), abs=1e-3)
    assert m["headline.roofline_fraction"]["higher_better"] is True


def test_synthetic_20pct_regression_fails_check_naming_metric(tmp_path):
    # THE acceptance criterion: a 20% step-time regression on the 8b
    # preset must exit nonzero and NAME the regressed metric
    base_res = tmp_path / "base.json"
    reg_res = tmp_path / "regressed.json"
    bfile = tmp_path / "PERF_BASELINE.json"
    base_res.write_text(json.dumps(_sample_bench()))
    worse = _sample_bench()
    worse["stages"]["8b"]["decode_ms_per_step"] *= 1.2
    worse["stages"]["8b"]["decode_tok_per_s"] /= 1.2
    reg_res.write_text(json.dumps(worse))

    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    rc_update = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--baseline",
         "update", "--result", str(base_res), "--baseline-file", str(bfile),
         "--name", "test"],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert rc_update.returncode == 0, rc_update.stderr
    # unregressed self-check passes
    ok = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--baseline",
         "check", "--result", str(base_res), "--baseline-file", str(bfile)],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    # the regressed side fails, naming the metric in BOTH the human
    # report and the emitted JSON line
    bad = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--baseline",
         "check", "--result", str(reg_res), "--baseline-file", str(bfile)],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "8b.decode_ms_per_step" in bad.stderr
    line = json.loads(bad.stdout.strip().splitlines()[-1])
    assert line["verdict"] == "regression"
    assert "8b.decode_ms_per_step" in line["regressed"]
    assert "8b.decode_tok_per_s" in line["regressed"]
    # the 1b preset moved 0% — well inside ITS (RTT-floor-raised) noise
    assert "1b.decode_tok_per_s" not in line["regressed"]


def test_zero_baseline_metric_is_evidence_not_noise():
    # a measured 0.0 (fully-overlapped exposed comm — the best possible
    # result) is EVIDENCE: it must be recorded, and a real later growth
    # is a regression, not a divide-by-zero or a silent drop
    base = {"stages": {"multichip": {"comm_exposed_ms": 0.0,
                                     "agg_tok_per_s": 10.0}}}
    m = perf_baseline.extract_metrics(base)
    assert m["multichip.comm_exposed_ms"]["value"] == 0.0
    bl = perf_baseline.make_baseline(base, "zero")
    worse = {"stages": {"multichip": {"comm_exposed_ms": 5.0,
                                      "agg_tok_per_s": 10.0}}}
    cmp = perf_baseline.compare(worse, bl)
    assert [r["metric"] for r in cmp["regressions"]] \
        == ["multichip.comm_exposed_ms"]
    # holding at zero is a perfect hold, not a regression
    cmp = perf_baseline.compare(base, bl)
    assert cmp["verdict"] == "ok" and not cmp["regressions"]
    # ...and sub-resolution timer jitter above an exact zero is NOISE —
    # a 0.05 ms union sliver must not hard-fail CI as a -100% regression
    jitter = {"stages": {"multichip": {"comm_exposed_ms": 0.05,
                                       "agg_tok_per_s": 10.0}}}
    cmp = perf_baseline.compare(jitter, bl)
    assert not cmp["regressions"] and cmp["verdict"] == "ok"
    # the band applies to NONZERO tiny latency baselines too: 0.15 ms →
    # 0.35 ms is the same sub-resolution sliver as 0 → 0.2, not a -133%
    # regression
    tiny = {"stages": {"multichip": {"comm_exposed_ms": 0.15,
                                     "agg_tok_per_s": 10.0}}}
    bl2 = perf_baseline.make_baseline(tiny, "tiny")
    drift = {"stages": {"multichip": {"comm_exposed_ms": 0.35,
                                      "agg_tok_per_s": 10.0}}}
    cmp = perf_baseline.compare(drift, bl2)
    assert not cmp["regressions"] and cmp["verdict"] == "ok"


def test_batched_stage_rtt_floor_uses_its_own_step_count():
    # @b16 stages measure 32 decode steps (bench.py stage_child), not 64:
    # their RTT floor is twice as tall as the same step time unbatched
    bench = {"stages": {
        "1b": {"decode_tok_per_s": 100.0, "decode_ms_per_step": 5.5,
               "fetch_rtt_ms": 66.0},
        "1b@b16": {"decode_tok_per_s": 400.0, "decode_ms_per_step": 5.5,
                   "fetch_rtt_ms": 66.0},
    }}
    m = perf_baseline.extract_metrics(bench)
    plain = m["1b.decode_tok_per_s"]["noise_frac"]
    batched = m["1b@b16.decode_tok_per_s"]["noise_frac"]
    assert plain == pytest.approx(66.0 / (64 * 5.5), abs=1e-3)
    assert batched == pytest.approx(66.0 / (32 * 5.5), abs=1e-3)


def test_corrupt_baseline_file_is_named_rc2_not_a_regression(tmp_path):
    bad = tmp_path / "PERF_BASELINE.json"
    bad.write_text('{"name": "r05", "metrics": {TRUNCATED')
    res = tmp_path / "r.json"
    res.write_text(json.dumps(_sample_bench()))
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--baseline",
         "check", "--result", str(res), "--baseline-file", str(bad)],
        capture_output=True, text=True, cwd=REPO)
    assert p.returncode == 2, p.stdout + p.stderr
    assert "baseline file unusable" in p.stderr
    assert "Traceback" not in p.stderr
    # a missing/corrupt RESULT file is rc 2 too — the regression exit
    # code stays reserved for real regressions
    good_bl = tmp_path / "good_bl.json"
    good_bl.write_text(json.dumps(
        perf_baseline.make_baseline(_sample_bench(), "ok")))
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--baseline",
         "check", "--result", str(tmp_path / "missing.json"),
         "--baseline-file", str(good_bl)],
        capture_output=True, text=True, cwd=REPO)
    assert p.returncode == 2, p.stdout + p.stderr
    assert "result file unusable" in p.stderr
    assert "Traceback" not in p.stderr


def test_skipped_run_is_no_evidence_never_a_verdict(tmp_path):
    bfile = tmp_path / "PERF_BASELINE.json"
    bfile.write_text(json.dumps(
        perf_baseline.make_baseline(_sample_bench(), "test")))
    skipped = {"metric": "decode_tok_per_s_llama8b_q40_1chip", "value": 0.0,
               "skipped": True,
               "skip_reason": "backend unavailable: 5 probe attempts failed",
               "stages": {}}
    cmp = perf_baseline.compare(skipped, json.loads(bfile.read_text()))
    assert cmp["verdict"] == "no_evidence"
    assert not cmp["regressions"] and not cmp["improvements"]
    assert len(cmp["no_evidence"]) == len(
        perf_baseline.extract_metrics(_sample_bench()))
    assert all("skipped" in r["reason"] for r in cmp["no_evidence"])
    # a skipped run must never overwrite a real baseline either
    with pytest.raises(ValueError):
        perf_baseline.make_baseline(skipped, "nope")
    # and the CLI exit code for no-evidence is 0 (green, explicitly
    # unverified — the make perf-check contract on no-hardware runners)
    skipped_path = tmp_path / "skipped.json"
    skipped_path.write_text(json.dumps(skipped))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--baseline",
         "check", "--result", str(skipped_path),
         "--baseline-file", str(bfile)],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "no evidence" in p.stderr


def test_committed_baseline_matches_recorded_bench_numbers():
    # the committed PERF_BASELINE.json must stay loadable and carry the
    # BENCH-trajectory headline (8B decode) with an RTT-aware threshold
    with open(os.path.join(REPO, "PERF_BASELINE.json")) as f:
        doc = json.load(f)
    assert doc["metrics"]["8b.decode_tok_per_s"]["value"] > 0
    assert 0.05 <= doc["metrics"]["8b.decode_tok_per_s"]["noise_frac"] <= 0.5
    # and bench_compare accepts it as a side (satellite: baseline
    # artifacts are comparable)
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_compare.py"),
         os.path.join(REPO, "PERF_BASELINE.json"),
         os.path.join(REPO, "BENCH_r04_manual.json")],
        capture_output=True, text=True, cwd=REPO)
    assert p.returncode == 0, p.stderr
    assert "decode_tok_per_s" in p.stdout
