"""Flight recorder, latency attribution, and Perfetto timeline export
(runtime/flightrec.py + the serving/engine wiring).

The ISSUE-7 acceptance criterion lives here: a continuous-batching run
(the CPU-mesh equivalent of ``bench.py --scenario continuous``) must
export a Perfetto-loadable Chrome trace in which every request's TTFT
attribution phases sum to within 5% of the measured wall TTFT — and the
compile ledger must show zero post-steady compiles with the recorder
enabled (recording is trace-invisible)."""

import json
import pathlib
import threading
import urllib.request

import numpy as np
import pytest

from dllama_tpu.formats import tfile
from dllama_tpu.runtime import flightrec, introspection
from dllama_tpu.runtime import telemetry as tm
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.runtime.serving import BatchScheduler

from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model

GOLDEN = pathlib.Path(__file__).parent / "goldens" / "flight_dump.json"


@pytest.fixture(autouse=True)
def _fresh_recorder():
    flightrec.recorder().reset()
    yield
    flightrec.recorder().reset()


@pytest.fixture(scope="module")
def paged_engine(tmp_path_factory):
    d = tmp_path_factory.mktemp("flightrec")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(31)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96),
                     rng)
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    return InferenceEngine(str(mpath), str(tpath), tp=1, temperature=0.0,
                           seed=3, kv_block_size=16)


# -- recorder unit behavior --------------------------------------------------


def test_rings_bounded_and_idle_ticks_dropped():
    rec = flightrec.FlightRecorder()
    for i in range(flightrec.RING_TICKS + 40):
        rec.begin_tick(queue_depth=1)
        rec.note("admit", i)
        rec.end_tick()
    snap = rec.snapshot()
    assert len(snap["ticks"]) == flightrec.RING_TICKS
    assert snap["ticks"][-1]["tick"] == flightrec.RING_TICKS + 40
    # an idle tick (no decisions, no dispatch, no prefill) is dropped but
    # still numbers — the gap marks the idle stretch in a dump
    rec.begin_tick(queue_depth=0)
    rec.end_tick()
    snap = rec.snapshot()
    assert snap["tick_seq"] == flightrec.RING_TICKS + 41
    assert snap["ticks"][-1]["tick"] == flightrec.RING_TICKS + 40


def test_events_ring_stamps_current_tick():
    rec = flightrec.FlightRecorder()
    rec.note("submit", 7)           # outside any tick: tick 0
    rec.begin_tick(queue_depth=1)
    rec.note("admit", 7, slot=0)
    rec.note_dispatch(1.25, 1, 1)
    rec.note_prefill(7, 0.5, 8)
    rec.end_tick(blocks={"total": 4, "used": 1, "shared": 0})
    evs = rec.snapshot()["events"]
    assert [e["tick"] for e in evs] == [0, 1]
    t = rec.snapshot()["ticks"][-1]
    assert t["decisions"] == [{"event": "admit", "rid": 7, "slot": 0}]
    assert t["dispatch_ms"] == 1.25 and t["prefill_tokens"] == 8
    assert t["blocks"]["total"] == 4


def test_dump_writes_postmortem_and_rate_limits(tmp_path, monkeypatch):
    monkeypatch.setenv("DLLAMA_FLIGHT_DIR", str(tmp_path))
    dumps = tm.registry().counter(tm.FLIGHT_DUMPS)
    d0 = dumps.total(reason="test_reason")
    rec = flightrec.FlightRecorder()
    rec.begin_tick(queue_depth=1)
    rec.note("retire", 7, reason="kv_block_exhaustion", slot=0)
    rec.end_tick()
    path = rec.dump("test_reason", victims=[7], info={"error": "boom"})
    assert path is not None and str(tmp_path) in path
    doc = json.loads(pathlib.Path(path).read_text())
    assert doc["reason"] == "test_reason" and doc["victims"] == [7]
    assert doc["info"]["error"] == "boom"
    assert doc["ticks"][-1]["decisions"][0]["reason"] == "kv_block_exhaustion"
    assert "spans" in doc and "events" in doc
    assert dumps.total(reason="test_reason") == d0 + 1
    # same reason inside the rate window: skipped, no second file
    assert rec.dump("test_reason", victims=[8]) is None
    assert dumps.total(reason="test_reason") == d0 + 1
    # a different reason is a different incident: not rate-limited
    assert rec.dump("other_reason") is not None


# -- golden chrome-trace fixture ---------------------------------------------


def test_golden_fixture_converts_to_valid_chrome_trace():
    """The checked-in mini-run dump converts to strict, Perfetto-shaped
    trace JSON: monotonic per-track timestamps, every submitted request
    a complete flow, tick/counter/slot tracks all present."""
    data = json.loads(GOLDEN.read_text(encoding="utf-8"))
    trace = flightrec.to_chrome_trace(data)
    # strict JSON round-trip (no NaN/Inf, no non-serializable leftovers)
    trace = json.loads(json.dumps(trace, allow_nan=False))
    rids = {e["rid"] for e in data["events"] if e["event"] == "submit"}
    assert rids == {0, 1, 2}
    assert flightrec.validate_chrome_trace(trace, expect_rids=rids) == []
    evs = trace["traceEvents"]
    names = {e["name"] for e in evs}
    assert {"tick 1", "queue_depth", "active_slots", "kv_blocks"} <= names
    # per-slot request tracks: slices for both slots under pid 2
    assert {e["tid"] for e in evs if e.get("pid") == 2 and e["ph"] == "X"} \
        == {0, 1}
    # every phase of the vocabulary the fixture uses is rendered
    phases = {e["args"]["phase"] for e in evs
              if e["ph"] == "X" and e.get("pid") == 2}
    assert {"queue", "admit", "prefill", "prefill_chunk", "decode"} <= phases


def test_validator_catches_regressions_and_broken_flows():
    data = json.loads(GOLDEN.read_text(encoding="utf-8"))
    trace = flightrec.to_chrome_trace(data)
    # missing request
    probs = flightrec.validate_chrome_trace(trace, expect_rids={0, 99})
    assert any("request 99" in p for p in probs)
    # ts regression on a track
    bad = json.loads(json.dumps(trace))
    xs = [e for e in bad["traceEvents"] if e["ph"] == "X"]
    xs[-1]["ts"] = 0.0
    assert any("regressed" in p
               for p in flightrec.validate_chrome_trace(bad))
    # broken flow chain
    bad2 = json.loads(json.dumps(trace))
    for e in bad2["traceEvents"]:
        if e["ph"] == "f" and e.get("id") == 1:
            e["ph"] = "t"
    assert any("flow 1" in p for p in flightrec.validate_chrome_trace(bad2))


def test_timeline_cli_converts_offline(tmp_path):
    from dllama_tpu.serve.cli import main

    out = tmp_path / "trace.json"
    rc = main(["timeline", "--dump", str(GOLDEN), "--out", str(out)])
    assert rc == 0
    trace = json.loads(out.read_text())
    assert trace["traceEvents"]
    assert flightrec.validate_chrome_trace(trace) == []


# -- the ISSUE-7 acceptance run ----------------------------------------------


def _run_wave(engine, sched, prompts, max_tokens=8):
    """Submit a wave, recording an INDEPENDENT wall-TTFT observation per
    request (this thread's clock at the submit call → the first on_token
    callback) — read at different sites than the scheduler's attribution
    stamps, so the ≤5% reassembly assertion is a real cross-check, not
    algebra on the same numbers."""
    t_sub, t_first = {}, {}
    reqs = []
    for i, p in enumerate(prompts):
        ids = engine.tokenizer.encode(p, is_start=True)

        def cb(tok, piece, i=i):
            t_first.setdefault(i, tm.now_ns())

        t_sub[i] = tm.now_ns()
        reqs.append(sched.submit(ids, max_tokens, stop_on_eos=False,
                                 on_token=cb))
    for r in reqs:
        assert r.done.wait(timeout=300)
        assert r.error is None, r.error
    walls = {i: (t_first[i] - t_sub[i]) / 1e6 for i in t_first}
    return reqs, walls


def test_continuous_run_attribution_trace_and_zero_post_steady_compiles(
        paged_engine):
    """6 requests through 2 paged slots (queueing, chunked-prefill
    interleave, a shared prefix): every request's TTFT attribution
    phases sum to within 5% of its wall TTFT, the live rings export a
    validating Chrome trace containing every request as a complete flow,
    and the compile ledger shows ZERO post-steady compiles with the
    recorder on."""
    sched = BatchScheduler(paged_engine, n_slots=2)
    scope = paged_engine.introspection_scope
    led = introspection.ledger()
    retrace = tm.registry().counter(tm.RETRACE_UNEXPECTED)
    try:
        prompts = ["hello world hello world", "hello", " world hello",
                   "hello world hello", "hell", "he"]
        reqs, walls = _run_wave(paged_engine, sched, prompts)

        # -- TTFT attribution: phases reassemble the INDEPENDENTLY
        # measured wall TTFT (≤ 5%; small absolute floor for clock-site
        # skew on sub-ms walls) --
        for i, r in enumerate(reqs):
            bd = r.ttft_breakdown()
            assert bd is not None, r.rid
            total = (bd["queue_ms"] + bd["admission_ms"]
                     + bd["prefill_ms"] + bd["first_decode_ms"])
            assert abs(total - walls[i]) <= 0.05 * walls[i] + 2.0, \
                (r.rid, total, walls[i])
        # the histogram twins were recorded once per request
        h = tm.registry().histogram(tm.TTFT_ATTRIB_MS)
        for ph in ("queue", "admission", "prefill", "first_decode"):
            assert h.count(phase=ph) >= len(reqs), ph
        itl = tm.registry().histogram(tm.ITL_ATTRIB_MS)
        assert itl.count(cause="step") >= 1
        assert itl.count(cause="preempt") >= 1

        # -- flight ring: ticks with decisions + block occupancy --
        snap = flightrec.recorder().snapshot()
        assert snap["ticks"], "no work-carrying ticks recorded"
        assert any(t.get("blocks") for t in snap["ticks"])
        assert any(t.get("dispatch_ms", 0) > 0 for t in snap["ticks"])
        events = snap["events"]
        for r in reqs:
            got = {e["event"] for e in events if e["rid"] == r.rid}
            assert {"submit", "admit", "decode_armed", "first_token",
                    "retire"} <= got, (r.rid, got)

        # -- Chrome trace export of the live rings --
        data = dict(snap)
        data["spans"] = tm.tracer().raw_spans()
        trace = json.loads(json.dumps(flightrec.to_chrome_trace(data),
                                      allow_nan=False))
        assert flightrec.validate_chrome_trace(
            trace, expect_rids={r.rid for r in reqs}) == []

        # -- zero post-steady compiles with the recorder enabled --
        assert led.steady(scope), "scheduler never reached steady state"
        compiles_at_steady = led.compile_count(scope)
        r_before = retrace.total()
        _run_wave(paged_engine, sched, ["hello world", " world"])
        assert led.compile_count(scope) == compiles_at_steady
        assert retrace.total() == r_before
    finally:
        sched.close()


def test_stats_line_shows_blocks_and_attribution(paged_engine):
    """Satellite: the periodic --stats line surfaces the paged block-pool
    gauges (blocks=used/total shared=N) and the TTFT attribution p50s."""
    sched = BatchScheduler(paged_engine, n_slots=2)
    try:
        _run_wave(paged_engine, sched, ["hello world", "hello"])
        line = tm.stats_line()
        assert "blocks=" in line and "/" in line.split("blocks=")[1]
        assert "shared=" in line
        assert "ttft[q/a/p/d]=" in line
    finally:
        sched.close()


# -- HTTP surface: /debug/flight, /debug/timeline, the timing block ----------


@pytest.fixture(scope="module")
def flight_server(tmp_path_factory):
    from http.server import ThreadingHTTPServer

    from dllama_tpu.serve.api import BatchedApiState, make_handler

    d = tmp_path_factory.mktemp("flight_api")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(37)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96),
                     rng)
    td = byte_vocab_tokenizer()
    td.chat_template = "<|start_header_id|>"  # detected as llama3
    tfile.write_tfile(tpath, td)
    eng = InferenceEngine(str(mpath), str(tpath), tp=1, temperature=0.0,
                          seed=3, kv_block_size=16)
    state = BatchedApiState(eng, n_slots=2)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(state))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{port}"
    httpd.shutdown()
    state.close()
    eng.close()


def test_debug_flight_timeline_routes_and_timing_block(flight_server):
    url = flight_server
    body = {"messages": [{"role": "user", "content": "hello world"}],
            "max_tokens": 4, "timing": True}
    req = urllib.request.Request(
        url + "/v1/chat/completions", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=300) as r:
        out = json.loads(r.read())
    # opt-in timing block: phases sum to the reported wall TTFT
    t = out["timing"]
    parts = (t["queue_ms"] + t["admission_ms"] + t["prefill_ms"]
             + t["first_decode_ms"])
    assert abs(parts - t["ttft_ms"]) <= 0.05 * max(t["ttft_ms"], 1e-3)
    assert "decode_step_ms" in t and "preempt_ms" in t
    # without the opt-in the response stays OpenAI-shaped
    del body["timing"]
    req = urllib.request.Request(
        url + "/v1/chat/completions", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=300) as r:
        assert "timing" not in json.loads(r.read())

    with urllib.request.urlopen(url + "/debug/flight", timeout=30) as r:
        flight = json.loads(r.read())
    assert flight["ticks"] and flight["events"]
    with urllib.request.urlopen(url + "/debug/timeline", timeout=30) as r:
        trace = json.loads(r.read())
    assert trace["traceEvents"]
    assert flightrec.validate_chrome_trace(trace) == []
