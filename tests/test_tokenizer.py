"""Tokenizer layer tests — ports of the reference's tokenizer-test.cpp cases
(chat template detection :122-127, EosDetector state machines :129-303) plus
encode/decode tests over the synthetic byte-level vocab."""

import numpy as np
import pytest

from dllama_tpu.tokenizer import (
    ChatItem,
    ChatTemplateGenerator,
    ChatTemplateType,
    EosDetector,
    EosResult,
    Sampler,
    Tokenizer,
)
from dllama_tpu.tokenizer.sampler import softmax, xorshift_random_f32

from helpers import byte_vocab_tokenizer


@pytest.fixture()
def tok():
    return Tokenizer(byte_vocab_tokenizer())


# -- encode ---------------------------------------------------------------


def test_encode_greedy_merges(tok):
    # "hello world" should use the best-score merges: hello (score 4), " world" (6)
    ids = tok.encode("hello world")
    assert ids[0] == tok.bos_id
    pieces = [tok.vocab[i] for i in ids[1:]]
    assert b"".join(pieces) == b"hello world"
    assert b"hello" in pieces and b" world" in pieces


def test_encode_no_bos(tok):
    ids = tok.encode("he", is_start=False)
    assert tok.bos_id not in ids
    assert [tok.vocab[i] for i in ids] == [b"he"]


def test_encode_special_tokens(tok):
    special = b"<|x|>"
    sid = tok.vocab.index(special)
    ids = tok.encode("he<|x|>he", is_start=False, add_special_tokens=True)
    assert sid in ids
    assert [tok.vocab[i] for i in ids] == [b"he", special, b"he"]
    # With add_special_tokens=False the bytes go through regular BPE and the
    # pattern byte-splits instead.
    ids2 = tok.encode("he<|x|>he", is_start=False, add_special_tokens=False)
    assert sid not in ids2
    assert b"".join(tok.vocab[i] for i in ids2) == b"he<|x|>he"


def test_encode_merge_priority_highest_score_wins(tok):
    # "llo" (score 3) outranks "ll" (score 2): "l"+"l"+"o" must end as ["llo"]
    ids = tok.encode("llo", is_start=False)
    assert [tok.vocab[i] for i in ids] == [b"llo"]


# -- streaming decode -----------------------------------------------------


def test_decode_stream_basic(tok):
    hello = tok.vocab.index(b"hello")
    assert tok.decode(tok.bos_id) is None
    assert tok.decode(hello) == "hello"
    assert tok.decode(tok.eos_token_ids[0]) is None


def test_decode_multibyte_utf8_accumulation(tok):
    # 😃 = F0 9F 98 83 fed byte by byte: nothing until the last byte arrives.
    emoji = "😃".encode("utf-8")
    tok.reset_decoder()
    out = [tok.decode(b) for b in emoji]
    assert out[:3] == [None, None, None]
    assert out[3] == "😃"


def test_decode_invalid_utf8_recovery(tok):
    tok.reset_decoder()
    # Lead byte announcing 3 continuations, then an ASCII byte: recovery emits
    # U+FFFD and keeps the stream going (tokenizer.cpp:224-285).
    assert tok.decode(0xF0) is None
    out = tok.decode(ord("Y"))
    assert out == "�Y"


def test_decode_flush_on_eos(tok):
    tok.reset_decoder()
    assert tok.decode(0xF0) is None  # incomplete sequence pending
    flushed = tok.decode(tok.eos_token_ids[0])
    assert flushed == "�"


# -- sampler ---------------------------------------------------------------


def test_sampler_greedy():
    s = Sampler(8, temperature=0.0, topp=0.9, seed=123)
    logits = np.array([0.1, 2.0, -1.0, 1.9, 0, 0, 0, 0], dtype=np.float32)
    assert s.sample(logits) == 1


def test_sampler_seeded_reproducible():
    a = Sampler(64, temperature=0.8, topp=0.9, seed=12345)
    b = Sampler(64, temperature=0.8, topp=0.9, seed=12345)
    rng = np.random.default_rng(0)
    logits = rng.standard_normal(64).astype(np.float32) * 3
    seq_a = [a.sample(logits.copy()) for _ in range(20)]
    seq_b = [b.sample(logits.copy()) for _ in range(20)]
    assert seq_a == seq_b
    assert len(set(seq_a)) > 1  # actually random, not collapsed


def test_sampler_topp_restricts_support():
    # One dominant token: top-p 0.5 must always pick it.
    logits = np.full(32, -10.0, dtype=np.float32)
    logits[7] = 10.0
    s = Sampler(32, temperature=1.0, topp=0.5, seed=999)
    assert all(s.sample(logits.copy()) == 7 for _ in range(10))


def test_xorshift_known_progression():
    # Fixed-seed progression is deterministic and within [0, 1).
    state = 42
    vals = []
    for _ in range(5):
        v, state = xorshift_random_f32(state)
        vals.append(v)
    assert all(0.0 <= v < 1.0 for v in vals)
    v2, _ = xorshift_random_f32(42)
    assert v2 == vals[0]


def test_softmax_matches_reference_semantics():
    x = np.array([1.0, 2.0, 3.0], dtype=np.float32)
    p = softmax(x)
    assert p.sum() == pytest.approx(1.0)
    assert p[2] > p[1] > p[0]


# -- chat template ----------------------------------------------------------


def test_chat_template_detection_llama3():
    # Same jinja snippet the reference test uses (tokenizer-test.cpp:122-127).
    tmpl = ("{% set loop_messages = messages %}{% for message in loop_messages %}"
            "{% set content = '<|start_header_id|>' + message['role'] + '<|end_header_id|>\n\n'"
            "+ message['content'] | trim + '<|eot_id|>' %}{{ content }}{% endfor %}")
    g = ChatTemplateGenerator(tmpl, eos="<eos>")
    assert g.type == ChatTemplateType.LLAMA3


def test_chat_template_llama3_render():
    g = ChatTemplateGenerator(None, eos="<|eot_id|>", type=ChatTemplateType.LLAMA3)
    out = g.generate([ChatItem("system", "be nice"), ChatItem("user", "hi")])
    assert out.content == (
        "<|start_header_id|>system<|end_header_id|>\n\nbe nice<|eot_id|>"
        "<|start_header_id|>user<|end_header_id|>\n\nhi<|eot_id|>"
        "<|start_header_id|>assistant<|end_header_id|>\n\n")


def test_chat_template_llama2_render():
    g = ChatTemplateGenerator(None, eos="</s>", type=ChatTemplateType.LLAMA2)
    out = g.generate([ChatItem("system", "sys"), ChatItem("user", "q1"),
                      ChatItem("assistant", "a1"), ChatItem("user", "q2")])
    assert out.content == ("[INST] <<SYS>>\nsys\n<</SYS>>\n\nq1 [/INST]</s>"
                           "a1</s>[INST] q2 [/INST]</s>")


def test_chat_template_deepseek_public_prompt():
    g = ChatTemplateGenerator(None, eos="<eos>", type=ChatTemplateType.DEEP_SEEK3)
    out = g.generate([ChatItem("user", "hi")])
    assert out.content.endswith("<｜Assistant｜><think>\n")
    assert out.public_prompt == "<think>\n"


def test_chat_template_forced_overrides_detection():
    """--chat-template semantics (reference app.cpp:17-22,109-110): an
    explicit family wins over whatever the tokenizer's stored template says."""
    chatml_tmpl = "{{ '<|im_start|>' + role }}"  # would auto-detect CHATML
    g = ChatTemplateGenerator(chatml_tmpl, eos="</s>",
                              type=ChatTemplateType.LLAMA2)
    assert g.type == ChatTemplateType.LLAMA2
    out = g.generate([ChatItem("user", "q")])
    assert out.content.startswith("[INST]")


def test_chat_template_unknown_raises():
    with pytest.raises(ValueError):
        ChatTemplateGenerator("no markers here", eos="")
    with pytest.raises(ValueError):
        ChatTemplateGenerator(None, eos="")


# -- EosDetector (ports of tokenizer-test.cpp:129-303) ---------------------

EOS_ID = 10000


def test_eos_detector_with_padding():
    d = EosDetector([EOS_ID, EOS_ID + 1], ["<eos>", "<stop>"], 1, 1)

    assert d.append(1, "<") == EosResult.MAYBE_EOS
    assert d.append(2, "eo") == EosResult.MAYBE_EOS
    assert d.append(3, "s>") == EosResult.EOS
    assert d.get_delta() is None

    d.reset()
    assert d.append(1, "<") == EosResult.MAYBE_EOS
    assert d.append(2, "stop") == EosResult.MAYBE_EOS
    assert d.append(3, "> ") == EosResult.EOS
    assert d.get_delta() is None

    d.reset()
    assert d.append(1, " ") == EosResult.NOT_EOS
    assert d.get_delta() == " "

    d.reset()
    assert d.append(1, "!<") == EosResult.MAYBE_EOS
    assert d.append(2, "eos") == EosResult.MAYBE_EOS
    assert d.append(3, "> ") == EosResult.EOS
    assert d.get_delta() == "!"

    d.reset()
    assert d.append(1, "<eo") == EosResult.MAYBE_EOS
    assert d.append(2, "s>XY") == EosResult.NOT_EOS
    assert d.get_delta() == "<eos>XY"

    d.reset()
    assert d.append(1, "<eo") == EosResult.MAYBE_EOS
    assert d.append(EOS_ID, None) == EosResult.EOS
    assert d.get_delta() == "<eo"

    d.reset()
    assert d.append(EOS_ID, None) == EosResult.EOS
    assert d.get_delta() is None

    d.reset()
    assert d.append(1, "x") == EosResult.NOT_EOS
    assert d.get_delta() == "x"
    d.reset()
    assert d.append(2, None) == EosResult.NOT_EOS
    assert d.get_delta() is None


def test_eos_detector_with_long_padding():
    d = EosDetector([EOS_ID], ["|end|"], 5, 5)
    assert d.append(1, "lipsum") == EosResult.NOT_EOS
    assert d.get_delta() == "lipsum"

    d.reset()
    assert d.append(1, "lorem") == EosResult.NOT_EOS
    assert d.get_delta() == "lorem"

    d.reset()
    assert d.append(1, "lorem|") == EosResult.MAYBE_EOS
    assert d.append(2, "enQ") == EosResult.NOT_EOS
    assert d.get_delta() == "lorem|enQ"


def test_eos_detector_without_padding():
    d = EosDetector([EOS_ID], ["<eos>"], 0, 0)
    assert d.append(1, "<") == EosResult.MAYBE_EOS
    assert d.append(2, "eo") == EosResult.MAYBE_EOS
    assert d.append(3, "s>") == EosResult.EOS
    assert d.get_delta() is None

    d.reset()
    assert d.append(1, " <") == EosResult.NOT_EOS
    assert d.get_delta() == " <"

    d.reset()
    assert d.append(1, "<eos") == EosResult.MAYBE_EOS
    assert d.append(2, "> ") == EosResult.NOT_EOS
    assert d.get_delta() == "<eos> "

    d.reset()
    assert d.append(EOS_ID, None) == EosResult.EOS
    assert d.get_delta() is None

    d.reset()
    assert d.append(EOS_ID, "😃") == EosResult.EOS
    assert d.get_delta() == "😃"


# -- heap merge vs reference rescan (VERDICT round-2 #8) -------------------


def _rescan_merge(tok, tokens):
    """The reference's O(n²) rescan-per-round merge (tokenizer.cpp:349-377),
    kept here as the behavioral oracle for the production heap merge."""
    tokens = list(tokens)
    while True:
        best_score, best_idx, best_id = -1e10, -1, -1
        for j in range(len(tokens) - 1):
            merged = tok.vocab[tokens[j]] + tok.vocab[tokens[j + 1]]
            mid = tok._regular.get(merged)
            if mid is not None and tok.scores[mid] > best_score:
                best_score, best_idx, best_id = tok.scores[mid], j, mid
        if best_idx == -1:
            break
        tokens[best_idx:best_idx + 2] = [best_id]
    return tokens


def _merge_rich_tokenizer():
    """A vocab with layered merges and deliberate score ties (equal-score
    pairs at different positions exercise the leftmost-wins rule)."""
    from dllama_tpu.formats import tfile

    vocab = [bytes([b]) for b in range(256)]
    scores = [0.0] * 256
    merges = [(b"ab", 3.0), (b"bc", 3.0), (b"cd", 3.0), (b"abc", 5.0),
              (b"bcd", 5.0), (b"abcd", 7.0), (b"aa", 1.0), (b"aaa", 1.0),
              (b"ba", 2.0), (b"ca", 2.0), (b"da", 2.0), (b"ad", 3.0),
              (b"dd", 0.5), (b"cdd", 4.0), (b" a", 2.5), (b" ab", 2.5)]
    for piece, score in merges:
        vocab.append(piece)
        scores.append(score)
    bos = len(vocab)
    vocab.append(b"<s>")
    scores.append(0.0)
    return Tokenizer(tfile.TokenizerData(
        vocab=vocab, scores=scores, bos_id=bos, add_bos=False,
        eos_token_ids=[], chat_template=None,
        max_token_length=max(len(v) for v in vocab)))


def test_heap_merge_matches_rescan_randomized():
    t = _merge_rich_tokenizer()
    rng = np.random.default_rng(123)
    alphabet = "abcd "
    for trial in range(200):
        n = int(rng.integers(0, 40))
        s = "".join(alphabet[i] for i in rng.integers(0, len(alphabet), n))
        base = [t._regular[bytes([b])] for b in s.encode()]
        assert t._merge(list(base)) == _rescan_merge(t, base), repr(s)


def test_heap_merge_matches_rescan_on_byte_vocab(tok):
    rng = np.random.default_rng(9)
    for trial in range(50):
        n = int(rng.integers(0, 60))
        ids = [int(x) for x in rng.integers(0, 256, n)]
        assert tok._merge(list(ids)) == _rescan_merge(tok, ids)


def test_encode_100k_chars_under_2s(tok):
    import time

    text = "hello world " * 8500  # ~102k chars, merge-heavy on this vocab
    t0 = time.perf_counter()
    ids = tok.encode(text)
    dt = time.perf_counter() - t0
    assert dt < 2.0, f"encode took {dt:.2f}s"
    assert tok.decode_all(ids) == text


def test_decode_overlong_utf8_replaced_not_crash(tok):
    """A length-complete but INVALID UTF-8 sequence (overlong f0 88 8f 83)
    must stream as replacement characters, not raise — regression for a
    crash surfaced by random-token serving streams."""
    out = []
    for b in (0xF0, 0x88, 0x8F, 0x83, ord("A")):
        p = tok.decode(b)
        if p is not None:
            out.append(p)
    s = "".join(out)
    assert "A" in s and "�" in s


def test_decode_surrogate_bytes_replaced(tok):
    # ed a0 80 is a UTF-8-encoded surrogate half: structurally complete,
    # strictly invalid
    out = [p for b in (0xED, 0xA0, 0x80, ord("B")) if (p := tok.decode(b))]
    s = "".join(out)
    assert "B" in s and "�" in s
