"""dlint self-tests: every analyzer must fire on its seeded-violation
fixture (right rule id, right line), the live repo must scan clean, and
a suppression comment must suppress exactly one finding.

All fixture trees are built under tmp_path with the repo's layout
(``dllama_tpu/...``); no jax anywhere — the lint must run on bare CI
runners."""

from __future__ import annotations

import json
import pathlib
import subprocess
import sys
import textwrap
from types import SimpleNamespace

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tools.dlint import Project, all_rules, get_rule  # noqa: E402
from tools.dlint.core import run_rule  # noqa: E402


def _tree(tmp_path, files: dict[str, str]) -> Project:
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    return Project(tmp_path)


def _run(name: str, project: Project):
    return run_rule(get_rule(name), project)


# -- framework ----------------------------------------------------------------

def test_all_rules_registered():
    names = set(all_rules())
    assert {"jit-entry", "shard-map-shim", "tracer-hazard", "guarded-twin",
            "thread-ownership", "lock-guard", "lock-order",
            "metrics-names", "exception-hygiene", "route-labels",
            "failpoint-sites", "span-phases", "pallas-gate",
            "tenant-reasons"} <= names


def test_live_repo_scans_clean():
    """The acceptance bar: python -m tools.dlint exits 0 on the repo."""
    from tools.dlint.core import run_rules

    rc = run_rules(Project(REPO), stream=open("/dev/null", "w"))
    assert rc == 0


def test_json_summary_cli():
    out = subprocess.run(
        [sys.executable, "-m", "tools.dlint", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stderr
    payload = json.loads(out.stdout.strip().splitlines()[-1])
    assert payload["ok"] is True
    assert payload["findings"] == 0
    assert payload["rules"] >= 12


def test_unknown_rule_is_an_error():
    out = subprocess.run(
        [sys.executable, "-m", "tools.dlint", "--only", "no-such-rule"],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert out.returncode != 0
    assert "unknown rule" in out.stderr


# -- trace safety -------------------------------------------------------------

_TRACED_FIXTURE = {
    "dllama_tpu/models/bad.py": """\
        import time
        import numpy as np


        def my_sampled_step(params, cfg, x, kv):
            t = time.time()
            if x > 0:
                y = bool(x)
            z = x.item()
            r = np.random.rand()
            return x
        """,
    "dllama_tpu/runtime/engine.py": """\
        from ..models.bad import my_sampled_step


        def build(engine):
            return plan_scoped_jit(my_sampled_step, static_argnums=1)
        """,
}


def test_tracer_hazards_fire_with_rule_and_line(tmp_path):
    project = _tree(tmp_path, _TRACED_FIXTURE)
    res = _run("tracer-hazard", project)
    got = {(f.rule, f.lineno) for f in res.findings}
    assert ("tracer-ambient", 6) in got      # time.time()
    assert ("tracer-branch", 7) in got       # if x > 0
    assert ("tracer-host-sync", 8) in got    # bool(x)
    assert ("tracer-host-sync", 9) in got    # .item()
    assert ("tracer-ambient", 10) in got     # np.random.rand()
    assert all(f.path == "dllama_tpu/models/bad.py" for f in res.findings)


def test_suppression_suppresses_exactly_one_finding(tmp_path):
    files = dict(_TRACED_FIXTURE)
    files["dllama_tpu/models/bad.py"] = files[
        "dllama_tpu/models/bad.py"].replace(
        "t = time.time()",
        "t = time.time()  # dlint: disable=tracer-ambient")
    project = _tree(tmp_path, files)
    res = _run("tracer-hazard", project)
    assert len(res.suppressed) == 1
    assert res.suppressed[0].rule == "tracer-ambient"
    assert res.suppressed[0].lineno == 6
    # the other findings (including the OTHER tracer-ambient) still fire
    got = {(f.rule, f.lineno) for f in res.findings}
    assert ("tracer-ambient", 10) in got
    assert ("tracer-host-sync", 9) in got


def test_raw_jit_fires_and_static_gates_untaint(tmp_path):
    project = _tree(tmp_path, {
        "dllama_tpu/models/rawjit.py": """\
            import jax


            def g(x):
                return x


            h = jax.jit(g)
            """,
        "dllama_tpu/ops/gates.py": """\
            def is_fast(x):  # dlint: static-fn
                return str(x.dtype) == "bfloat16"


            def op(params, cfg, x):
                fast = is_fast(x)
                if fast:
                    return x
                return x + 1
            """,
        "dllama_tpu/runtime/wire.py": """\
            from ..ops.gates import op


            def build():
                return plan_scoped_jit(op)
            """,
    })
    res = _run("jit-entry", project)
    assert [(f.rule, f.path, f.lineno) for f in res.findings] == [
        ("jit-entry", "dllama_tpu/models/rawjit.py", 8)]
    # the declared static-fn gate keeps `if fast:` out of tracer-branch
    res = _run("tracer-hazard", project)
    assert res.findings == []


def test_shard_map_shim_fires_on_code_not_prose(tmp_path):
    project = _tree(tmp_path, {
        "dllama_tpu/parallel/qc.py": '''\
            """Docs may name jax.experimental.shard_map freely."""
            # a comment naming jax.shard_map is fine too
            from jax.experimental.shard_map import shard_map
            ''',
    })
    res = _run("shard-map-shim", project)
    assert [(f.path, f.lineno) for f in res.findings] == [
        ("dllama_tpu/parallel/qc.py", 3)]


def test_guarded_twin_completeness(tmp_path):
    project = _tree(tmp_path, {
        "dllama_tpu/models/llama.py": """\
            def fancy_sampled_step(params, cfg, tokens, pos, kv):
                return tokens


            def sampled_step(params, cfg, tokens, pos, kv):
                return tokens


            def sampled_step_guarded(params, cfg, tokens, pos, kv, poison):
                return tokens
            """,
    })
    res = _run("guarded-twin", project)
    assert [(f.rule, f.lineno) for f in res.findings] == [
        ("guarded-twin", 1)]
    assert "fancy_sampled_step" in res.findings[0].message


# -- thread ownership ---------------------------------------------------------

def test_monitor_path_reaching_loop_owned_mutator_fires(tmp_path):
    project = _tree(tmp_path, {
        "dllama_tpu/runtime/kvblocks.py": """\
            class BlockPool:
                def alloc(self):  # dlint: owner=loop-thread
                    return 1
            """,
        "dllama_tpu/runtime/serving.py": """\
            class Sched:
                def _on_stall(self, info):  # dlint: owner=monitor-thread
                    self._cleanup()

                def _cleanup(self):
                    self.pool.alloc()

                def _on_crash(self, exc):  # dlint: owner=loop-thread
                    pass

                def _fail_all(self, msg):  # dlint: owner=any
                    pass
            """,
        "dllama_tpu/runtime/watchdog.py": "",
    })
    res = _run("thread-ownership", project)
    assert len(res.findings) == 1
    f = res.findings[0]
    assert f.lineno == 2 and "BlockPool.alloc" in f.message \
        and "_cleanup" in f.message


def test_missing_supervision_annotation_fires(tmp_path):
    project = _tree(tmp_path, {
        "dllama_tpu/runtime/serving.py": """\
            class Sched:
                def helper(self):  # dlint: owner=any
                    pass

                def _on_stall(self, info):
                    pass
            """,
        "dllama_tpu/runtime/kvblocks.py": "",
        "dllama_tpu/runtime/watchdog.py": "",
    })
    res = _run("thread-ownership", project)
    assert [f.lineno for f in res.findings] == [5]
    assert "owner=" in res.findings[0].message


def test_unguarded_shared_state_write_fires(tmp_path):
    project = _tree(tmp_path, {
        "dllama_tpu/runtime/serving.py": """\
            import threading


            class Sched:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._queue = []  # dlint: guarded-by=_lock

                def good(self, req):
                    with self._lock:
                        self._queue.append(req)

                def bad(self, req):
                    self._queue.append(req)
                    self._queue = []
            """,
        "dllama_tpu/runtime/kvblocks.py": "",
        "dllama_tpu/runtime/watchdog.py": "",
    })
    res = _run("lock-guard", project)
    assert [f.lineno for f in res.findings] == [14, 15]
    assert all("_queue" in f.message for f in res.findings)


def test_lock_order_cycle_fires(tmp_path):
    project = _tree(tmp_path, {
        "dllama_tpu/runtime/locky.py": """\
            import threading


            class Alpha:
                def __init__(self):
                    self._lock = threading.Lock()

                def hold_alpha(self):
                    with self._lock:
                        cross_to_beta()

                def take_alpha(self):
                    with self._lock:
                        pass


            class Beta:
                def __init__(self):
                    self._lock = threading.Lock()

                def hold_beta(self):
                    with self._lock:
                        cross_to_alpha()


            def cross_to_beta():
                Beta().hold_beta()


            def cross_to_alpha():
                Alpha().take_alpha()
            """,
    })
    res = _run("lock-order", project)
    assert any("cycle" in f.message for f in res.findings)
    msg = next(f.message for f in res.findings if "cycle" in f.message)
    assert "Alpha._lock" in msg and "Beta._lock" in msg


def test_lock_self_deadlock_fires(tmp_path):
    project = _tree(tmp_path, {
        "dllama_tpu/runtime/locky.py": """\
            import threading


            class Gamma:
                def __init__(self):
                    self._lock = threading.Lock()

                def outer(self):
                    with self._lock:
                        self.inner()

                def inner(self):
                    with self._lock:
                        pass
            """,
    })
    res = _run("lock-order", project)
    assert any("self-deadlock" in f.message for f in res.findings)


# -- the six migrated rules fire on seeded fixtures ---------------------------

def test_metrics_names_fixture_violations(tmp_path):
    from tools.dlint import metrics_names

    project = _tree(tmp_path, {
        "PERF.md": "dllama_counter_total\n",
        "dllama_tpu/x.py": 'NAME = "dllama_orphan_total"\n',
    })
    specs = {
        "dllama_counter": SimpleNamespace(kind="counter", help="x"),
        "dllama_Bad": SimpleNamespace(kind="gauge", help="y"),
    }
    findings, _ = metrics_names.check(project, specs=specs)
    msgs = "\n".join(f.message for f in findings)
    assert "must end in _total" in msgs
    assert "violates" in msgs                       # dllama_Bad naming
    assert "dllama_orphan_total" in msgs            # unregistered literal


def test_exception_hygiene_fixture_violations(tmp_path):
    project = _tree(tmp_path, {
        "dllama_tpu/runtime/bad.py": """\
            def f():
                try:
                    pass
                except:
                    pass


            def g():
                try:
                    pass
                except Exception:
                    return None
            """,
    })
    res = _run("exception-hygiene", project)
    assert [f.lineno for f in res.findings] == [4, 11]
    assert "bare" in res.findings[0].message
    assert "BLE001" in res.findings[1].message


def test_route_labels_fixture_violation(tmp_path):
    project = _tree(tmp_path, {
        "dllama_tpu/serve/api.py": """\
            _ROUTES = ("/v1/x", "/debug")
            _DEBUG_INDEX = {}


            class H:
                def do(self):
                    path = "/v1/x"
                    if path == "/v1/unregistered":
                        pass
            """,
    })
    res = _run("route-labels", project)
    assert any("/v1/unregistered" in f.message and f.lineno == 8
               for f in res.findings)


def test_failpoint_sites_fixture_violations(tmp_path):
    project = _tree(tmp_path, {
        "dllama_tpu/runtime/failpoints.py": '''\
            """Registry.

            * ``site_a`` — documented but never fired
            """
            ''',
        "dllama_tpu/runtime/uses.py": """\
            from . import failpoints


            def f():
                failpoints.fire("site_b")
            """,
    })
    res = _run("failpoint-sites", project)
    msgs = "\n".join(f.message for f in res.findings)
    assert "site_b" in msgs and "not documented" in msgs
    assert "site_a" in msgs and "never fired" in msgs


def test_span_phases_fixture_violation(tmp_path):
    from tools.dlint import span_phases

    project = _tree(tmp_path, {
        "dllama_tpu/runtime/emits.py": """\
            from . import telemetry


            def f(rid, t0, t1):
                telemetry.tracer().emit(rid, "bogus_phase", t0, t1)
            """,
    })
    findings, _ = span_phases.check(project, phases=(("queue",), ()))
    msgs = "\n".join(f.message for f in findings)
    assert "bogus_phase" in msgs                    # emitted, not in PHASES
    assert "queue" in msgs                          # documented, never emitted


def test_span_phases_router_vocabulary(tmp_path):
    """RouterSpanRing.emit_span literals are held to ROUTER_PHASES the
    same way tracer().emit literals are held to PHASES."""
    from tools.dlint import span_phases

    project = _tree(tmp_path, {
        "dllama_tpu/serve/rt.py": """\
            def f(spans, rid, t0, t1):
                spans.emit_span(rid, "rt_bogus", t0, t1)
            """,
    })
    findings, _ = span_phases.check(
        project, phases=((), ("rt_queue",)))
    msgs = "\n".join(f.message for f in findings)
    assert "rt_bogus" in msgs                   # emitted, not in vocabulary
    assert "rt_queue" in msgs                   # documented, never emitted


def test_pallas_gate_fixture_violation(tmp_path):
    """A new kernel module dispatching pl.pallas_call without consulting
    quant_matmul.pallas_mode_gate fires pallas-gate at the call line; a
    module that routes through the gate — and the exempt legacy modules —
    stay clean."""
    project = _tree(tmp_path, {
        "dllama_tpu/ops/rogue_kernel.py": """\
            from jax.experimental import pallas as pl


            def _kernel(x_ref, o_ref):
                o_ref[:] = x_ref[:]


            def rogue(x):
                import os
                interpret = os.environ.get("MY_OWN_KNOB") == "1"
                return pl.pallas_call(_kernel, out_shape=None,
                                      interpret=interpret)(x)
            """,
        "dllama_tpu/ops/good_kernel.py": """\
            from jax.experimental import pallas as pl

            from .quant_matmul import pallas_mode_gate


            def _kernel(x_ref, o_ref):
                o_ref[:] = x_ref[:]


            def good(x):
                kw = pallas_mode_gate(False)
                if kw is None:
                    return None
                return pl.pallas_call(_kernel, out_shape=None, **kw)(x)
            """,
        "dllama_tpu/ops/sneaky_kernel.py": """\
            from jax.experimental import pallas as pl

            from .quant_matmul import pallas_mode_gate  # imported, never CALLED


            def sneaky(x):
                return pl.pallas_call(lambda i, o: None, out_shape=None)(x)
            """,
        "dllama_tpu/ops/quant_matmul.py": """\
            from jax.experimental import pallas as pl


            def pallas_mode_gate(fast):
                return {"interpret": True}


            def run(x):
                return pl.pallas_call(lambda i, o: None, out_shape=None)(x)
            """,
    })
    res = _run("pallas-gate", project)
    assert len(res.findings) == 2, [str(f) for f in res.findings]
    by_path = {f.path.rsplit("/", 1)[-1]: f for f in res.findings}
    # a module with its own env knob fires; so does one that merely
    # IMPORTS the gate without calling it (an unused import is not a
    # consult)
    assert set(by_path) == {"rogue_kernel.py", "sneaky_kernel.py"}
    f = by_path["rogue_kernel.py"]
    assert "pallas_mode_gate" in f.message
    # the finding anchors the pallas_call line itself
    src = (tmp_path / "dllama_tpu/ops/rogue_kernel.py").read_text()
    assert "pl.pallas_call" in src.splitlines()[f.lineno - 1]


def test_pallas_gate_live_repo_kernels_routed():
    """The real kernel modules: paged_attention (and any future kernel
    module) must consult the shared gate; the two legacy modules are the
    documented exempt list."""
    res = _run("pallas-gate", Project(REPO))
    assert not res.findings, [str(f) for f in res.findings]


def test_shard_map_wrapper_cli_still_works():
    """The historical CLI entry points survive as thin wrappers."""
    out = subprocess.run(
        [sys.executable, "tools/check_shard_map_shim.py"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "shard-map-shim" in out.stdout


# -- tenant-reasons ------------------------------------------------------------

def test_tenant_reasons_fixture(tmp_path):
    """Both closed-world directions on a seeded fixture: an emit site
    naming an undeclared reason fires, and a declared reason with no
    emit site fires (injectable vocabulary, no repo import)."""
    from tools.dlint import tenant_names

    proj = _tree(tmp_path, {
        "dllama_tpu/runtime/tenancy.py": '''
            # * ``queue_full`` — the shared bound shed the submit.
            # * ``ghost_reason`` — declared but never emitted.
            ADMIT_REASONS = ("queue_full", "ghost_reason")
        ''',
        "dllama_tpu/runtime/serving.py": '''
            class S:
                def submit(self, tenant):
                    self._tenancy.note_shed(tenant, "queue_full")
                    self.flight.note("shed", reason="queue_full",
                                     tenant=tenant)
                    self.flight.note("defer", rid,
                                     reason="mystery_reason",
                                     tenant=tenant)
                    # lifecycle reasons are out of scope for the rule
                    self.flight.note("timeout", rid, reason="queued",
                                     tenant=tenant)
        ''',
        "dllama_tpu/serve/router.py": "",
        "PERF.md": "`dllama_tenant_shed_total{tenant,reason}` — sheds.\n"
                   "Reasons: queue_full, ghost_reason.\n",
    })
    specs = {"dllama_tenant_shed_total": SimpleNamespace(
        kind="counter", help="sheds")}
    findings, _ = tenant_names.check(
        proj, vocab=(("queue_full", "ghost_reason"), specs))
    msgs = [f.message for f in findings]
    assert any("mystery_reason" in m and "not in tenancy.ADMIT_REASONS"
               in m for m in msgs), msgs
    assert any("ghost_reason" in m and "no emit site" in m
               for m in msgs), msgs
    # nothing else fires: the in-scope emit sites are vocabulary-clean,
    # the docs cover the metric family and both declared reasons
    assert len(findings) == 2, msgs
    assert all(f.rule == "tenant-reasons" for f in findings)
    # the finding anchors the offending emit line
    bad = next(f for f in findings if "mystery_reason" in f.message)
    src = (tmp_path / "dllama_tpu/runtime/serving.py").read_text()
    assert 'reason="mystery_reason"' in "".join(
        src.splitlines()[bad.lineno - 1:bad.lineno + 1])


def test_tenant_reasons_live_repo_clean():
    res = _run("tenant-reasons", Project(REPO))
    assert not res.findings, [str(f) for f in res.findings]


def test_tenant_wrapper_cli_still_works():
    out = subprocess.run(
        [sys.executable, "tools/check_tenant_names.py"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "tenant-reasons" in out.stdout


# -- cycle-robustness regressions (review findings) ---------------------------

def test_ownership_violation_behind_call_cycle_found_for_every_entry(tmp_path):
    """A cycle in the pass-through call graph must not hide a violation
    from LATER entry points (the memo-under-cycle-cut bug): both
    _on_stall and _fail_all reach the loop-owned mutator through the
    chainB<->chainC cycle, and each finding's trail must name its OWN
    entry point."""
    project = _tree(tmp_path, {
        "dllama_tpu/runtime/kvblocks.py": """\
            class BlockPool:
                def alloc(self):  # dlint: owner=loop-thread
                    return 1
            """,
        "dllama_tpu/runtime/serving.py": """\
            class Sched:
                def _on_stall(self, info):  # dlint: owner=monitor-thread
                    self.chain_c()

                def _fail_all(self, msg):  # dlint: owner=any
                    self.chain_b()

                def chain_b(self):
                    self.chain_c()

                def chain_c(self):
                    self.chain_b()
                    self.pool.alloc()

                def _on_crash(self, exc):  # dlint: owner=loop-thread
                    pass
            """,
        "dllama_tpu/runtime/watchdog.py": "",
    })
    res = _run("thread-ownership", project)
    by_entry = {f.lineno: f.message for f in res.findings}
    assert set(by_entry) == {2, 5}            # _on_stall AND _fail_all
    assert "Sched._on_stall" in by_entry[2]
    assert "Sched._fail_all" in by_entry[5]   # its own trail, not a stale one
    assert "Sched._on_stall" not in by_entry[5]


def test_lock_order_edges_survive_call_cycles_and_site_order(tmp_path):
    """Transitive lock sets are a fixpoint, not a cycle-cut memo: the
    earlier hold-site visiting the h<->k cycle must not cache an empty
    set for k and hide the later site's edge (detection would otherwise
    depend on call-site order)."""
    project = _tree(tmp_path, {
        "dllama_tpu/runtime/locky.py": """\
            import threading


            class G:
                def __init__(self):
                    self._l1 = threading.Lock()
                    self._l2 = threading.Lock()
                    self._l3 = threading.Lock()

                def h(self):
                    with self._l1:
                        self.k()

                def k(self):
                    self.h()

                def early_site(self):
                    with self._l3:
                        self.h()

                def late_site(self):
                    with self._l2:
                        self.k()
            """,
    })
    res = _run("lock-order", project)
    # h() holds _l1 and (via the cycle) re-enters itself: self-deadlock
    assert any("self-deadlock" in f.message and "G._l1" in f.message
               for f in res.findings)
    # and the late site's l2->l1 edge must feed cycle detection: prove
    # the edge exists by closing the loop l1->l2 and expecting a cycle
    files2 = {
        "dllama_tpu/runtime/locky.py": (tmp_path / "dllama_tpu/runtime/locky.py").read_text().replace(
            "    def k(self):\n        self.h()\n",
            "    def k(self):\n        self.h()\n\n"
            "    def close_loop(self):\n"
            "        with self._l1:\n"
            "            self.late_site()\n"),
    }
    project2 = _tree(tmp_path, files2)
    res2 = _run("lock-order", project2)
    assert any("cycle" in f.message and "G._l1" in f.message
               and "G._l2" in f.message for f in res2.findings)


def test_non_utf8_file_is_reported_not_crashed(tmp_path):
    (tmp_path / "dllama_tpu" / "runtime").mkdir(parents=True)
    (tmp_path / "dllama_tpu" / "runtime" / "binary.py").write_bytes(
        b"x = 1  # caf\xe9 in latin-1\n")
    project = Project(tmp_path)
    res = _run("exception-hygiene", project)
    assert res.error is None
    assert any("non-UTF-8" in f.message for f in res.findings)
