"""Quality observatory tier (runtime/evalharness + the promotion quality
ledger): the committed fixture regenerates byte-identically; batched eval
through BatchScheduler/PagedGenerator is BIT-IDENTICAL to the single-seq
oracle (and spec-on to spec-off) on tests/goldens/eval_tiny.jsonl; a
second eval run on a warm scheduler adds zero unexpected compiles; a
mid-run fault yields a loud partial (completed vs in-flight), never a
silently truncated perplexity; eval residency is advertised on /readyz
and the last summary on GET /debug/eval; quality_baseline.py honors the
record/check contract (rc 1 names the regressed metric and parity
drift, rc 2 on corrupt files, no_evidence is never a verdict); and the
eval-names dlint rule fires on a seeded-bad vocabulary while the live
repo scans clean.

Engine-heavy assertions are consolidated (module-scoped model files, one
oracle engine) so the tier stays CPU-cheap; the model RNG seed matches
tools/quality_baseline.BUILTIN_SEED so the golden here and the committed
QUALITY_BASELINE.json pin the same numbers."""

import json
import math
import os
import sys
import threading
import urllib.request
from http.server import HTTPServer

import numpy as np
import pytest

from dllama_tpu.formats import tfile
from dllama_tpu.runtime import evalharness
from dllama_tpu.runtime import failpoints as fp
from dllama_tpu.runtime import telemetry as tm
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.runtime.serving import BatchScheduler
from dllama_tpu.serve import cli

from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "goldens", "eval_tiny.jsonl")
BASELINE = os.path.join(REPO, "QUALITY_BASELINE.json")

# tools/quality_baseline.run_builtin's model: same seed, same header —
# so the parity/golden asserted here is the committed baseline's
BUILTIN_SEED = 0x5EED


@pytest.fixture(autouse=True)
def _clean_failpoints():
    """A leaked armed failpoint would crash unrelated schedulers."""
    fp.registry().clear()
    yield
    fp.registry().clear()


@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("evalharness")
    mpath, tpath = d / "m.m", d / "t.t"
    write_tiny_model(mpath, tiny_header_params(seq_len=64),
                     np.random.RandomState(BUILTIN_SEED))
    td = byte_vocab_tokenizer()
    td.chat_template = "<|start_header_id|>"  # BatchedApiState needs one
    tfile.write_tfile(tpath, td)
    return str(mpath), str(tpath)


@pytest.fixture(scope="module")
def oracle_engine(model_files):
    mpath, tpath = model_files
    eng = InferenceEngine(mpath, tpath, tp=1)
    yield eng
    eng.close()


def _fixture_seqs():
    return evalharness.load_dataset(FIXTURE)


# -- satellite: the committed fixture is deterministic -----------------------


def test_fixture_regenerates_byte_identical(tmp_path, monkeypatch):
    from tools import make_eval_fixture as mef

    out = tmp_path / "regen.jsonl"
    monkeypatch.setattr(sys, "argv",
                        ["make_eval_fixture", "--out", str(out)])
    mef.main()
    committed = open(FIXTURE, "rb").read()
    assert out.read_bytes() == committed
    # a different seed is a DIFFERENT fixture (the seed is injectable,
    # not decorative)
    monkeypatch.setattr(sys, "argv",
                        ["make_eval_fixture", "--out", str(out),
                         "--seed", "0x1234"])
    mef.main()
    assert out.read_bytes() != committed
    # shape invariants the tiny models rely on
    seqs = mef.make_seqs(mef.DEFAULT_SEED)
    assert [len(s["tokens"]) for s in seqs] == list(mef.SEQ_LENS)
    assert all(0 <= t < 128 for s in seqs for t in s["tokens"])


# -- load_dataset error paths (no jax) ---------------------------------------


def test_load_dataset_rejects_bad_entries(tmp_path):
    p = tmp_path / "d.jsonl"

    p.write_text('{"text": "hello"}\n')
    with pytest.raises(ValueError, match=r"d\.jsonl:1: 'text' entry needs"):
        evalharness.load_dataset(str(p))  # text form without a tokenizer

    p.write_text('{"tokens": [5]}\n')
    with pytest.raises(ValueError, match=r":1: sequence has 1 token"):
        evalharness.load_dataset(str(p))

    p.write_text('{"tokens": [5, 6, 7]}\nnot json{\n')
    with pytest.raises(ValueError, match=r":2: not JSON"):
        evalharness.load_dataset(str(p))

    p.write_text('{"neither": 1}\n')
    with pytest.raises(ValueError, match=r"neither 'tokens' nor 'text'"):
        evalharness.load_dataset(str(p))

    p.write_text("\n\n")
    with pytest.raises(ValueError, match="empty eval dataset"):
        evalharness.load_dataset(str(p))

    # seq_len clips; ids coerce to int; default ids are positional
    p.write_text('{"tokens": [1, 2, 3, 4, 5]}\n')
    seqs = evalharness.load_dataset(str(p), seq_len=3)
    assert seqs == [{"id": "seq0", "tokens": [1, 2, 3]}]


def test_load_dataset_text_form_encodes(tmp_path, oracle_engine):
    p = tmp_path / "t.jsonl"
    p.write_text('{"id": "greeting", "text": "hello world"}\n')
    seqs = evalharness.load_dataset(str(p), oracle_engine.tokenizer)
    assert seqs[0]["id"] == "greeting"
    assert len(seqs[0]["tokens"]) >= 2


# -- tentpole: four-config bit-parity + the committed golden -----------------


def test_eval_parity_golden_and_compile_quiet(model_files, oracle_engine):
    """The load-bearing assertion of the quality observatory: all four
    configs (single oracle, dense batched, paged, paged+speculative)
    produce BIT-IDENTICAL total NLL on the committed fixture, the
    perplexity matches the committed QUALITY_BASELINE.json, and a second
    run on a warm scheduler is compile-quiet (zero unexpected retraces
    beyond the first run's known donated-output rekey)."""
    mpath, tpath = model_files
    seqs = _fixture_seqs()
    n_scored = sum(len(s["tokens"]) - 1 for s in seqs)
    runs = {}

    runs["single"] = evalharness.run_eval(
        seqs, dataset="eval_tiny", config="single", engine=oracle_engine)

    # dense batched rides the SAME engine the oracle just used
    sched = BatchScheduler(oracle_engine, n_slots=4)
    try:
        runs["dense"] = evalharness.run_eval(
            seqs, dataset="eval_tiny", config="dense", sched=sched)
    finally:
        sched.close()

    for config, kw in (("paged", {"kv_block_size": 8}),
                       ("paged_spec", {"kv_block_size": 8,
                                       "spec_lookup": 4})):
        eng = InferenceEngine(mpath, tpath, tp=1, **kw)
        sched = BatchScheduler(eng, n_slots=4)
        try:
            runs[config] = evalharness.run_eval(
                seqs, dataset="eval_tiny", config=config, sched=sched)
            if config == "paged":
                # warm-scheduler rerun: the retrace sentinel must stay
                # silent — a compile here means eval traffic retraces in
                # steady state (the property PERF.md promises)
                retraces = tm.registry().counter(tm.RETRACE_UNEXPECTED)
                before = retraces.total()
                rerun = evalharness.run_eval(
                    seqs, dataset="eval_tiny", config=config, sched=sched)
                assert retraces.total() == before
                assert (rerun["total_nll_hex"]
                        == runs[config]["total_nll_hex"])
        finally:
            sched.close()
            eng.close()

    # every run scored every position exactly once
    for config, run in runs.items():
        assert run["n_seqs"] == len(seqs), config
        assert run["n_tokens"] == n_scored, config
        assert run["partial"] is False
        assert math.isfinite(run["perplexity"])

    # the bit-parity contract: identical total hex AND identical
    # per-sequence hexes across all four configs
    hexes = {c: r["total_nll_hex"] for c, r in runs.items()}
    assert len(set(hexes.values())) == 1, hexes
    per_seq = {c: [e["nll_hex"] for e in r["seqs"]] for c, r in runs.items()}
    assert (per_seq["single"] == per_seq["dense"]
            == per_seq["paged"] == per_seq["paged_spec"])

    # the committed golden: same model seed as the baseline recorder, so
    # the perplexity here IS the committed number (tolerance only covers
    # cross-version float reassociation)
    with open(BASELINE, encoding="utf-8") as f:
        committed = json.load(f)
    golden_ppl = committed["metrics"]["eval_tiny.perplexity"]["value"]
    assert runs["single"]["perplexity"] == pytest.approx(golden_ppl,
                                                         rel=1e-4)

    # the dllama_eval_* family carries the evidence
    reg = tm.registry()
    assert reg.counter(tm.EVAL_TOKENS).total(
        dataset="eval_tiny", config="single") >= n_scored
    assert reg.counter(tm.EVAL_NLL).total(
        dataset="eval_tiny", config="paged") > 0
    ppl_gauge = reg.gauge(tm.EVAL_PERPLEXITY).value(dataset="eval_tiny")
    assert ppl_gauge == pytest.approx(runs["paged"]["perplexity"])

    # and the last-run store serves GET /debug/eval
    last = evalharness.last_run()
    assert last is not None and last["partial"] is False


def test_run_eval_rejects_unknown_config_and_missing_backend(oracle_engine):
    with pytest.raises(ValueError, match="unknown eval config"):
        evalharness.run_eval([], dataset="d", config="typo",
                             engine=oracle_engine)
    with pytest.raises(ValueError, match="needs engine="):
        evalharness.run_eval([], dataset="d", config="single")
    with pytest.raises(ValueError, match="needs sched="):
        evalharness.run_eval([], dataset="d", config="paged")


# -- satellite: chaos — a mid-run fault is loud, never a truncation ----------


def test_midrun_fault_yields_partial_with_completed_vs_in_flight(
        oracle_engine, monkeypatch):
    """Two sequences score, the third scorer call dies: the abort names
    exactly which sequences completed and which were in flight, and the
    partial carries ONLY the scored entries (no fabricated zeros)."""
    seqs = _fixture_seqs()
    real = oracle_engine.score_nll
    calls = {"n": 0}

    def flaky(ids):
        calls["n"] += 1
        if calls["n"] == 3:
            raise RuntimeError("injected scorer fault")
        return real(ids)

    monkeypatch.setattr(oracle_engine, "score_nll", flaky)
    with pytest.raises(evalharness.EvalAborted, match="seq2") as ei:
        evalharness.run_eval(seqs, dataset="eval_tiny", config="single",
                             engine=oracle_engine)
    p = ei.value.partial
    assert p["partial"] is True
    assert p["completed"] == ["seq0", "seq1"]
    assert p["in_flight"] == ["seq2", "seq3", "seq4", "seq5"]
    assert "injected scorer fault" in p["error"]
    assert [e["id"] for e in p["seqs"]] == ["seq0", "seq1"]
    assert evalharness.last_run()["partial"] is True


def test_eval_failpoint_aborts_batched_submit(oracle_engine):
    """The armed ``eval`` failpoint site fires on the first submission:
    nothing completed, everything in flight — and the scheduler is still
    healthy afterwards (the fault surfaced to the caller, not the loop)."""
    seqs = _fixture_seqs()
    sched = BatchScheduler(oracle_engine, n_slots=2, _start_thread=False)
    try:
        fp.registry().arm("eval", "raise", times=1)
        with pytest.raises(evalharness.EvalAborted, match="submit failed"):
            evalharness.run_eval(seqs, dataset="eval_tiny", config="dense",
                                 sched=sched)
        p = evalharness.last_run()
        assert p["partial"] is True
        assert p["completed"] == []
        assert p["in_flight"] == [s["id"] for s in seqs]
        assert sched.is_alive()
    finally:
        sched.close()


def test_scheduler_crash_midrun_aborts_with_partial(model_files):
    """A step_hang crash inside the scheduler loop fails the admitted
    eval requests; score_batched converts that into a loud EvalAborted
    partial instead of summing whatever happened to finish."""
    mpath, tpath = model_files
    eng = InferenceEngine(mpath, tpath, tp=1)
    sched = BatchScheduler(eng, n_slots=2)
    try:
        fp.registry().arm("step_hang", "raise", times=1)
        with pytest.raises(evalharness.EvalAborted):
            evalharness.run_eval(_fixture_seqs(), dataset="eval_tiny",
                                 config="dense", sched=sched,
                                 timeout_s=120.0)
        assert evalharness.last_run()["partial"] is True
    finally:
        sched.close()
        eng.close()


# -- CLI: python -m dllama_tpu eval ------------------------------------------


def _last_json(text: str) -> dict:
    for line in text.splitlines()[::-1]:
        if line.startswith("{"):
            return json.loads(line)
    raise AssertionError(f"no JSON line in: {text!r}")


def test_cli_eval_json_with_compare_parity(model_files, capsys):
    mpath, tpath = model_files
    rc = cli.main(["eval", "--model", mpath, "--tokenizer", tpath,
                   "--data", FIXTURE, "--json",
                   "--batch-slots", "2", "--kv-block-size", "8",
                   "--compare", "single"])
    out = capsys.readouterr().out
    assert rc == 0
    res = _last_json(out)
    assert res["config"] == "paged"
    assert res["dataset"] == "eval_tiny"
    assert res["compare"]["config"] == "single"
    assert res["parity_drift"] is False
    assert res["total_nll_hex"] == res["compare"]["total_nll_hex"]


def test_cli_eval_failpoint_exits_nonzero_with_partial_json(model_files,
                                                            capsys):
    mpath, tpath = model_files
    fp.registry().arm("eval", "raise", times=1)
    rc = cli.main(["eval", "--model", mpath, "--tokenizer", tpath,
                   "--data", FIXTURE, "--json"])
    cap = capsys.readouterr()
    assert rc == 1
    partial = _last_json(cap.out)
    assert partial["partial"] is True
    assert set(partial["completed"]) | set(partial["in_flight"]) == {
        f"seq{i}" for i in range(6)}
    assert "💥" in cap.err


def test_cli_eval_requires_data(model_files):
    mpath, tpath = model_files
    with pytest.raises(SystemExit, match="--data"):
        cli.main(["eval", "--model", mpath, "--tokenizer", tpath])


# -- satellite: residency on /readyz + GET /debug/eval -----------------------


def test_eval_resident_counts_scoring_work(oracle_engine):
    sched = BatchScheduler(oracle_engine, n_slots=2, _start_thread=False)
    try:
        assert sched.eval_resident() == 0
        sched.submit([1, 2, 3, 4], 0, score=True)
        sched.submit([5, 6, 7], 0, score=True)
        sched.submit([8, 9], 2)  # decode work is NOT eval residency
        assert sched.eval_resident() == 2
    finally:
        sched.close()


def test_readyz_advertises_eval_residency_and_debug_eval(oracle_engine):
    from dllama_tpu.serve.api import BatchedApiState, make_handler

    state = BatchedApiState(oracle_engine, n_slots=2)
    # swap in a hand-driven scheduler so residency is deterministic
    # (the real loop would drain the eval work before the probe lands)
    state.sched.close()
    state.sched = BatchScheduler(oracle_engine, n_slots=2,
                                 _start_thread=False)
    httpd = HTTPServer(("127.0.0.1", 0), make_handler(state))
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        with urllib.request.urlopen(url + "/readyz") as r:
            rz = json.loads(r.read())
        assert "eval_resident" not in rz  # zero is not advertised

        state.sched.submit([1, 2, 3, 4], 0, score=True)
        with urllib.request.urlopen(url + "/readyz") as r:
            rz = json.loads(r.read())
        assert rz["eval_resident"] == 1

        marker = {"dataset": "eval_tiny", "config": "single",
                  "partial": False, "perplexity": 42.0}
        evalharness.set_last_run(marker)
        with urllib.request.urlopen(url + "/debug/eval") as r:
            assert json.loads(r.read()) == marker
        with urllib.request.urlopen(url + "/debug") as r:
            assert "/debug/eval" in json.loads(r.read())["endpoints"]
    finally:
        httpd.shutdown()
        state.sched.close()


# -- satellite: the quality ledger contract (no engines) ---------------------


def _mk_run(config="single", ppl=100.0, nll_hex="0x1.9p+6", *,
            dataset="eval_tiny", partial=False):
    return {"dataset": dataset, "config": config, "n_seqs": 6,
            "n_tokens": 131, "total_nll": 603.2, "total_nll_hex": nll_hex,
            "perplexity": ppl, "partial": partial, "seqs": []}


class TestQualityBaselineContract:
    """record/check via quality_baseline.main() on synthesized eval
    JSON: rc 0 clean, rc 1 names the regressed metric / parity drift,
    rc 2 on corrupt files, absent overlap is no_evidence (rc 0)."""

    def _main(self, monkeypatch, *argv) -> int:
        from tools import quality_baseline as qb
        monkeypatch.setattr(sys, "argv", ["quality_baseline.py", *argv])
        return qb.main()

    def _record(self, tmp_path, monkeypatch, runs, name="t"):
        res = tmp_path / "result.json"
        res.write_text(json.dumps({"runs": runs}))
        bl = tmp_path / "baseline.json"
        rc = self._main(monkeypatch, "record", str(res),
                        "--baseline-file", str(bl), "--name", name)
        assert rc == 0
        return res, bl

    def test_record_then_clean_check(self, tmp_path, monkeypatch, capsys):
        runs = [_mk_run("single"), _mk_run("dense")]
        res, bl = self._record(tmp_path, monkeypatch, runs)
        doc = json.loads(bl.read_text())
        assert doc["metrics"]["eval_tiny.perplexity"]["value"] == 100.0
        assert doc["parity"]["eval_tiny"]["dense"] == "0x1.9p+6"
        rc = self._main(monkeypatch, "check", str(res),
                        "--baseline-file", str(bl))
        assert rc == 0
        assert "OK" in capsys.readouterr().out

    def test_regression_exits_1_naming_the_metric(self, tmp_path,
                                                  monkeypatch, capsys):
        _, bl = self._record(tmp_path, monkeypatch, [_mk_run()])
        worse = tmp_path / "worse.json"
        worse.write_text(json.dumps({"runs": [_mk_run(ppl=110.0)]}))
        rc = self._main(monkeypatch, "check", str(worse),
                        "--baseline-file", str(bl))
        out = capsys.readouterr().out
        assert rc == 1
        assert "REGRESSED eval_tiny.perplexity" in out

    def test_within_tolerance_passes(self, tmp_path, monkeypatch, capsys):
        _, bl = self._record(tmp_path, monkeypatch, [_mk_run()])
        near = tmp_path / "near.json"
        near.write_text(json.dumps({"runs": [_mk_run(ppl=101.0)]}))
        rc = self._main(monkeypatch, "check", str(near),
                        "--baseline-file", str(bl))
        assert rc == 0
        assert "within noise" in capsys.readouterr().out

    def test_parity_drift_exits_1_even_within_tolerance(self, tmp_path,
                                                        monkeypatch, capsys):
        _, bl = self._record(tmp_path, monkeypatch,
                             [_mk_run("single"), _mk_run("dense")])
        drift = tmp_path / "drift.json"
        drift.write_text(json.dumps({"runs": [
            _mk_run("single"), _mk_run("dense", nll_hex="0x1.ap+6")]}))
        rc = self._main(monkeypatch, "check", str(drift),
                        "--baseline-file", str(bl))
        out = capsys.readouterr().out
        assert rc == 1
        assert "PARITY DRIFT" in out
        assert "numerics bug, not a quality tradeoff" in out

    def test_no_overlap_is_no_evidence_not_a_verdict(self, tmp_path,
                                                     monkeypatch, capsys):
        _, bl = self._record(tmp_path, monkeypatch, [_mk_run()])
        other = tmp_path / "other.json"
        other.write_text(json.dumps({"runs": [_mk_run(dataset="wiki")]}))
        rc = self._main(monkeypatch, "check", str(other),
                        "--baseline-file", str(bl))
        out = capsys.readouterr().out
        assert rc == 0
        assert "NO_EVIDENCE" in out
        assert "not a pass, not a fail" in out

    def test_corrupt_baseline_is_rc2(self, tmp_path, monkeypatch, capsys):
        res = tmp_path / "r.json"
        res.write_text(json.dumps(_mk_run()))
        bad = tmp_path / "bad_baseline.json"
        bad.write_text("{corrupt")
        rc = self._main(monkeypatch, "check", str(res),
                        "--baseline-file", str(bad))
        assert rc == 2
        assert "baseline file unusable" in capsys.readouterr().err

    def test_corrupt_result_is_rc2(self, tmp_path, monkeypatch, capsys):
        bad = tmp_path / "bad_result.json"
        bad.write_text("no json here at all\n")
        rc = self._main(monkeypatch, "check", str(bad),
                        "--baseline-file", BASELINE)
        assert rc == 2
        assert "result file unusable" in capsys.readouterr().err

    def test_partial_runs_are_no_evidence_for_record(self, tmp_path,
                                                     monkeypatch, capsys):
        res = tmp_path / "partial.json"
        res.write_text(json.dumps({"runs": [_mk_run(partial=True)]}))
        rc = self._main(monkeypatch, "record", str(res),
                        "--baseline-file", str(tmp_path / "b.json"))
        assert rc == 2
        assert "no complete runs" in capsys.readouterr().err

    def test_compare_subrun_contributes_parity(self):
        from tools import quality_baseline as qb
        run = _mk_run("paged")
        run["compare"] = _mk_run("single")
        parity = qb.extract_parity(run)
        assert set(parity["eval_tiny"]) == {"paged", "single"}
        assert qb.check_parity(run) == []
        run["compare"]["total_nll_hex"] = "0x1.bp+6"
        drifts = qb.check_parity(run)
        assert drifts and drifts[0]["configs"] == ("paged", "single")


# -- satellite: the eval-names closed-world lint -----------------------------


def test_eval_names_rule_live_repo_clean():
    from tools.dlint import Project, eval_names

    findings, summary = eval_names.check(Project())
    assert findings == [], [str(f) for f in findings]
    assert "4 eval configs" in summary


def test_eval_names_rule_fires_on_seeded_bad_vocab():
    from tools.dlint import Project, eval_names

    bad_vocab = (("ok_cfg", "Bad-Config"),           # grammar violation
                 (("ok_cfg", "ok_cfg"),              # reflexive pair
                  ("ghost", "ok_cfg")),              # undeclared side
                 {})                                 # no eval metrics
    findings, _ = eval_names.check(Project(), vocab=bad_vocab)
    msgs = "\n".join(f.message for f in findings)
    assert "violates the grammar" in msgs
    assert "reflexive" in msgs
    assert "'ghost'" in msgs and "not in" in msgs
    assert "dllama_eval_tokens_total" in msgs
    # docs drift: 'ok_cfg' is not a README-documented config
    assert "not mentioned in README.md" in msgs
    # committed baseline closed-world: its real keys are undeclared
    # under the injected vocabulary
    assert "parity key 'single'" in msgs
