"""Model forward correctness vs an independent numpy golden implementation.

Mirrors the reference's test approach of checking op pipelines against
analytically computed expectations (nn-vulkan-test.cpp) — here the whole
transformer forward is cross-checked, including rope styles, GQA, KV cache
append, and the Qwen3 per-head norms."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dllama_tpu.formats import mfile
from dllama_tpu.models import ModelConfig, forward, init_random_params, load_params_from_mfile
from dllama_tpu.ops.linear import QuantizedWeight, dequantize_weight
from dllama_tpu.runtime import KVCache

from helpers import tiny_header_params, write_tiny_model


def golden_forward(dense, cfg: ModelConfig, tokens: np.ndarray, start_pos: int,
                   k_cache: np.ndarray, v_cache: np.ndarray):
    """Straight-line numpy reimplementation (no shared code with the model)."""
    B, T = tokens.shape
    hd = cfg.head_dim
    x = dense["embedding"][tokens].astype(np.float32)

    def rms(v, w):
        inv = 1.0 / np.sqrt(np.mean(v * v, axis=-1, keepdims=True) + cfg.norm_epsilon)
        return v * inv * w

    def rope(v, positions):  # v: [B,T,H,hd]
        half = hd // 2
        freqs = 1.0 / cfg.rope_theta ** (2.0 * np.arange(half, dtype=np.float32) / hd)
        ang = positions[..., None] * freqs  # [B,T,half]
        c, s = np.cos(ang)[:, :, None, :], np.sin(ang)[:, :, None, :]
        out = v.copy()
        if cfg.rope_type == mfile.RopeType.FALCON:
            a, b = v[..., :half], v[..., half:]
            out[..., :half] = a * c - b * s
            out[..., half:] = a * s + b * c
        else:
            a, b = v[..., 0::2], v[..., 1::2]
            out[..., 0::2] = a * c - b * s
            out[..., 1::2] = a * s + b * c
        return out

    positions = start_pos + np.arange(T)[None, :] + np.zeros((B, 1), np.int32)
    for l in range(cfg.n_layers):
        h = rms(x, dense[f"block_norm_0.{l}"])
        q = (h @ dense[f"block_matmul_q.{l}"].T).reshape(B, T, cfg.n_heads, hd)
        k = (h @ dense[f"block_matmul_k.{l}"].T).reshape(B, T, cfg.n_kv_heads, hd)
        v = (h @ dense[f"block_matmul_v.{l}"].T).reshape(B, T, cfg.n_kv_heads, hd)
        if cfg.arch == mfile.ArchType.QWEN3:
            q = rms(q, dense[f"block_norm_q.{l}"])
            k = rms(k, dense[f"block_norm_k.{l}"])
        q, k = rope(q, positions), rope(k, positions)
        # cache layout is head-major [L, B, H_kv, S, hd]
        k_cache[l, :, :, start_pos:start_pos + T] = k.transpose(0, 2, 1, 3)
        v_cache[l, :, :, start_pos:start_pos + T] = v.transpose(0, 2, 1, 3)
        att_out = np.zeros((B, T, cfg.n_heads, hd), np.float32)
        for hh in range(cfg.n_heads):
            kv_h = hh // (cfg.n_heads // cfg.n_kv_heads)
            for b in range(B):
                for t in range(T):
                    pos = positions[b, t]
                    scores = (k_cache[l, b, kv_h, :pos + 1] @ q[b, t, hh]) / np.sqrt(hd)
                    e = np.exp(scores - scores.max())
                    p = e / e.sum()
                    att_out[b, t, hh] = p @ v_cache[l, b, kv_h, :pos + 1]
        x = x + att_out.reshape(B, T, -1) @ dense[f"block_matmul_wo.{l}"].T
        h = rms(x, dense[f"block_norm_1.{l}"])
        g = h @ dense[f"block_matmul_w1.{l}"].T
        g = g / (1.0 + np.exp(-g))  # silu
        u = h @ dense[f"block_matmul_w3.{l}"].T
        x = x + (g * u) @ dense[f"block_matmul_w2.{l}"].T
    x = rms(x, dense["final_norm"])
    return x @ dense["final_matmul_logits"].T


def _dense_from_params(params, cfg):
    """Extract dense numpy weights from a Params tree for the golden impl."""
    out = {"embedding": np.asarray(params.embedding, np.float32),
           "final_norm": np.asarray(params.final_norm, np.float32)}

    def dn(w, l=None):
        if isinstance(w, QuantizedWeight):
            w = dequantize_weight(QuantizedWeight(w.scales[l], w.codes[l])) if l is not None \
                else dequantize_weight(w)
            return np.asarray(w, np.float32).T  # K-major → golden's [out, in]
        return np.asarray(w if l is None else w[l], np.float32)

    lp = params.layers
    for l in range(cfg.n_layers):
        for name, w in [("block_matmul_q", lp.wq), ("block_matmul_k", lp.wk),
                        ("block_matmul_v", lp.wv), ("block_matmul_wo", lp.wo),
                        ("block_matmul_w1", lp.w1), ("block_matmul_w2", lp.w2),
                        ("block_matmul_w3", lp.w3)]:
            out[f"{name}.{l}"] = dn(w, l)
        out[f"block_norm_0.{l}"] = np.asarray(lp.norm_att[l], np.float32)
        out[f"block_norm_1.{l}"] = np.asarray(lp.norm_ffn[l], np.float32)
        if lp.norm_q is not None:
            out[f"block_norm_q.{l}"] = np.asarray(lp.norm_q[l], np.float32)
            out[f"block_norm_k.{l}"] = np.asarray(lp.norm_k[l], np.float32)
    out["final_matmul_logits"] = dn(params.logits)
    return out


def _tiny_cfg(**kw):
    params = tiny_header_params(**kw)
    return ModelConfig(
        arch=mfile.ArchType(params["arch_type"]),
        dim=params["dim"], hidden_dim=params["hidden_dim"],
        n_layers=params["n_layers"], n_heads=params["n_heads"],
        n_kv_heads=params["n_kv_heads"],
        head_dim=params.get("head_dim") or params["dim"] // params["n_heads"],
        vocab_size=params["vocab_size"], seq_len=params["seq_len"],
        norm_epsilon=1e-5, rope_theta=float(params["rope_theta"]),
        rope_type=mfile.RopeType(params["rope_type"]),
    )


@pytest.mark.parametrize("arch,rope", [
    (mfile.ArchType.LLAMA, mfile.RopeType.LLAMA),
    (mfile.ArchType.QWEN3, mfile.RopeType.FALCON),
])
def test_forward_matches_golden(arch, rope):
    cfg = _tiny_cfg(arch=arch, rope_type=rope)
    params = init_random_params(cfg, seed=3)
    tokens = np.array([[5, 17, 99, 3]], dtype=np.int32)
    kv = KVCache.create(cfg, batch_size=1)

    logits, kv2 = jax.jit(forward, static_argnums=1)(
        params, cfg, jnp.asarray(tokens), jnp.int32(0), kv)

    gk = np.zeros(kv.k.shape, np.float32)
    gv = np.zeros(kv.v.shape, np.float32)
    want = golden_forward(_dense_from_params(params, cfg), cfg, tokens, 0, gk, gv)
    np.testing.assert_allclose(np.asarray(logits), want, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(kv2.k), gk, rtol=1e-4, atol=1e-5)


def test_prefill_then_decode_matches_single_shot():
    """Chunked prefill + decode must equal one full forward (KV correctness)."""
    cfg = _tiny_cfg()
    params = init_random_params(cfg, seed=4)
    toks = np.array([[1, 2, 3, 4, 5, 6]], dtype=np.int32)

    kv = KVCache.create(cfg)
    full_logits, _ = jax.jit(forward, static_argnums=1)(
        params, cfg, jnp.asarray(toks), jnp.int32(0), kv)

    kv = KVCache.create(cfg)
    fwd = jax.jit(forward, static_argnums=1)
    _, kv = fwd(params, cfg, jnp.asarray(toks[:, :3]), jnp.int32(0), kv)
    _, kv = fwd(params, cfg, jnp.asarray(toks[:, 3:5]), jnp.int32(3), kv)
    step_logits, kv = fwd(params, cfg, jnp.asarray(toks[:, 5:6]), jnp.int32(5), kv)

    np.testing.assert_allclose(np.asarray(step_logits[0, 0]),
                               np.asarray(full_logits[0, -1]), rtol=2e-4, atol=2e-5)


def test_forward_from_mfile(tmp_path):
    """Load a Q40 .m file and check quantized forward ≈ dense-dequantized forward."""
    path = tmp_path / "tiny.m"
    rng = np.random.default_rng(7)
    write_tiny_model(path, tiny_header_params(), rng)
    with mfile.ModelFile.open(path) as mf:
        cfg = ModelConfig.from_header(mf.header)
        qparams = load_params_from_mfile(mf, cfg, weight_mode="auto")
        fparams = load_params_from_mfile(mf, cfg, weight_mode="f32")
    assert isinstance(qparams.layers.wq, QuantizedWeight)
    tokens = jnp.asarray([[9, 27, 64]], dtype=jnp.int32)
    lq, _ = jax.jit(forward, static_argnums=1)(
        qparams, cfg, tokens, jnp.int32(0), KVCache.create(cfg))
    lf, _ = jax.jit(forward, static_argnums=1)(
        fparams, cfg, tokens, jnp.int32(0), KVCache.create(cfg))
    # Q40 planes dequantize to exactly the same f32 values the dense path uses,
    # so the two must agree to float tolerance.
    np.testing.assert_allclose(np.asarray(lq), np.asarray(lf), rtol=1e-5, atol=1e-5)


def test_batched_sequences():
    """B>1 (beyond the reference's single-sequence design) stays consistent."""
    cfg = _tiny_cfg()
    params = init_random_params(cfg, seed=5)
    t1 = np.array([[4, 8, 15]], dtype=np.int32)
    t2 = np.array([[16, 23, 42]], dtype=np.int32)
    both = np.concatenate([t1, t2], axis=0)

    fwd = jax.jit(forward, static_argnums=1)
    l_both, _ = fwd(params, cfg, jnp.asarray(both), jnp.int32(0),
                    KVCache.create(cfg, batch_size=2))
    l1, _ = fwd(params, cfg, jnp.asarray(t1), jnp.int32(0), KVCache.create(cfg))
    l2, _ = fwd(params, cfg, jnp.asarray(t2), jnp.int32(0), KVCache.create(cfg))
    np.testing.assert_allclose(np.asarray(l_both[0]), np.asarray(l1[0]), rtol=2e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(l_both[1]), np.asarray(l2[0]), rtol=2e-4, atol=1e-5)
