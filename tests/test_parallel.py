"""Tensor-parallel sharding tests on the 8-device virtual CPU mesh.

The distributed-correctness property is the same one the reference relies on
(SURVEY.md §4: "the TP math being node-count-invariant — same logits for
1/2/4/8 nodes"): shard the params over tp ∈ {1, 2, 4, 8} and assert the
logits match the unsharded run."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dllama_tpu.formats import mfile
from dllama_tpu.models import ModelConfig, forward, init_random_params
from dllama_tpu.parallel import use_plan
from dllama_tpu.parallel.api import make_mesh, make_tp_mesh
from dllama_tpu.parallel.sharding import (
    kv_cache_sharding,
    param_shardings,
    shard_params,
    validate_tp,
)
from dllama_tpu.runtime import KVCache


def _cfg(**kw):
    base = dict(
        arch=mfile.ArchType.LLAMA, dim=64, hidden_dim=96, n_layers=2,
        n_heads=8, n_kv_heads=4, head_dim=8, vocab_size=128, seq_len=32,
        norm_epsilon=1e-5, rope_theta=10000.0, rope_type=mfile.RopeType.LLAMA,
    )
    base.update(kw)
    return ModelConfig(**base)


def test_eight_cpu_devices_present():
    assert len(jax.devices()) == 8, (
        "tests require XLA_FLAGS=--xla_force_host_platform_device_count=8")


@pytest.mark.parametrize("tp", [2, 4, 8])
def test_tp_logits_match_unsharded(tp):
    cfg = _cfg()
    params = init_random_params(cfg, seed=11)
    tokens = jnp.asarray([[3, 1, 4, 1, 5]], dtype=jnp.int32)

    ref_logits, _ = jax.jit(forward, static_argnums=1)(
        params, cfg, tokens, jnp.int32(0), KVCache.create(cfg))

    plan = make_tp_mesh(tp)
    validate_tp(cfg, tp)
    sharded = shard_params(plan, params)
    kv = jax.device_put(KVCache.create(cfg), kv_cache_sharding(plan, KVCache.create(cfg)))
    with use_plan(plan):
        tp_logits, tp_kv = jax.jit(forward, static_argnums=1)(
            sharded, cfg, tokens, jnp.int32(0), kv)

    np.testing.assert_allclose(np.asarray(tp_logits), np.asarray(ref_logits),
                               rtol=2e-5, atol=2e-6)


def test_tp_quantized_weights_shard():
    cfg = _cfg()
    params = init_random_params(cfg, seed=13, quantized=True)
    tokens = jnp.asarray([[7, 7, 7]], dtype=jnp.int32)

    ref_logits, _ = jax.jit(forward, static_argnums=1)(
        params, cfg, tokens, jnp.int32(0), KVCache.create(cfg))

    plan = make_tp_mesh(4)
    sharded = shard_params(plan, params)
    # Q40 planes must shard on the out axis: K-major scales [L, in/32, out]
    assert sharded.layers.wq.scales.sharding.spec[2] == "tp"
    with use_plan(plan):
        tp_logits, _ = jax.jit(forward, static_argnums=1)(
            sharded, cfg, tokens, jnp.int32(0),
            jax.device_put(KVCache.create(cfg), kv_cache_sharding(plan, KVCache.create(cfg))))
    np.testing.assert_allclose(np.asarray(tp_logits), np.asarray(ref_logits),
                               rtol=2e-5, atol=2e-6)


def test_dp_tp_mesh():
    """2-way data parallel × 4-way tensor parallel on 8 devices."""
    cfg = _cfg()
    params = init_random_params(cfg, seed=17)
    tokens = jnp.asarray([[3, 1, 4, 1], [2, 7, 1, 8]], dtype=jnp.int32)

    ref_logits, _ = jax.jit(forward, static_argnums=1)(
        params, cfg, tokens, jnp.int32(0), KVCache.create(cfg, batch_size=2))

    plan = make_mesh({"dp": 2, "tp": 4})
    sharded = shard_params(plan, params)
    kv0 = KVCache.create(cfg, batch_size=2)
    kv = jax.device_put(kv0, kv_cache_sharding(plan, kv0))
    with use_plan(plan):
        out, _ = jax.jit(forward, static_argnums=1)(
            sharded, cfg, tokens, jnp.int32(0), kv)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_logits),
                               rtol=2e-5, atol=2e-6)


def test_validate_tp_rules():
    cfg = _cfg(n_heads=6, n_kv_heads=3, hidden_dim=96, vocab_size=120)
    with pytest.raises(ValueError):
        validate_tp(cfg, 4)  # n_heads 6 % 4 != 0
    validate_tp(cfg, 3)
    cfg2 = _cfg(n_kv_heads=2)
    validate_tp(cfg2, 8)  # tp 8 > kv 2 but 8 % 2 == 0 → replication groups


def test_kv_cache_shards_over_heads():
    cfg = _cfg()
    plan = make_tp_mesh(4)
    kv = jax.device_put(KVCache.create(cfg), kv_cache_sharding(plan, KVCache.create(cfg)))
    assert kv.k.sharding.spec[2] == "tp"


@pytest.mark.parametrize("tp", [2, 4])
def test_tp_forward_with_pallas_kernel_matches_xla(tp, monkeypatch):
    """The production TP path runs the Pallas quant matmul (shard_map-wrapped,
    interpret mode on CPU) — logits must match the XLA dequant+dot path.
    Closes round-1 weak #2 (kernel bypassed whenever a plan was active)."""
    cfg = _cfg()
    params = init_random_params(cfg, seed=17, quantized=True)
    tokens = jnp.asarray([[3, 1, 4, 1, 5]], dtype=jnp.int32)

    plan = make_tp_mesh(tp)
    sharded = shard_params(plan, params)
    kv_shardings = kv_cache_sharding(plan, KVCache.create(cfg))

    def run():
        from dllama_tpu.parallel.api import plan_scoped_jit

        kv = jax.device_put(KVCache.create(cfg), kv_shardings)
        with use_plan(plan):
            # plan_scoped_jit, NOT a raw jit of the shared module-level
            # forward: jax's trace cache keys on the function identity,
            # so a raw jit here reuses the trace of whichever tp ran
            # first ("Received incompatible devices ... sharding_
            # constraint inside jit" on the second parametrization) and
            # lets the second run() of THIS parametrization ride the
            # first's trace-time DLLAMA_TPU_QUANT_KERNEL decision —
            # comparing a program against itself. A fresh per-call
            # closure re-traces both honestly (the jit-entry invariant
            # tools/dlint enforces in the package).
            logits, _ = plan_scoped_jit(forward, static_argnums=1)(
                sharded, cfg, tokens, jnp.int32(0), kv)
        return np.asarray(logits)

    monkeypatch.setenv("DLLAMA_TPU_QUANT_KERNEL", "xla")
    want = run()
    monkeypatch.setenv("DLLAMA_TPU_QUANT_KERNEL", "pallas")
    got = run()
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)
