"""Model zoo launcher: registry integrity, resumable download, run scripts.

Network is mocked via the ``fetch`` injection point (the environment has no
egress); the download machinery — per-part files, byte-range resume after
mid-stream failures, multi-part assembly — runs for real against it.
"""

import numpy as np
import pytest

from dllama_tpu import zoo


def test_registry_matches_reference_table():
    """Same 10 models as the reference launcher (reference: launch.py:17-68)."""
    assert len(zoo.MODELS) == 10
    assert zoo.MODELS["llama3_1_405b_instruct_q40"].model_urls[0].endswith(
        "dllama_model_llama31_405b_q40_aa?download=true")
    assert len(zoo.MODELS["llama3_1_405b_instruct_q40"].model_urls) == 56
    assert len(zoo.MODELS["llama3_3_70b_instruct_q40"].model_urls) == 11
    assert len(zoo.MODELS["qwen3_14b_q40"].model_urls) == 2
    for m in zoo.MODELS.values():
        assert m.buffer_type == "q80"
        assert all(u.startswith("https://huggingface.co/") for u in m.model_urls)
        assert m.tokenizer_url.endswith(".t?download=true")


def test_part_suffixes():
    s = zoo.part_suffixes(56)
    assert s[0] == "aa" and s[25] == "az" and s[26] == "ba" and s[-1] == "cd"


class FlakyStore:
    """Fake origin: serves ranges of per-url payloads, failing mid-stream a
    configurable number of times per url."""

    def __init__(self, payloads: dict[str, bytes], failures: int = 0):
        self.payloads = payloads
        self.failures = {u: failures for u in payloads}
        self.range_starts: dict[str, list[int]] = {u: [] for u in payloads}

    def fetch(self, url: str, start: int):
        data = self.payloads[url]
        self.range_starts[url].append(start)
        if start > 0 and start >= len(data):
            # a real origin answers a past-EOF Range with HTTP 416
            raise zoo.RangeNotSatisfiable(url)
        if self.failures[url] > 0:
            self.failures[url] -= 1
            # emit roughly half of the remainder, then die mid-stream
            half = data[start:start + max(1, (len(data) - start) // 2)]
            yield half
            raise OSError("connection reset (simulated)")
        yield data[start:]


@pytest.fixture(autouse=True)
def _no_sleep(monkeypatch):
    monkeypatch.setattr(zoo, "_sleep", lambda s: None)


def test_download_single_file(tmp_path):
    rng = np.random.default_rng(0)
    data = rng.bytes(100_000)
    store = FlakyStore({"u0": data})
    out = zoo.download_file(["u0"], tmp_path / "f.m", fetch=store.fetch,
                            log=lambda s: None)
    assert out.read_bytes() == data


def test_download_resumes_from_exact_byte(tmp_path):
    rng = np.random.default_rng(1)
    data = rng.bytes(64_000)
    store = FlakyStore({"u0": data}, failures=2)
    out = zoo.download_file(["u0"], tmp_path / "f.m", fetch=store.fetch,
                            log=lambda s: None)
    assert out.read_bytes() == data
    starts = store.range_starts["u0"]
    assert len(starts) == 3 and starts[0] == 0
    # each retry resumed from the bytes already on disk, not from zero
    assert starts[1] > 0 and starts[2] > starts[1]


def test_download_multipart_assembles_in_order(tmp_path):
    rng = np.random.default_rng(2)
    parts = {f"u{i}": rng.bytes(10_000 + i) for i in range(4)}
    store = FlakyStore(parts, failures=1)
    out = zoo.download_file(list(parts), tmp_path / "big.m", fetch=store.fetch,
                            log=lambda s: None)
    assert out.read_bytes() == b"".join(parts.values())
    assert not list(tmp_path.glob("*.part*"))  # parts cleaned up


def test_download_resumes_across_restart_with_complete_part(tmp_path):
    """A part fully downloaded before a crash must not re-download or 416-loop
    on restart (the origin answers its past-EOF Range with 416)."""
    rng = np.random.default_rng(4)
    parts = {f"u{i}": rng.bytes(8_000) for i in range(3)}
    # simulate the pre-crash state: part00 complete, part01 half done
    (tmp_path / "big.m.part00").write_bytes(parts["u0"])
    (tmp_path / "big.m.part01").write_bytes(parts["u1"][:4_000])
    store = FlakyStore(parts)
    out = zoo.download_file(list(parts), tmp_path / "big.m", fetch=store.fetch,
                            log=lambda s: None)
    assert out.read_bytes() == b"".join(parts.values())
    assert store.range_starts["u0"] == [8_000]   # 416'd, no re-download
    assert store.range_starts["u1"] == [4_000]   # resumed from exact byte


def test_run_command_quotes_paths_with_spaces(tmp_path):
    cmd = zoo.run_command("qwen3_8b_q40", "/tmp/My Models/m.m", "/tmp/t.t")
    assert "'/tmp/My Models/m.m'" in cmd


def test_download_gives_up_after_max_attempts(tmp_path):
    store = FlakyStore({"u0": b"x" * 1000}, failures=zoo.ATTEMPTS + 1)
    with pytest.raises(OSError, match="failed to download"):
        zoo.download_file(["u0"], tmp_path / "f.m", fetch=store.fetch,
                          log=lambda s: None)


def test_existing_file_skipped_unless_force(tmp_path):
    p = tmp_path / "f.m"
    p.write_bytes(b"old")
    store = FlakyStore({"u0": b"new"})
    zoo.download_file(["u0"], p, fetch=store.fetch, log=lambda s: None)
    assert p.read_bytes() == b"old" and store.range_starts["u0"] == []
    zoo.download_file(["u0"], p, fetch=store.fetch, log=lambda s: None, force=True)
    assert p.read_bytes() == b"new"


def test_download_model_layout_and_run_script(tmp_path):
    name = "qwen3_14b_q40"
    urls = list(zoo.MODELS[name].model_urls) + [zoo.MODELS[name].tokenizer_url]
    store = FlakyStore({u: f"data-{i}".encode() for i, u in enumerate(urls)})
    mp, tp = zoo.download_model(name, models_dir=tmp_path, fetch=store.fetch,
                                log=lambda s: None)
    assert mp == tmp_path / name / f"dllama_model_{name}.m"
    assert mp.read_bytes() == b"data-0data-1"
    assert tp.read_bytes() == b"data-2"

    cmd = zoo.run_command(name, mp, tp)
    assert "-m dllama_tpu chat" in cmd
    assert f"--model {mp}" in cmd and "--buffer-float-type q80" in cmd
    assert "--max-seq-len 4096" in cmd
    script = zoo.write_run_script(name, cmd, tmp_path)
    assert script.read_text().startswith("#!/bin/sh\n") and cmd in script.read_text()


def test_cli_unknown_model(capsys):
    assert zoo.main(["nope"]) == 1
    assert "Available models" in capsys.readouterr().out


class _RangeIgnoringStore(FlakyStore):
    """Origin that answers every ranged request with the full body (HTTP 200
    semantics) — resume is impossible."""

    def fetch(self, url: str, start: int):
        self.range_starts[url].append(start)
        if start > 0:
            raise zoo.RangeIgnored(f"status 200 for bytes={start}-")
        data = self.payloads[url]
        if self.failures[url] > 0:
            self.failures[url] -= 1
            yield data[: len(data) // 2]
            raise OSError("connection reset (simulated)")
        yield data


def test_range_ignoring_server_restarts_part_from_zero(tmp_path):
    """A 200-to-Range origin must trigger a restart-from-byte-0, not 8
    identical doomed resume attempts (advisor round-1 finding)."""
    store = _RangeIgnoringStore({"u0": b"A" * 64}, failures=1)
    out = zoo.download_file(["u0"], tmp_path / "f.m", fetch=store.fetch,
                            log=lambda s: None)
    assert out.read_bytes() == b"A" * 64
    # one initial attempt (0), one failed resume (32), one clean restart (0)
    assert store.range_starts["u0"] == [0, 32, 0]


def test_range_ignored_is_remembered_across_attempts(tmp_path):
    """After the first 200-to-Range answer, later retries restart from 0
    directly — no further doomed resume probes burning attempts."""
    store = _RangeIgnoringStore({"u0": b"B" * 64}, failures=2)
    out = zoo.download_file(["u0"], tmp_path / "f.m", fetch=store.fetch,
                            log=lambda s: None)
    assert out.read_bytes() == b"B" * 64
    # fail@0, doomed resume@32 (once), then from-0 restarts only
    assert store.range_starts["u0"] == [0, 32, 0, 0]
