"""Converter tests: HF safetensors dir → .m round-trip, tokenizer → .t
round-trip, Q/K rope-row permutation, tiktoken-file parsing.

Mirrors the reference's converter/writer-test.py (byte-golden writer check)
plus end-to-end checks the reference lacks: a converted model must open in
ModelFile and produce finite logits through the real forward pass.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np
import pytest

from dllama_tpu.convert.hf import (
    convert_hf,
    encode_tensor,
    hf_tensor_plan,
    load_hf_config,
    permute_rope_rows,
)
from dllama_tpu.convert.tokenizers import (
    convert_tokenizer_llama3,
    resolve_hf_vocab,
    token_str_to_bytes,
    unicode_to_bytes,
)
from dllama_tpu.formats import quants
from dllama_tpu.formats.mfile import ArchType, ModelFile
from dllama_tpu.formats.tfile import read_tfile


def _hf_llama_dir(tmp_path: Path, *, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
                  hidden_dim=96, vocab=128, tied=False, n_experts=0) -> Path:
    from safetensors.numpy import save_file

    head_dim = dim // n_heads
    rng = np.random.default_rng(7)

    def rand(*shape):
        return (rng.standard_normal(shape) * 0.05).astype(np.float32)

    tensors = {"model.embed_tokens.weight": rand(vocab, dim)}
    for l in range(n_layers):
        pre = f"model.layers.{l}"
        tensors[f"{pre}.self_attn.q_proj.weight"] = rand(n_heads * head_dim, dim)
        tensors[f"{pre}.self_attn.k_proj.weight"] = rand(n_kv_heads * head_dim, dim)
        tensors[f"{pre}.self_attn.v_proj.weight"] = rand(n_kv_heads * head_dim, dim)
        tensors[f"{pre}.self_attn.o_proj.weight"] = rand(dim, n_heads * head_dim)
        if n_experts > 0:  # Mixtral-style sparse FFN
            tensors[f"{pre}.block_sparse_moe.gate.weight"] = rand(n_experts, dim)
            for e in range(n_experts):
                ex = f"{pre}.block_sparse_moe.experts.{e}"
                tensors[f"{ex}.w1.weight"] = rand(hidden_dim, dim)
                tensors[f"{ex}.w2.weight"] = rand(dim, hidden_dim)
                tensors[f"{ex}.w3.weight"] = rand(hidden_dim, dim)
        else:
            tensors[f"{pre}.mlp.gate_proj.weight"] = rand(hidden_dim, dim)
            tensors[f"{pre}.mlp.down_proj.weight"] = rand(dim, hidden_dim)
            tensors[f"{pre}.mlp.up_proj.weight"] = rand(hidden_dim, dim)
        tensors[f"{pre}.input_layernorm.weight"] = rand(dim) + 1.0
        tensors[f"{pre}.post_attention_layernorm.weight"] = rand(dim) + 1.0
    tensors["model.norm.weight"] = rand(dim) + 1.0
    if not tied:
        tensors["lm_head.weight"] = rand(vocab, dim)

    d = tmp_path / "hf_model"
    d.mkdir()
    # split across two shards to exercise the multi-file index
    keys = sorted(tensors)
    half = len(keys) // 2
    save_file({k: tensors[k] for k in keys[:half]},
              str(d / "model-00001-of-00002.safetensors"))
    save_file({k: tensors[k] for k in keys[half:]},
              str(d / "model-00002-of-00002.safetensors"))

    config = {
        "model_type": "mixtral" if n_experts else "llama",
        "hidden_act": "silu", "hidden_size": dim,
        "intermediate_size": hidden_dim, "num_hidden_layers": n_layers,
        "num_attention_heads": n_heads, "num_key_value_heads": n_kv_heads,
        "max_position_embeddings": 64, "vocab_size": vocab,
        "rope_theta": 10000.0, "rms_norm_eps": 1e-5,
    }
    if n_experts:
        config["num_local_experts"] = n_experts
        config["num_experts_per_tok"] = 2
    (d / "config.json").write_text(json.dumps(config))
    return d


class TestPermute:
    def test_round_trip_pairs(self):
        # permute must map HF half-split [h0..h{d/2-1}, g0..g{d/2-1}] rows into
        # interleaved [h0,g0,h1,g1,...] order per head (reference semantics:
        # convert-hf.py:12-15 + interleaved rope kernel nn-cpu-ops.cpp:836-856)
        n_heads, head_dim, cols = 2, 8, 4
        w = np.arange(n_heads * head_dim * cols, dtype=np.float32).reshape(
            n_heads * head_dim, cols)
        p = permute_rope_rows(w, n_heads)
        for h in range(n_heads):
            base = h * head_dim
            for i in range(head_dim // 2):
                np.testing.assert_array_equal(p[base + 2 * i], w[base + i])
                np.testing.assert_array_equal(p[base + 2 * i + 1],
                                              w[base + head_dim // 2 + i])

    def test_identity_when_single_pair(self):
        w = np.random.default_rng(0).standard_normal((4, 3)).astype(np.float32)
        np.testing.assert_array_equal(permute_rope_rows(w, 2), w)


class TestEncodeTensor:
    def test_f32_passthrough(self):
        x = np.random.default_rng(0).standard_normal(64).astype(np.float32)
        assert encode_tensor(x, quants.F32) == x.tobytes()

    def test_q40_matches_codec(self):
        x = np.random.default_rng(1).standard_normal(128).astype(np.float32)
        assert encode_tensor(x, quants.Q40) == quants.quantize_q40(x)


class TestConvertHF:
    def test_round_trip_through_model_file(self, tmp_path):
        d = _hf_llama_dir(tmp_path)
        out = tmp_path / "model.m"
        convert_hf(d, "q40", out, progress=False)

        with ModelFile.open(out) as mf:
            h = mf.header
            assert h.arch_type == ArchType.LLAMA
            assert h.dim == 64 and h.n_layers == 2
            assert h.n_heads == 4 and h.n_kv_heads == 2
            # the walker validates total size; spot-check a weight round-trips
            from safetensors.numpy import load_file
            shard1 = load_file(str(d / "model-00001-of-00002.safetensors"))
            shard2 = load_file(str(d / "model-00002-of-00002.safetensors"))
            src = {**shard1, **shard2}
            v = mf.tensor_f32("block_matmul_v.0")
            np.testing.assert_allclose(
                v, src["model.layers.0.self_attn.v_proj.weight"], atol=0.02)
            # q is permuted: dequantized file rows == permuted source rows
            q = mf.tensor_f32("block_matmul_q.0")
            np.testing.assert_allclose(
                q, permute_rope_rows(
                    src["model.layers.0.self_attn.q_proj.weight"], 4), atol=0.02)

    def test_tied_embeddings_fallback(self, tmp_path):
        d = _hf_llama_dir(tmp_path, tied=True)
        out = tmp_path / "tied.m"
        convert_hf(d, "q40", out, progress=False)
        with ModelFile.open(out) as mf:
            emb = mf.tensor_f32("embedding")
            logits = mf.tensor_f32("final_matmul_logits")
            np.testing.assert_allclose(logits, emb, atol=0.02)

    def test_converted_model_runs_forward(self, tmp_path):
        d = _hf_llama_dir(tmp_path)
        out = tmp_path / "model.m"
        convert_hf(d, "q40", out, progress=False)

        from dllama_tpu.runtime.engine import InferenceEngine
        eng = InferenceEngine(str(out))
        try:
            logits, _ = eng.prefill([1, 5, 9])
            assert np.all(np.isfinite(np.asarray(logits)))
        finally:
            eng.close()

    def test_f32_weights(self, tmp_path):
        d = _hf_llama_dir(tmp_path)
        out = tmp_path / "model_f32.m"
        convert_hf(d, "f32", out, progress=False)
        with ModelFile.open(out) as mf:
            from safetensors.numpy import load_file
            src = {**load_file(str(d / "model-00001-of-00002.safetensors")),
                   **load_file(str(d / "model-00002-of-00002.safetensors"))}
            w1 = mf.tensor_f32("block_matmul_w1.0")
            np.testing.assert_array_equal(
                w1, src["model.layers.0.mlp.gate_proj.weight"])


class TestConvertMeta:
    def test_two_shard_merge(self, tmp_path):
        import torch
        from dllama_tpu.convert.hf import convert_meta_llama

        dim, n_heads, n_kv, hidden, vocab, n_layers = 32, 4, 2, 48, 64, 1
        rng = np.random.default_rng(11)

        def r(*shape):
            return torch.from_numpy(
                (rng.standard_normal(shape) * 0.05).astype(np.float32))

        full = {
            "tok_embeddings.weight": r(vocab, dim),
            "layers.0.attention.wq.weight": r(dim, dim),
            "layers.0.attention.wk.weight": r(dim // 2, dim),
            "layers.0.attention.wv.weight": r(dim // 2, dim),
            "layers.0.attention.wo.weight": r(dim, dim),
            "layers.0.feed_forward.w1.weight": r(hidden, dim),
            "layers.0.feed_forward.w2.weight": r(dim, hidden),
            "layers.0.feed_forward.w3.weight": r(hidden, dim),
            "layers.0.attention_norm.weight": r(dim) + 1.0,
            "layers.0.ffn_norm.weight": r(dim) + 1.0,
            "norm.weight": r(dim) + 1.0,
            "output.weight": r(vocab, dim),
        }
        col_split = {"tok_embeddings.weight", "layers.0.attention.wo.weight",
                     "layers.0.feed_forward.w2.weight"}
        shards: list[dict] = [{}, {}]
        for name, t in full.items():
            if t.ndim == 1:
                shards[0][name] = shards[1][name] = t
            else:
                axis = 1 if name in col_split else 0
                a, b = torch.chunk(t, 2, dim=axis)
                shards[0][name], shards[1][name] = a.contiguous(), b.contiguous()

        d = tmp_path / "meta"
        d.mkdir()
        torch.save(shards[0], d / "consolidated.00.pth")
        torch.save(shards[1], d / "consolidated.01.pth")
        (d / "params.json").write_text(json.dumps({
            "dim": dim, "n_layers": n_layers, "n_heads": n_heads,
            "n_kv_heads": n_kv, "vocab_size": vocab, "max_seq_len": 64,
            "norm_eps": 1e-5, "rope_theta": 10000,
        }))

        out = tmp_path / "meta.m"
        convert_meta_llama(d, "f32", out, progress=False)
        with ModelFile.open(out) as mf:
            assert mf.header.hidden_dim == hidden
            np.testing.assert_array_equal(
                mf.tensor_f32("block_matmul_wo.0"),
                full["layers.0.attention.wo.weight"].numpy())
            np.testing.assert_array_equal(
                mf.tensor_f32("block_matmul_w1.0"),
                full["layers.0.feed_forward.w1.weight"].numpy())
            np.testing.assert_array_equal(
                mf.tensor_f32("embedding"),
                full["tok_embeddings.weight"].numpy())


class TestConfigMapping:
    def test_rejects_unknown_arch(self, tmp_path):
        d = tmp_path / "bad"
        d.mkdir()
        (d / "config.json").write_text(json.dumps({"model_type": "gpt2"}))
        with pytest.raises(ValueError, match="unsupported arch"):
            load_hf_config(d, quants.Q40)

    def test_rope_scaling_llama31(self, tmp_path):
        d = tmp_path / "rs"
        d.mkdir()
        config = {
            "model_type": "llama", "hidden_act": "silu", "hidden_size": 64,
            "intermediate_size": 96, "num_hidden_layers": 1,
            "num_attention_heads": 4, "num_key_value_heads": 2,
            "max_position_embeddings": 64, "vocab_size": 128,
            "rope_theta": 500000.0, "rms_norm_eps": 1e-5,
            "rope_scaling": {"rope_type": "llama3", "factor": 32,
                             "low_freq_factor": 1, "high_freq_factor": 4,
                             "original_max_position_embeddings": 8192},
        }
        (d / "config.json").write_text(json.dumps(config))
        params = load_hf_config(d, quants.Q40)
        assert params["rope_scaling_factor"] == 32
        assert params["rope_type"] == 2  # LLAMA3_1

    def test_plan_covers_qwen3_norms(self):
        params = {"weight_float_type": quants.Q40,
                  "arch_type": int(ArchType.QWEN3), "n_heads": 4,
                  "n_kv_heads": 2, "n_layers": 1, "n_experts": 0}
        plan = hf_tensor_plan(params)
        keys = [p.keys[0] for p in plan]
        assert "model.layers.0.self_attn.q_norm.weight" in keys
        assert "model.layers.0.self_attn.k_norm.weight" in keys


class TestTokenizerConverters:
    def test_unicode_byte_table_complete(self):
        table = unicode_to_bytes()
        assert sorted(table.values()) == list(range(256))

    def test_token_str_to_bytes_gpt2_space(self):
        table = unicode_to_bytes()
        # GPT-2 byte-level BPE encodes space as U+0120 'Ġ'
        assert token_str_to_bytes("Ġhello", table) == b" hello"

    def test_resolve_hf_vocab_scores_monotonic(self):
        vocab, scores = resolve_hf_vocab(["a", "b", "Ġc"])
        assert vocab == [b"a", b"b", b" c"]
        assert scores == [0.0, -1.0, -2.0]

    def test_llama3_tiktoken_file(self, tmp_path):
        import base64
        lines = []
        base_vocab = [b"a", b"b", b"ab", b" the"]
        for i, tok in enumerate(base_vocab):
            lines.append(f"{base64.b64encode(tok).decode()} {i}")
        model = tmp_path / "tokenizer.model"
        model.write_text("\n".join(lines))

        out = tmp_path / "llama3.t"
        convert_tokenizer_llama3(model, out, progress=False)
        data = read_tfile(out)
        assert data.vocab[:4] == base_vocab
        assert data.vocab[4] == b"<|begin_of_text|>"
        assert len(data.vocab) == 4 + 256
        assert data.scores[0] == 0.0 and data.scores[2] == -2.0
        assert data.bos_id == 128000
        assert data.eos_token_ids == [128001, 128009]
        assert data.chat_template and "<|start_header_id|>" in data.chat_template

    def test_hf_fast_tokenizer_dir(self, tmp_path):
        # minimal byte-level-BPE tokenizer.json for PreTrainedTokenizerFast
        tok_json = {
            "version": "1.0",
            "truncation": None, "padding": None,
            "added_tokens": [
                {"id": 4, "content": "<|bos|>", "single_word": False,
                 "lstrip": False, "rstrip": False, "normalized": False,
                 "special": True},
                {"id": 5, "content": "<|eos|>", "single_word": False,
                 "lstrip": False, "rstrip": False, "normalized": False,
                 "special": True},
            ],
            "normalizer": None,
            "pre_tokenizer": {"type": "ByteLevel", "add_prefix_space": False,
                              "trim_offsets": True, "use_regex": True},
            "post_processor": None,
            "decoder": {"type": "ByteLevel", "add_prefix_space": True,
                        "trim_offsets": True, "use_regex": True},
            "model": {"type": "BPE", "dropout": None, "unk_token": None,
                      "continuing_subword_prefix": None,
                      "end_of_word_suffix": None, "fuse_unk": False,
                      "byte_fallback": False,
                      "vocab": {"a": 0, "b": 1, "ab": 2, "Ġx": 3},
                      "merges": [["a", "b"]]},
        }
        d = tmp_path / "tok"
        d.mkdir()
        (d / "tokenizer.json").write_text(json.dumps(tok_json))
        (d / "tokenizer_config.json").write_text(json.dumps({
            "tokenizer_class": "PreTrainedTokenizerFast",
            "bos_token": "<|bos|>", "eos_token": "<|eos|>",
            "chat_template": "{{ messages }}", "add_bos_token": True,
        }))
        (d / "config.json").write_text(json.dumps(
            {"bos_token_id": 4, "eos_token_id": 5}))

        from dllama_tpu.convert.tokenizers import convert_tokenizer_hf
        out = tmp_path / "hf.t"
        convert_tokenizer_hf(d, out, progress=False)
        data = read_tfile(out)
        assert data.vocab[0] == b"a" and data.vocab[2] == b"ab"
        assert data.vocab[3] == b" x"
        assert data.bos_id == 4 and data.eos_token_ids == [5]
        assert data.chat_template == "{{ messages }}"


class TestChecksumManifest:
    """The converter emits a per-tensor crc32 sidecar and the loader
    verifies against it — a flipped byte must be detected AND blamed on
    the exact tensor (ISSUE 4 satellite)."""

    def test_convert_emits_manifest_covering_every_tensor(self, tmp_path):
        d = _hf_llama_dir(tmp_path)
        out = tmp_path / "model.m"
        convert_hf(d, "q40", out, progress=False)
        from dllama_tpu.formats.mfile import manifest_path

        assert Path(manifest_path(out)).exists()
        with ModelFile.open(out) as mf:
            assert mf.checksums is not None
            assert set(mf.checksums) == set(mf.tensors)

    def test_bit_flipped_tensor_detected_with_tensor_name(self, tmp_path):
        d = _hf_llama_dir(tmp_path)
        out = tmp_path / "model.m"
        convert_hf(d, "q40", out, progress=False)
        with ModelFile.open(out) as mf:
            rec = mf.tensors["block_matmul_w1.1"]
        with open(out, "r+b") as f:
            f.seek(rec.offset + 3)
            b = f.read(1)
            f.seek(rec.offset + 3)
            f.write(bytes([b[0] ^ 0x01]))  # one flipped bit
        from dllama_tpu.runtime.engine import InferenceEngine
        from dllama_tpu.runtime.weights import WeightIntegrityError

        with pytest.raises(WeightIntegrityError,
                           match=r"block_matmul_w1\.1"):
            InferenceEngine(str(out))

    def test_reconvert_over_existing_output_refreshes_manifest(self, tmp_path):
        """Converting onto a path that already has a model + manifest
        (e.g. the same checkpoint at a different float type) must replace
        both, not choke on the now-stale sidecar mid-write."""
        d = _hf_llama_dir(tmp_path)
        out = tmp_path / "model.m"
        convert_hf(d, "q40", out, progress=False)
        convert_hf(d, "f32", out, progress=False)  # stale .sums left behind
        with ModelFile.open(out) as mf:
            assert mf.header.weight_type == quants.F32
            assert set(mf.checksums) == set(mf.tensors)

    def test_unflipped_converted_model_loads_verified(self, tmp_path):
        d = _hf_llama_dir(tmp_path)
        out = tmp_path / "model.m"
        convert_hf(d, "q40", out, progress=False)
        from dllama_tpu.runtime.engine import InferenceEngine

        eng = InferenceEngine(str(out))
        try:
            logits, _ = eng.prefill([1, 5, 9])
            assert np.all(np.isfinite(np.asarray(logits)))
        finally:
            eng.close()


class TestQwen3MoeMixedConfigs:
    """Mixed dense/MoE stacks can't be expressed in the .m layer plan —
    conversion must reject them instead of writing a wrong model
    (advisor round-1 finding)."""

    def _cfg(self, **extra):
        return {
            "model_type": "qwen3_moe", "hidden_act": "silu", "hidden_size": 64,
            "intermediate_size": 96, "moe_intermediate_size": 48,
            "num_hidden_layers": 4, "num_attention_heads": 4,
            "num_key_value_heads": 2, "max_position_embeddings": 64,
            "vocab_size": 128, "rope_theta": 10000.0, "rms_norm_eps": 1e-5,
            "num_experts": 8, "num_experts_per_tok": 2, **extra,
        }

    def _load(self, tmp_path, cfg):
        d = tmp_path / "moe"
        d.mkdir(exist_ok=True)
        (d / "config.json").write_text(json.dumps(cfg))
        return load_hf_config(d, quants.Q40)

    def test_all_moe_accepted(self, tmp_path):
        params = self._load(tmp_path, self._cfg())
        assert params["n_experts"] == 8

    def test_mlp_only_layers_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="mlp_only_layers"):
            self._load(tmp_path, self._cfg(mlp_only_layers=[0, 1]))

    def test_sparse_step_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="decoder_sparse_step"):
            self._load(tmp_path, self._cfg(decoder_sparse_step=2))


def test_converted_mixtral_runs_quantized_experts(tmp_path):
    """End-to-end MoE: synthetic Mixtral-style HF checkpoint → q40 .m file
    (expert tensors quantized on disk, router emitted) → engine loads the
    expert planes as stacked QuantizedWeight (1 B/weight resident). The
    TIGHT check is quantized-resident vs dense-load of the SAME q40 file
    (identical dequant values); the f32-converted twin only bounds overall
    Q40 whole-model drift via correlation."""
    from dllama_tpu.convert.hf import convert_hf
    from dllama_tpu.ops.linear import QuantizedWeight
    from dllama_tpu.runtime.engine import InferenceEngine

    d = _hf_llama_dir(tmp_path, n_experts=4)
    out_q = tmp_path / "moe_q40.m"
    convert_hf(d, "q40", out_q, progress=False)
    out_f = tmp_path / "moe_f32.m"
    convert_hf(d, "f32", out_f, progress=False)

    with ModelFile.open(out_q) as mf:
        assert mf.header.n_experts == 4 and mf.has_moe_router
        assert mf.tensors["block_expert_w1.0.0"].float_type == quants.Q40
        assert mf.tensors["block_moe_gate.0"].float_type == quants.F32

    eng = InferenceEngine(str(out_q))
    try:
        assert isinstance(eng.params.layers.we1, QuantizedWeight)
        lq, _ = eng.prefill([1, 5, 9])
    finally:
        eng.close()
    # dense-load the SAME q40 file: identical dequant values, so parity is
    # tight (residency differs, math doesn't)
    eng_d = InferenceEngine(str(out_q), weight_mode="f32")
    try:
        assert not isinstance(eng_d.params.layers.we1, QuantizedWeight)
        ld, _ = eng_d.prefill([1, 5, 9])
    finally:
        eng_d.close()
    assert np.all(np.isfinite(np.asarray(lq)))
    np.testing.assert_allclose(np.asarray(lq), np.asarray(ld),
                               rtol=1e-5, atol=1e-5)
    # and the f32-converted twin stays in the same ballpark (pure Q40
    # whole-model quantization drift on a random tiny model)
    eng_f = InferenceEngine(str(out_f))
    try:
        lf, _ = eng_f.prefill([1, 5, 9])
    finally:
        eng_f.close()
    assert np.corrcoef(np.asarray(lq).ravel(),
                       np.asarray(lf).ravel())[0, 1] > 0.95
