"""Ring attention / sequence parallelism vs the dense oracle.

The correctness property mirrors the reference's node-count invariance
(SURVEY.md §4) extended to the seq axis: attention over a seq-sharded KV
cache must produce the same output as the dense single-device path, for both
the ring (seq-sharded queries, prefill) and LSE-merge (replicated queries,
decode) paths, alone and composed with tp/dp.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dllama_tpu.formats import mfile
from dllama_tpu.models import ModelConfig, forward, init_random_params
from dllama_tpu.ops.attention import attention
from dllama_tpu.parallel import use_plan
from dllama_tpu.parallel.api import make_mesh
from dllama_tpu.parallel.ring import sp_attention, sp_supported
from dllama_tpu.parallel.sharding import kv_cache_sharding, shard_params
from dllama_tpu.runtime import KVCache
from dllama_tpu.runtime.kvcache import update_layer


def _rand_case(rng, B, T, H, n_kv, S, hd, start_pos):
    q = jnp.asarray(rng.standard_normal((B, T, H, hd)), dtype=jnp.float32)
    new_k = jnp.asarray(rng.standard_normal((B, T, n_kv, hd)), dtype=jnp.float32)
    new_v = jnp.asarray(rng.standard_normal((B, T, n_kv, hd)), dtype=jnp.float32)
    # cache prefilled with history rows 0..start_pos
    k_cache = jnp.asarray(rng.standard_normal((B, n_kv, S, hd)), dtype=jnp.float32)
    v_cache = jnp.asarray(rng.standard_normal((B, n_kv, S, hd)), dtype=jnp.float32)
    positions = start_pos + jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))
    return q, new_k, new_v, k_cache, v_cache, positions


def _oracle(q, new_k, new_v, k_cache, v_cache, positions, start_pos, hd):
    k_cache, v_cache = update_layer(k_cache, v_cache, new_k, new_v,
                                    jnp.int32(start_pos))
    out = attention(q, k_cache, v_cache, positions, hd)
    return out, k_cache, v_cache


@pytest.mark.parametrize("mesh_axes,T,start_pos", [
    ({"sp": 8}, 16, 0),        # prefill, ring path, pure sp
    ({"sp": 4}, 1, 9),         # decode, merge path
    ({"sp": 2, "tp": 4}, 8, 4),   # sp × tp, ring
    ({"dp": 2, "sp": 2, "tp": 2}, 1, 13),  # 3-axis decode
    ({"dp": 2, "sp": 2, "tp": 2}, 6, 2),   # 3-axis prefill, ring (6 % sp2 == 0)
    ({"sp": 4}, 6, 3),   # T=6 not divisible by sp=4 → replicated-q merge, T>1
    ({"sp": 8}, 3, 0),   # prefill chunk smaller than ring → merge, T>1
])
def test_sp_attention_matches_oracle(mesh_axes, T, start_pos):
    B = 2 if "dp" in mesh_axes else 1
    H, n_kv, S, hd = 8, 4, 32, 16
    rng = np.random.default_rng(42 + T + start_pos)
    q, new_k, new_v, k_cache, v_cache, positions = _rand_case(
        rng, B, T, H, n_kv, S, hd, start_pos)

    ref_out, ref_k, ref_v = _oracle(q, new_k, new_v, k_cache, v_cache,
                                    positions, start_pos, hd)

    plan = make_mesh(mesh_axes)
    assert sp_supported(plan, q.shape, k_cache.shape)
    got = jax.jit(lambda *a: sp_attention(plan, *a, head_dim=hd))(
        q, k_cache, v_cache, new_k, new_v, positions, jnp.int32(start_pos))
    out, got_k, got_v = got

    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got_k), np.asarray(ref_k), atol=1e-6)
    np.testing.assert_allclose(np.asarray(got_v), np.asarray(ref_v), atol=1e-6)


def test_sp_attention_sequential_decode():
    """Prefill via ring then several decode steps via merge, one shared cache."""
    B, H, n_kv, S, hd = 1, 4, 2, 16, 8
    plan = make_mesh({"sp": 4})
    rng = np.random.default_rng(7)

    k_cache = jnp.zeros((B, n_kv, S, hd))
    v_cache = jnp.zeros((B, n_kv, S, hd))
    ref_k, ref_v = k_cache, v_cache

    pos = 0
    for T in (8, 1, 1, 1):
        q, new_k, new_v, _, _, positions = _rand_case(
            rng, B, T, H, n_kv, S, hd, pos)
        ref_out, ref_k, ref_v = _oracle(q, new_k, new_v, ref_k, ref_v,
                                        positions, pos, hd)
        out, k_cache, v_cache = sp_attention(
            plan, q, k_cache, v_cache, new_k, new_v, positions,
            jnp.int32(pos), head_dim=hd)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                                   rtol=1e-5, atol=1e-5)
        pos += T
    np.testing.assert_allclose(np.asarray(k_cache), np.asarray(ref_k), atol=1e-6)


def _cfg(**kw):
    base = dict(
        arch=mfile.ArchType.LLAMA, dim=64, hidden_dim=96, n_layers=2,
        n_heads=8, n_kv_heads=4, head_dim=8, vocab_size=128, seq_len=32,
        norm_epsilon=1e-5, rope_theta=10000.0, rope_type=mfile.RopeType.LLAMA,
    )
    base.update(kw)
    return ModelConfig(**base)


@pytest.mark.parametrize("mesh_axes", [
    {"sp": 8},
    {"sp": 2, "tp": 4},
    {"dp": 2, "sp": 2, "tp": 2},
])
def test_forward_with_sp_matches_unsharded(mesh_axes):
    """Full model forward on an sp mesh — prefill chunk + decode step — must
    match the single-device run (the seq-parallel analogue of
    test_tp_logits_match_unsharded)."""
    cfg = _cfg()
    B = 2 if "dp" in mesh_axes else 1
    params = init_random_params(cfg, seed=23)
    rng = np.random.default_rng(5)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, 8)), dtype=jnp.int32)

    ref_logits, ref_kv = jax.jit(forward, static_argnums=1)(
        params, cfg, prompt, jnp.int32(0), KVCache.create(cfg, batch_size=B))
    nxt = jnp.argmax(ref_logits[:, -1:], axis=-1).astype(jnp.int32)
    ref_logits2, _ = jax.jit(forward, static_argnums=1)(
        params, cfg, nxt, jnp.int32(8), ref_kv)

    plan = make_mesh(mesh_axes)
    sharded = shard_params(plan, params)
    kv0 = KVCache.create(cfg, batch_size=B)
    kv = jax.device_put(kv0, kv_cache_sharding(plan, kv0))
    with use_plan(plan):
        logits, kv = jax.jit(forward, static_argnums=1)(
            sharded, cfg, prompt, jnp.int32(0), kv)
        np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                                   rtol=2e-5, atol=2e-6)
        nxt2 = jnp.argmax(logits[:, -1:], axis=-1).astype(jnp.int32)
        logits2, _ = jax.jit(forward, static_argnums=1)(
            sharded, cfg, nxt2, jnp.int32(8), kv)
    np.testing.assert_allclose(np.asarray(logits2), np.asarray(ref_logits2),
                               rtol=2e-5, atol=2e-6)


def test_sp_unsupported_falls_back():
    plan = make_mesh({"sp": 8})
    # cache seq 20 not divisible by 8 → path must decline
    assert not sp_supported(plan, (1, 4, 8, 16), (1, 4, 20, 16))


# ---------------------------------------------------------------------------
# Pallas kernel inside the ring (VERDICT round-2 #5): per-block flash kernel
# (interpret mode on CPU) must match the einsum block path exactly — same
# online-softmax algebra, same collectives, kernel-computed blocks.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mesh_axes,T,start_pos", [
    ({"sp": 2}, 8, 0),            # prefill, ring path (s_local = 128)
    ({"sp": 2}, 1, 130),          # decode, merge path, history in shard 2
    ({"sp": 2, "tp": 2}, 4, 7),   # sp × tp ring
    ({"sp": 2}, 3, 100),          # T not divisible by sp → merge, T>1
])
def test_sp_attention_kernel_matches_oracle(mesh_axes, T, start_pos):
    """attn_impl='flash' forces the Pallas block kernel (interpret on CPU)
    inside the sp shard_map; outputs must match the dense oracle."""
    B, H, n_kv, hd = 1, 8, 4, 16
    S = 256  # S / sp = 128: one kernel block per shard
    rng = np.random.default_rng(1000 + T + start_pos)
    q, new_k, new_v, k_cache, v_cache, positions = _rand_case(
        rng, B, T, H, n_kv, S, hd, start_pos)

    ref_out, ref_k, ref_v = _oracle(q, new_k, new_v, k_cache, v_cache,
                                    positions, start_pos, hd)

    plan = make_mesh(mesh_axes)
    out, got_k, got_v = jax.jit(
        lambda *a: sp_attention(plan, *a, head_dim=hd, attn_impl="flash"))(
        q, k_cache, v_cache, new_k, new_v, positions, jnp.int32(start_pos))

    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got_k), np.asarray(ref_k), atol=1e-6)
    np.testing.assert_allclose(np.asarray(got_v), np.asarray(ref_v), atol=1e-6)


@pytest.mark.slow
@pytest.mark.parametrize("T,start_pos", [
    (4, 1996),   # T % sp == 0 → RING path
    (3, 1997),   # indivisible T → LSE-merge path over the long cache
])
def test_sp_long_context_kernel_matches_oracle(T, start_pos):
    """Long-context shape: S=2048 over sp=2 — 1024 rows per shard, so
    _pick_bs chooses 512 and each shard's kernel runs TWO blocks: the
    intra-shard online-softmax m/l carry is exercised, not just the
    cross-shard ring/merge combining (review finding: sp=4 would make each
    shard a single block). Late positions; both the ring (divisible T) and
    merge (indivisible T) paths."""
    B, H, n_kv, hd = 1, 8, 4, 16
    S = 2048
    rng = np.random.default_rng(2048 + T)
    q, new_k, new_v, k_cache, v_cache, positions = _rand_case(
        rng, B, T, H, n_kv, S, hd, start_pos)
    ref_out, ref_k, ref_v = _oracle(q, new_k, new_v, k_cache, v_cache,
                                    positions, start_pos, hd)
    plan = make_mesh({"sp": 2})
    out, got_k, got_v = jax.jit(
        lambda *a: sp_attention(plan, *a, head_dim=hd, attn_impl="flash"))(
        q, k_cache, v_cache, new_k, new_v, positions, jnp.int32(start_pos))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got_k), np.asarray(ref_k), atol=1e-6)
    np.testing.assert_allclose(np.asarray(got_v), np.asarray(ref_v), atol=1e-6)


def test_sp_kernel_forced_on_unsupported_shape_raises():
    """attn_impl='flash' with an sp shard too small for the kernel must fail
    loudly, not silently fall back (the advisor's forced-flash rule)."""
    plan = make_mesh({"sp": 8})
    rng = np.random.default_rng(0)
    q, new_k, new_v, k_cache, v_cache, positions = _rand_case(
        rng, 1, 8, 8, 4, 32, 16, 0)  # s_local = 4: no 128-block fits
    with pytest.raises(ValueError, match="flash"):
        sp_attention(plan, q, k_cache, v_cache, new_k, new_v, positions,
                     jnp.int32(0), head_dim=16, attn_impl="flash")


def test_forward_sp_with_kernel_matches_unsharded():
    """Full model forward with attn_impl='flash' on an sp mesh (kernel inside
    the ring) vs the unsharded xla forward — the determinism property the
    VERDICT asked to keep on the kernel path."""
    cfg = _cfg(seq_len=256, attn_impl="flash")
    cfg_ref = _cfg(seq_len=256, attn_impl="xla")
    params = init_random_params(cfg, seed=29)
    rng = np.random.default_rng(17)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 8)), dtype=jnp.int32)

    ref_logits, ref_kv = jax.jit(forward, static_argnums=1)(
        params, cfg_ref, prompt, jnp.int32(0), KVCache.create(cfg_ref))
    nxt = jnp.argmax(ref_logits[:, -1:], axis=-1).astype(jnp.int32)
    ref_logits2, _ = jax.jit(forward, static_argnums=1)(
        params, cfg_ref, nxt, jnp.int32(8), ref_kv)

    plan = make_mesh({"sp": 2})
    sharded = shard_params(plan, params)
    kv0 = KVCache.create(cfg)
    kv = jax.device_put(kv0, kv_cache_sharding(plan, kv0))
    with use_plan(plan):
        logits, kv = jax.jit(forward, static_argnums=1)(
            sharded, cfg, prompt, jnp.int32(0), kv)
        np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                                   rtol=2e-5, atol=2e-6)
        nxt2 = jnp.argmax(logits[:, -1:], axis=-1).astype(jnp.int32)
        logits2, _ = jax.jit(forward, static_argnums=1)(
            sharded, cfg, nxt2, jnp.int32(8), kv)
    np.testing.assert_allclose(np.asarray(logits2), np.asarray(ref_logits2),
                               rtol=2e-5, atol=2e-6)


# ---------------------------------------------------------------------------
# sp × ragged (VERDICT r4 next #6): per-row depths ride the same ring/merge
# paths — positions are affine within each batch row, which is all the
# per-row masks (and the kernel's per-row pos table) assume.
# ---------------------------------------------------------------------------


def _ragged_case(rng, B, T, H, n_kv, S, hd, depths):
    q = jnp.asarray(rng.standard_normal((B, T, H, hd)), dtype=jnp.float32)
    new_k = jnp.asarray(rng.standard_normal((B, T, n_kv, hd)), dtype=jnp.float32)
    new_v = jnp.asarray(rng.standard_normal((B, T, n_kv, hd)), dtype=jnp.float32)
    k_cache = jnp.asarray(rng.standard_normal((B, n_kv, S, hd)), dtype=jnp.float32)
    v_cache = jnp.asarray(rng.standard_normal((B, n_kv, S, hd)), dtype=jnp.float32)
    start = jnp.asarray(depths, dtype=jnp.int32)
    positions = start[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    return q, new_k, new_v, k_cache, v_cache, positions, start


@pytest.mark.parametrize("mesh_axes,T,depths", [
    ({"sp": 4}, 1, [9, 17]),            # ragged decode, merge path
    ({"sp": 2}, 4, [3, 11]),            # ragged verify (T=K+1), ring path
    ({"sp": 2, "tp": 2}, 1, [5, 20]),   # composed with tp
    ({"dp": 2, "sp": 2}, 1, [0, 13]),   # composed with dp
])
def test_sp_attention_ragged_matches_oracle(mesh_axes, T, depths):
    B = len(depths)
    H, n_kv, S, hd = 8, 4, 32, 16
    rng = np.random.default_rng(61 + T)
    q, new_k, new_v, k_cache, v_cache, positions, start = _ragged_case(
        rng, B, T, H, n_kv, S, hd, depths)

    ref_k, ref_v = update_layer(k_cache, v_cache, new_k, new_v, start)
    ref_out = attention(q, ref_k, ref_v, positions, hd)

    plan = make_mesh(mesh_axes)
    assert sp_supported(plan, q.shape, k_cache.shape)
    got = jax.jit(lambda *a: sp_attention(plan, *a, head_dim=hd))(
        q, k_cache, v_cache, new_k, new_v, positions, start)
    assert got is not None
    out, got_k, got_v = got
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got_k), np.asarray(ref_k), atol=1e-6)
    np.testing.assert_allclose(np.asarray(got_v), np.asarray(ref_v), atol=1e-6)


def test_sp_attention_ragged_kernel_matches_oracle():
    """Ragged depths through the Pallas per-block kernel (forced, interpret
    off-TPU): the kernel's per-batch-row pos table carries the slot depths."""
    B, T, H, n_kv, hd, S = 2, 1, 8, 4, 16, 256  # S/sp = 128: kernel tile
    rng = np.random.default_rng(77)
    q, new_k, new_v, k_cache, v_cache, positions, start = _ragged_case(
        rng, B, T, H, n_kv, S, hd, [9, 130])

    ref_k, ref_v = update_layer(k_cache, v_cache, new_k, new_v, start)
    ref_out = attention(q, ref_k, ref_v, positions, hd)

    plan = make_mesh({"sp": 2})
    got = jax.jit(lambda *a: sp_attention(plan, *a, head_dim=hd,
                                          attn_impl="flash"))(
        q, k_cache, v_cache, new_k, new_v, positions, start)
    assert got is not None
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(ref_out),
                               rtol=1e-5, atol=1e-5)


def test_forward_sp_ragged_matches_unsharded():
    """Model-level: forward with a [B] start_pos vector under an sp mesh
    equals the unsharded ragged run (the gate _layer_step used to apply)."""
    from dllama_tpu.models import ModelConfig, forward, init_random_params
    from dllama_tpu.formats import mfile as _mf

    cfg = ModelConfig(
        arch=_mf.ArchType.LLAMA, dim=64, hidden_dim=96, n_layers=2,
        n_heads=8, n_kv_heads=4, head_dim=8, vocab_size=128, seq_len=32,
        norm_epsilon=1e-5, rope_theta=10000.0, rope_type=_mf.RopeType.LLAMA)
    params = init_random_params(cfg, seed=8)
    rng = np.random.default_rng(3)
    tokens = jnp.asarray(rng.integers(0, 128, (2, 1)), dtype=jnp.int32)
    start = jnp.asarray([7, 19], dtype=jnp.int32)
    kv0 = KVCache.create(cfg, batch_size=2)
    ref, _ = jax.jit(forward, static_argnums=1)(params, cfg, tokens, start, kv0)

    plan = make_mesh({"sp": 4})
    sharded = shard_params(plan, params)
    kv1 = KVCache.create(cfg, batch_size=2)
    kv = jax.device_put(kv1, kv_cache_sharding(plan, kv1))
    with use_plan(plan):
        got, _ = jax.jit(forward, static_argnums=1)(
            sharded, cfg, tokens, start, kv)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_engine_sp_with_non_divisible_seq_len(tmp_path):
    """seq_len 100 under sp=2: the padded cache (128 rows) divides the sp
    axis, so the old 'seq_len not divisible by sp' rejection is gone and
    generation matches the unsharded engine."""
    import numpy as np

    from dllama_tpu.formats import tfile
    from dllama_tpu.runtime.engine import InferenceEngine
    from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model

    m, t = tmp_path / "m.m", tmp_path / "t.t"
    write_tiny_model(m, tiny_header_params(vocab_size=268, seq_len=100),
                     np.random.default_rng(51))
    tfile.write_tfile(t, byte_vocab_tokenizer())
    solo = InferenceEngine(str(m), str(t), tp=1, temperature=0.0)
    want = solo.generate("hello world", 6, stop_on_eos=False).tokens
    solo.close()
    spe = InferenceEngine(str(m), str(t), tp=1, sp=2, temperature=0.0)
    got = spe.generate("hello world", 6, stop_on_eos=False).tokens
    spe.close()
    assert got == want
