"""Paged KV cache + continuous batching (runtime/kvblocks.py, the paged
program family in models/llama.py, and PagedGenerator/BatchScheduler in
runtime/serving.py).

Three tiers:

1. **Allocator properties** — pure host bookkeeping, no jax: thousands of
   alloc/free/share/copy-on-write cycles asserting the refcount invariants
   (no double free, freed blocks reusable, shared blocks never a write
   target, cached LRU eviction unregisters).
2. **Gather parity** — ``paged_forward`` through a deliberately scrambled
   block table is bit-identical to the dense slot-pool ``forward`` on the
   same inputs: the block-table indirection must be value-invisible.
3. **Serving acceptance** — the ISSUE-6 criteria: a request stream larger
   than the slot capacity completes under continuous batching token-exact
   vs fresh solo oracles; chunked prefill interleaves with decode; a
   shared-prefix workload shows ``dllama_kv_blocks_shared > 0`` with
   block-level reuse >= the dense pool's longest-prefix accounting, and
   zero post-steady compiles (ledger-asserted).
"""

import numpy as np
import pytest

from dllama_tpu.formats import tfile
from dllama_tpu.runtime import introspection
from dllama_tpu.runtime import telemetry as tm
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.runtime.kvblocks import (BlockPool, BlockPoolExhausted,
                                         PagedKVCache, blocks_per_seq,
                                         validate_block_size)
from dllama_tpu.runtime.kvcache import padded_cache_len
from dllama_tpu.runtime.serving import BatchScheduler, PagedGenerator, Request

from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model


# ---------------------------------------------------------------------------
# 1. BlockPool allocator properties (pure host, no jax)
# ---------------------------------------------------------------------------


def test_alloc_refcount_free_roundtrip():
    pool = BlockPool(8, 16)
    assert pool.free_blocks() == 7  # block 0 is the null block
    a = pool.alloc()
    b = pool.alloc()
    assert a != b and a != pool.NULL and b != pool.NULL
    assert pool.refcount(a) == 1 and pool.refcount(b) == 1
    assert pool.used_blocks() == 2
    pool.release(a)
    assert pool.refcount(a) == 0
    assert pool.free_blocks() == 6  # unregistered: straight back to free
    assert pool.used_blocks() == 1


def test_double_free_raises():
    pool = BlockPool(4, 8)
    a = pool.alloc()
    pool.release(a)
    with pytest.raises(ValueError, match="double free"):
        pool.release(a)


def test_null_block_is_never_sharable_or_releasable():
    pool = BlockPool(4, 8)
    with pytest.raises(ValueError):
        pool.share(pool.NULL)
    with pytest.raises(ValueError):
        pool.release(pool.NULL)


def test_share_free_block_raises():
    pool = BlockPool(4, 8)
    a = pool.alloc()
    pool.release(a)  # unregistered -> free, not cached
    with pytest.raises(ValueError, match="not shareable"):
        pool.share(a)


def test_exhaustion_raises_then_recovers_after_release():
    pool = BlockPool(4, 8)
    got = [pool.alloc() for _ in range(3)]
    with pytest.raises(BlockPoolExhausted):
        pool.alloc()
    pool.release(got[1])
    again = pool.alloc()  # freed block is reusable
    assert again == got[1]
    assert pool.used_blocks() == 3


def test_shared_blocks_counts_refcount_above_one():
    pool = BlockPool(8, 4)
    bids = [pool.alloc(), pool.alloc()]
    pool.register_prompt(bids, list(range(8)))  # two full blocks
    assert pool.shared_blocks() == 0
    shared, n, cow, cow_r = pool.match_prefix(list(range(8)))
    assert shared == bids and n == 8 and cow is None and cow_r == 0
    for b in shared:
        pool.share(b)
    assert pool.shared_blocks() == 2
    for b in shared:
        pool.release(b)
    assert pool.shared_blocks() == 0


def test_released_registered_blocks_park_in_cache_and_still_match():
    pool = BlockPool(8, 4)
    bids = [pool.alloc()]
    pool.register_prompt(bids, list(range(4)))
    pool.release(bids[0])
    assert pool.refcount(bids[0]) == 0
    assert pool.free_blocks() == 7  # cached blocks stay allocatable
    shared, n, _, _ = pool.match_prefix(list(range(4)))
    assert shared == bids and n == 4  # retired prompt still shareable
    pool.share(bids[0])  # resurrect from the cache
    assert pool.refcount(bids[0]) == 1


def test_lru_eviction_recycles_cached_blocks_and_unregisters():
    pool = BlockPool(4, 4)  # 3 usable blocks
    # register three single-block prompts, release all -> all cached
    prompts = [[1, 2, 3, 4], [5, 6, 7, 8], [9, 10, 11, 12]]
    bids = []
    for p in prompts:
        b = pool.alloc()
        pool.register_prompt([b], p)
        pool.release(b)
        bids.append(b)
    assert pool.free_blocks() == 3
    # allocation pressure: the OLDEST cached block (prompts[0]) is evicted
    fresh = pool.alloc()
    assert fresh == bids[0]
    shared, n, cow, cow_r = pool.match_prefix(prompts[0])
    assert shared == [] and n == 0 and cow is None  # evicted = unregistered
    shared, n, _, _ = pool.match_prefix(prompts[1])
    assert shared == [bids[1]] and n == 4  # younger entries survive


def test_match_prefix_cow_tail():
    pool = BlockPool(8, 4)
    bids = [pool.alloc(), pool.alloc()]
    # one full block [1,2,3,4] + a partial tail [5,6]
    pool.register_prompt(bids, [1, 2, 3, 4, 5, 6])
    pool.release(bids[0])
    pool.release(bids[1])
    # a new prompt sharing the full block and 1 token of the tail
    shared, n, cow, cow_r = pool.match_prefix([1, 2, 3, 4, 5, 99, 100])
    assert shared == [bids[0]] and n == 4
    assert cow == bids[1] and cow_r == 1
    # divergence inside the first block: nothing shared, CoW from pos 0
    shared, n, cow, cow_r = pool.match_prefix([1, 2, 99, 100])
    assert shared == [] and n == 0
    assert cow == bids[0] and cow_r == 2


def test_register_prompt_skips_already_indexed_blocks():
    pool = BlockPool(8, 4)
    a = pool.alloc()
    pool.register_prompt([a], [1, 2, 3, 4])
    # a second sequence SHARING block `a` re-registers the same chain
    pool.share(a)
    b = pool.alloc()
    pool.register_prompt([a, b], [1, 2, 3, 4, 5, 6, 7, 8])
    shared, n, _, _ = pool.match_prefix([1, 2, 3, 4, 5, 6, 7, 8])
    assert shared == [a, b] and n == 8


def test_reset_clears_refcounts_and_prefix_index():
    pool = BlockPool(8, 4)
    a = pool.alloc()
    pool.register_prompt([a], [1, 2, 3, 4])
    pool.reset()
    assert pool.free_blocks() == 7 and pool.used_blocks() == 0
    shared, n, cow, _ = pool.match_prefix([1, 2, 3, 4])
    assert shared == [] and n == 0 and cow is None


def test_validate_block_size():
    validate_block_size(96, 16)
    validate_block_size(96, 128)  # padded_cache_len(96) == 128
    with pytest.raises(ValueError, match="power of two"):
        validate_block_size(96, 24)
    with pytest.raises(ValueError, match="power of two"):
        validate_block_size(96, 0)
    with pytest.raises(ValueError, match="tile the padded context"):
        validate_block_size(96, 256)
    assert blocks_per_seq(96, 16) == padded_cache_len(96) // 16


def test_randomized_refcount_invariants():
    """Thousands of random alloc/share/release/register cycles against a
    model of the refcount state: no double allocation, conservation of
    blocks, free/cached/live partitions stay disjoint."""
    rng = np.random.default_rng(0xB10C)
    pool = BlockPool(16, 4)
    live: dict[int, int] = {}  # bid -> model refcount
    registered: set[int] = set()
    next_tok = [1000]

    for step in range(4000):
        op = rng.integers(0, 4)
        if op == 0:  # alloc
            try:
                b = pool.alloc()
            except BlockPoolExhausted:
                assert sum(live.values()) > 0  # only when everything is live
                continue
            assert b != pool.NULL
            assert b not in live, "double allocation of a live block"
            live[b] = 1
            registered.discard(b)  # eviction/recycle forgets the index
        elif op == 1 and live:  # share a live block
            b = int(rng.choice(list(live)))
            pool.share(b)
            live[b] += 1
        elif op == 2 and live:  # release
            b = int(rng.choice(list(live)))
            pool.release(b)
            live[b] -= 1
            if not live[b]:
                del live[b]
        elif op == 3 and live:  # register a fresh 1-block prompt
            b = int(rng.choice(list(live)))
            if b not in registered and pool.refcount(b) == 1:
                toks = [next_tok[0] + i for i in range(4)]
                next_tok[0] += 4
                pool.register_prompt([b], toks)
                registered.add(b)
        # invariants
        for b, r in live.items():
            assert pool.refcount(b) == r
        assert pool.used_blocks() == len(live)
        assert pool.free_blocks() == pool.n_blocks - 1 - len(live)
        assert pool.shared_blocks() == sum(1 for r in live.values() if r > 1)
    # drain: everything releasable exactly its refcount times, no more
    for b, r in list(live.items()):
        for _ in range(r):
            pool.release(b)
        with pytest.raises(ValueError):
            pool.release(b)
    assert pool.used_blocks() == 0 and pool.free_blocks() == pool.n_blocks - 1


# ---------------------------------------------------------------------------
# 1b. Tiered allocator properties (host spill tier; still pure host, no jax —
#     a stub spill_fn stands in for the device copies)
# ---------------------------------------------------------------------------


def _tiered_pool(n_blocks=4, bs=4, n_host=8):
    pool = BlockPool(n_blocks, bs, n_host_blocks=n_host)
    pool.spill_fn = lambda devs, hosts: True
    return pool


def _fill_cached(pool, n, bs=4, base=100):
    """Register n single-block prompts and retire them -> n cached."""
    bids = []
    for i in range(n):
        b = pool.alloc()
        pool.register_prompt([b], [base + bs * i + j for j in range(bs)])
        pool.release(b)
        bids.append(b)
    return bids


def test_spill_moves_cold_blocks_to_host_instead_of_dropping():
    pool = _tiered_pool()
    bids = _fill_cached(pool, 3)
    fresh = pool.alloc()  # pressure: free list dry, cached spill to host
    assert fresh in bids  # the device ids recycled
    assert pool.host_used_blocks() == 3
    # ALL three prompts still match — under host ids now
    for i in range(3):
        sh, n, _, _ = pool.match_prefix([100 + 4 * i + j for j in range(4)])
        assert n == 4 and len(sh) == 1 and pool.is_host(sh[0]), i


def test_pagein_restores_exact_trie_chain():
    """Page-back restores the exact chain: a two-block chain spilled and
    paged back matches the same prompt block-for-block, and the partial
    CoW tail candidacy survives the round trip too."""
    pool = BlockPool(4, 4, n_host_blocks=8)
    pool.spill_fn = lambda devs, hosts: True
    a, b = pool.alloc(), pool.alloc()
    toks = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10]  # 2 full blocks + tail [9,10]
    c = pool.alloc()
    pool.register_prompt([a, b, c], toks)
    for x in (a, b, c):
        pool.release(x)
    taken = [pool.alloc() for _ in range(3)]  # spills the whole chain
    assert pool.host_used_blocks() == 3
    sh, n, cow, cow_r = pool.match_prefix([1, 2, 3, 4, 5, 6, 7, 8, 9, 99])
    assert n == 8 and len(sh) == 2 and all(pool.is_host(x) for x in sh)
    assert cow is not None and pool.is_host(cow) and cow_r == 1
    for x in taken:
        pool.release(x)
    pairs = pool.begin_pagein(sh + [cow])
    pool.commit_pagein(pairs)
    sh2, n2, cow2, cow_r2 = pool.match_prefix([1, 2, 3, 4, 5, 6, 7, 8, 9,
                                               99])
    assert n2 == 8 and cow_r2 == 1
    assert [pool.is_host(x) for x in sh2] == [False, False]
    assert not pool.is_host(cow2)
    assert sh2 == [dev for _, dev in pairs[:2]] and cow2 == pairs[2][1]
    # the caller owns rc 1 on each paged-in block (share()-equivalent)
    for _, dev in pairs:
        assert pool.refcount(dev) == 1


def test_spilled_then_paged_in_blocks_stay_refcount_correct():
    """A spilled block paged back in and shared by several sequences
    keeps exact refcounts through the whole cycle (the 'spilled shared
    blocks stay refcount-correct' invariant)."""
    pool = _tiered_pool()
    _fill_cached(pool, 3)
    fresh = pool.alloc()  # spill everything cached
    pool.release(fresh)
    sh, _, _, _ = pool.match_prefix([100, 101, 102, 103])
    pairs = pool.begin_pagein(sh)
    pool.commit_pagein(pairs)
    dev = pairs[0][1]
    assert pool.refcount(dev) == 1
    pool.share(dev)
    pool.share(dev)
    assert pool.refcount(dev) == 3 and pool.shared_blocks() == 1
    for _ in range(3):
        pool.release(dev)
    assert pool.refcount(dev) == 0
    # back in the device cached LRU, still matchable
    sh2, n2, _, _ = pool.match_prefix([100, 101, 102, 103])
    assert sh2 == [dev] and n2 == 4
    with pytest.raises(ValueError, match="double free"):
        pool.release(dev)


def test_host_blocks_never_sharable_or_releasable_directly():
    pool = _tiered_pool()
    _fill_cached(pool, 3)
    pool.alloc()  # spill
    sh, _, _, _ = pool.match_prefix([100, 101, 102, 103])
    hb = sh[0]
    assert pool.is_host(hb)
    with pytest.raises(ValueError, match="host-resident"):
        pool.share(hb)
    with pytest.raises(ValueError, match="host-resident"):
        pool.release(hb)


def test_spill_failure_degrades_to_drop_evict():
    """spill_fn returning False (or raising) falls back to the pre-tier
    contract: the LRU cached block is dropped and unregistered, nothing
    crashes, nothing leaks to the host tier."""
    for mode in ("false", "raise"):
        pool = BlockPool(4, 4, n_host_blocks=8)
        if mode == "false":
            pool.spill_fn = lambda d, h: False
        else:
            def _boom(d, h):
                raise RuntimeError("injected")
            pool.spill_fn = _boom
        bids = _fill_cached(pool, 3)
        fresh = pool.alloc()
        assert fresh == bids[0]  # LRU dropped, recycled
        assert pool.host_used_blocks() == 0
        sh, n, _, _ = pool.match_prefix([100, 101, 102, 103])
        assert n == 0  # dropped = unregistered, exactly the old behavior


def test_host_lru_eviction_drops_for_real_and_notifies():
    """When the host tier itself fills, ITS LRU drops for good (and the
    mirror hook is told which lanes died)."""
    pool = BlockPool(4, 4, n_host_blocks=2)
    pool.spill_fn = lambda d, h: True
    dropped = []
    pool.host_drop_fn = dropped.extend
    _fill_cached(pool, 3)
    pool.alloc()  # spill: only 2 host lanes -> 2 spill, 1 drop-evicted
    assert pool.host_used_blocks() == 2
    first = [b for b in list(pool._host_cached)]
    _fill_cached(pool, 2, base=500)
    pool.alloc()  # second spill wave: host full -> oldest host blocks drop
    assert dropped and all(pool.is_host(b) for b in dropped)
    assert dropped[0] == first[0]
    sh, n, _, _ = pool.match_prefix([100, 101, 102, 103])
    assert n == 0  # the host-dropped chain is gone for good


def test_spill_room_precheck_never_destroys_content_for_refused_spill():
    """Review regression: when the mirror's chunk budget has no room and
    the host LRU has nothing to drain, the spill must refuse WITHOUT
    evicting host content first — destroying idle sessions' KV for a
    spill that never happens is the exact anti-contract."""
    pool = BlockPool(4, 4, n_host_blocks=8)
    pool.spill_fn = lambda d, h: True
    pool.host_room_fn = lambda: False  # budget full, nothing drainable
    dropped = []
    pool.host_drop_fn = dropped.extend
    _fill_cached(pool, 3)
    fresh = pool.alloc()  # pressure: spill refused -> drop-evict
    assert fresh is not None
    assert pool.host_used_blocks() == 0 and not dropped
    sh, n, _, _ = pool.match_prefix([100, 101, 102, 103])
    assert n == 0  # device LRU dropped: the pre-tier contract, no worse


def test_spill_room_precheck_drains_host_lru_until_chunk_frees():
    """The budget-full-on-fragmented-chunks wedge: evicting the host LRU
    oldest-first frees a chunk (the drop hook fires per victim so the
    mirror can notice the moment its last lane dies), after which the
    spill PROCEEDS — the tier keeps cycling instead of refusing
    forever."""
    pool = BlockPool(4, 4, n_host_blocks=8)
    pool.spill_fn = lambda d, h: True
    chunk_lanes: set = set()  # the fake mirror's one resident chunk

    def drop(victims):
        chunk_lanes.difference_update(victims)
    pool.host_drop_fn = drop
    pool.host_room_fn = lambda: not chunk_lanes
    bids_a = _fill_cached(pool, 3, base=100)
    pool.alloc()  # first wave: room ok -> spills the 3 cached blocks
    assert pool.host_used_blocks() == 3
    chunk_lanes.update(b for b in pool._host_cached)  # chunk now "live"
    _fill_cached(pool, 2, base=500)
    pool.alloc()  # second wave: budget full -> drain host LRU, chunk
    #               frees, THEN the new cold blocks spill
    assert pool.host_used_blocks() == 2
    assert not any(pool.is_host(b) and b in pool._meta
                   for b in list(chunk_lanes))
    sh, n, _, _ = pool.match_prefix([500, 501, 502, 503])
    assert n == 4 and pool.is_host(sh[0])  # the NEW content made it out
    sh, n, _, _ = pool.match_prefix([100, 101, 102, 103])
    assert n == 0  # the stale chunk's content paid for it, oldest-first


def test_begin_pagein_exhaustion_rolls_back_atomically():
    pool = _tiered_pool()
    _fill_cached(pool, 3)
    pool.alloc()  # spill all three
    # occupy the remaining device blocks
    pool.alloc()
    pool.alloc()
    sh, _, _, _ = pool.match_prefix([100, 101, 102, 103])
    sh2, _, _, _ = pool.match_prefix([104, 105, 106, 107])
    with pytest.raises(BlockPoolExhausted):
        pool.begin_pagein(sh + sh2)
    # both host blocks still pinned-in-cache, still matchable
    for i in range(2):
        shx, n, _, _ = pool.match_prefix([100 + 4 * i + j for j in range(4)])
        assert n == 4 and pool.is_host(shx[0])
    assert pool.used_blocks() == 3  # no leaked device refcount


def test_randomized_tiered_invariants():
    """The randomized suite, tiered: random alloc/share/release/register
    cycles with a bookkeeping-only spill_fn and random page-ins, against
    a model of both tiers. Invariants: no logical block is ever device-
    AND host-live, refcounts exact, the free/cached/live/host partitions
    stay disjoint and conserve blocks, and every registered prompt keeps
    matching (from whichever tier) until genuinely dropped."""
    rng = np.random.default_rng(0x71E2)
    pool = BlockPool(10, 4, n_host_blocks=6)
    pool.spill_fn = lambda devs, hosts: True
    dropped_host: list[int] = []
    pool.host_drop_fn = dropped_host.extend
    live: dict[int, int] = {}
    next_tok = [1000]
    prompts: dict[int, list[int]] = {}  # bid -> registered tokens (model)

    for step in range(6000):
        op = rng.integers(0, 5)
        if op == 0:  # alloc (may spill)
            try:
                b = pool.alloc()
            except BlockPoolExhausted:
                assert sum(live.values()) > 0
                continue
            assert not pool.is_host(b)
            assert b not in live
            live[b] = 1
        elif op == 1 and live:  # share
            b = int(rng.choice(list(live)))
            pool.share(b)
            live[b] += 1
        elif op == 2 and live:  # release
            b = int(rng.choice(list(live)))
            pool.release(b)
            live[b] -= 1
            if not live[b]:
                del live[b]
        elif op == 3 and live:  # register a fresh 1-block prompt
            b = int(rng.choice(list(live)))
            if b not in pool._meta and pool.refcount(b) == 1:
                toks = [next_tok[0] + i for i in range(4)]
                next_tok[0] += 4
                pool.register_prompt([b], toks)
                prompts[b] = toks
        elif op == 4:  # page a random host-resident block back in
            host_live = [b for b in prompts if pool.is_host(b)]
            if not host_live:
                continue
            hb = int(rng.choice(host_live))
            toks = prompts[hb]
            try:
                pairs = pool.begin_pagein([hb])
            except BlockPoolExhausted:
                continue
            pool.commit_pagein(pairs)
            dev = pairs[0][1]
            prompts[dev] = prompts.pop(hb)
            live[dev] = 1
            sh, n, _, _ = pool.match_prefix(toks)
            assert sh == [dev] and n == 4

        # model sync (white-box): a spill REBINDS a registration to a
        # host id (same tokens, new key) and a drop removes it — rebuild
        # the id->tokens view from the pool's own meta so the match
        # invariant below checks every surviving registration, wherever
        # it lives now
        prompts = {bid: list(meta[2])
                   for bid, meta in pool._meta.items() if meta[0] == "full"}
        # invariants ------------------------------------------------------
        for b, r in live.items():
            assert pool.refcount(b) == r and not pool.is_host(b)
        assert pool.used_blocks() == len(live)
        n_dev_cached = len(pool._cached)
        assert pool.free_blocks() == len(pool._free) + n_dev_cached
        assert pool.used_blocks() + pool.free_blocks() == pool.n_blocks - 1
        # host partition: used lanes = cached host entries; disjoint ids
        assert pool.host_used_blocks() == len(pool._host_cached)
        assert all(pool.is_host(b) for b in pool._host_cached)
        dev_ids = set(pool._free) | set(pool._cached) | set(live)
        assert not (dev_ids & set(pool._host_cached))
        # NO logical block in both tiers: every registered bid is either
        # a device id or a host id, and each meta key appears once
        for bid in pool._meta:
            assert (bid in pool._host_cached) == pool.is_host(bid)
        # every surviving registered prompt still matches from its tier
        for bid, toks in prompts.items():
            sh, n, _, _ = pool.match_prefix(toks)
            assert n == 4 and sh == [bid], (bid, sh, n)

    # drain
    for b, r in list(live.items()):
        for _ in range(r):
            pool.release(b)
    assert pool.used_blocks() == 0


# ---------------------------------------------------------------------------
# 2. Gather parity: paged_forward ≡ dense forward through a scrambled table
# ---------------------------------------------------------------------------


def test_paged_forward_matches_dense_forward_bitwise():
    """The block-table indirection is value-invisible: a prefill-width
    ``paged_forward`` through a deliberately out-of-order block table
    produces bit-identical logits to the dense ``forward``, and the rows it
    scatters into the pool equal the dense cache's rows."""
    import jax.numpy as jnp

    from dllama_tpu.formats.mfile import ArchType, RopeType
    from dllama_tpu.models import ModelConfig
    from dllama_tpu.models.llama import forward, init_random_params, paged_forward
    from dllama_tpu.runtime.kvcache import KVCache

    cfg = ModelConfig(arch=ArchType.LLAMA, dim=32, hidden_dim=64, n_layers=2,
                      n_heads=4, n_kv_heads=2, head_dim=8, vocab_size=64,
                      seq_len=64, norm_epsilon=1e-5, rope_theta=10000.0,
                      rope_type=RopeType.LLAMA)
    params = init_random_params(cfg, seed=7)
    T = 24
    tokens = jnp.asarray(
        np.random.default_rng(5).integers(0, cfg.vocab_size, (1, T)),
        jnp.int32)

    logits_d, kv = forward(params, cfg, tokens, jnp.int32(0),
                           KVCache.create(cfg))

    bs = 16
    M = blocks_per_seq(cfg.seq_len, bs)
    # scrambled physical placement: logical block j -> physical block
    # (descending from the top of the pool), so any row-order dependence
    # in the gather/scatter would break parity
    n_blocks = 2 * M + 1
    table = np.zeros((1, M), dtype=np.int32)
    table[0, :] = np.arange(n_blocks - 1, n_blocks - 1 - M, -1)
    pkv = PagedKVCache.create(cfg, n_blocks, bs)
    logits_p, pkv = paged_forward(params, cfg, tokens,
                                  jnp.asarray([0], jnp.int32), pkv,
                                  jnp.asarray(table))
    np.testing.assert_array_equal(np.asarray(logits_d), np.asarray(logits_p))

    # the scattered rows, gathered back through the table, equal the dense
    # cache rows the slot-pool forward produced
    k_p = np.asarray(pkv.k)[:, table[0]]       # [L, M, n_kv, bs, hd]
    k_p = np.moveaxis(k_p, 2, 1).reshape(cfg.n_layers, cfg.n_kv_heads,
                                         M * bs, cfg.head_dim)
    k_d = np.asarray(kv.k)[:, 0]               # [L, n_kv, S, hd]
    np.testing.assert_array_equal(k_p[:, :, :T], k_d[:, :, :T])


# ---------------------------------------------------------------------------
# 3. Serving acceptance (PagedGenerator / BatchScheduler)
# ---------------------------------------------------------------------------

PATHS = {}


@pytest.fixture(scope="module")
def paged_engine(tmp_path_factory):
    d = tmp_path_factory.mktemp("kvblocks")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(41)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96),
                     rng)
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    PATHS["m"], PATHS["t"] = str(mpath), str(tpath)
    return InferenceEngine(str(mpath), str(tpath), tp=1, kv_block_size=16)


def solo(temperature=0.0, seed=7, **kw):
    """Fresh single-sequence engine on the same files — the oracle."""
    return InferenceEngine(PATHS["m"], PATHS["t"], tp=1,
                           temperature=temperature, seed=seed, **kw)


def _enc(engine, text):
    return engine.tokenizer.encode(text, is_start=True)


def test_engine_validates_block_size_and_combos(tmp_path_factory):
    d = tmp_path_factory.mktemp("kvblocks_val")
    mpath, tpath = d / "m.m", d / "t.t"
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96),
                     np.random.default_rng(1))
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    with pytest.raises(ValueError, match="power of two"):
        InferenceEngine(str(mpath), str(tpath), tp=1, kv_block_size=24)
    with pytest.raises(ValueError, match="tile the padded context"):
        InferenceEngine(str(mpath), str(tpath), tp=1, kv_block_size=512)
    # spec composes with paged KV now (ISSUE 14) — only a verify width
    # past the decode regime refuses (spec_lookup + 1 > 16)
    eng = InferenceEngine(str(mpath), str(tpath), tp=1, kv_block_size=16,
                          spec_lookup=3)
    eng.close()
    with pytest.raises(ValueError, match="--spec-lookup > 15"):
        InferenceEngine(str(mpath), str(tpath), tp=1, kv_block_size=16,
                        spec_lookup=16)
    with pytest.raises(ValueError, match="--decode-chunk"):
        InferenceEngine(str(mpath), str(tpath), tp=1, kv_block_size=16,
                        decode_chunk=4)
    with pytest.raises(ValueError, match="--dp"):
        InferenceEngine(str(mpath), str(tpath), tp=1, dp=2, kv_block_size=16)


def test_continuous_stream_exceeds_slot_capacity_token_exact(paged_engine):
    """THE tentpole acceptance: a stream of 6 mixed requests through 2
    slots completes under continuous batching — sequences admit and retire
    mid-batch — and every transcript equals a fresh solo run."""
    prompts = ["hello world", "hello there", "abc",
               "hello world how are you", "xyzzy", "hello hello hello"]
    specs = [dict(temperature=0.0, seed=1), dict(temperature=0.8, seed=2),
             dict(temperature=0.0, seed=3), dict(temperature=1.2, seed=4),
             dict(temperature=0.0, seed=5), dict(temperature=0.6, seed=6)]
    want = []
    for p, s in zip(prompts, specs):
        e = solo(**s)
        want.append(e.generate(p, 8, stop_on_eos=False).tokens)
        e.close()

    admissions = tm.registry().counter(tm.ADMISSIONS)
    retires = tm.registry().counter(tm.RETIRES)
    a0, r0 = admissions.total(), retires.total()
    sched = BatchScheduler(paged_engine, n_slots=2)
    assert isinstance(sched.gen, PagedGenerator)
    try:
        reqs = [sched.submit(_enc(paged_engine, p), 8, stop_on_eos=False,
                             temperature=s["temperature"], seed=s["seed"])
                for p, s in zip(prompts, specs)]
        for r in reqs:
            assert r.done.wait(timeout=300)
            assert r.error is None, r.error
        for r, w, p in zip(reqs, want, prompts):
            assert r.tokens == w, p
    finally:
        sched.close()
    assert admissions.total() - a0 == len(prompts)
    assert retires.total() - r0 >= len(prompts)


def test_block_sharing_live_and_cow_write_isolation(paged_engine):
    """Block-level prefix sharing: a second live sequence with a >= 1-block
    common prefix SHARES physical blocks (``dllama_kv_blocks_shared`` > 0
    while both run; reuse counted at block granularity), the shared bytes
    are never rewritten, and both transcripts stay solo-exact."""
    # 26 distinct chars -> BOS + 26 ids; rest = 26 >= one full 16-block
    base = "abcdefghijklmnopqrstuvwxy "
    e1 = solo()
    want_a = e1.generate(base + "111", 6, stop_on_eos=False).tokens
    e1.close()
    e2 = solo()
    want_b = e2.generate(base + "222", 6, stop_on_eos=False).tokens
    e2.close()

    gen = PagedGenerator(paged_engine, n_slots=2)
    reuse = tm.registry().counter(tm.PREFIX_REUSE_TOKENS)
    shared_gauge = tm.registry().gauge(tm.KV_BLOCKS_SHARED)

    r_a = Request(rid=0, prompt_ids=_enc(paged_engine, base + "111"),
                  max_tokens=6, stop_on_eos=False)
    gen.admit(r_a, 0)
    gen.step()  # r_a live and decoding; its prompt blocks are registered

    ids_b = _enc(paged_engine, base + "222")
    n_common = 0
    for x, y in zip(ids_b[:-1], r_a.prompt_ids[:-1]):
        if x != y:
            break
        n_common += 1
    assert n_common >= gen.block_size, "workload must share a full block"

    c0 = reuse.total()
    shared_before = gen.pool.shared_blocks()
    r_b = Request(rid=1, prompt_ids=ids_b, max_tokens=6, stop_on_eos=False)
    gen.admit(r_b, 1)

    # both sequences live: physical sharing is visible in pool + telemetry
    assert gen.pool.shared_blocks() > shared_before
    assert shared_gauge.value() > 0
    # block-level reuse >= the dense pool's longest-prefix token accounting
    # (full shared blocks + the copy-on-write tail cover the whole prefix)
    assert reuse.total() - c0 >= n_common

    # copy-on-write safety: the shared block's device bytes never change
    shared_bids = [b for b in gen._seq_bids[1] if gen.pool.refcount(b) > 1]
    assert shared_bids
    before = np.asarray(gen.pkv.k[:, shared_bids[0]]).copy()
    while gen.n_active:
        gen.step()
    np.testing.assert_array_equal(
        before, np.asarray(gen.pkv.k[:, shared_bids[0]]))

    assert r_a.tokens == want_a
    assert r_b.tokens == want_b


def test_paged_prefill_interleaves_with_decode(tmp_path_factory):
    """Chunked prefill interleaves with decode on the paged pool: an active
    slot keeps emitting between a newcomer's prefill chunks, and both
    match their solo runs."""
    d = tmp_path_factory.mktemp("kvblocks_inc")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(41)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96),
                     rng)
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    eng = InferenceEngine(str(mpath), str(tpath), tp=1, n_batches=4,
                          kv_block_size=16)
    long_ids = [int(x) for x in np.random.default_rng(3).integers(1, 200, 40)]

    solo_a = InferenceEngine(str(mpath), str(tpath), tp=1, n_batches=4)
    want_a = solo_a.generate("hello world", 16, stop_on_eos=False).tokens
    solo_a.close()
    solo_b = InferenceEngine(str(mpath), str(tpath), tp=1, n_batches=4)
    want_b = solo_b.generate(long_ids, 4, stop_on_eos=False).tokens
    solo_b.close()

    gen = PagedGenerator(eng, n_slots=2)
    r_a = Request(rid=0, prompt_ids=_enc(eng, "hello world"),
                  max_tokens=16, stop_on_eos=False)
    gen.admit(r_a, 0)
    gen.step()
    a_before = len(r_a.tokens)

    r_b = Request(rid=1, prompt_ids=long_ids, max_tokens=4,
                  stop_on_eos=False)
    adm = gen.begin_admit(r_b, 1)
    interleaved = 0
    while not gen.continue_admit(adm):
        gen.step()  # active slot decodes between the newcomer's chunks
        interleaved += 1
    assert interleaved >= 5  # 39 prompt tokens / 4-token chunks
    assert len(r_a.tokens) > a_before
    while gen.n_active:
        gen.step()
    assert r_a.tokens == want_a
    assert r_b.tokens == want_b


def test_shared_prefix_workload_is_ledger_quiet_post_steady(paged_engine):
    """Zero post-steady compiles across a CoW + sharing + admit/retire
    wave: the paged program family is jitted once per pool geometry, so
    block-table contents, occupancy, and sharing must never retrace."""
    sched = BatchScheduler(paged_engine, n_slots=2)
    scope = paged_engine.introspection_scope
    try:
        # steady-state warmup: the full program family (prefill buckets,
        # paged step, CoW copy) compiles here
        warm = [sched.submit(_enc(paged_engine, p), 4, stop_on_eos=False)
                for p in ["abcdefghijklmnopqrstuvwxy 0",
                          "abcdefghijklmnopqrstuvwxy 1", "hello"]]
        for r in warm:
            assert r.done.wait(timeout=300) and r.error is None
        c0 = introspection.ledger().compile_count(scope)
        wave = [sched.submit(_enc(paged_engine, p), 4, stop_on_eos=False)
                for p in ["abcdefghijklmnopqrstuvwxy 2",
                          "abcdefghijklmnopqrstuvwxy 3",
                          "abcdefghijklmnopqrstuvwxy 4", "hello there"]]
        for r in wave:
            assert r.done.wait(timeout=300) and r.error is None
        assert introspection.ledger().compile_count(scope) == c0, \
            "post-steady recompile on the paged path"
    finally:
        sched.close()


def test_paged_under_tp_matches_solo(tmp_path_factory):
    """The paged pool composes with tensor parallelism: kv-heads shard over
    tp (parallel/sharding.paged_kv_sharding), transcripts equal solo tp
    runs."""
    d = tmp_path_factory.mktemp("kvblocks_tp")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(41)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96),
                     rng)
    tfile.write_tfile(tpath, byte_vocab_tokenizer())

    s1 = InferenceEngine(str(mpath), str(tpath), tp=2)
    want_a = s1.generate("hello world", 8, stop_on_eos=False).tokens
    s1.close()
    s2 = InferenceEngine(str(mpath), str(tpath), tp=2, temperature=0.8,
                         seed=6)
    want_b = s2.generate("hello", 8, stop_on_eos=False).tokens
    s2.close()

    eng = InferenceEngine(str(mpath), str(tpath), tp=2, kv_block_size=16)
    gen = PagedGenerator(eng, n_slots=2)
    r_a = Request(rid=0, prompt_ids=_enc(eng, "hello world"), max_tokens=8,
                  stop_on_eos=False)
    r_b = Request(rid=1, prompt_ids=_enc(eng, "hello"), max_tokens=8,
                  stop_on_eos=False, temperature=0.8, seed=6)
    gen.admit(r_a, 0)
    gen.admit(r_b, 1)
    while gen.n_active:
        gen.step()
    assert r_a.tokens == want_a
    assert r_b.tokens == want_b


def test_mid_decode_block_growth_is_lazy(paged_engine):
    """A sequence only holds the blocks its live context spans: decoding
    across a block boundary allocates exactly one more block, at the
    boundary — the continuous-batching memory win."""
    gen = PagedGenerator(paged_engine, n_slots=2)
    # prompt of 10 ids -> rest 9 -> 1 block; decode grows past row 16
    r = Request(rid=0, prompt_ids=_enc(paged_engine, "hello w"),
                max_tokens=24, stop_on_eos=False)
    gen.admit(r, 0)
    assert len(gen._seq_bids[0]) == 1
    grew_at = None
    while gen.n_active:
        pos_before = int(gen.pos[0])
        blocks_before = len(gen._seq_bids[0])
        gen.step()
        if gen.n_active and len(gen._seq_bids[0]) > blocks_before:
            assert grew_at is None, "grew more than once before row 32"
            grew_at = pos_before
    assert grew_at is not None and grew_at % gen.block_size == 0


def test_fit_block_pool_tests_the_min_blocks_floor(monkeypatch):
    """The degrade loop must test min_blocks itself even when the step
    sequence would skip past it (want - min not divisible by the step):
    a budget that fits exactly the floor returns the floor, not 0."""
    from dllama_tpu.formats.mfile import ArchType, RopeType
    from dllama_tpu.models import ModelConfig
    from dllama_tpu.runtime.hbm import (estimate_block_pool_bytes,
                                        estimate_device_bytes,
                                        fit_block_pool)

    cfg = ModelConfig(arch=ArchType.LLAMA, dim=64, hidden_dim=96, n_layers=2,
                      n_heads=4, n_kv_heads=2, head_dim=16, vocab_size=128,
                      seq_len=64, norm_epsilon=1e-5, rope_theta=10000.0,
                      rope_type=RopeType.LLAMA)
    want, mn, bs = 53, 14, 16  # step = (53-14)//16 = 2: 53, 51, ... 15, SKIPS 14
    base = estimate_device_bytes(cfg, weight_repr="q40", kv_dtype_bytes=4,
                                 batch=1, n_shards=1)["need_per_device"]
    floor_pool = estimate_block_pool_bytes(cfg, mn, bs, 4)
    above_pool = estimate_block_pool_bytes(cfg, mn + 1, bs, 4)
    # a limit between the floor pool's need and one-block-more's need
    monkeypatch.setenv("DLLAMA_HBM_BYTES",
                       str(base + (int(floor_pool * 1.15)
                                   + int(above_pool * 1.15)) // 2))
    n_fit, est = fit_block_pool(cfg, want, block_size=bs, min_blocks=mn,
                                weight_repr="q40", kv_dtype_bytes=4)
    assert n_fit == mn, (n_fit, est)
    # and a limit below even the floor still refuses with 0
    monkeypatch.setenv("DLLAMA_HBM_BYTES", str(base))
    n_fit, _ = fit_block_pool(cfg, want, block_size=bs, min_blocks=mn,
                              weight_repr="q40", kv_dtype_bytes=4)
    assert n_fit == 0


def test_fully_shared_prompt_skips_prefill_and_stays_token_exact(
        paged_engine):
    """Resubmitting an identical prompt (the repeated-system-prompt hot
    path) reuses EVERY prefill position — no prefill dispatch, no column
    gather/scatter (adm.col is None) — and still decodes token-exactly."""
    gen = PagedGenerator(paged_engine, n_slots=2)
    ids = _enc(paged_engine, "abcdefghijklmnopqrstuvwxy!")
    r_a = Request(rid=0, prompt_ids=ids, max_tokens=6, stop_on_eos=False)
    gen.admit(r_a, 0)
    while gen.n_active:
        gen.step()

    reuse = tm.registry().counter(tm.PREFIX_REUSE_TOKENS)
    c0 = reuse.total()
    r_b = Request(rid=1, prompt_ids=list(ids), max_tokens=6,
                  stop_on_eos=False)
    adm = gen.begin_admit(r_b, 1)
    assert adm.col is None  # zero device work beyond the one CoW copy
    assert adm.pos == len(ids) - 1  # nothing left to prefill
    assert reuse.total() - c0 == len(ids) - 1
    assert gen.continue_admit(adm)
    while gen.n_active:
        gen.step()
    assert r_b.tokens == r_a.tokens


def test_mid_admission_ride_along_never_writes_shared_blocks(paged_engine):
    """The slot table must stay all-null until the admission COMMITS: a
    slot mid-admission still rides along decode dispatches with whatever
    stale ``pos`` its previous occupant left, and that ride-along write
    must land in the null block — publishing shared bids early would let
    it corrupt prefix KV other live sequences attend to."""
    base = "abcdefghijklmnopqrstuvwxy "  # rest >= one full 16-block
    e1 = solo()
    want_a = e1.generate(base + "111", 8, stop_on_eos=False).tokens
    e1.close()
    e2 = solo()
    want_b = e2.generate(base + "222", 6, stop_on_eos=False).tokens
    e2.close()

    gen = PagedGenerator(paged_engine, n_slots=2)
    # previous occupant of slot 1: retires with stale pos INSIDE block 0,
    # so a published shared bids[0] would be the ride-along write target
    r0 = Request(rid=0, prompt_ids=_enc(paged_engine, "hi"),
                 max_tokens=12, stop_on_eos=False)
    gen.admit(r0, 1)
    while gen.n_active:
        gen.step()
    stale = int(gen.pos[1])
    assert 0 < stale < gen.block_size

    r_a = Request(rid=1, prompt_ids=_enc(paged_engine, base + "111"),
                  max_tokens=8, stop_on_eos=False)
    gen.admit(r_a, 0)
    gen.step()  # r_a live; its prompt blocks registered for sharing

    r_b = Request(rid=2, prompt_ids=_enc(paged_engine, base + "222"),
                  max_tokens=6, stop_on_eos=False)
    adm = gen.begin_admit(r_b, 1)
    shared_bids = [b for b in gen._seq_bids[1] if gen.pool.refcount(b) > 1]
    assert shared_bids  # the base prefix really is physically shared
    assert (gen.tables[1] == gen.pool.NULL).all()  # not published yet
    before = np.asarray(gen.pkv.k[:, shared_bids[0]]).copy()
    gen.step()  # slot 1 rides along with its stale pos mid-admission
    np.testing.assert_array_equal(
        before, np.asarray(gen.pkv.k[:, shared_bids[0]]))
    while not gen.continue_admit(adm):
        gen.step()
    while gen.n_active:
        gen.step()
    assert r_a.tokens == want_a
    assert r_b.tokens == want_b


def test_admission_reserves_decode_growth_no_organic_exhaustion(
        paged_engine):
    """Block-priced admission holds across the BATCH: every live
    sequence's worst-case decode growth stays reserved, so a second
    request that would double-spend the same free blocks queues instead
    of admitting — and nobody ever hits organic mid-decode exhaustion
    (503) on a pool the admission gate said was affordable."""
    from dllama_tpu.runtime.kvblocks import BlockPool
    from dllama_tpu.runtime.serving import BatchScheduler

    exhaustion = tm.registry().counter(tm.KV_BLOCK_EXHAUSTION)
    e0 = exhaustion.total()
    sched = BatchScheduler(paged_engine, n_slots=2, _start_thread=False)
    try:
        # shrink the allocatable pool to 9 blocks (< two 6-block worst
        # cases); bids 1..9 stay valid indices into the larger device pool
        sched.gen.pool = BlockPool(10, sched.gen.block_size)
        ids = _enc(paged_engine, "hello wor")  # rest 9 -> 1 block held
        # worst case: 9 + 85 = 94 rows -> 6 blocks per request
        r1 = sched.submit(ids, 85, stop_on_eos=False)
        r2 = sched.submit(list(ids), 85, stop_on_eos=False)
        max_active = 0
        for _ in range(500):
            sched._tick()
            max_active = max(max_active, sched.gen.n_active)
            if r1.done.is_set() and r2.done.is_set():
                break
        assert r1.done.is_set() and r2.done.is_set()
        assert r1.error is None and r2.error is None
        assert len(r1.tokens) == 85 and len(r2.tokens) == 85
        assert max_active == 1  # the second request QUEUED, not gambled
        assert exhaustion.total() == e0  # and nothing ever ran dry
    finally:
        sched.close()


def test_begin_admit_rolls_back_blocks_on_any_failure(paged_engine):
    """A device error mid-admission (not just exhaustion) must release
    every block taken — a leaked refcount would shrink the allocatable
    pool forever on a healthy server."""
    gen = PagedGenerator(paged_engine, n_slots=2)
    free0 = gen.pool.free_blocks()
    orig = gen._take

    def boom(*a):
        raise RuntimeError("device boom")

    gen._take = boom
    r = Request(rid=0, prompt_ids=_enc(paged_engine, "hello world"),
                max_tokens=4, stop_on_eos=False)
    with pytest.raises(RuntimeError, match="device boom"):
        gen.begin_admit(r, 0)
    assert gen.pool.free_blocks() == free0  # atomic rollback
    gen._take = orig
    gen.admit(r, 0)  # the pool is intact: the same request admits fine
    while gen.n_active:
        gen.step()
    assert len(r.tokens) == 4


def test_cancelled_mid_admission_releases_blocks(paged_engine):
    """A client cancel between prefill chunks aborts the admission AND
    returns its blocks to the pool (dense slots had nothing to release;
    paged refcounts would leak without abort_admit)."""
    from dllama_tpu.runtime.serving import BatchScheduler

    sched = BatchScheduler(paged_engine, n_slots=2, _start_thread=False)
    try:
        free0 = sched.gen.pool.free_blocks()
        # rest of 79 ids needs 2 chunks (64-bucket + tail) -> the cancel
        # window between ticks exists
        ids = [int(x) for x in np.random.default_rng(9).integers(1, 200, 80)]
        req = sched.submit(ids, 8, stop_on_eos=False)
        sched._tick()
        assert sched._admissions  # still prefilling
        assert sched.gen.pool.free_blocks() < free0
        req.cancel.set()
        sched._tick()
        assert req.done.is_set()
        assert not sched._admissions
        assert sched.gen.pool.free_blocks() == free0  # all blocks back
    finally:
        sched.close()


def test_cancel_behind_prefill_budget_releases_immediately(paged_engine):
    """The cancel sweep runs over EVERY in-flight admission before the
    budgeted prefill loop: a cancelled client queued behind the budget
    cutoff must not keep blocks/reservation/slot for the remaining ticks
    of the admissions ahead of it."""
    from dllama_tpu.runtime.serving import BatchScheduler

    sched = BatchScheduler(paged_engine, n_slots=2, _start_thread=False)
    sched.prefill_budget = 1  # only the FIRST admission advances per tick
    try:
        free0 = sched.gen.pool.free_blocks()
        rng = np.random.default_rng(11)
        a = sched.submit([int(x) for x in rng.integers(1, 200, 80)], 4,
                         stop_on_eos=False)
        b = sched.submit([int(x) for x in rng.integers(1, 200, 80)], 4,
                         stop_on_eos=False)
        sched._tick()  # both begin; only A's prefill advances
        assert len(sched._admissions) == 2
        held = free0 - sched.gen.pool.free_blocks()
        b.cancel.set()
        sched._tick()  # cancel sweep precedes the budget break
        assert b.done.is_set()
        assert all(adm.req is not b for adm in sched._admissions)
        assert free0 - sched.gen.pool.free_blocks() < held  # B's came back
        while not a.done.is_set():
            sched._tick()
        assert a.error is None and len(a.tokens) == 4
    finally:
        sched.close()
