"""End-to-end determinism — the TPU analogue of the reference's macbeth.sh.

The reference's strongest end-to-end test generates 2048 steps with a fixed
seed on a 4-node localhost cluster and diffs the transcript against a golden
(examples/macbeth.sh; noted CPU-dependent). Machine-embedded goldens are
brittle across XLA versions, so these tests assert the two properties that
test actually encodes:

* same seed → byte-identical transcript (run-to-run determinism), and
* the node-count invariance the BASELINE north star requires — the same
  tokens whether the model runs unsharded, tensor-parallel, or
  sequence-parallel on the virtual 8-device mesh.

Perplexity regression rides along the same fixtures (the reference has a
perplexity CLI mode but no CI regression for it, SURVEY.md §4 gaps).
"""

import numpy as np
import pytest

from dllama_tpu.formats import quants, tfile
from dllama_tpu.runtime.engine import InferenceEngine

from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model


@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("det")
    tok = byte_vocab_tokenizer()
    params = tiny_header_params(vocab_size=tok.vocab_size, seq_len=64,
                                weight_type=quants.Q40)
    write_tiny_model(d / "m.m", params, np.random.default_rng(11))
    tfile.write_tfile(d / "t.t", tok)
    return str(d / "m.m"), str(d / "t.t")


def _generate(model_files, *, seed=1234, temperature=0.9, steps=48, **engine_kw):
    m, t = model_files
    eng = InferenceEngine(m, t, temperature=temperature, seed=seed, **engine_kw)
    try:
        out = eng.generate("the quick brown fox", steps)
    finally:
        eng.close()
    return out.tokens


def test_same_seed_same_transcript(model_files):
    a = _generate(model_files)
    b = _generate(model_files)
    assert a == b and len(a) > 8


def test_different_seed_differs(model_files):
    a = _generate(model_files, seed=1)
    b = _generate(model_files, seed=2)
    assert a != b


@pytest.mark.parametrize("kw", [{"tp": 2}, {"tp": 4}, {"sp": 2}, {"tp": 2, "sp": 2}])
def test_sharded_generation_token_identical(model_files, kw):
    """The north-star property: output identical across parallelism plans
    (reference: per-token identity across 1/2/4/8 nodes, SURVEY.md §4/§6)."""
    ref = _generate(model_files, tp=1)
    got = _generate(model_files, **kw)
    assert got == ref


def test_perplexity_stable_and_plan_invariant(model_files):
    m, t = model_files
    text = "hello world " * 20
    values = []
    for kw in ({}, {"tp": 2}):
        eng = InferenceEngine(m, t, **kw)
        try:
            ids = eng.tokenizer.encode(text)[: eng.cfg.seq_len]
            values.append(eng.perplexity(ids))
        finally:
            eng.close()
    assert np.isfinite(values).all()
    np.testing.assert_allclose(values[0], values[1], rtol=1e-4)
