"""Tensor-parallel Pallas flash attention (shard_map path) vs the oracle.

Runs the kernel in interpret mode on the virtual CPU mesh — the multi-chip
analogue of test_flash_attention's single-device parity checks.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dllama_tpu.formats import mfile
from dllama_tpu.models import ModelConfig, forward, init_random_params
from dllama_tpu.ops.attention import attention
from dllama_tpu.ops.flash_attention import flash_attention_sharded
from dllama_tpu.parallel import use_plan
from dllama_tpu.parallel.api import make_mesh, make_tp_mesh
from dllama_tpu.parallel.sharding import kv_cache_sharding, shard_params
from dllama_tpu.runtime import KVCache


@pytest.mark.parametrize("mesh_axes,B,T", [
    ({"tp": 4}, 1, 1),          # decode
    ({"tp": 2}, 1, 8),          # prefill chunk
    ({"dp": 2, "tp": 4}, 2, 4),  # composed with dp
])
def test_sharded_flash_matches_oracle(mesh_axes, B, T):
    H, n_kv, S, hd = 8, 4, 128, 16
    start_pos = 16
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((B, T, H, hd)), dtype=jnp.float32)
    k_cache = jnp.asarray(rng.standard_normal((B, n_kv, S, hd)), dtype=jnp.float32)
    v_cache = jnp.asarray(rng.standard_normal((B, n_kv, S, hd)), dtype=jnp.float32)
    positions = start_pos + jnp.broadcast_to(
        jnp.arange(T, dtype=jnp.int32)[None], (B, T))

    want = attention(q, k_cache, v_cache, positions, hd)

    plan = make_mesh(mesh_axes)
    got = flash_attention_sharded(plan, q, k_cache, v_cache,
                                  jnp.int32(start_pos), hd, interpret=True)
    assert got is not None
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_sharded_flash_declines_unsupported():
    plan = make_mesh({"tp": 8})
    # irregular q-head/kv-group split: n_kv=3 with tp=8 (neither divides)
    q = jnp.zeros((1, 1, 24, 16))
    kv = jnp.zeros((1, 3, 128, 16))
    assert flash_attention_sharded(plan, q, kv, kv, jnp.int32(0), 16) is None
    plan2 = make_mesh({"sp": 2, "tp": 2})  # sp path owns attention
    q2 = jnp.zeros((1, 1, 8, 16))
    kv2 = jnp.zeros((1, 4, 128, 16))
    assert flash_attention_sharded(plan2, q2, kv2, kv2, jnp.int32(0), 16) is None


@pytest.mark.parametrize("B,T,tp,n_kv", [
    (1, 1, 8, 4),   # decode, 2 devices per kv group
    (1, 4, 4, 2),   # prefill chunk, replication groups
    (2, 1, 8, 2),   # 4 devices per group
])
def test_sharded_flash_kv_replication_groups(B, T, tp, n_kv):
    """tp > n_kv_heads (the v5e-16 70B shape): the cache stays replicated
    and each device slices its q-head shard's single kv head — parity with
    the oracle (VERDICT r4 next #6)."""
    H, S, hd = 16, 128, 16
    start_pos = 16
    rng = np.random.default_rng(4)
    q = jnp.asarray(rng.standard_normal((B, T, H, hd)), dtype=jnp.float32)
    k_cache = jnp.asarray(rng.standard_normal((B, n_kv, S, hd)), dtype=jnp.float32)
    v_cache = jnp.asarray(rng.standard_normal((B, n_kv, S, hd)), dtype=jnp.float32)
    positions = start_pos + jnp.broadcast_to(
        jnp.arange(T, dtype=jnp.int32)[None], (B, T))
    want = attention(q, k_cache, v_cache, positions, hd)
    plan = make_mesh({"tp": tp})
    got = flash_attention_sharded(plan, q, k_cache, v_cache,
                                  jnp.int32(start_pos), hd, interpret=True)
    assert got is not None
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_forward_tp_with_forced_flash_matches_unsharded():
    """Full model under tp=4 with attn_impl='flash' (interpret kernel inside
    shard_map) must match the unsharded oracle run."""
    cfg = ModelConfig(
        arch=mfile.ArchType.LLAMA, dim=64, hidden_dim=96, n_layers=2,
        n_heads=8, n_kv_heads=4, head_dim=8, vocab_size=128, seq_len=128,
        norm_epsilon=1e-5, rope_theta=10000.0, rope_type=mfile.RopeType.LLAMA,
        attn_impl="flash")
    params = init_random_params(cfg, seed=5)
    tokens = jnp.asarray([[3, 1, 4, 1, 5]], dtype=jnp.int32)

    from dataclasses import replace
    cfg_oracle = replace(cfg, attn_impl="xla")
    ref, _ = jax.jit(forward, static_argnums=1)(
        params, cfg_oracle, tokens, jnp.int32(0), KVCache.create(cfg_oracle))

    plan = make_tp_mesh(4)
    sharded = shard_params(plan, params)
    kv0 = KVCache.create(cfg)
    kv = jax.device_put(kv0, kv_cache_sharding(plan, kv0))
    with use_plan(plan):
        got, _ = jax.jit(forward, static_argnums=1)(
            sharded, cfg, tokens, jnp.int32(0), kv)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_forced_flash_under_unsupported_plan_raises():
    """attn_impl='flash' under a plan the sharded kernel can't take (an
    IRREGULAR q-head/kv-group split: neither n_kv % tp nor tp % n_kv is 0,
    so a device's q heads straddle kv groups) must fail loudly, not
    silently run the oracle (advisor round-1 finding)."""
    cfg = ModelConfig(
        arch=mfile.ArchType.LLAMA, dim=64, hidden_dim=96, n_layers=2,
        n_heads=24, n_kv_heads=3, head_dim=8, vocab_size=128, seq_len=128,
        norm_epsilon=1e-5, rope_theta=10000.0, rope_type=mfile.RopeType.LLAMA,
        attn_impl="flash")
    params = init_random_params(cfg, seed=1)
    tokens = jnp.asarray([[3, 1]], dtype=jnp.int32)
    plan = make_tp_mesh(8)  # n_kv=3, tp=8: neither divides — kernel declines
    sharded = shard_params(plan, params)
    kv0 = KVCache.create(cfg)
    kv = jax.device_put(kv0, kv_cache_sharding(plan, kv0))
    with use_plan(plan):
        with pytest.raises(ValueError, match="forced"):
            jax.jit(forward, static_argnums=1)(
                sharded, cfg, tokens, jnp.int32(0), kv)
