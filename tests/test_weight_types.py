"""All four reference weight formats load and run: f32 / f16 / q40 / q80.

The reference runtime accepts any of its converter's float types
(converter/writer.py:6-17; kernel dispatch nn-cpu-ops.cpp) — a user switching
from it must be able to bring an f16 or q80 .m file here too. Q40 and Q80
share the QuantizedWeight plane layout on device (codes*scales), so q80 rides
every quantized path (XLA dequant-dot, Pallas kernel, TP sharding) unchanged;
f16 loads dense.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import helpers
from dllama_tpu.formats import mfile, quants
from dllama_tpu.models import ModelConfig, forward
from dllama_tpu.models.llama import load_params_from_mfile
from dllama_tpu.ops.linear import QuantizedWeight, dequantize_weight
from dllama_tpu.parallel.api import make_tp_mesh, use_plan
from dllama_tpu.parallel.sharding import kv_cache_sharding
from dllama_tpu.runtime import KVCache

ALL_TYPES = [quants.F32, quants.F16, quants.Q40, quants.Q80]


def _build(tmp_path, weight_type, seed=5):
    rng = np.random.default_rng(seed)
    hdr = helpers.tiny_header_params(weight_type=weight_type)
    m = tmp_path / f"m{weight_type}.m"
    dense = helpers.write_tiny_model(m, hdr, rng)
    mf = mfile.ModelFile.open(m)
    return mf, ModelConfig.from_header(mf.header), dense


def _roundtrip(w: np.ndarray, weight_type: int) -> np.ndarray:
    """The dense weights as the on-disk format represents them."""
    flat = w.astype(np.float32).reshape(-1)
    if weight_type == quants.F32:
        return w.astype(np.float32)
    if weight_type == quants.F16:
        return flat.astype(np.float16).astype(np.float32).reshape(w.shape)
    if weight_type == quants.Q40:
        return quants.dequantize_q40(quants.quantize_q40(flat),
                                     flat.size).reshape(w.shape)
    return quants.dequantize_q80(quants.quantize_q80(flat),
                                 flat.size).reshape(w.shape)


@pytest.mark.parametrize("weight_type", ALL_TYPES)
def test_loaded_weights_match_disk_representation(tmp_path, weight_type):
    mf, cfg, dense = _build(tmp_path, weight_type)
    params = load_params_from_mfile(mf, cfg)
    lp = params.layers
    quantized = weight_type in (quants.Q40, quants.Q80)
    assert isinstance(lp.wq, QuantizedWeight) == quantized
    for l in range(mf.header.n_layers):
        want = _roundtrip(dense[f"block_matmul_q.{l}"], weight_type)
        if quantized:
            got = np.asarray(dequantize_weight(QuantizedWeight(
                scales=lp.wq.scales[l], codes=lp.wq.codes[l]))).T
        else:
            got = np.asarray(lp.wq[l], np.float32)
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
    mf.close()


def test_quantization_fidelity_ordering(tmp_path):
    """Same model in every format: f16 ~= f32; q80 strictly closer than q40
    (8-bit codes vs 4-bit). Runs the full forward, so the q80 matmul path is
    exercised end to end."""
    tokens = jnp.asarray([[3, 1, 4, 1, 5, 9, 2, 6]], dtype=jnp.int32)
    logits = {}
    for wt in ALL_TYPES:
        mf, cfg, _ = _build(tmp_path, wt)
        params = load_params_from_mfile(mf, cfg)
        out, _ = jax.jit(forward, static_argnums=1)(
            params, cfg, tokens, jnp.int32(0), KVCache.create(cfg))
        logits[wt] = np.asarray(out, np.float32)
        mf.close()
    ref = logits[quants.F32]
    err = {wt: np.abs(logits[wt] - ref).max() for wt in ALL_TYPES}
    assert err[quants.F16] < 0.02, err
    assert err[quants.Q80] < err[quants.Q40], err
    assert err[quants.Q80] < 0.1 and err[quants.Q40] < 1.0, err


def test_q80_tp_sharded_matches_unsharded(tmp_path):
    """Q80 planes through the TP shard loader: logits identical to the
    single-device load (same guarantee test_parallel proves for Q40)."""
    mf, cfg, _ = _build(tmp_path, quants.Q80)
    tokens = jnp.asarray([[3, 1, 4, 1, 5]], dtype=jnp.int32)
    params = load_params_from_mfile(mf, cfg)
    ref, _ = jax.jit(forward, static_argnums=1)(
        params, cfg, tokens, jnp.int32(0), KVCache.create(cfg))

    plan = make_tp_mesh(2)
    sharded = load_params_from_mfile(mf, cfg, plan=plan)
    kv = jax.device_put(KVCache.create(cfg),
                        kv_cache_sharding(plan, KVCache.create(cfg)))
    with use_plan(plan):
        got, _ = jax.jit(forward, static_argnums=1)(
            sharded, cfg, tokens, jnp.int32(0), kv)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)
    mf.close()
