"""Durable streams acceptance (serve/router.py + serve/api.py +
runtime/serving.py): a streaming request survives the death of its
serving replica with a token-exact, gapless, duplicate-free transcript.

The kill is the real thing: ``BatchedApiState.close(drain_s=0)``
fail-alls the scheduler mid-generation, the in-flight handler writes
the terminal ``finish_reason: "error"`` chunk over a cleanly-FINed
socket (exactly what a killed api-server process produces), and the
router must classify that as mid-stream death, splice a continuation
on a healthy replica, and deliver a transcript bitwise equal to an
unkilled solo run — greedy AND sampled, speculation on AND off, with
the KV-wire pull from the still-advertising dying donor degrading to
recompute when the wire fails, and the armed resume path adding zero
post-steady compiles."""

import json
import threading
import time
import urllib.request
from http.server import ThreadingHTTPServer

import numpy as np
import pytest

from dllama_tpu.formats import tfile
from dllama_tpu.runtime import failpoints as fp
from dllama_tpu.runtime import introspection
from dllama_tpu.runtime import telemetry as tm
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.serve.router import FleetRouter, make_router_handler

from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model
from test_router import _sse_events, _wait

BLOCK = 16


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.registry().clear()
    yield
    fp.registry().clear()


@pytest.fixture(scope="module")
def files(tmp_path_factory):
    d = tmp_path_factory.mktemp("stream_resume")
    mpath, tpath = d / "m.m", d / "t.t"
    # seq_len 256: room for the ~130-token templated prompt plus a
    # generation long enough that the kill always lands mid-stream
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=256),
                     np.random.default_rng(23))
    td = byte_vocab_tokenizer()
    td.chat_template = "<|start_header_id|>"
    tfile.write_tfile(tpath, td)
    return str(mpath), str(tpath)


def _state(files, spec=0):
    from dllama_tpu.serve.api import BatchedApiState

    m, t = files
    kw = {"spec_lookup": spec} if spec else {}
    engine = InferenceEngine(m, t, tp=1, kv_block_size=BLOCK,
                             temperature=0.0, seed=3, **kw)
    return BatchedApiState(engine, n_slots=2)


def _serve(state):
    from dllama_tpu.serve.api import make_handler

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(state))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, httpd.server_address[1]


@pytest.fixture(scope="module")
def oracle(files):
    """The unkilled solo baseline: one replica, no router, never killed
    — its streamed transcript is the bitwise contract every spliced
    fleet run must reproduce (spec-off: the exact-match speculative
    contract makes spec-on output identical to it by construction)."""
    state = _state(files)
    httpd, port = _serve(state)
    yield f"http://127.0.0.1:{port}"
    httpd.shutdown()
    httpd.server_close()
    state.close()


class _Fleet:
    """N real batched replicas behind a FleetRouter, with name→state
    access so a test can kill the one that serves."""

    def __init__(self, files, n, spec=0, **router_kw):
        self.by_name: dict = {}
        self.httpds = []
        urls = []
        for _ in range(n):
            st = _state(files, spec=spec)
            httpd, port = _serve(st)
            self.httpds.append(httpd)
            self.by_name[f"127.0.0.1:{port}"] = st
            urls.append(f"127.0.0.1:{port}")
        router_kw.setdefault("probe_interval_s", 0.05)
        self.fleet = FleetRouter(urls, **router_kw)
        self.r_httpd = ThreadingHTTPServer(("127.0.0.1", 0),
                                           make_router_handler(self.fleet))
        self.url = f"http://127.0.0.1:{self.r_httpd.server_address[1]}"
        threading.Thread(target=self.r_httpd.serve_forever,
                         daemon=True).start()

    def wait_up(self):
        _wait(lambda: all(r.state == "up" for r in self.fleet.replicas),
              timeout=60, what="replicas probed up")

    def sticky(self, key) -> str:
        with self.fleet._lock:
            rep = self.fleet._affinity.get(key)
        assert rep is not None, f"no sticky binding for {key}"
        return rep.name

    def pin(self, key, name) -> None:
        rep = [r for r in self.fleet.replicas if r.name == name][0]
        with self.fleet._lock:
            self.fleet._affinity[key] = rep

    def close(self):
        self.r_httpd.shutdown()
        self.r_httpd.server_close()
        self.fleet.close()
        for h in self.httpds:
            h.shutdown()
            h.server_close()
        for st in self.by_name.values():
            try:
                st.close()
            except Exception:  # noqa: BLE001 — victims are already closed
                pass


def _body(session, n=80, **extra):
    text = session + "".join(chr(97 + j % 26) for j in range(40))
    return {"messages": [{"role": "user", "content": text}],
            "max_tokens": n, "temperature": 0, "stream": True,
            "session_id": session, **extra}


def _post_json(url, payload, timeout=300):
    req = urllib.request.Request(
        url + "/v1/chat/completions",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _open_stream(url, payload, timeout=300):
    req = urllib.request.Request(
        url + "/v1/chat/completions",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=timeout)


def _read_until_stamped(resp, n) -> bytes:
    """Relay bytes until n token-carrying stamped chunks arrived — the
    mid-stream point where the test pulls the trigger."""
    raw = b""
    seen = 0
    while seen < n:
        line = resp.readline()
        if not line:
            break
        raw += line
        if line.startswith(b"data:") and b'"dllama"' in line:
            meta = json.loads(line[5:].strip()).get("dllama") or {}
            if meta.get("index", 0) > 0 and meta.get("tokens"):
                seen += 1
    return raw


def _transcript(events):
    """(token ids, text, finish_reason) with the gapless duplicate-free
    ledger asserted chunk by chunk: every stamped index advances by
    exactly the ids the chunk carries (a same-index empty-token chunk is
    the stop-string detector's tail flush)."""
    n, toks, text, finish = 0, [], "", None
    for e in events:
        if e == "[DONE]":
            continue
        ch = (e.get("choices") or [{}])[0]
        if ch.get("finish_reason"):
            finish = ch["finish_reason"]
        meta = e.get("dllama")
        if meta is None:
            continue
        idx, t = meta["index"], meta["tokens"]
        if idx == 0:
            continue  # the prompt-echo chunk
        assert idx == n + len(t), \
            f"transcript gap/duplicate: index {idx} after {n}"
        n = idx
        toks += t
        text += (ch.get("delta") or {}).get("content") or ""
    return n, toks, text, finish


def _resumed_total():
    return tm.registry().counter(tm.ROUTER_STREAM_RESUMES).total(
        outcome="resumed")


def _oracle_run(oracle_url, body):
    with _open_stream(oracle_url, body) as r:
        return _transcript(_sse_events(r.read()))


def _kill_mid_stream(fl, body, after=2):
    """Warm the session (binds affinity + advertises the prefix), open
    the stream, kill the serving replica after ``after`` stamped chunks,
    and return (full raw transcript bytes, victim name)."""
    key = f"sid:{body['session_id']}"
    _post_json(fl.url, dict(body, stream=False, max_tokens=4))
    victim = fl.sticky(key)
    _wait(lambda: any(r.holds_prefix(key) for r in fl.fleet.replicas),
          what="prefix advertisement probed")
    resp = _open_stream(fl.url, body)
    raw = _read_until_stamped(resp, after)
    fl.by_name[victim].close(drain_s=0.0)  # the replica dies NOW
    raw += resp.read()
    return raw, victim


def test_greedy_midstream_kill_token_exact_and_ledger_quiet(files, oracle):
    """The acceptance contract: 3 replicas, the serving one killed
    mid-stream — the client transcript is gapless, duplicate-free, and
    bitwise equal to the unkilled solo run, finish_reason normal, the
    resume on the counters and the rt_resume span in the fleet
    timeline. A second kill/resume cycle (same shapes, fresh session)
    then proves the armed resume path adds zero post-steady compiles."""
    fl = _Fleet(files, 3)
    try:
        fl.wait_up()

        def cycle(tag):
            body = _body(tag)
            want = _oracle_run(oracle, body)
            r0 = _resumed_total()
            raw, victim = _kill_mid_stream(fl, body)
            events = _sse_events(raw)
            got = _transcript(events)
            assert b'"upstream_error"' not in raw
            assert events[-1] == "[DONE]"
            assert got == want, "spliced transcript diverged from solo"
            assert got[3] in ("length", "stop")
            assert _resumed_total() == r0 + 1
            return victim

        v1 = cycle("dur-a")
        spans = [s for s in fl.fleet.fleet_snapshot()["spans"]
                 if s["phase"] == "rt_resume"]
        assert spans, "rt_resume span missing from the fleet timeline"
        assert spans[-1]["resume_from"] >= 2

        # -- ledger-quiet second cycle ---------------------------------
        # cycle 1's resume target already served a full splice; pin the
        # next session's victim to the OTHER survivor so the second
        # resume re-runs the identical path on the warmed target
        alive = [n for n in fl.by_name if n != v1]
        target = [s for s in fl.fleet.fleet_snapshot()["spans"]
                  if s["phase"] == "rt_resume"][-1]["replica"]
        victim2 = [n for n in alive if n != target][0]
        fl.pin("sid:dur-b", victim2)
        # steady state first: the resume point drifts with scheduler
        # racing, so the continuation's tail prefill chunk can land in
        # any bucket — sweep direct prompt lengths 32 apart so every
        # tail bucket is compiled before the measured cycle
        for extra in (16, 48, 80):
            _post_json(f"http://{target}",
                       dict(_body(f"warm{extra}", n=2), stream=False,
                            messages=[{"role": "user",
                                       "content": "w" * (40 + extra)}]))
        scope = fl.by_name[target].engine.introspection_scope
        c0 = introspection.ledger().compile_count(scope)
        v2 = cycle("dur-b")
        assert v2 == victim2
        assert introspection.ledger().compile_count(scope) == c0, \
            "resume admission recompiled on a warmed replica"
    finally:
        fl.close()


def test_sampled_resume_bitwise_and_kv_failure_recomputes(files, oracle):
    """Sampled stream (temperature 0.9, fixed seed): the deterministic
    coin stream is fast-forwarded by the emitted-token count at the
    splice, so the resumed transcript is bitwise equal to the unkilled
    solo run — even when the KV-wire pull from the dying donor fails
    (armed kvwire failpoint) and the target recomputes the prefix."""
    fl = _Fleet(files, 2)
    try:
        fl.wait_up()
        body = _body("dur-s", temperature=0.9, seed=7)
        want = _oracle_run(oracle, body)
        assert want[0] > 4  # sampled run long enough to splice inside
        mig = tm.registry().counter(tm.KVWIRE_MIGRATIONS)
        f0 = mig.total(outcome="fallback")
        r0 = _resumed_total()
        fp.arm("kvwire", "short_read", times=1)
        raw, _ = _kill_mid_stream(fl, body)
        got = _transcript(_sse_events(raw))
        assert got == want, "sampled splice diverged from solo"
        assert _resumed_total() == r0 + 1
        # the migration was attempted against the dying donor and
        # degraded to recompute — and the transcript still matched
        assert mig.total(outcome="fallback") == f0 + 1
    finally:
        fl.close()


def test_spec_on_sampled_resume_bitwise_vs_spec_off_oracle(files, oracle):
    """Speculation on: the exact-match accept rule keeps sampled spec
    output identical to spec-off, and the coins-consumed == tokens-
    emitted invariant makes the resume fast-forward land on the same
    coin — so a spec-on fleet's spliced transcript equals the spec-off
    unkilled oracle bitwise, with drafting live on both hops."""
    fl = _Fleet(files, 2, spec=4)
    try:
        fl.wait_up()
        body = _body("dur-v", temperature=0.9, seed=11)
        want = _oracle_run(oracle, body)
        drafted = tm.registry().counter(tm.SPEC_DRAFT_TOKENS)
        d0 = drafted.total(generator="paged")
        r0 = _resumed_total()
        raw, _ = _kill_mid_stream(fl, body)
        got = _transcript(_sse_events(raw))
        assert got == want, "spec-on splice diverged from spec-off solo"
        assert _resumed_total() == r0 + 1
        assert drafted.total(generator="paged") > d0  # spec was live
    finally:
        fl.close()
