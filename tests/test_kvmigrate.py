"""Fault-tolerant KV migration (runtime/kvwire + serving + api + router).

THE correctness property: a request whose prefix KV was migrated over
the checksummed Q80 wire produces output token-identical to one that
recomputed the prefix locally — and EVERY wire failure (dead peer,
corrupt frame, expired deadline, exhausted destination pool) degrades to
that local recompute with the reason on the fallback counter, never to a
user-visible error. The wire codec itself must equal one in-graph
``fake_quant_q80`` application bit for bit, so a migrated prefix carries
exactly the quantization the sync-q80 parity mode already defines."""

import io
import json
import socket
import struct
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from dllama_tpu.formats import tfile
from dllama_tpu.runtime import failpoints as fp
from dllama_tpu.runtime import kvwire
from dllama_tpu.runtime import telemetry as tm
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.runtime.kvblocks import BlockPoolExhausted

from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model

BLOCK = 16
PATHS = {}


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.registry().clear()
    yield
    fp.registry().clear()


# -- codec + framing (no engine) ---------------------------------------------

GEOM = {"n_layers": 2, "n_kv_heads": 4, "block_size": 16, "head_dim": 8,
        "dtype": "float32"}


def _mk_blocks(n, seed=0):
    rng = np.random.default_rng(seed)
    shape = (GEOM["n_layers"], GEOM["n_kv_heads"], GEOM["block_size"],
             GEOM["head_dim"])
    return [(rng.standard_normal(shape).astype(np.float32) * 3,
             rng.standard_normal(shape).astype(np.float32) * 3)
            for _ in range(n)]


def _stream_bytes(blocks, geom=None, n_tokens=None):
    g = dict(geom or GEOM)
    g["n_blocks"] = len(blocks)
    g["n_tokens"] = (n_tokens if n_tokens is not None
                     else len(blocks) * g["block_size"])
    buf = io.BytesIO()
    kvwire.write_stream(buf, g, blocks)
    return buf.getvalue()


def test_q80_codec_matches_fake_quant_bitwise():
    """The wire roundtrip IS one fake_quant_q80 application: codes from
    the unrounded f32 scale, dequant by the f16-rounded stored scale."""
    import jax.numpy as jnp

    from dllama_tpu.ops.linear import fake_quant_q80

    rng = np.random.default_rng(7)
    x = (rng.standard_normal((4, 256)).astype(np.float32) * 5)
    x[0, :32] = 0.0  # an all-zero Q80 group must decode to exact zeros
    codes, scales = kvwire.q80_encode(x)
    back = kvwire.q80_decode(codes, scales.reshape(-1, 1), x.shape)
    want = np.asarray(fake_quant_q80(jnp.asarray(x)), np.float32)
    np.testing.assert_array_equal(back, want)


def test_stream_roundtrip_counts_and_order():
    blocks = _mk_blocks(3)
    reg = tm.registry()
    tx0 = reg.counter(tm.KVWIRE_TX_FRAMES).total()
    rx0 = reg.counter(tm.KVWIRE_RX_BYTES).total()
    data = _stream_bytes(blocks)
    hdr, rx = kvwire.read_stream(io.BytesIO(data), GEOM)
    assert hdr["n_tokens"] == 48 and hdr["n_blocks"] == 3
    assert [i for i, _, _ in rx] == [0, 1, 2]
    # header + 3 blocks + end frame on the TX counter; RX counted bytes
    assert reg.counter(tm.KVWIRE_TX_FRAMES).total() - tx0 == 5
    assert reg.counter(tm.KVWIRE_RX_BYTES).total() - rx0 == len(data)
    for (k, _v), (_, rk, _rv) in zip(blocks, rx):
        ck, sk = kvwire.q80_encode(k)
        np.testing.assert_array_equal(
            rk, kvwire.q80_decode(ck, sk.reshape(-1, 1), k.shape))


def test_flipped_byte_fails_crc():
    data = bytearray(_stream_bytes(_mk_blocks(2)))
    # flip a byte deep inside the first block frame's payload (the
    # header frame is < 200 B; block frames are ~2.2 kB each)
    data[400] ^= 0x40
    with pytest.raises(kvwire.ChecksumError):
        kvwire.read_stream(io.BytesIO(bytes(data)), GEOM)
    assert kvwire.classify_failure(kvwire.ChecksumError("x")) == "crc"


def test_truncation_is_peer_death():
    data = _stream_bytes(_mk_blocks(2))
    for cut in (len(data) // 2, len(data) - 6):  # mid-frame, pre-end
        with pytest.raises(kvwire.TruncatedStream) as e:
            kvwire.read_stream(io.BytesIO(data[:cut]), GEOM)
        assert kvwire.classify_failure(e.value) == "peer_death"


def test_geometry_mismatch_refuses_loudly():
    data = _stream_bytes(_mk_blocks(1))
    expect = dict(GEOM, head_dim=16)
    with pytest.raises(kvwire.GeometryMismatch) as e:
        kvwire.read_stream(io.BytesIO(data), expect)
    assert "head_dim" in str(e.value)  # the refusal names the field


def test_version_mismatch_refuses():
    body = json.dumps(GEOM).encode()
    hdr = struct.pack(">4sHI", kvwire.MAGIC, kvwire.VERSION + 1,
                      len(body)) + body
    frame = (struct.pack(">I", len(hdr)) + hdr
             + struct.pack(">I", __import__("zlib").crc32(hdr)))
    with pytest.raises(kvwire.GeometryMismatch):
        kvwire.read_stream(io.BytesIO(frame), GEOM)


def test_expired_deadline_mid_stream():
    data = _stream_bytes(_mk_blocks(1))
    with pytest.raises(kvwire.DeadlineExceeded) as e:
        kvwire.read_stream(io.BytesIO(data), GEOM,
                           deadline=time.monotonic() - 1.0)
    assert kvwire.classify_failure(e.value) == "timeout"


def test_failpoint_short_read_classifies_crc():
    """kvwire:short_read truncates a frame section → the integrity
    class (reason "crc"), same as a flipped bit — and does NOT retry."""
    fp.arm("kvwire", "short_read", times=1)
    with pytest.raises(kvwire.ChecksumError) as e:
        kvwire.read_stream(io.BytesIO(_stream_bytes(_mk_blocks(1))), GEOM)
    assert kvwire.classify_failure(e.value) == "crc"


def test_failpoint_raise_classifies_peer_death():
    fp.arm("kvwire", "raise", times=1)
    with pytest.raises(fp.FailpointError) as e:
        kvwire.read_stream(io.BytesIO(_stream_bytes(_mk_blocks(1))), GEOM)
    assert kvwire.classify_failure(e.value) == "peer_death"


# -- fetch client (stub HTTP peers) ------------------------------------------


class _StubPeer:
    """A /v1/kv/export stand-in with scripted per-request behavior:
    each entry of ``script`` is ``"reset"`` (close before any status),
    ``"busy"`` (503), bytes (serve verbatim), or ``("truncate", bytes,
    n)`` (serve the first n bytes then close)."""

    def __init__(self, script):
        self.script = list(script)
        self.n_requests = 0
        stub = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def do_POST(self):
                self.rfile.read(int(self.headers.get("Content-Length", 0)))
                stub.n_requests += 1
                step = (stub.script.pop(0) if stub.script else "busy")
                if step == "reset":
                    # close before any status byte: the client sees
                    # RemoteDisconnected (an OSError → transient class)
                    self.close_connection = True
                    try:
                        self.connection.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    self.connection.close()
                    return
                if step == "busy":
                    body = b'{"error": "not now"}'
                    self.send_response(503)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                data = step[1][:step[2]] if isinstance(step, tuple) else step
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/octet-stream")
                self.send_header("Connection", "close")
                self.end_headers()
                self.wfile.write(data)
                self.close_connection = True

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    @property
    def peer(self):
        return f"127.0.0.1:{self.port}"

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_fetch_kv_retries_transient_then_succeeds():
    """A peer that dies on the first attempt (connection reset before
    any status byte) is retried with backoff inside the deadline."""
    data = _stream_bytes(_mk_blocks(2))
    stub = _StubPeer(["reset", data])
    try:
        hdr, blocks = kvwire.fetch_kv(stub.peer, [1, 2, 3], GEOM,
                                      deadline_s=5.0)
        assert hdr["n_blocks"] == 2 and len(blocks) == 2
        assert stub.n_requests == 2
    finally:
        stub.close()


def test_fetch_kv_exhausts_attempts_on_dead_peer():
    stub = _StubPeer(["reset", "reset", "reset", "reset"])
    try:
        with pytest.raises((kvwire.KVWireError, OSError)) as e:
            kvwire.fetch_kv(stub.peer, [1], GEOM, deadline_s=5.0,
                            max_attempts=3)
        assert stub.n_requests == 3  # bounded: exactly max_attempts
        assert kvwire.classify_failure(e.value) == "peer_death"
    finally:
        stub.close()


def test_fetch_kv_integrity_failure_does_not_retry():
    """A corrupt frame means the SOURCE is bad — retrying the same
    source would re-download the same corruption; recompute instead."""
    data = bytearray(_stream_bytes(_mk_blocks(2)))
    data[400] ^= 0x40
    stub = _StubPeer([bytes(data), bytes(data)])
    try:
        with pytest.raises(kvwire.ChecksumError):
            kvwire.fetch_kv(stub.peer, [1], GEOM, deadline_s=5.0)
        assert stub.n_requests == 1
    finally:
        stub.close()


# -- engine-level migration ----------------------------------------------------


@pytest.fixture(scope="module")
def files(tmp_path_factory):
    d = tmp_path_factory.mktemp("kvmigrate")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(41)
    # seq_len 256: the llama3 chat template alone is ~90 byte-tokens, and
    # the migration tests want several full 16-row blocks of prompt KV
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=256),
                     rng)
    td = byte_vocab_tokenizer()
    td.chat_template = "<|start_header_id|>"  # detected as llama3
    tfile.write_tfile(tpath, td)
    PATHS["m"], PATHS["t"] = str(mpath), str(tpath)
    return PATHS


def _paged_state(files, n_slots=2, role=None):
    from dllama_tpu.serve.api import BatchedApiState

    engine = InferenceEngine(files["m"], files["t"], tp=1,
                             kv_block_size=BLOCK, temperature=0.0, seed=3)
    return BatchedApiState(engine, n_slots=n_slots, role=role)


def _serve(state):
    from dllama_tpu.serve.api import make_handler

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(state))
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, port


def _post(url, payload, timeout=120):
    req = urllib.request.Request(
        url + "/v1/chat/completions",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _body(prompt, n=8, **extra):
    return {"messages": [{"role": "user", "content": prompt}],
            "max_tokens": n, "temperature": 0, **extra}


def _session_text(tag):
    # >= 2 full 16-row blocks of templated prompt per session
    return tag + "".join(chr(97 + j % 26) for j in range(40))


@pytest.fixture(scope="module")
def src_server(files):
    """The migration SOURCE: a paged batched api-server whose pool is
    warmed per test; also the never-migrated baseline oracle (batched
    output equals solo output — the serving invariant pinned by
    tests/test_serving.py)."""
    state = _paged_state(files)
    httpd, port = _serve(state)
    yield f"http://127.0.0.1:{port}", state, port
    httpd.shutdown()
    httpd.server_close()
    state.close()


@pytest.fixture(scope="module")
def dst_state(files):
    """The migration DESTINATION, driven directly through
    ``BatchedApiState.complete(..., kv_peer=...)`` (what the HTTP
    handler does with the X-Dllama-KV-Peer header)."""
    state = _paged_state(files)
    yield state
    state.close()


def _mig_totals():
    reg = tm.registry()
    return {
        "migrated": reg.counter(tm.KVWIRE_MIGRATIONS).total(
            outcome="migrated"),
        "fallback": reg.counter(tm.KVWIRE_MIGRATIONS).total(
            outcome="fallback"),
        **{r: reg.counter(tm.KVWIRE_FALLBACK).total(reason=r)
           for r in ("timeout", "crc", "peer_death", "exhaustion")},
    }


def _delta(after, before):
    return {k: after[k] - before[k] for k in after}


def test_migrated_decode_token_exact(src_server, dst_state):
    """The tentpole contract end to end: warm the source, migrate the
    prefix to the destination over the wire, and the destination's
    completion is byte-identical to the never-migrated source run —
    with the migration on the counters and the kvmigrate TTFT phase."""
    url, _, port = src_server
    body = _body(_session_text("mig-exact-"), n=8)
    baseline = _post(url, body)  # warms the source's pool
    t0 = _mig_totals()
    rx0 = tm.registry().counter(tm.KVWIRE_RX_BYTES).total()
    out = dst_state.complete(dict(body, timing=True),
                             kv_peer=f"127.0.0.1:{port}")
    d = _delta(_mig_totals(), t0)
    assert out["text"] == baseline["choices"][0]["message"]["content"]
    assert out["finish_reason"] == baseline["choices"][0]["finish_reason"]
    assert d["migrated"] == 1 and d["fallback"] == 0
    assert tm.registry().counter(tm.KVWIRE_RX_BYTES).total() > rx0
    # the migration wall is attributed to its own TTFT phase, carved
    # out of the queue window (runtime/flightrec.ttft_phases)
    assert out["timing"]["kvmigrate_ms"] > 0


def test_peer_refuses_when_prefix_not_resident(src_server):
    """/v1/kv/export answers 404 for an unknown prefix; the importer
    treats it as any other failure — recompute, reason peer_death."""
    url, state, _ = src_server
    req = urllib.request.Request(
        url + "/v1/kv/export",
        data=json.dumps({"tokens": [9, 9, 9, 9]}).encode(),
        headers={"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=30)
    assert e.value.code == 404
    assert "not resident" in json.loads(e.value.read())["error"]
    # malformed body: 400, never a 500
    req = urllib.request.Request(
        url + "/v1/kv/export", data=b'{"tokens": "nope"}',
        headers={"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=30)
    assert e.value.code == 400


def test_chaos_dead_peer_falls_back_to_recompute(src_server, dst_state):
    """kv_peer names a port nobody listens on: bounded retries burn
    out, the request recomputes locally and completes token-exact."""
    url, _, _ = src_server
    body = _body(_session_text("mig-dead-"), n=8)
    baseline = _post(url, body)
    t0 = _mig_totals()
    # an unbound port refuses instantly; keep the wire deadline small
    # anyway so a filtered port can't stall the test
    out = dst_state.complete(body, kv_peer="127.0.0.1:9")
    d = _delta(_mig_totals(), t0)
    assert out["text"] == baseline["choices"][0]["message"]["content"]
    assert d["fallback"] == 1 and d["migrated"] == 0
    assert d["peer_death"] == 1


def test_chaos_source_killed_mid_transfer(src_server, dst_state):
    """The peer dies mid-stream (header + partial block, then the
    socket closes): the destination rolls back its staged transfer,
    recomputes, and the request completes token-exact."""
    url, _, _ = src_server
    geom = dst_state.sched.gen.wire_geometry()
    shape = (geom["n_layers"], geom["n_kv_heads"], geom["block_size"],
             geom["head_dim"])
    rng = np.random.default_rng(5)
    blocks = [(rng.standard_normal(shape).astype(np.float32),
               rng.standard_normal(shape).astype(np.float32))
              for _ in range(2)]
    data = _stream_bytes(blocks, geom=geom)
    # every attempt dies at 60% of the stream — mid-transfer death,
    # repeated until the retry budget is spent
    stub = _StubPeer([("truncate", data, int(len(data) * 0.6))] * 3)
    body = _body(_session_text("mig-kill-"), n=8)
    baseline = _post(url, body)
    t0 = _mig_totals()
    try:
        out = dst_state.complete(body, kv_peer=stub.peer)
    finally:
        stub.close()
    d = _delta(_mig_totals(), t0)
    assert out["text"] == baseline["choices"][0]["message"]["content"]
    assert d["fallback"] == 1 and d["peer_death"] == 1


def test_chaos_short_read_injection_is_crc_fallback(src_server, dst_state):
    """kvwire:short_read fired on the import side truncates a frame →
    integrity failure (reason "crc"), no retry against the corrupt
    source, local recompute, token-exact completion."""
    url, _, port = src_server
    body = _body(_session_text("mig-crc-"), n=8)
    baseline = _post(url, body)
    fired0 = fp.registry().fired("kvwire")
    fp.arm("kvwire", "short_read", times=1)
    t0 = _mig_totals()
    out = dst_state.complete(body, kv_peer=f"127.0.0.1:{port}")
    d = _delta(_mig_totals(), t0)
    assert out["text"] == baseline["choices"][0]["message"]["content"]
    assert d["fallback"] == 1 and d["crc"] == 1 and d["migrated"] == 0
    assert fp.registry().fired("kvwire") == fired0 + 1


def test_chaos_stalled_stream_is_timeout_fallback(src_server, dst_state,
                                                  monkeypatch):
    """kvwire:sleep stalls the stream past the per-transfer deadline
    (shrunk via DLLAMA_KVWIRE_DEADLINE_S) → reason "timeout", local
    recompute, token-exact completion."""
    url, _, port = src_server
    body = _body(_session_text("mig-slow-"), n=8)
    baseline = _post(url, body)
    monkeypatch.setenv("DLLAMA_KVWIRE_DEADLINE_S", "0.3")
    fp.registry().arm("kvwire", "sleep", times=1, delay_s=0.8)
    t0 = _mig_totals()
    out = dst_state.complete(body, kv_peer=f"127.0.0.1:{port}")
    d = _delta(_mig_totals(), t0)
    assert out["text"] == baseline["choices"][0]["message"]["content"]
    assert d["fallback"] == 1 and d["timeout"] == 1
    fp.registry().clear()


def test_chaos_destination_pool_exhausted(src_server, dst_state):
    """The wire delivered, but the destination can't stage: allocation
    fails mid-ingest → partial blocks released (no leak), reason
    "exhaustion", the request admits normally and recomputes."""
    url, _, port = src_server
    body = _body(_session_text("mig-full-"), n=8)
    baseline = _post(url, body)
    pool = dst_state.sched.gen.pool
    orig_alloc = pool.alloc
    state = {"armed": True}

    def failing_alloc(*a, **kw):
        if state["armed"]:
            state["armed"] = False
            raise BlockPoolExhausted("injected: no blocks for staging")
        return orig_alloc(*a, **kw)

    pool.alloc = failing_alloc
    t0 = _mig_totals()
    try:
        out = dst_state.complete(body, kv_peer=f"127.0.0.1:{port}")
    finally:
        pool.alloc = orig_alloc
    d = _delta(_mig_totals(), t0)
    assert out["text"] == baseline["choices"][0]["message"]["content"]
    assert d["fallback"] == 1 and d["exhaustion"] == 1
    assert not state["armed"]  # the injection actually fired


# -- full stack: router-orchestrated disaggregation ---------------------------


def test_disaggregated_decode_through_router(files):
    """The acceptance path end to end: router → prefill warm-up on the
    prefill-role replica → kvwire export → decode replica imports →
    streams the completion. Output equals a never-migrated direct run;
    the migration and the prefill dispatch are telemetry-visible."""
    from dllama_tpu.serve.router import FleetRouter, make_router_handler

    p_state = _paged_state(files, role="prefill")
    d_state = _paged_state(files)
    p_httpd, p_port = _serve(p_state)
    d_httpd, d_port = _serve(d_state)
    fleet = FleetRouter([f"127.0.0.1:{p_port}", f"127.0.0.1:{d_port}"],
                        probe_interval_s=0.05)
    r_httpd, r_port = (lambda h: (h, h.server_address[1]))(
        ThreadingHTTPServer(("127.0.0.1", 0),
                            make_router_handler(fleet)))
    threading.Thread(target=r_httpd.serve_forever, daemon=True).start()
    try:
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if (all(r.state == "up" for r in fleet.replicas)
                    and any(r.is_prefill() for r in fleet.replicas)):
                break
            time.sleep(0.02)
        assert all(r.state == "up" for r in fleet.replicas), \
            "replicas never probed up"
        assert any(r.is_prefill() for r in fleet.replicas), \
            "prefill role never probed"
        body = _body(_session_text("disagg-"), n=8,
                     session_id="disagg-e2e", timing=True)
        t0 = _mig_totals()
        out = _post(f"http://127.0.0.1:{r_port}", body)
        d = _delta(_mig_totals(), t0)
        # the decode replica pulled the prefix the prefill replica
        # computed — a real wire migration, not a local recompute
        assert d["migrated"] == 1 and d["fallback"] == 0
        assert out["timing"]["kvmigrate_ms"] > 0
        # decode-role replica served it (prefill is fenced off the
        # dispatch pool)
        assert tm.registry().counter(tm.ROUTER_DISPATCHES).total(
            replica=f"127.0.0.1:{d_port}") >= 1
        assert tm.registry().counter(tm.ROUTER_DISPATCHES).total(
            replica=f"127.0.0.1:{p_port}") == 0
        # token-exactness vs a never-migrated run: the prefill replica
        # already holds the prefix locally, so a direct full completion
        # there is the recompute oracle (prefix sharing is invariant —
        # tests/test_serving.py pins that)
        oracle = _post(f"http://127.0.0.1:{p_port}", _body(
            _session_text("disagg-"), n=8, session_id="disagg-e2e"))
        assert out["choices"][0]["message"]["content"] \
            == oracle["choices"][0]["message"]["content"]
    finally:
        r_httpd.shutdown()
        r_httpd.server_close()
        fleet.close()
        for h in (p_httpd, d_httpd):
            h.shutdown()
            h.server_close()
        p_state.close()
        d_state.close()
