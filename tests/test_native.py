"""Native C++ codec vs numpy golden model — byte-exact parity.

The native library is the host-runtime hot path (weight load repack + codecs);
it must be bit-identical to the portable numpy implementations, which are
themselves byte-golden with the reference converter (test_formats.py /
test_convert.py). Mirrors the reference's converter/writer-test.py golden-hex
approach plus nn-cpu-ops-test.cpp's quantize→dequantize round-trips.
"""

import os

import numpy as np
import pytest

from dllama_tpu import native
from dllama_tpu.formats import quants

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no toolchain)")


def _cases():
    rng = np.random.default_rng(99)
    yield rng.standard_normal(32 * 17).astype(np.float32) * 3.0
    yield np.zeros(64, dtype=np.float32)                 # d == 0 path
    yield -np.abs(rng.standard_normal(96)).astype(np.float32)  # negative absmax
    big = rng.standard_normal(32 * 64).astype(np.float32)
    big[::7] *= 1e4                                      # wide dynamic range
    yield big
    # exact rounding ties for q80: x/d lands on k+0.5 → half-to-even
    t = np.full(32, 63.5 / 127.0, dtype=np.float32)
    t[0] = 1.0
    yield t


@pytest.mark.parametrize("i,x", list(enumerate(_cases())))
def test_q40_quantize_byte_identical(i, x):
    assert native.q40_quantize(x) == quants.quantize_q40_np(x)


@pytest.mark.parametrize("i,x", list(enumerate(_cases())))
def test_q80_quantize_byte_identical(i, x):
    assert native.q80_quantize(x) == quants.quantize_q80_np(x)


@pytest.mark.parametrize("i,x", list(enumerate(_cases())))
def test_dequantize_bit_identical(i, x):
    q40 = quants.quantize_q40_np(x)
    got = native.q40_dequantize(q40, x.size)
    np.testing.assert_array_equal(got, quants.dequantize_q40_np(q40, x.size))
    q80 = quants.quantize_q80_np(x)
    got = native.q80_dequantize(q80, x.size)
    np.testing.assert_array_equal(got, quants.dequantize_q80_np(q80, x.size))


def test_repack_kmajor_matches_numpy_transpose():
    rng = np.random.default_rng(5)
    rows, cols = 24, 96
    w = rng.standard_normal((rows, cols)).astype(np.float32)
    buf = quants.quantize_q40_np(w.reshape(-1))

    got_scales, got_codes = native.q40_repack_kmajor(buf, rows, cols)

    scales, codes = quants.unpack_q40(buf, rows * cols)
    want_scales = scales.reshape(rows, cols // 32).T.astype(np.float32)
    want_codes = codes.reshape(rows, cols).T
    np.testing.assert_array_equal(got_scales, want_scales)
    np.testing.assert_array_equal(got_codes, want_codes)
    assert got_scales.dtype == np.float32 and got_codes.dtype == np.int8


def test_threaded_matches_single_thread():
    rng = np.random.default_rng(8)
    x = rng.standard_normal(32 * 1024).astype(np.float32)
    assert native.q40_quantize(x, nthreads=4) == native.q40_quantize(x, nthreads=1)
    assert native.q80_quantize(x, nthreads=4) == native.q80_quantize(x, nthreads=1)
    buf = native.q40_quantize(x)
    rows, cols = 32, 1024
    s1, c1 = native.q40_repack_kmajor(buf, rows, cols, nthreads=1)
    s4, c4 = native.q40_repack_kmajor(buf, rows, cols, nthreads=4)
    np.testing.assert_array_equal(s1, s4)
    np.testing.assert_array_equal(c1, c4)


def test_dispatch_uses_native():
    """Public codecs and the native path agree end to end (mfile load path)."""
    rng = np.random.default_rng(3)
    x = rng.standard_normal(32 * 8).astype(np.float32)
    assert quants.quantize_q40(x) == quants.quantize_q40_np(x)
    assert quants.quantize_q80(x) == quants.quantize_q80_np(x)


def test_stale_on_host_signature_change(monkeypatch, tmp_path):
    """A .so built on another CPU (-march=native, shared FS) must be
    rebuilt, not dlopened into a potential SIGILL (advisor round-1
    finding)."""
    from dllama_tpu import native

    if native.get_lib() is None:
        pytest.skip("native toolchain unavailable")
    assert not native._stale()  # fresh build on this host
    assert native._so_path().exists()
    # another CPU -> different signature -> different filename: that host's
    # loader neither sees nor dlopens this build (atomic check-and-load)
    monkeypatch.setattr(native, "_host_signature", lambda: "otherhost")
    assert native._stale()
    assert not native._so_path().exists()


# -- native BPE merge engine (tokenizer.cpp) --------------------------------


def _merge_rich_tokenizer():
    import test_tokenizer

    return test_tokenizer._merge_rich_tokenizer()


def test_bpe_native_matches_python_heap():
    """Native merge vs the Python heap fallback on the tie-heavy vocab —
    identical output on every random input (both must equal the reference's
    rescan policy; test_tokenizer proves heap == rescan)."""
    t_nat = _merge_rich_tokenizer()
    t_py = _merge_rich_tokenizer()
    t_py._bpe_native = False  # pin the Python path
    assert t_nat._native_merger() is not None, "native merger did not build"
    rng = np.random.default_rng(7)
    alphabet = "abcd "
    for _ in range(300):
        n = int(rng.integers(0, 64))
        s = "".join(alphabet[i] for i in rng.integers(0, len(alphabet), n))
        base = [t_nat._regular[bytes([b])] for b in s.encode()]
        assert t_nat._merge(list(base)) == t_py._merge(list(base)), repr(s)


def test_bpe_native_rejects_bad_ids():
    t = _merge_rich_tokenizer()
    m = t._native_merger()
    assert m is not None
    assert m.merge([0, 10 ** 6]) is None  # out-of-vocab id → fallback signal
    assert m.merge([5]) == [5]
    assert m.merge([]) == []


def test_bpe_native_encode_is_fast():
    """100k chars through the full encode (native merge) well under the
    2s bound the Python path is held to — same corpus and vocab as
    test_tokenizer.test_encode_100k_chars_under_2s."""
    import time

    from helpers import byte_vocab_tokenizer
    from dllama_tpu.tokenizer.bpe import Tokenizer

    t = Tokenizer(byte_vocab_tokenizer())
    assert t._native_merger() is not None
    text = "hello world " * 8500
    t0 = time.perf_counter()
    ids = t.encode(text)
    dt = time.perf_counter() - t0
    assert t.decode_all(ids) == text
    assert dt < 1.5, f"native-backed encode took {dt:.2f}s"


def test_native_tsan_tier():
    """Race-detection tier (SURVEY §5 'partial' row): the threaded codec +
    BPE paths run under ThreadSanitizer in a standalone instrumented binary
    (TSAN can't load late into python via dlopen). halt_on_error turns any
    detected race into a nonzero exit."""
    import shutil
    import subprocess
    from pathlib import Path

    if shutil.which("g++") is None:
        pytest.skip("no g++")
    d = Path(__file__).parent.parent / "dllama_tpu" / "native"
    build = subprocess.run(["make", "-C", str(d), "-s", "tsan"],
                           capture_output=True, text=True, timeout=180)
    if build.returncode != 0:
        pytest.skip(f"tsan build unavailable: {build.stderr[-200:]}")
    run = subprocess.run(
        [str(d / "tsan_stress")], capture_output=True, text=True, timeout=120,
        env={**os.environ, "TSAN_OPTIONS": "halt_on_error=1 exitcode=66"})
    assert run.returncode == 0, (run.returncode, run.stderr[-800:])
    assert "ThreadSanitizer" not in run.stderr, run.stderr[-800:]
    assert "tsan stress ok" in run.stdout
