"""Native C++ codec vs numpy golden model — byte-exact parity.

The native library is the host-runtime hot path (weight load repack + codecs);
it must be bit-identical to the portable numpy implementations, which are
themselves byte-golden with the reference converter (test_formats.py /
test_convert.py). Mirrors the reference's converter/writer-test.py golden-hex
approach plus nn-cpu-ops-test.cpp's quantize→dequantize round-trips.
"""

import numpy as np
import pytest

from dllama_tpu import native
from dllama_tpu.formats import quants

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no toolchain)")


def _cases():
    rng = np.random.default_rng(99)
    yield rng.standard_normal(32 * 17).astype(np.float32) * 3.0
    yield np.zeros(64, dtype=np.float32)                 # d == 0 path
    yield -np.abs(rng.standard_normal(96)).astype(np.float32)  # negative absmax
    big = rng.standard_normal(32 * 64).astype(np.float32)
    big[::7] *= 1e4                                      # wide dynamic range
    yield big
    # exact rounding ties for q80: x/d lands on k+0.5 → half-to-even
    t = np.full(32, 63.5 / 127.0, dtype=np.float32)
    t[0] = 1.0
    yield t


@pytest.mark.parametrize("i,x", list(enumerate(_cases())))
def test_q40_quantize_byte_identical(i, x):
    assert native.q40_quantize(x) == quants.quantize_q40_np(x)


@pytest.mark.parametrize("i,x", list(enumerate(_cases())))
def test_q80_quantize_byte_identical(i, x):
    assert native.q80_quantize(x) == quants.quantize_q80_np(x)


@pytest.mark.parametrize("i,x", list(enumerate(_cases())))
def test_dequantize_bit_identical(i, x):
    q40 = quants.quantize_q40_np(x)
    got = native.q40_dequantize(q40, x.size)
    np.testing.assert_array_equal(got, quants.dequantize_q40_np(q40, x.size))
    q80 = quants.quantize_q80_np(x)
    got = native.q80_dequantize(q80, x.size)
    np.testing.assert_array_equal(got, quants.dequantize_q80_np(q80, x.size))


def test_repack_kmajor_matches_numpy_transpose():
    rng = np.random.default_rng(5)
    rows, cols = 24, 96
    w = rng.standard_normal((rows, cols)).astype(np.float32)
    buf = quants.quantize_q40_np(w.reshape(-1))

    got_scales, got_codes = native.q40_repack_kmajor(buf, rows, cols)

    scales, codes = quants.unpack_q40(buf, rows * cols)
    want_scales = scales.reshape(rows, cols // 32).T.astype(np.float32)
    want_codes = codes.reshape(rows, cols).T
    np.testing.assert_array_equal(got_scales, want_scales)
    np.testing.assert_array_equal(got_codes, want_codes)
    assert got_scales.dtype == np.float32 and got_codes.dtype == np.int8


def test_threaded_matches_single_thread():
    rng = np.random.default_rng(8)
    x = rng.standard_normal(32 * 1024).astype(np.float32)
    assert native.q40_quantize(x, nthreads=4) == native.q40_quantize(x, nthreads=1)
    assert native.q80_quantize(x, nthreads=4) == native.q80_quantize(x, nthreads=1)
    buf = native.q40_quantize(x)
    rows, cols = 32, 1024
    s1, c1 = native.q40_repack_kmajor(buf, rows, cols, nthreads=1)
    s4, c4 = native.q40_repack_kmajor(buf, rows, cols, nthreads=4)
    np.testing.assert_array_equal(s1, s4)
    np.testing.assert_array_equal(c1, c4)


def test_dispatch_uses_native():
    """Public codecs and the native path agree end to end (mfile load path)."""
    rng = np.random.default_rng(3)
    x = rng.standard_normal(32 * 8).astype(np.float32)
    assert quants.quantize_q40(x) == quants.quantize_q40_np(x)
    assert quants.quantize_q80(x) == quants.quantize_q80_np(x)


def test_stale_on_host_signature_change(monkeypatch, tmp_path):
    """A .so built on another CPU (-march=native, shared FS) must be
    rebuilt, not dlopened into a potential SIGILL (advisor round-1
    finding)."""
    from dllama_tpu import native

    if native.get_lib() is None:
        pytest.skip("native toolchain unavailable")
    assert not native._stale()  # fresh build on this host
    assert native._so_path().exists()
    # another CPU -> different signature -> different filename: that host's
    # loader neither sees nor dlopens this build (atomic check-and-load)
    monkeypatch.setattr(native, "_host_signature", lambda: "otherhost")
    assert native._stale()
    assert not native._so_path().exists()
