"""Pipeline parallelism (pp mesh axis, parallel/pipeline.py) vs the
single-device oracle.

The correctness property is the same node-count invariance the whole test
strategy is built on (SURVEY.md §4): sharding the layer stack across pipeline
stages must not change logits or generated tokens. New capability — the
reference has no pipeline axis at all (SURVEY.md §2.2)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dllama_tpu.formats import mfile, tfile
from dllama_tpu.models import ModelConfig, forward, init_random_params
from dllama_tpu.parallel import use_plan
from dllama_tpu.parallel.api import make_mesh
from dllama_tpu.parallel.pipeline import validate_pp
from dllama_tpu.parallel.sharding import kv_cache_sharding, shard_params
from dllama_tpu.runtime import KVCache
from dllama_tpu.runtime.engine import InferenceEngine

from helpers import (byte_vocab_tokenizer, require_pinned_host,
                     tiny_header_params, write_tiny_model)


def _cfg(**kw):
    base = dict(
        arch=mfile.ArchType.LLAMA, dim=64, hidden_dim=96, n_layers=4,
        n_heads=8, n_kv_heads=4, head_dim=8, vocab_size=128, seq_len=32,
        norm_epsilon=1e-5, rope_theta=10000.0, rope_type=mfile.RopeType.LLAMA,
    )
    base.update(kw)
    return ModelConfig(**base)


@pytest.mark.parametrize("mesh_axes,B", [
    ({"pp": 2}, 1),
    ({"pp": 4}, 1),
    ({"pp": 2, "tp": 2}, 1),            # stages with tensor-parallel layers
    ({"dp": 2, "pp": 2, "tp": 2}, 2),   # 3-axis
])
def test_pp_forward_matches_unsharded(mesh_axes, B):
    """Prefill chunk + decode step through pipeline stages must equal the
    single-device run (logits and updated KV)."""
    cfg = _cfg()
    params = init_random_params(cfg, seed=3)
    rng = np.random.default_rng(9)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, 8)), dtype=jnp.int32)

    ref_logits, ref_kv = jax.jit(forward, static_argnums=1)(
        params, cfg, prompt, jnp.int32(0), KVCache.create(cfg, batch_size=B))
    nxt = jnp.argmax(ref_logits[:, -1:], axis=-1).astype(jnp.int32)
    ref_logits2, _ = jax.jit(forward, static_argnums=1)(
        params, cfg, nxt, jnp.int32(8), ref_kv)

    plan = make_mesh(mesh_axes)
    sharded = shard_params(plan, params)
    kv0 = KVCache.create(cfg, batch_size=B)
    kv = jax.device_put(kv0, kv_cache_sharding(plan, kv0))
    with use_plan(plan):
        logits, kv = jax.jit(forward, static_argnums=1)(
            sharded, cfg, prompt, jnp.int32(0), kv)
        np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                                   rtol=2e-5, atol=2e-6)
        nxt2 = jnp.argmax(logits[:, -1:], axis=-1).astype(jnp.int32)
        logits2, _ = jax.jit(forward, static_argnums=1)(
            sharded, cfg, nxt2, jnp.int32(8), kv)
    np.testing.assert_allclose(np.asarray(logits2), np.asarray(ref_logits2),
                               rtol=2e-5, atol=2e-6)


def test_pp_kv_cache_is_layer_sharded():
    """Each stage must hold only its own layers' KV slices."""
    cfg = _cfg()
    plan = make_mesh({"pp": 4})
    kv = KVCache.create(cfg)
    sh = kv_cache_sharding(plan, kv)
    assert sh.k.spec[0] == "pp"


def test_pp_moe_matches_unsharded():
    """MoE layers run stage-locally under pp (full expert set per stage)."""
    cfg = _cfg(n_experts=4, n_active_experts=2)
    params = init_random_params(cfg, seed=5)
    tokens = jnp.asarray([[3, 1, 4]], dtype=jnp.int32)
    ref, _ = jax.jit(forward, static_argnums=1)(
        params, cfg, tokens, jnp.int32(0), KVCache.create(cfg))

    plan = make_mesh({"pp": 2})
    sharded = shard_params(plan, params)
    kv0 = KVCache.create(cfg)
    kv = jax.device_put(kv0, kv_cache_sharding(plan, kv0))
    with use_plan(plan):
        got, _ = jax.jit(forward, static_argnums=1)(
            sharded, cfg, tokens, jnp.int32(0), kv)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


def test_validate_pp_rules():
    with pytest.raises(ValueError, match="divisible"):
        validate_pp(_cfg(), 3)  # 4 layers % 3 != 0
    from dataclasses import replace

    # pure pp composes with flash (per-stage plain kernel); pp×tp / pp×dp
    # cannot nest the pallas_call inside the manual shard_map
    validate_pp(replace(_cfg(), attn_impl="flash"), 2)
    with pytest.raises(ValueError, match="flash"):
        validate_pp(replace(_cfg(), attn_impl="flash"), 2, tp=2)
    with pytest.raises(ValueError, match="flash"):
        validate_pp(replace(_cfg(), attn_impl="flash"), 2, dp=2)


@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("pp")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(21)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=48,
                                               n_layers=4), rng)
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    return str(mpath), str(tpath)


def test_engine_pp_generation_matches_single(model_files):
    """End-to-end: the engine with --pp 2 (streamed loader places each
    stage's layer shards) generates the same tokens as the tp-only engine,
    for both greedy and fused sampled decode."""
    base = InferenceEngine(*model_files, tp=1)
    rb = base.generate("hello world", 6, stop_on_eos=False)
    ppe = InferenceEngine(*model_files, tp=1, pp=2)
    assert ppe.params.layers.wq.codes.sharding.spec[0] == "pp"
    rp = ppe.generate("hello world", 6, stop_on_eos=False)
    assert rb.tokens == rp.tokens

    s1 = InferenceEngine(*model_files, tp=1, temperature=0.8, seed=11)
    r1 = s1.generate("hello world", 6, stop_on_eos=False)
    s2 = InferenceEngine(*model_files, tp=2, pp=2, temperature=0.8, seed=11)
    r2 = s2.generate("hello world", 6, stop_on_eos=False)
    assert r1.tokens == r2.tokens


@pytest.mark.parametrize("pp,B", [(2, 4), (4, 4), (2, 2)])
def test_pp_microbatch_schedule_matches_unsharded(pp, B):
    """B >= pp and divisible: the GPipe microbatch schedule (stages work on
    different microbatches concurrently) must be value-identical to the
    single-device run."""
    cfg = _cfg()
    params = init_random_params(cfg, seed=7)
    rng = np.random.default_rng(11)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, 6)), dtype=jnp.int32)

    ref_logits, ref_kv = jax.jit(forward, static_argnums=1)(
        params, cfg, prompt, jnp.int32(0), KVCache.create(cfg, batch_size=B))
    nxt = jnp.argmax(ref_logits[:, -1:], axis=-1).astype(jnp.int32)
    ref_logits2, _ = jax.jit(forward, static_argnums=1)(
        params, cfg, nxt, jnp.int32(6), ref_kv)

    plan = make_mesh({"pp": pp})
    sharded = shard_params(plan, params)
    kv0 = KVCache.create(cfg, batch_size=B)
    kv = jax.device_put(kv0, kv_cache_sharding(plan, kv0))
    with use_plan(plan):
        logits, kv = jax.jit(forward, static_argnums=1)(
            sharded, cfg, prompt, jnp.int32(0), kv)
        np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                                   rtol=2e-5, atol=2e-6)
        nxt2 = jnp.argmax(logits[:, -1:], axis=-1).astype(jnp.int32)
        logits2, _ = jax.jit(forward, static_argnums=1)(
            sharded, cfg, nxt2, jnp.int32(6), kv)
    np.testing.assert_allclose(np.asarray(logits2), np.asarray(ref_logits2),
                               rtol=2e-5, atol=2e-6)


def test_pp_forward_with_forced_flash_matches_oracle():
    """Pure pp composes with the flash kernel: inside the manual pp
    shard_map each stage's arrays are fully local, so the plain kernel runs
    per stage (VERDICT r4 next #6). Forced + interpret off-TPU."""
    cfg = _cfg(seq_len=128, attn_impl="flash")
    params = init_random_params(cfg, seed=6)
    rng = np.random.default_rng(2)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 8)), dtype=jnp.int32)

    from dataclasses import replace

    cfg_oracle = replace(cfg, attn_impl="xla")
    ref, _ = jax.jit(forward, static_argnums=1)(
        params, cfg_oracle, prompt, jnp.int32(0), KVCache.create(cfg_oracle))

    plan = make_mesh({"pp": 2})
    sharded = shard_params(plan, params)
    kv0 = KVCache.create(cfg)
    kv = jax.device_put(kv0, kv_cache_sharding(plan, kv0))
    with use_plan(plan):
        got, _ = jax.jit(forward, static_argnums=1)(
            sharded, cfg, prompt, jnp.int32(0), kv)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("mesh_axes", [
    {"pp": 2, "sp": 2},
    {"pp": 2, "sp": 2, "tp": 2},
])
def test_pp_sp_forward_matches_unsharded(mesh_axes):
    """pp × sp: inside the pp-manual region sp stays an AUTO axis, so the
    per-stage attention runs the XLA oracle over the seq-sharded cache —
    prefill + decode parity with the single-device run."""
    cfg = _cfg(seq_len=128)
    params = init_random_params(cfg, seed=13)
    rng = np.random.default_rng(5)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 8)), dtype=jnp.int32)

    ref, ref_kv = jax.jit(forward, static_argnums=1)(
        params, cfg, prompt, jnp.int32(0), KVCache.create(cfg))
    nxt = jnp.argmax(ref[:, -1:], axis=-1).astype(jnp.int32)
    ref2, _ = jax.jit(forward, static_argnums=1)(
        params, cfg, nxt, jnp.int32(8), ref_kv)

    plan = make_mesh(mesh_axes)
    sharded = shard_params(plan, params)
    kv0 = KVCache.create(cfg)
    kv = jax.device_put(kv0, kv_cache_sharding(plan, kv0))
    with use_plan(plan):
        got, kv = jax.jit(forward, static_argnums=1)(
            sharded, cfg, prompt, jnp.int32(0), kv)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-6)
        nxt2 = jnp.argmax(got[:, -1:], axis=-1).astype(jnp.int32)
        got2, _ = jax.jit(forward, static_argnums=1)(
            sharded, cfg, nxt2, jnp.int32(8), kv)
    np.testing.assert_allclose(np.asarray(got2), np.asarray(ref2),
                               rtol=2e-5, atol=2e-6)


def test_engine_pp_sp_generation_matches_single(model_files):
    """End-to-end: --pp 2 --sp 2 engine generates the same tokens as tp=1."""
    base = InferenceEngine(*model_files, tp=1)
    want = base.generate("hello world", 6, stop_on_eos=False).tokens
    base.close()
    eng = InferenceEngine(*model_files, tp=1, pp=2, sp=2)
    got = eng.generate("hello world", 6, stop_on_eos=False).tokens
    eng.close()
    assert got == want


def test_engine_pp_offload_matches_single(model_files):
    """--pp 2 composes with --weight-mode offload: each stage's layer shard
    stays in pinned host memory (placement asserted) and streams per layer
    inside the stage scan; generation matches the resident tp=1 engine."""
    require_pinned_host()
    import jax

    base = InferenceEngine(*model_files, tp=1)
    want = base.generate("hello world", 6, stop_on_eos=False).tokens
    base.close()
    eng = InferenceEngine(*model_files, tp=1, pp=2, weight_mode="offload")
    kinds = {leaf.sharding.memory_kind
             for leaf in jax.tree_util.tree_leaves(eng.params.layers)}
    assert kinds == {"pinned_host"}
    assert eng.params.layers.wq.codes.sharding.spec[0] == "pp"
    got = eng.generate("hello world", 6, stop_on_eos=False).tokens
    eng.close()
    assert got == want


def test_validate_pp_rejects_forced_flash_under_sp():
    from dataclasses import replace

    with pytest.raises(ValueError, match="flash"):
        validate_pp(replace(_cfg(), attn_impl="flash"), 2, sp=2)
