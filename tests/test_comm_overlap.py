"""Overlapped quantized multichip decode (ISSUE 8) — engine-level coverage.

The collective-level invariants (bit-exact chunking, q80 ring == reference
merge, poison site) live in tests/test_qcollectives.py; here the knob is
exercised through the REAL engine on the CPU mesh: token parity against
overlap-off, startup refusals, the compile ledger staying quiet, and the
new collective telemetry family."""

import numpy as np
import pytest

from dllama_tpu.runtime import introspection
from dllama_tpu.runtime import telemetry as tm
from dllama_tpu.runtime.engine import InferenceEngine

from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model


@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("overlap")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(0x51)
    write_tiny_model(mpath, tiny_header_params(
        dim=256, hidden_dim=512, n_layers=2, n_heads=4, n_kv_heads=2,
        head_dim=64, vocab_size=268, seq_len=128), rng)
    from dllama_tpu.formats import tfile

    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    return str(mpath), str(tpath)


def _tokens(model_files, *, overlap, n=12, **kw):
    mpath, tpath = model_files
    eng = InferenceEngine(mpath, tpath, tp=2, comm_overlap=overlap,
                          temperature=0.0, **kw)
    try:
        return eng.generate([1, 5, 9, 13], n, stop_on_eos=False).tokens
    finally:
        eng.close()


def test_auto_resolves_chunks_and_tokens_identical_to_off(model_files):
    """The ISSUE acceptance invariant: on a >=2-device mesh, decode with
    --comm-overlap auto produces tokens IDENTICAL to overlap-off for the
    f32 wire (the ring's rank-order sums replace the GSPMD psum without
    changing what the model emits)."""
    mpath, tpath = model_files
    eng = InferenceEngine(mpath, tpath, tp=2, comm_overlap="auto")
    assert eng.cfg.comm_overlap == 4  # dim 256 -> four 64-wide chunks
    eng.close()
    assert _tokens(model_files, overlap="auto") \
        == _tokens(model_files, overlap="off")


def test_chunked_decode_dispatch_rides_the_overlapped_merge(model_files):
    """--decode-chunk fuses K steps into one scan whose body is the same
    T=1 forward — the ring merges trace inside it and the chunked stream
    stays identical to overlap-off."""
    assert _tokens(model_files, overlap="auto", decode_chunk=4) \
        == _tokens(model_files, overlap="off", decode_chunk=4)


def test_explicit_n_needs_tp_and_divisibility(model_files):
    mpath, tpath = model_files
    with pytest.raises(ValueError, match="tensor-parallel"):
        InferenceEngine(mpath, tpath, tp=1, comm_overlap=4)
    with pytest.raises(ValueError, match="does not divide"):
        InferenceEngine(mpath, tpath, tp=2, comm_overlap=7)
    # auto degrades to off on one device instead of refusing
    eng = InferenceEngine(mpath, tpath, tp=1, comm_overlap="auto")
    assert eng.cfg.comm_overlap == 0
    eng.close()


def test_unsupported_combos_refused_at_startup(model_files, monkeypatch):
    mpath, tpath = model_files
    with pytest.raises(ValueError, match="--sp"):
        InferenceEngine(mpath, tpath, tp=2, sp=2, comm_overlap=4)
    with pytest.raises(ValueError, match="--pp"):
        InferenceEngine(mpath, tpath, tp=2, pp=2, comm_overlap=4)
    with pytest.raises(ValueError, match="offload"):
        InferenceEngine(mpath, tpath, tp=2, weight_mode="offload",
                        comm_overlap=4)
    # turbo weights skip the overlapped merge entirely — a knob that
    # would silently do nothing (while the banner and the bytes counter
    # claim otherwise) must refuse, not lie
    monkeypatch.setenv("DLLAMA_TPU_QUANT_MODE", "turbo16")
    with pytest.raises(ValueError, match="turbo"):
        InferenceEngine(mpath, tpath, tp=2, comm_overlap=4)
    monkeypatch.delenv("DLLAMA_TPU_QUANT_MODE")
    with pytest.raises(ValueError, match="off.*auto.*integer"):
        InferenceEngine(mpath, tpath, tp=2, comm_overlap="bananas")


def test_pricing_tracks_per_merge_fallback(tmp_path):
    """A merge whose quantized shard can't split its scale rows falls
    back to the monolithic path at trace time — the bytes counter must
    price THAT merge as the all-reduce it actually is (hidden_dim 96 at
    tp=2 → 48-row shards, not 32-divisible; q_dim 64 still overlaps)."""
    mpath, tpath = tmp_path / "m.m", tmp_path / "t.t"
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=64),
                     np.random.default_rng(5))
    from dllama_tpu.formats import tfile

    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    eng = InferenceEngine(str(mpath), str(tpath), tp=2, comm_overlap="auto")
    try:
        assert eng.cfg.comm_overlap == 2  # dim 64 -> two 32-wide chunks
        traffic = {(op, w): b for op, w, b in eng._wire_traffic}
        # wo (q_dim 64): overlapped ring; w2 (hidden 96): monolithic
        assert ("ppermute", "f32") in traffic
        assert ("all_reduce", "f32") in traffic
    finally:
        eng.close()


def test_zero_post_steady_compiles_with_overlap_enabled(model_files):
    """The chunked ring is STATIC trace config (cfg.comm_overlap): once the
    program family is warm, further generations must not retrace — the
    continuous-serving requirement every feature in this tree meets."""
    mpath, tpath = model_files
    eng = InferenceEngine(mpath, tpath, tp=2, comm_overlap="auto")
    try:
        eng.generate([1, 5, 9, 13], 6, stop_on_eos=False)  # warm
        eng.reset()
        c0 = introspection.ledger().compile_count(eng.introspection_scope)
        eng.generate([2, 6, 8, 12], 6, stop_on_eos=False)
        assert introspection.ledger().compile_count(
            eng.introspection_scope) == c0, \
            "post-steady recompile with --comm-overlap enabled"
    finally:
        eng.close()


def test_collective_bytes_counter_prices_decode_tokens(model_files):
    """dllama_collective_bytes_total{op,wire}: each emitted decode token
    charges the analytic col-split wire bytes fixed at construction
    (qcollectives.wire_traffic_model x 2 merges x n_layers)."""
    mpath, tpath = model_files
    eng = InferenceEngine(mpath, tpath, tp=2, comm_overlap="auto")
    try:
        [(op, wire, per_tok)] = eng._wire_traffic
        assert (op, wire) == ("ppermute", "f32")
        # 2 merges/layer x 2 layers x (n-1) x 4 B/value x dim
        assert per_tok == pytest.approx(4 * 1 * 4.0 * 256)
        ctr = tm.registry().counter(tm.COLLECTIVE_BYTES)
        b0 = ctr.total(op=op, wire=wire)
        n = len(eng.generate([1, 5, 9, 13], 8, stop_on_eos=False).tokens)
        assert ctr.total(op=op, wire=wire) == pytest.approx(
            b0 + n * per_tok)
    finally:
        eng.close()


def test_overlap_off_prices_the_gspmd_all_reduce(model_files):
    mpath, tpath = model_files
    eng = InferenceEngine(mpath, tpath, tp=2, comm_overlap="off")
    try:
        [(op, wire, per_tok)] = eng._wire_traffic
        assert (op, wire) == ("all_reduce", "f32")
        assert per_tok == pytest.approx(4 * 2 * (2 - 1) / 2 * 4.0 * 256)
    finally:
        eng.close()


def test_measure_split_publishes_exposed_comm_gauge(model_files):
    """dllama_comm_exposed_ms: measure_split's capture classifies the
    EXPOSED collective wall (sync lane time not covered by concurrent
    compute) and publishes it next to the sync fraction. On the CPU
    thunk runtime collectives execute synchronously, so exposure is
    positive whenever the program has collectives at all."""
    mpath, tpath = model_files
    eng = InferenceEngine(mpath, tpath, tp=2, comm_overlap="auto")
    try:
        eng.generate([1, 5, 9], 4, stop_on_eos=False)  # warm + position
        split = eng.measure_split()
        assert split.exposed_ms >= 0.0
        assert split.exposed_ms <= split.sync_ms + 1e-9
        g = tm.registry().gauge(tm.COMM_EXPOSED_MS)
        assert g.value() == pytest.approx(split.exposed_ms)
    finally:
        eng.close()


def test_multihost_fingerprint_includes_overlap(model_files):
    """A root/worker --comm-overlap mismatch compiles different programs
    and must be caught by the cluster fingerprint, not a collective
    deadlock. Single-process: just pin the field's presence."""
    mpath, tpath = model_files
    eng = InferenceEngine(mpath, tpath, tp=2, comm_overlap="auto")
    try:
        assert eng.cfg.comm_overlap == 4  # the value the fingerprint ships
    finally:
        eng.close()


def test_spec_lookup_beyond_overlap_width_refused(model_files):
    """A K+1-wide verify past the overlap width gate would trace the
    monolithic psum while greedy traces the ring — refusing preserves the
    engine's spec≡greedy bit-identity invariant."""
    mpath, tpath = model_files
    with pytest.raises(ValueError, match="spec-lookup"):
        InferenceEngine(mpath, tpath, tp=2, comm_overlap=4, spec_lookup=16)
    # inside the width gate the combo stays legal
    eng = InferenceEngine(mpath, tpath, tp=2, comm_overlap=4, spec_lookup=4)
    eng.close()
