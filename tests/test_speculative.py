"""Prompt-lookup speculative decode: exactness and acceptance.

Speculative greedy must be BIT-IDENTICAL to plain greedy on every input —
the verify step accepts exactly the prefix the model itself would have
produced (models.llama.verify_step) — while a self-repeating prompt must
show real multi-token acceptance (fewer dispatches than tokens). The
reference has no speculative path (one token per step, dllama.cpp:88-99);
this is a TPU-economics feature: decode is HBM-bound, so tokens per weight
read is the lever.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.formats import quants, tfile
from dllama_tpu.models import ModelConfig, init_random_params
from dllama_tpu.models.llama import greedy_step, verify_step
from dllama_tpu.runtime import KVCache
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.runtime.speculative import NgramProposer

from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model


# -- proposer ---------------------------------------------------------------


def test_proposer_drafts_previous_continuation():
    p = NgramProposer(3)
    p.extend([1, 2, 3, 4, 9, 1, 2])  # trailing bigram (1,2) seen before at ..3,4
    assert p.draft() == [3, 4, 9]


def test_proposer_pads_short_continuation():
    p = NgramProposer(4)
    p.extend([1, 2, 3, 1, 2])  # earlier (1,2) is followed only by [3, 1, 2]
    assert p.draft() == [3, 1, 2, 2]


def test_proposer_no_signal_repeats_last():
    p = NgramProposer(2)
    p.extend([5, 6, 7])
    assert p.draft() == [7, 7]
    assert NgramProposer(2).draft() == [0, 0]


def test_proposer_self_overlap():
    p = NgramProposer(3)
    p.extend([8, 8, 8, 8])  # overlapping (8,8): drafts self-extension
    assert p.draft() == [8, 8, 8]


def test_proposer_trigram_beats_bigram():
    """Two continuations of the bigram (1,2) exist; the trailing TRIGRAM
    (9,1,2) disambiguates to the second one."""
    p = NgramProposer(2)
    p.extend([0, 1, 2, 7, 7,    # (1,2) -> 7,7  (bigram candidate)
              9, 1, 2, 5, 5,    # (9,1,2) -> 5,5 (trigram match)
              9, 1, 2])
    assert p.draft() == [5, 5]


def test_proposer_bigram_fallback_when_trigram_unseen():
    p = NgramProposer(2)
    p.extend([4, 1, 2, 7, 7, 3, 1, 2])  # trailing trigram (3,1,2) unseen
    assert p.draft() == [7, 7]


# -- verify_step vs sequential greedy ---------------------------------------


def _cfg():
    from dllama_tpu.formats import mfile

    return ModelConfig(
        arch=mfile.ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
        n_heads=4, n_kv_heads=2, head_dim=16, vocab_size=256, seq_len=64,
        norm_epsilon=1e-5, rope_theta=10000.0, rope_type=mfile.RopeType.LLAMA)


@pytest.mark.parametrize("trial", range(3))
def test_verify_matches_sequential_greedy(trial):
    cfg = _cfg()
    params = init_random_params(cfg, seed=trial)
    rng = np.random.default_rng(trial)
    token = int(rng.integers(0, cfg.vocab_size))
    drafts = [int(t) for t in rng.integers(0, cfg.vocab_size, 4)]
    pos = 0

    # sequential oracle
    kv = KVCache.create(cfg)
    step = jax.jit(greedy_step, static_argnums=1)
    seq = []
    t = token
    for i in range(len(drafts) + 1):
        nxt, kv = step(params, cfg, jnp.asarray([[t]]), jnp.int32(pos + i), kv)
        seq.append(int(nxt[0]))
        t = seq[-1]

    # one verify dispatch
    kv2 = KVCache.create(cfg)
    ver = jax.jit(verify_step, static_argnums=1)
    n_acc, preds, _ = ver(params, cfg,
                          jnp.asarray([[token, *drafts]], jnp.int32),
                          jnp.int32(pos), kv2)
    n_acc = int(n_acc[0])
    preds = np.asarray(preds)[0]

    # the accepted run equals the sequential transcript prefix
    assert [int(x) for x in preds[: n_acc + 1]] == seq[: n_acc + 1]
    # acceptance is exactly the longest draft prefix matching the oracle
    expect_acc = 0
    for i, d in enumerate(drafts):
        if d == seq[i]:
            expect_acc += 1
        else:
            break
    assert n_acc == expect_acc


# -- engine end-to-end ------------------------------------------------------


@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("spec")
    tok = byte_vocab_tokenizer()
    hdr = tiny_header_params(vocab_size=tok.vocab_size, seq_len=128,
                             weight_type=quants.Q40)
    write_tiny_model(d / "m.m", hdr, np.random.default_rng(11))
    tfile.write_tfile(d / "t.t", tok)
    return str(d / "m.m"), str(d / "t.t")


def _gen(model_files, prompt, steps, **kw):
    m, t = model_files
    eng = InferenceEngine(m, t, temperature=0.0, **kw)
    try:
        out = eng.generate(prompt, steps, stop_on_eos=False)
    finally:
        eng.close()
    return out


@pytest.mark.parametrize("prompt", ["the quick brown fox", "ababababababab"])
def test_speculative_identical_to_plain_greedy(model_files, prompt):
    plain = _gen(model_files, prompt, 48)
    spec = _gen(model_files, prompt, 48, spec_lookup=4)
    assert spec.tokens == plain.tokens
    assert spec.text == plain.text


def test_speculative_accepts_on_repetitive_output(model_files):
    """Greedy decode on a tiny random model degenerates into a cycle; the
    proposer must exploit it: strictly fewer dispatches than tokens."""
    spec = _gen(model_files, "hello hello hello hello", 64, spec_lookup=4)
    pred_steps = [s for s in spec.steps if s.kind == "pred"]
    n_tokens = sum(s.n_tokens for s in pred_steps)
    assert n_tokens == len(spec.tokens)
    assert len(pred_steps) < n_tokens, (
        f"no acceptance: {len(pred_steps)} dispatches for {n_tokens} tokens")


def test_spec_and_chunk_are_exclusive(model_files):
    m, t = model_files
    with pytest.raises(ValueError, match="exclusive"):
        InferenceEngine(m, t, temperature=0.0, spec_lookup=4, decode_chunk=8)


def test_spec_ignored_at_temperature(model_files):
    """temperature>0 keeps the sampled path (speculative is greedy-only)."""
    m, t = model_files
    eng = InferenceEngine(m, t, temperature=0.9, seed=7, spec_lookup=4)
    try:
        a = eng.generate("the quick", 24, stop_on_eos=False).tokens
    finally:
        eng.close()
    eng2 = InferenceEngine(m, t, temperature=0.9, seed=7)
    try:
        b = eng2.generate("the quick", 24, stop_on_eos=False).tokens
    finally:
        eng2.close()
    assert a == b


def test_ragged_verify_matches_per_row_oracles():
    """ragged_verify_step row-by-row: greedy rows equal a solo verify_step
    at that row's position; sampled rows equal sampled_token on the
    position-0 logits with n_acc forced to 0."""
    from dllama_tpu.models.llama import ragged_verify_step
    from dllama_tpu.ops.sampling import sampled_token

    cfg = _cfg()
    params = init_random_params(cfg, seed=5)
    rng = np.random.default_rng(5)
    B, K = 3, 3
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, K + 1)), jnp.int32)
    pos = jnp.asarray([4, 0, 9], jnp.int32)
    temps = jnp.asarray([0.0, 0.8, 0.0], jnp.float32)
    topps = jnp.full((B,), 0.9, jnp.float32)
    coins = jnp.asarray([0.0, 0.37, 0.0], jnp.float32)

    kv = KVCache.create(cfg, batch_size=B)
    n_acc, preds, _ = jax.jit(ragged_verify_step, static_argnums=1)(
        params, cfg, toks, pos, kv, temps, topps, coins)
    n_acc, preds = np.asarray(n_acc), np.asarray(preds)

    for b in (0, 2):  # greedy rows: equal a solo single-row verify
        kv1 = KVCache.create(cfg)
        na1, p1, _ = jax.jit(verify_step, static_argnums=1)(
            params, cfg, toks[b:b + 1], pos[b], kv1)
        assert int(na1[0]) == n_acc[b]
        np.testing.assert_array_equal(np.asarray(p1)[0], preds[b])

    # sampled row: n_acc 0 and first token from the row's own coin
    assert n_acc[1] == 0
    from dllama_tpu.models import forward

    kv1 = KVCache.create(cfg)
    logits, _ = jax.jit(forward, static_argnums=1)(
        params, cfg, toks[1:2], pos[1], kv1)
    want = sampled_token(logits[:, 0], jnp.float32(0.8), jnp.float32(0.9),
                         jnp.float32(0.37))
    assert int(want[0]) == preds[1, 0]


def test_speculative_on_moe_model(tmp_path):
    """verify_step is forward-based, so speculation rides MoE models too:
    identical to plain greedy."""
    m, t = tmp_path / "m.m", tmp_path / "t.t"
    write_tiny_model(m, tiny_header_params(vocab_size=268, seq_len=96,
                                           n_experts=4, n_active_experts=2),
                     np.random.default_rng(11))
    tfile.write_tfile(t, byte_vocab_tokenizer())
    plain = InferenceEngine(str(m), str(t), temperature=0.0)
    want = plain.generate("hello hello", 20, stop_on_eos=False).tokens
    plain.close()
    spec = InferenceEngine(str(m), str(t), temperature=0.0, spec_lookup=3)
    got = spec.generate("hello hello", 20, stop_on_eos=False).tokens
    spec.close()
    assert got == want


@pytest.mark.parametrize("tp", [1, 2])
def test_speculative_under_sp_matches_plain(model_files, tp):
    """Speculation composes with sequence parallelism (verify rides the ring
    attention path at T=K+1): identical to plain greedy under sp=2."""
    m, t = model_files
    plain = InferenceEngine(m, t, sp=2, tp=tp, temperature=0.0)
    want = plain.generate("hello hello hello", 12, stop_on_eos=False).tokens
    plain.close()
    spec = InferenceEngine(m, t, sp=2, tp=tp, temperature=0.0, spec_lookup=2)
    got = spec.generate("hello hello hello", 12, stop_on_eos=False).tokens
    spec.close()
    assert got == want


# -- rejection sampling (runtime/speculative.spec_decide) --------------------


def test_spec_decide_zero_draft_is_plain_sampled_step():
    """A zero-length draft degrades to the plain sampled decode step
    BIT-exactly: position 0's sample runs ops.sampling.sampled_token on
    the position-0 logits with position 0's coin (``acoins[:, 0]`` — the
    next draw of the request's sequential coin stream, the same draw
    the non-speculative step would consume)."""
    from dllama_tpu.ops.sampling import sampled_token
    from dllama_tpu.runtime.speculative import spec_decide

    rng = np.random.default_rng(3)
    B, K, V = 4, 3, 64
    logits = jnp.asarray(rng.standard_normal((B, K + 1, V)), jnp.float32)
    tokens = jnp.asarray(rng.integers(0, V, (B, K + 1)), jnp.int32)
    temps = jnp.asarray([0.6, 0.9, 1.3, 0.8], jnp.float32)
    topps = jnp.asarray([0.9, 0.5, 1.0, 0.95], jnp.float32)  # incl. topp=1
    acoins = jnp.asarray(rng.random((B, K)), jnp.float32)
    n_acc, out = jax.jit(spec_decide)(
        logits, tokens, jnp.zeros(B, jnp.int32), temps, topps,
        acoins, jnp.asarray(rng.random(B), jnp.float32))
    np.testing.assert_array_equal(np.asarray(n_acc), 0)
    want = sampled_token(logits[:, 0], temps, topps, acoins[:, 0])
    np.testing.assert_array_equal(np.asarray(out)[:, 0], np.asarray(want))


def test_spec_decide_greedy_rows_match_exact_prefix_rule():
    """Greedy rows (temp <= 0) keep the exact-match acceptance capped at
    the row's draft length, and emit the model's own argmax run."""
    from dllama_tpu.runtime.speculative import spec_decide

    rng = np.random.default_rng(7)
    B, K, V = 3, 4, 32
    logits = jnp.asarray(rng.standard_normal((B, K + 1, V)), jnp.float32)
    preds = np.argmax(np.asarray(logits), -1)
    # row 0: drafts equal the model's own predictions (full acceptance up
    # to lens); row 1: first draft wrong; row 2: lens caps acceptance
    tokens = np.zeros((B, K + 1), np.int32)
    tokens[:, 1:] = preds[:, :-1]
    tokens[1, 1] = (preds[1, 0] + 1) % V
    lens = jnp.asarray([K, K, 2], jnp.int32)
    n_acc, out = jax.jit(spec_decide)(
        logits, jnp.asarray(tokens), lens,
        jnp.zeros(B, jnp.float32), jnp.full((B,), 0.9, jnp.float32),
        jnp.zeros((B, K), jnp.float32), jnp.zeros(B, jnp.float32))
    assert list(np.asarray(n_acc)) == [K, 0, 2]
    np.testing.assert_array_equal(np.asarray(out), preds)


def test_spec_decide_distribution_preserved_tv_bound():
    """The satellite's statistical acceptance: the emitted next-token
    distribution of spec-sampled decode equals non-spec sampling within
    a total-variation bound on a toy model (fixed seeds). Exact-match
    verify emits the plain-decode sample at every position, so the
    marginal IS p_target by construction (and the accept rate equals
    p_target(draft)); the empirical TV distance over N draws
    concentrates within ~sqrt(V/N)."""
    from dllama_tpu.ops.sampling import sampled_token
    from dllama_tpu.runtime.speculative import spec_decide

    rng = np.random.default_rng(17)
    V, N, draft = 16, 20000, 3
    logits = jnp.asarray(rng.standard_normal((1, 2, V)) * 2.0, jnp.float32)
    toks = jnp.asarray([[0, draft]], jnp.int32)
    lens = jnp.asarray([1], jnp.int32)
    temps = jnp.asarray([0.8], jnp.float32)
    topps = jnp.asarray([0.9], jnp.float32)

    def one(ac, fc):
        return spec_decide(logits, toks, lens, temps, topps,
                           ac[None, None], fc[None])

    acs = jnp.asarray(rng.random(N), jnp.float32)
    fcs = jnp.asarray(rng.random(N), jnp.float32)
    n_accs, outs = jax.jit(jax.vmap(one))(acs, fcs)
    n_accs, outs = np.asarray(n_accs)[:, 0], np.asarray(outs)[:, 0]
    first = np.where(n_accs >= 1, draft, outs[:, 0])

    plain = jax.jit(jax.vmap(
        lambda c: sampled_token(logits[:, 0], temps, topps, c)))(
        jnp.asarray(rng.random(N), jnp.float32))
    plain = np.asarray(plain)[:, 0]

    p_spec = np.bincount(first, minlength=V) / N
    p_plain = np.bincount(plain, minlength=V) / N
    tv = 0.5 * np.abs(p_spec - p_plain).sum()
    assert tv < 0.03, f"TV distance {tv:.4f} — distribution not preserved"
    # and the accept rate itself matches the drafted token's target prob
    from dllama_tpu.runtime.speculative import target_sampling_probs

    p_d = float(target_sampling_probs(logits[:, 0], temps, topps)[0, draft])
    assert abs(float((n_accs >= 1).mean()) - p_d) < 0.02


def test_spec_coins_consumed_rule():
    """The host commit rule: one coin per EMITTED token (n_acc accepted
    drafts + the position-n_acc sample), independent of draft length —
    the stream-position invariant resume fast-forwards on."""
    from dllama_tpu.runtime.speculative import spec_coins_consumed

    assert spec_coins_consumed(0, 0) == 1   # no draft: plain decode's coin
    assert spec_coins_consumed(0, 4) == 1   # first draft wrong: 1 emitted
    assert spec_coins_consumed(2, 4) == 3   # 2 accepted + the sample
    assert spec_coins_consumed(4, 4) == 5   # all accepted + bonus


def test_speculative_identical_under_turbo(model_files, monkeypatch):
    """Speculation composes with turbo numerics: a8 quantizes activations
    per ROW, so each token position quantizes identically in a [B, K+1]
    verify and a [B, 1] decode dispatch — greedy identity holds modulo the
    same dispatch-shape ulp hazard the fast path documents (asserted
    exactly here on CPU, like the fast-mode identity tests)."""
    monkeypatch.setenv("DLLAMA_TPU_QUANT_MODE", "turbo")
    plain = _gen(model_files, "the quick brown fox", 32,
                 compute_dtype="bfloat16")
    spec = _gen(model_files, "the quick brown fox", 32, spec_lookup=4,
                compute_dtype="bfloat16")
    assert spec.tokens == plain.tokens
