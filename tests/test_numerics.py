"""Numerics observatory tier (runtime/numerics.py): activation taps,
the non-finite tripwire + fail-fast, the golden canary drift sentinel
(including the ISSUE-5 acceptance criteria: a patched weight trips
``dllama_canary_drift_total`` with the divergent layer named, and a
taps-off canary adds ZERO compiles after steady state — ledger-asserted),
the offline quant-error audit, and the ``/debug/numerics`` endpoint."""

import json
import math
import threading
import urllib.request

import numpy as np
import pytest

from dllama_tpu.formats import mfile, tfile
from dllama_tpu.runtime import failpoints as fp
from dllama_tpu.runtime import introspection, numerics
from dllama_tpu.runtime import telemetry as tm
from dllama_tpu.runtime.engine import InferenceEngine

from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.registry().clear()
    yield
    fp.registry().clear()


@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("numerics")
    mpath, tpath = d / "m.m", d / "t.t"
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96),
                     np.random.default_rng(17))
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    return str(mpath), str(tpath)


def _engine(model_files, **kw):
    kw.setdefault("tp", 1)
    kw.setdefault("temperature", 0.0)
    kw.setdefault("seed", 3)
    return InferenceEngine(*model_files, **kw)


# -- activation taps ----------------------------------------------------------


def test_tapped_forward_is_bit_identical_and_stats_shaped(model_files):
    """forward_with_taps returns the SAME logits as the plain forward
    (the taps are observers, never participants) plus a stats pytree
    with every documented site, per-layer leaves, zero non-finite
    counts on a healthy model, and a nonzero Q80 roundtrip error."""
    plain = _engine(model_files)
    tapped = _engine(model_files, numerics_taps=True)
    try:
        ids = plain.tokenizer.encode("hello world", is_start=True)
        lp, _ = plain.prefill(ids)
        lt, _ = tapped.prefill(ids)
        np.testing.assert_array_equal(np.asarray(lp), np.asarray(lt))

        snap = numerics.debug_snapshot(tapped)
        taps = snap["taps"]
        assert sorted(taps) == sorted(numerics.TAP_SITES)
        n_layers = tapped.cfg.n_layers
        for site in ("attn_out", "mlp_out"):
            assert len(taps[site]["rms"]) == n_layers
            assert taps[site]["nonfinite"] == 0
            assert all(v > 0 for v in taps[site]["rms"])
            assert all(v > 0 for v in taps[site]["q80_err"])
        assert taps["logits"]["nonfinite"] == 0
        reg = tm.registry()
        assert reg.gauge(tm.ACTIVATION_RMS).value(site="mlp_out") > 0
        assert reg.gauge(tm.ACTIVATION_ABSMAX).value(site="logits") > 0
        assert reg.gauge(tm.Q80_ROUNDTRIP_ERROR).value(site="attn_out") > 0
    finally:
        plain.close()
        tapped.close()


def test_taps_flag_rejected_under_multihost(model_files):
    with pytest.raises(ValueError, match="numerics-taps"):
        _engine(model_files, numerics_taps=True, multihost=True)


# -- non-finite tripwire ------------------------------------------------------


def test_logits_failpoint_poisons_decode_and_counts(model_files):
    """Armed `logits:nonfinite` → the fused in-graph tripwire counts the
    poisoned decode dispatch (site=decode) while the default mode still
    emits a (garbage) token — count, don't alter behavior."""
    eng = _engine(model_files)
    nf = tm.registry().counter(tm.NONFINITE)
    fired = tm.registry().counter(tm.FAILPOINTS_FIRED)
    before, f0 = nf.total(site="decode"), fired.total(name="logits")
    try:
        ids = eng.tokenizer.encode("hello", is_start=True)
        eng.prefill(ids[:-1])
        fp.arm("logits", "nonfinite", times=1)
        tok = eng.next_token(ids[-1])
        assert 0 <= tok < eng.cfg.vocab_size  # a token WAS emitted
        assert nf.total(site="decode") == before + 1
        assert fired.total(name="logits") == f0 + 1
        # disarmed again: clean steps don't count
        eng.next_token(tok)
        assert nf.total(site="decode") == before + 1
    finally:
        eng.close()


def test_failfast_raises_numerics_error_naming_site(model_files):
    eng = _engine(model_files, numerics_failfast=True)
    try:
        ids = eng.tokenizer.encode("hello", is_start=True)
        eng.prefill(ids[:-1])
        fp.arm("logits", "nonfinite", times=1)
        with pytest.raises(numerics.NumericsError, match="site=decode"):
            eng.next_token(ids[-1])
        # the failpoint consumed itself: the engine still serves
        tok = eng.next_token(ids[-1])
        assert 0 <= tok < eng.cfg.vocab_size
    finally:
        eng.close()


def test_tripwire_covers_chunked_and_verify_dispatches(model_files):
    """The guarded chunk and speculative-verify programs carry the same
    fused count (site=decode / site=verify)."""
    nf = tm.registry().counter(tm.NONFINITE)
    eng = _engine(model_files, decode_chunk=4)
    try:
        ids = eng.tokenizer.encode("hello", is_start=True)
        eng.prefill(ids[:-1])
        d0 = nf.total(site="decode")
        fp.arm("logits", "nonfinite", times=1)
        toks = eng.decode_chunk_tokens(ids[-1], 4)
        assert len(toks) == 4
        assert nf.total(site="decode") == d0 + 1
    finally:
        eng.close()
    eng = _engine(model_files, spec_lookup=2)
    try:
        ids = eng.tokenizer.encode("hello", is_start=True)
        eng.prefill(ids[:-1])
        v0 = nf.total(site="verify")
        fp.arm("logits", "nonfinite", times=1)
        run = eng.speculative_tokens(ids[-1], [1, 2])
        assert 1 <= len(run) <= 3
        assert nf.total(site="verify") == v0 + 1
    finally:
        eng.close()


def test_poison_inf_mode(model_files):
    """`arm(..., mode="inf")` injects Inf instead of NaN — both are
    non-finite, both trip."""
    eng = _engine(model_files)
    nf = tm.registry().counter(tm.NONFINITE)
    before = nf.total(site="decode")
    try:
        ids = eng.tokenizer.encode("hi", is_start=True)
        eng.prefill(ids[:-1])
        fp.arm("logits", "nonfinite", times=1, mode="inf")
        eng.next_token(ids[-1])
        assert nf.total(site="decode") == before + 1
    finally:
        eng.close()


# -- golden canary drift sentinel --------------------------------------------


def test_canary_clean_replay_does_not_drift(model_files):
    eng = _engine(model_files, numerics_taps=True)
    try:
        c = numerics.CanarySentinel(eng, interval_s=0.0)
        c.ensure_golden()
        drift0 = tm.registry().counter(tm.CANARY_DRIFT).total()
        for _ in range(2):
            res = c.run()
            assert res["drift"] is False
        assert tm.registry().counter(tm.CANARY_DRIFT).total() == drift0
        st = c.status()
        assert st["golden_recorded"] and st["runs"] >= 2
        assert st["drifts"] == 0
    finally:
        eng.close()


def test_canary_detects_patched_weight_and_names_layer(model_files, capsys):
    """ISSUE-5 acceptance: a deliberately perturbed forward (patched
    weight) trips dllama_canary_drift_total and the WARN names the first
    divergent layer via the taps."""
    eng = _engine(model_files, numerics_taps=True)
    try:
        c = numerics.CanarySentinel(eng, interval_s=0.0)
        c.ensure_golden()
        assert c.run()["drift"] is False
        layers = eng.params.layers
        eng.params = eng.params._replace(layers=layers._replace(
            norm_ffn=layers.norm_ffn.at[1].multiply(3.0)))
        drift0 = tm.registry().counter(tm.CANARY_DRIFT).total()
        res = c.run()
        assert res["drift"] is True
        assert res["divergent_layer"] == "layer 1 (mlp_out)"
        assert tm.registry().counter(tm.CANARY_DRIFT).total() == drift0 + 1
        out = capsys.readouterr().out
        assert "canary drift" in out and "layer 1 (mlp_out)" in out
    finally:
        eng.close()


def test_canary_is_compile_ledger_quiet_without_taps(model_files):
    """ISSUE-5 acceptance: with taps disabled the canary adds ZERO
    compiles to the engine's scope after steady state (every replay is a
    cache hit on the prefill-width forward program), and the retrace
    sentinel stays silent — asserted through the compile ledger."""
    led = introspection.ledger()
    eng = _engine(model_files)
    try:
        assert getattr(eng, "_step_tapped", None) is None  # taps off
        eng.generate("hello there friend", 3, stop_on_eos=False)
        scope = eng.introspection_scope
        compiles0 = led.compile_count(scope)
        led.mark_steady(scope)
        retrace0 = tm.registry().counter(tm.RETRACE_UNEXPECTED).total()
        c = numerics.CanarySentinel(eng, interval_s=0.0)
        c.ensure_golden()
        c.run()
        c.run()
        assert led.compile_count(scope) == compiles0
        assert tm.registry().counter(tm.RETRACE_UNEXPECTED).total() \
            == retrace0
        assert led.steady(scope)
    finally:
        eng.close()


def test_canary_maybe_run_respects_interval(model_files):
    eng = _engine(model_files)
    try:
        c = numerics.CanarySentinel(eng, interval_s=3600.0)
        c.ensure_golden()
        runs0 = c.runs
        assert c.maybe_run() is None  # inside the interval: no-op
        assert c.runs == runs0
    finally:
        eng.close()


def test_canary_rejected_under_multihost(model_files):
    eng = _engine(model_files)
    try:
        eng.multihost = True
        with pytest.raises(ValueError, match="single-host"):
            numerics.CanarySentinel(eng)
    finally:
        eng.multihost = False
        eng.close()


# -- offline quant-error audit ------------------------------------------------


def test_audit_scores_healthy_model(model_files, tmp_path):
    res = numerics.audit_model(model_files[0], emit=None)
    assert res["tensors"] > 0
    assert res["nonfinite_tensors"] == []
    by_name = {r["tensor"]: r for r in res["rows"]}
    # quantized matmul tensors carry scale stats; healthy blocks
    # re-encode exactly (self-consistency — the signal a mis-scaled
    # block would break)
    w1 = by_name["block_matmul_w1.0"]
    assert w1["type"] == "q40" and w1["scale_nonfinite"] == 0
    assert w1["q40_exact"] is True and w1["q40_mse"] == 0.0
    # dense tensors report what Q40 quantization WOULD cost
    emb = by_name["embedding"]
    assert emb["type"] == "f32" and emb["q40_snr_db"] > 0
    assert res["min_snr_db"] is not None and res["min_snr_db"] > 0
    assert tm.registry().gauge(tm.QUANT_AUDIT_MIN_SNR).value() \
        == pytest.approx(res["min_snr_db"])


def test_audit_flags_nonfinite_scale_naming_tensor(model_files, tmp_path):
    """A Q40 block scale flipped to f16 Inf — the mis-scaled-block defect
    the audit exists to catch — is reported against the exact tensor and
    advances the audit counter."""
    import shutil

    broken = tmp_path / "broken.m"
    shutil.copy(model_files[0], broken)
    with mfile.ModelFile.open(str(broken)) as mf:
        rec = mf.tensors["block_matmul_w2.1"]
    with open(broken, "r+b") as f:
        f.seek(rec.offset)  # first block's f16 scale → +Inf (0x7C00)
        f.write(bytes([0x00, 0x7C]))
    audit_nf = tm.registry().counter(tm.QUANT_AUDIT_NONFINITE)
    before = audit_nf.total()
    res = numerics.audit_model(str(broken), emit=None)
    assert "block_matmul_w2.1" in res["nonfinite_tensors"]
    row = {r["tensor"]: r for r in res["rows"]}["block_matmul_w2.1"]
    assert row["nonfinite"] > 0 and row["scale_nonfinite"] == 1
    assert audit_nf.total() > before


def test_audit_cli_mode(model_files, capsys):
    from dllama_tpu.serve.cli import main

    rc = main(["audit", "--model", model_files[0], "--audit-json"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()[-1]
    data = json.loads(out)
    assert data["tensors"] > 0 and data["nonfinite_tensors"] == []


# -- /debug/numerics + --stats markers ----------------------------------------


def test_debug_numerics_endpoint_and_stats_markers(model_files, tmp_path):
    from http.server import HTTPServer

    from dllama_tpu.serve.api import ApiState, make_handler

    # ApiState needs a chat template; build a templated tokenizer twin
    td = byte_vocab_tokenizer()
    td.chat_template = "<|start_header_id|>"  # detected as llama3
    tfile.write_tfile(tmp_path / "t.t", td)
    eng = _engine((model_files[0], str(tmp_path / "t.t")))
    eng.canary = numerics.CanarySentinel(eng, interval_s=3600.0)
    eng.canary.ensure_golden()
    state = ApiState(eng)
    httpd = HTTPServer(("127.0.0.1", 0), make_handler(state))
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}/debug/numerics"
        with urllib.request.urlopen(url, timeout=30) as r:
            assert r.status == 200
            snap = json.loads(r.read())
        assert snap["canary"]["golden_recorded"] is True
        assert "nonfinite_total" in snap and "taps" in snap
        # the route is a first-class label, not "other"
        http = tm.registry().counter(tm.HTTP_REQUESTS)
        assert http.total(route="/debug/numerics", status="200") >= 1
    finally:
        httpd.shutdown()
        httpd.server_close()
        eng.close()

    # --stats alarm markers ride the same counters (satellite: the
    # nonfinite=N!/drift=N! convention, like retrace=N!)
    reg = tm.registry()
    reg.counter(tm.NONFINITE).inc(site="decode")
    reg.counter(tm.CANARY_DRIFT).inc()
    line = tm.stats_line(reg)
    assert "nonfinite=" in line and line.split("nonfinite=")[1][0].isdigit()
    assert "drift=" in line
    assert "!" in line.split("drift=")[1][:4]


def test_first_divergent_layer_ordering():
    mk = lambda rms: {"rms": list(rms), "absmax": [0.0] * len(rms),
                      "nonfinite": 0, "q80_err": [0.0] * len(rms)}
    golden = {"attn_out": mk([1.0, 1.0]), "mlp_out": mk([2.0, 2.0]),
              "final_norm": mk([3.0]), "logits": mk([4.0])}
    drifted = {"attn_out": mk([1.0, 1.5]), "mlp_out": mk([2.0, 9.0]),
               "final_norm": mk([3.0]), "logits": mk([4.0])}
    assert numerics.first_divergent_layer(drifted, golden) \
        == "layer 1 (attn_out)"
    assert numerics.first_divergent_layer(golden, golden) is None
    head_only = {k: (mk([3.0]) if k == "final_norm" else golden[k])
                 for k in golden}
    head_only["logits"] = mk([9.0])
    assert numerics.first_divergent_layer(head_only, golden) == "logits"
