"""InferenceEngine tests: chunked prefill parity, generation determinism,
seq-len guards, perplexity (reference flows: dllama.cpp inference/perplexity)."""

import numpy as np
import pytest

from dllama_tpu.formats import tfile
from dllama_tpu.runtime.engine import InferenceEngine

from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model


@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("engine")
    mpath = d / "m.m"
    tpath = d / "t.t"
    rng = np.random.default_rng(123)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=48), rng)
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    return str(mpath), str(tpath)


def make_engine(model_files, **kw):
    kw.setdefault("temperature", 0.0)
    kw.setdefault("seed", 7)
    return InferenceEngine(model_files[0], model_files[1], **kw)


def test_generate_greedy_deterministic(model_files):
    e1 = make_engine(model_files)
    r1 = e1.generate("hello world", 8, stop_on_eos=False)
    e2 = make_engine(model_files)
    r2 = e2.generate("hello world", 8, stop_on_eos=False)
    assert r1.tokens == r2.tokens
    assert len(r1.tokens) == 8
    assert r1.prompt_tokens > 1
    assert any(s.kind == "eval" for s in r1.steps)
    assert sum(s.n_tokens for s in r1.steps if s.kind == "pred") == 8


def test_prefill_chunking_invariant(model_files):
    """nbatches=2 vs nbatches=32 must produce identical generations —
    the reference's positions-as-batch semantics (SURVEY.md §2.2)."""
    small = make_engine(model_files, n_batches=2)
    big = make_engine(model_files, n_batches=32)
    rs = small.generate("hello world hello world", 6, stop_on_eos=False)
    rb = big.generate("hello world hello world", 6, stop_on_eos=False)
    assert rs.tokens == rb.tokens


def test_continuation_matches_fresh_longer_prompt(model_files):
    """generate → continue == the cache holds exactly the generated tokens."""
    e = make_engine(model_files)
    r1 = e.generate("hello world", 4, stop_on_eos=False)
    r2 = e.generate([r1.tokens[-1]] if False else r1.tokens[-1:], 3, stop_on_eos=False)

    f = make_engine(model_files)
    prompt_ids = f.tokenizer.encode("hello world") + r1.tokens
    rf = f.generate(prompt_ids, 3, stop_on_eos=False)
    assert r2.tokens == rf.tokens


def test_seq_len_guard(model_files):
    e = make_engine(model_files, max_seq_len=8)
    assert e.cfg.seq_len == 8
    with pytest.raises(ValueError):
        e.prefill(list(range(9)))
    r = e.generate("hello", 100, stop_on_eos=False)  # capped at seq_len
    assert e.pos <= 8


def test_generation_caps_at_seq_len(model_files):
    e = make_engine(model_files, max_seq_len=10)
    r = e.generate("hello world", 100, stop_on_eos=False)
    assert e.pos == 10


def test_perplexity_prefers_repetition(model_files):
    e = make_engine(model_files)
    ids = e.tokenizer.encode("hello world hello world hello world")
    ppl_rep = e.perplexity(ids)
    assert np.isfinite(ppl_rep) and ppl_rep > 0
    rng = np.random.default_rng(0)
    rand_ids = [int(x) for x in rng.integers(0, 256, size=len(ids))]
    ppl_rand = e.perplexity(rand_ids)
    assert np.isfinite(ppl_rand)


def test_tp_engine_matches_single(model_files):
    base = make_engine(model_files, tp=1)
    rb = base.generate("hello world", 6, stop_on_eos=False)
    tp = make_engine(model_files, tp=4)
    rt = tp.generate("hello world", 6, stop_on_eos=False)
    assert rb.tokens == rt.tokens


def test_prefill_tail_padding_does_not_corrupt_history(model_files):
    """Regression: a padded chunk near seq_len must not clamp-and-overwrite
    older KV entries (dynamic_update_slice clamps start indices)."""
    # seq_len=48, n_batches=32: prompt of 40 once triggered a 32-wide padded
    # chunk at pos 32 spanning past 48 → clamped to 16, corrupting history.
    e = make_engine(model_files, n_batches=32)
    ids = [int(x) for x in np.random.default_rng(1).integers(1, 200, size=40)]
    e.prefill(ids)
    logits_a = e.decode_step(5)

    f = make_engine(model_files, n_batches=8)  # 8 divides 40: no tail padding
    f.prefill(ids)
    logits_b = f.decode_step(5)
    np.testing.assert_allclose(logits_a, logits_b, rtol=2e-4, atol=2e-5)


def test_sync_q80_parity_mode_changes_logits(model_files):
    """--buffer-float-type q80 must actually fake-quantize in-graph."""
    from dllama_tpu.formats.quants import Q80

    e32 = make_engine(model_files)
    eq = make_engine(model_files, sync_type=Q80)
    assert eq.cfg.sync_q80 and not e32.cfg.sync_q80
    ids = e32.tokenizer.encode("hello world")
    la, _ = e32.prefill(ids)
    lb, _ = eq.prefill(ids)
    assert not np.allclose(la, lb)  # quantization must have an effect
    assert np.abs(la - lb).max() < 0.5  # but a small one


def test_bf16_compute_mode(model_files):
    """Serving mode: bf16 activations + bf16 KV cache generate sane tokens
    (not token-identical to f32 — different arithmetic — but deterministic)."""
    import jax.numpy as jnp

    e = make_engine(model_files, compute_dtype="bfloat16")
    assert e.kv.k.dtype == jnp.bfloat16
    r1 = e.generate("hello world", 6, stop_on_eos=False)
    e2 = make_engine(model_files, compute_dtype="bfloat16")
    r2 = e2.generate("hello world", 6, stop_on_eos=False)
    assert r1.tokens == r2.tokens and len(r1.tokens) == 6
    assert all(0 <= t < e.cfg.vocab_size for t in r1.tokens)


def test_prefill_bucket_selection(model_files):
    """Default nbatches -> adaptive TPU-sized buckets; explicit -> pinned."""
    e = make_engine(model_files)  # seq_len 48: only the 32 bucket fits
    assert e.prefill_buckets == (32,)
    assert e._prefill_chunk_size(100) == 32
    e2 = make_engine(model_files, n_batches=16)
    assert e2.prefill_buckets == (16,)


def test_prefill_bucketed_matches_fixed(tmp_path):
    """Adaptive bucketing (128+64+32 chunks) must generate exactly what a
    fixed-chunk engine does — positions-as-batch semantics are chunk-size
    invariant (same property the reference relies on, SURVEY.md §4)."""
    from dllama_tpu.formats import tfile as _tfile
    from helpers import byte_vocab_tokenizer as _bv, tiny_header_params as _hp
    from helpers import write_tiny_model as _wm

    mpath, tpath = tmp_path / "m.m", tmp_path / "t.t"
    rng = np.random.default_rng(321)
    _wm(mpath, _hp(vocab_size=268, seq_len=192), rng)
    _tfile.write_tfile(tpath, _bv())

    adaptive = InferenceEngine(str(mpath), str(tpath), temperature=0.0, seed=7)
    assert adaptive.prefill_buckets == (128, 64, 32)
    fixed = InferenceEngine(str(mpath), str(tpath), temperature=0.0, seed=7,
                            n_batches=8)
    prompt = [int(t) for t in rng.integers(4, 260, size=150)]
    ra = adaptive.generate(prompt, 6, stop_on_eos=False)
    rf = fixed.generate(prompt, 6, stop_on_eos=False)
    assert ra.tokens == rf.tokens
    # 149 prompt-eval tokens (last seeds decode): 128 + 21 = two dispatches
    assert sum(1 for s in ra.steps if s.kind == "eval") == 2


def test_quant_mode_flip_after_load_fails_loudly(model_files, monkeypatch):
    """Flipping DLLAMA_TPU_QUANT_MODE after load must raise, not silently run
    one mode's math over the other mode's stored weights (bf16 scales, logits
    head and turbo planes are baked in at load — ADVICE r4 drift finding)."""
    monkeypatch.setenv("DLLAMA_TPU_QUANT_MODE", "fast")
    e = make_engine(model_files, compute_dtype="bfloat16")
    e.generate("ab", 2, stop_on_eos=False)  # sanity: matching env serves
    # same RESOLUTION under a different spelling (auto on bf16 == fast):
    # must NOT trip the guard — only genuine numerics changes do
    monkeypatch.setenv("DLLAMA_TPU_QUANT_MODE", "auto")
    e.generate("ab", 2, stop_on_eos=False)
    monkeypatch.setenv("DLLAMA_TPU_QUANT_MODE", "exact")
    with pytest.raises(RuntimeError, match="changed after load"):
        e.generate("ab", 2, stop_on_eos=False)
