"""CLI-mode tests driven in-process (reference flows: dllama.cpp
inference/chat). The API and worker modes have their own test files; this
covers the inference printout contract and the chat REPL loop (template
render → prefill → sampled decode → EOS/seq-len stop) end to end."""

import io
import os

import numpy as np
import pytest

from dllama_tpu.formats import tfile
from dllama_tpu.serve import cli

from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model

LLAMA3_SNIPPET = (
    "{% set content = '<|start_header_id|>' + message['role'] + "
    "'<|end_header_id|>\n\n' + message['content'] | trim + '<|eot_id|>' %}")


@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("cli")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(77)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=192), rng)
    data = byte_vocab_tokenizer()
    data.chat_template = LLAMA3_SNIPPET  # autodetects as llama3
    tfile.write_tfile(tpath, data)
    return str(mpath), str(tpath)


def test_inference_mode_prints_reference_style_stats(model_files, capsys):
    m, t = model_files
    rc = cli.main(["inference", "--model", m, "--tokenizer", t,
                   "--prompt", "hello world", "--steps", "16",
                   "--temperature", "0.0", "--seed", "1"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Evaluation" in out and "Prediction" in out
    assert "tokens/s" in out and "nTokens" in out


def test_inference_requires_prompt_and_steps(model_files):
    m, t = model_files
    with pytest.raises(SystemExit):
        cli.main(["inference", "--model", m, "--tokenizer", t, "--steps", "4"])
    with pytest.raises(SystemExit):
        cli.main(["inference", "--model", m, "--tokenizer", t,
                  "--prompt", "hi"])


def test_chat_mode_replies_and_exits_on_eof(model_files, capsys, monkeypatch):
    """One user turn through the real REPL: template render, prefill, fused
    sampled decode, stream until EOS or the context cap, clean EOF exit
    (reference: dllama.cpp:174-258)."""
    m, t = model_files
    monkeypatch.setattr("sys.stdin", io.StringIO("hello\n"))
    rc = cli.main(["chat", "--model", m, "--tokenizer", t,
                   "--temperature", "0.8", "--seed", "3",
                   "--max-seq-len", "128"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "🤖" in out  # the assistant turn streamed something
    assert "context is full" not in out.split("🤖")[0]  # prompt fit


def test_promoted_quant_mode_becomes_default(model_files, tmp_path,
                                             monkeypatch, capsys):
    """A perf-matrix promotion (bench_promoted.json) becomes the SERVING
    default: --quant-mode auto with no user env resolves to the promoted
    mode with a provenance line; an explicit flag still wins."""
    import json as _json

    from dllama_tpu.ops.turbo import TurboWeight

    promo = tmp_path / "bench_promoted.json"
    promo.write_text(_json.dumps({
        "env": {"DLLAMA_TPU_QUANT_MODE": "turbo16"}, "combo": "turbo16",
        "evidence": {"decode_tok_per_s": 70.2, "auto_decode_tok_per_s": 34.5,
                     "gain": 2.03}}))
    monkeypatch.setenv("DLLAMA_TPU_PROMOTED_CONFIG", str(promo))
    monkeypatch.delenv("DLLAMA_TPU_SCAN_UNROLL", raising=False)
    # DLLAMA_TPU_QUANT_MODE is managed MANUALLY, not via monkeypatch:
    # make_engine itself writes the var by design, and monkeypatch.setenv
    # would record that cli-written value as "previous" and re-instate it
    # at teardown — leaking turbo/fast numerics into the rest of the suite
    # (the round-5 full-suite golden failures).
    prev_qm = os.environ.pop("DLLAMA_TPU_QUANT_MODE", None)
    base = ["inference", "--model", model_files[0],
            "--tokenizer", model_files[1], "--compute-dtype", "bf16",
            "--temperature", "0"]
    try:
        eng = cli.make_engine(cli.build_parser().parse_args(base))
        assert isinstance(eng.params.layers.wq, TurboWeight)
        eng.close()
        assert "promoted serving config" in capsys.readouterr().out
        # explicit --quant-mode overrides the promotion
        eng2 = cli.make_engine(cli.build_parser().parse_args(
            base + ["--quant-mode", "fast"]))
        assert not isinstance(eng2.params.layers.wq, TurboWeight)
        eng2.close()
        # user-exported env overrides it too
        os.environ["DLLAMA_TPU_QUANT_MODE"] = "fast"
        cli._cli_wrote_quant_mode = False
        eng3 = cli.make_engine(cli.build_parser().parse_args(base))
        assert not isinstance(eng3.params.layers.wq, TurboWeight)
        eng3.close()
    finally:
        cli._cli_wrote_quant_mode = False
        cli._env_quant_before_cli = None
        cli._promo_applied.clear()
        if prev_qm is None:
            os.environ.pop("DLLAMA_TPU_QUANT_MODE", None)
        else:
            os.environ["DLLAMA_TPU_QUANT_MODE"] = prev_qm
