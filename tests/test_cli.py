"""CLI-mode tests driven in-process (reference flows: dllama.cpp
inference/chat). The API and worker modes have their own test files; this
covers the inference printout contract and the chat REPL loop (template
render → prefill → sampled decode → EOS/seq-len stop) end to end."""

import io

import numpy as np
import pytest

from dllama_tpu.formats import tfile
from dllama_tpu.serve import cli

from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model

LLAMA3_SNIPPET = (
    "{% set content = '<|start_header_id|>' + message['role'] + "
    "'<|end_header_id|>\n\n' + message['content'] | trim + '<|eot_id|>' %}")


@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("cli")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(77)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=192), rng)
    data = byte_vocab_tokenizer()
    data.chat_template = LLAMA3_SNIPPET  # autodetects as llama3
    tfile.write_tfile(tpath, data)
    return str(mpath), str(tpath)


def test_inference_mode_prints_reference_style_stats(model_files, capsys):
    m, t = model_files
    rc = cli.main(["inference", "--model", m, "--tokenizer", t,
                   "--prompt", "hello world", "--steps", "16",
                   "--temperature", "0.0", "--seed", "1"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Evaluation" in out and "Prediction" in out
    assert "tokens/s" in out and "nTokens" in out


def test_inference_requires_prompt_and_steps(model_files):
    m, t = model_files
    with pytest.raises(SystemExit):
        cli.main(["inference", "--model", m, "--tokenizer", t, "--steps", "4"])
    with pytest.raises(SystemExit):
        cli.main(["inference", "--model", m, "--tokenizer", t,
                  "--prompt", "hi"])


def test_chat_mode_replies_and_exits_on_eof(model_files, capsys, monkeypatch):
    """One user turn through the real REPL: template render, prefill, fused
    sampled decode, stream until EOS or the context cap, clean EOF exit
    (reference: dllama.cpp:174-258)."""
    m, t = model_files
    monkeypatch.setattr("sys.stdin", io.StringIO("hello\n"))
    rc = cli.main(["chat", "--model", m, "--tokenizer", t,
                   "--temperature", "0.8", "--seed", "3",
                   "--max-seq-len", "128"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "🤖" in out  # the assistant turn streamed something
    assert "context is full" not in out.split("🤖")[0]  # prompt fit
