"""Test config: force an 8-device virtual CPU platform before JAX initializes.

This is the TPU build's equivalent of the reference's NnFakeNodeSynchronizer +
localhost-TCP-worker strategy (reference: src/nn/nn-executor.hpp:29-33,
examples/n-workers.sh): multi-chip behavior is tested on a single host by
letting XLA present 8 virtual CPU devices, so every sharding/collective path
runs for real — just not over ICI.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the live session exposes a TPU
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The axon sitecustomize calls jax.config.update("jax_platforms", "axon,cpu")
# at interpreter start, which overrides the env var — undo it here, before any
# backend initializes.
jax.config.update("jax_platforms", "cpu")
