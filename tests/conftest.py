"""Test config: force an 8-device virtual CPU platform before JAX initializes.

This is the TPU build's equivalent of the reference's NnFakeNodeSynchronizer +
localhost-TCP-worker strategy (reference: src/nn/nn-executor.hpp:29-33,
examples/n-workers.sh): multi-chip behavior is tested on a single host by
letting XLA present 8 virtual CPU devices, so every sharding/collective path
runs for real — just not over ICI.
"""

import os

# DLLAMA_TESTS_TPU=1 runs the @pytest.mark.tpu tier on real hardware
# (pytest -m tpu); default is the 8-device virtual CPU mesh.
_TPU_TIER = os.environ.get("DLLAMA_TESTS_TPU") == "1"

# an operator's local bench_promoted.json must not flip test numerics:
# promotion is off for the whole suite unless a test opts in explicitly
os.environ.setdefault("DLLAMA_TPU_PROMOTED_CONFIG", "off")

if not _TPU_TIER:
    os.environ["JAX_PLATFORMS"] = "cpu"  # force: the live session exposes a TPU
    xla_flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in xla_flags:
        os.environ["XLA_FLAGS"] = (
            xla_flags + " --xla_force_host_platform_device_count=8"
        ).strip()

import jax  # noqa: E402

if not _TPU_TIER:
    # The axon sitecustomize calls jax.config.update("jax_platforms",
    # "axon,cpu") at interpreter start, which overrides the env var — undo it
    # here, before any backend initializes.
    jax.config.update("jax_platforms", "cpu")
    # Persistent XLA compile cache for the CPU tier: since plan_scoped_jit
    # (parallel/api.py) scoped trace caches per engine, every engine
    # legitimately compiles its own programs — identical HLO across the
    # suite's hundreds of tiny engines now hits this disk cache instead of
    # recompiling (~30% wall time; keeps the tier-1 run inside its budget).
    # An explicit JAX_COMPILATION_CACHE_DIR env wins.
    if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
        import tempfile

        _cache = os.path.join(tempfile.gettempdir(), "dllama-tests-xla-cache")
        try:
            os.makedirs(_cache, exist_ok=True)
            os.environ["JAX_COMPILATION_CACHE_DIR"] = _cache
            jax.config.update("jax_compilation_cache_dir", _cache)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.5)
        except OSError:
            pass  # unwritable tmp: run uncached


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running test")
    config.addinivalue_line(
        "markers",
        "tpu: needs real TPU hardware (run: DLLAMA_TESTS_TPU=1 pytest -m tpu)")


def pytest_collection_modifyitems(config, items):
    """Deselect tpu-marked tests unless the TPU tier is active (they compile
    real Pallas kernels; pointless and slow on the CPU mesh), and everything
    else when it is."""
    import pytest as _pytest

    skip_tpu = _pytest.mark.skip(reason="TPU tier off (set DLLAMA_TESTS_TPU=1)")
    skip_cpu = _pytest.mark.skip(reason="TPU tier on: only -m tpu tests run")
    for item in items:
        has_tpu = "tpu" in item.keywords
        if has_tpu and not _TPU_TIER:
            item.add_marker(skip_tpu)
        elif _TPU_TIER and not has_tpu:
            item.add_marker(skip_cpu)


import pytest as _pt


@_pt.fixture(autouse=True)
def _dllama_env_leak_sentinel():
    """Fail the OFFENDING test when it leaks a DLLAMA_* env knob.

    The quant/serving knobs are read at trace time, so a leaked var flips
    numerics for every later test — the round-5 full-suite incident was 36
    order-dependent golden failures traced to one test's env interplay.
    Autouse + declared first => torn down last, AFTER monkeypatch undo."""
    before = {k: v for k, v in os.environ.items() if k.startswith("DLLAMA_")}
    yield
    after = {k: v for k, v in os.environ.items() if k.startswith("DLLAMA_")}
    assert after == before, (
        "test leaked DLLAMA_* env state: "
        + str({k: (before.get(k), after.get(k))
               for k in set(before) | set(after)
               if before.get(k) != after.get(k)}))
