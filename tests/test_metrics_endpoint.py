"""GET /metrics end-to-end: a real API server over a tiny model serves one
completion, then the scrape must show non-zero TTFT/ITL histograms, token
counters, occupancy gauges, and the HTTP route counters — plus the JSON 404
for unknown routes (satellite)."""

import json
import threading
import urllib.error
import urllib.request
from http.server import ThreadingHTTPServer

import numpy as np
import pytest

from dllama_tpu.formats import tfile
from dllama_tpu.runtime import telemetry as tm
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.serve.api import BatchedApiState, make_handler

from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    d = tmp_path_factory.mktemp("metrics_api")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(9)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96), rng)
    td = byte_vocab_tokenizer()
    td.chat_template = "<|start_header_id|>"  # detected as llama3
    tfile.write_tfile(tpath, td)
    engine = InferenceEngine(str(mpath), str(tpath), temperature=0.0, seed=3)
    state = BatchedApiState(engine, n_slots=2)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(state))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{port}"
    httpd.shutdown()
    state.close()
    engine.close()


def _scrape(url: str) -> dict[str, float]:
    """Parse the exposition text into {sample_name_with_labels: value}."""
    with urllib.request.urlopen(url + "/metrics", timeout=30) as r:
        assert r.status == 200
        assert r.headers["Content-Type"].startswith("text/plain")
        text = r.read().decode()
    out = {}
    for line in text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        name, _, value = line.rpartition(" ")
        out[name] = float(value)
    return out


def test_metrics_endpoint_after_one_completion(server):
    req = urllib.request.Request(
        server + "/v1/chat/completions",
        data=json.dumps({"messages": [{"role": "user", "content": "hello"}],
                         "max_tokens": 6, "temperature": 0}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as r:
        out = json.loads(r.read())
    n_out = out["usage"]["completion_tokens"]
    assert n_out >= 2  # need >= 2 tokens for a non-zero ITL histogram

    samples = _scrape(server)
    # acceptance set: request count, TTFT, ITL, batch + KV occupancy,
    # per-token collective bytes
    assert samples[
        'dllama_http_requests_total{route="/v1/chat/completions",'
        'status="200"}'] >= 1
    assert samples["dllama_ttft_ms_count"] >= 1
    assert samples["dllama_ttft_ms_sum"] > 0
    assert samples["dllama_itl_ms_count"] >= n_out - 1
    assert "dllama_batch_occupancy" in samples
    assert samples["dllama_batch_slots"] == 2
    # the request has retired by scrape time, so pooled KV occupancy is
    # back to 0 (live-rows semantics); the gauge itself must be present
    assert 0.0 <= samples["dllama_kv_occupancy"] <= 1.0
    assert "dllama_collective_sent_kb_per_token" in samples
    assert "dllama_collective_recv_kb_per_token" in samples
    assert "dllama_sync_fraction" in samples
    # token counters
    assert samples["dllama_prompt_tokens_total"] >= 1
    assert samples["dllama_completion_tokens_total"] >= n_out
    assert samples["dllama_batch_tokens_total"] >= n_out
    # serving pipeline counters
    assert samples["dllama_admissions_total"] >= 1
    assert samples["dllama_retires_total"] >= 1
    assert samples["dllama_queue_wait_ms_count"] >= 1
    assert samples["dllama_batch_step_ms_count"] >= 1
    assert samples["dllama_hbm_need_bytes"] > 0
    assert samples["dllama_requests_in_flight"] == 0

    # the scrape itself is counted on the next scrape
    samples2 = _scrape(server)
    assert samples2[
        'dllama_http_requests_total{route="/metrics",status="200"}'] >= 1


def test_metrics_names_all_match_convention(server):
    """Every sample name on the wire derives from a dllama_[a-z0-9_]+
    metric (the contract tools/check_metrics_names.py lints at the source
    level; digits admitted for format names like q80)."""
    import re

    pat = re.compile(r"^dllama_[a-z0-9_]+(_bucket|_sum|_count)?(\{.*\})?$")
    for name in _scrape(server):
        assert pat.match(name), name


def test_unknown_route_returns_json_404(server):
    for method, path in (("GET", "/nope"), ("GET", "/v1/metrics"),
                         ("POST", "/v1/completions")):
        req = urllib.request.Request(server + path, method=method,
                                     data=b"{}" if method == "POST" else None)
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=30)
        assert e.value.code == 404
        assert e.value.headers["Content-Type"] == "application/json"
        body = json.loads(e.value.read())
        assert body["error"] == "not found"
        assert body["path"] == path
        assert "/metrics" in body["routes"]
    # 404s are visible in the route counter under the bounded "other" label
    samples = _scrape(server)
    assert samples['dllama_http_requests_total{route="other",'
                   'status="404"}'] >= 3
