"""XLA compile introspection (runtime/introspection): the compile ledger,
retrace sentinel, HBM startup report, and the /debug/* HTTP surface.

Acceptance tier (ISSUE 3): a steady-state batched-serving test drives TWO
engines, asserts ``dllama_retrace_unexpected_total`` stays 0 across
steady-state traffic, that ``GET /debug/compiles`` lists every compiled
program with nonzero HBM bytes, and that ``POST /debug/profile`` returns a
parseable eval/sync summary — all on the CPU mesh, no silicon."""

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import ThreadingHTTPServer

import numpy as np
import pytest

from dllama_tpu.formats import tfile
from dllama_tpu.runtime import introspection, telemetry
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.serve.api import BatchedApiState, make_handler

from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model


@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("introspect")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(21)
    # seq_len 256: the llama3 template wraps a short user message into
    # ~90-110 prompt tokens, and the profile test decodes 60 more on top
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=256),
                     rng)
    td = byte_vocab_tokenizer()
    td.chat_template = "<|start_header_id|>"  # detected as llama3
    tfile.write_tfile(tpath, td)
    return str(mpath), str(tpath)


# -- ledger unit tier ---------------------------------------------------------


def test_sig_diff_reports_changed_leaves():
    old = {"a": "i32[1,32]", "b": "f32[4]", "gone": "i32[2]"}
    new = {"a": "i32[1,1]", "b": "f32[4]", "new": "f32[8]"}
    diff = introspection._sig_diff(old, new)
    assert "~ a: i32[1,32] -> i32[1,1]" in diff
    assert "+ new = f32[8]" in diff
    assert "- gone = i32[2]" in diff
    assert not any("b" == d.split()[1] for d in diff)
    assert introspection._sig_diff(None, new) == \
        ["(first compile in scope — no prior signature)"]
    # identical signatures still explain themselves (sharding-keyed compile)
    assert "identical leaf shapes" in introspection._sig_diff(old, old)[0]


def test_ledger_records_compiles_hits_and_analysis(model_files):
    led = introspection.ledger()
    prev_analyze = led.analyze
    led.analyze = True
    try:
        e = InferenceEngine(model_files[0], model_files[1], temperature=0.0,
                            seed=3, tp=1)
        r = e.generate("hello world", 4, stop_on_eos=False)
        assert len(r.tokens) == 4
        snap = led.snapshot()
        mine = {p["program"]: p for p in snap["programs"]
                if p["scope"] == e.introspection_scope}
        # prefill (forward) and fused greedy decode both compiled exactly once
        assert mine["forward"]["compiles"] == 1
        assert mine["greedy_step"]["compiles"] == 1
        # 4 decode tokens = 1 compile + 3 cache hits
        assert mine["greedy_step"]["hits"] >= 2
        # per-miss AOT analysis delivered nonzero HBM bytes and FLOPs
        for prog in ("forward", "greedy_step"):
            assert mine[prog]["hbm_total_bytes"] > 0
            assert mine[prog]["analysis"]["flops"] > 0
        # events carry plan + wall time; this scope is not yet steady
        evs = [ev for ev in snap["events"]
               if ev["scope"] == e.introspection_scope]
        assert evs and all(ev["compile_s"] > 0 for ev in evs)
        assert all(not ev["unexpected"] for ev in evs)
        assert snap["steady"][e.introspection_scope] is False
        # metrics side: counter and histogram moved
        reg = telemetry.registry()
        assert reg.counter(telemetry.COMPILE_TOTAL).total(
            scope=e.introspection_scope) >= 2
        assert reg.histogram(telemetry.COMPILE_SECONDS).count() >= 2
        assert reg.gauge(telemetry.PROGRAM_HBM_BYTES).value(
            scope=e.introspection_scope, program="greedy_step",
            kind="output") > 0
        e.close()
    finally:
        led.analyze = prev_analyze


def test_retrace_sentinel_fires_after_steady(model_files, capsys):
    led = introspection.ledger()
    reg = telemetry.registry()
    e = InferenceEngine(model_files[0], model_files[1], temperature=0.0,
                        seed=3, tp=1)
    e.generate("hi there", 4, stop_on_eos=False)
    led.mark_steady(e.introspection_scope)
    assert led.steady(e.introspection_scope)
    before = reg.counter(telemetry.RETRACE_UNEXPECTED).total()
    # force a program this scope never compiled: the sampled step
    e.sampler.set_temp(0.7)
    e.reset()
    e.generate("hello", 2, stop_on_eos=False)
    after = reg.counter(telemetry.RETRACE_UNEXPECTED).total()
    assert after > before
    assert "unexpected recompile after steady state" in capsys.readouterr().out
    evs = [ev for ev in led.snapshot()["events"]
           if ev["scope"] == e.introspection_scope and ev["unexpected"]]
    assert evs and evs[-1]["diff"]  # the shape/plan diff is recorded
    e.close()


def test_new_engine_scope_does_not_inherit_steadiness(model_files):
    led = introspection.ledger()
    reg = telemetry.registry()
    e1 = InferenceEngine(model_files[0], model_files[1], temperature=0.0,
                         seed=3, tp=1)
    e1.generate("hi", 3, stop_on_eos=False)
    led.mark_steady(e1.introspection_scope)
    before = reg.counter(telemetry.RETRACE_UNEXPECTED).total()
    # a second engine's warm-up compiles are expected, not retraces
    e2 = InferenceEngine(model_files[0], model_files[1], temperature=0.0,
                         seed=3, tp=1)
    assert e2.introspection_scope != e1.introspection_scope
    e2.generate("hi", 3, stop_on_eos=False)
    assert reg.counter(telemetry.RETRACE_UNEXPECTED).total() == before
    assert led.steady(e1.introspection_scope)       # e1 untouched
    assert not led.steady(e2.introspection_scope)   # e2 still warming
    e1.close()
    e2.close()


def test_hbm_startup_report(model_files):
    e = InferenceEngine(model_files[0], model_files[1], temperature=0.0,
                        seed=3, tp=2)
    lines: list[str] = []
    rep = introspection.hbm_startup_report(e, emit=lines.append)
    assert rep["weights_bytes"] > 0 and rep["kv_bytes"] > 0
    assert rep["need_per_device"] > rep["weights_bytes"] // 2  # margin+fixed
    for name in ("decode", "prefill"):
        info = rep["programs"][name]
        assert info["hbm_bytes"]["output"] > 0
        assert info["hbm_bytes"]["argument"] > 0
        assert info["flops"] > 0
    # prefill runs a whole chunk per dispatch: strictly more FLOPs
    assert rep["programs"]["prefill"]["flops"] > \
        rep["programs"]["decode"]["flops"]
    assert any("HBM budget/device" in ln for ln in lines)
    assert sum("program" in ln for ln in lines) >= 2
    # gauges published under the ledger's (scope, program) labels — two
    # engines share program NAMES, so scope must disambiguate
    g = telemetry.registry().gauge(telemetry.PROGRAM_HBM_BYTES)
    sc = e.introspection_scope
    assert g.value(scope=sc, program="greedy_step", kind="argument") > 0
    assert g.value(scope=sc, program="forward", kind="argument") > 0
    e.close()


# -- acceptance tier: steady-state batched serving + /debug endpoints ---------


def _post(url, payload=None, timeout=120):
    data = json.dumps(payload).encode() if payload is not None else b""
    req = urllib.request.Request(url, data=data,
                                 headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _get(url, timeout=60):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _chat(base, text, max_tokens=8):
    return _post(base + "/v1/chat/completions",
                 {"messages": [{"role": "user", "content": text}],
                  "max_tokens": max_tokens, "temperature": 0})


@pytest.fixture(scope="module")
def two_servers(model_files):
    led = introspection.ledger()
    prev_analyze = led.analyze
    led.analyze = True
    servers = []
    try:
        for tp in (1, 2):
            engine = InferenceEngine(model_files[0], model_files[1],
                                     temperature=0.0, seed=3, tp=tp)
            state = BatchedApiState(engine, n_slots=2)
            httpd = ThreadingHTTPServer(("127.0.0.1", 0),
                                        make_handler(state))
            threading.Thread(target=httpd.serve_forever, daemon=True).start()
            servers.append((f"http://127.0.0.1:{httpd.server_address[1]}",
                            engine, state, httpd))
        yield servers
    finally:
        led.analyze = prev_analyze
        for _, engine, state, httpd in servers:
            httpd.shutdown()
            state.close()
            engine.close()


def test_steady_state_batched_serving_two_engines(two_servers):
    led = introspection.ledger()
    reg = telemetry.registry()
    # warm both engines: identical request shapes, several requests each so
    # the schedulers see compile-quiet ticks and mark their scopes steady
    for base, _, _, _ in two_servers:
        for _ in range(3):
            status, out = _chat(base, "hello world")
            assert status == 200
            assert out["usage"]["completion_tokens"] >= 1
    for _, engine, _, _ in two_servers:
        assert led.steady(engine.introspection_scope), \
            f"{engine.introspection_scope} never reached steady state"

    # steady-state traffic of the same shape: ZERO unexpected retraces
    before = reg.counter(telemetry.RETRACE_UNEXPECTED).total()
    for base, _, _, _ in two_servers:
        for _ in range(2):
            status, _out = _chat(base, "hello world")
            assert status == 200
    assert reg.counter(telemetry.RETRACE_UNEXPECTED).total() == before

    # GET /debug/compiles lists every compiled program with nonzero HBM bytes
    base0 = two_servers[0][0]
    status, snap = _get(base0 + "/debug/compiles")
    assert status == 200
    scopes = {e.introspection_scope for _, e, _, _ in two_servers}
    listed = [p for p in snap["programs"] if p["scope"] in scopes]
    compiled = [p for p in listed if p["compiles"] > 0]
    assert len(compiled) >= 4  # ≥2 programs per engine (prefill + ragged)
    for p in compiled:
        assert p["hbm_total_bytes"] > 0, \
            f"{p['scope']}/{p['program']} has no HBM analysis"
        assert p["total_compile_s"] > 0
    for scope in scopes:
        assert snap["steady"][scope] is True
    assert all("last_sig" not in p for p in snap["programs"])  # bounded dump


def test_debug_profile_returns_parseable_split(two_servers):
    base = two_servers[0][0]
    reg = telemetry.registry()

    def _decode_steps() -> int:
        # the same step count live_split_summary diffs across its window
        return (reg.histogram(telemetry.BATCH_STEP_MS).count()
                + reg.histogram(telemetry.DECODE_STEP_MS).count())

    # A single 400 ms window RACES the background request under full-suite
    # load: the tiny model can finish decoding before the capture opens,
    # or the scheduler thread can be starved past the whole window (the
    # PR8-era flake — passed in isolation, failed under load). So each
    # attempt starts a FRESH background generation, waits until its decode
    # steps are observably flowing, THEN opens the window — and because
    # load can still starve any one attempt, the overlap assertion is on
    # "some attempt", bounded, not on a single roll of the dice.
    summary = None
    for attempt in range(6):
        bg_done = threading.Event()

        def _bg():
            try:
                _chat(base, f"profile me while I decode {attempt}",
                      max_tokens=96)
            finally:
                bg_done.set()

        n0 = _decode_steps()
        threading.Thread(target=_bg, daemon=True).start()
        deadline = time.monotonic() + 60
        while (_decode_steps() == n0 and not bg_done.is_set()
               and time.monotonic() < deadline):
            time.sleep(0.005)
        status, s = _post(base + "/debug/profile?ms=400")
        assert status == 200
        for key in ("duration_ms", "n_steps", "eval_ms", "sync_ms",
                    "sync_frac", "n_lanes"):
            assert key in s, s
            assert isinstance(s[key], (int, float))
        assert s["duration_ms"] == pytest.approx(400.0)
        assert 0.0 <= s["sync_frac"] <= 1.0
        # static collective accounting rides along (tp=1: present, empty)
        assert "collective_traffic" in s
        bg_done.wait(timeout=120)
        if s["n_steps"] >= 1:
            summary = s
            break
    # at least one window overlapped live decode steps
    assert summary is not None, "6 profile windows all missed decode steps"

    # the per-op view (?ops=1) returns the op-class attribution shape on
    # the same live path (content is backend-dependent; shape is not)
    status, s = _post(base + "/debug/profile?ms=50&ops=1")
    assert status == 200
    assert "op_attribution" in s
    for key in ("classes", "top_ops", "total_ms_per_step", "n_lanes"):
        assert key in s["op_attribution"]

    # bad/oversized windows are client errors, not captures
    for q in ("ms=nope", "ms=999999", "ms=1", "ms=100&ops=x"):
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(base + f"/debug/profile?{q}")
        assert err.value.code == 400


def test_debug_requests_timeline(two_servers):
    base = two_servers[0][0]
    _chat(base, "leave a span trail")
    status, out = _get(base + "/debug/requests")
    assert status == 200
    assert out["requests"], "span ring is empty after a completion"
    # the ring is process-global and request ids are per-scheduler counters,
    # so other engines' spans (rid -1 single-sequence spans from earlier
    # tests in the suite) can interleave — find a batched completion's
    # timeline instead of pinning the newest entry (documented best-effort)
    tl = next(t for t in out["requests"]
              if {"queue", "prefill", "decode"}
              <= {p["phase"] for p in t["phases"]})
    assert {"request_id", "total_ms", "phases"} <= set(tl)
    assert tl["total_ms"] > 0
    for p in tl["phases"]:
        assert p["ms"] >= 0 and p["start_ms"] >= 0


def test_debug_routes_have_their_own_metric_labels(two_servers):
    base = two_servers[0][0]
    _get(base + "/debug/compiles")
    with urllib.request.urlopen(base + "/metrics", timeout=60) as r:
        assert r.headers["Content-Type"].startswith(
            "text/plain; version=0.0.4")
        text = r.read().decode()
    # per-route labels, not folded into "other" (satellite: closed-world
    # route labels; the query-string form must still label /debug/profile)
    assert 'route="/debug/compiles",status="200"' in text
    assert 'route="/debug/profile",status="200"' in text
    assert 'route="/debug/requests",status="200"' in text


# -- cost_analysis version compat ---------------------------------------------

class _FakeCompiled:
    """cost_analysis() return shape varies by jax version: a dict on new
    jax, [dict] on 0.4.x. The shared accessor must normalize both."""

    def __init__(self, ret):
        self._ret = ret

    def cost_analysis(self):
        return self._ret


@pytest.mark.parametrize("ret, want", [
    ({"flops": 7.0}, {"flops": 7.0}),        # newer jax: one dict
    ([{"flops": 7.0}], {"flops": 7.0}),      # 0.4.x: one-element list
    (({"flops": 7.0},), {"flops": 7.0}),     # tuple variant
    ([], {}),                                # no analysis available
    (None, {}),
])
def test_cost_analysis_dict_normalizes_every_shape(ret, want):
    assert introspection.cost_analysis_dict(_FakeCompiled(ret)) == want


def test_cost_analysis_dict_is_what_the_moe_flops_test_consumes():
    """The satellite contract: tests/test_moe.py measures FLOPs through
    THIS accessor, so `[dict]`-returning jax can never TypeError it
    again. Keyed access on the normalized dict must work."""
    ca = introspection.cost_analysis_dict(_FakeCompiled([{"flops": 3.5}]))
    assert ca["flops"] == 3.5
