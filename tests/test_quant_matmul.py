"""Pallas Q40 matmul kernel vs the XLA dequant+dot oracle (the parity
methodology of nn-vulkan-test.cpp: accelerated op vs reference semantics)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dllama_tpu.ops.linear import linear, quantize_weight_q40
from dllama_tpu.ops.quant_matmul import quant_matmul, supports


def _mk(out, in_, seed=0):
    rng = np.random.default_rng(seed)
    w = (rng.standard_normal((out, in_)) * 0.1).astype(np.float32)
    return quantize_weight_q40(w)


@pytest.mark.parametrize("m,n,k", [
    (1, 256, 512),     # decode step
    (8, 512, 1024),    # small prefill
    (32, 128, 256),    # reference nBatches
    (16, 64, 128),     # kv-proj-like narrow output
])
def test_kernel_matches_xla_oracle(m, n, k):
    w = _mk(n, k, seed=n + k)
    x = jnp.asarray(np.random.default_rng(m).standard_normal((m, k)), jnp.float32)
    want = linear(x, w)
    got = quant_matmul(x, w, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_kernel_q80_planes():
    """Q80 weights land in the same (scales, int8-codes) planes — the kernel
    consumes them unchanged (codes*scales; nothing 4-bit-specific). The
    codes span the full int8 range here, unlike Q40's [-8, 7]."""
    from dllama_tpu.formats.quants import quantize_q80, unpack_q80

    rng = np.random.default_rng(5)
    w = (rng.standard_normal((256, 512)) * 0.1).astype(np.float32)
    scales, codes = unpack_q80(quantize_q80(w.reshape(-1)), w.size)
    from dllama_tpu.ops.linear import QuantizedWeight

    qw = QuantizedWeight(
        scales=jnp.asarray(scales.reshape(256, 16).T.astype(np.float32)),
        codes=jnp.asarray(np.ascontiguousarray(codes.reshape(256, 512).T)))
    assert int(np.abs(np.asarray(qw.codes)).max()) > 8  # genuinely 8-bit
    x = jnp.asarray(rng.standard_normal((4, 512)), jnp.float32)
    want = linear(x, qw)
    got = quant_matmul(x, qw, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_kernel_3d_batch():
    w = _mk(256, 512, seed=1)
    x = jnp.asarray(np.random.default_rng(2).standard_normal((2, 3, 512)), jnp.float32)
    want = linear(x, w)
    got = quant_matmul(x, w, interpret=True)
    assert got.shape == (2, 3, 256)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_supports_predicate():
    assert supports((1, 512), _mk(256, 512))
    assert supports((1, 96), _mk(256, 96))  # K=96: whole-K block (÷32)
    assert supports((1, 512), _mk(96, 512))  # N=96: whole-N block
    # K mismatch between x and w is never dispatched to the kernel
    assert not supports((1, 256), _mk(96, 512))
    # oversized batch falls back to XLA (VMEM bound on the un-tiled M axis)
    assert not supports((2048, 512), _mk(96, 512))
    # stacked (3D) weights fall back to XLA
    from dllama_tpu.ops.linear import QuantizedWeight

    w = _mk(96, 512)
    stacked = QuantizedWeight(scales=w.scales[None], codes=w.codes[None])
    assert not supports((1, 512), stacked)


# ---------------------------------------------------------------------------
# sharded kernel (shard_map wrapper) vs the auto-sharded XLA path
# ---------------------------------------------------------------------------

from dllama_tpu.parallel.api import make_mesh, make_tp_mesh, use_plan  # noqa: E402
from dllama_tpu.ops.quant_matmul import quant_matmul_sharded  # noqa: E402


def _x3(b, t, k, seed=3):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal((b, t, k)), jnp.float32)


@pytest.mark.parametrize("tp", [2, 4])
def test_sharded_row_split_matches_oracle(tp):
    plan = make_tp_mesh(tp)
    w = _mk(256, 512, seed=9)
    x = _x3(1, 8, 512)
    want = linear(x, w)
    got = quant_matmul_sharded(plan, x, w, out_axis="hidden", interpret=True)
    assert got is not None
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("tp", [2, 4])
def test_sharded_col_split_matches_oracle(tp):
    plan = make_tp_mesh(tp)
    w = _mk(256, 512, seed=10)
    x = _x3(1, 8, 512)
    want = linear(x, w)
    got = quant_matmul_sharded(plan, x, w, in_axis="hidden", interpret=True)
    assert got is not None
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_sharded_replicated_fallback_runs_kernel():
    """Non-divisible shard dim (KV replication case): kernel runs replicated."""
    plan = make_tp_mesh(4)
    w = _mk(96, 512, seed=11)  # 96 % 4 != 0 at lane granularity... 96/4=24, divisible
    # use an axis name the mesh doesn't carry to force replication instead
    got = quant_matmul_sharded(plan, _x3(1, 4, 512), w, out_axis="experts",
                               interpret=True)
    assert got is not None
    want = linear(_x3(1, 4, 512), w)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_sharded_with_dp_batch():
    plan = make_mesh({"dp": 2, "tp": 2})
    w = _mk(256, 512, seed=12)
    x = _x3(4, 2, 512)
    want = linear(x, w)
    got = quant_matmul_sharded(plan, x, w, out_axis="hidden", interpret=True)
    assert got is not None
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# fast mode (bf16 dequant, one MXU pass) vs exact mode — SURVEY §7.4's
# exact/fast split; drift bound is the deliverable (VERDICT r3 next #2)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("m,n,k", [(1, 256, 512), (8, 512, 1024)])
def test_fast_mode_drift_bounded(m, n, k):
    """Fast-mode output drifts from the exact kernel only by bf16 rounding of
    the weights/activations: relative error stays under ~1%, typical ~0.3%.
    The accumulator is f32, so error does NOT grow with K."""
    w = _mk(n, k, seed=n + k + 1)
    x = jnp.asarray(np.random.default_rng(m + 7).standard_normal((m, k)),
                    jnp.float32)
    exact = np.asarray(quant_matmul(x, w, interpret=True))
    fast = np.asarray(quant_matmul(x, w, interpret=True, fast=True))
    rel = np.abs(fast - exact) / np.maximum(np.abs(exact), 1e-3)
    assert float(np.median(rel)) < 3e-3, float(np.median(rel))
    # elementwise max-rel explodes where the exact output cancels to ~0, so
    # the worst-case bound is error relative to the output's RMS magnitude
    rms = float(np.sqrt(np.mean(exact ** 2)))
    assert float(np.abs(fast - exact).max()) / rms < 2e-2, \
        (float(np.abs(fast - exact).max()), rms)


def test_fast_mode_env_knob_xla_path(monkeypatch):
    """DLLAMA_TPU_QUANT_MODE=fast flips the XLA fallback to bf16 dequant; the
    output dtype still matches the caller's activation dtype."""
    monkeypatch.setenv("DLLAMA_TPU_QUANT_KERNEL", "xla")
    w = _mk(256, 512, seed=21)
    x = jnp.asarray(np.random.default_rng(8).standard_normal((4, 512)),
                    jnp.float32)
    monkeypatch.setenv("DLLAMA_TPU_QUANT_MODE", "exact")
    exact = linear(x, w)
    monkeypatch.setenv("DLLAMA_TPU_QUANT_MODE", "fast")
    fast = linear(x, w)
    assert fast.dtype == x.dtype
    denom = np.maximum(np.abs(np.asarray(exact)), 1e-3)
    rel = np.abs(np.asarray(fast) - np.asarray(exact)) / denom
    assert float(np.median(rel)) < 5e-3, float(np.median(rel))


def test_fast_mode_auto_keys_off_bf16_activations(monkeypatch):
    """Unit-tests the mode predicate: auto resolves to fast iff activations
    are bf16; explicit exact/fast override the dtype. (The numerics each mode
    produces are covered by the drift tests above.)"""
    from dllama_tpu.ops.linear import _fast_mode

    monkeypatch.delenv("DLLAMA_TPU_QUANT_MODE", raising=False)
    assert _fast_mode(jnp.zeros((1, 4), jnp.bfloat16)) is True
    assert _fast_mode(jnp.zeros((1, 4), jnp.float32)) is False
    monkeypatch.setenv("DLLAMA_TPU_QUANT_MODE", "exact")
    assert _fast_mode(jnp.zeros((1, 4), jnp.bfloat16)) is False
    monkeypatch.setenv("DLLAMA_TPU_QUANT_MODE", "fast")
    assert _fast_mode(jnp.zeros((1, 4), jnp.float32)) is True


def test_fast_mode_sharded_matches_plain_fast():
    """The shard_map-wrapped fast kernel reproduces the single-device fast
    kernel (row and col splits)."""
    plan = make_tp_mesh(2)
    w = _mk(256, 512, seed=22)
    x = _x3(1, 8, 512, seed=23)
    want = np.asarray(quant_matmul(x.reshape(8, 512), w, interpret=True,
                                   fast=True)).reshape(1, 8, 256)
    for kw in ({"out_axis": "hidden"}, {"in_axis": "hidden"}):
        got = quant_matmul_sharded(plan, x, w, interpret=True, fast=True, **kw)
        assert got is not None
        np.testing.assert_allclose(np.asarray(got), want, rtol=2e-2, atol=1e-3)


def test_fast_mode_model_logit_drift(monkeypatch):
    """End-to-end logit drift of fast-mode numerics on a full (tiny) model
    forward — the quantified exact-vs-fast deliverable at the level users see.
    Drift is bf16-rounding-sized; argmax (greedy token) is stable here."""
    from dllama_tpu.formats import mfile
    from dllama_tpu.models import ModelConfig, forward, init_random_params
    from dllama_tpu.runtime import KVCache

    cfg = ModelConfig(
        arch=mfile.ArchType.LLAMA, dim=64, hidden_dim=96, n_layers=2,
        n_heads=8, n_kv_heads=4, head_dim=8, vocab_size=128, seq_len=32,
        norm_epsilon=1e-5, rope_theta=10000.0,
        rope_type=mfile.RopeType.LLAMA)
    params = init_random_params(cfg, seed=31, quantized=True)
    tokens = jnp.asarray([[3, 1, 4, 1, 5, 9, 2, 6]], dtype=jnp.int32)

    # fresh lambdas per mode: jit wrappers around the SAME function object
    # share the global pjit executable cache, which would reuse the exact
    # program for the fast run and make this test vacuous
    monkeypatch.setenv("DLLAMA_TPU_QUANT_KERNEL", "xla")
    monkeypatch.setenv("DLLAMA_TPU_QUANT_MODE", "exact")
    exact, _ = jax.jit(lambda p, c, t, s, k: forward(p, c, t, s, k),
                       static_argnums=1)(
        params, cfg, tokens, jnp.int32(0), KVCache.create(cfg))
    monkeypatch.setenv("DLLAMA_TPU_QUANT_MODE", "fast")
    fast, _ = jax.jit(lambda p, c, t, s, k: forward(p, c, t, s, k),
                      static_argnums=1)(
        params, cfg, tokens, jnp.int32(0), KVCache.create(cfg))

    e = np.asarray(exact, np.float32)
    f = np.asarray(fast, np.float32)
    assert not np.array_equal(e, f)  # the mode switch actually engaged
    rms = float(np.sqrt(np.mean(e ** 2)))
    drift = float(np.abs(f - e).max()) / rms
    assert drift < 5e-2, drift
    np.testing.assert_array_equal(e.argmax(-1), f.argmax(-1))


# ---------------------------------------------------------------------------
# decode-shaped FUSED dequant-GEMV kernel (DLLAMA_TPU_QUANT_KERNEL=fused):
# one full-K pass per N stripe, dequant in-register — BIT-PARITY with the
# XLA fused-dequant reference in exact mode (the single full-K dot keeps
# the reference's reduction structure; the tiled kernel's blocked
# k-accumulation cannot make this claim)
# ---------------------------------------------------------------------------

from dllama_tpu.ops.linear import dequantize_weight  # noqa: E402
from dllama_tpu.ops.quant_matmul import supports_decode  # noqa: E402


def _xla_fused_dequant(x, w, fast=False):
    """The XLA fused-dequant reference linear() falls back to — computed
    with the same ops, so the kernel's parity target is the real thing."""
    wd = dequantize_weight(w, dtype=jnp.bfloat16 if fast else x.dtype)
    xr = x.astype(jnp.bfloat16) if fast else x
    return jax.lax.dot_general(
        xr, wd, dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


@pytest.mark.parametrize("m,n,k", [
    (1, 256, 512),     # decode step
    (16, 512, 1024),   # FUSED_MAX_M edge (verify width)
    (4, 96, 96),       # whole-N block, tiny-K
    (2, 128, 2048),    # multi-chunk scale expansion (bk_e < K)
])
def test_fused_kernel_bit_parity_q40(m, n, k):
    w = _mk(n, k, seed=n + k)
    x = jnp.asarray(np.random.default_rng(m).standard_normal((m, k)),
                    jnp.float32)
    assert supports_decode((m, k), w)
    got = quant_matmul(x, w, interpret=True, fused=True)
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(_xla_fused_dequant(x, w)))


def test_fused_kernel_bit_parity_q80_planes():
    """Q80 weights land in the same (scales, int8-codes) planes; the fused
    kernel consumes them unchanged and stays bit-parity."""
    from dllama_tpu.formats.quants import quantize_q80, unpack_q80
    from dllama_tpu.ops.linear import QuantizedWeight

    rng = np.random.default_rng(5)
    w = (rng.standard_normal((256, 512)) * 0.1).astype(np.float32)
    scales, codes = unpack_q80(quantize_q80(w.reshape(-1)), w.size)
    qw = QuantizedWeight(
        scales=jnp.asarray(scales.reshape(256, 16).T.astype(np.float32)),
        codes=jnp.asarray(np.ascontiguousarray(codes.reshape(256, 512).T)))
    assert int(np.abs(np.asarray(qw.codes)).max()) > 8  # genuinely 8-bit
    x = jnp.asarray(rng.standard_normal((1, 512)), jnp.float32)
    got = quant_matmul(x, qw, interpret=True, fused=True)
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(_xla_fused_dequant(x, qw)))


def test_fused_kernel_fast_mode_drift_bounded():
    """Fast mode (bf16 dequant, one MXU pass, f32 accumulation): the XLA
    reference's in-jaxpr fusion may elide the bf16 rounding of the dequant
    transient, so fast parity is drift-bounded (bf16-rounding-sized), not
    bitwise — same contract as the tiled kernel's fast mode."""
    w = _mk(256, 2048, seed=77)
    x = jnp.asarray(np.random.default_rng(9).standard_normal((1, 2048)),
                    jnp.float32)
    fast = np.asarray(quant_matmul(x, w, interpret=True, fused=True,
                                   fast=True))
    exact = np.asarray(quant_matmul(x, w, interpret=True, fused=True))
    rel = np.abs(fast - exact) / np.maximum(np.abs(exact), 1e-3)
    assert float(np.median(rel)) < 3e-3, float(np.median(rel))
    rms = float(np.sqrt(np.mean(exact ** 2)))
    assert float(np.abs(fast - exact).max()) / rms < 2e-2


def test_fused_exact_bf16_graph_mirrors_reference_dequant():
    """An exact-mode bf16 activation graph: the kernel dequantizes at
    bf16 like the XLA reference (dequant-at-activation-dtype rule), so
    xla↔fused drift is bf16-rounding-sized — NOT bitwise (XLA fusion may
    elide the bf16 rounding on either side; the bitwise claim is scoped
    to f32 graphs)."""
    w = _mk(256, 512, seed=61)
    x = jnp.asarray(np.random.default_rng(3).standard_normal((1, 512)),
                    jnp.bfloat16)
    got = np.asarray(quant_matmul(x, w, interpret=True, fused=True),
                     np.float32)
    want = np.asarray(_xla_fused_dequant(x.astype(jnp.float32), w),
                      np.float32)
    rms = float(np.sqrt(np.mean(want ** 2)))
    assert float(np.abs(got - want).max()) / rms < 2e-2


def test_fused_falls_back_to_tiled_for_prefill_widths():
    """fused=True on an M > FUSED_MAX_M dispatch silently takes the tiled
    kernel — a fused-mode engine never fails on its prefill chunks."""
    from dllama_tpu.ops.quant_matmul import FUSED_MAX_M

    m = FUSED_MAX_M * 2
    w = _mk(256, 512, seed=31)
    x = jnp.asarray(np.random.default_rng(4).standard_normal((m, 512)),
                    jnp.float32)
    assert not supports_decode((m, 512), w)
    got = quant_matmul(x, w, interpret=True, fused=True)
    want = quant_matmul(x, w, interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_fused_mode_gate(monkeypatch):
    """DLLAMA_TPU_QUANT_KERNEL=fused resolves through pallas_mode_gate
    (the ONE gate): fused kwargs off-TPU carry interpret=True; auto never
    resolves to fused (a built-but-unpromoted mode, à la turbo)."""
    from dllama_tpu.ops.quant_matmul import pallas_mode_gate

    monkeypatch.setenv("DLLAMA_TPU_QUANT_KERNEL", "fused")
    for fast in (False, True):
        kw = pallas_mode_gate(fast)
        assert kw is not None and kw["fused"] is True
        assert kw["interpret"] is True  # off-TPU test path
    monkeypatch.delenv("DLLAMA_TPU_QUANT_KERNEL", raising=False)
    kw = pallas_mode_gate(False)
    assert kw is None or "fused" not in kw


def test_fused_mode_linear_end_to_end(monkeypatch):
    """linear() under DLLAMA_TPU_QUANT_KERNEL=fused dispatches the decode
    kernel for a decode-shaped activation and matches the XLA reference
    bitwise (exact numerics)."""
    monkeypatch.setenv("DLLAMA_TPU_QUANT_MODE", "exact")
    w = _mk(256, 512, seed=41)
    x = jnp.asarray(np.random.default_rng(6).standard_normal((1, 512)),
                    jnp.float32)
    monkeypatch.setenv("DLLAMA_TPU_QUANT_KERNEL", "xla")
    want = linear(x, w)
    monkeypatch.setenv("DLLAMA_TPU_QUANT_KERNEL", "fused")
    got = linear(x, w)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_fused_sharded_col_split_matches_oracle():
    """The shard_map-wrapped fused kernel under a tp mesh (col-split: the
    decode hot path's wo/w2 merges)."""
    plan = make_tp_mesh(2)
    w = _mk(256, 512, seed=51)
    x = _x3(1, 4, 512, seed=52)
    want = linear(x, w)
    got = quant_matmul_sharded(plan, x, w, in_axis="hidden",
                               interpret=True, fused=True)
    assert got is not None
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_linear_dispatches_sharded_kernel_under_plan(monkeypatch):
    """linear() no longer bypasses the kernel under a mesh plan
    (VERDICT round-1 weak #2): DLLAMA_TPU_QUANT_KERNEL=pallas + plan routes
    through quant_matmul_sharded in interpret mode off-TPU."""
    monkeypatch.setenv("DLLAMA_TPU_QUANT_KERNEL", "pallas")
    plan = make_tp_mesh(2)
    w = _mk(256, 512, seed=13)
    x = _x3(1, 4, 512)
    monkeypatch.setenv("DLLAMA_TPU_QUANT_KERNEL", "xla")
    want = linear(x, w)
    monkeypatch.setenv("DLLAMA_TPU_QUANT_KERNEL", "pallas")
    with use_plan(plan):
        got = linear(x, w, out_axis="hidden")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)
