"""Pallas Q40 matmul kernel vs the XLA dequant+dot oracle (the parity
methodology of nn-vulkan-test.cpp: accelerated op vs reference semantics)."""

import numpy as np
import jax.numpy as jnp
import pytest

from dllama_tpu.ops.linear import linear, quantize_weight_q40
from dllama_tpu.ops.quant_matmul import quant_matmul, supports


def _mk(out, in_, seed=0):
    rng = np.random.default_rng(seed)
    w = (rng.standard_normal((out, in_)) * 0.1).astype(np.float32)
    return quantize_weight_q40(w)


@pytest.mark.parametrize("m,n,k", [
    (1, 256, 512),     # decode step
    (8, 512, 1024),    # small prefill
    (32, 128, 256),    # reference nBatches
    (16, 64, 128),     # kv-proj-like narrow output
])
def test_kernel_matches_xla_oracle(m, n, k):
    w = _mk(n, k, seed=n + k)
    x = jnp.asarray(np.random.default_rng(m).standard_normal((m, k)), jnp.float32)
    want = linear(x, w)
    got = quant_matmul(x, w, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_kernel_3d_batch():
    w = _mk(256, 512, seed=1)
    x = jnp.asarray(np.random.default_rng(2).standard_normal((2, 3, 512)), jnp.float32)
    want = linear(x, w)
    got = quant_matmul(x, w, interpret=True)
    assert got.shape == (2, 3, 256)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_supports_predicate():
    assert supports((1, 512), _mk(256, 512))
    assert supports((1, 96), _mk(256, 96))  # K=96: whole-K block (÷32)
    assert supports((1, 512), _mk(96, 512))  # N=96: whole-N block
    # K mismatch between x and w is never dispatched to the kernel
    assert not supports((1, 256), _mk(96, 512))
    # oversized batch falls back to XLA (VMEM bound on the un-tiled M axis)
    assert not supports((2048, 512), _mk(96, 512))
    # stacked (3D) weights fall back to XLA
    from dllama_tpu.ops.linear import QuantizedWeight

    w = _mk(96, 512)
    stacked = QuantizedWeight(scales=w.scales[None], codes=w.codes[None])
    assert not supports((1, 512), stacked)
