"""Turbo quant mode (ops/turbo.py): per-column int8 weights, integer dots.

The reference's Q80xQ40 integer-dot shape (nn-cpu-ops.cpp:229-447) mapped
to the MXU: scales leave the per-element hot loop and apply at the output.
Opt-in via DLLAMA_TPU_QUANT_MODE=turbo (a8 activations) / turbo16 (bf16
activations); these tests bound its drift against the exact dequant oracle
and drive the engine end-to-end under the knob.
"""

from __future__ import annotations

import os

import numpy as np
import pytest


def _mk_qw(rng, out, in_, stacked_layers=0):
    from dllama_tpu.ops.linear import quantize_weight_q40

    if not stacked_layers:
        return quantize_weight_q40(
            (rng.standard_normal((out, in_)) * 0.1).astype(np.float32))
    from dllama_tpu.models.llama import _stack_weights

    return _stack_weights([
        quantize_weight_q40(
            (rng.standard_normal((out, in_)) * 0.1).astype(np.float32))
        for _ in range(stacked_layers)])


def test_derive_matches_numpy_oracle():
    import jax.numpy as jnp

    from dllama_tpu.ops.linear import dequantize_weight
    from dllama_tpu.ops.turbo import derive_turbo

    rng = np.random.default_rng(3)
    qw = _mk_qw(rng, 128, 256)
    tw = derive_turbo(qw)

    dense = np.asarray(dequantize_weight(qw, dtype=jnp.float32))
    amax = np.abs(dense).max(axis=0)
    scale = np.where(amax > 0, amax / 127.0, 1.0)
    w8 = np.clip(np.round(dense / scale[None, :]), -127, 127).astype(np.int8)
    # XLA lowers the divide as multiply-by-reciprocal, so codes sitting on a
    # .5 rounding boundary may differ by one step from the numpy oracle —
    # allow that, and bound the reconstruction error instead (the contract
    # that matters for the matmul)
    assert np.abs(np.asarray(tw.w8, np.int16) - w8.astype(np.int16)).max() <= 1
    np.testing.assert_allclose(np.asarray(tw.scale), scale, rtol=1e-6)
    recon = np.asarray(tw.w8, np.float32) * np.asarray(tw.scale)[None, :]
    assert np.abs(recon - dense).max() <= scale.max() + 1e-7


def test_derive_zero_column_guard():
    import jax.numpy as jnp

    from dllama_tpu.ops.linear import QuantizedWeight
    from dllama_tpu.ops.turbo import derive_turbo

    qw = QuantizedWeight(scales=jnp.zeros((2, 64), jnp.float32),
                         codes=jnp.zeros((64, 64), jnp.int8))
    tw = derive_turbo(qw)
    assert np.all(np.asarray(tw.scale) == 1.0)  # no div-by-zero
    assert np.all(np.asarray(tw.w8) == 0)


def test_stacked_derive_equals_per_layer():
    from dllama_tpu.ops.linear import QuantizedWeight
    from dllama_tpu.ops.turbo import derive_turbo

    rng = np.random.default_rng(5)
    stacked = _mk_qw(rng, 64, 128, stacked_layers=3)
    tw = derive_turbo(stacked)
    for l in range(3):
        one = derive_turbo(QuantizedWeight(scales=stacked.scales[l],
                                           codes=stacked.codes[l]))
        np.testing.assert_array_equal(np.asarray(tw.w8[l]), np.asarray(one.w8))
        np.testing.assert_allclose(np.asarray(tw.scale[l]),
                                   np.asarray(one.scale), rtol=1e-6)


@pytest.mark.parametrize("a8", [True, False])
def test_turbo_matmul_drift_bounded(a8):
    import jax.numpy as jnp

    from dllama_tpu.ops.linear import dequantize_weight
    from dllama_tpu.ops.turbo import derive_turbo, turbo_matmul

    rng = np.random.default_rng(11)
    qw = _mk_qw(rng, 256, 512)
    tw = derive_turbo(qw, a8=a8)
    x = jnp.asarray(rng.standard_normal((4, 512)), jnp.bfloat16)

    got = np.asarray(turbo_matmul(x, tw), np.float32)
    want = np.asarray(x.astype(jnp.float32)
                      @ dequantize_weight(qw, dtype=jnp.float32))
    rms = float(np.sqrt(np.mean(want ** 2)))
    drift = float(np.abs(got - want).max()) / max(rms, 1e-9)
    # a8 stacks activation quantization (~1/254 rel) on weight requant
    assert drift < (8e-2 if a8 else 5e-2), drift


def test_linear_dispatches_turbo(monkeypatch):
    import jax.numpy as jnp

    from dllama_tpu.ops.linear import linear
    from dllama_tpu.ops.turbo import derive_turbo

    rng = np.random.default_rng(13)
    qw = _mk_qw(rng, 128, 256)
    x = jnp.asarray(rng.standard_normal((1, 4, 256)), jnp.bfloat16)
    # the mode rides ON the weight — env changes after derivation are inert
    monkeypatch.setenv("DLLAMA_TPU_QUANT_MODE", "auto")
    y16 = np.asarray(linear(x, derive_turbo(qw, a8=False)), np.float32)
    y8 = np.asarray(linear(x, derive_turbo(qw, a8=True)), np.float32)
    ref = np.asarray(linear(x.astype(jnp.float32), qw), np.float32)
    rms = float(np.sqrt(np.mean(ref ** 2)))
    assert float(np.abs(y16 - ref).max()) / rms < 5e-2
    assert float(np.abs(y8 - ref).max()) / rms < 8e-2


def test_engine_end_to_end_turbo(tmp_path, monkeypatch):
    """The CLI-facing path: load a tiny model with the knob set; every Q40
    plane becomes a TurboWeight, decode runs, and the transcript is
    deterministic across a fresh engine."""
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from helpers import byte_vocab_tokenizer, tiny_header_params, \
        write_tiny_model

    from dllama_tpu.formats import tfile
    from dllama_tpu.ops.turbo import TurboWeight
    from dllama_tpu.runtime.engine import InferenceEngine

    rng = np.random.default_rng(7)
    m, t = tmp_path / "m.m", tmp_path / "t.t"
    write_tiny_model(m, tiny_header_params(vocab_size=268, seq_len=96), rng)
    tfile.write_tfile(t, byte_vocab_tokenizer())

    monkeypatch.setenv("DLLAMA_TPU_QUANT_MODE", "turbo")
    eng = InferenceEngine(str(m), str(t), temperature=0.0, seed=3,
                          compute_dtype="bfloat16")
    assert isinstance(eng.params.layers.wq, TurboWeight)
    r1 = eng.generate([2, 5, 9], max_tokens=8)
    eng2 = InferenceEngine(str(m), str(t), temperature=0.0, seed=3,
                           compute_dtype="bfloat16")
    r2 = eng2.generate([2, 5, 9], max_tokens=8)
    assert r1.tokens == r2.tokens
    assert len(r1.tokens) > 0


@pytest.mark.parametrize("a8", [True, False])
def test_turbo_tp_matches_unsharded(monkeypatch, a8):
    """Turbo planes under a tp mesh (param_shardings TurboWeight branch +
    auto-sharded integer dots — including the a8 row-amax + s8xs8->s32
    epilogue under GSPMD) reproduce the single-device turbo logits."""
    import jax
    import jax.numpy as jnp

    from dllama_tpu.formats import mfile
    from dllama_tpu.models import ModelConfig, init_random_params
    from dllama_tpu.models.llama import forward
    from dllama_tpu.ops.turbo import TurboWeight, turbo_params
    from dllama_tpu.parallel import use_plan
    from dllama_tpu.parallel.api import make_tp_mesh
    from dllama_tpu.parallel.sharding import kv_cache_sharding, shard_params
    from dllama_tpu.runtime import KVCache

    monkeypatch.setenv("DLLAMA_TPU_QUANT_MODE",
                       "turbo" if a8 else "turbo16")
    cfg = ModelConfig(
        arch=mfile.ArchType.LLAMA, dim=64, hidden_dim=128, n_layers=2,
        n_heads=4, n_kv_heads=4, head_dim=16, vocab_size=96, seq_len=32,
        norm_epsilon=1e-5, rope_theta=10000.0, rope_type=mfile.RopeType.LLAMA,
        compute_dtype="bfloat16")
    params = turbo_params(init_random_params(cfg, seed=17, quantized=True),
                          a8=a8)
    assert isinstance(params.layers.wq, TurboWeight)
    tokens = jnp.asarray([[3, 1, 4, 1, 5]], jnp.int32)

    ref_logits, _ = jax.jit(forward, static_argnums=1)(
        params, cfg, tokens, jnp.int32(0), KVCache.create(cfg))

    plan = make_tp_mesh(2)
    sharded = shard_params(plan, params)
    kv = jax.device_put(KVCache.create(cfg),
                        kv_cache_sharding(plan, KVCache.create(cfg)))
    with use_plan(plan):
        tp_logits, _ = jax.jit(forward, static_argnums=1)(
            sharded, cfg, tokens, jnp.int32(0), kv)
    np.testing.assert_allclose(np.asarray(tp_logits),
                               np.asarray(ref_logits), rtol=2e-2, atol=2e-2)
