"""Cross-implementation token parity vs the reference C++ binary.

The committed goldens (tests/goldens/*.json, produced by
tools/golden_reference.py running the actual reference ``dllama`` binary)
record the reference's greedy transcript and perplexity on tiny synthetic
models written by our own format writers. These tests rebuild the identical
assets from the seeded RNG and assert the TPU engine reproduces the
reference's output token-for-token — the macbeth.sh determinism strategy
(reference: examples/macbeth.sh:1-60) without needing a real checkpoint.

The engine is driven exactly the way the reference CLI drives itself,
including its off-by-one (src/dllama.cpp:54): decode is seeded with token id 0
instead of the last prompt token (see tools/golden_reference.py docstring).
"""

from __future__ import annotations

import pytest

from dllama_tpu.formats.quants import F32, Q80
from dllama_tpu.runtime.engine import InferenceEngine

import golden_assets

BUFFER_TYPES = {"f32": F32, "q80": Q80}


def _engine_for(variant: str, tmp_path, tp: int,
                spec_lookup: int = 0) -> tuple[InferenceEngine, dict]:
    golden = golden_assets.load_golden(variant)
    if golden is None:
        pytest.skip(f"no golden for {variant} (run tools/golden_reference.py)")
    m, t, m_sha, t_sha = golden_assets.build_assets(variant, tmp_path)
    if m_sha != golden["m_sha256"] or t_sha != golden["t_sha256"]:
        pytest.skip("synthetic assets no longer match the golden's hashes "
                    "(numpy RNG stream changed?) — regenerate goldens")
    eng = InferenceEngine(
        str(m), str(t), tp=tp,
        sync_type=BUFFER_TYPES[golden["buffer_float_type"]],
        compute_dtype="float32", spec_lookup=spec_lookup,
        temperature=golden["temperature"], topp=golden.get("topp", 0.9),
        seed=golden["sampler_seed"])
    return eng, golden


@pytest.mark.parametrize("variant,tp", [
    ("llama_q40", 1),
    ("llama_q40", 2),  # TP must not change tokens (reference TP invariance)
    ("llama_f32", 1),
    ("qwen3_q40", 1),
    ("llama31_q40", 1),    # rope-scaling math vs the reference, not our oracle
    ("llama31_q40", 2),
    ("qwen3_q40", 2),
    ("llama_sampled_f32", 1),  # temp 0.7 top-p: xorshift+sampler vs the binary
    ("llama_sampled_f32", 2),  # sampling must be TP-invariant too
    ("llama_deep_f32", 1),  # 8 layers × 292 pieces: accumulation-order drift
    ("qwen3_deep_f32", 1),  # deep per-head-norm + neox-rope coverage
    pytest.param("llama_macbeth_f32", 1, marks=pytest.mark.slow),  # 2049 steps
])
def test_transcript_matches_reference(variant, tp, tmp_path):
    eng, golden = _engine_for(variant, tmp_path, tp)
    try:
        ids = eng.tokenizer.encode(golden["prompt"], is_start=True)
        # prompt "w001 ... w008 " must encode as [bos, 1..8]
        data = golden_assets.word_vocab_tokenizer()
        assert ids == [data.bos_id] + list(range(1, 9))

        got, res = golden_assets.replay_reference_driver(eng, golden)
        assert len(res.tokens) == len(golden["pieces"])
        assert got == golden["pieces"], (
            f"token divergence at index "
            f"{next(i for i, (a, b) in enumerate(zip(got, golden['pieces'])) if a != b)}")
    finally:
        eng.close()


def test_transcript_matches_reference_with_speculation(tmp_path):
    """The reference-binary golden reproduced BY the speculative decode path:
    cross-implementation parity through verify dispatches (greedy speculation
    is exact, so the transcript must be identical token-for-token)."""
    eng, golden = _engine_for("llama_q40", tmp_path, tp=1, spec_lookup=4)
    if golden["temperature"] != 0.0:
        eng.close()
        pytest.skip("speculation is greedy-only")
    try:
        ids = eng.tokenizer.encode(golden["prompt"], is_start=True)
        drive = ids[:-1] + [golden["effective_seed_token"]]
        res = eng.generate(drive, max_tokens=len(golden["pieces"]),
                           stop_on_eos=False)
        eng.tokenizer.reset_decoder()
        got = [p if (p := eng.tokenizer.decode(tok)) is not None else "~"
               for tok in res.tokens]
        assert got == golden["pieces"]
    finally:
        eng.close()


# llama_sampled_f32 shares llama_f32's model bytes (same header/seed) and
# perplexity is sampler-independent — its ppl case would duplicate llama_f32's
@pytest.mark.parametrize("variant", [v for v in golden_assets.VARIANTS
                                     if v != "llama_sampled_f32"])
def test_perplexity_matches_reference(variant, tmp_path):
    eng, golden = _engine_for(variant, tmp_path, tp=1)
    try:
        ids = eng.tokenizer.encode(golden["perplexity"]["prompt"], is_start=True)
        ppl = eng.perplexity(ids)
        want = golden["perplexity"]["perplexity"]
        assert ppl == pytest.approx(want, rel=1e-3), (ppl, want)
    finally:
        eng.close()
