"""Telemetry registry semantics: counter/gauge/histogram behavior, Prometheus
text rendering, JSONL span schema, thread-safety (raw and under the
BatchScheduler loop), and the zero-duration GenerationResult guards."""

import json
import threading

import numpy as np
import pytest

from dllama_tpu.formats import tfile
from dllama_tpu.runtime import telemetry as tm

from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model


def fresh() -> tm.Registry:
    return tm.Registry()


# -- counter/gauge/histogram semantics ---------------------------------------


def test_counter_monotonic_and_labels():
    r = fresh()
    c = r.counter(tm.HTTP_REQUESTS)
    c.inc(route="/metrics", status="200")
    c.inc(2, route="/metrics", status="200")
    c.inc(route="/v1/models", status="404")
    assert c.total(route="/metrics", status="200") == 3
    assert c.total(route="/v1/models", status="404") == 1
    assert c.total() == 4  # unlabeled total sums every series
    c.inc(route="/metrics", status="500")
    assert c.total(route="/metrics") == 4  # subset match sums all statuses
    assert c.total(status="200") == 3
    with pytest.raises(ValueError):
        c.inc(-1)


def test_gauge_set_add_value():
    r = fresh()
    g = r.gauge(tm.QUEUE_DEPTH)
    assert g.value() == 0.0
    g.set(5)
    g.add(-2)
    assert g.value() == 3.0


def test_histogram_buckets_sum_count_quantile():
    r = fresh()
    h = r.histogram(tm.TTFT_MS)
    for v in (0.2, 3.0, 3.0, 40.0, 10**6):  # 10**6 lands in +Inf overflow
        h.record(v)
    assert h.count() == 5
    assert h.sum() == pytest.approx(0.2 + 3.0 + 3.0 + 40.0 + 10**6)
    # median of {0.2, 3, 3, 40, 1e6} is 3.0 -> bucket upper bound 5.0
    assert h.quantile(0.5) == 5.0
    assert h.quantile(0.0) <= h.quantile(1.0)


def test_registry_rejects_unknown_and_mistyped_names():
    r = fresh()
    with pytest.raises(KeyError):
        r.counter("dllama_not_a_metric")
    with pytest.raises(TypeError):
        r.counter(tm.QUEUE_DEPTH)  # registered as a gauge


def test_reset_keeps_handles_valid():
    r = fresh()
    c = r.counter(tm.ADMISSIONS)
    c.inc(7)
    r.reset()
    assert c.total() == 0
    c.inc()
    assert c.total() == 1


# -- Prometheus text rendering ------------------------------------------------


def test_render_prometheus_text():
    r = fresh()
    r.counter(tm.HTTP_REQUESTS).inc(route="/v1/models", status="200")
    h = r.histogram(tm.ITL_MS)
    h.record(0.7)
    h.record(3.0)
    text = r.render()
    assert '# TYPE dllama_http_requests_total counter' in text
    assert 'dllama_http_requests_total{route="/v1/models",status="200"} 1' \
        in text
    # histogram: cumulative buckets, +Inf, sum, count
    assert 'dllama_itl_ms_bucket{le="1"} 1' in text
    assert 'dllama_itl_ms_bucket{le="5"} 2' in text
    assert 'dllama_itl_ms_bucket{le="+Inf"} 2' in text
    assert 'dllama_itl_ms_count 2' in text
    assert 'dllama_itl_ms_sum 3.7' in text
    # an untouched metric still renders (full schema per scrape)
    assert 'dllama_kv_occupancy 0' in text
    # every spec'd metric has HELP + TYPE headers
    for name in tm.SPECS:
        assert f"# TYPE {name} " in text


def test_render_escapes_label_values():
    r = fresh()
    r.counter(tm.HTTP_REQUESTS).inc(route='a"b\nc', status="200")
    text = r.render()
    assert 'route="a\\"b\\nc"' in text


# -- JSONL span tracing -------------------------------------------------------


def test_span_tracer_jsonl_schema(tmp_path):
    out = tmp_path / "trace.jsonl"
    tr = tm.SpanTracer()
    assert not tr.enabled
    tr.emit(1, "queue", 0, 1)  # disabled: no file, no error
    tr.configure(str(out))
    assert tr.enabled
    tr.emit(7, "decode", 100, 250, slot=3, n_tokens=12)
    tr.emit(8, "prefill", 50, 90)
    tr.configure(None)
    assert not tr.enabled
    lines = [json.loads(ln) for ln in out.read_text().splitlines()]
    assert lines == [
        {"request_id": 7, "phase": "decode", "start_ns": 100, "end_ns": 250,
         "slot": 3, "n_tokens": 12},
        {"request_id": 8, "phase": "prefill", "start_ns": 50, "end_ns": 90,
         "slot": -1, "n_tokens": 0},
    ]
    assert all(ln["phase"] in tm.PHASES for ln in lines)


def test_span_tracer_ring_and_recent_requests():
    tr = tm.SpanTracer()
    # the ring records regardless of the file sink (GET /debug/requests
    # must work without --trace-out)
    tr.emit(3, "queue", 0, 1_000_000, slot=1)
    tr.emit(3, "prefill", 1_000_000, 3_000_000, slot=1, n_tokens=5)
    tr.emit(3, "decode", 3_000_000, 9_000_000, slot=1, n_tokens=4)
    tr.emit(4, "decode", 0, 2_000_000)
    out = tr.recent_requests()
    assert [r["request_id"] for r in out] == [4, 3]  # newest first
    r3 = out[1]
    assert r3["total_ms"] == pytest.approx(9.0)
    assert [p["phase"] for p in r3["phases"]] == ["queue", "prefill",
                                                  "decode"]
    assert r3["phases"][2]["ms"] == pytest.approx(6.0)
    assert r3["phases"][2]["start_ms"] == pytest.approx(3.0)
    # bounded: the ring caps at RING_SPANS spans, oldest dropped
    for i in range(tm.SpanTracer.RING_SPANS + 10):
        tr.emit(100 + i, "decode", 0, 1)
    assert len(tr._ring) == tm.SpanTracer.RING_SPANS
    assert tr.recent_requests(limit=10_000)[-1]["request_id"] > 4


def test_stats_line_folds_in_compile_counts():
    r = fresh()
    assert "compiles=" not in tm.stats_line(r)
    r.counter(tm.COMPILE_TOTAL).inc(3, scope="engine-1", program="forward")
    line = tm.stats_line(r)
    assert "compiles=3" in line and "retrace" not in line
    r.counter(tm.RETRACE_UNEXPECTED).inc(program="forward")
    assert "retrace=1!" in tm.stats_line(r)


# -- thread safety ------------------------------------------------------------


def test_registry_thread_safety_exact_totals():
    r = fresh()
    c = r.counter(tm.BATCH_TOKENS)
    h = r.histogram(tm.QUEUE_WAIT_MS)
    n_threads, n_iter = 8, 2000

    def hammer():
        for i in range(n_iter):
            c.inc()
            h.record(float(i % 100))

    threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.total() == n_threads * n_iter
    assert h.count() == n_threads * n_iter
    # bucket counts are consistent with the total count
    assert f"dllama_queue_wait_ms_count {n_threads * n_iter}" in r.render()


# -- instrumentation under the BatchScheduler loop ---------------------------


@pytest.fixture(scope="module")
def tiny_engine(tmp_path_factory):
    from dllama_tpu.runtime.engine import InferenceEngine

    d = tmp_path_factory.mktemp("telemetry")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(11)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96), rng)
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    engine = InferenceEngine(str(mpath), str(tpath), temperature=0.0, seed=3)
    yield engine
    engine.close()


def test_batch_scheduler_records_metrics(tiny_engine, tmp_path):
    from dllama_tpu.runtime.serving import BatchScheduler

    reg = tm.registry()
    trace = tmp_path / "sched.jsonl"
    tm.tracer().configure(str(trace))
    admissions0 = reg.counter(tm.ADMISSIONS).total()
    retires0 = reg.counter(tm.RETIRES).total()
    tokens0 = reg.counter(tm.BATCH_TOKENS).total()
    waits0 = reg.histogram(tm.QUEUE_WAIT_MS).count()
    steps0 = reg.histogram(tm.BATCH_STEP_MS).count()
    sched = BatchScheduler(tiny_engine, n_slots=2)
    try:
        tok = tiny_engine.tokenizer
        prompts = [tok.encode(p) for p in ("hello", "world", "hi there")]
        reqs = [sched.submit(ids, 5) for ids in prompts]
        for r in reqs:
            assert r.done.wait(timeout=120)
    finally:
        sched.close()
        tm.tracer().configure(None)
    assert reg.counter(tm.ADMISSIONS).total() - admissions0 == 3
    assert reg.counter(tm.RETIRES).total() - retires0 == 3
    assert reg.counter(tm.BATCH_TOKENS).total() - tokens0 >= 3
    assert reg.histogram(tm.QUEUE_WAIT_MS).count() - waits0 == 3
    assert reg.histogram(tm.BATCH_STEP_MS).count() - steps0 >= 1
    assert reg.gauge(tm.BATCH_SLOTS).value() == 2
    # all requests retired: their rows are reclaimable (kept only for
    # prefix reuse), so pooled KV occupancy must have dropped back to 0
    assert reg.gauge(tm.KV_OCCUPANCY).value() == 0.0
    # every request traced a queue→prefill→decode span chain, slots recorded
    spans = [json.loads(ln) for ln in trace.read_text().splitlines()]
    by_rid: dict = {}
    for s in spans:
        by_rid.setdefault(s["request_id"], set()).add(s["phase"])
    done_rids = [rid for rid, phases in by_rid.items()
                 if {"queue", "prefill", "decode"} <= phases]
    assert len(done_rids) >= 3
    decode_spans = [s for s in spans if s["phase"] == "decode"]
    assert all(s["end_ns"] >= s["start_ns"] and s["slot"] in (0, 1)
               for s in decode_spans)
    assert any(s["n_tokens"] > 0 for s in decode_spans)


def test_engine_decode_and_prefill_metrics(tiny_engine):
    reg = tm.registry()
    steps0 = reg.histogram(tm.DECODE_STEP_MS).count()
    dec0 = reg.counter(tm.DECODE_TOKENS).total()
    pre0 = reg.counter(tm.PREFILL_TOKENS).total()
    tiny_engine.reset()
    res = tiny_engine.generate("hello world", 4, stop_on_eos=False)
    assert len(res.tokens) == 4
    assert reg.counter(tm.DECODE_TOKENS).total() - dec0 == 4
    assert reg.histogram(tm.DECODE_STEP_MS).count() - steps0 == 4
    assert reg.counter(tm.PREFILL_TOKENS).total() - pre0 >= 1
    assert reg.histogram(tm.PREFILL_CHUNK_MS).count() >= 1
    assert reg.gauge(tm.HBM_NEED_BYTES).value() > 0
    assert reg.gauge(tm.KV_OCCUPANCY).value() == pytest.approx(
        tiny_engine.pos / tiny_engine.cfg.seq_len)


# -- GenerationResult zero-duration guards (satellite) ------------------------


def test_generation_result_zero_token_rates():
    from dllama_tpu.runtime.engine import GenerationResult, StepMetrics

    # 0 predicted tokens: no "pred" steps at all
    r = GenerationResult(tokens=[], text="", prompt_tokens=3,
                         steps=[StepMetrics("eval", 1.5, 3)])
    assert r.pred_tok_per_s == 0.0
    assert r.eval_tok_per_s > 0.0
    # a sub-resolution clock can report 0.0 ms for a real step
    r2 = GenerationResult(tokens=[1], text="x", prompt_tokens=1,
                          steps=[StepMetrics("pred", 0.0, 1),
                                 StepMetrics("eval", 0.0, 1)])
    assert r2.pred_tok_per_s == 0.0
    assert r2.eval_tok_per_s == 0.0
    assert GenerationResult([], "", 0).pred_tok_per_s == 0.0
