"""Q40/Q80 codec tests — mirrors the reference's quantize→dequantize tolerance
tests (reference: src/nn/nn-cpu-ops-test.cpp:83-100) plus byte-golden checks
against hand-computed block layouts (reference: converter/writer-test.py)."""

import struct

import numpy as np
import pytest

from dllama_tpu.formats import quants


def test_q40_roundtrip_tolerance():
    rng = np.random.default_rng(12345)
    x = (rng.standard_normal(4096) * 2.0).astype(np.float32)
    buf = quants.quantize_q40(x)
    assert len(buf) == quants.q40_bytes(4096)
    y = quants.dequantize_q40(buf, 4096)
    # Max error per element is ~ absmax/8 within each block; use the same
    # spirit as nn-cpu-ops-test.cpp's epsilon checks.
    err = np.abs(x - y).reshape(-1, 32)
    scale = np.abs(x.reshape(-1, 32)).max(axis=1, keepdims=True)
    # bound: clip asymmetry can cost up to absmax/8, plus half a step of rounding
    assert (err <= scale / 8.0 + scale / 16.0 + 1e-6).all()


def test_q80_roundtrip_tolerance():
    rng = np.random.default_rng(7)
    x = (rng.standard_normal(2048) * 3.0).astype(np.float32)
    buf = quants.quantize_q80(x)
    assert len(buf) == quants.q80_bytes(2048)
    y = quants.dequantize_q80(buf, 2048)
    scale = np.abs(x.reshape(-1, 32)).max(axis=1, keepdims=True)
    assert np.abs(x - y).max() <= (scale / 127.0).max() * 0.51 + 1e-6


def test_q40_block_layout_golden():
    # One block: element k = k - 8 (so absmax value is -8 at k=0 → d = -8/-8 = 1...
    # construct explicitly: x[k] = (k % 16) - 8 gives signed max -8).
    x = np.array([(k % 16) - 8 for k in range(32)], dtype=np.float32)
    buf = quants.quantize_q40(x)
    assert len(buf) == 18
    d = np.frombuffer(buf[:2], dtype=np.float16)[0]
    assert d == np.float16(1.0)  # signed absmax is -8 → d = -8/-8 = 1
    packed = np.frombuffer(buf[2:], dtype=np.uint8)
    lo = (packed & 0xF).astype(np.int8) - 8
    hi = (packed >> 4).astype(np.int8) - 8
    np.testing.assert_array_equal(lo, x[:16].astype(np.int8))
    np.testing.assert_array_equal(hi, x[16:].astype(np.int8))


def test_q80_block_layout_golden():
    x = np.linspace(-127, 127, 32).astype(np.float32)
    buf = quants.quantize_q80(x)
    d, = struct.unpack_from("<e", buf, 0)
    assert d == pytest.approx(1.0, rel=1e-3)
    q = np.frombuffer(buf, dtype=np.int8, count=32, offset=2)
    assert q[0] == -127 and q[-1] == 127


def test_q40_unpack_planes_shapes():
    rng = np.random.default_rng(3)
    rows, cols = 8, 64
    x = rng.standard_normal(rows * cols).astype(np.float32)
    buf = quants.quantize_q40(x)
    scales, codes = quants.unpack_q40(buf, rows * cols)
    assert scales.shape == (rows * cols // 32,)
    assert codes.shape == (rows * cols // 32, 32)
    assert codes.min() >= -8 and codes.max() <= 7
    recon = (codes.astype(np.float32) * scales[:, None].astype(np.float32)).reshape(-1)
    np.testing.assert_allclose(recon, quants.dequantize_q40(buf, rows * cols))


def test_zero_block():
    x = np.zeros(32, dtype=np.float32)
    np.testing.assert_array_equal(quants.dequantize_q40(quants.quantize_q40(x), 32), x)
    np.testing.assert_array_equal(quants.dequantize_q80(quants.quantize_q80(x), 32), x)


# -- adversarial roundtrip bounds (ISSUE-5 satellite) -------------------------
#
# Property-style blocks: all-zero, colmax at the clamp edge (x/d in
# (7.5, 8) clips to code 15 — the worst Q40 case), denormal magnitudes
# (the f16 scale rounds to 0), and ±large magnitudes. Documented per-block
# bound (in-range blocks): |err| <= absmax/8 (clip asymmetry) + absmax/16
# (half a rounding step) + 8·2^-24 (f16 scale subnormal quantum, which
# dominates once the scale itself denormalizes). Finite input must NEVER
# dequantize non-finite (the stored scale saturates at the f16 max —
# quants.py module docstring).


def _adversarial_blocks(rng):
    blocks = [
        np.zeros(32, np.float32),                       # all-zero
        np.full(32, 7.9, np.float32),                   # clamp edge...
        np.linspace(-8.0, 7.9, 32).astype(np.float32),  # ...with -absmax
        np.full(32, 1e-40, np.float32),                 # denormal block
        (rng.standard_normal(32) * 1e-39).astype(np.float32),
        (rng.standard_normal(32) * 1e4).astype(np.float32),   # ±large
        (rng.standard_normal(32) * 5e4).astype(np.float32),
        np.array([5e4] + [0.0] * 31, np.float32),       # lone spike
        -np.array([5e4] + [0.0] * 31, np.float32),
    ]
    # clamp-edge block where x/d lands in (7.5, 8): gmin = -8 wins the
    # signed max, d = 1, so +7.9 clips from code 16 to 15 (error 0.9 < 1)
    blocks[1][0] = -8.0
    return np.concatenate(blocks)


def _q40_bound(x):
    absmax = np.abs(x.reshape(-1, 32)).max(axis=1, keepdims=True)
    return absmax / 8.0 + absmax / 16.0 + 8.0 * 2.0 ** -24 + 1e-30


def test_q40_adversarial_blocks_within_documented_bound():
    x = _adversarial_blocks(np.random.default_rng(0))
    y = quants.dequantize_q40(quants.quantize_q40(x), x.size)
    assert np.all(np.isfinite(y))
    err = np.abs(x - y).reshape(-1, 32)
    assert (err <= _q40_bound(x)).all(), \
        (err - _q40_bound(x)).max()


def test_q80_adversarial_blocks_within_documented_bound():
    x = _adversarial_blocks(np.random.default_rng(1))
    y = quants.dequantize_q80(quants.quantize_q80(x), x.size)
    assert np.all(np.isfinite(y))
    err = np.abs(x - y).reshape(-1, 32)
    absmax = np.abs(x.reshape(-1, 32)).max(axis=1, keepdims=True)
    # half a step of round-to-nearest + the f16 rounding of the stored
    # scale over up to 127 code steps (+ subnormal quantum for denormals)
    bound = absmax / 127.0 * 0.51 + absmax * 2.0 ** -11 \
        + 127.0 * 2.0 ** -24 + 1e-30
    assert (err <= bound).all(), (err - bound).max()


def test_finite_input_never_dequantizes_nonfinite():
    """Scale saturation: magnitudes whose block scale would overflow f16
    (absmax > 8·65504 for Q40, 127·65504 for Q80) used to dequantize to
    Inf/NaN; the stored scale now clamps to the finite f16 range."""
    for mag in (6e5, 1e20, 3e38):
        x = np.linspace(-mag, mag, 64).astype(np.float32)
        y40 = quants.dequantize_q40(quants.quantize_q40(x), 64)
        assert np.all(np.isfinite(y40)), mag
        y80 = quants.dequantize_q80(quants.quantize_q80(x), 64)
        assert np.all(np.isfinite(y80)), mag
    # in-range blocks are byte-identical to the unclamped encoding: the
    # stored f16 scales must equal the plain (clip-free) f16 rounding of
    # the reference scale formula d = signed_absmax / -8
    rng = np.random.default_rng(2)
    x = (rng.standard_normal(256) * 3.0).astype(np.float32)
    g = x.reshape(-1, 32)
    d = np.where(-g.min(axis=1) > g.max(axis=1),
                 g.min(axis=1), g.max(axis=1)) / -8.0
    stored = np.frombuffer(quants.quantize_q40_np(x), np.uint8) \
        .reshape(-1, quants.Q40_BLOCK_BYTES)[:, :2].copy() \
        .view(np.float16).reshape(-1)
    np.testing.assert_array_equal(stored, d.astype(np.float16))


def test_denormal_block_roundtrip_is_finite_and_bounded():
    """A block of denormal values rounds its f16 scale to 0: the
    reconstruction collapses to 0 (error <= absmax, trivially inside the
    f16-quantum term of the documented bound) and stays finite."""
    x = np.full(32, 1e-40, np.float32)
    y = quants.dequantize_q40(quants.quantize_q40(x), 32)
    assert np.all(np.isfinite(y))
    assert np.abs(x - y).max() <= np.abs(x).max() + 8.0 * 2.0 ** -24
