"""Q40/Q80 codec tests — mirrors the reference's quantize→dequantize tolerance
tests (reference: src/nn/nn-cpu-ops-test.cpp:83-100) plus byte-golden checks
against hand-computed block layouts (reference: converter/writer-test.py)."""

import struct

import numpy as np
import pytest

from dllama_tpu.formats import quants


def test_q40_roundtrip_tolerance():
    rng = np.random.default_rng(12345)
    x = (rng.standard_normal(4096) * 2.0).astype(np.float32)
    buf = quants.quantize_q40(x)
    assert len(buf) == quants.q40_bytes(4096)
    y = quants.dequantize_q40(buf, 4096)
    # Max error per element is ~ absmax/8 within each block; use the same
    # spirit as nn-cpu-ops-test.cpp's epsilon checks.
    err = np.abs(x - y).reshape(-1, 32)
    scale = np.abs(x.reshape(-1, 32)).max(axis=1, keepdims=True)
    # bound: clip asymmetry can cost up to absmax/8, plus half a step of rounding
    assert (err <= scale / 8.0 + scale / 16.0 + 1e-6).all()


def test_q80_roundtrip_tolerance():
    rng = np.random.default_rng(7)
    x = (rng.standard_normal(2048) * 3.0).astype(np.float32)
    buf = quants.quantize_q80(x)
    assert len(buf) == quants.q80_bytes(2048)
    y = quants.dequantize_q80(buf, 2048)
    scale = np.abs(x.reshape(-1, 32)).max(axis=1, keepdims=True)
    assert np.abs(x - y).max() <= (scale / 127.0).max() * 0.51 + 1e-6


def test_q40_block_layout_golden():
    # One block: element k = k - 8 (so absmax value is -8 at k=0 → d = -8/-8 = 1...
    # construct explicitly: x[k] = (k % 16) - 8 gives signed max -8).
    x = np.array([(k % 16) - 8 for k in range(32)], dtype=np.float32)
    buf = quants.quantize_q40(x)
    assert len(buf) == 18
    d = np.frombuffer(buf[:2], dtype=np.float16)[0]
    assert d == np.float16(1.0)  # signed absmax is -8 → d = -8/-8 = 1
    packed = np.frombuffer(buf[2:], dtype=np.uint8)
    lo = (packed & 0xF).astype(np.int8) - 8
    hi = (packed >> 4).astype(np.int8) - 8
    np.testing.assert_array_equal(lo, x[:16].astype(np.int8))
    np.testing.assert_array_equal(hi, x[16:].astype(np.int8))


def test_q80_block_layout_golden():
    x = np.linspace(-127, 127, 32).astype(np.float32)
    buf = quants.quantize_q80(x)
    d, = struct.unpack_from("<e", buf, 0)
    assert d == pytest.approx(1.0, rel=1e-3)
    q = np.frombuffer(buf, dtype=np.int8, count=32, offset=2)
    assert q[0] == -127 and q[-1] == 127


def test_q40_unpack_planes_shapes():
    rng = np.random.default_rng(3)
    rows, cols = 8, 64
    x = rng.standard_normal(rows * cols).astype(np.float32)
    buf = quants.quantize_q40(x)
    scales, codes = quants.unpack_q40(buf, rows * cols)
    assert scales.shape == (rows * cols // 32,)
    assert codes.shape == (rows * cols // 32, 32)
    assert codes.min() >= -8 and codes.max() <= 7
    recon = (codes.astype(np.float32) * scales[:, None].astype(np.float32)).reshape(-1)
    np.testing.assert_allclose(recon, quants.dequantize_q40(buf, rows * cols))


def test_zero_block():
    x = np.zeros(32, dtype=np.float32)
    np.testing.assert_array_equal(quants.dequantize_q40(quants.quantize_q40(x), 32), x)
    np.testing.assert_array_equal(quants.dequantize_q80(quants.quantize_q80(x), 32), x)
