"""Checksummed resilient weight loading (ISSUE 4 tentpole #1).

The manifest layer (formats/mfile.py: ``<model>.m.sums``) + the loader's
verify/retry guard (runtime/weights.py ResilientReader) + the offline
surfaces (``python -m dllama_tpu verify``, ``--verify-weights``). The
chaos-driven paths (failpoint retries, corruption mid-engine-load,
atomicity) live in test_chaos.py; this file covers the format and the
offline tooling.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

import helpers
from dllama_tpu.formats import mfile, quants
from dllama_tpu.runtime import telemetry as tm
from dllama_tpu.runtime.weights import (ResilientReader, WeightIntegrityError,
                                        verify_weights)


def _model(tmp_path, name="m.m", seed=5, manifest=True, **hdr):
    p = tmp_path / name
    helpers.write_tiny_model(p, helpers.tiny_header_params(**hdr),
                             np.random.default_rng(seed))
    if manifest:
        mfile.write_manifest(p)
    return p


def _flip(path, key, byte_off=3):
    with mfile.ModelFile.open(path) as mf:
        rec = mf.tensors[key]
    with open(path, "r+b") as f:
        f.seek(rec.offset + byte_off)
        b = f.read(1)
        f.seek(rec.offset + byte_off)
        f.write(bytes([b[0] ^ 0x40]))


# -- manifest format ----------------------------------------------------------


def test_manifest_roundtrip_and_open_picks_it_up(tmp_path):
    p = _model(tmp_path)
    with mfile.ModelFile.open(p) as mf:
        assert mf.checksums is not None
        assert set(mf.checksums) == set(mf.tensors)
        assert mf.checksums == mfile.compute_checksums(mf)


def test_missing_manifest_loads_unverified(tmp_path):
    p = _model(tmp_path, manifest=False)
    with mfile.ModelFile.open(p) as mf:
        assert mf.checksums is None  # legacy files stay loadable


def test_stale_manifest_rejected(tmp_path):
    p = _model(tmp_path)
    # the model is regenerated (self-consistent, different size) but the
    # old manifest is left behind: verification must refuse, not silently
    # check the wrong sums or skip
    helpers.write_tiny_model(p, helpers.tiny_header_params(n_layers=3),
                             np.random.default_rng(9))
    with pytest.raises(ValueError, match="stale|truncated"):
        mfile.ModelFile.open(p)


def test_stale_manifest_is_regenerable(tmp_path):
    """`verify --write` is what the stale-manifest error tells the user to
    run — regeneration must bypass (not validate) the sidecar it
    replaces, or the repair path is circular."""
    from dllama_tpu.serve.cli import main

    p = _model(tmp_path)
    helpers.write_tiny_model(p, helpers.tiny_header_params(n_layers=3),
                             np.random.default_rng(9))  # manifest now stale
    assert main(["verify", "--model", str(p), "--write"]) == 0
    assert main(["verify", "--model", str(p)]) == 0
    with mfile.ModelFile.open(p) as mf:  # and normal opens verify again
        assert mf.checksums is not None


def test_malformed_manifest_rejected(tmp_path):
    p = _model(tmp_path, manifest=False)
    with open(mfile.manifest_path(p), "w") as f:
        f.write("{not json")
    with pytest.raises(ValueError, match="malformed"):
        mfile.ModelFile.open(p)
    # wrong SHAPE (tensors as a list) must get the same clean refusal,
    # not an AttributeError traceback
    with open(mfile.manifest_path(p), "w") as f:
        json.dump({"version": 1, "algo": "crc32", "file_size": 1,
                   "tensors": [1, 2]}, f)
    with pytest.raises(ValueError, match="malformed"):
        mfile.ModelFile.open(p)


def test_wrong_algo_rejected(tmp_path):
    p = _model(tmp_path)
    mp = mfile.manifest_path(p)
    doc = json.load(open(mp))
    doc["algo"] = "md5"
    json.dump(doc, open(mp, "w"))
    with pytest.raises(ValueError, match="algo"):
        mfile.ModelFile.open(p)


# -- offline verification -----------------------------------------------------


def test_verify_weights_reports_every_corrupt_tensor(tmp_path):
    p = _model(tmp_path)
    _flip(p, "block_matmul_q.0")
    _flip(p, "block_norm_1.1")
    with mfile.ModelFile.open(p) as mf:
        res = verify_weights(mf)
    assert sorted(res["corrupt"]) == ["block_matmul_q.0", "block_norm_1.1"]
    assert res["tensors"] == len(mf.tensors)


def test_verify_weights_requires_manifest(tmp_path):
    p = _model(tmp_path, manifest=False)
    with mfile.ModelFile.open(p) as mf:
        with pytest.raises(WeightIntegrityError, match="no checksum"):
            verify_weights(mf)


def test_cli_verify_check_write_and_corrupt_rcs(tmp_path, capsys):
    from dllama_tpu.serve.cli import main

    p = str(_model(tmp_path, manifest=False))
    assert main(["verify", "--model", p]) == 2      # no manifest yet
    assert main(["verify", "--model", p, "--write"]) == 0
    assert main(["verify", "--model", p]) == 0       # clean
    _flip(p, "block_matmul_v.1")
    rc = main(["verify", "--model", p])
    assert rc == 1
    assert "block_matmul_v.1" in capsys.readouterr().out


def test_engine_verify_weights_flag_names_corrupt_tensor(tmp_path):
    from dllama_tpu.runtime.engine import InferenceEngine

    p = _model(tmp_path, vocab_size=268, seq_len=48)
    _flip(p, "block_matmul_wo.0")
    with pytest.raises(WeightIntegrityError, match=r"block_matmul_wo\.0"):
        InferenceEngine(str(p), verify_weights=True)
    # and the clean twin passes the full sweep then loads
    p2 = _model(tmp_path, name="clean.m", vocab_size=268, seq_len=48)
    eng = InferenceEngine(str(p2), verify_weights=True)
    try:
        logits, _ = eng.prefill([1, 2, 3])
        assert np.all(np.isfinite(np.asarray(logits)))
    finally:
        eng.close()


# -- resilient reader ---------------------------------------------------------


def test_resilient_reader_retry_budget_is_bounded(tmp_path):
    from dllama_tpu.runtime import failpoints as fp
    from dllama_tpu.runtime.weights import WeightLoadError

    p = _model(tmp_path)
    retries = tm.registry().counter(tm.WEIGHT_IO_RETRIES)
    r0 = retries.total()
    with mfile.ModelFile.open(p) as mf:
        rd = ResilientReader(mf, max_retries=2, backoff_s=0.001)
        fp.arm("load_read", "oserror")
        try:
            with pytest.raises(WeightLoadError, match="after 2 retries"):
                rd.tensor_f32("embedding")
        finally:
            fp.registry().clear()
        assert retries.total() == r0 + 2
        # non-transient failures are NOT retried: corrupt bytes raise once
        _flip(p, "final_norm")
        c0 = retries.total()
        with pytest.raises(WeightIntegrityError, match="final_norm"):
            rd.tensor_f32("final_norm")
        assert retries.total() == c0


def test_reader_verifies_each_tensor_once(tmp_path):
    p = _model(tmp_path)
    with mfile.ModelFile.open(p) as mf:
        rd = ResilientReader(mf)
        calls = []
        orig = mf.tensor_crc32
        mf.tensor_crc32 = lambda k: (calls.append(k), orig(k))[1]
        rd.tensor_f32_rows("embedding", 0, 4)
        rd.tensor_f32_rows("embedding", 4, 8)
        assert calls == ["embedding"]  # verified once, not per slice


# -- scales-only reader (the per-callback allocation bound fix) ---------------


@pytest.mark.parametrize("weight_type", [quants.Q40, quants.Q80])
def test_scales_only_reader_matches_pair_reader(tmp_path, weight_type):
    p = _model(tmp_path, manifest=False, weight_type=weight_type,
               dim=64, hidden_dim=96)
    with mfile.ModelFile.open(p) as mf:
        sub = (mf.tensor_q40_kmajor_sub if weight_type == quants.Q40
               else mf.tensor_q80_kmajor_sub)
        for key, (o_lo, o_hi, i_lo, i_hi) in [
                ("block_matmul_q.0", (0, 64, 0, 64)),
                ("block_matmul_q.0", (16, 48, 32, 64)),
                ("block_matmul_w2.1", (8, 40, 32, 96)),
        ]:
            want, _ = sub(key, o_lo, o_hi, i_lo, i_hi)
            got = mf.tensor_scales_kmajor_sub(key, o_lo, o_hi, i_lo, i_hi)
            np.testing.assert_array_equal(got, want)
            assert got.dtype == np.float32 and got.flags["C_CONTIGUOUS"]
