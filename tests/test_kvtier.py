"""Tiered KV memory (runtime/kvblocks.py host tier + HostKVMirror via
runtime/serving.PagedGenerator): host spill and page-back for cold
paged-KV blocks.

Three tiers of proof:

1. **Transfer round-trip** — a spilled block's device bytes equal the
   host mirror copy equal the paged-back-in device bytes, bit for bit
   (the whole tier is copies; any transform would break the resume
   bit-exactness contract).
2. **Capacity proof (THE ISSUE-15 acceptance)** — with the device pool
   deliberately sized below the workload's total KV, a multi-session
   idle/resume stream completes with zero KV-exhaustion 503s and zero
   requeues-for-capacity, every resumed session's tokens bitwise equal
   a never-spilled solo run, ``dllama_kv_spill_blocks_total > 0`` and
   ``dllama_kv_blocks_host_used > 0`` observed mid-run, and zero
   post-steady compiles with tiering on (ledger-asserted).
3. **Attribution** — resumed requests carry a ``pagein`` TTFT phase
   that sums with the others to wall TTFT; spill/pagein decisions land
   in the flight-recorder ticks and survive into the Chrome-trace
   export.

These run the REAL spill/page-back path on the CPU tier through the
``unpinned_host`` fallback (helpers.pinned_host_probe) instead of
capability-skipping like the pinned_host-only offload tests must.
"""

import numpy as np
import pytest

from dllama_tpu.formats import tfile
from dllama_tpu.runtime import flightrec, introspection
from dllama_tpu.runtime import telemetry as tm
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.runtime.serving import BatchScheduler, PagedGenerator, Request

from helpers import (byte_vocab_tokenizer, require_host_memory,
                     tiny_header_params, write_tiny_model)

PATHS = {}
BLOCK = 16


@pytest.fixture(scope="module")
def tiered_engine(tmp_path_factory):
    d = tmp_path_factory.mktemp("kvtier")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(41)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96),
                     rng)
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    PATHS["m"], PATHS["t"] = str(mpath), str(tpath)
    return InferenceEngine(str(mpath), str(tpath), tp=1, kv_block_size=BLOCK,
                           kv_host_blocks=64)


def _enc(engine, text):
    return engine.tokenizer.encode(text, is_start=True)


def _session_text(i: int) -> str:
    """Distinct 33-char session prompt: >= 2 full 16-row blocks of
    prefill-built context per session, so ~12 sessions exceed a 16-block
    device pool several times over."""
    return "".join(chr(97 + (i + j) % 26) for j in range(33))


def _run(sched, req, ticks=800):
    for _ in range(ticks):
        sched._tick()
        if req.done.is_set():
            return
    raise AssertionError(f"request {req.rid} never finished")


def test_engine_validates_host_blocks_need_block_size(tmp_path_factory):
    d = tmp_path_factory.mktemp("kvtier_val")
    mpath, tpath = d / "m.m", d / "t.t"
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96),
                     np.random.default_rng(1))
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    with pytest.raises(ValueError, match="--kv-host-blocks"):
        InferenceEngine(str(mpath), str(tpath), tp=1, kv_host_blocks=8)


def test_spill_pagein_roundtrip_is_bit_exact(tiered_engine):
    """Tier traffic is pure copies: device bytes -> host mirror bytes ->
    paged-back device bytes, all array-equal. Also pins the satellite
    contract that the CPU tier really exercises the transfer path (the
    probe's unpinned_host fallback, not a skip)."""
    kind = require_host_memory()
    gen = PagedGenerator(tiered_engine, n_slots=2)
    assert gen.mirror is not None and gen.mirror.kind == kind

    r = Request(rid=0, prompt_ids=_enc(tiered_engine, _session_text(0)),
                max_tokens=2, stop_on_eos=False)
    gen.admit(r, 0)
    while gen.n_active:
        gen.step()
    toks = _session_text(0)
    sh, n, _, _ = gen.pool.match_prefix(r.prompt_ids[:-1])
    assert n >= BLOCK and not gen.pool.is_host(sh[0])
    bid = sh[0]
    before_k = np.asarray(gen.pkv.k[:, bid]).copy()
    before_v = np.asarray(gen.pkv.v[:, bid]).copy()
    assert before_k.any(), "the block must hold real context rows"

    # pressure: drain the free list so the cached blocks spill
    taken = []
    while gen.pool._cached:
        taken.append(gen.pool.alloc())
    sh2, n2, _, _ = gen.pool.match_prefix(r.prompt_ids[:-1])
    assert n2 == n and gen.pool.is_host(sh2[0])
    assert gen.pool.host_used_blocks() > 0

    # the host mirror holds the exact bytes
    cid, lane = gen.mirror._where[sh2[0]]
    ch = gen.mirror._chunks[cid]
    np.testing.assert_array_equal(np.asarray(ch["k"])[:, lane], before_k)
    np.testing.assert_array_equal(np.asarray(ch["v"])[:, lane], before_v)

    # page back in: device bytes restored bit-exactly under the new id
    for b in taken[:2]:
        gen.pool.release(b)
    pairs = gen.pool.begin_pagein([sh2[0]])
    ref = [gen.pkv]
    gen.mirror.load(ref, pairs)
    gen.pkv = ref[0]
    gen.pool.commit_pagein(pairs)
    dev = pairs[0][1]
    np.testing.assert_array_equal(np.asarray(gen.pkv.k[:, dev]), before_k)
    np.testing.assert_array_equal(np.asarray(gen.pkv.v[:, dev]), before_v)
    sh3, n3, _, _ = gen.pool.match_prefix(r.prompt_ids[:-1])
    assert sh3[0] == dev and n3 == n


def test_capacity_proof_idle_resume_stream(tiered_engine):
    """THE acceptance: 12 idle sessions' KV (~36 blocks) through a
    16-block device pool + host tier, then resumes — zero exhaustion,
    zero requeues, resumed transcripts bitwise equal never-spilled solo
    runs, spill/host-used observed mid-run, ledger-quiet post-steady."""
    flightrec.recorder().reset()
    reg = tm.registry()
    exh0 = reg.counter(tm.KV_BLOCK_EXHAUSTION).total()
    spill0 = reg.counter(tm.KV_SPILL_BLOCKS).total()
    pagein0 = reg.counter(tm.KV_PAGEIN_BLOCKS).total()
    scope = tiered_engine.introspection_scope

    sched = BatchScheduler(tiered_engine, n_slots=2, _start_thread=False)
    assert sched.gen.pool.n_blocks - 1 == 16  # deliberately < workload KV
    assert sched.gen.pool.n_host_blocks > 0
    try:
        # steady-state warmup: prefill buckets (32/4/8 widths), the paged
        # step, CoW copy, and the tier transfer programs (init warmup +
        # post-first-step rewarm) all compile in this wave
        for i in (0, 1):
            _run(sched, sched.submit(_enc(tiered_engine, _session_text(i)),
                                     4, stop_on_eos=False))
        _run(sched, sched.submit(_enc(tiered_engine, "hello"), 4,
                                 stop_on_eos=False))
        _run(sched, sched.submit(
            _enc(tiered_engine, _session_text(0) + " warm"), 4,
            stop_on_eos=False))
        c0 = introspection.ledger().compile_count(scope)

        # idle wave: 10 more sessions, each completing then idling —
        # their cached blocks exceed the device pool, so cold ones spill
        for i in range(2, 12):
            r = sched.submit(_enc(tiered_engine, _session_text(i)), 4,
                             stop_on_eos=False)
            _run(sched, r)
            assert r.error is None, r.error
        spill_mid = reg.counter(tm.KV_SPILL_BLOCKS).total() - spill0
        host_used_mid = reg.gauge(tm.KV_BLOCKS_HOST_USED).value()
        assert spill_mid > 0, "pressure must have spilled cold blocks"
        assert host_used_mid > 0

        # resumes: each session comes back with its history + new text.
        # Oracle = a never-spilled fresh solo run of the same prompt.
        for i in (2, 5, 8, 11):
            prompt = _session_text(i) + " and then"
            solo = InferenceEngine(PATHS["m"], PATHS["t"], tp=1)
            want = solo.generate(prompt, 6, stop_on_eos=False).tokens
            solo.close()
            r = sched.submit(_enc(tiered_engine, prompt), 6,
                             stop_on_eos=False)
            _run(sched, r)
            assert r.error is None, r.error
            assert r.tokens == want, f"resume {i} diverged from solo"
        assert reg.counter(tm.KV_PAGEIN_BLOCKS).total() > pagein0

        # zero KV-exhaustion 503s / requeues-for-capacity
        assert reg.counter(tm.KV_BLOCK_EXHAUSTION).total() == exh0
        events = flightrec.recorder().snapshot()["events"]
        assert not [e for e in events if e["event"] == "requeue"]
        # spill + pagein decisions are on the tick record, and the host
        # occupancy rides the Chrome-trace kv_blocks counter track
        assert [e for e in events if e["event"] == "spill"]
        assert [e for e in events if e["event"] == "pagein"]
        trace = flightrec.to_chrome_trace(flightrec.recorder().snapshot())
        assert not flightrec.validate_chrome_trace(trace)
        host_counters = [e for e in trace["traceEvents"]
                        if e.get("name") == "kv_blocks"
                        and "host_used" in e.get("args", {})]
        assert host_counters

        # zero post-steady compiles with tiering on
        assert introspection.ledger().compile_count(scope) == c0, \
            "post-steady recompile with the KV tier on"
    finally:
        sched.close()


def test_resume_carries_pagein_ttft_phase(tiered_engine):
    """A resumed session's TTFT decomposition has a nonzero ``pagein``
    phase and the five phases sum to wall TTFT; the phase lands in the
    ``dllama_ttft_attrib_ms`` histogram and the span ring."""
    h = tm.registry().histogram(tm.TTFT_ATTRIB_MS)
    p0 = h.count(phase="pagein")
    sched = BatchScheduler(tiered_engine, n_slots=2, _start_thread=False)
    try:
        # distinct sessions for this test (module counters are shared)
        texts = [_session_text(13 + i) for i in range(10)]
        for txt in texts:
            r = sched.submit(_enc(tiered_engine, txt), 4, stop_on_eos=False)
            _run(sched, r)
        # ensure this session's blocks really are host-resident
        ids0 = _enc(tiered_engine, texts[0])
        sh, _, cow, _ = sched.gen.pool.match_prefix(ids0[:-1])
        assert any(sched.gen.pool.is_host(b) for b in sh), \
            "workload must have spilled the resumed session"
        r = sched.submit(_enc(tiered_engine, texts[0] + " resume"), 4,
                         stop_on_eos=False)
        _run(sched, r)
        assert r.error is None
        bd = r.ttft_breakdown()
        assert bd["pagein_ms"] > 0
        total = (bd["queue_ms"] + bd["pagein_ms"] + bd["admission_ms"]
                 + bd["prefill_ms"] + bd["first_decode_ms"])
        assert abs(total - bd["ttft_ms"]) <= 1e-6 * max(1.0, bd["ttft_ms"])
        assert h.count(phase="pagein") > p0
        spans = [s for s in tm.tracer().raw_spans()
                 if s["phase"] == "pagein" and s["request_id"] == r.rid]
        assert spans and spans[0]["n_tokens"] > 0
    finally:
        sched.close()


def test_mixed_tier_resume_under_pressure_pins_matched_blocks(
        tmp_path_factory):
    """Review regression: a resume whose match spans BOTH tiers under a
    bone-dry free list, with the matched DEVICE block sitting at the LRU
    end of the cached list. begin_admit must pin the device-resident
    matches BEFORE the page-in's own allocations resolve pressure
    against the cached LRU — unpinned, the staging drop-evicts the very
    block the match returned and then recycles its id as a page-in
    destination, so the later share() silently points the request's
    table at ANOTHER session's restored content (or, if unrecycled,
    dies with a spurious 'not shareable' reject). Pinned, the eviction
    routes to the unmatched cached blocks and the resume completes
    token-exact."""
    d = tmp_path_factory.mktemp("kvtier_pin")
    mpath, tpath = d / "m.m", d / "t.t"
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96),
                     np.random.default_rng(41))
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    # host tier of exactly 2 lanes: session A's two FULL blocks spill,
    # its partial CoW tail block stays device-cached (and, as the LRU
    # entry, is exactly what an unpinned staging would drop-evict)
    eng = InferenceEngine(str(mpath), str(tpath), tp=1, kv_block_size=BLOCK,
                          kv_host_blocks=2)
    try:
        sched = BatchScheduler(eng, n_slots=2, _start_thread=False)
        gen = sched.gen
        assert gen.pool.n_host_blocks == 2
        a_text = _session_text(50)  # 33 ids -> 2 full blocks + 1 tail
        solo = InferenceEngine(str(mpath), str(tpath), tp=1)
        want = solo.generate(a_text + " back", 4, stop_on_eos=False).tokens
        solo.close()
        for text in (a_text, _session_text(60)):  # A idles first (LRU)
            r = sched.submit(_enc(eng, text), 2, stop_on_eos=False)
            _run(sched, r)
            assert r.error is None
        # drain the free list, trigger ONE spill (A's two full blocks
        # fill the 2 host lanes; its tail + B's blocks stay cached on
        # device), then drain again: free list bone-dry, host tier full
        taken = []
        while gen.pool._free:
            taken.append(gen.pool.alloc())
        taken.append(gen.pool.alloc())
        while gen.pool._free:
            taken.append(gen.pool.alloc())
        ids_a = _enc(eng, a_text + " back")
        shared, n, cow, cow_r = gen.pool.match_prefix(ids_a[:-1])
        # the match spans both tiers: A's second full block (+ tail)
        # spilled into the 2 host lanes, its first full block stayed
        # device-cached — and sits at the LRU end of the cached list
        # (session B's admission CoW-touched it last via the shared BOS)
        assert any(gen.pool.is_host(b) for b in shared)
        dev_matched = [b for b in shared if not gen.pool.is_host(b)]
        assert dev_matched and cow is not None and cow_r > 0
        assert dev_matched[0] == next(iter(gen.pool._cached)), \
            "scenario setup: the matched device block must be the LRU"
        assert not gen.pool._free and gen.pool._cached
        # the resume: staging must take its device blocks from the
        # cached LRU — whose OLDEST entry is the matched device block.
        # The pin must route the eviction to the younger (unmatched)
        # cached blocks.
        resume = sched.submit(ids_a[:-1] + [ids_a[-1]], 4,
                              stop_on_eos=False)
        _run(sched, resume)
        assert resume.error is None, resume.error
        assert resume.tokens == want
        sched.close()
    finally:
        eng.close()


def test_host_budget_is_chunk_accounted_under_fragmentation(
        tmp_path_factory):
    """Review regression: mirror chunks are SPILL_BATCH blocks of host
    RAM whether or not every lane is live, so the budget is enforced in
    chunks — under fragmentation (a chunk alive on a few lanes after a
    partial page-in) a new spill first drains the host LRU until the
    fragmented chunk frees (oldest content pays, the tier keeps
    cycling), and resident chunks NEVER exceed the budget."""
    d = tmp_path_factory.mktemp("kvtier_frag")
    mpath, tpath = d / "m.m", d / "t.t"
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96),
                     np.random.default_rng(41))
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    eng = InferenceEngine(str(mpath), str(tpath), tp=1, kv_block_size=BLOCK,
                          kv_host_blocks=4)  # exactly ONE chunk of budget
    try:
        gen = PagedGenerator(eng, n_slots=2)
        assert gen.mirror.max_chunks == 1
        r = Request(rid=0, prompt_ids=_enc(eng, _session_text(70)),
                    max_tokens=2, stop_on_eos=False)
        gen.admit(r, 0)
        while gen.n_active:
            gen.step()
        taken = []
        while gen.pool._free:
            taken.append(gen.pool.alloc())
        taken.append(gen.pool.alloc())  # spills A's cold blocks: 1 chunk
        assert len(gen.mirror._chunks) == 1
        spilled = [b for b in list(gen.pool._host_cached)]
        assert spilled
        # partial page-in fragments the chunk: lanes free pool-side, but
        # the chunk stays resident on the survivors
        gen.pool.release(taken.pop())
        pairs = gen.pool.begin_pagein(spilled[:1])
        ref = [gen.pkv]
        gen.mirror.load(ref, pairs)
        gen.pkv = ref[0]
        gen.pool.commit_pagein(pairs)
        gen.pool.release(pairs[0][1])
        if not gen.pool._host_cached:
            pytest.skip("chunk fully drained — fragmentation state "
                        "not reachable with this geometry")
        assert len(gen.mirror._chunks) == 1  # fragmented, still resident
        assert gen.pool._host_free, "lanes freed pool-side"
        # new cold content under pressure: the fragmented chunk's stale
        # survivors drain (oldest-first) so the chunk frees, the NEW
        # content spills into a fresh chunk — and the resident count
        # never exceeds the 1-chunk budget
        reg = tm.registry()
        s0 = reg.counter(tm.KV_SPILL_BLOCKS).total()
        r2 = Request(rid=1, prompt_ids=_enc(eng, _session_text(80)),
                     max_tokens=2, stop_on_eos=False)
        gen.admit(r2, 1)
        while gen.n_active:
            gen.step()
        while gen.pool._free:
            taken.append(gen.pool.alloc())
        taken.append(gen.pool.alloc())  # pressure again
        assert len(gen.mirror._chunks) <= 1, "budget overshot by a chunk"
        # the tier kept cycling: the fragmented chunk drained (freeing
        # its buffer — lane ids recycle into the fresh chunk) and the
        # new cold content spilled instead of being refused forever
        assert reg.counter(tm.KV_SPILL_BLOCKS).total() > s0
    finally:
        eng.close()


def test_scheduler_crash_reset_clears_host_tier(tiered_engine):
    """Crash recovery: reset_state forgets the host tier (pool lanes AND
    mirror buffers) along with everything else — nothing can page in
    blocks a half-finished dispatch may have corrupted."""
    gen = PagedGenerator(tiered_engine, n_slots=2)
    r = Request(rid=0, prompt_ids=_enc(tiered_engine, _session_text(40)),
                max_tokens=2, stop_on_eos=False)
    gen.admit(r, 0)
    while gen.n_active:
        gen.step()
    while gen.pool._cached:  # force the cached blocks out to host
        gen.pool.alloc()
    assert gen.pool.host_used_blocks() > 0
    assert gen.mirror._chunks
    gen.reset_state()
    assert gen.pool.host_used_blocks() == 0
    assert not gen.mirror._chunks and not gen.mirror._where
