"""SLO observatory math (runtime/slo.py): the streaming log-bucket
histogram's bounded quantile error vs exact quantiles, burn-rate
windows under an injectable clock (no wall reads in the hot path), and
compliance flipping exactly at the configured threshold. Pure host-side
— no jax, no engine, no sockets."""

from __future__ import annotations

import json
import math

import pytest

from dllama_tpu.runtime import slo, telemetry


# -- grammar -----------------------------------------------------------------


def test_parse_slo_happy_path():
    got = slo.parse_slo("ttft_p95_ms=500, itl_p50_ms=40,shed_rate=0.01")
    assert got == {"ttft_p95_ms": 500.0, "itl_p50_ms": 40.0,
                   "shed_rate": 0.01}


@pytest.mark.parametrize("spec,frag", [
    ("ttft_p95_ms", "not name=value"),
    ("latency_p95=5", "unknown SLO objective"),
    ("ttft_p95_ms=500,ttft_p95_ms=600", "duplicate"),
    ("ttft_p95_ms=banana", "not a number"),
    ("ttft_p95_ms=0", "positive"),
    ("ttft_p95_ms=-3", "positive"),
    ("ttft_p95_ms=inf", "positive"),
    ("", "empty SLO spec"),
    (" , ,", "empty SLO spec"),
])
def test_parse_slo_rejects(spec, frag):
    with pytest.raises(ValueError, match=frag):
        slo.parse_slo(spec)


def test_load_slo_json_file(tmp_path):
    p = tmp_path / "slo.json"
    p.write_text(json.dumps({"ttft_p95_ms": 500, "shed_rate": 0.01}))
    assert slo.load_slo(str(p)) == {"ttft_p95_ms": 500.0,
                                    "shed_rate": 0.01}
    bad = tmp_path / "bad.json"
    bad.write_text("[1, 2]")
    with pytest.raises(ValueError, match="JSON object"):
        slo.load_slo(str(bad))
    # a non-file argument parses as the inline grammar
    assert slo.load_slo("itl_p50_ms=40") == {"itl_p50_ms": 40.0}


# -- streaming histogram vs exact quantiles ----------------------------------


def _exact_quantile(values, q):
    s = sorted(values)
    return s[max(1, math.ceil(q * len(s))) - 1]


@pytest.mark.parametrize("name,values", [
    # a point mass: every estimate must land in the value's own bucket
    ("point_mass", [250.0] * 500),
    # bimodal: the p50/p95 straddle the modes
    ("bimodal", [10.0] * 400 + [900.0] * 100),
    # heavy tail: two decades of spread (deterministic lognormal-ish)
    ("heavy_tail", [math.exp(1 + 3 * ((i * 37 % 500) / 500.0))
                    for i in range(500)]),
])
@pytest.mark.parametrize("q", [0.50, 0.90, 0.95, 0.99])
def test_log_histogram_quantile_error_bound(name, values, q):
    h = slo.LogHistogram()
    for v in values:
        h.record(v)
    exact = _exact_quantile(values, q)
    est = h.quantile(q)
    assert abs(est - exact) / exact <= h.rel_error_bound() + 1e-12, \
        f"{name} q={q}: est {est} vs exact {exact}"


def test_log_histogram_underflow_and_empty():
    h = slo.LogHistogram()
    assert h.quantile(0.5) == 0.0
    h.record(0.0)
    h.record(-5.0)
    h.record(100.0)
    assert h.quantile(0.25) == 0.0          # the non-positive mass
    assert h.quantile(0.99) > 0.0           # the real observation
    assert h.n == 3


# -- burn windows under an injectable clock ----------------------------------


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def test_burn_rate_windows_fill_and_expire():
    clk = _Clock()
    eng = slo.SloEngine({"ttft_p95_ms": 100.0}, clock=clk,
                        registry=telemetry.Registry())
    # 10% of requests blow the threshold against a 5% budget → burn 2.0
    for i in range(100):
        eng.observe_ttft(500.0 if i % 10 == 0 else 10.0)
        clk.t += 1.0
    ev = eng.evaluate()
    rec = ev["objectives"]["ttft_p95_ms"]
    assert rec["burn"]["5m"] == pytest.approx(0.10 / 0.05, rel=1e-6)
    assert rec["burn"]["1h"] == pytest.approx(0.10 / 0.05, rel=1e-6)
    # advance past the short window with no traffic: the 5m burn
    # expires, the 1h burn still remembers
    clk.t += 400.0
    rec = eng.evaluate()["objectives"]["ttft_p95_ms"]
    assert rec["burn"]["5m"] == 0.0
    assert rec["burn"]["1h"] == pytest.approx(0.10 / 0.05, rel=1e-6)
    clk.t += 4000.0
    rec = eng.evaluate()["objectives"]["ttft_p95_ms"]
    assert rec["burn"]["1h"] == 0.0


def test_no_wall_clock_reads_in_hot_path(monkeypatch):
    """The hot path must use the injected clock only — a wall/monotonic
    read would let a clock step fabricate or destroy a burn window."""
    import time as _time

    def _bomb():  # pragma: no cover - failing is the test
        raise AssertionError("slo hot path read the process clock")

    clk = _Clock()
    eng = slo.SloEngine({"ttft_p95_ms": 100.0, "shed_rate": 0.01},
                        clock=clk, registry=telemetry.Registry())
    monkeypatch.setattr(_time, "monotonic", _bomb)
    monkeypatch.setattr(_time, "time", _bomb)
    eng.observe_ttft(50.0)
    eng.observe_itl(5.0)
    eng.observe_outcome(shed=False)
    eng.evaluate()


# -- compliance semantics ----------------------------------------------------


def test_latency_compliance_flips_exactly_at_threshold():
    clk = _Clock()
    reg = telemetry.Registry()
    probe = slo.LogHistogram()
    for _ in range(200):
        probe.record(250.0)
    est = probe.quantile(0.95)  # the bucket-midpoint estimate
    # threshold == estimate → compliant (<=); one ulp below → violated
    eng_at = slo.SloEngine({"ttft_p95_ms": est}, clock=clk, registry=reg)
    eng_below = slo.SloEngine(
        {"ttft_p95_ms": math.nextafter(est, 0.0)}, clock=clk,
        registry=telemetry.Registry())
    for _ in range(200):
        eng_at.observe_ttft(250.0)
        eng_below.observe_ttft(250.0)
    assert eng_at.evaluate()["objectives"]["ttft_p95_ms"]["compliant"]
    rec = eng_below.evaluate()["objectives"]["ttft_p95_ms"]
    assert not rec["compliant"]
    assert rec["estimate"] == est


def test_shed_rate_compliance_and_gauges():
    clk = _Clock()
    reg = telemetry.Registry()
    eng = slo.SloEngine({"shed_rate": 0.10}, clock=clk, registry=reg)
    for i in range(100):
        eng.observe_outcome(shed=(i < 10))   # exactly at the 10% budget
    ev = eng.evaluate()
    rec = ev["objectives"]["shed_rate"]
    assert rec["compliant"] and rec["estimate"] == pytest.approx(0.10)
    assert rec["burn"]["5m"] == pytest.approx(1.0)   # burning the whole
    # budget exactly — the boundary of sustainable
    comp = reg.gauge(telemetry.SLO_COMPLIANCE)
    burn = reg.gauge(telemetry.SLO_BURN_RATE)
    assert comp.value(objective="shed_rate") == 1.0
    assert burn.value(objective="shed_rate", window="5m") \
        == pytest.approx(1.0)
    # one more shed tips the lifetime fraction over the threshold
    eng.observe_outcome(shed=True)
    assert not eng.evaluate()["objectives"]["shed_rate"]["compliant"]
    assert comp.value(objective="shed_rate") == 0.0


def test_itl_objective_routes_to_its_own_histogram():
    clk = _Clock()
    eng = slo.SloEngine({"itl_p50_ms": 40.0, "ttft_p95_ms": 500.0},
                        clock=clk, registry=telemetry.Registry())
    for _ in range(50):
        eng.observe_itl(10.0)
        eng.observe_ttft(1000.0)   # blows ttft, must not touch itl
    ev = eng.evaluate()["objectives"]
    assert ev["itl_p50_ms"]["compliant"]
    assert not ev["ttft_p95_ms"]["compliant"]
    assert ev["itl_p50_ms"]["n"] == 50
