"""Fleet router tests (serve/router.py): health-driven dispatch over
replica api-servers with circuit breaking, retry, affinity, shedding,
and replica-churn survival.

Most tests drive the router against STUB replicas — tiny deterministic
HTTP servers speaking exactly the api-server surface the router consumes
(/readyz with the machine-readable ``code``, /metrics load gauges, SSE +
JSON completions) — so failure timing is exact and golden byte
comparison is possible. One test fronts a real tiny CPU-mesh engine to
prove end-to-end compatibility. The chaos acceptance test (3 replicas,
mid-run kill + restart under continuous mixed traffic) is the ISSUE-12
contract: zero silent failures, retries visible in telemetry, explicit
terminal 502s for mid-stream victims, breaker re-admission after the
restart."""

import json
import socket
import struct
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dllama_tpu.runtime import failpoints as fp
from dllama_tpu.runtime import telemetry as tm
from dllama_tpu.serve.router import (FleetRouter, affinity_key,
                                     make_router_handler)


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.registry().clear()
    yield
    fp.registry().clear()


def _wait(cond, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


# -- stub replica ------------------------------------------------------------


class StubReplica:
    """A deterministic api-server stand-in. ``behavior`` is mutated by
    tests mid-run; the handler reads it per request."""

    def __init__(self, name: str):
        self.name = name
        self.port: int | None = None
        self.httpd: ThreadingHTTPServer | None = None
        self.behavior: dict = {
            "ready": True,          # /readyz 200 vs 503
            "ready_code": "ok",     # unready code when not ready
            "queue_depth": 0,       # /metrics load gauges
            "inflight": 0,
            "completion_status": 200,   # non-200: error passthrough body
            "error_code": None,         # machine code in the error body
            "stream_chunks": ["Hel", "lo ", "fleet"],
            "chunk_delay_s": 0.0,
            "die_after_chunks": None,   # RST mid-stream after N chunks
            "truncate_nonstream": False,  # declare CL, RST mid-body
            "nonstream_delay_s": 0.0,
            "role": None,               # /readyz disaggregation tag
            "kv_prefixes": [],          # /readyz residency advertisement
            # stamped streaming (serve/api.py batched mode): chunks carry
            # the dllama {"index", "tokens"} resume meta, and a body with
            # resume_from is honored — continuation starts AT that index
            # (replaying it once; the router must dedup), exactly like a
            # real replica racing the splice
            "stamp": False,
            # emit a terminal finish_reason "error" chunk + [DONE] after
            # N token chunks — what a killed api-server's fail-all path
            # actually writes (ThreadingHTTPServer handlers survive
            # shutdown; the scheduler fails the slot, the socket FINs
            # cleanly)
            "error_after_chunks": None,
            # a canned /debug/tenants snapshot (None -> 404), so the
            # router's fleet-wide tenant join can be driven end to end
            "tenants_snapshot": None,
        }
        self.n_completions = 0
        # resume capture: one dict per STREAM completion attempt with the
        # X-Dllama-Resume-From header and the request body as received
        self.seen_resumes: list = []
        # KV migration capture: the X-Dllama-KV-Peer value (or None)
        # seen on each completion attempt, in arrival order
        self.seen_kv_peers: list = []
        # tenant capture: the X-Dllama-Tenant value (or None) seen on
        # each completion attempt, in arrival order
        self.seen_tenants: list = []
        # fleet-trace capture: (fleet_rid, hop) per completion attempt,
        # plus a flight-shaped dump served at /debug/flight so the
        # router's fleet-timeline join can be driven end to end
        self.seen_fleet: list = []
        self.flight_events: list = []
        self.flight_spans: list = []
        self._rid_lock = threading.Lock()
        self._local_rid = 0

    def note_fleet(self, frid, fhop) -> int:
        """Record a completion attempt's fleet identity headers the way
        serve/api.py binds them; returns the engine-local rid."""
        with self._rid_lock:
            self._local_rid += 1
            local = self._local_rid
        if frid is not None:
            hop = int(fhop or 0)
            self.seen_fleet.append((frid, hop))
            self.flight_events.append(
                {"event": "fleet_rid", "rid": local, "reason": frid,
                 "hop": hop, "t_ns": time.monotonic_ns()})
        return local

    def note_span(self, local, t0_ns, frid, fhop) -> None:
        s = {"request_id": local, "phase": "decode", "start_ns": t0_ns,
             "end_ns": time.monotonic_ns(), "slot": 0, "n_tokens": 3}
        if frid is not None:
            s["fleet"] = frid
            s["hop"] = int(fhop or 0)
        self.flight_spans.append(s)

    def start(self) -> None:
        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _rst(self):
                # force an RST (not a clean FIN): an EOF-delimited SSE
                # stream must look DEAD, not complete. The LINGER(1,0)
                # option rides the fd; the abort fires when the handler
                # teardown closes the last file object over it —
                # close_connection makes that happen NOW instead of
                # parking in the keep-alive readline
                self.connection.setsockopt(
                    socket.SOL_SOCKET, socket.SO_LINGER,
                    struct.pack("ii", 1, 0))
                self.close_connection = True

            def _json(self, code, payload, headers=None):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                b = stub.behavior
                if self.path == "/readyz":
                    extra = {}
                    if b["role"]:
                        extra["role"] = b["role"]
                    if b["kv_prefixes"]:
                        extra["kv_prefixes"] = list(b["kv_prefixes"])
                    if b["ready"]:
                        self._json(200, {"status": "ok", "reason": "ok",
                                         "code": "ok", **extra})
                    else:
                        self._json(503, {"status": "unready",
                                         "reason": b["ready_code"],
                                         "code": b["ready_code"], **extra},
                                   headers={"Retry-After": "5"})
                elif self.path == "/metrics":
                    text = (f"dllama_queue_depth {b['queue_depth']}\n"
                            f"dllama_requests_in_flight {b['inflight']}\n")
                    body = text.encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/v1/models":
                    self._json(200, {"object": "list", "data": [
                        {"id": f"stub-{stub.name}", "object": "model"}]})
                elif self.path == "/debug/tenants":
                    if b["tenants_snapshot"] is None:
                        self._json(404, {"error": "not found"})
                    else:
                        self._json(200, b["tenants_snapshot"])
                elif self.path == "/debug/flight":
                    self._json(200, {
                        "tick_seq": 0, "ticks": [], "dumps": [],
                        "events": list(stub.flight_events),
                        "spans": list(stub.flight_spans)})
                else:
                    self._json(404, {"error": "not found"})

            def do_POST(self):
                b = stub.behavior
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length)
                if self.path != "/v1/chat/completions":
                    self._json(404, {"error": "not found"})
                    return
                stub.n_completions += 1
                frid = self.headers.get("X-Dllama-Request-Id")
                fhop = self.headers.get("X-Dllama-Hop")
                stub.seen_kv_peers.append(
                    self.headers.get("X-Dllama-KV-Peer"))
                stub.seen_tenants.append(
                    self.headers.get("X-Dllama-Tenant"))
                t0_ns = time.monotonic_ns()
                local = stub.note_fleet(frid, fhop)
                if b["nonstream_delay_s"]:
                    time.sleep(b["nonstream_delay_s"])
                if b["completion_status"] != 200:
                    hdrs = ({"Retry-After": "5"}
                            if b["completion_status"] in (429, 503) else {})
                    payload = {"error": f"stub error "
                                        f"{b['completion_status']}"}
                    if b["error_code"]:
                        payload["code"] = b["error_code"]
                    self._json(b["completion_status"], payload,
                               headers=hdrs)
                    stub.note_span(local, t0_ns, frid, fhop)
                    return
                try:
                    body = json.loads(raw or b"{}")
                except ValueError:
                    self._json(400, {"error": "invalid JSON body"})
                    return
                if body.get("stream"):
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    self.send_header("Cache-Control", "no-cache")
                    self.send_header("Connection", "close")
                    self.end_headers()

                    def send(piece, finish=None, meta=None):
                        chunk = {"object": "chat.completion.chunk",
                                 "replica": stub.name,
                                 "choices": [{"index": 0,
                                              "delta": ({"content": piece}
                                                        if piece else {}),
                                              "finish_reason": finish}]}
                        if meta is not None:
                            chunk["dllama"] = meta
                        self.wfile.write(b"data: "
                                         + json.dumps(chunk).encode()
                                         + b"\n\n")
                        self.wfile.flush()

                    if b["stamp"]:
                        stub.seen_resumes.append({
                            "header": self.headers.get(
                                "X-Dllama-Resume-From"),
                            "body": body})
                        resume_from = int(body.get("resume_from") or 0)
                        pieces = list(b["stream_chunks"])
                        if resume_from == 0:
                            # the prompt-echo chunk, index 0
                            send("", meta={"index": 0, "tokens": []})
                        n_emitted = 0
                        # a resume replays its splice index once — the
                        # router's exactly-once filter must drop it
                        for i in range(max(1, resume_from),
                                       len(pieces) + 1):
                            send(pieces[i - 1],
                                 meta={"index": i, "tokens": [100 + i]})
                            n_emitted += 1
                            if b["chunk_delay_s"]:
                                time.sleep(b["chunk_delay_s"])
                            if b["die_after_chunks"] is not None \
                                    and n_emitted >= b["die_after_chunks"]:
                                self.close_connection = True
                                stub.note_span(local, t0_ns, frid, fhop)
                                return
                            if b["error_after_chunks"] is not None \
                                    and n_emitted >= \
                                    b["error_after_chunks"]:
                                send("", finish="error")
                                self.wfile.write(b"data: [DONE]\n\n")
                                self.close_connection = True
                                stub.note_span(local, t0_ns, frid, fhop)
                                return
                        # the real final chunk is unstamped (api.py
                        # writes it outside the emit path)
                        send("", finish="length")
                        self.wfile.write(b"data: [DONE]\n\n")
                        self.close_connection = True
                        stub.note_span(local, t0_ns, frid, fhop)
                        return
                    for i, piece in enumerate(b["stream_chunks"]):
                        send(piece)
                        if b["chunk_delay_s"]:
                            time.sleep(b["chunk_delay_s"])
                        if b["die_after_chunks"] is not None \
                                and i + 1 >= b["die_after_chunks"]:
                            # a dying replica closes with a clean FIN
                            # and no [DONE] — exactly what a killed
                            # api-server's SSE stream looks like
                            self.close_connection = True
                            stub.note_span(local, t0_ns, frid, fhop)
                            return
                    self.wfile.write(b"data: [DONE]\n\n")
                    self.close_connection = True
                    stub.note_span(local, t0_ns, frid, fhop)
                    return
                if b["truncate_nonstream"]:
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", "1000")
                    self.end_headers()
                    self.wfile.write(b'{"partial": tru')
                    self.wfile.flush()
                    self._rst()
                    stub.note_span(local, t0_ns, frid, fhop)
                    return
                self._json(200, {
                    "object": "chat.completion", "replica": stub.name,
                    "choices": [{"index": 0,
                                 "message": {"role": "assistant",
                                             "content": "".join(
                                                 b["stream_chunks"])},
                                 "finish_reason": "stop"}],
                    "usage": {"prompt_tokens": 3, "completion_tokens": 3,
                              "total_tokens": 6}})
                stub.note_span(local, t0_ns, frid, fhop)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", self.port or 0),
                                         Handler)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def kill(self) -> None:
        """Replica death: the listening socket closes — new connections
        are refused (in-flight handler threads die on their own RSTs)."""
        self.httpd.shutdown()
        self.httpd.server_close()
        self.httpd = None

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"


def make_router(stubs, **kw):
    """Router + HTTP front end over the given stubs, with test-speed
    probe/breaker timings; returns (base_url, fleet, closer)."""
    kw.setdefault("probe_interval_s", 0.05)
    kw.setdefault("eject_after", 2)
    kw.setdefault("backoff_min_s", 0.1)
    kw.setdefault("backoff_max_s", 0.4)
    kw.setdefault("connect_timeout_s", 2.0)
    fleet = FleetRouter([s.url for s in stubs], **kw)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0),
                                make_router_handler(fleet))
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()

    def closer():
        httpd.shutdown()
        httpd.server_close()
        fleet.close()

    return f"http://127.0.0.1:{port}", fleet, closer


def _post(url, payload, timeout=30):
    req = urllib.request.Request(
        url + "/v1/chat/completions",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=timeout)


def _body(prompt, stream=False, **extra):
    return {"messages": [{"role": "user", "content": prompt}],
            "max_tokens": 8, "stream": stream, **extra}


def _up(fleet, name):
    return tm.registry().gauge(tm.ROUTER_REPLICA_UP).value(replica=name)


# -- surfaces ----------------------------------------------------------------


def test_router_surfaces_and_replica_up(tmp_path):
    a, b = StubReplica("a"), StubReplica("b")
    a.start(), b.start()
    url, fleet, close = make_router([a, b])
    try:
        # readiness flips at the FIRST dispatchable replica; wait for
        # both probes before asserting fleet-wide state
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas),
              what="both replicas up")
        assert fleet.readiness()[0]
        with urllib.request.urlopen(url + "/readyz", timeout=10) as r:
            body = json.loads(r.read())
        assert body == {"status": "ok", "reason": "ok", "code": "ok"}
        with urllib.request.urlopen(url + "/healthz", timeout=10) as r:
            assert json.loads(r.read())["status"] == "ok"
        with urllib.request.urlopen(url + "/debug/fleet", timeout=10) as r:
            snap = json.loads(r.read())
        assert {s["replica"] for s in snap["replicas"]} \
            == {r.name for r in fleet.replicas}
        assert all(s["state"] == "up" for s in snap["replicas"])
        with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
            text = r.read().decode()
        assert "dllama_router_replica_up{" in text
        # /v1/models proxies to a live replica
        with urllib.request.urlopen(url + "/v1/models", timeout=10) as r:
            assert json.loads(r.read())["object"] == "list"
        # unknown routes: JSON 404
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(url + "/nope", timeout=10)
        assert e.value.code == 404
    finally:
        close()
        a.kill(), b.kill()


def test_least_loaded_dispatch_uses_probed_queue_depth():
    a, b = StubReplica("a"), StubReplica("b")
    a.behavior["queue_depth"] = 50
    a.start(), b.start()
    url, fleet, close = make_router([a, b])
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas)
              and fleet.replicas[0].load_score() >= 50,
              what="probe load refresh")
        # distinct prompts (distinct affinity keys): all land on the
        # unloaded replica
        for i in range(3):
            with _post(url, _body(f"p{i}")) as r:
                assert json.loads(r.read())["replica"] == "b"
    finally:
        close()
        a.kill(), b.kill()


def test_session_affinity_sticks_while_healthy():
    a, b = StubReplica("a"), StubReplica("b")
    a.start(), b.start()
    url, fleet, close = make_router([a, b])
    hits = tm.registry().counter(tm.ROUTER_AFFINITY_HITS)
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas),
              what="both replicas up")
        h0 = hits.total()
        with _post(url, _body("sticky conversation")) as r:
            first = json.loads(r.read())["replica"]
        # load now favors the OTHER replica; affinity must still win
        (a if first == "a" else b).behavior["queue_depth"] = 50
        _wait(lambda: max(r.load_score() for r in fleet.replicas) >= 50,
              what="probe load refresh")
        for _ in range(3):
            with _post(url, _body("sticky conversation")) as r:
                assert json.loads(r.read())["replica"] == first
        assert hits.total() >= h0 + 3
        # an explicit session_id key overrides the prefix hash
        k1 = affinity_key({"session_id": "s1", "messages": []})
        k2 = affinity_key(_body("sticky conversation"))
        assert k1.startswith("sid:") and k2.startswith("pfx:")
    finally:
        close()
        a.kill(), b.kill()


def test_affinity_rebinds_when_sticky_replica_dies():
    a, b = StubReplica("a"), StubReplica("b")
    a.start(), b.start()
    url, fleet, close = make_router([a, b])
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas),
              what="both replicas up")
        with _post(url, _body("rebind me")) as r:
            first = json.loads(r.read())["replica"]
        victim = a if first == "a" else b
        survivor = b if first == "a" else a
        victim.kill()
        _wait(lambda: _up(fleet, f"127.0.0.1:{victim.port}") == 0,
              what="victim ejected")
        with _post(url, _body("rebind me")) as r:
            assert json.loads(r.read())["replica"] == survivor.name
        # the session is now stuck to the survivor — even after the old
        # replica returns, the sticky map keeps it where its KV lives
        victim.start()
        _wait(lambda: _up(fleet, f"127.0.0.1:{victim.port}") == 1,
              what="victim re-admitted")
        with _post(url, _body("rebind me")) as r:
            assert json.loads(r.read())["replica"] == survivor.name
    finally:
        close()
        for s in (a, b):
            if s.httpd is not None:
                s.kill()


# -- retry / circuit breaker -------------------------------------------------


def test_proxy_failpoint_drives_transparent_retry():
    """Armed `proxy` failpoint severs the first upstream connection —
    the request transparently retries on a different replica and
    completes; the retry is visible in dllama_router_retries_total."""
    a, b = StubReplica("a"), StubReplica("b")
    a.start(), b.start()
    url, fleet, close = make_router([a, b])
    retries = tm.registry().counter(tm.ROUTER_RETRIES)
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas),
              what="both replicas up")
        r0 = retries.total()
        fp.arm("proxy", "conn_reset", times=1)
        with _post(url, _body("retry me")) as r:
            out = json.loads(r.read())
        assert out["replica"] in ("a", "b")
        assert retries.total() == r0 + 1
    finally:
        close()
        a.kill(), b.kill()


def test_midbody_death_retries_before_first_client_byte():
    """A replica that dies mid-body on a Content-Length response fails
    before anything reached the client — retried, not a 502."""
    a, b = StubReplica("a"), StubReplica("b")
    a.behavior["truncate_nonstream"] = True
    a.start(), b.start()
    url, fleet, close = make_router([a, b])
    retries = tm.registry().counter(tm.ROUTER_RETRIES)
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas),
              what="both replicas up")
        r0 = retries.total()
        n_ok = 0
        for i in range(4):  # distinct keys: some land on the truncator
            with _post(url, _body(f"q{i}")) as r:
                out = json.loads(r.read())
            assert out["replica"] == "b"  # only b can COMPLETE one
            n_ok += 1
        assert n_ok == 4
        # at least one request was dispatched to a first and retried
        assert retries.total() >= r0 + 1
    finally:
        close()
        a.kill(), b.kill()


def test_circuit_breaker_ejects_then_halfopen_readmits():
    a, b = StubReplica("a"), StubReplica("b")
    a.start(), b.start()
    url, fleet, close = make_router([a, b])
    reg = tm.registry()
    ejects = reg.counter(tm.ROUTER_EJECTS)
    readmits = reg.counter(tm.ROUTER_READMITS)
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas),
              what="both replicas up")
        name = f"127.0.0.1:{a.port}"
        e0, ra0 = ejects.total(replica=name), readmits.total(replica=name)
        # seed sticky sessions; the entries pointing at the victim must
        # be purged at ejection (affinity hygiene), not left to rot as
        # one dispatchable() miss per returning session
        purged = tm.registry().counter(tm.ROUTER_AFFINITY_PURGED)
        p0 = purged.total(replica=name)
        stuck_on_a = 0
        for i in range(6):
            with _post(url, _body(f"warm-{i}",
                                  session_id=f"sess-{i}")) as r:
                if json.loads(r.read())["replica"] == "a":
                    stuck_on_a += 1
        assert stuck_on_a  # at least one sticky entry names the victim
        a.kill()
        _wait(lambda: ejects.total(replica=name) == e0 + 1,
              what="breaker ejection")
        assert _up(fleet, name) == 0
        assert purged.total(replica=name) - p0 == stuck_on_a
        with fleet._lock:
            assert not any(rep.name == name
                           for rep in fleet._affinity.values())
        snap = [s for s in fleet.fleet_snapshot()["replicas"]
                if s["replica"] == name][0]
        assert snap["state"] == "down" and snap["backoff_s"] > 0
        # traffic keeps flowing on the survivor meanwhile
        with _post(url, _body("meanwhile")) as r:
            assert json.loads(r.read())["replica"] == "b"
        # restart: a bounded-backoff half-open probe re-admits it
        a.start()
        _wait(lambda: readmits.total(replica=name) == ra0 + 1,
              what="half-open re-admission")
        assert _up(fleet, name) == 1
        # dispatch returns to the re-admitted replica
        _wait(lambda: _served_by(url, "a"), timeout=10,
              what="dispatch back on a")
    finally:
        close()
        for s in (a, b):
            if s.httpd is not None:
                s.kill()


def _served_by(url, name, n=6):
    for i in range(n):
        with _post(url, _body(f"probe-{name}-{i}-{time.monotonic_ns()}")) \
                as r:
            if json.loads(r.read())["replica"] == name:
                return True
    return False


# -- KV migration orchestration ----------------------------------------------


def test_kv_donor_header_on_residency_hit():
    """A peer advertising the prompt's affinity key on /readyz becomes
    the KV donor: the dispatch carries X-Dllama-KV-Peer naming it. When
    the chosen replica itself advertises the key, no donor is named
    (migrating a prefix onto the replica that already holds it would be
    pure wire waste)."""
    a, b = StubReplica("a"), StubReplica("b")
    a.start(), b.start()
    url, fleet, close = make_router([a, b])
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas),
              what="both replicas up")
        key = "sid:donor-sess"
        b.behavior["kv_prefixes"] = [key]
        _wait(lambda: any(r.holds_prefix(key) for r in fleet.replicas),
              what="residency advertisement probed")
        with _post(url, _body("migrate me",
                              session_id="donor-sess")) as r:
            assert json.loads(r.read())["replica"] == "a"
        assert a.seen_kv_peers[-1] == f"127.0.0.1:{b.port}"
        # /debug/fleet surfaces the advertisement
        snap = fleet.fleet_snapshot()["replicas"]
        assert [s for s in snap
                if s["replica"] == f"127.0.0.1:{b.port}"][0][
                    "kv_prefixes"] == [key]
        # chosen replica already resident: no donor header
        a.behavior["kv_prefixes"] = [key]
        rep_a = [r for r in fleet.replicas
                 if r.name == f"127.0.0.1:{a.port}"][0]
        _wait(lambda: rep_a.holds_prefix(key),
              what="chosen replica's own advertisement probed")
        with _post(url, _body("already here",
                              session_id="donor-sess")) as r:
            r.read()
        assert a.seen_kv_peers[-1] is None
    finally:
        close()
        a.kill(), b.kill()


def test_prefill_role_warms_then_names_donor():
    """Explicit disaggregation: a prefill-role replica never serves
    decode traffic; with no resident donor, the router first runs a
    one-token warm-up on it, then dispatches to the decode replica with
    the prefill replica named as KV donor."""
    p, d = StubReplica("p"), StubReplica("d")
    p.start(), d.start()
    p.behavior["role"] = "prefill"
    url, fleet, close = make_router([p, d])
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas),
              what="both replicas up")
        rep_p = [r for r in fleet.replicas
                 if r.name == f"127.0.0.1:{p.port}"][0]
        _wait(lambda: rep_p.is_prefill(), what="prefill role probed")
        with _post(url, _body("disaggregate me",
                              session_id="disagg-sess")) as r:
            assert json.loads(r.read())["replica"] == "d"
        # the prefill replica saw exactly the warm-up (no donor header,
        # max_tokens clamped to 1, not streamed)
        assert p.n_completions == 1
        assert p.seen_kv_peers == [None]
        # the decode dispatch names the prefill replica as donor
        assert d.seen_kv_peers[-1] == f"127.0.0.1:{p.port}"
    finally:
        close()
        p.kill(), d.kill()


# -- shedding / drain --------------------------------------------------------


def test_all_replicas_saturated_sheds_429_with_retry_after():
    a, b = StubReplica("a"), StubReplica("b")
    a.start(), b.start()
    url, fleet, close = make_router([a, b])
    shed = tm.registry().counter(tm.ROUTER_SHED)
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas),
              what="both replicas up")
        for s in (a, b):
            s.behavior.update(ready=False, ready_code="queue_full")
        _wait(lambda: not fleet.readiness()[0], what="fleet saturated")
        assert fleet.readiness()[2] == "queue_full"
        s0 = shed.total()
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(url, _body("shed me"))
        assert e.value.code == 429
        assert e.value.headers["Retry-After"] is not None
        assert json.loads(e.value.read())["code"] == "queue_full"
        assert shed.total() == s0 + 1
        # replicas recover -> dispatch resumes
        for s in (a, b):
            s.behavior.update(ready=True)
        _wait(lambda: fleet.readiness()[0], what="fleet recovered")
        with _post(url, _body("recovered")) as r:
            assert r.status == 200
    finally:
        close()
        a.kill(), b.kill()


def test_router_max_queue_bound_sheds():
    a = StubReplica("a")
    a.behavior["nonstream_delay_s"] = 0.6
    a.start()
    url, fleet, close = make_router([a], max_inflight=1)
    shed = tm.registry().counter(tm.ROUTER_SHED)
    try:
        _wait(lambda: fleet.readiness()[0], what="replica up")
        s0 = shed.total()
        codes = []

        def slow():
            with _post(url, _body("slow one"), timeout=30) as r:
                codes.append(r.status)

        t = threading.Thread(target=slow)
        t.start()
        _wait(lambda: fleet.fleet_snapshot()["inflight_total"] >= 1,
              what="first request in flight")
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(url, _body("beyond the bound"))
        assert e.value.code == 429
        assert e.value.headers["Retry-After"] is not None
        assert shed.total() == s0 + 1
        t.join(timeout=30)
        assert codes == [200]  # the in-flight one finished fine
    finally:
        close()
        a.kill()


def test_dispatch_503_draining_reclassifies_without_eject():
    """The drain-awareness contract on the DISPATCH path: a replica
    whose completions answer 503 code=draining (the probe hasn't
    noticed yet) is reclassified unready — the request retries on the
    other replica and the circuit breaker is NOT fed (a draining pod
    must never be ejected into the crash-backoff schedule)."""
    a, b = StubReplica("a"), StubReplica("b")
    a.start(), b.start()
    # probes too slow to see the drain first: the dispatch path must
    # handle the classification itself
    url, fleet, close = make_router([a, b], probe_interval_s=30.0)
    ejects = tm.registry().counter(tm.ROUTER_EJECTS)
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas),
              what="both replicas up")
        name_a = f"127.0.0.1:{a.port}"
        e0 = ejects.total(replica=name_a)
        a.behavior.update(completion_status=503, error_code="draining")
        for i in range(4):
            with _post(url, _body(f"drain-race-{i}")) as r:
                assert json.loads(r.read())["replica"] == "b"
        assert ejects.total(replica=name_a) == e0  # reclassified, NOT ejected
        snap = [s for s in fleet.fleet_snapshot()["replicas"]
                if s["replica"] == name_a][0]
        assert snap["state"] == "unready" and snap["code"] == "draining"
    finally:
        close()
        a.kill(), b.kill()


def test_probe_sanitizes_unknown_ready_codes():
    """An out-of-vocabulary /readyz code degrades to "crashed" — the
    READY_CODES closed world is enforced at the router's probe parse,
    not just documented."""
    a = StubReplica("a")
    a.start()
    url, fleet, close = make_router([a])
    try:
        _wait(lambda: fleet.readiness()[0], what="replica up")
        a.behavior.update(ready=False, ready_code="weird_code")
        name = f"127.0.0.1:{a.port}"
        _wait(lambda: _up(fleet, name) == 0, what="unready observed")
        snap = fleet.fleet_snapshot()["replicas"][0]
        assert snap["state"] == "unready" and snap["code"] == "crashed"
    finally:
        close()
        a.kill()


def test_draining_replica_stops_new_dispatch():
    a, b = StubReplica("a"), StubReplica("b")
    a.start(), b.start()
    url, fleet, close = make_router([a, b])
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas),
              what="both replicas up")
        a.behavior.update(ready=False, ready_code="draining")
        name = f"127.0.0.1:{a.port}"
        _wait(lambda: _up(fleet, name) == 0, what="drain observed")
        snap = [s for s in fleet.fleet_snapshot()["replicas"]
                if s["replica"] == name][0]
        assert snap["state"] == "unready" and snap["code"] == "draining"
        for i in range(4):  # nothing new lands on the draining replica
            with _post(url, _body(f"drain-{i}")) as r:
                assert json.loads(r.read())["replica"] == "b"
        # drain is not an ejection: no breaker backoff involved, and
        # recovery is immediate on the next probe
        a.behavior.update(ready=True)
        _wait(lambda: _up(fleet, name) == 1, what="drain ended")
    finally:
        close()
        a.kill(), b.kill()


# -- single-replica degradation (golden) -------------------------------------


def test_single_replica_router_is_byte_identical_passthrough():
    """ISSUE-12 satellite: a router fronting ONE replica returns byte-
    identical bodies to direct access — non-streaming, streaming, and
    error statuses (with Retry-After) pass through unmangled."""
    a = StubReplica("a")
    a.start()
    url, fleet, close = make_router([a], eject_after=100)
    try:
        _wait(lambda: fleet.readiness()[0], what="replica up")

        def both(payload):
            direct = _post(a.url, payload)
            routed = _post(url, payload)
            with direct, routed:
                return (direct.status, direct.read(),
                        routed.status, routed.read())

        # non-streaming completion
        ds, db, rs, rb = both(_body("golden"))
        assert (ds, db) == (rs, rb)
        # streaming completion: the SSE byte stream is identical
        ds, db, rs, rb = both(_body("golden", stream=True))
        assert (ds, db) == (rs, rb)
        assert b"data: [DONE]" in rb
        # error statuses pass through unmangled (status, body, and the
        # upstream's own Retry-After header)
        for status in (400, 429, 503):
            a.behavior["completion_status"] = status
            errs = []
            for base in (a.url, url):
                with pytest.raises(urllib.error.HTTPError) as e:
                    _post(base, _body("err"))
                errs.append((e.value.code, e.value.read(),
                             e.value.headers.get("Retry-After")))
            assert errs[0] == errs[1], status
        a.behavior["completion_status"] = 200
    finally:
        close()
        a.kill()


# -- mid-stream death --------------------------------------------------------


def test_midstream_death_gets_terminal_502_event_never_a_hang():
    a = StubReplica("a")
    a.behavior["die_after_chunks"] = 2
    a.start()
    url, fleet, close = make_router([a])
    http = tm.registry().counter(tm.HTTP_REQUESTS)
    try:
        _wait(lambda: fleet.readiness()[0], what="replica up")
        c0 = http.total(route="/v1/chat/completions", status="502")
        with _post(url, _body("doomed stream", stream=True),
                   timeout=30) as r:
            raw = r.read().decode()
        # the two relayed chunks arrived, then the EXPLICIT terminal
        # event naming the 502 — and the stream still ends with [DONE]
        # (a client can always tell this abort from a dropped socket)
        assert raw.count('"delta"') == 2
        assert '"upstream_error"' in raw and '"code": 502' in raw
        assert raw.rstrip().endswith("data: [DONE]")
        assert http.total(route="/v1/chat/completions",
                          status="502") == c0 + 1
    finally:
        close()
        a.kill()


# -- durable streams: mid-stream failover ------------------------------------


def _sse_events(raw: bytes) -> list:
    """Parsed data events of an SSE transcript, [DONE] as the string."""
    out = []
    for evt in raw.split(b"\n\n"):
        evt = evt.strip()
        if not evt.startswith(b"data:"):
            continue
        data = evt[5:].strip()
        out.append("[DONE]" if data == b"[DONE]" else json.loads(data))
    return out


def _stamp_indices(events) -> list:
    return [e["dllama"]["index"] for e in events
            if isinstance(e, dict) and "dllama" in e]


def _resume_totals():
    c = tm.registry().counter(tm.ROUTER_STREAM_RESUMES)
    return {o: c.total(outcome=o)
            for o in ("resumed", "exhausted", "no_budget", "failed")}


def test_midstream_death_splices_resume_exactly_once():
    """The tentpole contract at the router tier: a stamped stream whose
    replica dies mid-flight is re-dispatched to a healthy replica as a
    spliced continuation (resume_from + full token history + the
    X-Dllama-Resume-From header), the replayed splice index is dropped,
    and the client sees one gapless duplicate-free transcript ending in
    a normal finish — with the resume on the outcome counter, the
    latency histogram, and an rt_resume span, and the dying replica
    (still advertising the prefix) named as KV donor."""
    a, b = StubReplica("a"), StubReplica("b")
    for s in (a, b):
        s.behavior["stamp"] = True
        s.behavior["stream_chunks"] = ["t1 ", "t2 ", "t3 ", "t4 ", "t5"]
    a.behavior["die_after_chunks"] = 2
    a.behavior["kv_prefixes"] = ["sid:resume-sess"]
    b.behavior["queue_depth"] = 50  # first dispatch lands on a
    a.start(), b.start()
    url, fleet, close = make_router([a, b])
    h_resume = tm.registry().histogram(tm.ROUTER_STREAM_RESUME_MS)
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas)
              and fleet.replicas[1].load_score() >= 50
              and any(r.holds_prefix("sid:resume-sess")
                      for r in fleet.replicas),
              what="probes: up + load + residency")
        t0, n0 = _resume_totals(), h_resume.count()
        with _post(url, _body("durable", stream=True,
                              session_id="resume-sess", timeout=30),
                   timeout=30) as r:
            raw = r.read()
        events = _sse_events(raw)
        # gapless, duplicate-free: echo once, every index exactly once
        assert _stamp_indices(events) == [0, 1, 2, 3, 4, 5]
        assert b'"upstream_error"' not in raw
        finals = [e for e in events if isinstance(e, dict)
                  and e.get("choices")
                  and e["choices"][0].get("finish_reason")]
        assert [e["choices"][0]["finish_reason"] for e in finals] \
            == ["length"]
        assert events[-1] == "[DONE]"
        # both replicas contributed — the splice really happened
        assert {e["replica"] for e in events if isinstance(e, dict)} \
            == {"a", "b"}
        d = {k: v - t0[k] for k, v in _resume_totals().items()}
        assert d == {"resumed": 1, "exhausted": 0, "no_budget": 0,
                     "failed": 0}
        assert h_resume.count() == n0 + 1
        # the resume dispatch b saw: splice position 2, the 2 relayed
        # ids as history, the remaining deadline re-budgeted
        res = b.seen_resumes[-1]
        assert res["header"] == "2"
        assert res["body"]["resume_from"] == 2
        assert res["body"]["resume_tokens"] == [101, 102]
        assert 0 < res["body"]["timeout"] <= 30
        # the dying donor still serves the prefix over the KV wire
        assert b.seen_kv_peers[-1] == f"127.0.0.1:{a.port}"
        spans = [s for s in fleet.fleet_snapshot()["spans"]
                 if s["phase"] == "rt_resume"]
        assert spans and spans[-1]["resume_from"] == 2
        assert spans[-1]["replica"] == f"127.0.0.1:{b.port}"
        for k in ("detect_ms", "redispatch_ms", "first_token_ms"):
            assert spans[-1][k] >= 0
    finally:
        close()
        a.kill(), b.kill()


def test_upstream_error_chunk_is_resumed_not_relayed():
    """The third death signal: a killed api-server's handler threads
    outlive the process shutdown and write a terminal finish_reason
    "error" chunk over a cleanly-FINed socket. On a stamped stream the
    router holds that chunk back, treats it as mid-stream death, and
    splices a continuation — the client never sees the error."""
    a, b = StubReplica("a"), StubReplica("b")
    for s in (a, b):
        s.behavior["stamp"] = True
        s.behavior["stream_chunks"] = ["x1 ", "x2 ", "x3 ", "x4"]
    a.behavior["error_after_chunks"] = 2
    b.behavior["queue_depth"] = 50
    a.start(), b.start()
    url, fleet, close = make_router([a, b])
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas)
              and fleet.replicas[1].load_score() >= 50,
              what="probes: up + load")
        t0 = _resume_totals()
        with _post(url, _body("heal the error", stream=True),
                   timeout=30) as r:
            raw = r.read()
        events = _sse_events(raw)
        assert _stamp_indices(events) == [0, 1, 2, 3, 4]
        reasons = [e["choices"][0].get("finish_reason") for e in events
                   if isinstance(e, dict) and e.get("choices")]
        assert "error" not in reasons and reasons[-1] == "length"
        assert b'"upstream_error"' not in raw
        assert _resume_totals()["resumed"] == t0["resumed"] + 1
    finally:
        close()
        a.kill(), b.kill()


def test_resume_budget_exhausted_ends_with_terminal_502():
    """Per-attempt + terminal accounting: the resume target dies too —
    its splice counts \"resumed\" (a continued token reached the
    client), the next death finds the --max-stream-resumes budget spent
    (\"exhausted\") and the stream ends with the explicit terminal 502
    event + [DONE], everything delivered so far intact."""
    a, b = StubReplica("a"), StubReplica("b")
    for s in (a, b):
        s.behavior["stamp"] = True
        s.behavior["stream_chunks"] = ["y1 ", "y2 ", "y3 ", "y4 ", "y5"]
        s.behavior["die_after_chunks"] = 2
    b.behavior["queue_depth"] = 50
    a.start(), b.start()
    url, fleet, close = make_router([a, b])
    http = tm.registry().counter(tm.HTTP_REQUESTS)
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas)
              and fleet.replicas[1].load_score() >= 50,
              what="probes: up + load")
        t0 = _resume_totals()
        c0 = http.total(route="/v1/chat/completions", status="502")
        with _post(url, _body("doubly doomed", stream=True),
                   timeout=30) as r:
            raw = r.read()
        events = _sse_events(raw)
        # a delivered 1,2; b replayed 2 (dropped) and delivered 3, then
        # died — the transcript stays gapless and duplicate-free
        assert _stamp_indices(events) == [0, 1, 2, 3]
        assert b'"upstream_error"' in raw and b'"code": 502' in raw
        assert events[-1] == "[DONE]"
        d = {k: v - t0[k] for k, v in _resume_totals().items()}
        assert d == {"resumed": 1, "exhausted": 1, "no_budget": 0,
                     "failed": 0}
        assert http.total(route="/v1/chat/completions",
                          status="502") == c0 + 1
    finally:
        close()
        a.kill(), b.kill()


def test_max_stream_resumes_zero_keeps_legacy_contract():
    """--max-stream-resumes 0 is the pre-failover behavior: the death is
    classified (\"exhausted\") and the stream ends with the terminal 502
    event immediately — no re-dispatch ever leaves the router."""
    a, b = StubReplica("a"), StubReplica("b")
    for s in (a, b):
        s.behavior["stamp"] = True
    a.behavior["die_after_chunks"] = 1
    b.behavior["queue_depth"] = 50
    a.start(), b.start()
    url, fleet, close = make_router([a, b], max_stream_resumes=0)
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas)
              and fleet.replicas[1].load_score() >= 50,
              what="probes: up + load")
        t0 = _resume_totals()
        n_b0 = b.n_completions
        with _post(url, _body("no budget at all", stream=True),
                   timeout=30) as r:
            raw = r.read()
        assert b'"upstream_error"' in raw
        assert raw.rstrip().endswith(b"data: [DONE]")
        d = {k: v - t0[k] for k, v in _resume_totals().items()}
        assert d == {"resumed": 0, "exhausted": 1, "no_budget": 0,
                     "failed": 0}
        assert b.n_completions == n_b0  # nothing was re-dispatched
    finally:
        close()
        a.kill(), b.kill()


def test_resume_outside_request_timeout_is_no_budget():
    """A spliced continuation must fit inside the remaining
    --request-timeout budget: with the deadline already burned at
    detection time the outcome is \"no_budget\" and the stream ends
    with the terminal 502, not a hopeless re-dispatch."""
    a, b = StubReplica("a"), StubReplica("b")
    for s in (a, b):
        s.behavior["stamp"] = True
    a.behavior["die_after_chunks"] = 1
    b.behavior["queue_depth"] = 50
    a.start(), b.start()
    url, fleet, close = make_router([a, b], request_timeout_s=0.04)
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas)
              and fleet.replicas[1].load_score() >= 50,
              what="probes: up + load")
        t0 = _resume_totals()
        with _post(url, _body("late already", stream=True),
                   timeout=30) as r:
            raw = r.read()
        assert b'"upstream_error"' in raw
        d = {k: v - t0[k] for k, v in _resume_totals().items()}
        assert d == {"resumed": 0, "exhausted": 0, "no_budget": 1,
                     "failed": 0}
    finally:
        close()
        a.kill(), b.kill()


# -- the ISSUE-12 chaos acceptance test --------------------------------------


def test_fleet_survives_replica_kill_and_restart_under_traffic():
    """3 replicas, continuous mixed traffic, one replica killed mid-run:
    every request that had not yet streamed a byte completes via retry
    on a survivor (zero silent failures; retries visible in
    dllama_router_retries_total), mid-stream victims get the explicit
    terminal 502 event, and after the restart the circuit breaker
    re-admits the replica and dispatch returns to all 3 — all
    telemetry-asserted."""
    stubs = [StubReplica(f"r{i}") for i in range(3)]
    for s in stubs:
        s.behavior["stream_chunks"] = ["a", "b", "c", "d"]
        s.behavior["chunk_delay_s"] = 0.01
        s.start()
    url, fleet, close = make_router(stubs)
    reg = tm.registry()
    retries = reg.counter(tm.ROUTER_RETRIES)
    ejects = reg.counter(tm.ROUTER_EJECTS)
    readmits = reg.counter(tm.ROUTER_READMITS)
    dispatch = reg.counter(tm.ROUTER_DISPATCHES)
    victim = stubs[1]
    vname = f"127.0.0.1:{victim.port}"
    r0, e0, ra0 = (retries.total(), ejects.total(replica=vname),
                   readmits.total(replica=vname))
    outcomes: list = []  # ("ok"|"midstream_502"|"silent"|..., detail)
    out_lock = threading.Lock()
    stop = threading.Event()

    def traffic(i):
        n = 0
        while not stop.is_set():
            n += 1
            stream = (i + n) % 2 == 0
            try:
                with _post(url, _body(f"t{i}-{n}", stream=stream),
                           timeout=30) as r:
                    raw = r.read()
                if not stream:
                    ok = r.status == 200 and b'"usage"' in raw
                    rec = ("ok" if ok else "silent", raw[:120])
                elif b'"upstream_error"' in raw:
                    rec = ("midstream_502", raw[-200:])
                elif b"[DONE]" in raw:
                    rec = ("ok", b"")
                else:
                    rec = ("silent", raw[:120])
            except urllib.error.HTTPError as e:
                rec = (f"http_{e.code}", e.read()[:120])
            except Exception as e:  # noqa: BLE001 — recorded, asserted below
                rec = ("silent", repr(e)[:120])
            with out_lock:
                outcomes.append(rec)
            time.sleep(0.01)

    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas),
              what="all 3 replicas up")
        threads = [threading.Thread(target=traffic, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.4)  # steady traffic over all three
        # mid-run kill: streams in flight on the victim die with an RST
        # mid-chunk; new connections are refused
        victim.behavior["die_after_chunks"] = 1
        time.sleep(0.1)
        victim.kill()
        _wait(lambda: ejects.total(replica=vname) == e0 + 1,
              what="victim ejection", timeout=15)
        time.sleep(0.4)  # traffic continues on the 2 survivors
        victim.behavior["die_after_chunks"] = None
        victim.start()
        _wait(lambda: readmits.total(replica=vname) == ra0 + 1,
              what="victim re-admission", timeout=15)
        d_back = dispatch.total(replica=vname)
        time.sleep(0.5)  # dispatch spreads back over all 3
        stop.set()
        for t in threads:
            t.join(timeout=30)

        silent = [o for o in outcomes if o[0] == "silent"]
        assert not silent, silent[:3]
        errors = [o for o in outcomes if o[0].startswith("http_")]
        assert not errors, errors[:3]  # retries absorbed every pre-byte death
        n_ok = sum(1 for o in outcomes if o[0] == "ok")
        assert n_ok >= 20, f"only {n_ok} completions of {len(outcomes)}"
        # the kill was actually felt: pre-byte deaths were retried ...
        assert retries.total() > r0
        # ... and the re-admitted replica serves again
        assert dispatch.total(replica=vname) > d_back
        assert _up(fleet, vname) == 1
    finally:
        stop.set()
        close()
        for s in stubs:
            if s.httpd is not None:
                s.kill()


# -- fleet tracing + SLO observatory ------------------------------------------


def _post_raw(url, payload, headers=None, timeout=30):
    req = urllib.request.Request(
        url + "/v1/chat/completions",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    return urllib.request.urlopen(req, timeout=timeout)


def test_fleet_rid_minted_forwarded_and_echoed():
    """The trace-identity contract: the router mints (or accepts a
    sanitary) X-Dllama-Request-Id, forwards it with a hop index, and
    echoes it on the response."""
    a = StubReplica("a")
    a.start()
    url, fleet, close = make_router([a])
    try:
        _wait(lambda: fleet.readiness()[0], what="replica up")
        # no client id: router mints one, forwards it at hop 0, echoes
        with _post(url, _body("mint me")) as r:
            rid = r.headers["X-Dllama-Request-Id"]
        assert rid and a.seen_fleet[-1] == (rid, 0)
        # a sanitary client id is honored end to end
        with _post_raw(url, _body("keep me"),
                       headers={"X-Dllama-Request-Id": "client.id-7"}) as r:
            assert r.headers["X-Dllama-Request-Id"] == "client.id-7"
        assert a.seen_fleet[-1] == ("client.id-7", 0)
        # an unsanitary id is replaced, never trusted
        with _post_raw(url, _body("spoof me"),
                       headers={"X-Dllama-Request-Id": "bad id!{}"}) as r:
            rid = r.headers["X-Dllama-Request-Id"]
        assert rid != "bad id!{}" and rid.startswith("r")
        assert a.seen_fleet[-1] == (rid, 0)
    finally:
        close()
        a.kill()


def test_retry_carries_hop_index_to_replica():
    """ISSUE-16 satellite: a retried request is visible AT THE REPLICA —
    the serving hop arrives with X-Dllama-Hop: 1 under the same fleet
    id, and dllama_router_retry_hops_total counts both hops."""
    a, b = StubReplica("a"), StubReplica("b")
    a.start(), b.start()
    url, fleet, close = make_router([a, b])
    hops = tm.registry().counter(tm.ROUTER_RETRY_HOPS)
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas),
              what="both replicas up")
        h0, h1 = hops.total(hop="0"), hops.total(hop="1")
        fp.arm("proxy", "conn_reset", times=1)
        with _post(url, _body("retry with id")) as r:
            rid = r.headers["X-Dllama-Request-Id"]
        assert hops.total(hop="0") == h0 + 1
        assert hops.total(hop="1") == h1 + 1
        # the hop that actually served carries index 1 — the replica's
        # flight dump can name which attempt it was
        served = [s for s in (a, b) if (rid, 1) in s.seen_fleet]
        assert len(served) == 1
        # the retry/TTFT/connect histograms populated on /metrics
        with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
            text = r.read().decode()
        assert "dllama_router_ttft_ms_bucket" in text
        assert "dllama_router_connect_ms_bucket" in text
        assert "dllama_router_retry_ms_count 1" in text \
            or "dllama_router_retry_ms_count" in text
        assert 'dllama_router_retry_hops_total{hop="1"}' in text
    finally:
        close()
        a.kill(), b.kill()


def test_fleet_timeline_joins_chaos_run(tmp_path):
    """ISSUE-16 satellite: a 3-replica run with a mid-run kill/restart
    joins into ONE strictly-valid Chrome trace — every completed
    request id in exactly one flow, a pre-byte-retried request's flow
    crossing two replica tracks, no orphaned replica spans — and the
    same join runs offline through the fleettrace CLI."""
    from dllama_tpu.runtime import flightrec
    from dllama_tpu.serve.cli import main as cli_main

    stubs = [StubReplica(f"r{i}") for i in range(3)]
    for s in stubs:
        s.start()
    url, fleet, close = make_router(stubs)
    completed: list = []
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas),
              what="all 3 replicas up")

        def go(prompt, stream=False):
            with _post(url, _body(prompt, stream=stream)) as r:
                raw = r.read()
                assert (b"[DONE]" in raw) if stream else (b"usage" in raw)
                completed.append(r.headers["X-Dllama-Request-Id"])

        for i in range(6):           # steady phase, mixed traffic
            go(f"steady-{i}", stream=i % 2 == 0)
        # churn phase 1: r0 answers then dies mid-body — the router
        # retries pre-first-byte, so the SAME fleet id lands on two
        # replica tracks
        stubs[0].behavior["truncate_nonstream"] = True
        retries = tm.registry().counter(tm.ROUTER_RETRIES)
        r0 = retries.total()
        for i in range(4):
            go(f"churn-{i}")
        assert retries.total() > r0
        stubs[0].behavior["truncate_nonstream"] = False
        # churn phase 2: hard kill + restart under sequential traffic
        victim = stubs[1]
        vname = f"127.0.0.1:{victim.port}"
        victim.kill()
        _wait(lambda: _up(fleet, vname) == 0, what="victim ejected",
              timeout=15)
        for i in range(3):
            go(f"post-kill-{i}")
        victim.start()
        _wait(lambda: _up(fleet, vname) == 1, what="victim re-admitted",
              timeout=15)
        for i in range(3):
            go(f"post-restart-{i}", stream=True)

        with urllib.request.urlopen(url + "/debug/fleet/timeline",
                                    timeout=10) as r:
            trace = json.loads(r.read())
        assert flightrec.validate_chrome_trace(
            trace, expect_rids=completed) == []
        evs = trace["traceEvents"]
        # every completed request id: exactly one flow (one "s" start)
        starts: dict = {}
        for e in evs:
            if e.get("cat") == "fleet" and e["ph"] == "s":
                starts[e["id"]] = starts.get(e["id"], 0) + 1
        for rid in completed:
            assert starts.get(rid) == 1, rid
        # the retried ids cross two replica tracks (two distinct pids>1)
        repl_pids: dict = {}
        for e in evs:
            if e.get("ph") == "X" and e.get("cat") == "replica":
                repl_pids.setdefault(
                    e["args"]["request_id"], set()).add(e["pid"])
        assert any(len(pids) >= 2 for pids in repl_pids.values())
        # no orphaned replica spans: all traffic came via the router
        assert trace["fleetJoin"]["unjoined_replica_spans"] == 0
        assert trace["fleetJoin"]["joined"] >= len(set(completed))
        # router track present with the full phase story
        phases = {e["args"]["phase"] for e in evs
                  if e.get("ph") == "X" and e.get("cat") == "router"}
        assert {"rt_queue", "rt_dispatch", "rt_connect", "rt_first_byte",
                "rt_stream", "rt_retry"} <= phases

        # -- offline joiner over saved dumps ------------------------------
        with urllib.request.urlopen(url + "/debug/fleet",
                                    timeout=10) as r:
            (tmp_path / "fleet.json").write_bytes(r.read())
        args = ["fleettrace", "--router-dump",
                str(tmp_path / "fleet.json"),
                "--out", str(tmp_path / "trace.json")]
        for s in stubs:
            with urllib.request.urlopen(s.url + "/debug/flight",
                                        timeout=10) as r:
                (tmp_path / f"{s.name}.json").write_bytes(r.read())
            args += ["--replica-dump",
                     f"{s.name}={tmp_path / f'{s.name}.json'}"]
        assert cli_main(args) == 0
        offline = json.loads((tmp_path / "trace.json").read_text())
        assert flightrec.validate_chrome_trace(
            offline, expect_rids=completed) == []
        assert offline["fleetJoin"]["joined"] >= len(set(completed))
    finally:
        close()
        for s in stubs:
            if s.httpd is not None:
                s.kill()


def test_fleettrace_cli_rejects_malformed_and_unjoinable(tmp_path):
    from dllama_tpu.serve.cli import main as cli_main

    # malformed: not JSON at all
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert cli_main(["fleettrace", "--router-dump", str(bad)]) == 1
    # malformed: spans that are not span-shaped
    broken = tmp_path / "broken.json"
    broken.write_text(json.dumps({"spans": [{"wrong": 1}]}))
    assert cli_main(["fleettrace", "--router-dump", str(broken)]) == 1
    # unjoinable: router saw requests, replica dump shares none of them
    router_dump = tmp_path / "router.json"
    router_dump.write_text(json.dumps({"spans": [
        {"request_id": "r1-1", "phase": "rt_queue",
         "start_ns": 1000, "end_ns": 2000}]}))
    replica_dump = tmp_path / "replica.json"
    replica_dump.write_text(json.dumps(
        {"ticks": [], "events": [], "spans": []}))
    assert cli_main(["fleettrace", "--router-dump", str(router_dump),
                     "--replica-dump", f"r0={replica_dump}"]) == 1
    # the same dumps WITH a joining replica span succeed
    replica_dump.write_text(json.dumps({"ticks": [], "events": [], "spans": [
        {"request_id": 5, "phase": "decode", "start_ns": 1200,
         "end_ns": 1800, "slot": 0, "fleet": "r1-1", "hop": 0}]}))
    out = tmp_path / "ok.json"
    assert cli_main(["fleettrace", "--router-dump", str(router_dump),
                     "--replica-dump", f"r0={replica_dump}",
                     "--out", str(out)]) == 0
    assert json.loads(out.read_text())["fleetJoin"]["joined"] == 1


def test_debug_slo_endpoint_and_metrics():
    """--slo objectives evaluated from router-measured observations:
    /debug/slo compliance + burn, gauges on /metrics, 404 without
    --slo."""
    a = StubReplica("a")
    a.behavior["chunk_delay_s"] = 0.01
    a.start()
    url, fleet, close = make_router(
        [a], slo_objectives={"ttft_p95_ms": 60000.0, "itl_p50_ms": 60000.0,
                             "shed_rate": 0.9})
    try:
        _wait(lambda: fleet.readiness()[0], what="replica up")
        for i in range(3):
            with _post(url, _body(f"slo-{i}", stream=i % 2 == 0)) as r:
                r.read()
        with urllib.request.urlopen(url + "/debug/slo", timeout=10) as r:
            body = json.loads(r.read())
        assert body["windows"] == ["5m", "1h"]
        objs = body["objectives"]
        assert set(objs) == {"ttft_p95_ms", "itl_p50_ms", "shed_rate"}
        assert objs["ttft_p95_ms"]["n"] >= 3
        assert objs["ttft_p95_ms"]["compliant"]      # loose threshold
        assert objs["itl_p50_ms"]["n"] >= 1          # SSE chunk gaps
        assert objs["shed_rate"]["estimate"] == 0.0
        assert all(b == 0.0 for b in objs["ttft_p95_ms"]["burn"].values())
        with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
            text = r.read().decode()
        assert 'dllama_slo_compliance{objective="ttft_p95_ms"} 1' in text
        assert 'dllama_slo_burn_rate{objective="shed_rate",window="5m"}' \
            in text
    finally:
        close()
        a.kill()


def test_debug_slo_404_without_objectives():
    a = StubReplica("a")
    a.start()
    url, fleet, close = make_router([a])
    try:
        _wait(lambda: fleet.readiness()[0], what="replica up")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(url + "/debug/slo", timeout=10)
        assert e.value.code == 404
    finally:
        close()
        a.kill()


def test_shed_feeds_slo_outcome():
    a = StubReplica("a")
    a.start()
    url, fleet, close = make_router(
        [a], slo_objectives={"shed_rate": 0.25})
    try:
        _wait(lambda: fleet.readiness()[0], what="replica up")
        with _post(url, _body("admitted one")) as r:
            r.read()
        # the admitted outcome is fed after the response is written, so
        # the client can get here first — wait for it to land
        _wait(lambda: fleet.slo.evaluate()
              ["objectives"]["shed_rate"]["n"] >= 1,
              what="admitted outcome observed")
        a.behavior.update(ready=False, ready_code="queue_full")
        _wait(lambda: not fleet.readiness()[0], what="fleet saturated")
        for _ in range(3):
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(url, _body("shed me"))
            assert e.value.code == 429
        body = json.loads(urllib.request.urlopen(
            url + "/debug/slo", timeout=10).read())
        rec = body["objectives"]["shed_rate"]
        assert rec["n"] == 4 and rec["estimate"] == pytest.approx(0.75)
        assert not rec["compliant"]         # 75% shed vs a 25% budget
        assert rec["burn"]["5m"] == pytest.approx(0.75 / 0.25)
    finally:
        close()
        a.kill()


# -- end-to-end against a real engine ----------------------------------------


def test_router_fronts_real_engine_replica(tmp_path):
    """One real tiny CPU-mesh api-server behind the router: a chat
    completion through the router matches direct access (content +
    usage; ids/timestamps differ by design)."""
    import numpy as np
    from http.server import HTTPServer

    from dllama_tpu.formats import tfile
    from dllama_tpu.runtime.engine import InferenceEngine
    from dllama_tpu.serve.api import ApiState, make_handler

    from helpers import (byte_vocab_tokenizer, tiny_header_params,
                         write_tiny_model)

    mpath, tpath = tmp_path / "m.m", tmp_path / "t.t"
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96),
                     np.random.default_rng(9))
    td = byte_vocab_tokenizer()
    td.chat_template = "<|start_header_id|>"
    tfile.write_tfile(tpath, td)
    engine = InferenceEngine(str(mpath), str(tpath), temperature=0.0, seed=3)
    state = ApiState(engine)
    httpd = HTTPServer(("127.0.0.1", 0), make_handler(state))
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url, fleet, close = make_router([_FakeStub(port)])
    try:
        _wait(lambda: fleet.readiness()[0], what="engine replica up",
              timeout=30)
        body = {"messages": [{"role": "user", "content": "hello"}],
                "max_tokens": 6, "temperature": 0}
        with _post(f"http://127.0.0.1:{port}", body, timeout=120) as r:
            direct = json.loads(r.read())
        with _post(url, body, timeout=120) as r:
            routed = json.loads(r.read())
        assert routed["choices"] == direct["choices"]
        assert routed["usage"] == direct["usage"]
        # and the streaming path relays the real SSE stream
        with _post(url, dict(body, stream=True), timeout=120) as r:
            raw = r.read().decode()
        assert "data: [DONE]" in raw
        # trace identity reaches the REAL replica: a completion routed
        # with a client-chosen id lands in the api server's flight dump
        # as a fleet_rid binding with the serving hop, its span ring
        # records carry the fleet id, and the opt-in timing block names
        # the request by the same id
        req = urllib.request.Request(
            url + "/v1/chat/completions",
            data=json.dumps(dict(body, timing=True)).encode(),
            headers={"Content-Type": "application/json",
                     "X-Dllama-Request-Id": "e2e.trace-1"})
        with urllib.request.urlopen(req, timeout=120) as r:
            assert r.headers["X-Dllama-Request-Id"] == "e2e.trace-1"
            timed = json.loads(r.read())
        assert timed["timing"]["request_id"] == "e2e.trace-1"
        assert timed["timing"]["hop"] == 0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/flight", timeout=30) as r:
            flight = json.loads(r.read())
        binds = [ev for ev in flight["events"]
                 if ev.get("event") == "fleet_rid"
                 and ev.get("reason") == "e2e.trace-1"]
        assert len(binds) == 1 and binds[0]["hop"] == 0
        fleet_spans = [s for s in flight["spans"]
                       if s.get("fleet") == "e2e.trace-1"]
        assert fleet_spans and all(s["hop"] == 0 for s in fleet_spans)
    finally:
        close()
        httpd.shutdown()
        httpd.server_close()
        engine.close()


class _FakeStub:
    """Adapter so make_router can front an arbitrary local port."""

    def __init__(self, port):
        self.port = port

    @property
    def url(self):
        return f"http://127.0.0.1:{self.port}"


# -- tenant observatory -------------------------------------------------------


def test_tenant_header_echoed_forwarded_and_sanitized():
    """The tenant-identity contract at the router tier: a sanitary
    X-Dllama-Tenant is forwarded to the replica and echoed on the
    response; a malformed one collapses to "anon"; no header is "anon"
    too — the router never invents or trusts unsanitary identity."""
    from dllama_tpu.runtime import tenancy

    tenancy.reset()
    a = StubReplica("a")
    a.start()
    url, fleet, close = make_router([a])
    try:
        _wait(lambda: fleet.readiness()[0], what="replica up")
        with _post_raw(url, _body("bill me"),
                       headers={"X-Dllama-Tenant": "acme"}) as r:
            assert r.headers["X-Dllama-Tenant"] == "acme"
        assert a.seen_tenants[-1] == "acme"
        # malformed id: never forwarded verbatim — collapses to anon
        with _post_raw(url, _body("spoof me"),
                       headers={"X-Dllama-Tenant": "no spaces!{}"}) as r:
            assert r.headers["X-Dllama-Tenant"] == "anon"
        assert a.seen_tenants[-1] == "anon"
        # absent header: anon, still forwarded so the replica bills it
        with _post(url, _body("nameless")) as r:
            assert r.headers["X-Dllama-Tenant"] == "anon"
        assert a.seen_tenants[-1] == "anon"
        # the router's own registry saw both identities
        snap = tenancy.registry().snapshot()
        assert {"acme", "anon"} <= set(snap["tenants"])
    finally:
        close()
        a.kill()
        tenancy.reset()


def test_router_shed_names_tenant_and_reason():
    """A router-tier shed is attributable: the 429 carries the tenant
    echo, dllama_tenant_shed_total counts it under the closed-world
    reason router_queue_full, and the rt_queue span names both."""
    from dllama_tpu.runtime import tenancy

    tenancy.reset()
    a, b = StubReplica("a"), StubReplica("b")
    a.start(), b.start()
    url, fleet, close = make_router([a, b])
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas),
              what="both replicas up")
        for s in (a, b):
            s.behavior.update(ready=False, ready_code="queue_full")
        _wait(lambda: not fleet.readiness()[0], what="fleet saturated")
        with pytest.raises(urllib.error.HTTPError) as e:
            _post_raw(url, _body("shed me"),
                      headers={"X-Dllama-Tenant": "flooder"})
        assert e.value.code == 429
        assert e.value.headers["X-Dllama-Tenant"] == "flooder"
        snap = tenancy.registry().snapshot()
        assert snap["tenants"]["flooder"]["sheds"] \
            == {"router_queue_full": 1}
        shed = tm.registry().counter("dllama_tenant_shed_total")
        assert shed.total(tenant="flooder",
                          reason="router_queue_full") == 1
        spans = [s for s in fleet.fleet_snapshot()["spans"]
                 if s["phase"] == "rt_queue"
                 and s.get("reason") == "router_queue_full"]
        assert spans and spans[-1]["tenant"] == "flooder"
    finally:
        close()
        a.kill(), b.kill()
        tenancy.reset()


def test_stream_resume_carries_originating_tenant():
    """ISSUE-20 satellite: a mid-stream failover continuation must NOT
    land on the resume replica as "anon" — the re-dispatch carries the
    originating tenant so the continuation bills to the caller."""
    from dllama_tpu.runtime import tenancy

    tenancy.reset()
    a, b = StubReplica("a"), StubReplica("b")
    for s in (a, b):
        s.behavior["stamp"] = True
        s.behavior["stream_chunks"] = ["t1 ", "t2 ", "t3 ", "t4 ", "t5"]
    a.behavior["die_after_chunks"] = 2
    b.behavior["queue_depth"] = 50  # first dispatch lands on a
    a.start(), b.start()
    url, fleet, close = make_router([a, b])
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas)
              and fleet.replicas[1].load_score() >= 50,
              what="probes: up + load")
        req = urllib.request.Request(
            url + "/v1/chat/completions",
            data=json.dumps(_body("durable", stream=True,
                                  timeout=30)).encode(),
            headers={"Content-Type": "application/json",
                     "X-Dllama-Tenant": "acme"})
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.headers["X-Dllama-Tenant"] == "acme"
            raw = r.read()
        events = _sse_events(raw)
        assert _stamp_indices(events) == [0, 1, 2, 3, 4, 5]
        # the splice happened, and BOTH hops saw the tenant: the
        # original dispatch on a, the resume re-dispatch on b
        assert {e["replica"] for e in events if isinstance(e, dict)} \
            == {"a", "b"}
        assert a.seen_tenants[-1] == "acme"
        assert b.seen_resumes[-1]["body"]["resume_from"] == 2
        assert b.seen_tenants[-1] == "acme"
    finally:
        close()
        a.kill(), b.kill()
        tenancy.reset()


def test_prefill_warm_carries_originating_tenant():
    """ISSUE-20 satellite: the disaggregation warm-up request the
    router sends to a prefill-role replica carries the caller's tenant
    — warm-up work bills to the tenant who triggered it, not "anon"."""
    from dllama_tpu.runtime import tenancy

    tenancy.reset()
    p, d = StubReplica("p"), StubReplica("d")
    p.start(), d.start()
    p.behavior["role"] = "prefill"
    url, fleet, close = make_router([p, d])
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas),
              what="both replicas up")
        rep_p = [r for r in fleet.replicas
                 if r.name == f"127.0.0.1:{p.port}"][0]
        _wait(lambda: rep_p.is_prefill(), what="prefill role probed")
        with _post_raw(url, _body("disaggregate me",
                                  session_id="disagg-sess"),
                       headers={"X-Dllama-Tenant": "acme"}) as r:
            assert json.loads(r.read())["replica"] == "d"
        # the warm-up on the prefill replica carried the tenant, and so
        # did the decode dispatch
        assert p.seen_tenants == ["acme"]
        assert d.seen_tenants[-1] == "acme"
    finally:
        close()
        p.kill(), d.kill()
        tenancy.reset()


def test_fleet_tenants_join_sums_replicas():
    """GET /debug/fleet/tenants joins per-replica usage registries:
    numeric totals and shed maps sum per tenant, the fleet Jain index
    covers the summed decode tokens, dead replicas contribute nothing,
    and the router's own registry rides along."""
    from dllama_tpu.runtime import tenancy

    tenancy.reset()
    a, b = StubReplica("a"), StubReplica("b")
    a.behavior["tenants_snapshot"] = {
        "cap": 64, "n_tenants": 2, "overflow_total": 0,
        "tenants": {
            "acme": {"decode_tokens": 300, "prefill_tokens": 40,
                     "sheds": {"queue_full": 2}},
            "zed": {"decode_tokens": 100, "prefill_tokens": 10,
                    "sheds": {}}}}
    b.behavior["tenants_snapshot"] = {
        "cap": 64, "n_tenants": 1, "overflow_total": 0,
        "tenants": {
            "acme": {"decode_tokens": 100, "prefill_tokens": 5,
                     "sheds": {"queue_full": 1,
                               "tenant_rate_budget": 3}}}}
    a.start(), b.start()
    url, fleet, close = make_router([a, b])
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas),
              what="both replicas up")
        with urllib.request.urlopen(url + "/debug/fleet/tenants",
                                    timeout=10) as r:
            body = json.loads(r.read())
        assert body["replicas_joined"] == 2
        acme = body["tenants"]["acme"]
        assert acme["decode_tokens"] == 400
        assert acme["prefill_tokens"] == 45
        assert acme["sheds"] == {"queue_full": 3, "tenant_rate_budget": 3}
        assert body["tenants"]["zed"]["decode_tokens"] == 100
        # Jain over (400, 100): 500^2 / (2 * 170000) ~= 0.735
        assert abs(body["fleet_jain_index"]
                   - 500 ** 2 / (2 * (400 ** 2 + 100 ** 2))) < 1e-9
        assert body["router"]["cap"] == 64
        # a dead replica contributes nothing, join count says so
        b.kill()
        with urllib.request.urlopen(url + "/debug/fleet/tenants",
                                    timeout=10) as r:
            body = json.loads(r.read())
        assert body["replicas_joined"] == 1
        assert body["tenants"]["acme"]["decode_tokens"] == 300
    finally:
        close()
        a.kill()
        if b.httpd is not None:
            b.kill()
        tenancy.reset()
