"""Fleet router tests (serve/router.py): health-driven dispatch over
replica api-servers with circuit breaking, retry, affinity, shedding,
and replica-churn survival.

Most tests drive the router against STUB replicas — tiny deterministic
HTTP servers speaking exactly the api-server surface the router consumes
(/readyz with the machine-readable ``code``, /metrics load gauges, SSE +
JSON completions) — so failure timing is exact and golden byte
comparison is possible. One test fronts a real tiny CPU-mesh engine to
prove end-to-end compatibility. The chaos acceptance test (3 replicas,
mid-run kill + restart under continuous mixed traffic) is the ISSUE-12
contract: zero silent failures, retries visible in telemetry, explicit
terminal 502s for mid-stream victims, breaker re-admission after the
restart."""

import json
import socket
import struct
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dllama_tpu.runtime import failpoints as fp
from dllama_tpu.runtime import telemetry as tm
from dllama_tpu.serve.router import (FleetRouter, affinity_key,
                                     make_router_handler)


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.registry().clear()
    yield
    fp.registry().clear()


def _wait(cond, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


# -- stub replica ------------------------------------------------------------


class StubReplica:
    """A deterministic api-server stand-in. ``behavior`` is mutated by
    tests mid-run; the handler reads it per request."""

    def __init__(self, name: str):
        self.name = name
        self.port: int | None = None
        self.httpd: ThreadingHTTPServer | None = None
        self.behavior: dict = {
            "ready": True,          # /readyz 200 vs 503
            "ready_code": "ok",     # unready code when not ready
            "queue_depth": 0,       # /metrics load gauges
            "inflight": 0,
            "completion_status": 200,   # non-200: error passthrough body
            "error_code": None,         # machine code in the error body
            "stream_chunks": ["Hel", "lo ", "fleet"],
            "chunk_delay_s": 0.0,
            "die_after_chunks": None,   # RST mid-stream after N chunks
            "truncate_nonstream": False,  # declare CL, RST mid-body
            "nonstream_delay_s": 0.0,
        }
        self.n_completions = 0

    def start(self) -> None:
        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _rst(self):
                # force an RST (not a clean FIN): an EOF-delimited SSE
                # stream must look DEAD, not complete. The LINGER(1,0)
                # option rides the fd; the abort fires when the handler
                # teardown closes the last file object over it —
                # close_connection makes that happen NOW instead of
                # parking in the keep-alive readline
                self.connection.setsockopt(
                    socket.SOL_SOCKET, socket.SO_LINGER,
                    struct.pack("ii", 1, 0))
                self.close_connection = True

            def _json(self, code, payload, headers=None):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                b = stub.behavior
                if self.path == "/readyz":
                    if b["ready"]:
                        self._json(200, {"status": "ok", "reason": "ok",
                                         "code": "ok"})
                    else:
                        self._json(503, {"status": "unready",
                                         "reason": b["ready_code"],
                                         "code": b["ready_code"]},
                                   headers={"Retry-After": "5"})
                elif self.path == "/metrics":
                    text = (f"dllama_queue_depth {b['queue_depth']}\n"
                            f"dllama_requests_in_flight {b['inflight']}\n")
                    body = text.encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path == "/v1/models":
                    self._json(200, {"object": "list", "data": [
                        {"id": f"stub-{stub.name}", "object": "model"}]})
                else:
                    self._json(404, {"error": "not found"})

            def do_POST(self):
                b = stub.behavior
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length)
                if self.path != "/v1/chat/completions":
                    self._json(404, {"error": "not found"})
                    return
                stub.n_completions += 1
                if b["nonstream_delay_s"]:
                    time.sleep(b["nonstream_delay_s"])
                if b["completion_status"] != 200:
                    hdrs = ({"Retry-After": "5"}
                            if b["completion_status"] in (429, 503) else {})
                    payload = {"error": f"stub error "
                                        f"{b['completion_status']}"}
                    if b["error_code"]:
                        payload["code"] = b["error_code"]
                    self._json(b["completion_status"], payload,
                               headers=hdrs)
                    return
                try:
                    body = json.loads(raw or b"{}")
                except ValueError:
                    self._json(400, {"error": "invalid JSON body"})
                    return
                if body.get("stream"):
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    self.send_header("Cache-Control", "no-cache")
                    self.send_header("Connection", "close")
                    self.end_headers()
                    for i, piece in enumerate(b["stream_chunks"]):
                        chunk = {"object": "chat.completion.chunk",
                                 "replica": stub.name,
                                 "choices": [{"index": 0,
                                              "delta": {"content": piece},
                                              "finish_reason": None}]}
                        self.wfile.write(b"data: "
                                         + json.dumps(chunk).encode()
                                         + b"\n\n")
                        self.wfile.flush()
                        if b["chunk_delay_s"]:
                            time.sleep(b["chunk_delay_s"])
                        if b["die_after_chunks"] is not None \
                                and i + 1 >= b["die_after_chunks"]:
                            # a dying replica closes with a clean FIN
                            # and no [DONE] — exactly what a killed
                            # api-server's SSE stream looks like
                            self.close_connection = True
                            return
                    self.wfile.write(b"data: [DONE]\n\n")
                    self.close_connection = True
                    return
                if b["truncate_nonstream"]:
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", "1000")
                    self.end_headers()
                    self.wfile.write(b'{"partial": tru')
                    self.wfile.flush()
                    self._rst()
                    return
                self._json(200, {
                    "object": "chat.completion", "replica": stub.name,
                    "choices": [{"index": 0,
                                 "message": {"role": "assistant",
                                             "content": "".join(
                                                 b["stream_chunks"])},
                                 "finish_reason": "stop"}],
                    "usage": {"prompt_tokens": 3, "completion_tokens": 3,
                              "total_tokens": 6}})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", self.port or 0),
                                         Handler)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def kill(self) -> None:
        """Replica death: the listening socket closes — new connections
        are refused (in-flight handler threads die on their own RSTs)."""
        self.httpd.shutdown()
        self.httpd.server_close()
        self.httpd = None

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"


def make_router(stubs, **kw):
    """Router + HTTP front end over the given stubs, with test-speed
    probe/breaker timings; returns (base_url, fleet, closer)."""
    kw.setdefault("probe_interval_s", 0.05)
    kw.setdefault("eject_after", 2)
    kw.setdefault("backoff_min_s", 0.1)
    kw.setdefault("backoff_max_s", 0.4)
    kw.setdefault("connect_timeout_s", 2.0)
    fleet = FleetRouter([s.url for s in stubs], **kw)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0),
                                make_router_handler(fleet))
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()

    def closer():
        httpd.shutdown()
        httpd.server_close()
        fleet.close()

    return f"http://127.0.0.1:{port}", fleet, closer


def _post(url, payload, timeout=30):
    req = urllib.request.Request(
        url + "/v1/chat/completions",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=timeout)


def _body(prompt, stream=False, **extra):
    return {"messages": [{"role": "user", "content": prompt}],
            "max_tokens": 8, "stream": stream, **extra}


def _up(fleet, name):
    return tm.registry().gauge(tm.ROUTER_REPLICA_UP).value(replica=name)


# -- surfaces ----------------------------------------------------------------


def test_router_surfaces_and_replica_up(tmp_path):
    a, b = StubReplica("a"), StubReplica("b")
    a.start(), b.start()
    url, fleet, close = make_router([a, b])
    try:
        # readiness flips at the FIRST dispatchable replica; wait for
        # both probes before asserting fleet-wide state
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas),
              what="both replicas up")
        assert fleet.readiness()[0]
        with urllib.request.urlopen(url + "/readyz", timeout=10) as r:
            body = json.loads(r.read())
        assert body == {"status": "ok", "reason": "ok", "code": "ok"}
        with urllib.request.urlopen(url + "/healthz", timeout=10) as r:
            assert json.loads(r.read())["status"] == "ok"
        with urllib.request.urlopen(url + "/debug/fleet", timeout=10) as r:
            snap = json.loads(r.read())
        assert {s["replica"] for s in snap["replicas"]} \
            == {r.name for r in fleet.replicas}
        assert all(s["state"] == "up" for s in snap["replicas"])
        with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
            text = r.read().decode()
        assert "dllama_router_replica_up{" in text
        # /v1/models proxies to a live replica
        with urllib.request.urlopen(url + "/v1/models", timeout=10) as r:
            assert json.loads(r.read())["object"] == "list"
        # unknown routes: JSON 404
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(url + "/nope", timeout=10)
        assert e.value.code == 404
    finally:
        close()
        a.kill(), b.kill()


def test_least_loaded_dispatch_uses_probed_queue_depth():
    a, b = StubReplica("a"), StubReplica("b")
    a.behavior["queue_depth"] = 50
    a.start(), b.start()
    url, fleet, close = make_router([a, b])
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas)
              and fleet.replicas[0].load_score() >= 50,
              what="probe load refresh")
        # distinct prompts (distinct affinity keys): all land on the
        # unloaded replica
        for i in range(3):
            with _post(url, _body(f"p{i}")) as r:
                assert json.loads(r.read())["replica"] == "b"
    finally:
        close()
        a.kill(), b.kill()


def test_session_affinity_sticks_while_healthy():
    a, b = StubReplica("a"), StubReplica("b")
    a.start(), b.start()
    url, fleet, close = make_router([a, b])
    hits = tm.registry().counter(tm.ROUTER_AFFINITY_HITS)
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas),
              what="both replicas up")
        h0 = hits.total()
        with _post(url, _body("sticky conversation")) as r:
            first = json.loads(r.read())["replica"]
        # load now favors the OTHER replica; affinity must still win
        (a if first == "a" else b).behavior["queue_depth"] = 50
        _wait(lambda: max(r.load_score() for r in fleet.replicas) >= 50,
              what="probe load refresh")
        for _ in range(3):
            with _post(url, _body("sticky conversation")) as r:
                assert json.loads(r.read())["replica"] == first
        assert hits.total() >= h0 + 3
        # an explicit session_id key overrides the prefix hash
        k1 = affinity_key({"session_id": "s1", "messages": []})
        k2 = affinity_key(_body("sticky conversation"))
        assert k1.startswith("sid:") and k2.startswith("pfx:")
    finally:
        close()
        a.kill(), b.kill()


def test_affinity_rebinds_when_sticky_replica_dies():
    a, b = StubReplica("a"), StubReplica("b")
    a.start(), b.start()
    url, fleet, close = make_router([a, b])
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas),
              what="both replicas up")
        with _post(url, _body("rebind me")) as r:
            first = json.loads(r.read())["replica"]
        victim = a if first == "a" else b
        survivor = b if first == "a" else a
        victim.kill()
        _wait(lambda: _up(fleet, f"127.0.0.1:{victim.port}") == 0,
              what="victim ejected")
        with _post(url, _body("rebind me")) as r:
            assert json.loads(r.read())["replica"] == survivor.name
        # the session is now stuck to the survivor — even after the old
        # replica returns, the sticky map keeps it where its KV lives
        victim.start()
        _wait(lambda: _up(fleet, f"127.0.0.1:{victim.port}") == 1,
              what="victim re-admitted")
        with _post(url, _body("rebind me")) as r:
            assert json.loads(r.read())["replica"] == survivor.name
    finally:
        close()
        for s in (a, b):
            if s.httpd is not None:
                s.kill()


# -- retry / circuit breaker -------------------------------------------------


def test_proxy_failpoint_drives_transparent_retry():
    """Armed `proxy` failpoint severs the first upstream connection —
    the request transparently retries on a different replica and
    completes; the retry is visible in dllama_router_retries_total."""
    a, b = StubReplica("a"), StubReplica("b")
    a.start(), b.start()
    url, fleet, close = make_router([a, b])
    retries = tm.registry().counter(tm.ROUTER_RETRIES)
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas),
              what="both replicas up")
        r0 = retries.total()
        fp.arm("proxy", "conn_reset", times=1)
        with _post(url, _body("retry me")) as r:
            out = json.loads(r.read())
        assert out["replica"] in ("a", "b")
        assert retries.total() == r0 + 1
    finally:
        close()
        a.kill(), b.kill()


def test_midbody_death_retries_before_first_client_byte():
    """A replica that dies mid-body on a Content-Length response fails
    before anything reached the client — retried, not a 502."""
    a, b = StubReplica("a"), StubReplica("b")
    a.behavior["truncate_nonstream"] = True
    a.start(), b.start()
    url, fleet, close = make_router([a, b])
    retries = tm.registry().counter(tm.ROUTER_RETRIES)
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas),
              what="both replicas up")
        r0 = retries.total()
        n_ok = 0
        for i in range(4):  # distinct keys: some land on the truncator
            with _post(url, _body(f"q{i}")) as r:
                out = json.loads(r.read())
            assert out["replica"] == "b"  # only b can COMPLETE one
            n_ok += 1
        assert n_ok == 4
        # at least one request was dispatched to a first and retried
        assert retries.total() >= r0 + 1
    finally:
        close()
        a.kill(), b.kill()


def test_circuit_breaker_ejects_then_halfopen_readmits():
    a, b = StubReplica("a"), StubReplica("b")
    a.start(), b.start()
    url, fleet, close = make_router([a, b])
    reg = tm.registry()
    ejects = reg.counter(tm.ROUTER_EJECTS)
    readmits = reg.counter(tm.ROUTER_READMITS)
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas),
              what="both replicas up")
        name = f"127.0.0.1:{a.port}"
        e0, ra0 = ejects.total(replica=name), readmits.total(replica=name)
        a.kill()
        _wait(lambda: ejects.total(replica=name) == e0 + 1,
              what="breaker ejection")
        assert _up(fleet, name) == 0
        snap = [s for s in fleet.fleet_snapshot()["replicas"]
                if s["replica"] == name][0]
        assert snap["state"] == "down" and snap["backoff_s"] > 0
        # traffic keeps flowing on the survivor meanwhile
        with _post(url, _body("meanwhile")) as r:
            assert json.loads(r.read())["replica"] == "b"
        # restart: a bounded-backoff half-open probe re-admits it
        a.start()
        _wait(lambda: readmits.total(replica=name) == ra0 + 1,
              what="half-open re-admission")
        assert _up(fleet, name) == 1
        # dispatch returns to the re-admitted replica
        _wait(lambda: _served_by(url, "a"), timeout=10,
              what="dispatch back on a")
    finally:
        close()
        for s in (a, b):
            if s.httpd is not None:
                s.kill()


def _served_by(url, name, n=6):
    for i in range(n):
        with _post(url, _body(f"probe-{name}-{i}-{time.monotonic_ns()}")) \
                as r:
            if json.loads(r.read())["replica"] == name:
                return True
    return False


# -- shedding / drain --------------------------------------------------------


def test_all_replicas_saturated_sheds_429_with_retry_after():
    a, b = StubReplica("a"), StubReplica("b")
    a.start(), b.start()
    url, fleet, close = make_router([a, b])
    shed = tm.registry().counter(tm.ROUTER_SHED)
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas),
              what="both replicas up")
        for s in (a, b):
            s.behavior.update(ready=False, ready_code="queue_full")
        _wait(lambda: not fleet.readiness()[0], what="fleet saturated")
        assert fleet.readiness()[2] == "queue_full"
        s0 = shed.total()
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(url, _body("shed me"))
        assert e.value.code == 429
        assert e.value.headers["Retry-After"] is not None
        assert json.loads(e.value.read())["code"] == "queue_full"
        assert shed.total() == s0 + 1
        # replicas recover -> dispatch resumes
        for s in (a, b):
            s.behavior.update(ready=True)
        _wait(lambda: fleet.readiness()[0], what="fleet recovered")
        with _post(url, _body("recovered")) as r:
            assert r.status == 200
    finally:
        close()
        a.kill(), b.kill()


def test_router_max_queue_bound_sheds():
    a = StubReplica("a")
    a.behavior["nonstream_delay_s"] = 0.6
    a.start()
    url, fleet, close = make_router([a], max_inflight=1)
    shed = tm.registry().counter(tm.ROUTER_SHED)
    try:
        _wait(lambda: fleet.readiness()[0], what="replica up")
        s0 = shed.total()
        codes = []

        def slow():
            with _post(url, _body("slow one"), timeout=30) as r:
                codes.append(r.status)

        t = threading.Thread(target=slow)
        t.start()
        _wait(lambda: fleet.fleet_snapshot()["inflight_total"] >= 1,
              what="first request in flight")
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(url, _body("beyond the bound"))
        assert e.value.code == 429
        assert e.value.headers["Retry-After"] is not None
        assert shed.total() == s0 + 1
        t.join(timeout=30)
        assert codes == [200]  # the in-flight one finished fine
    finally:
        close()
        a.kill()


def test_dispatch_503_draining_reclassifies_without_eject():
    """The drain-awareness contract on the DISPATCH path: a replica
    whose completions answer 503 code=draining (the probe hasn't
    noticed yet) is reclassified unready — the request retries on the
    other replica and the circuit breaker is NOT fed (a draining pod
    must never be ejected into the crash-backoff schedule)."""
    a, b = StubReplica("a"), StubReplica("b")
    a.start(), b.start()
    # probes too slow to see the drain first: the dispatch path must
    # handle the classification itself
    url, fleet, close = make_router([a, b], probe_interval_s=30.0)
    ejects = tm.registry().counter(tm.ROUTER_EJECTS)
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas),
              what="both replicas up")
        name_a = f"127.0.0.1:{a.port}"
        e0 = ejects.total(replica=name_a)
        a.behavior.update(completion_status=503, error_code="draining")
        for i in range(4):
            with _post(url, _body(f"drain-race-{i}")) as r:
                assert json.loads(r.read())["replica"] == "b"
        assert ejects.total(replica=name_a) == e0  # reclassified, NOT ejected
        snap = [s for s in fleet.fleet_snapshot()["replicas"]
                if s["replica"] == name_a][0]
        assert snap["state"] == "unready" and snap["code"] == "draining"
    finally:
        close()
        a.kill(), b.kill()


def test_probe_sanitizes_unknown_ready_codes():
    """An out-of-vocabulary /readyz code degrades to "crashed" — the
    READY_CODES closed world is enforced at the router's probe parse,
    not just documented."""
    a = StubReplica("a")
    a.start()
    url, fleet, close = make_router([a])
    try:
        _wait(lambda: fleet.readiness()[0], what="replica up")
        a.behavior.update(ready=False, ready_code="weird_code")
        name = f"127.0.0.1:{a.port}"
        _wait(lambda: _up(fleet, name) == 0, what="unready observed")
        snap = fleet.fleet_snapshot()["replicas"][0]
        assert snap["state"] == "unready" and snap["code"] == "crashed"
    finally:
        close()
        a.kill()


def test_draining_replica_stops_new_dispatch():
    a, b = StubReplica("a"), StubReplica("b")
    a.start(), b.start()
    url, fleet, close = make_router([a, b])
    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas),
              what="both replicas up")
        a.behavior.update(ready=False, ready_code="draining")
        name = f"127.0.0.1:{a.port}"
        _wait(lambda: _up(fleet, name) == 0, what="drain observed")
        snap = [s for s in fleet.fleet_snapshot()["replicas"]
                if s["replica"] == name][0]
        assert snap["state"] == "unready" and snap["code"] == "draining"
        for i in range(4):  # nothing new lands on the draining replica
            with _post(url, _body(f"drain-{i}")) as r:
                assert json.loads(r.read())["replica"] == "b"
        # drain is not an ejection: no breaker backoff involved, and
        # recovery is immediate on the next probe
        a.behavior.update(ready=True)
        _wait(lambda: _up(fleet, name) == 1, what="drain ended")
    finally:
        close()
        a.kill(), b.kill()


# -- single-replica degradation (golden) -------------------------------------


def test_single_replica_router_is_byte_identical_passthrough():
    """ISSUE-12 satellite: a router fronting ONE replica returns byte-
    identical bodies to direct access — non-streaming, streaming, and
    error statuses (with Retry-After) pass through unmangled."""
    a = StubReplica("a")
    a.start()
    url, fleet, close = make_router([a], eject_after=100)
    try:
        _wait(lambda: fleet.readiness()[0], what="replica up")

        def both(payload):
            direct = _post(a.url, payload)
            routed = _post(url, payload)
            with direct, routed:
                return (direct.status, direct.read(),
                        routed.status, routed.read())

        # non-streaming completion
        ds, db, rs, rb = both(_body("golden"))
        assert (ds, db) == (rs, rb)
        # streaming completion: the SSE byte stream is identical
        ds, db, rs, rb = both(_body("golden", stream=True))
        assert (ds, db) == (rs, rb)
        assert b"data: [DONE]" in rb
        # error statuses pass through unmangled (status, body, and the
        # upstream's own Retry-After header)
        for status in (400, 429, 503):
            a.behavior["completion_status"] = status
            errs = []
            for base in (a.url, url):
                with pytest.raises(urllib.error.HTTPError) as e:
                    _post(base, _body("err"))
                errs.append((e.value.code, e.value.read(),
                             e.value.headers.get("Retry-After")))
            assert errs[0] == errs[1], status
        a.behavior["completion_status"] = 200
    finally:
        close()
        a.kill()


# -- mid-stream death --------------------------------------------------------


def test_midstream_death_gets_terminal_502_event_never_a_hang():
    a = StubReplica("a")
    a.behavior["die_after_chunks"] = 2
    a.start()
    url, fleet, close = make_router([a])
    http = tm.registry().counter(tm.HTTP_REQUESTS)
    try:
        _wait(lambda: fleet.readiness()[0], what="replica up")
        c0 = http.total(route="/v1/chat/completions", status="502")
        with _post(url, _body("doomed stream", stream=True),
                   timeout=30) as r:
            raw = r.read().decode()
        # the two relayed chunks arrived, then the EXPLICIT terminal
        # event naming the 502 — and the stream still ends with [DONE]
        # (a client can always tell this abort from a dropped socket)
        assert raw.count('"delta"') == 2
        assert '"upstream_error"' in raw and '"code": 502' in raw
        assert raw.rstrip().endswith("data: [DONE]")
        assert http.total(route="/v1/chat/completions",
                          status="502") == c0 + 1
    finally:
        close()
        a.kill()


# -- the ISSUE-12 chaos acceptance test --------------------------------------


def test_fleet_survives_replica_kill_and_restart_under_traffic():
    """3 replicas, continuous mixed traffic, one replica killed mid-run:
    every request that had not yet streamed a byte completes via retry
    on a survivor (zero silent failures; retries visible in
    dllama_router_retries_total), mid-stream victims get the explicit
    terminal 502 event, and after the restart the circuit breaker
    re-admits the replica and dispatch returns to all 3 — all
    telemetry-asserted."""
    stubs = [StubReplica(f"r{i}") for i in range(3)]
    for s in stubs:
        s.behavior["stream_chunks"] = ["a", "b", "c", "d"]
        s.behavior["chunk_delay_s"] = 0.01
        s.start()
    url, fleet, close = make_router(stubs)
    reg = tm.registry()
    retries = reg.counter(tm.ROUTER_RETRIES)
    ejects = reg.counter(tm.ROUTER_EJECTS)
    readmits = reg.counter(tm.ROUTER_READMITS)
    dispatch = reg.counter(tm.ROUTER_DISPATCHES)
    victim = stubs[1]
    vname = f"127.0.0.1:{victim.port}"
    r0, e0, ra0 = (retries.total(), ejects.total(replica=vname),
                   readmits.total(replica=vname))
    outcomes: list = []  # ("ok"|"midstream_502"|"silent"|..., detail)
    out_lock = threading.Lock()
    stop = threading.Event()

    def traffic(i):
        n = 0
        while not stop.is_set():
            n += 1
            stream = (i + n) % 2 == 0
            try:
                with _post(url, _body(f"t{i}-{n}", stream=stream),
                           timeout=30) as r:
                    raw = r.read()
                if not stream:
                    ok = r.status == 200 and b'"usage"' in raw
                    rec = ("ok" if ok else "silent", raw[:120])
                elif b'"upstream_error"' in raw:
                    rec = ("midstream_502", raw[-200:])
                elif b"[DONE]" in raw:
                    rec = ("ok", b"")
                else:
                    rec = ("silent", raw[:120])
            except urllib.error.HTTPError as e:
                rec = (f"http_{e.code}", e.read()[:120])
            except Exception as e:  # noqa: BLE001 — recorded, asserted below
                rec = ("silent", repr(e)[:120])
            with out_lock:
                outcomes.append(rec)
            time.sleep(0.01)

    try:
        _wait(lambda: all(_up(fleet, r.name) for r in fleet.replicas),
              what="all 3 replicas up")
        threads = [threading.Thread(target=traffic, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.4)  # steady traffic over all three
        # mid-run kill: streams in flight on the victim die with an RST
        # mid-chunk; new connections are refused
        victim.behavior["die_after_chunks"] = 1
        time.sleep(0.1)
        victim.kill()
        _wait(lambda: ejects.total(replica=vname) == e0 + 1,
              what="victim ejection", timeout=15)
        time.sleep(0.4)  # traffic continues on the 2 survivors
        victim.behavior["die_after_chunks"] = None
        victim.start()
        _wait(lambda: readmits.total(replica=vname) == ra0 + 1,
              what="victim re-admission", timeout=15)
        d_back = dispatch.total(replica=vname)
        time.sleep(0.5)  # dispatch spreads back over all 3
        stop.set()
        for t in threads:
            t.join(timeout=30)

        silent = [o for o in outcomes if o[0] == "silent"]
        assert not silent, silent[:3]
        errors = [o for o in outcomes if o[0].startswith("http_")]
        assert not errors, errors[:3]  # retries absorbed every pre-byte death
        n_ok = sum(1 for o in outcomes if o[0] == "ok")
        assert n_ok >= 20, f"only {n_ok} completions of {len(outcomes)}"
        # the kill was actually felt: pre-byte deaths were retried ...
        assert retries.total() > r0
        # ... and the re-admitted replica serves again
        assert dispatch.total(replica=vname) > d_back
        assert _up(fleet, vname) == 1
    finally:
        stop.set()
        close()
        for s in stubs:
            if s.httpd is not None:
                s.kill()


# -- end-to-end against a real engine ----------------------------------------


def test_router_fronts_real_engine_replica(tmp_path):
    """One real tiny CPU-mesh api-server behind the router: a chat
    completion through the router matches direct access (content +
    usage; ids/timestamps differ by design)."""
    import numpy as np
    from http.server import HTTPServer

    from dllama_tpu.formats import tfile
    from dllama_tpu.runtime.engine import InferenceEngine
    from dllama_tpu.serve.api import ApiState, make_handler

    from helpers import (byte_vocab_tokenizer, tiny_header_params,
                         write_tiny_model)

    mpath, tpath = tmp_path / "m.m", tmp_path / "t.t"
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96),
                     np.random.default_rng(9))
    td = byte_vocab_tokenizer()
    td.chat_template = "<|start_header_id|>"
    tfile.write_tfile(tpath, td)
    engine = InferenceEngine(str(mpath), str(tpath), temperature=0.0, seed=3)
    state = ApiState(engine)
    httpd = HTTPServer(("127.0.0.1", 0), make_handler(state))
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url, fleet, close = make_router([_FakeStub(port)])
    try:
        _wait(lambda: fleet.readiness()[0], what="engine replica up",
              timeout=30)
        body = {"messages": [{"role": "user", "content": "hello"}],
                "max_tokens": 6, "temperature": 0}
        with _post(f"http://127.0.0.1:{port}", body, timeout=120) as r:
            direct = json.loads(r.read())
        with _post(url, body, timeout=120) as r:
            routed = json.loads(r.read())
        assert routed["choices"] == direct["choices"]
        assert routed["usage"] == direct["usage"]
        # and the streaming path relays the real SSE stream
        with _post(url, dict(body, stream=True), timeout=120) as r:
            raw = r.read().decode()
        assert "data: [DONE]" in raw
    finally:
        close()
        httpd.shutdown()
        httpd.server_close()
        engine.close()


class _FakeStub:
    """Adapter so make_router can front an arbitrary local port."""

    def __init__(self, port):
        self.port = port

    @property
    def url(self):
        return f"http://127.0.0.1:{self.port}"
