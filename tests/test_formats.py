"""Round-trip tests for the .m and .t file formats using the format writers
(the same writers back the offline converter, mirroring the reference's
converter/writer.py + tokenizer-writer.py)."""

import numpy as np
import pytest

from dllama_tpu.formats import mfile, quants, tfile

from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model


def test_mfile_header_roundtrip(tmp_path):
    path = tmp_path / "tiny.m"
    params = tiny_header_params()
    rng = np.random.default_rng(0)
    write_tiny_model(path, params, rng)
    mf = mfile.ModelFile.open(path)
    h = mf.header
    assert h.arch_type == mfile.ArchType.LLAMA
    assert h.dim == 64 and h.n_layers == 2 and h.n_heads == 4 and h.n_kv_heads == 2
    assert h.head_dim == 16 and h.q_dim == 64 and h.kv_dim == 32
    assert h.vocab_size == 128 and h.seq_len == 64
    assert h.weight_type == quants.Q40
    assert h.rope_theta == 10000.0
    assert h.norm_epsilon == pytest.approx(1e-5)
    mf.close()


def test_mfile_tensor_walk_and_dequant(tmp_path):
    path = tmp_path / "tiny.m"
    params = tiny_header_params()
    rng = np.random.default_rng(1)
    dense = write_tiny_model(path, params, rng)
    with mfile.ModelFile.open(path) as mf:
        assert set(mf.tensors) == set(dense)
        # F32 tensors byte-exact; Q40 within block tolerance.
        np.testing.assert_array_equal(mf.tensor_f32("embedding"), dense["embedding"])
        w = mf.tensor_f32("block_matmul_q.0")
        ref = dense["block_matmul_q.0"]
        assert w.shape == ref.shape == (64, 64)
        scale = np.abs(ref).max()
        assert np.abs(w - ref).max() <= scale / 8 + 1e-6


def test_mfile_qwen3_walk(tmp_path):
    path = tmp_path / "tiny-qwen.m"
    params = tiny_header_params(arch=mfile.ArchType.QWEN3, head_dim=24)
    rng = np.random.default_rng(2)
    write_tiny_model(path, params, rng)
    with mfile.ModelFile.open(path) as mf:
        assert mf.header.rope_type == mfile.RopeType.FALCON  # forced (llm.cpp:109-110)
        assert mf.header.head_dim == 24
        assert "block_norm_q.0" in mf.tensors
        assert mf.tensors["block_norm_q.1"].shape == (24,)


def test_mfile_max_seq_len_truncation(tmp_path):
    path = tmp_path / "tiny.m"
    rng = np.random.default_rng(3)
    write_tiny_model(path, tiny_header_params(seq_len=64), rng)
    with mfile.ModelFile.open(path, max_seq_len=16) as mf:
        assert mf.header.seq_len == 16 and mf.header.orig_seq_len == 64


def test_mfile_q40_planes(tmp_path):
    path = tmp_path / "tiny.m"
    rng = np.random.default_rng(4)
    dense = write_tiny_model(path, tiny_header_params(), rng)
    with mfile.ModelFile.open(path) as mf:
        scales, codes = mf.tensor_q40_planes("block_matmul_w1.0")
        assert scales.shape == (96, 2) and codes.shape == (96, 64)
        recon = codes.astype(np.float32).reshape(96, 2, 32) * scales.astype(np.float32)[:, :, None]
        np.testing.assert_allclose(recon.reshape(96, 64), mf.tensor_f32("block_matmul_w1.0"))


def test_tfile_roundtrip(tmp_path):
    data = byte_vocab_tokenizer()
    data.chat_template = "{% for m in messages %}...{% endfor %}"
    path = tmp_path / "tok.t"
    tfile.write_tfile(path, data)
    rd = tfile.read_tfile(path)
    assert rd.vocab == data.vocab
    assert rd.scores == pytest.approx(data.scores)
    assert rd.bos_id == data.bos_id
    assert rd.add_bos == data.add_bos
    assert rd.eos_token_ids == data.eos_token_ids
    assert rd.chat_template == data.chat_template
    assert rd.regular_vocab_size == data.bos_id


# -- malformed-file error paths (a user pointing at the wrong file must get
# -- a clean diagnostic, not a crash, hang, or silent garbage) --------------


def test_mfile_rejects_wrong_magic(tmp_path):
    p = tmp_path / "bad.m"
    p.write_bytes(b"\x00" * 256)
    with pytest.raises(ValueError, match="magic"):
        mfile.ModelFile.open(p)


def test_mfile_rejects_truncated_body(tmp_path):
    """A valid header whose tensor data is cut short: the tensor walk's size
    check must fail loudly (reference: file-size assert, llm.cpp)."""
    path = tmp_path / "tiny.m"
    write_tiny_model(path, tiny_header_params(), np.random.default_rng(0))
    data = path.read_bytes()
    trunc = tmp_path / "trunc.m"
    trunc.write_bytes(data[: len(data) - 64])
    with pytest.raises(ValueError, match="size mismatch"):
        mfile.ModelFile.open(trunc)


def test_mfile_rejects_empty_file(tmp_path):
    p = tmp_path / "empty.m"
    p.write_bytes(b"")
    with pytest.raises((ValueError, OSError)):
        mfile.ModelFile.open(p)


def test_tfile_rejects_garbage(tmp_path):
    p = tmp_path / "bad.t"
    p.write_bytes(b"not a tokenizer file at all" * 4)
    with pytest.raises((ValueError, AssertionError)):
        tfile.read_tfile(p)
