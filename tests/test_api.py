"""HTTP API server tests: /v1/models, /v1/chat/completions (plain + SSE),
NaiveCache prefix reuse, error paths (reference: dllama-api.cpp)."""

import json
import threading
import urllib.request
from http.server import HTTPServer

import numpy as np
import pytest

from dllama_tpu.formats import tfile
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.serve.api import ApiState, make_handler

from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    d = tmp_path_factory.mktemp("api")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(9)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96), rng)
    td = byte_vocab_tokenizer()
    td.chat_template = "<|start_header_id|>"  # detected as llama3
    tfile.write_tfile(tpath, td)
    engine = InferenceEngine(str(mpath), str(tpath), temperature=0.0, seed=3)
    state = ApiState(engine)
    httpd = HTTPServer(("127.0.0.1", 0), make_handler(state))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{port}", state
    httpd.shutdown()


def _post(url, payload):
    req = urllib.request.Request(
        url + "/v1/chat/completions",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=120)


def test_models_endpoint(server):
    url, _ = server
    with urllib.request.urlopen(url + "/v1/models", timeout=30) as r:
        data = json.loads(r.read())
    assert data["object"] == "list"
    assert data["data"][0]["id"] == "dllama-tpu"


def test_chat_completion(server):
    url, _ = server
    with _post(url, {"messages": [{"role": "user", "content": "hello"}],
                     "max_tokens": 6, "temperature": 0}) as r:
        data = json.loads(r.read())
    assert data["object"] == "chat.completion"
    choice = data["choices"][0]
    assert choice["message"]["role"] == "assistant"
    assert data["usage"]["completion_tokens"] >= 1
    assert data["usage"]["total_tokens"] == (
        data["usage"]["prompt_tokens"] + data["usage"]["completion_tokens"])


def test_chat_completion_sse_stream(server):
    url, _ = server
    with _post(url, {"messages": [{"role": "user", "content": "hello"}],
                     "max_tokens": 5, "temperature": 0, "stream": True}) as r:
        assert r.headers["Content-Type"].startswith("text/event-stream")
        raw = r.read().decode()
    events = [line[6:] for line in raw.split("\n") if line.startswith("data: ")]
    assert events[-1] == "[DONE]"
    parsed = [json.loads(e) for e in events[:-1]]
    assert all(p["object"] == "chat.completion.chunk" for p in parsed)
    assert parsed[-1]["choices"][0]["finish_reason"] in ("stop", "length")


def test_request_stop_strings(server):
    """OpenAI ``stop`` per request: generation ends at the first custom stop
    string, which is excluded from the returned text. (The reference parses
    this field but never honors it — dllama-api.cpp:509-513.)"""
    url, _ = server
    base = {"messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 12, "temperature": 0}
    with _post(url, base) as r:
        full = json.loads(r.read())["choices"][0]["message"]["content"]
    assert len(full) >= 4, full
    stop = full[2:4]  # a substring the greedy run provably emits
    with _post(url, {**base, "stop": stop}) as r:
        data = json.loads(r.read())
    choice = data["choices"][0]
    assert choice["finish_reason"] == "stop"
    got = choice["message"]["content"]
    assert stop not in got
    assert got == full[:full.index(stop)]


def test_naive_cache_prefix_reuse(server):
    url, state = server
    convo = [{"role": "user", "content": "hi"}]
    with _post(url, {"messages": convo, "max_tokens": 4, "temperature": 0}) as r:
        first = json.loads(r.read())
    cached_items = len(state.cache.items)
    assert cached_items >= 1
    convo2 = convo + [{"role": "assistant",
                       "content": first["choices"][0]["message"]["content"]},
                      {"role": "user", "content": "again"}]
    delta, start = state.cache.resolve_delta(convo2)
    assert start > 0
    assert len(delta) < len(convo2)


def test_bad_json_body(server):
    url, _ = server
    req = urllib.request.Request(url + "/v1/chat/completions", data=b"{not json",
                                 headers={"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=30)
    assert e.value.code == 400


def test_missing_messages(server):
    url, _ = server
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(url, {"max_tokens": 3})
    assert e.value.code == 400


def test_unknown_route(server):
    url, _ = server
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(url + "/nope", timeout=30)
    assert e.value.code == 404


def test_single_mode_request_timeout(server):
    """The single-sequence path honors the body `timeout` inline: the
    decode loop stops at the deadline with finish_reason "timeout" (or
    408 when nothing was produced) instead of running to max_tokens."""
    from dllama_tpu.runtime import telemetry as tm

    url, _ = server
    before = tm.registry().counter(tm.REQUEST_TIMEOUTS).total()
    try:
        with _post(url, {"messages": [{"role": "user", "content": "hello"}],
                         "max_tokens": 80, "timeout": 0.015}) as r:
            out = json.loads(r.read())
        assert out["choices"][0]["finish_reason"] == "timeout"
        assert out["usage"]["completion_tokens"] < 80
    except urllib.error.HTTPError as e:
        assert e.code == 408  # deadline expired before the first token
    assert tm.registry().counter(tm.REQUEST_TIMEOUTS).total() >= before + 1


def test_healthz_and_readyz(server):
    url, _ = server
    for path in ("/health", "/healthz", "/readyz"):
        with urllib.request.urlopen(url + path, timeout=30) as r:
            assert r.status == 200
            assert json.loads(r.read())["status"] == "ok"
    # /readyz carries the machine-readable code next to the human reason
    with urllib.request.urlopen(url + "/readyz", timeout=30) as r:
        assert json.loads(r.read())["code"] == "ok"


def test_malformed_bodies_return_400_never_500(server):
    """Typed-field garbage must die as a 400 JSON error, not a 500
    (ISSUE 2 satellite; the fault-tolerance contract's input edge)."""
    url, _ = server
    ok_msgs = [{"role": "user", "content": "hi"}]
    bad_bodies = [
        {"max_tokens": 3},                                   # no messages
        {"messages": "not a list", "max_tokens": 3},         # non-list
        {"messages": [], "max_tokens": 3},                   # empty list
        {"messages": ["loose string"]},                      # non-dict item
        {"messages": [{"role": 5, "content": "hi"}]},        # non-str role
        {"messages": [{"role": "user", "content": 7}]},      # non-str content
        {"messages": ok_msgs, "max_tokens": -4},             # negative
        {"messages": ok_msgs, "max_tokens": 2.5},            # non-int
        {"messages": ok_msgs, "max_tokens": True},           # bool-as-int
        {"messages": ok_msgs, "temperature": "hot"},         # non-numeric
        {"messages": ok_msgs, "temperature": -1},            # out of range
        {"messages": ok_msgs, "top_p": 40},                  # out of range
        {"messages": ok_msgs, "seed": "lucky"},              # non-int
        {"messages": ok_msgs, "timeout": "soon"},            # non-numeric
        {"messages": ok_msgs, "timeout": -3},                # non-positive
        {"messages": ok_msgs, "timeout": 1e9},               # absurd
        {"messages": ok_msgs, "stop": 42},                   # non str/list
        {"messages": ok_msgs, "stop": [42]},                 # non-str item
        {"messages": ok_msgs, "stop": ["x", None]},          # null item
        [1, 2, 3],                                           # non-object body
    ]
    for body in bad_bodies:
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(url, body)
        assert e.value.code == 400, body
        assert "error" in json.loads(e.value.read()), body
    # stream requests get the same 400 (SSE headers are sent lazily, so a
    # pre-flight failure can still carry a real status code)
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(url, {"messages": ok_msgs, "max_tokens": -1, "stream": True})
    assert e.value.code == 400
    # explicit JSON null means "absent" (OpenAI semantics), never a 500
    with _post(url, {"messages": ok_msgs, "max_tokens": 3,
                     "temperature": None, "top_p": None, "seed": None,
                     "timeout": None, "stop": None}) as r:
        assert json.loads(r.read())["usage"]["completion_tokens"] >= 1


# -- continuous batching mode (--batch-slots; runtime/serving.py) ----------


@pytest.fixture(scope="module")
def batched_server(tmp_path_factory):
    from http.server import ThreadingHTTPServer

    from dllama_tpu.serve.api import BatchedApiState

    d = tmp_path_factory.mktemp("api_batched")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(9)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96), rng)
    td = byte_vocab_tokenizer()
    td.chat_template = "<|start_header_id|>"  # detected as llama3
    tfile.write_tfile(tpath, td)
    engine = InferenceEngine(str(mpath), str(tpath), temperature=0.0, seed=3)
    state = BatchedApiState(engine, n_slots=2)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(state))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{port}", state
    httpd.shutdown()
    state.close()


def test_batched_concurrent_requests_complete_and_are_deterministic(batched_server):
    """4 concurrent HTTP requests through 2 slots: all finish, and identical
    request bodies (same seed) produce identical completions regardless of
    what shared the batch."""
    url, _ = batched_server
    bodies = [
        {"messages": [{"role": "user", "content": "hello"}],
         "max_tokens": 6, "temperature": 0},
        {"messages": [{"role": "user", "content": "world"}],
         "max_tokens": 6, "temperature": 0.8, "seed": 5},
        {"messages": [{"role": "user", "content": "hello"}],
         "max_tokens": 6, "temperature": 0},
        {"messages": [{"role": "user", "content": "hi there"}],
         "max_tokens": 4, "temperature": 0},
    ]
    results: dict[int, dict] = {}
    errs: list = []

    def call(i):
        try:
            with _post(url, bodies[i]) as r:
                results[i] = json.loads(r.read())
        except Exception as e:  # noqa: BLE001
            errs.append((i, e))

    threads = [threading.Thread(target=call, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not errs, errs
    assert len(results) == 4
    for i, data in results.items():
        assert data["usage"]["completion_tokens"] >= 1, i
    # identical bodies 0 and 2 → identical text (batch-composition invariant)
    a = results[0]["choices"][0]["message"]["content"]
    b = results[2]["choices"][0]["message"]["content"]
    assert a == b


def test_batched_sse_stream(batched_server):
    url, _ = batched_server
    with _post(url, {"messages": [{"role": "user", "content": "hello"}],
                     "max_tokens": 5, "temperature": 0, "stream": True}) as r:
        raw = r.read().decode()
    assert "data: [DONE]" in raw
    chunks = [json.loads(ln[len("data: "):]) for ln in raw.splitlines()
              if ln.startswith("data: ") and "[DONE]" not in ln]
    assert any(c["choices"][0]["delta"].get("content") for c in chunks)


def test_batched_request_stop_strings(batched_server):
    """Custom stop strings under continuous batching: the slot is cancelled
    at the match and the stop text is excluded."""
    url, _ = batched_server
    base = {"messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 12, "temperature": 0}
    with _post(url, base) as r:
        full = json.loads(r.read())["choices"][0]["message"]["content"]
    assert len(full) >= 4, full
    stop = full[2:4]
    with _post(url, {**base, "stop": [stop]}) as r:
        data = json.loads(r.read())
    choice = data["choices"][0]
    assert choice["finish_reason"] == "stop"
    assert choice["message"]["content"] == full[:full.index(stop)]


def test_api_speculative_matches_plain(tmp_path):
    """ApiState.complete with an engine built with spec_lookup: identical
    text/usage to the plain engine (speculative greedy is exact)."""
    mpath, tpath = tmp_path / "m.m", tmp_path / "t.t"
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96),
                     np.random.default_rng(9))
    td = byte_vocab_tokenizer()
    td.chat_template = "<|start_header_id|>"
    tfile.write_tfile(tpath, td)
    body = {"messages": [{"role": "user", "content": "hello hello"}],
            "max_tokens": 24, "temperature": 0}
    results = []
    for kw in ({}, {"spec_lookup": 4}):
        eng = InferenceEngine(str(mpath), str(tpath), temperature=0.0, **kw)
        try:
            results.append(ApiState(eng).complete(dict(body)))
        finally:
            eng.close()
    plain, spec = results
    assert spec["text"] == plain["text"]
    assert spec["completion_tokens"] == plain["completion_tokens"]
    assert spec["finish_reason"] == plain["finish_reason"]


def test_eos_gate_flushes_maybe_eos_tail():
    """Generation ending by LENGTH with a buffered stop-piece prefix must
    flush that text instead of silently truncating (review finding)."""
    from dllama_tpu.serve.api import _EosGate

    class FakeTok:
        eos_token_ids = [999]

    gate = _EosGate(FakeTok(), ["<|eot|>"])
    assert not gate.feed(1, "hi ")
    assert not gate.feed(2, "<|eo")  # MAYBE_EOS: buffered, not emitted
    assert "".join(gate.parts) == "hi "
    gate.flush_tail()
    assert "".join(gate.parts) == "hi <|eo"


def test_batched_defaults_to_engine_sampler_settings(batched_server):
    """A body without 'temperature' must use the engine's CLI temperature
    (here 0.0 → greedy): two such requests give identical text even without
    a seed, and match an explicit temperature=0 request."""
    url, _ = batched_server
    body = {"messages": [{"role": "user", "content": "abc"}], "max_tokens": 5}
    with _post(url, body) as r:
        a = json.loads(r.read())["choices"][0]["message"]["content"]
    with _post(url, dict(body, temperature=0)) as r:
        b = json.loads(r.read())["choices"][0]["message"]["content"]
    assert a == b


# -- durable-stream satellites: resume validation, jitter, advertisement --


def test_resume_field_validation():
    """The router-only resume fields die in _validate_body as 400-shaped
    ValueErrors: resume_from a positive true int, resume_tokens exactly
    resume_from non-negative ids, never one without the other."""
    from dllama_tpu.serve.api import _validate_body

    ok = {"messages": [{"role": "user", "content": "hi"}]}
    _validate_body(dict(ok, resume_from=2, resume_tokens=[5, 9]))
    bad = [
        dict(ok, resume_from=0, resume_tokens=[]),        # zero
        dict(ok, resume_from=-1, resume_tokens=[1]),      # negative
        dict(ok, resume_from=True, resume_tokens=[1]),    # bool-as-int
        dict(ok, resume_from="2", resume_tokens=[1, 2]),  # non-int
        dict(ok, resume_from=2),                          # from w/o tokens
        dict(ok, resume_tokens=[1, 2]),                   # tokens w/o from
        dict(ok, resume_from=2, resume_tokens=[1]),       # length mismatch
        dict(ok, resume_from=1, resume_tokens="x"),       # non-list
        dict(ok, resume_from=2, resume_tokens=[1, -2]),   # negative id
        dict(ok, resume_from=2, resume_tokens=[1, True]), # bool id
    ]
    for body in bad:
        with pytest.raises(ValueError):
            _validate_body(body)


def test_backpressure_retry_after_jitter_bounds():
    """Retry-After carries bounded random jitter (base..base+jitter) so
    a synchronized 429/503 wave doesn't re-arrive as one — and the
    jitter actually varies rather than collapsing to the base."""
    from dllama_tpu.serve.api import (RETRY_AFTER_JITTER_S, RETRY_AFTER_S,
                                      backpressure_headers)

    for status in (429, 503):
        lo = RETRY_AFTER_S[status]
        hi = lo + RETRY_AFTER_JITTER_S[status]
        got = {int(backpressure_headers(status)["Retry-After"])
               for _ in range(200)}
        assert min(got) >= lo and max(got) <= hi
        assert len(got) > 1, f"Retry-After jitter never varied for {status}"


def test_kv_prefix_advertisement_ttl_and_lru_bound():
    """The prefix-residency advertisement is a TTL'd bounded LRU:
    re-notes refresh, drops evict early, expired stamps never reach a
    probe, and the cap sheds the oldest entry first."""
    from collections import OrderedDict

    from dllama_tpu.serve.api import BatchedApiState

    st = BatchedApiState.__new__(BatchedApiState)  # advertisement only
    st._kv_prefixes = OrderedDict()
    st._kv_lock = threading.Lock()

    st.note_kv_prefix("sid:a")
    st.note_kv_prefix("sid:b")
    st.note_kv_prefix("sid:a")  # re-note refreshes and moves to front
    assert st.kv_prefix_list() == ["sid:a", "sid:b"]
    st.drop_kv_prefix("sid:b")
    st.drop_kv_prefix(None)  # no-op, never raises
    assert st.kv_prefix_list() == ["sid:a"]

    with st._kv_lock:  # age the stamp past the TTL window
        st._kv_prefixes["sid:a"] -= BatchedApiState.KV_PREFIX_TTL_S + 1
    assert st.kv_prefix_list() == []

    for i in range(BatchedApiState.KV_PREFIX_MAX + 5):
        st.note_kv_prefix(f"sid:{i}")
    lst = st.kv_prefix_list()
    assert len(lst) == BatchedApiState.KV_PREFIX_MAX
    assert lst[0] == f"sid:{BatchedApiState.KV_PREFIX_MAX + 4}"
    assert "sid:0" not in lst


def test_tenant_echo_and_debug_tenants(batched_server):
    """ISSUE-20: the api server echoes the sanitized X-Dllama-Tenant on
    the response, bills the request's tokens to that tenant, and serves
    the observatory at GET /debug/tenants; a malformed id is anon."""
    from dllama_tpu.runtime import tenancy

    url, _ = batched_server
    body = {"messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 4, "temperature": 0}
    req = urllib.request.Request(
        url + "/v1/chat/completions", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json",
                 "X-Dllama-Tenant": "acme-api"})
    with urllib.request.urlopen(req, timeout=120) as r:
        assert r.headers["X-Dllama-Tenant"] == "acme-api"
        data = json.loads(r.read())
    n = data["usage"]["completion_tokens"]
    with urllib.request.urlopen(url + "/debug/tenants", timeout=30) as r:
        snap = json.loads(r.read())
    assert snap["cap"] == tenancy.TENANT_CAP
    st = snap["tenants"]["acme-api"]
    assert st["decode_tokens"] >= n
    assert st["admissions"] >= 1
    assert "jain_index" in snap["fairness"]
    # malformed identity collapses to anon on the echo
    req = urllib.request.Request(
        url + "/v1/chat/completions", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json",
                 "X-Dllama-Tenant": "bad id!{}"})
    with urllib.request.urlopen(req, timeout=120) as r:
        assert r.headers["X-Dllama-Tenant"] == "anon"
