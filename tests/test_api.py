"""HTTP API server tests: /v1/models, /v1/chat/completions (plain + SSE),
NaiveCache prefix reuse, error paths (reference: dllama-api.cpp)."""

import json
import threading
import urllib.request
from http.server import HTTPServer

import numpy as np
import pytest

from dllama_tpu.formats import tfile
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.serve.api import ApiState, make_handler

from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    d = tmp_path_factory.mktemp("api")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(9)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96), rng)
    td = byte_vocab_tokenizer()
    td.chat_template = "<|start_header_id|>"  # detected as llama3
    tfile.write_tfile(tpath, td)
    engine = InferenceEngine(str(mpath), str(tpath), temperature=0.0, seed=3)
    state = ApiState(engine)
    httpd = HTTPServer(("127.0.0.1", 0), make_handler(state))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{port}", state
    httpd.shutdown()


def _post(url, payload):
    req = urllib.request.Request(
        url + "/v1/chat/completions",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=120)


def test_models_endpoint(server):
    url, _ = server
    with urllib.request.urlopen(url + "/v1/models", timeout=30) as r:
        data = json.loads(r.read())
    assert data["object"] == "list"
    assert data["data"][0]["id"] == "dllama-tpu"


def test_chat_completion(server):
    url, _ = server
    with _post(url, {"messages": [{"role": "user", "content": "hello"}],
                     "max_tokens": 6, "temperature": 0}) as r:
        data = json.loads(r.read())
    assert data["object"] == "chat.completion"
    choice = data["choices"][0]
    assert choice["message"]["role"] == "assistant"
    assert data["usage"]["completion_tokens"] >= 1
    assert data["usage"]["total_tokens"] == (
        data["usage"]["prompt_tokens"] + data["usage"]["completion_tokens"])


def test_chat_completion_sse_stream(server):
    url, _ = server
    with _post(url, {"messages": [{"role": "user", "content": "hello"}],
                     "max_tokens": 5, "temperature": 0, "stream": True}) as r:
        assert r.headers["Content-Type"].startswith("text/event-stream")
        raw = r.read().decode()
    events = [line[6:] for line in raw.split("\n") if line.startswith("data: ")]
    assert events[-1] == "[DONE]"
    parsed = [json.loads(e) for e in events[:-1]]
    assert all(p["object"] == "chat.completion.chunk" for p in parsed)
    assert parsed[-1]["choices"][0]["finish_reason"] in ("stop", "length")


def test_naive_cache_prefix_reuse(server):
    url, state = server
    convo = [{"role": "user", "content": "hi"}]
    with _post(url, {"messages": convo, "max_tokens": 4, "temperature": 0}) as r:
        first = json.loads(r.read())
    cached_items = len(state.cache.items)
    assert cached_items >= 1
    convo2 = convo + [{"role": "assistant",
                       "content": first["choices"][0]["message"]["content"]},
                      {"role": "user", "content": "again"}]
    delta, start = state.cache.resolve_delta(convo2)
    assert start > 0
    assert len(delta) < len(convo2)


def test_bad_json_body(server):
    url, _ = server
    req = urllib.request.Request(url + "/v1/chat/completions", data=b"{not json",
                                 headers={"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=30)
    assert e.value.code == 400


def test_missing_messages(server):
    url, _ = server
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(url, {"max_tokens": 3})
    assert e.value.code == 400


def test_unknown_route(server):
    url, _ = server
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(url + "/nope", timeout=30)
    assert e.value.code == 404
