"""Real-TPU kernel tier (@pytest.mark.tpu) — run in the bench window:

    DLLAMA_TESTS_TPU=1 python -m pytest tests/ -m tpu -q

Makes the Pallas-kernel error-bound claims (ops/quant_matmul.py module doc:
~2e-5 abs error at Precision.HIGHEST) reproducible artifacts instead of
builder folklore (VERDICT round-1 weak #7), and exercises the fused greedy
decode + sharded kernels on actual hardware. Every test here skips cleanly
when the backend isn't a TPU.
"""

from __future__ import annotations

import numpy as np
import pytest

pytestmark = pytest.mark.tpu


@pytest.fixture(scope="module")
def tpu_backend():
    import jax

    devs = jax.devices()
    if not devs or "tpu" not in devs[0].device_kind.lower():
        pytest.skip(f"no TPU backend (devices: {devs})")
    return devs


def test_quant_matmul_error_bound_on_hw(tpu_backend):
    """Kernel vs exact float64 host oracle: abs error ~2e-5 at HIGHEST."""
    import jax.numpy as jnp

    from dllama_tpu.ops.linear import dequantize_weight, quantize_weight_q40
    from dllama_tpu.ops.quant_matmul import quant_matmul

    rng = np.random.default_rng(7)
    w = quantize_weight_q40((rng.standard_normal((512, 1024)) * 0.1).astype(np.float32))
    x = jnp.asarray(rng.standard_normal((8, 1024)), jnp.float32)

    got = np.asarray(quant_matmul(x, w))
    wd = np.asarray(dequantize_weight(w)).astype(np.float64)
    want = np.asarray(x, np.float64) @ wd
    err = np.abs(got - want).max()
    assert err < 5e-5, f"max abs error {err}"


def test_fused_decode_kernel_error_bound_on_hw(tpu_backend):
    """The decode-shaped fused dequant-GEMV (DLLAMA_TPU_QUANT_KERNEL=fused
    candidate) compiled by Mosaic: exact mode vs the float64 host oracle at
    the tiled kernel's error bound; fast mode within bf16-rounding drift of
    exact (the serving-mode contract)."""
    import jax.numpy as jnp

    from dllama_tpu.ops.linear import dequantize_weight, quantize_weight_q40
    from dllama_tpu.ops.quant_matmul import quant_matmul, supports_decode

    rng = np.random.default_rng(17)
    w = quantize_weight_q40(
        (rng.standard_normal((512, 2048)) * 0.1).astype(np.float32))
    x = jnp.asarray(rng.standard_normal((1, 2048)), jnp.float32)
    assert supports_decode((1, 2048), w)

    got = np.asarray(quant_matmul(x, w, fused=True))
    wd = np.asarray(dequantize_weight(w)).astype(np.float64)
    want = np.asarray(x, np.float64) @ wd
    assert np.abs(got - want).max() < 5e-5

    fast = np.asarray(quant_matmul(x, w, fused=True, fast=True))
    rms = float(np.sqrt(np.mean(got ** 2)))
    assert np.abs(fast - got).max() / rms < 2e-2


def test_flash_attention_parity_on_hw(tpu_backend):
    """Kernel vs XLA oracle on the MXU. At default matmul precision the MXU
    runs one bf16 pass per f32 dot, so kernel-vs-oracle differences are
    accumulation-order noise at bf16 scale (~2.5e-3 measured on v5e) — assert
    a gross-error bound there. Under HIGHEST (3-pass f32 emulation) both
    paths are f32-exact and agree to float epsilon."""
    import jax
    import jax.numpy as jnp

    from dllama_tpu.ops.attention import attention
    from dllama_tpu.ops.flash_attention import flash_attention

    rng = np.random.default_rng(11)
    B, T, H, KV, D, S = 1, 4, 8, 2, 64, 256
    q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, KV, S, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, KV, S, D)), jnp.float32)
    start = jnp.int32(17)
    positions = start + jnp.arange(T, dtype=jnp.int32)[None, :]

    with jax.default_matmul_precision("highest"):
        got = np.asarray(flash_attention(q, k, v, start, D))
        want = np.asarray(attention(q, k, v, positions, D))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    got_d = np.asarray(flash_attention(q, k, v, start, D))
    want_d = np.asarray(attention(q, k, v, positions, D))
    assert np.abs(got_d - want_d).max() < 2e-2


def test_fused_greedy_decode_on_hw(tpu_backend):
    """The production decode step compiles and steps on hardware, quantized
    params + donated KV, token never leaving the device."""
    import jax
    import jax.numpy as jnp

    from dllama_tpu.formats.mfile import ArchType, RopeType
    from dllama_tpu.models import ModelConfig, init_random_params
    from dllama_tpu.models.llama import greedy_step
    from dllama_tpu.runtime import KVCache

    cfg = ModelConfig(
        arch=ArchType.LLAMA, dim=256, hidden_dim=512, n_layers=2,
        n_heads=4, n_kv_heads=2, head_dim=64, vocab_size=2048, seq_len=256,
        norm_epsilon=1e-5, rope_theta=10000.0, rope_type=RopeType.LLAMA,
        compute_dtype="bfloat16")
    params = init_random_params(cfg, seed=3, quantized=True)
    kv = KVCache.create(cfg, dtype=jnp.bfloat16)
    greedy = jax.jit(greedy_step, static_argnums=1, donate_argnums=(4,))

    token = jnp.zeros((1, 1), jnp.int32)
    toks = []
    for pos in range(4):
        nxt, kv = greedy(params, cfg, token, jnp.int32(pos), kv)
        token = nxt[:, None]
        toks.append(int(nxt[0]))
    assert all(0 <= t < cfg.vocab_size for t in toks)
    # determinism: same inputs, fresh cache -> same tokens
    kv2 = KVCache.create(cfg, dtype=jnp.bfloat16)
    token = jnp.zeros((1, 1), jnp.int32)
    toks2 = []
    for pos in range(4):
        nxt, kv2 = greedy(params, cfg, token, jnp.int32(pos), kv2)
        token = nxt[:, None]
        toks2.append(int(nxt[0]))
    assert toks == toks2


def test_sharded_quant_matmul_on_hw(tpu_backend):
    """TP shard_map kernel path on hardware (single chip = tp 1 mesh still
    routes through quant_matmul_sharded's shard_map)."""
    import jax.numpy as jnp

    from dllama_tpu.ops.linear import linear, quantize_weight_q40
    from dllama_tpu.ops.quant_matmul import quant_matmul_sharded
    from dllama_tpu.parallel.api import make_tp_mesh

    rng = np.random.default_rng(13)
    w = quantize_weight_q40((rng.standard_normal((256, 512)) * 0.1).astype(np.float32))
    x = jnp.asarray(rng.standard_normal((1, 8, 512)), jnp.float32)
    plan = make_tp_mesh(len(tpu_backend))
    got = quant_matmul_sharded(plan, x, w, out_axis="hidden")
    assert got is not None
    want = linear(x, w)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_f8_kv_flash_on_hw(tpu_backend):
    """float8_e4m3 cache through the real Mosaic-lowered flash kernel: f8
    loads + upcast must match the XLA oracle reading the same stored cache."""
    import jax
    import jax.numpy as jnp

    from dllama_tpu.ops.attention import attention
    from dllama_tpu.ops.flash_attention import flash_attention

    rng = np.random.default_rng(41)
    B, T, H, KV, D, S = 1, 4, 8, 2, 64, 256
    q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    k8 = jnp.asarray(rng.standard_normal((B, KV, S, D)),
                     jnp.float32).astype(jnp.float8_e4m3fn)
    v8 = jnp.asarray(rng.standard_normal((B, KV, S, D)),
                     jnp.float32).astype(jnp.float8_e4m3fn)
    start = jnp.int32(17)
    positions = start + jnp.arange(T, dtype=jnp.int32)[None, :]
    with jax.default_matmul_precision("highest"):
        got = np.asarray(flash_attention(q, k8, v8, start, D))
        want = np.asarray(attention(q, k8, v8, positions, D))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_ragged_serving_programs_on_hw(tpu_backend):
    """The batched-serving dispatches on real hardware: one ragged mixed
    greedy/sampled step and one ragged speculative verify, per-row
    positions, donated KV."""
    import jax
    import jax.numpy as jnp

    from dllama_tpu.formats.mfile import ArchType, RopeType
    from dllama_tpu.models import ModelConfig, init_random_params
    from dllama_tpu.models.llama import ragged_verify_step, sampled_step
    from dllama_tpu.runtime import KVCache

    cfg = ModelConfig(
        arch=ArchType.LLAMA, dim=256, hidden_dim=512, n_layers=2,
        n_heads=4, n_kv_heads=2, head_dim=64, vocab_size=2048, seq_len=256,
        norm_epsilon=1e-5, rope_theta=10000.0, rope_type=RopeType.LLAMA,
        compute_dtype="bfloat16")
    params = init_random_params(cfg, seed=9, quantized=True)
    n_slots = 4
    kv = KVCache.create(cfg, batch_size=n_slots, dtype=jnp.bfloat16)
    step = jax.jit(sampled_step, static_argnums=1, donate_argnums=(4,))
    verify = jax.jit(ragged_verify_step, static_argnums=1, donate_argnums=(4,))

    pos = jnp.asarray([3, 0, 9, 5], jnp.int32)
    temps = jnp.asarray([0.0, 0.8, 0.0, 1.2], jnp.float32)
    topps = jnp.full((n_slots,), 0.9, jnp.float32)
    coins = jnp.full((n_slots,), 0.4, jnp.float32)
    toks = jnp.ones((n_slots, 1), jnp.int32)
    nxt, kv = step(params, cfg, toks, pos, kv, temps, topps, coins)
    assert nxt.shape == (n_slots,)
    draft = jnp.tile(nxt[:, None], (1, 5))
    n_acc, preds, kv = verify(params, cfg, draft, pos + 1, kv,
                              temps, topps, coins)
    n_acc, preds = np.asarray(n_acc), np.asarray(preds)
    assert preds.shape == (n_slots, 5)
    sampled_rows = np.asarray(temps) > 0
    assert (n_acc[sampled_rows] == 0).all()  # sampled rows accept nothing


def test_spec_transcript_identity_on_hw(tpu_backend):
    """--spec-lookup vs plain greedy transcript identity ON HARDWARE
    (ADVICE r3 #1): the claim 'exact by construction' rides on logits being
    bit-equal between the [1, K+1] verify dispatch and the [1, 1] decode
    dispatch — exactly the dispatch-shape ulp hazard golden_assets documents.
    CPU asserts it in test_speculative.py; this asserts it where it can
    actually break. A mismatch here would demote speculation from 'exact'
    to 'approximate' and must fail loudly."""
    import numpy as np

    from dllama_tpu.formats import tfile
    from dllama_tpu.runtime.engine import InferenceEngine
    from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model

    import tempfile, os
    d = tempfile.mkdtemp(prefix="dllama-hw-spec-")
    m, t = os.path.join(d, "m.m"), os.path.join(d, "t.t")
    rng = np.random.default_rng(17)
    write_tiny_model(m, tiny_header_params(vocab_size=268, seq_len=160), rng)
    tfile.write_tfile(t, byte_vocab_tokenizer())

    plain = InferenceEngine(m, t, temperature=0.0, seed=5,
                            compute_dtype="bfloat16")
    r_plain = plain.generate("hello world hello world", 24, stop_on_eos=False)
    spec = InferenceEngine(m, t, temperature=0.0, seed=5,
                           compute_dtype="bfloat16", spec_lookup=4)
    r_spec = spec.generate("hello world hello world", 24, stop_on_eos=False)
    assert r_spec.tokens == r_plain.tokens
    # speculation actually engaged: fewer dispatches than tokens
    n_disp = sum(1 for s in r_spec.steps if s.kind == "pred")
    assert n_disp < len(r_spec.tokens)


def test_fast_mode_quant_matmul_drift_on_hw(tpu_backend):
    """Exact-vs-fast drift on the REAL MXU (the CPU interpret-mode drift
    test can't see Mosaic's actual bf16 pass): fast mode must stay within
    bf16-rounding distance of the exact kernel, and the model-level argmax
    (greedy token) must be stable at these shapes."""
    import jax.numpy as jnp

    from dllama_tpu.ops.linear import quantize_weight_q40
    from dllama_tpu.ops.quant_matmul import quant_matmul

    rng = np.random.default_rng(23)
    w = quantize_weight_q40(
        (rng.standard_normal((512, 1024)) * 0.1).astype(np.float32))
    x = jnp.asarray(rng.standard_normal((8, 1024)), jnp.float32)

    exact = np.asarray(quant_matmul(x, w))
    fast = np.asarray(quant_matmul(x, w, fast=True))
    rms = float(np.sqrt(np.mean(exact ** 2)))
    drift = float(np.abs(fast - exact).max()) / rms
    assert drift < 2e-2, drift
    # row argmax (the greedy-token proxy) unchanged — asserted only where
    # the top-2 gap exceeds twice the tolerated drift, so a legal rounding
    # difference on a near-tie can't flake the test across TPU generations
    top2 = np.sort(exact, axis=-1)[:, -2:]
    decisive = (top2[:, 1] - top2[:, 0]) > 2 * 2e-2 * rms
    assert decisive.any()
    np.testing.assert_array_equal(exact.argmax(-1)[decisive],
                                  fast.argmax(-1)[decisive])


def test_decode_rate_physically_sane_on_hw(tpu_backend):
    """Fetch-forced decode rate sits inside its physical window.

    Two regression classes this guards (both happened in round 4):
    * timing that doesn't force execution (block_until_ready on the axon
      tunnel) reports ENQUEUE rates far ABOVE the HBM roofline;
    * a quant-matmul dispatch regression (e.g. back to the ~130 GB/s
      custom-call path) drops the rate far BELOW the fused-dequant band.
    Bounds are generous (roofline/6 .. roofline*1.3) so chip generations
    and tunnel jitter can't flake them; the production path measures
    ~roofline/3 (CHANGELOG round 4).
    """
    import time

    import jax
    import jax.numpy as jnp

    from dllama_tpu.formats.mfile import ArchType, RopeType
    from dllama_tpu.models import ModelConfig, init_random_params
    from dllama_tpu.models.llama import greedy_step
    from dllama_tpu.ops.linear import QuantizedWeight
    from dllama_tpu.runtime import KVCache

    cfg = ModelConfig(
        arch=ArchType.LLAMA, dim=2048, hidden_dim=8192, n_layers=8,
        n_heads=16, n_kv_heads=8, head_dim=128, vocab_size=32000,
        seq_len=512, norm_epsilon=1e-5, rope_theta=500000.0,
        rope_type=RopeType.LLAMA, compute_dtype="bfloat16")
    params = init_random_params(cfg, seed=5, quantized=True)
    kv = KVCache.create(cfg, dtype=jnp.bfloat16)
    greedy = jax.jit(greedy_step, static_argnums=1, donate_argnums=(4,))

    def fetch(x):
        jax.device_get(jnp.ravel(x)[0])

    token = jnp.zeros((1,), jnp.int32)
    token, kv = greedy(params, cfg, token[:, None], jnp.int32(0), kv)
    fetch(token)
    token, kv = greedy(params, cfg, token[:, None], jnp.int32(1), kv)
    fetch(token)  # throwaway: first post-compile dispatch absorbs backlog
    probe = jax.jit(lambda x: x + 1)(jnp.zeros((8,), jnp.int32))
    fetch(probe)
    t0 = time.perf_counter()
    fetch(probe)
    rtt = time.perf_counter() - t0

    steps = 24
    t0 = time.perf_counter()
    for i in range(steps):
        token, kv = greedy(params, cfg, token[:, None], jnp.int32(2 + i), kv)
    fetch(token)
    ms = 1e3 * max(1e-9, time.perf_counter() - t0 - rtt) / steps

    # bytes a decode step must stream: the layer stacks + the head
    # (embedding excluded: one gathered row per step)
    nbytes = 0
    for leaf in jax.tree_util.tree_leaves(
            (params.layers, params.logits),
            is_leaf=lambda x: isinstance(x, QuantizedWeight)):
        if isinstance(leaf, QuantizedWeight):
            nbytes += leaf.codes.nbytes + leaf.scales.nbytes
        elif hasattr(leaf, "nbytes"):
            nbytes += leaf.nbytes  # dense head / norms
    from bench import detect_specs

    _, gbps = detect_specs(jax.devices()[0].device_kind)
    roofline_ms = 1e3 * nbytes / (gbps * 1e9)
    assert ms < 6 * roofline_ms, (
        f"decode {ms:.2f} ms/step is >6x the {roofline_ms:.2f} ms HBM "
        f"roofline — quant-matmul dispatch regression?")
    assert ms > 0.77 * roofline_ms, (
        f"decode {ms:.2f} ms/step is above the physical roofline "
        f"({roofline_ms:.2f} ms) — timing is not forcing execution")


def test_turbo_matmul_on_hw(tpu_backend):
    """Turbo integer-dot planes on real hardware: the s8 x s8 -> s32 MXU
    lowering (a8) and the s8->bf16 epilogue path (a16) both execute and
    stay within the CPU-validated drift bounds vs the exact dequant oracle
    (tests/test_turbo.py) — neither path has hardware coverage anywhere
    else, and a Mosaic/XLA-TPU rejection should fail HERE with a clean
    signal, not mid-capture in a perf-matrix row."""
    import jax.numpy as jnp

    from dllama_tpu.ops.linear import dequantize_weight, quantize_weight_q40
    from dllama_tpu.ops.turbo import derive_turbo, turbo_matmul

    rng = np.random.default_rng(29)
    qw = quantize_weight_q40(
        (rng.standard_normal((512, 1024)) * 0.1).astype(np.float32))
    x = jnp.asarray(rng.standard_normal((8, 1024)), jnp.bfloat16)
    want = np.asarray(x.astype(jnp.float32)
                      @ dequantize_weight(qw, dtype=jnp.float32))
    rms = float(np.sqrt(np.mean(want ** 2)))

    for a8, bound in ((True, 8e-2), (False, 5e-2)):
        tw = derive_turbo(qw, a8=a8)
        got = np.asarray(turbo_matmul(x, tw), np.float32)
        drift = float(np.abs(got - want).max()) / max(rms, 1e-9)
        assert drift < bound, (a8, drift)


def test_macbeth_transcript_on_hw(tpu_backend):
    """The macbeth-scale determinism chain ON CHIP (VERDICT r4 next #8): the
    reference's strongest test drives 2048+ greedy steps and diffs the
    transcript (examples/macbeth.sh:5,192); here the committed
    reference-binary golden (2049-step transcript from the rebuilt C++
    dllama) replays through the real-TPU engine in exact numerics. This is
    the longest cross-implementation chain in the suite — accumulation-order
    or dispatch-shape drift anywhere in 2k steps breaks it.

    Uses --decode-chunk to keep the tunnel's per-fetch RTT off the critical
    path (chunked decode is bit-identical by construction,
    tests/test_decode_chunk.py)."""
    from pathlib import Path
    import tempfile

    import golden_assets
    from dllama_tpu.formats.quants import F32
    from dllama_tpu.runtime.engine import InferenceEngine

    variant = "llama_macbeth_f32"
    golden = golden_assets.load_golden(variant)
    if golden is None:
        pytest.skip("no macbeth golden (run tools/golden_reference.py)")
    tmp = Path(tempfile.mkdtemp(prefix="dllama-hw-macbeth-"))
    m, t, m_sha, t_sha = golden_assets.build_assets(variant, tmp)
    if m_sha != golden["m_sha256"] or t_sha != golden["t_sha256"]:
        pytest.skip("synthetic assets no longer match the golden's hashes")

    eng = InferenceEngine(
        str(m), str(t), sync_type=F32, compute_dtype="float32",
        temperature=golden["temperature"], seed=golden["sampler_seed"],
        decode_chunk=32)
    try:
        got, r = golden_assets.replay_reference_driver(eng, golden)
        want = golden["pieces"]
        assert len(r.tokens) == len(want) >= 2000
        mismatches = [i for i in range(len(want)) if got[i] != want[i]]
        assert not mismatches, (mismatches[:5], len(want))
    finally:
        eng.close()
