"""Step watchdog unit tier (runtime/watchdog.py): budget shape, EWMA
training, trip-once semantics, callbacks. The end-to-end trip through the
scheduler (fail-all, /readyz, telemetry) is chaos-driven in
test_chaos.py::test_watchdog_trips_within_budget_and_routes_to_supervision."""

import time

from dllama_tpu.runtime import telemetry as tm
from dllama_tpu.runtime.watchdog import StepWatchdog


def test_budget_trains_after_min_samples_with_floor_and_margin():
    wd = StepWatchdog("t1", margin=10.0, min_budget_s=0.5, min_samples=3,
                      enabled=True)
    assert wd.budget_s() is None
    for _ in range(3):
        wd.observe(20.0)  # 20 ms steps
    # 20ms * 10x = 0.2s, floored at 0.5s
    assert wd.budget_s() == 0.5
    for _ in range(50):
        wd.observe(200.0)  # EWMA converges toward 200 ms
    assert 1.5 < wd.budget_s() <= 2.0
    wd.close()


def test_disabled_watchdog_never_arms():
    wd = StepWatchdog("t2", margin=1.0, min_budget_s=0.01, min_samples=1,
                      enabled=False)
    for _ in range(5):
        wd.observe(1.0)
    assert wd.budget_s() is None
    with wd.guard("x"):
        pass
    assert wd._thread is None  # no monitor thread was ever needed
    wd.close()


def test_guard_trips_once_and_calls_callbacks():
    stalls = tm.registry().counter(tm.WATCHDOG_STALLS)
    s0 = stalls.total(name="t3")
    wd = StepWatchdog("t3", margin=1.0, min_budget_s=0.05, min_samples=1,
                      enabled=True)
    wd.observe(1.0)
    hits = []
    wd.on_stall.append(lambda info: hits.append(info))
    with wd.guard("slowpoke"):
        time.sleep(0.4)  # well past the 50 ms budget
    assert wd.stalled and wd.stall_count == 1
    assert len(hits) == 1 and hits[0]["label"] == "slowpoke"
    assert hits[0]["budget_s"] <= 0.06
    assert stalls.total(name="t3") == s0 + 1
    # a fast guarded step after the trip does not re-trip
    with wd.guard("fine"):
        pass
    time.sleep(0.1)
    assert wd.stall_count == 1
    wd.close()


def test_fast_guards_never_trip():
    wd = StepWatchdog("t4", margin=50.0, min_budget_s=0.2, min_samples=1,
                      enabled=True)
    wd.observe(1.0)
    for _ in range(10):
        with wd.guard("fast"):
            pass
    time.sleep(0.05)
    assert not wd.stalled and wd.stall_count == 0
    wd.close()
