"""Eval/Sync split + collective-traffic accounting (runtime.profiling) —
the reference's per-token `Eval ms / Sync ms / Sent kB / Recv kB` metrics
(src/dllama.cpp:59-67, socket counters nn-network.cpp:493-508), re-derived
the TPU way: measured collective device time from a profiler capture, and
exact payload bytes from the compiled HLO."""

import os
import shutil

import numpy as np
import pytest

from dllama_tpu.formats import tfile
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.runtime.profiling import (TrafficStats, collective_traffic,
                                          split_from_trace, union_span)

from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model

GOLDEN_XPLANE = os.path.join(os.path.dirname(__file__), "goldens",
                             "synthetic.xplane.pb")


@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("prof")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(55)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=48), rng)
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    return str(mpath), str(tpath)


# -- xplane parsing against the checked-in synthetic fixture -----------------
# (regenerate with tools/make_xplane_fixture.py; the expected numbers are
# derived in that script's docstring)


def test_union_span_basics():
    assert union_span([]) == 0
    assert union_span([(0, 10)]) == 10
    assert union_span([(0, 10), (20, 30)]) == 20          # disjoint
    assert union_span([(0, 10), (5, 15)]) == 15           # overlapping
    assert union_span([(0, 10), (2, 8)]) == 10            # nested
    assert union_span([(0, 10), (10, 20)]) == 20          # adjacent
    # unsorted input with a span swallowing everything
    assert union_span([(50, 60), (0, 100), (10, 20)]) == 100


def test_split_from_trace_synthetic_fixture(tmp_path):
    """Known-answer test: two device lanes, nested rendezvous inside an
    all-reduce (must not double-count), compute overlapping sync (counts
    once, as sync), an ExecuteHelper noise event, and a host plane that must
    be ignored — numbers from tools/make_xplane_fixture.py."""
    shutil.copy(GOLDEN_XPLANE, tmp_path / "t.xplane.pb")
    s = split_from_trace(str(tmp_path), n_steps=2)
    assert s.n_lanes == 2
    assert s.n_steps == 2
    assert s.sync_ms == pytest.approx(0.75)
    assert s.eval_ms == pytest.approx(2.0)
    assert s.sync_frac == pytest.approx(0.75 / 2.75)


def test_split_from_trace_nested_dirs_picks_newest(tmp_path):
    """The capture layout nests xplane.pb files under plugins/...; the
    recursive glob must find them."""
    d = tmp_path / "plugins" / "profile" / "run1"
    d.mkdir(parents=True)
    shutil.copy(GOLDEN_XPLANE, d / "host.xplane.pb")
    s = split_from_trace(str(tmp_path), n_steps=1)
    assert s.n_lanes == 2
    assert s.sync_ms == pytest.approx(1.5)  # n_steps=1: per-lane avg only


def test_split_from_trace_empty_dir_raises(tmp_path):
    with pytest.raises(RuntimeError, match="no xplane.pb"):
        split_from_trace(str(tmp_path), n_steps=1)


def test_split_from_trace_malformed_pb_raises(tmp_path):
    (tmp_path / "bad.xplane.pb").write_bytes(b"\xff\xff\x9c\x01garbage")
    with pytest.raises(RuntimeError, match="malformed xplane trace"):
        split_from_trace(str(tmp_path), n_steps=1)


def test_split_from_trace_no_device_lanes(tmp_path):
    """A structurally valid trace with zero device events (an idle window,
    or the profiler's occasionally-empty first session) yields the zero
    split, not an error — POST /debug/profile depends on this."""
    (tmp_path / "empty.xplane.pb").write_bytes(b"")  # valid: empty XSpace
    s = split_from_trace(str(tmp_path), n_steps=3)
    assert s.n_lanes == 0
    assert s.eval_ms == 0.0 and s.sync_ms == 0.0
    assert s.sync_frac == 0.0


def _xplane_module():
    """The lazily-loaded xplane proto module (shared with the parser so the
    test can synthesize traces in the exact format it reads)."""
    from dllama_tpu.runtime import profiling

    profiling._load_xplane(os.devnull)  # empty file = valid empty XSpace
    return profiling._xplane_pb2


def _write_trace(path, planes):
    """planes: [(plane_name, [(line_name, [(event, start_ps, dur_ps)])])]"""
    pb = _xplane_module()
    xs = pb.XSpace()
    mid = 0
    for pname, lines in planes:
        plane = xs.planes.add()
        plane.name = pname
        for lname, events in lines:
            line = plane.lines.add()
            line.name = lname
            for name, start, dur in events:
                mid += 1
                plane.event_metadata[mid].id = mid
                plane.event_metadata[mid].name = name
                ev = line.events.add()
                ev.metadata_id = mid
                ev.offset_ps = start
                ev.duration_ps = dur
    with open(path, "wb") as f:
        f.write(xs.SerializeToString())


def test_split_lane_family_priority(tmp_path):
    """The thunk-based CPU runtime puts op events on tf_XLAEigen* pools and
    scaffolding on tf_XLATfrtCpuClient* dispatch threads: only ONE family
    may count as device lanes, or the per-lane average is diluted by
    threads that aren't devices."""
    ms = 10 ** 9
    _write_trace(tmp_path / "cpu.xplane.pb", [
        ("/host:CPU", [
            ("python", [("$builtins isinstance", 0, ms)]),
            ("tf_XLAEigen/-111", [("fusion.1", 0, 3 * ms),
                                  ("all-reduce.2", 3 * ms, ms)]),
            ("tf_XLAEigen/-222", [("fusion.1", 0, 3 * ms),
                                  ("all-reduce.2", 3 * ms, ms)]),
            ("tf_XLATfrtCpuClient/-333", [
                ("TfrtCpuExecutable::ExecuteHelper", 0, 5 * ms),
                ("broadcast.9", 0, ms)]),
        ]),
    ])
    s = split_from_trace(str(tmp_path), n_steps=1)
    assert s.n_lanes == 2  # the Eigen pools only, not the client thread
    assert s.sync_ms == pytest.approx(1.0)
    assert s.eval_ms == pytest.approx(3.0)


def test_split_falls_back_to_client_lanes(tmp_path):
    """With no PjRt/Eigen lanes at all, the TfrtCpuClient dispatch threads
    are better than nothing (small thunks can execute inline there)."""
    ms = 10 ** 9
    _write_trace(tmp_path / "cpu.xplane.pb", [
        ("/host:CPU", [
            ("tf_XLATfrtCpuClient/-1", [("dot_fusion.3", 0, 2 * ms),
                                        ("psum.1", 2 * ms, 2 * ms)]),
        ]),
    ])
    s = split_from_trace(str(tmp_path), n_steps=2)
    assert s.n_lanes == 1
    assert s.sync_ms == pytest.approx(1.0)
    assert s.eval_ms == pytest.approx(1.0)


def test_collective_traffic_empty_and_collective_free_hlo():
    assert not collective_traffic("", n_devices=8)
    hlo = "%add.1 = f32[4] add(f32[4] %a, f32[4] %b)"
    tr = collective_traffic(hlo, n_devices=8)
    assert tr.n_collectives == 0 and tr.sent_kb == 0.0 and not tr


def test_capture_serializes_sessions(tmp_path):
    """capture() is THE jax.profiler.trace entry point (CLI --profile, POST
    /debug/profile, measure_eval_sync): a second concurrent session must
    fail fast with CaptureBusyError, not corrupt the active one."""
    from dllama_tpu.runtime import profiling

    assert profiling._capture_lock.acquire(timeout=1)
    try:
        with pytest.raises(profiling.CaptureBusyError):
            with profiling.capture(str(tmp_path)):
                pass
    finally:
        profiling._capture_lock.release()
    # and the lock is released on normal exit: a second session works
    with profiling.capture(str(tmp_path / "a")):
        pass
    with profiling.capture(str(tmp_path / "b")):
        pass


def test_collective_traffic_parses_hlo():
    hlo = """
  %all-reduce.3 = f32[4,1024] all-reduce(f32[4,1024] %x), replica_groups={}
  %ag = bf16[8,256] all-gather(bf16[1,256] %y), dimensions={0}
  %noise = f32[4] add(f32[4] %a, f32[4] %b)
"""
    tr = collective_traffic(hlo, n_devices=8)
    assert tr.n_collectives == 2
    # all-reduce: 2 * payload * 7/8; all-gather: 1 * payload * 7/8
    ar = 2 * (4 * 1024 * 4 / 1024) * 7 / 8
    ag = 1 * (8 * 256 * 2 / 1024) * 7 / 8
    assert tr.sent_kb == pytest.approx(ar + ag)
    assert tr.recv_kb == tr.sent_kb
    assert set(tr.by_kind) == {"all-reduce", "all-gather"}
    assert bool(tr)
    assert not TrafficStats(0.0, 0.0, 0, {})


def test_collective_traffic_async_pairs_and_consumers_count_once():
    """TPU HLO uses all-reduce-start/-done async pairs, and consumers name
    the collective as an operand — exactly one count, from the -start."""
    hlo = """
  %all-reduce-start.1 = (f32[4,1024], f32[4,1024]) all-reduce-start(f32[4,1024] %x), replica_groups={}
  %all-reduce-done.1 = f32[4,1024] all-reduce-done((f32[4,1024], f32[4,1024]) %all-reduce-start.1)
  %copy.2 = f32[4,1024] copy(f32[4,1024] %all-reduce-done.1)
"""
    tr = collective_traffic(hlo, n_devices=8)
    assert tr.n_collectives == 1
    assert tr.sent_kb == pytest.approx(2 * (4 * 1024 * 4 / 1024) * 7 / 8)


def test_collective_traffic_replica_groups_and_reduce_scatter():
    """Ring model runs over each op's own replica group, not the global
    device count; reduce-scatter moves (n-1) x its shard-sized result."""
    hlo = """
  %all-reduce.9 = f32[1024] all-reduce(f32[1024] %x), replica_groups={{0,1},{2,3},{4,5},{6,7}}
  %rs.1 = f32[128] reduce-scatter(f32[1024] %y), replica_groups=[1,8]<=[8], dimensions={0}
"""
    tr = collective_traffic(hlo, n_devices=8)
    assert tr.n_collectives == 2
    ar = 2 * (1024 * 4 / 1024) * 1 / 2          # tp-pair group: 2(n-1)/n, n=2
    rs = (128 * 4 / 1024) * 7                   # (n-1) x shard, n=8
    assert tr.by_kind["all-reduce"] == pytest.approx(ar)
    assert tr.by_kind["reduce-scatter"] == pytest.approx(rs)


def test_collective_traffic_while_body_multiplier():
    """Per-layer collectives live inside the layer-scan's while body: one HLO
    instruction, n_layers executions. loop_multiplier scales them; top-level
    collectives (the argmax epilogue) stay at 1."""
    hlo = """
%region_0.5 (arg: (s32[], f32[1,64])) -> (s32[], f32[1,64]) {
  %all-reduce.10 = f32[1,64] all-reduce(%x), replica_groups={}
}
ENTRY %main.42 (p0: f32[1,64]) -> f32[1,64] {
  %w = (s32[], f32[1,64]) while(%init), condition=%cond.2, body=%region_0.5
  %all-gather.3 = f32[1,8] all-gather(%y), replica_groups={}
}
"""
    tr1 = collective_traffic(hlo, n_devices=8, loop_multiplier=1)
    tr32 = collective_traffic(hlo, n_devices=8, loop_multiplier=32)
    ar = 2 * (64 * 4 / 1024) * 7 / 8
    ag = (8 * 4 / 1024) * 7 / 8
    assert tr1.sent_kb == pytest.approx(ar + ag)
    assert tr32.sent_kb == pytest.approx(32 * ar + ag)
    assert tr32.n_collectives == 33


def test_single_device_engine_sync_is_zero(model_files):
    """tp=1: the compiled decode program has no collectives, so the split is
    (eval, 0) by construction and no profiler trace is taken."""
    e = InferenceEngine(model_files[0], model_files[1], temperature=0.0,
                        seed=7, tp=1, profile_split=True)
    r = e.generate("hello world", 4, stop_on_eos=False)
    assert e.split is not None
    assert e.split.sync_ms == 0.0
    assert e.traffic is not None and not e.traffic
    pred = [s for s in r.steps if s.kind == "pred"]
    assert pred and all(s.sync_ms == 0.0 for s in pred)
    assert all(s.eval_only_ms == s.ms for s in pred)
    # no collectives in ANY program: the prefill split is zero too
    assert e.split_prefill is not None and e.split_prefill.sync_ms == 0.0
    assert all(s.sync_ms == 0.0 for s in r.steps if s.kind == "eval")


def test_tp_engine_measures_collective_split(model_files):
    """tp=2 on the virtual CPU mesh: the compiled program carries psum
    collectives — traffic accounting sees them, and the measured split
    attributes a nonzero share of device time to sync."""
    e = InferenceEngine(model_files[0], model_files[1], temperature=0.0,
                        seed=7, tp=2, profile_split=True)
    r = e.generate("hello world", 4, stop_on_eos=False)
    assert e.traffic is not None and e.traffic.n_collectives > 0
    assert e.traffic.sent_kb > 0
    assert e.split is not None and e.split.n_lanes >= 1
    assert e.split.sync_ms > 0.0
    assert 0.0 < e.split.sync_frac < 1.0
    pred = [s for s in r.steps if s.kind == "pred"]
    assert pred
    for s in pred:
        assert s.sync_ms is not None and 0.0 < s.sync_ms < s.ms
        assert s.eval_only_ms == pytest.approx(s.ms - s.sync_ms)
    # eval steps carry the PREFILL program's own fraction (per-phase split,
    # VERDICT r4 weak #5) — deterministic for this fixture (a bucket always
    # fits the remaining logical tail)
    assert e.split_prefill is not None and e.split_prefill.n_steps > 0
    ev = [s for s in r.steps if s.kind == "eval"]
    assert ev and all(s.sync_ms is not None and 0.0 <= s.sync_ms < s.ms
                      for s in ev)


def test_generation_unperturbed_by_split_measurement(model_files):
    """The scratch profiling dispatches must not change the transcript."""
    e1 = InferenceEngine(model_files[0], model_files[1], temperature=0.0,
                         seed=7, tp=2, profile_split=True)
    r1 = e1.generate("hello world", 6, stop_on_eos=False)
    e2 = InferenceEngine(model_files[0], model_files[1], temperature=0.0,
                         seed=7, tp=2)
    r2 = e2.generate("hello world", 6, stop_on_eos=False)
    assert r1.tokens == r2.tokens
