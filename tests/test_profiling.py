"""Eval/Sync split + collective-traffic accounting (runtime.profiling) —
the reference's per-token `Eval ms / Sync ms / Sent kB / Recv kB` metrics
(src/dllama.cpp:59-67, socket counters nn-network.cpp:493-508), re-derived
the TPU way: measured collective device time from a profiler capture, and
exact payload bytes from the compiled HLO."""

import numpy as np
import pytest

from dllama_tpu.formats import tfile
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.runtime.profiling import TrafficStats, collective_traffic

from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model


@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("prof")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(55)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=48), rng)
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    return str(mpath), str(tpath)


def test_collective_traffic_parses_hlo():
    hlo = """
  %all-reduce.3 = f32[4,1024] all-reduce(f32[4,1024] %x), replica_groups={}
  %ag = bf16[8,256] all-gather(bf16[1,256] %y), dimensions={0}
  %noise = f32[4] add(f32[4] %a, f32[4] %b)
"""
    tr = collective_traffic(hlo, n_devices=8)
    assert tr.n_collectives == 2
    # all-reduce: 2 * payload * 7/8; all-gather: 1 * payload * 7/8
    ar = 2 * (4 * 1024 * 4 / 1024) * 7 / 8
    ag = 1 * (8 * 256 * 2 / 1024) * 7 / 8
    assert tr.sent_kb == pytest.approx(ar + ag)
    assert tr.recv_kb == tr.sent_kb
    assert set(tr.by_kind) == {"all-reduce", "all-gather"}
    assert bool(tr)
    assert not TrafficStats(0.0, 0.0, 0, {})


def test_collective_traffic_async_pairs_and_consumers_count_once():
    """TPU HLO uses all-reduce-start/-done async pairs, and consumers name
    the collective as an operand — exactly one count, from the -start."""
    hlo = """
  %all-reduce-start.1 = (f32[4,1024], f32[4,1024]) all-reduce-start(f32[4,1024] %x), replica_groups={}
  %all-reduce-done.1 = f32[4,1024] all-reduce-done((f32[4,1024], f32[4,1024]) %all-reduce-start.1)
  %copy.2 = f32[4,1024] copy(f32[4,1024] %all-reduce-done.1)
"""
    tr = collective_traffic(hlo, n_devices=8)
    assert tr.n_collectives == 1
    assert tr.sent_kb == pytest.approx(2 * (4 * 1024 * 4 / 1024) * 7 / 8)


def test_collective_traffic_replica_groups_and_reduce_scatter():
    """Ring model runs over each op's own replica group, not the global
    device count; reduce-scatter moves (n-1) x its shard-sized result."""
    hlo = """
  %all-reduce.9 = f32[1024] all-reduce(f32[1024] %x), replica_groups={{0,1},{2,3},{4,5},{6,7}}
  %rs.1 = f32[128] reduce-scatter(f32[1024] %y), replica_groups=[1,8]<=[8], dimensions={0}
"""
    tr = collective_traffic(hlo, n_devices=8)
    assert tr.n_collectives == 2
    ar = 2 * (1024 * 4 / 1024) * 1 / 2          # tp-pair group: 2(n-1)/n, n=2
    rs = (128 * 4 / 1024) * 7                   # (n-1) x shard, n=8
    assert tr.by_kind["all-reduce"] == pytest.approx(ar)
    assert tr.by_kind["reduce-scatter"] == pytest.approx(rs)


def test_collective_traffic_while_body_multiplier():
    """Per-layer collectives live inside the layer-scan's while body: one HLO
    instruction, n_layers executions. loop_multiplier scales them; top-level
    collectives (the argmax epilogue) stay at 1."""
    hlo = """
%region_0.5 (arg: (s32[], f32[1,64])) -> (s32[], f32[1,64]) {
  %all-reduce.10 = f32[1,64] all-reduce(%x), replica_groups={}
}
ENTRY %main.42 (p0: f32[1,64]) -> f32[1,64] {
  %w = (s32[], f32[1,64]) while(%init), condition=%cond.2, body=%region_0.5
  %all-gather.3 = f32[1,8] all-gather(%y), replica_groups={}
}
"""
    tr1 = collective_traffic(hlo, n_devices=8, loop_multiplier=1)
    tr32 = collective_traffic(hlo, n_devices=8, loop_multiplier=32)
    ar = 2 * (64 * 4 / 1024) * 7 / 8
    ag = (8 * 4 / 1024) * 7 / 8
    assert tr1.sent_kb == pytest.approx(ar + ag)
    assert tr32.sent_kb == pytest.approx(32 * ar + ag)
    assert tr32.n_collectives == 33


def test_single_device_engine_sync_is_zero(model_files):
    """tp=1: the compiled decode program has no collectives, so the split is
    (eval, 0) by construction and no profiler trace is taken."""
    e = InferenceEngine(model_files[0], model_files[1], temperature=0.0,
                        seed=7, tp=1, profile_split=True)
    r = e.generate("hello world", 4, stop_on_eos=False)
    assert e.split is not None
    assert e.split.sync_ms == 0.0
    assert e.traffic is not None and not e.traffic
    pred = [s for s in r.steps if s.kind == "pred"]
    assert pred and all(s.sync_ms == 0.0 for s in pred)
    assert all(s.eval_only_ms == s.ms for s in pred)
    # no collectives in ANY program: the prefill split is zero too
    assert e.split_prefill is not None and e.split_prefill.sync_ms == 0.0
    assert all(s.sync_ms == 0.0 for s in r.steps if s.kind == "eval")


def test_tp_engine_measures_collective_split(model_files):
    """tp=2 on the virtual CPU mesh: the compiled program carries psum
    collectives — traffic accounting sees them, and the measured split
    attributes a nonzero share of device time to sync."""
    e = InferenceEngine(model_files[0], model_files[1], temperature=0.0,
                        seed=7, tp=2, profile_split=True)
    r = e.generate("hello world", 4, stop_on_eos=False)
    assert e.traffic is not None and e.traffic.n_collectives > 0
    assert e.traffic.sent_kb > 0
    assert e.split is not None and e.split.n_lanes >= 1
    assert e.split.sync_ms > 0.0
    assert 0.0 < e.split.sync_frac < 1.0
    pred = [s for s in r.steps if s.kind == "pred"]
    assert pred
    for s in pred:
        assert s.sync_ms is not None and 0.0 < s.sync_ms < s.ms
        assert s.eval_only_ms == pytest.approx(s.ms - s.sync_ms)
    # eval steps carry the PREFILL program's own fraction (per-phase split,
    # VERDICT r4 weak #5) — deterministic for this fixture (a bucket always
    # fits the remaining logical tail)
    assert e.split_prefill is not None and e.split_prefill.n_steps > 0
    ev = [s for s in r.steps if s.kind == "eval"]
    assert ev and all(s.sync_ms is not None and 0.0 <= s.sync_ms < s.ms
                      for s in ev)


def test_generation_unperturbed_by_split_measurement(model_files):
    """The scratch profiling dispatches must not change the transcript."""
    e1 = InferenceEngine(model_files[0], model_files[1], temperature=0.0,
                         seed=7, tp=2, profile_split=True)
    r1 = e1.generate("hello world", 6, stop_on_eos=False)
    e2 = InferenceEngine(model_files[0], model_files[1], temperature=0.0,
                         seed=7, tp=2)
    r2 = e2.generate("hello world", 6, stop_on_eos=False)
    assert r1.tokens == r2.tokens
