"""Pre-staging HBM budget guard (runtime.hbm) — the reference prints its
required-memory estimate before loading (nn-core.cpp:162-176); here a misfit
must refuse cleanly instead of OOM-wedging the TPU backend (VERDICT r3 #7)."""

import pytest

from dllama_tpu.formats import mfile
from dllama_tpu.models import ModelConfig
from dllama_tpu.runtime.hbm import (
    check_budget,
    device_memory_bytes,
    estimate_device_bytes,
    matmul_weight_count,
)


def _cfg(**kw):
    base = dict(
        arch=mfile.ArchType.LLAMA, dim=4096, hidden_dim=14336, n_layers=32,
        n_heads=32, n_kv_heads=8, head_dim=128, vocab_size=128256,
        seq_len=1024, norm_epsilon=1e-5, rope_theta=500000.0,
        rope_type=mfile.RopeType.LLAMA)
    base.update(kw)
    return ModelConfig(**base)


def test_8b_q40_fits_16gb_chip():
    """The north-star config (8B Q40, one v5e 16 GB chip) must fit by
    construction — the guard exists to stop misfits, not the headline run."""
    est = estimate_device_bytes(_cfg(), weight_repr="q40", kv_dtype_bytes=2)
    assert est["need_per_device"] < 16 * 1024 ** 3
    # and the estimate is in the right ballpark: ~8B params * 1.125 B
    assert 7e9 < matmul_weight_count(_cfg()) < 9e9
    assert est["weights_bytes"] > 8e9


def test_8b_f32_refuses_16gb(monkeypatch):
    monkeypatch.setenv("DLLAMA_HBM_BYTES", str(16 * 1024 ** 3))
    est = estimate_device_bytes(_cfg(), weight_repr="f32", kv_dtype_bytes=2)
    with pytest.raises(RuntimeError, match="refusing to stage"):
        check_budget(est["need_per_device"], "test model")


def test_skip_env_bypasses(monkeypatch):
    monkeypatch.setenv("DLLAMA_HBM_BYTES", str(16 * 1024 ** 3))
    monkeypatch.setenv("DLLAMA_SKIP_HBM_CHECK", "1")
    est = estimate_device_bytes(_cfg(), weight_repr="f32", kv_dtype_bytes=2)
    assert check_budget(est["need_per_device"], "test model") is None


def test_sharding_and_offload_shrink_need():
    c = _cfg()
    full = estimate_device_bytes(c, weight_repr="q40", kv_dtype_bytes=2)
    tp8 = estimate_device_bytes(c, weight_repr="q40", kv_dtype_bytes=2,
                                n_shards=8)
    off = estimate_device_bytes(c, weight_repr="q40", kv_dtype_bytes=2,
                                offload=True)
    assert tp8["need_per_device"] < full["need_per_device"] / 4
    assert off["need_per_device"] < full["need_per_device"] / 2


def test_70b_single_chip_refuses(monkeypatch):
    monkeypatch.setenv("DLLAMA_HBM_BYTES", str(16 * 1024 ** 3))
    c = _cfg(dim=8192, hidden_dim=28672, n_layers=80, n_heads=64)
    est = estimate_device_bytes(c, weight_repr="q40", kv_dtype_bytes=2)
    with pytest.raises(RuntimeError):
        check_budget(est["need_per_device"], "70B")
    # but offload over 8 shards fits
    est8 = estimate_device_bytes(c, weight_repr="q40", kv_dtype_bytes=2,
                                 n_shards=8, offload=True)
    assert check_budget(est8["need_per_device"], "70B offload") is not None


def test_device_memory_env_override(monkeypatch):
    monkeypatch.setenv("DLLAMA_HBM_BYTES", "123456")
    assert device_memory_bytes() == 123456


def test_engine_records_estimate(tmp_path):
    import numpy as np
    from dllama_tpu.formats import tfile
    from dllama_tpu.runtime.engine import InferenceEngine
    from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model

    mpath, tpath = tmp_path / "m.m", tmp_path / "t.t"
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=48),
                     np.random.default_rng(1))
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    e = InferenceEngine(str(mpath), str(tpath))
    assert e.hbm_estimate["need_per_device"] > 0


# -- HBM admission guard (ISSUE 4) --------------------------------------------


def test_fit_batch_slots_degrades_in_dp_steps(monkeypatch):
    from dllama_tpu.runtime.hbm import fit_batch_slots

    c = _cfg(dim=512, hidden_dim=1024, n_layers=4, vocab_size=2048,
             n_heads=8, n_kv_heads=4, head_dim=64, seq_len=512)
    # dp=2: n slots -> batch n/2+1, so 8->b5, 6->b4, 4->b3. A limit
    # between need(b3) and need(b4) fits only the 4-slot pool.
    mid = (estimate_device_bytes(c, weight_repr="q40", kv_dtype_bytes=4,
                                 batch=3)["need_per_device"]
           + estimate_device_bytes(c, weight_repr="q40", kv_dtype_bytes=4,
                                   batch=4)["need_per_device"]) // 2
    monkeypatch.setenv("DLLAMA_HBM_BYTES", str(mid))
    n, est = fit_batch_slots(c, 8, weight_repr="q40", kv_dtype_bytes=4,
                             dp=2)
    assert n == 4 and n % 2 == 0
    assert est["need_per_device"] <= mid
    # nothing fits -> 0 (caller refuses)
    monkeypatch.setenv("DLLAMA_HBM_BYTES", "1000")
    n, _ = fit_batch_slots(c, 8, weight_repr="q40", kv_dtype_bytes=4, dp=2)
    assert n == 0
    # unknown limit / explicit skip -> untouched
    monkeypatch.delenv("DLLAMA_HBM_BYTES")
    n, _ = fit_batch_slots(c, 8, weight_repr="q40", kv_dtype_bytes=4, dp=2)
    assert n == 8
    monkeypatch.setenv("DLLAMA_HBM_BYTES", "1000")
    monkeypatch.setenv("DLLAMA_SKIP_HBM_CHECK", "1")
    n, _ = fit_batch_slots(c, 8, weight_repr="q40", kv_dtype_bytes=4, dp=2)
    assert n == 8


def test_admission_check_uses_measured_bytes_and_uncompiled_extra(monkeypatch):
    from dllama_tpu.runtime.hbm import admission_check

    monkeypatch.setenv("DLLAMA_HBM_BYTES", str(1_000_000))
    ok, _ = admission_check(need_bytes=400_000, measured_bytes={},
                            extra_bytes=0, what="x")
    assert ok
    # measured evidence RAISES the estimate past the limit
    ok, reason = admission_check(need_bytes=400_000,
                                 measured_bytes={"forward": 1_200_000},
                                 extra_bytes=0, what="x")
    assert not ok and "measured" in reason
    # uncompiled-program workspace pushes a borderline admission over
    ok, reason = admission_check(need_bytes=900_000, measured_bytes={},
                                 extra_bytes=200_000, what="x")
    assert not ok and "uncompiled" in reason
    # the guard stands down when the limit is unknown
    monkeypatch.delenv("DLLAMA_HBM_BYTES")
    ok, _ = admission_check(need_bytes=10**15, measured_bytes={},
                            extra_bytes=0, what="x")
    assert ok


def test_estimate_prefill_temp_bytes_scales_with_tokens():
    from dllama_tpu.runtime.hbm import estimate_prefill_temp_bytes

    c = _cfg()
    small = estimate_prefill_temp_bytes(c, 32)
    big = estimate_prefill_temp_bytes(c, 256)
    assert big == small * 8 and small > 0
