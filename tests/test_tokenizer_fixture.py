"""Production-shape BPE fixture tests (VERDICT r3 missing #3).

The reference gates tokenizer goldens on a real downloaded Llama-3 tokenizer
(src/tokenizer-test.cpp:44-120). Zero-egress here, so the committed fixture
(tests/goldens/fixture_bpe.t, built by tools/make_tokenizer_fixture.py) is a
byte-level BPE trained deterministically on an embedded multilingual corpus:
2k+ learned merges with genuine rank-ordered scores, hundreds of multi-byte
(non-ASCII) pieces, laid out exactly as convert/tokenizers.py lays out real
HF vocabs. These tests pin encode goldens, UTF-8 round-trips, the special
-token prefix scan, and native-vs-Python merge equivalence at production
vocab size — the synthetic ``wNNN`` vocabs elsewhere can't exercise any of
that realistically.
"""

import json
import os

import pytest

from dllama_tpu.tokenizer.bpe import Tokenizer

GOLDENS_DIR = os.path.join(os.path.dirname(__file__), "goldens")
T_PATH = os.path.join(GOLDENS_DIR, "fixture_bpe.t")
J_PATH = os.path.join(GOLDENS_DIR, "fixture_bpe.json")


@pytest.fixture(scope="module")
def tok() -> Tokenizer:
    return Tokenizer.load(T_PATH)


@pytest.fixture(scope="module")
def goldens() -> dict:
    with open(J_PATH) as f:
        return json.load(f)


def test_fixture_is_production_shape(tok, goldens):
    st = goldens["stats"]
    assert st["n_merges"] >= 2000
    assert st["multi_byte_merges"] >= 300
    assert tok.regular_vocab_size == 256 + st["n_merges"]
    # merge ranks are genuine: scores strictly decrease with id (the
    # convert/tokenizers.py -id convention for byte-level BPE vocabs)
    assert all(tok.scores[i] > tok.scores[i + 1]
               for i in range(tok.regular_vocab_size - 1))
    # real multi-byte UTF-8 pieces exist (whole characters merged)
    assert any(len(tok.vocab[i]) >= 3 and tok.vocab[i][0] >= 0xE0
               for i in range(256, tok.regular_vocab_size))


def test_committed_encode_goldens(tok, goldens):
    for g in goldens["goldens"]:
        assert tok.encode(g["text"], is_start=False) == g["ids"], g["text"]


def test_multilingual_roundtrip(tok):
    texts = [
        "The tokenizer handles English prose without trouble.",
        "Čeština, polszczyzna, français, español, português — all byte-level.",
        "Смешанный текст: русский + English + 中文 in one line",
        "数字 123 と記号 !@# を含む日本語テキスト",
        "🎉🦊 emoji sequences 👩‍💻 with ZWJ",
        "tab\tand\nnewline and  double  spaces",
        "".join(chr(c) for c in range(0x20, 0x7F)),  # full printable ASCII
    ]
    for s in texts:
        ids = tok.encode(s, is_start=False)
        tok.reset_decoder()
        rt = "".join(p for t in ids if (p := tok.decode(t)) is not None)
        assert rt == s, s
        # the trained vocab actually compresses (merges engaged): fewer
        # tokens than bytes for natural text
        if s.isascii() and len(s) > 40:
            assert len(ids) < len(s.encode())


def test_special_token_prefix_scan(tok):
    s = "<|start_header_id|>user<|end_header_id|>\n\nhello<|eot_id|>"
    ids = tok.encode(s, is_start=False)
    names = [tok.vocab[i] for i in ids]
    assert b"<|start_header_id|>" in names
    assert b"<|end_header_id|>" in names
    assert b"<|eot_id|>" in names
    assert tok.is_eos(ids[-1])
    # a '<' that does NOT start a special must fall through to byte merges
    ids2 = tok.encode("< |not_special|>", is_start=False)
    assert all(i < tok.regular_vocab_size for i in ids2)


def test_native_matches_python_on_fixture(tok):
    """The C++ merge engine and the Python heap merger must agree token-for
    -token on a production-size vocab over long multilingual text (the
    synthetic-vocab equivalence suite can't see rank-ordering subtleties)."""
    from dllama_tpu import native

    if not native.available():
        pytest.skip("native library unavailable")
    corpus = ("The quick brown fox. Résumé café déjà. Быстрая лиса. "
              "素早い狐が犬を飛び越える。🎉 emoji! def f(x):\n  return x\n") * 40
    got = tok.encode(corpus, is_start=False)

    # force the pure-Python path for the oracle
    tok_py = Tokenizer.load(T_PATH)
    tok_py._bpe_native = False
    want = tok_py.encode(corpus, is_start=False)
    assert got == want
    assert len(got) < len(corpus.encode())  # merges actually engaged


def test_streaming_decoder_splits_multibyte(tok):
    """Multi-byte pieces may split mid-character across tokens: the
    streaming decoder must buffer and emit whole characters only."""
    s = "価格は42€で、犬🐕と狐🦊がいます"
    ids = tok.encode(s, is_start=False)
    tok.reset_decoder()
    out = []
    for t in ids:
        p = tok.decode(t)
        if p is not None:
            assert not p.endswith("�") or "�" in s
            out.append(p)
    assert "".join(out) == s
