"""Continuous batched serving (runtime/serving.py).

THE correctness property: a request's output is byte-identical to running it
alone on the single-sequence engine — batch composition, admission order, and
slot reuse must be invisible. This extends the node-count-invariance test
philosophy (SURVEY.md §4) to the serving axis the reference doesn't have."""

import numpy as np
import pytest

from dllama_tpu.formats import tfile
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.runtime.serving import BatchedGenerator, BatchScheduler, Request

from helpers import (byte_vocab_tokenizer, require_pinned_host,
                     tiny_header_params, write_tiny_model)


PATHS = {}


@pytest.fixture(scope="module")
def engine(tmp_path_factory):
    d = tmp_path_factory.mktemp("serving")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(41)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96), rng)
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    PATHS["m"], PATHS["t"] = str(mpath), str(tpath)
    return InferenceEngine(str(mpath), str(tpath), tp=1)


def solo(temperature=0.0, seed=7):
    """Fresh single-sequence engine on the same files — the oracle."""
    return InferenceEngine(PATHS["m"], PATHS["t"], tp=1,
                           temperature=temperature, seed=seed)


def test_batched_matches_solo_mixed_greedy_and_sampled(engine):
    """Four concurrent requests — different prompts, lengths, greedy and
    sampled, different seeds — each must equal its solo run."""
    prompts = ["hello world", "hello", " world hello world", "hell"]
    specs = [dict(temperature=0.0, seed=1), dict(temperature=0.8, seed=2),
             dict(temperature=0.0, seed=3), dict(temperature=1.2, seed=4)]
    n = 10

    want = []
    for p, s in zip(prompts, specs):
        e = solo(temperature=s["temperature"], seed=s["seed"])
        want.append(e.generate(p, n, stop_on_eos=False).tokens)

    gen = BatchedGenerator(engine, n_slots=4)
    reqs = []
    for i, (p, s) in enumerate(zip(prompts, specs)):
        ids = engine.tokenizer.encode(p, is_start=True)
        r = Request(rid=i, prompt_ids=ids, max_tokens=n, stop_on_eos=False,
                    temperature=s["temperature"], topp=0.9, seed=s["seed"])
        gen.admit(r, i)
        reqs.append(r)
    while gen.n_active:
        gen.step()
    for r, w in zip(reqs, want):
        assert r.tokens == w, r.rid


def test_batched_slot_reuse_and_staggered_admission(engine):
    """Requests admitted mid-flight into freed slots must still match solo
    runs (stale KV from the previous occupant must be invisible)."""
    n_long, n_short = 12, 4
    want_long = solo().generate("hello world", n_long, stop_on_eos=False).tokens
    want_a = solo(temperature=0.9, seed=9).generate(
        "hello", n_short, stop_on_eos=False).tokens
    want_b = solo(temperature=0.9, seed=9).generate(
        " world", n_short, stop_on_eos=False).tokens

    gen = BatchedGenerator(engine, n_slots=2)
    enc = lambda p: engine.tokenizer.encode(p, is_start=True)
    r_long = Request(rid=0, prompt_ids=enc("hello world"),
                     max_tokens=n_long, stop_on_eos=False)
    r_a = Request(rid=1, prompt_ids=enc("hello"), max_tokens=n_short,
                  stop_on_eos=False, temperature=0.9, seed=9)
    gen.admit(r_long, 0)
    gen.admit(r_a, 1)
    while not r_a.done.is_set():
        gen.step()
    # slot 1 freed mid-run of r_long: admit r_b into it
    r_b = Request(rid=2, prompt_ids=enc(" world"), max_tokens=n_short,
                  stop_on_eos=False, temperature=0.9, seed=9)
    gen.admit(r_b, 1)
    while gen.n_active:
        gen.step()
    assert r_long.tokens == want_long
    assert r_a.tokens == want_a
    assert r_b.tokens == want_b


def test_scheduler_queues_beyond_slots(engine):
    """6 requests through 2 slots: all complete, each equals its solo run."""
    sched = BatchScheduler(engine, n_slots=2)
    try:
        prompts = ["hello", " world", "hello world", "hell", "he", " w"]
        n = 5
        want = [solo().generate(p, n, stop_on_eos=False).tokens
                for p in prompts]
        reqs = [sched.submit(engine.tokenizer.encode(p, is_start=True), n,
                             stop_on_eos=False) for p in prompts]
        for r, w in zip(reqs, want):
            assert r.done.wait(timeout=300)
            assert r.error is None
            assert r.tokens == w
    finally:
        sched.close()


def test_scheduler_rejects_oversized_prompt(engine):
    sched = BatchScheduler(engine, n_slots=2)
    try:
        r = sched.submit(list(range(1, 200)), 4)  # > seq_len 96
        assert r.done.wait(timeout=60)
        assert r.error is not None and "seq_len" in r.error
    finally:
        sched.close()


def test_streaming_decoders_are_independent(engine):
    """Interleaved slots must not corrupt each other's UTF-8 streaming."""
    gen = BatchedGenerator(engine, n_slots=2)
    pieces: dict[int, list] = {0: [], 1: []}
    enc = lambda p: engine.tokenizer.encode(p, is_start=True)
    for rid, prompt in ((0, "hello"), (1, " world")):
        r = Request(rid=rid, prompt_ids=enc(prompt), max_tokens=6,
                    stop_on_eos=False,
                    on_token=lambda t, p, rid=rid: pieces[rid].append(p))
        gen.admit(r, rid)
        if rid == 0:
            gen.step()  # stagger so decoders interleave
    while gen.n_active:
        gen.step()
    # every emitted piece decodes through the request's own stream
    for rid in (0, 1):
        assert len([p for p in pieces[rid] if p is not None]) > 0


def test_cancel_retires_slot_next_step(engine):
    """Client-side cancel (stop-string matched in the text layer) frees the
    slot at the next step boundary while other slots continue."""
    gen = BatchedGenerator(engine, n_slots=2)
    enc = lambda p: engine.tokenizer.encode(p, is_start=True)
    r0 = Request(rid=0, prompt_ids=enc("hello"), max_tokens=50,
                 stop_on_eos=False)
    r1 = Request(rid=1, prompt_ids=enc(" world"), max_tokens=6,
                 stop_on_eos=False)
    gen.admit(r0, 0)
    gen.admit(r1, 1)
    gen.step()
    r0.cancel.set()
    gen.step()
    assert r0.done.is_set() and len(r0.tokens) == 1  # no token after cancel
    while gen.n_active:
        gen.step()
    assert len(r1.tokens) == 6  # neighbor unaffected


def test_incremental_prefill_interleaves_with_decode(tmp_path_factory):
    """A long prompt admitted mid-flight must NOT stall active decodes: with
    chunked admission, the active slot emits tokens BETWEEN the newcomer's
    prefill chunks — and both outputs still match their solo runs."""
    d = tmp_path_factory.mktemp("serving_inc")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(41)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96), rng)
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    # tiny n_batches: the long prompt needs many prefill chunks
    eng = InferenceEngine(str(mpath), str(tpath), tp=1, n_batches=4)
    long_ids = [int(x) for x in np.random.default_rng(3).integers(1, 200, 40)]

    solo_a = InferenceEngine(str(mpath), str(tpath), tp=1, n_batches=4)
    want_a = solo_a.generate("hello world", 16, stop_on_eos=False).tokens
    solo_b = InferenceEngine(str(mpath), str(tpath), tp=1, n_batches=4)
    want_b = solo_b.generate(long_ids, 4, stop_on_eos=False).tokens

    gen = BatchedGenerator(eng, n_slots=2)
    r_a = Request(rid=0, prompt_ids=eng.tokenizer.encode("hello world",
                                                         is_start=True),
                  max_tokens=16, stop_on_eos=False)
    gen.admit(r_a, 0)
    gen.step()  # r_a decoding
    a_before = len(r_a.tokens)

    r_b = Request(rid=1, prompt_ids=long_ids, max_tokens=4, stop_on_eos=False)
    adm = gen.begin_admit(r_b, 1)
    interleaved = 0
    while not gen.continue_admit(adm):
        gen.step()  # active slot keeps decoding between prefill chunks
        interleaved += 1
    assert interleaved >= 5  # 39 prompt tokens / 4 per chunk
    assert len(r_a.tokens) > a_before  # r_a made progress during admission
    while gen.n_active:
        gen.step()
    assert r_a.tokens == want_a
    assert r_b.tokens == want_b


def test_batched_under_tp_matches_solo(tmp_path_factory):
    """Batched serving composes with tensor parallelism: tp=4 engine, mixed
    batch, each request equals its solo tp=4 run."""
    d = tmp_path_factory.mktemp("serving_tp")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(41)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96), rng)
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    eng = InferenceEngine(str(mpath), str(tpath), tp=4)

    s1 = InferenceEngine(str(mpath), str(tpath), tp=4)
    want_a = s1.generate("hello world", 8, stop_on_eos=False).tokens
    s2 = InferenceEngine(str(mpath), str(tpath), tp=4, temperature=0.8, seed=6)
    want_b = s2.generate("hello", 8, stop_on_eos=False).tokens

    gen = BatchedGenerator(eng, n_slots=2)
    enc = lambda p: eng.tokenizer.encode(p, is_start=True)
    r_a = Request(rid=0, prompt_ids=enc("hello world"), max_tokens=8,
                  stop_on_eos=False)
    r_b = Request(rid=1, prompt_ids=enc("hello"), max_tokens=8,
                  stop_on_eos=False, temperature=0.8, seed=6)
    gen.admit(r_a, 0)
    gen.admit(r_b, 1)
    while gen.n_active:
        gen.step()
    assert r_a.tokens == want_a
    assert r_b.tokens == want_b


def test_batched_speculative_matches_solo_mixed(tmp_path_factory):
    """Speculative batched serving: greedy rows ride verify runs, sampled
    rows keep their one-token/one-coin stream — every request must still be
    byte-identical to its solo (non-spec) run, and the greedy repetitive
    request must show multi-token acceptance (fewer steps than tokens)."""
    d = tmp_path_factory.mktemp("spec_serving")
    mpath, tpath = d / "m.m", d / "t.t"
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96),
                     np.random.default_rng(41))
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    eng_spec = InferenceEngine(str(mpath), str(tpath), tp=1, spec_lookup=3)

    prompts = ["hello hello hello", "hello", " world hello world", "hell"]
    specs = [dict(temperature=0.0, seed=1), dict(temperature=0.8, seed=2),
             dict(temperature=0.0, seed=3), dict(temperature=1.2, seed=4)]
    n = 12
    want = []
    for p, s in zip(prompts, specs):
        e = InferenceEngine(str(mpath), str(tpath), tp=1,
                            temperature=s["temperature"], seed=s["seed"])
        want.append(e.generate(p, n, stop_on_eos=False).tokens)
        e.close()

    gen = BatchedGenerator(eng_spec, n_slots=4)
    assert gen.spec == 3
    reqs = []
    for i, (p, s) in enumerate(zip(prompts, specs)):
        ids = eng_spec.tokenizer.encode(p, is_start=True)
        r = Request(rid=i, prompt_ids=ids, max_tokens=n, stop_on_eos=False,
                    temperature=s["temperature"], topp=0.9, seed=s["seed"])
        gen.admit(r, i)
        reqs.append(r)
    steps = steps_r0 = 0
    while gen.n_active:
        gen.step()
        steps += 1
        if not reqs[0].done.is_set():
            steps_r0 = steps
    for r, w in zip(reqs, want):
        assert r.tokens == w, r.rid
    # the greedy repetitive request (slot 0) finished in fewer dispatches
    # than tokens — real multi-token acceptance (sampled rows stay 1/step)
    assert steps_r0 + 1 < n, (
        f"no acceptance on the greedy row: {steps_r0 + 1} steps for {n}")
    eng_spec.close()


def test_batched_speculative_under_tp_matches_solo(tmp_path_factory):
    """Speculative batched serving under tensor parallelism: the ragged
    verify dispatch runs inside the tp plan; outputs equal solo tp runs."""
    d = tmp_path_factory.mktemp("spec_tp")
    mpath, tpath = d / "m.m", d / "t.t"
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96),
                     np.random.default_rng(41))
    tfile.write_tfile(tpath, byte_vocab_tokenizer())

    s1 = InferenceEngine(str(mpath), str(tpath), tp=2)
    want_a = s1.generate("hello hello hello", 10, stop_on_eos=False).tokens
    s1.close()
    s2 = InferenceEngine(str(mpath), str(tpath), tp=2, temperature=0.8, seed=6)
    want_b = s2.generate("hello", 10, stop_on_eos=False).tokens
    s2.close()

    eng = InferenceEngine(str(mpath), str(tpath), tp=2, spec_lookup=3)
    gen = BatchedGenerator(eng, n_slots=2)
    enc = lambda p: eng.tokenizer.encode(p, is_start=True)
    r_a = Request(rid=0, prompt_ids=enc("hello hello hello"), max_tokens=10,
                  stop_on_eos=False)
    r_b = Request(rid=1, prompt_ids=enc("hello"), max_tokens=10,
                  stop_on_eos=False, temperature=0.8, seed=6)
    gen.admit(r_a, 0)
    gen.admit(r_b, 1)
    while gen.n_active:
        gen.step()
    assert r_a.tokens == want_a
    assert r_b.tokens == want_b
    eng.close()


def test_batched_under_dp_tp_matches_solo(tmp_path_factory):
    """Batched serving with the slot pool SHARDED over a dp axis (dp=2 ×
    tp=2): every request equals its solo unsharded run — mesh invariance
    extended to the serving batch axis."""
    d = tmp_path_factory.mktemp("serving_dp")
    mpath, tpath = d / "m.m", d / "t.t"
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96),
                     np.random.default_rng(41))
    tfile.write_tfile(tpath, byte_vocab_tokenizer())

    want = []
    for p, s in [("hello world", dict(temperature=0.0, seed=1)),
                 ("hello", dict(temperature=0.8, seed=2)),
                 (" world hello", dict(temperature=0.0, seed=3)),
                 ("hell", dict(temperature=1.2, seed=4))]:
        e = InferenceEngine(str(mpath), str(tpath), tp=1, **s)
        want.append(e.generate(p, 8, stop_on_eos=False).tokens)
        e.close()

    eng = InferenceEngine(str(mpath), str(tpath), dp=2, tp=2)
    gen = BatchedGenerator(eng, n_slots=4)
    reqs = []
    for i, (p, s) in enumerate([
            ("hello world", dict(temperature=0.0, seed=1)),
            ("hello", dict(temperature=0.8, seed=2)),
            (" world hello", dict(temperature=0.0, seed=3)),
            ("hell", dict(temperature=1.2, seed=4))]):
        ids = eng.tokenizer.encode(p, is_start=True)
        r = Request(rid=i, prompt_ids=ids, max_tokens=8, stop_on_eos=False,
                    topp=0.9, **s)
        gen.admit(r, i)
        reqs.append(r)
    while gen.n_active:
        gen.step()
    for r, w in zip(reqs, want):
        assert r.tokens == w, r.rid
    eng.close()


def test_batched_dp_requires_divisible_slots(tmp_path_factory):
    d = tmp_path_factory.mktemp("serving_dp_bad")
    mpath, tpath = d / "m.m", d / "t.t"
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96),
                     np.random.default_rng(41))
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    eng = InferenceEngine(str(mpath), str(tpath), dp=2, tp=1)
    with pytest.raises(ValueError, match="divide over dp"):
        BatchedGenerator(eng, n_slots=3)
    eng.close()


def test_batched_speculative_near_cap_retires_early(tmp_path_factory):
    """A slot within spec+1 positions of seq_len retires instead of letting
    the K+1-wide cache write clamp and corrupt earlier rows — and every
    dispatch observed the safe bound. The emitted tokens must be a prefix of
    the non-spec run (speculation trades tail capacity, never content)."""
    d = tmp_path_factory.mktemp("spec_cap")
    mpath, tpath = d / "m.m", d / "t.t"
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=32),
                     np.random.default_rng(41))
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    prompt = "hello world hello"

    eng0 = InferenceEngine(str(mpath), str(tpath), tp=1)
    want = eng0.generate(prompt, 64, stop_on_eos=False).tokens
    eng0.close()

    eng = InferenceEngine(str(mpath), str(tpath), tp=1, spec_lookup=4)
    gen = BatchedGenerator(eng, n_slots=1)
    ids = eng.tokenizer.encode(prompt, is_start=True)
    r = Request(rid=0, prompt_ids=ids, max_tokens=64, stop_on_eos=False)
    gen.admit(r, 0)
    while gen.n_active:
        before, n_before = int(gen.pos[0]), len(r.tokens)
        gen.step()
        if len(r.tokens) > n_before:
            # a dispatch ran from `before`: its K+1-wide write must have fit
            # under seq_len (the REAL clamp-safety invariant)
            assert before + gen.spec + 1 <= eng.cfg.seq_len, before
    assert r.done.is_set() and len(r.tokens) >= 1
    assert r.tokens == want[: len(r.tokens)]
    eng.close()


def test_batched_spec_rejects_prompt_in_unsafe_zone(tmp_path_factory):
    """Prompts that would leave no room for a single K+1-wide dispatch are
    rejected at admission with a clear error (they would otherwise complete
    silently with zero tokens — review finding)."""
    d = tmp_path_factory.mktemp("spec_rej")
    mpath, tpath = d / "m.m", d / "t.t"
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=32),
                     np.random.default_rng(41))
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    eng = InferenceEngine(str(mpath), str(tpath), tp=1, spec_lookup=4)
    gen = BatchedGenerator(eng, n_slots=1)
    ids = list(range(1, 30))  # 29 tokens: >= seq_len(32) - spec(4)
    with pytest.raises(ValueError, match="usable context"):
        gen.begin_admit(Request(rid=0, prompt_ids=ids, max_tokens=8), 0)
    eng.close()


def test_cross_slot_prefix_reuse_exact_and_skips_prefill(engine):
    """Batched prefix KV reuse: a request sharing a prompt prefix with a
    previous (even retired) slot skips prefilling that prefix, and its
    output is identical to a solo run — the batched analogue of NaiveCache.
    Only the prefill-built region is matched (decode-built rows are
    excluded; see BatchedGenerator._ctx)."""
    sys_prompt = "hello world hello world "  # shared system prompt

    e1 = solo()
    want_b = e1.generate(sys_prompt + "abc", 8, stop_on_eos=False).tokens
    e1.close()
    e2 = solo(temperature=0.8, seed=5)
    want_c = e2.generate(sys_prompt + "xyz", 8, stop_on_eos=False).tokens
    e2.close()

    gen = BatchedGenerator(engine, n_slots=2)
    enc = lambda p: engine.tokenizer.encode(p, is_start=True)

    r_a = Request(rid=0, prompt_ids=enc(sys_prompt + "abc"), max_tokens=8,
                  stop_on_eos=False)
    gen.admit(r_a, 0)
    while gen.n_active:
        gen.step()
    assert r_a.tokens == want_b  # sanity: same request as want_b

    # request B: same prompt — admission must skip the ENTIRE prefix
    ids_b = enc(sys_prompt + "abc")
    adm = gen.begin_admit(Request(rid=1, prompt_ids=ids_b, max_tokens=8,
                                  stop_on_eos=False), 1)
    assert adm.pos == len(ids_b) - 1, "full-prefix reuse expected"
    while not gen.continue_admit(adm):
        pass
    while gen.n_active:
        gen.step()
    assert adm.req.tokens == want_b

    # request C: shares only the system prompt, then diverges (and samples)
    ids_c = enc(sys_prompt + "xyz")
    adm_c = gen.begin_admit(Request(rid=2, prompt_ids=ids_c, max_tokens=8,
                                    stop_on_eos=False, temperature=0.8,
                                    seed=5), 0)
    shared = 0
    for a, b in zip(ids_c[:-1], ids_b[:-1]):
        if a != b:
            break
        shared += 1
    assert adm_c.pos == shared > 4, "partial-prefix reuse expected"
    while not gen.continue_admit(adm_c):
        pass
    while gen.n_active:
        gen.step()
    assert adm_c.req.tokens == want_c


def test_paged_lifecycle_emits_spans_and_debug_requests_timeline(
        tmp_path_factory):
    """ISSUE-7 satellite: the paged lifecycle speaks the span vocabulary —
    admit / prefill_chunk spans per admission (on top of the shared
    queue/prefill/decode spans) — and the /debug/requests timeline payload
    shows them under a continuous-batching run."""
    from dllama_tpu.runtime import telemetry as tm

    d = tmp_path_factory.mktemp("serving_paged_spans")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(43)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96),
                     rng)
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    eng = InferenceEngine(str(mpath), str(tpath), tp=1, kv_block_size=16)
    sched = BatchScheduler(eng, n_slots=2)
    t0 = tm.now_ns()
    try:
        prompts = ["hello world hello", "hello", " world hello world"]
        reqs = [sched.submit(eng.tokenizer.encode(p, is_start=True), 4,
                             stop_on_eos=False) for p in prompts]
        for r in reqs:
            assert r.done.wait(timeout=300) and r.error is None
    finally:
        sched.close()
        eng.close()
    # raw ring, filtered to this run (the ring is process-global and
    # request ids restart per scheduler)
    spans = [s for s in tm.tracer().raw_spans() if s["start_ns"] >= t0]
    by_rid = {}
    for s in spans:
        by_rid.setdefault(s["request_id"], set()).add(s["phase"])
    for r in reqs:
        assert {"queue", "admit", "prefill_chunk", "prefill",
                "decode"} <= by_rid[r.rid], (r.rid, by_rid.get(r.rid))
    # every emitted phase is in the documented vocabulary (the lint's
    # runtime twin)
    assert {p for ps in by_rid.values() for p in ps} <= set(tm.PHASES)
    # and the /debug/requests payload (recent_requests) carries the paged
    # phases (the ring is shared process-wide, so assert our rids are
    # present with the new vocabulary rather than exact-matching)
    timelines = {t["request_id"]: t for t in tm.tracer().recent_requests()}
    for r in reqs:
        phases = [p["phase"] for p in timelines[r.rid]["phases"]]
        assert "admit" in phases and "prefill_chunk" in phases
        assert timelines[r.rid]["total_ms"] > 0


def test_batched_serving_on_moe_model(tmp_path_factory):
    """Continuous batching over a Mixture-of-Experts model: the ragged decode
    program rides the sparse MoE ffn (expert dispatch is positionwise, so
    per-row positions don't interact with it) — outputs equal solo runs."""
    d = tmp_path_factory.mktemp("serving_moe")
    mpath, tpath = d / "m.m", d / "t.t"
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96,
                                               n_experts=4,
                                               n_active_experts=2),
                     np.random.default_rng(41))
    tfile.write_tfile(tpath, byte_vocab_tokenizer())

    want = []
    cases = [("hello world", dict(temperature=0.0, seed=1)),
             ("hello", dict(temperature=0.8, seed=2))]
    for p, s in cases:
        e = InferenceEngine(str(mpath), str(tpath), tp=1, **s)
        want.append(e.generate(p, 8, stop_on_eos=False).tokens)
        e.close()

    eng = InferenceEngine(str(mpath), str(tpath), tp=1)
    gen = BatchedGenerator(eng, n_slots=2)
    reqs = []
    for i, (p, s) in enumerate(cases):
        ids = eng.tokenizer.encode(p, is_start=True)
        r = Request(rid=i, prompt_ids=ids, max_tokens=8, stop_on_eos=False,
                    topp=0.9, **s)
        gen.admit(r, i)
        reqs.append(r)
    while gen.n_active:
        gen.step()
    for r, w in zip(reqs, want):
        assert r.tokens == w, r.rid
    eng.close()


def test_chunked_batched_matches_solo_mixed(engine):
    """K fused ragged steps per dispatch (step_chunk / models.sampled_steps):
    every request — greedy and sampled, different lengths — must still equal
    its solo single-step run: tokens AND coin streams (VERDICT r3 weak #5,
    the batched-serving host loop; chunking divides host ticks by K)."""
    prompts = ["hello world", "hello", " world hello world", "hell"]
    specs = [dict(temperature=0.0, seed=1), dict(temperature=0.8, seed=2),
             dict(temperature=0.0, seed=3), dict(temperature=1.2, seed=4)]
    n = 12

    want = []
    for p, s in zip(prompts, specs):
        e = solo(temperature=s["temperature"], seed=s["seed"])
        want.append(e.generate(p, n, stop_on_eos=False).tokens)

    gen = BatchedGenerator(engine, n_slots=4)
    reqs = []
    for i, (p, s) in enumerate(zip(prompts, specs)):
        ids = engine.tokenizer.encode(p, is_start=True)
        r = Request(rid=i, prompt_ids=ids, max_tokens=n, stop_on_eos=False,
                    temperature=s["temperature"], topp=0.9, seed=s["seed"])
        gen.admit(r, i)
        reqs.append(r)
    ticks = 0
    while gen.n_active:
        gen.step_chunk(4)
        ticks += 1
    for r, w in zip(reqs, want):
        assert r.tokens == w, r.rid
    # the chunk actually engaged: 12 tokens in 3 four-wide ticks
    assert ticks == 3


def test_chunked_batched_eos_truncates_and_rng_rewinds(engine):
    """A slot hitting EOS mid-chunk keeps only the prefix through EOS, and a
    sampled request admitted AFTER that still sees the exact coin stream its
    solo run would (the un-kept draws were never committed)."""
    tok = engine.tokenizer
    eos = tok.eos_token_ids[0]
    gen = BatchedGenerator(engine, n_slots=2)

    # greedy request whose max_tokens forces the single-step fallback tail
    ids = tok.encode("hello world", is_start=True)
    r1 = Request(rid=0, prompt_ids=ids, max_tokens=6, stop_on_eos=True,
                 temperature=0.0)
    gen.admit(r1, 0)
    while gen.n_active:
        gen.step_chunk(4)  # 4 + fallback(2): headroom guard takes the tail
    w = solo(temperature=0.0).generate("hello world", 6).tokens
    assert r1.tokens == w

    # sampled request: chunked transcript equals solo
    r2 = Request(rid=1, prompt_ids=tok.encode("hell", is_start=True),
                 max_tokens=8, stop_on_eos=False, temperature=0.9, seed=11)
    gen.admit(r2, 1)
    while gen.n_active:
        gen.step_chunk(4)
    w2 = solo(temperature=0.9, seed=11).generate("hell", 8,
                                                 stop_on_eos=False).tokens
    assert r2.tokens == w2
    assert eos >= 0  # (fixture sanity)


def test_scheduler_uses_chunked_steps(tmp_path_factory):
    """--decode-chunk composes with --batch-slots through the scheduler."""
    d = tmp_path_factory.mktemp("serving-chunk")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(43)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96), rng)
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    eng = InferenceEngine(str(mpath), str(tpath), tp=1, decode_chunk=4)
    sched = BatchScheduler(eng, n_slots=2)
    try:
        got = sched.generate(eng.tokenizer.encode("hello world", is_start=True),
                             8, temperature=0.0, stop_on_eos=False)
        ref = InferenceEngine(str(mpath), str(tpath), tp=1)
        ids = ref.tokenizer.encode("hello world", is_start=True)
        want = ref.generate(ids, 8, stop_on_eos=False).tokens
        assert got == want
    finally:
        sched.close()


# ---------------------------------------------------------------------------
# composition: batched serving × offload / f8 KV (round-4 matrix closure)
# ---------------------------------------------------------------------------


def test_batched_serving_with_offload_matches_solo(tmp_path_factory):
    """--weight-mode offload (host-DRAM layer streaming) composes with the
    slot pool: the ragged programs pull the same pinned-host stacks the solo
    forward does, so transcripts must match solo offload runs."""
    require_pinned_host()
    d = tmp_path_factory.mktemp("serving-off")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(61)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96), rng)
    tfile.write_tfile(tpath, byte_vocab_tokenizer())

    ref = InferenceEngine(str(mpath), str(tpath), tp=1,
                          weight_mode="offload", temperature=0.0, seed=7)
    ids = ref.tokenizer.encode("hello world", is_start=True)
    want = ref.generate(ids, 6, stop_on_eos=False).tokens

    eng = InferenceEngine(str(mpath), str(tpath), tp=1,
                          weight_mode="offload", temperature=0.0, seed=7)
    gen = BatchedGenerator(eng, n_slots=2)
    r = Request(rid=0, prompt_ids=ids, max_tokens=6, temperature=0.0,
                stop_on_eos=False)
    gen.admit(r, 0)
    while gen.n_active:
        gen.step()
    assert r.tokens == want


def test_batched_serving_with_f8_kv_runs_and_is_deterministic(
        tmp_path_factory):
    """--kv-dtype f8 composes with the slot pool (the serving cache is
    created at engine.kv_dtype): same request twice -> same tokens."""
    import jax.numpy as jnp

    d = tmp_path_factory.mktemp("serving-f8")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(62)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96), rng)
    tfile.write_tfile(tpath, byte_vocab_tokenizer())

    eng = InferenceEngine(str(mpath), str(tpath), tp=1, kv_dtype="f8",
                          compute_dtype="bfloat16", temperature=0.0, seed=7)
    gen = BatchedGenerator(eng, n_slots=2)
    assert gen.kv.k.dtype == jnp.float8_e4m3fn
    ids = eng.tokenizer.encode("hello world", is_start=True)
    outs = []
    for slot in (0, 1):
        r = Request(rid=slot, prompt_ids=ids, max_tokens=6,
                    temperature=0.0, stop_on_eos=False)
        gen.admit(r, slot)
        while gen.slots[slot] is not None:
            gen.step()
        outs.append(r.tokens)
    assert outs[0] == outs[1] and len(outs[0]) == 6


def test_batched_under_turbo_matches_solo(tmp_path_factory, monkeypatch):
    """Serving composes with turbo numerics: batched transcripts equal
    turbo solo runs (the solo-identity invariant holds within the mode —
    turbo vs fast numerics differ, turbo-batched vs turbo-solo must not)."""
    monkeypatch.setenv("DLLAMA_TPU_QUANT_MODE", "turbo")
    d = tmp_path_factory.mktemp("serving_turbo")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(43)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96),
                     rng)
    tfile.write_tfile(tpath, byte_vocab_tokenizer())

    from dllama_tpu.ops.turbo import TurboWeight

    prompts = ["hello world", "hello", " world"]
    specs = [dict(temperature=0.0, seed=1), dict(temperature=0.8, seed=2),
             dict(temperature=0.0, seed=3)]
    n = 8
    want = []
    for p, s in zip(prompts, specs):
        e = InferenceEngine(str(mpath), str(tpath), tp=1,
                            compute_dtype="bfloat16", **s)
        want.append(e.generate(p, n, stop_on_eos=False).tokens)

    eng = InferenceEngine(str(mpath), str(tpath), tp=1,
                          compute_dtype="bfloat16")
    assert isinstance(eng.params.layers.wq, TurboWeight)
    gen = BatchedGenerator(eng, n_slots=3)
    reqs = []
    for i, (p, s) in enumerate(zip(prompts, specs)):
        ids = eng.tokenizer.encode(p, is_start=True)
        r = Request(rid=i, prompt_ids=ids, max_tokens=n, stop_on_eos=False,
                    temperature=s["temperature"], topp=0.9, seed=s["seed"])
        gen.admit(r, i)
        reqs.append(r)
    while gen.n_active:
        gen.step()
    for r, w in zip(reqs, want):
        assert r.tokens == w, r.rid


def test_batched_under_sp_matches_solo(tmp_path_factory):
    """Batched serving under an sp mesh (ragged per-slot depths through the
    ring/merge attention paths, parallel/ring.py): every request equals its
    solo unsharded run (VERDICT r4 next #6 — sp×ragged was an oracle-only
    hole)."""
    d = tmp_path_factory.mktemp("serving_sp")
    mpath, tpath = d / "m.m", d / "t.t"
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96),
                     np.random.default_rng(43))
    tfile.write_tfile(tpath, byte_vocab_tokenizer())

    cases = [("hello world", dict(temperature=0.0, seed=1)),
             ("hello", dict(temperature=0.8, seed=2)),
             (" world", dict(temperature=0.0, seed=3))]
    want = []
    for p, s in cases:
        e = InferenceEngine(str(mpath), str(tpath), tp=1, **s)
        want.append(e.generate(p, 8, stop_on_eos=False).tokens)
        e.close()

    eng = InferenceEngine(str(mpath), str(tpath), sp=2, tp=2)
    gen = BatchedGenerator(eng, n_slots=3)
    reqs = []
    for i, (p, s) in enumerate(cases):
        ids = eng.tokenizer.encode(p, is_start=True)
        r = Request(rid=i, prompt_ids=ids, max_tokens=8, stop_on_eos=False,
                    topp=0.9, **s)
        gen.admit(r, i)
        reqs.append(r)
    while gen.n_active:
        gen.step()
    for r, w in zip(reqs, want):
        assert r.tokens == w, r.rid
    eng.close()


def test_batched_under_pp_matches_solo(tmp_path_factory):
    """Batched serving under a pp mesh (VERDICT r4 next #7): ragged per-slot
    depths flow through the pipeline stages — both schedules (the GPipe
    microbatch path when the pool divides by pp, the sequential path
    otherwise) — and every request equals its solo unsharded run."""
    d = tmp_path_factory.mktemp("serving_pp")
    mpath, tpath = d / "m.m", d / "t.t"
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96),
                     np.random.default_rng(44))
    tfile.write_tfile(tpath, byte_vocab_tokenizer())

    cases = [("hello world", dict(temperature=0.0, seed=1)),
             ("hello", dict(temperature=0.8, seed=2)),
             (" world", dict(temperature=0.0, seed=3)),
             ("hell", dict(temperature=1.2, seed=4))]
    want = []
    for p, s in cases:
        e = InferenceEngine(str(mpath), str(tpath), tp=1, **s)
        want.append(e.generate(p, 8, stop_on_eos=False).tokens)
        e.close()

    eng = InferenceEngine(str(mpath), str(tpath), tp=1, pp=2)
    gen = BatchedGenerator(eng, n_slots=4)  # 4 % pp2 == 0: microbatch path
    reqs = []
    for i, (p, s) in enumerate(cases):
        ids = eng.tokenizer.encode(p, is_start=True)
        r = Request(rid=i, prompt_ids=ids, max_tokens=8, stop_on_eos=False,
                    topp=0.9, **s)
        gen.admit(r, i)
        reqs.append(r)
    while gen.n_active:
        gen.step()
    for r, w in zip(reqs, want):
        assert r.tokens == w, r.rid
    eng.close()

    # odd pool (sequential schedule) composed with tp
    eng2 = InferenceEngine(str(mpath), str(tpath), tp=2, pp=2)
    gen2 = BatchedGenerator(eng2, n_slots=3)
    reqs2 = []
    for i, (p, s) in enumerate(cases[:3]):
        ids = eng2.tokenizer.encode(p, is_start=True)
        r = Request(rid=i, prompt_ids=ids, max_tokens=8, stop_on_eos=False,
                    topp=0.9, **s)
        gen2.admit(r, i)
        reqs2.append(r)
    while gen2.n_active:
        gen2.step()
    for r, w in zip(reqs2, want[:3]):
        assert r.tokens == w, r.rid
    eng2.close()
