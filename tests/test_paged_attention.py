"""Ragged paged attention kernel vs the gather+oracle reference —
BITWISE, adversarially (the parity methodology of nn-vulkan-test.cpp,
escalated: the paged kernel replaces the PR6 ``pool[tables]`` gather
bit-for-bit, so every table shape continuous batching can produce must
reproduce the dense path's exact float pattern).

The reference side is the JITTED gather+oracle composition — the program
the seam in models/llama.py actually swaps out (eager op-by-op execution
rounds differently than a fused jaxpr; the claim is program-vs-program)."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dllama_tpu.ops.attention import attention
from dllama_tpu.ops.paged_attention import (
    kernel_choice,
    paged_ragged_attention,
    supports,
)


def _reference(q, k_pool, v_pool, tables, positions, head_dim):
    """The gather+oracle pair, jitted — exactly what _paged_layer_step's
    fallback branch traces."""
    B, M = tables.shape
    n_kv, bs, hd = k_pool.shape[1], k_pool.shape[2], k_pool.shape[3]

    @jax.jit
    def ref(q, k_pool, v_pool, tables, positions):
        def view(pool):
            gathered = pool[tables]              # [B, M, n_kv, bs, hd]
            return jnp.moveaxis(gathered, 2, 1).reshape(
                B, n_kv, M * bs, hd)

        return attention(q, view(k_pool), view(v_pool), positions, head_dim)

    return ref(q, k_pool, v_pool, tables, positions)


def _mk(rng, B, T, n_heads, n_kv, hd, bs, M, nb, dtype=jnp.float32):
    k_pool = jnp.asarray(rng.standard_normal((nb, n_kv, bs, hd)), dtype)
    v_pool = jnp.asarray(rng.standard_normal((nb, n_kv, bs, hd)), dtype)
    q = jnp.asarray(rng.standard_normal((B, T, n_heads, hd)), jnp.float32)
    return q, k_pool, v_pool


def _assert_bitwise(q, k_pool, v_pool, tables, positions, hd):
    got = paged_ragged_attention(q, k_pool, v_pool, tables, positions, hd,
                                 interpret=True)
    want = _reference(q, k_pool, v_pool, tables, positions, hd)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_scrambled_block_table_bitwise():
    """Arbitrary physical placement: every row's blocks land at scrambled
    pool ids (the steady-state of a churning allocator)."""
    rng = np.random.default_rng(0)
    B, T, n_heads, n_kv, hd, bs, M, nb = 3, 1, 8, 2, 16, 16, 4, 14
    q, kp, vp = _mk(rng, B, T, n_heads, n_kv, hd, bs, M, nb)
    tables = jnp.asarray(
        rng.permutation(np.arange(1, 1 + B * M)).reshape(B, M).astype(np.int32))
    positions = jnp.asarray([[37], [5], [63]], jnp.int32)
    _assert_bitwise(q, kp, vp, tables, positions, hd)


def test_partial_tail_block_and_ragged_rows():
    """Each row mid-block at its own depth: the newest block is partially
    valid and masked per position, never per block."""
    rng = np.random.default_rng(1)
    B, T, n_heads, n_kv, hd, bs, M, nb = 4, 1, 8, 4, 32, 16, 8, 40
    q, kp, vp = _mk(rng, B, T, n_heads, n_kv, hd, bs, M, nb)
    tables = jnp.asarray(rng.integers(1, nb, (B, M)).astype(np.int32))
    # depths chosen to hit block offsets 0, 1, bs-1 and a mid-block point
    positions = jnp.asarray([[0], [bs - 1], [bs], [3 * bs + 7]], jnp.int32)
    _assert_bitwise(q, kp, vp, tables, positions, hd)


def test_shared_and_null_redirected_blocks():
    """Block-level sharing (two rows aliasing one physical prefix block —
    the prefix-reuse steady state) and CoW-retired tails redirected to the
    null block 0: the garbage behind null entries is position-masked on
    both paths identically."""
    rng = np.random.default_rng(2)
    B, T, n_heads, n_kv, hd, bs, M, nb = 3, 1, 4, 2, 16, 16, 6, 10
    q, kp, vp = _mk(rng, B, T, n_heads, n_kv, hd, bs, M, nb)
    tables = np.zeros((B, M), np.int32)        # all-null tails
    tables[0, :3] = [5, 6, 7]
    tables[1, :3] = [5, 6, 8]                  # shares blocks 5, 6 with row 0
    tables[2, :2] = [9, 3]
    tables = jnp.asarray(tables)
    positions = jnp.asarray([[2 * bs + 3], [2 * bs + 9], [bs + 1]], jnp.int32)
    _assert_bitwise(q, kp, vp, tables, positions, hd)


@pytest.mark.parametrize("t", [1, 16])
def test_query_width_edges(t):
    """T=1 (decode) and T=16 (chunked-prefill tail / verify width)."""
    rng = np.random.default_rng(3 + t)
    B, n_heads, n_kv, hd, bs, M, nb = 2, 8, 2, 16, 16, 6, 20
    q, kp, vp = _mk(rng, B, t, n_heads, n_kv, hd, bs, M, nb)
    tables = jnp.asarray(rng.integers(1, nb, (B, M)).astype(np.int32))
    positions = (jnp.asarray([3, 2 * bs + 1], jnp.int32)[:, None]
                 + jnp.arange(t)[None, :])
    _assert_bitwise(q, kp, vp, tables, positions, hd)


@pytest.mark.parametrize("hd", [40, 72])
def test_non_128_aligned_head_dims(hd):
    rng = np.random.default_rng(11)
    B, T, n_heads, n_kv, bs, M, nb = 2, 2, 4, 4, 8, 4, 9
    q, kp, vp = _mk(rng, B, T, n_heads, n_kv, hd, bs, M, nb)
    tables = jnp.asarray(rng.integers(0, nb, (B, M)).astype(np.int32))
    positions = (jnp.asarray([7, 19], jnp.int32)[:, None]
                 + jnp.arange(T)[None, :])
    _assert_bitwise(q, kp, vp, tables, positions, hd)


def test_bf16_pool_bitwise():
    """The serving pool dtype: both paths cast pool rows to f32 the same
    way, so bf16 storage stays bit-identical too."""
    rng = np.random.default_rng(21)
    B, T, n_heads, n_kv, hd, bs, M, nb = 2, 1, 4, 2, 16, 16, 4, 8
    q, kp, vp = _mk(rng, B, T, n_heads, n_kv, hd, bs, M, nb,
                    dtype=jnp.bfloat16)
    tables = jnp.asarray(rng.integers(1, nb, (B, M)).astype(np.int32))
    positions = jnp.asarray([[9], [3 * bs - 1]], jnp.int32)
    _assert_bitwise(q, kp, vp, tables, positions, hd)


def test_supports_predicate():
    assert supports((2, 1, 8, 128), 2, 8, 16)
    assert supports((2, 16, 8, 40), 2, 8, 16)
    assert not supports((2, 1, 8, 129), 2, 8, 16)   # head dim not 8-aligned
    assert not supports((2, 1, 8, 128), 2, 8, 4)    # block_size below a tile
    assert not supports((2, 1, 8, 128), 3, 8, 16)   # irregular GQA split
    # VMEM bound: a 1M-row logical context can't stage
    assert not supports((1, 1, 8, 128), 1, 8192, 128)


def test_kernel_choice_routes_through_the_one_gate(monkeypatch):
    """Mode selection is quant_matmul.pallas_mode_gate — xla kills the
    kernel, pallas forces it (interpret off-TPU), and an active mesh plan
    falls back (the auto-sharder can't partition a pallas_call)."""
    from dllama_tpu.parallel.api import make_tp_mesh, use_plan

    shape = ((2, 1, 8, 16), 2, 4, 16)
    monkeypatch.setenv("DLLAMA_TPU_QUANT_KERNEL", "xla")
    assert kernel_choice(*shape) is None
    monkeypatch.setenv("DLLAMA_TPU_QUANT_KERNEL", "pallas")
    kw = kernel_choice(*shape)
    assert kw is not None and kw["interpret"] is True  # off-TPU test path
    monkeypatch.setenv("DLLAMA_TPU_QUANT_KERNEL", "fused")
    assert kernel_choice(*shape) is not None
    monkeypatch.setenv("DLLAMA_TPU_QUANT_KERNEL", "pallas")
    with use_plan(make_tp_mesh(2)):
        assert kernel_choice(*shape) is None


# ---------------------------------------------------------------------------
# program-level: the paged forward family through the seam
# ---------------------------------------------------------------------------


def _tiny_cfg():
    from dllama_tpu.formats import mfile
    from dllama_tpu.models import ModelConfig

    return ModelConfig(
        arch=mfile.ArchType.LLAMA, dim=64, hidden_dim=96, n_layers=2,
        n_heads=8, n_kv_heads=2, head_dim=8, vocab_size=128, seq_len=64,
        norm_epsilon=1e-5, rope_theta=10000.0, rope_type=mfile.RopeType.LLAMA)


def test_paged_forward_bitwise_through_scrambled_tables(monkeypatch):
    """The full paged decode program (logits AND written pool) is
    bit-identical between the gather+oracle trace and the kernel trace,
    through a scrambled block table — the acceptance bar for the seam
    swap."""
    from dllama_tpu.models import init_random_params
    from dllama_tpu.models.llama import paged_forward
    from dllama_tpu.runtime.kvblocks import PagedKVCache

    cfg = _tiny_cfg()
    params = init_random_params(cfg, seed=7)
    pkv = PagedKVCache.create(cfg, n_blocks=14, block_size=16)
    rng = np.random.default_rng(3)
    B, M = 3, 4
    tables = jnp.asarray(
        rng.permutation(np.arange(1, 1 + B * M)).reshape(B, M).astype(np.int32))
    pos = jnp.asarray([5, 0, 33], jnp.int32)
    toks = jnp.asarray(rng.integers(1, 127, (B, 1)).astype(np.int32))

    # fresh lambdas per mode: jit wrappers around the SAME function object
    # share the pjit executable cache, which would reuse the oracle program
    # for the kernel run and make this test vacuous
    monkeypatch.setenv("DLLAMA_TPU_QUANT_KERNEL", "xla")
    lx, px = jax.jit(lambda p, c, t, s, kv, tb: paged_forward(p, c, t, s, kv, tb),
                     static_argnums=1)(params, cfg, toks, pos, pkv, tables)
    monkeypatch.setenv("DLLAMA_TPU_QUANT_KERNEL", "pallas")
    lp, pp = jax.jit(lambda p, c, t, s, kv, tb: paged_forward(p, c, t, s, kv, tb),
                     static_argnums=1)(params, cfg, toks, pos, pkv, tables)

    np.testing.assert_array_equal(np.asarray(lx), np.asarray(lp))
    np.testing.assert_array_equal(np.asarray(px.k), np.asarray(pp.k))
    np.testing.assert_array_equal(np.asarray(px.v), np.asarray(pp.v))


def test_paged_kernel_steady_state_never_retraces(monkeypatch):
    """Zero post-steady compiles with the kernel enabled: table contents,
    positions, and tokens all vary dispatch to dispatch without a retrace
    (the continuous-batching requirement, ledger-asserted at the engine
    level by test_kvblocks — this is the kernel-path twin)."""
    from dllama_tpu.models import init_random_params
    from dllama_tpu.models.llama import paged_forward
    from dllama_tpu.runtime.kvblocks import PagedKVCache

    monkeypatch.setenv("DLLAMA_TPU_QUANT_KERNEL", "pallas")
    cfg = _tiny_cfg()
    params = init_random_params(cfg, seed=8)
    pkv = PagedKVCache.create(cfg, n_blocks=14, block_size=16)
    rng = np.random.default_rng(5)
    fwd = jax.jit(paged_forward, static_argnums=1)
    n_compiles = []
    for step in range(4):
        tables = jnp.asarray(rng.integers(0, 14, (3, 4)).astype(np.int32))
        pos = jnp.asarray(rng.integers(0, 40, 3).astype(np.int32))
        toks = jnp.asarray(rng.integers(1, 127, (3, 1)).astype(np.int32))
        logits, pkv = fwd(params, cfg, toks, pos, pkv, tables)
        jax.block_until_ready(logits)
        n_compiles.append(fwd._cache_size())
    assert n_compiles[0] == 1 and n_compiles[-1] == 1, n_compiles


# ---------------------------------------------------------------------------
# real-chip tier (the capability-probe skip idiom: compiled kernels only
# ever run under DLLAMA_TESTS_TPU=1 on a real backend — tier-1 stays
# deterministic off-TPU)
# ---------------------------------------------------------------------------


@pytest.mark.tpu
def test_paged_kernel_compiled_parity_on_hw():
    devs = jax.devices()
    if not devs or "tpu" not in devs[0].device_kind.lower():
        pytest.skip(f"no TPU backend (devices: {devs})")
    rng = np.random.default_rng(31)
    B, T, n_heads, n_kv, hd, bs, M, nb = 2, 1, 8, 2, 128, 16, 4, 10
    q, kp, vp = _mk(rng, B, T, n_heads, n_kv, hd, bs, M, nb)
    tables = jnp.asarray(rng.integers(0, nb, (B, M)).astype(np.int32))
    positions = jnp.asarray([[17], [3]], jnp.int32)
    got = paged_ragged_attention(q, kp, vp, tables, positions, hd)
    want = _reference(q, kp, vp, tables, positions, hd)
    # Mosaic compiled vs XLA: accumulation-order noise at f32 scale only
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
