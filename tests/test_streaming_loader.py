"""Streaming weight loader: correctness vs the in-memory oracle + bounded RSS.

The round-1 loader stacked the whole model in host RAM before device_put
(VERDICT missing #4); the streaming loader (runtime/weights.py) must keep peak
host memory near one tensor shard. The RSS test runs in a subprocess so the
high-water mark isn't polluted by this process's jax history.
"""

from __future__ import annotations

import subprocess
import sys
import textwrap
from pathlib import Path

import numpy as np
import pytest

import helpers
from dllama_tpu.formats import mfile, quants
from dllama_tpu.models import ModelConfig
from dllama_tpu.models.llama import load_params_from_mfile
from dllama_tpu.ops.linear import QuantizedWeight, dequantize_weight
from dllama_tpu.parallel.api import make_tp_mesh

REPO = Path(__file__).resolve().parent.parent


@pytest.mark.parametrize("weight_type", [quants.Q40, quants.F32])
def test_streaming_load_matches_file_contents(tmp_path, weight_type):
    """Every loaded leaf equals the dense weights written to disk."""
    rng = np.random.default_rng(5)
    params_hdr = helpers.tiny_header_params(weight_type=weight_type)
    m = tmp_path / "m.m"
    dense = helpers.write_tiny_model(m, params_hdr, rng)
    mf = mfile.ModelFile.open(m)
    cfg = ModelConfig.from_header(mf.header)
    params = load_params_from_mfile(mf, cfg)

    def check(name, got, l=None):
        want = dense[f"{name}.{l}"] if l is not None else dense[name]
        if isinstance(got, QuantizedWeight):
            gl = QuantizedWeight(scales=got.scales[l], codes=got.codes[l]) \
                if l is not None else got
            g = np.asarray(dequantize_weight(gl)).T  # K-major -> [out, in]
            want = np.asarray(
                quants.dequantize_q40(quants.quantize_q40(
                    want.astype(np.float32).reshape(-1)), want.size)
            ).reshape(want.shape)
        else:
            g = np.asarray(got[l] if l is not None else got, np.float32)
        np.testing.assert_allclose(g, want, rtol=1e-6, atol=1e-6)

    lp = params.layers
    for l in range(mf.header.n_layers):
        check("block_matmul_q", lp.wq, l)
        check("block_matmul_wo", lp.wo, l)
        check("block_matmul_w2", lp.w2, l)
        check("block_norm_0", lp.norm_att, l)
    check("embedding", params.embedding)
    check("final_matmul_logits", params.logits)
    mf.close()


def test_streaming_load_sharded_equals_unsharded(tmp_path):
    """tp-sharded streaming load reassembles to the same values."""
    rng = np.random.default_rng(6)
    m = tmp_path / "m.m"
    helpers.write_tiny_model(m, helpers.tiny_header_params(), rng)
    mf = mfile.ModelFile.open(m)
    cfg = ModelConfig.from_header(mf.header)
    base = load_params_from_mfile(mf, cfg)
    sharded = load_params_from_mfile(mf, cfg, plan=make_tp_mesh(4))

    import jax

    def cmp(a, b):
        if a is None:
            return
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    jax.tree.map(cmp, base, sharded, is_leaf=lambda x: x is None)
    mf.close()


WRITE_SCRIPT = textwrap.dedent("""
    import sys
    sys.path.insert(0, sys.argv[1] + "/tests"); sys.path.insert(0, sys.argv[1])
    import numpy as np
    import helpers
    hdr = helpers.tiny_header_params(
        dim=512, n_layers=40, n_heads=8, n_kv_heads=4, hidden_dim=1536,
        vocab_size=4096, seq_len=64)
    helpers.write_tiny_model(sys.argv[2], hdr, np.random.default_rng(0))
""")

LOAD_SCRIPT = textwrap.dedent("""
    import os, resource, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, sys.argv[1])
    import jax
    jax.config.update("jax_platforms", "cpu")
    from dllama_tpu.formats import mfile
    from dllama_tpu.models import ModelConfig
    from dllama_tpu.models.llama import load_params_from_mfile

    path = sys.argv[2]
    mf = mfile.ModelFile.open(path)
    cfg = ModelConfig.from_header(mf.header)
    # warm the jit/backend machinery so the measured delta is the load itself
    import jax.numpy as jnp
    jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    rss_before = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    params = load_params_from_mfile(mf, cfg)
    jax.block_until_ready(params)
    rss_after = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    print(f"RESULT {os.path.getsize(path)} {rss_after - rss_before}")
""")


@pytest.mark.slow
def test_streaming_load_rss_bounded(tmp_path):
    """Peak RSS growth during load stays near the placed-params footprint
    (device = CPU here, so placed arrays count too): the round-1 stacking
    loader held host copies of everything at once (>= 2x model). The load
    runs in its own subprocess so ru_maxrss measures only the load."""
    path = str(tmp_path / "big.m")
    w = subprocess.run([sys.executable, "-c", WRITE_SCRIPT, str(REPO), path],
                       capture_output=True, timeout=600)
    assert w.returncode == 0, w.stderr.decode()[-2000:]
    out = subprocess.run([sys.executable, "-c", LOAD_SCRIPT, str(REPO), path],
                         capture_output=True, timeout=600)
    assert out.returncode == 0, out.stderr.decode()[-2000:]
    line = [ln for ln in out.stdout.decode().splitlines()
            if ln.startswith("RESULT")][0]
    model_bytes, delta = map(int, line.split()[1:])
    # Measured budget on the CPU backend (where "device" buffers are host RAM
    # too): placed params ~1.3x file + resident mmap pages ~1x + per-tensor
    # transients ~1x => ~3.3x observed. The stacking loader this replaced
    # measured 4.65x on the same model (full host copy of the model alive at
    # peak); 3.9 catches a regression to that shape while allowing noise.
    assert delta < model_bytes * 3.9, (
        f"load RSS delta {delta / 1e6:.1f} MB vs model {model_bytes / 1e6:.1f} MB")


def test_per_callback_allocation_bounded_to_shard(tmp_path):
    """The precise form of the "bounded host memory" claim (VERDICT round-2
    weak #6): during load, each make_array_from_callback callback allocates
    at most ~its own shard (plus one layer-slice transient), never a
    model-sized buffer. Measured with tracemalloc (device buffers excluded —
    numpy allocations inside the callback only), replacing the coarse
    subprocess-RSS multiple."""
    import tracemalloc

    from dllama_tpu.runtime import weights as W

    rng = np.random.default_rng(11)
    hdr = helpers.tiny_header_params(dim=256, hidden_dim=512, n_layers=8,
                                     n_heads=8, n_kv_heads=4, vocab_size=2048,
                                     seq_len=64)
    m = tmp_path / "big.m"
    helpers.write_tiny_model(m, hdr, rng)
    mf = mfile.ModelFile.open(m)
    cfg = ModelConfig.from_header(mf.header)

    records: list[tuple[int, int]] = []  # (peak_alloc, result_nbytes)
    orig_make = W._make

    def measuring_make(shape, dtype, sharding, cb):
        def cb2(idx):
            tracemalloc.start()
            try:
                out = np.asarray(cb(idx))
            finally:
                _, peak = tracemalloc.get_traced_memory()
                tracemalloc.stop()
            records.append((peak, out.nbytes))
            return out
        return orig_make(shape, dtype, sharding, cb2)

    try:
        W._make = measuring_make
        params = W.load_params(mf, cfg)
    finally:
        W._make = orig_make
    assert records, "instrumentation never fired"

    import jax as _jax

    leaves = [np.asarray(x).nbytes for x in _jax.tree.leaves(params)]
    total_param_bytes = sum(leaves)
    worst_peak = 0
    for peak, nbytes in records:
        # shard + one layer-slice transient + small slack; never model-sized
        assert peak <= nbytes * 1.6 + (1 << 20), (peak, nbytes)
        worst_peak = max(worst_peak, peak)
    # the high-water mark is set by the LARGEST single tensor stack, not by
    # the model: exactly the "one tensor shard" claim
    assert worst_peak <= max(leaves) * 1.6 + (1 << 20), (worst_peak, max(leaves))
    assert worst_peak < total_param_bytes / 2, (worst_peak, total_param_bytes)
