"""Tenant observatory tests (runtime/tenancy.py + scheduler wiring).

THE property under test is conservation: every per-tenant total is
incremented at the same site, with the same value, as its global
counter — so per-tenant sums reconcile bit-exactly with the tenant-blind
series under mixed multi-tenant continuous batching. On top of that:
the identity contract (sanitize → anon, cardinality cap → other), the
weighted-round-robin FairQueue, token-rate budgets (per-tenant 429,
not a global one), the usage ledger's monotonic JSONL, and the
contention acceptance — a flooding tenant cannot starve a light one."""

import json
import time
from types import SimpleNamespace

import numpy as np
import pytest

from dllama_tpu.formats import tfile
from dllama_tpu.runtime import telemetry as tm
from dllama_tpu.runtime import tenancy
from dllama_tpu.runtime.engine import InferenceEngine
from dllama_tpu.runtime.serving import (BatchScheduler, QueueFullError,
                                        TenantOverBudgetError)

from helpers import byte_vocab_tokenizer, tiny_header_params, write_tiny_model


@pytest.fixture(autouse=True)
def _fresh_tenancy():
    tenancy.reset()
    yield
    tenancy.reset()


# -- identity ----------------------------------------------------------------


def test_sanitize_tenant_contract():
    assert tenancy.sanitize_tenant("acme") == "acme"
    assert tenancy.sanitize_tenant("a.b_c-d.42") == "a.b_c-d.42"
    assert tenancy.sanitize_tenant("  acme  ") == "acme"  # stripped
    assert tenancy.sanitize_tenant("x" * 64) == "x" * 64
    # everything malformed is anon, never an error
    for bad in (None, "", " ", "x" * 65, "has space", "no/slash",
                "bad{brace}", "naïve", b"bytes"):
        assert tenancy.sanitize_tenant(bad) == tenancy.ANON, bad


def test_cardinality_cap_1000_distinct_ids():
    """ISSUE-20 satellite: a tenant-id fuzzer inflates ONE counter.
    1000 distinct ids: the first TENANT_CAP get real labels, the other
    936 collapse into "other" and each counts
    dllama_tenant_overflow_total — /metrics cardinality stays bounded."""
    reg = tenancy.registry()
    c = tm.registry().counter(tm.TENANT_OVERFLOW)
    base = c.total()
    ids = [f"fuzz-{i:04d}" for i in range(1000)]
    labels = [reg.resolve(t) for t in ids]
    kept = ids[:tenancy.TENANT_CAP]
    assert labels[:tenancy.TENANT_CAP] == kept
    assert set(labels[tenancy.TENANT_CAP:]) == {tenancy.OTHER}
    assert c.total() - base == 1000 - tenancy.TENANT_CAP
    # known tenants still resolve to themselves (LRU refresh, no evict);
    # an overflowed id keeps collapsing and keeps counting
    assert reg.resolve(kept[0]) == kept[0]
    assert reg.resolve("fuzz-0999") == tenancy.OTHER
    assert c.total() - base == 1000 - tenancy.TENANT_CAP + 1
    snap = reg.snapshot()
    assert snap["cap"] == tenancy.TENANT_CAP
    assert snap["n_tenants"] == tenancy.TENANT_CAP + 1  # + "other"
    assert snap["overflow_total"] == c.total()
    # accounting against an overflowed id lands on "other"
    reg.note_decode_tokens(reg.resolve("fuzz-0500"), 7)
    assert reg.snapshot()["tenants"][tenancy.OTHER]["decode_tokens"] == 7


# -- limits ------------------------------------------------------------------


def test_parse_limits_and_validation():
    lims = tenancy.parse_limits({
        "acme": {"weight": 4, "max_slots": 2, "tokens_per_s": 100},
        "*": {"weight": 1}})
    assert lims["acme"].weight == 4.0
    assert lims["acme"].max_slots == 2
    assert lims["acme"].tokens_per_s == 100.0
    assert lims["*"].max_slots == 0
    # a limits doc that silently never applies is how a flooder wins:
    # every malformed shape fails loudly at startup
    for bad in ([1, 2],                              # not an object
                {"bad id!": {}},                     # id charset
                {"t": 7},                            # entry not an object
                {"t": {"weigth": 2}},                # typo'd field
                {"t": {"weight": 0}},                # weight must be > 0
                {"t": {"weight": -1}},
                {"t": {"max_slots": -1}},
                {"t": {"tokens_per_s": -5}}):
        with pytest.raises(ValueError):
            tenancy.parse_limits(bad)


def test_load_limits_inline_and_file(tmp_path):
    inline = tenancy.load_limits('{"a": {"weight": 2}}')
    assert inline["a"].weight == 2.0
    p = tmp_path / "limits.json"
    p.write_text('{"b": {"max_slots": 3}}')
    from_file = tenancy.load_limits(str(p))
    assert from_file["b"].max_slots == 3
    with pytest.raises(ValueError):
        tenancy.load_limits("not json and not a file")


def test_token_bucket_rate_and_burst():
    t = [0.0]
    reg = tenancy.TenantRegistry(clock=lambda: t[0])
    reg.set_limits(tenancy.parse_limits({"metered": {"tokens_per_s": 10}}))
    # bucket starts full at BURST_S seconds of rate
    cap = 10 * tenancy.BURST_S
    assert reg.try_charge_tokens("metered", cap)
    assert not reg.try_charge_tokens("metered", 1)
    t[0] += 1.0  # refill 10 tokens
    assert reg.try_charge_tokens("metered", 10)
    assert not reg.try_charge_tokens("metered", 1)
    # an unlimited tenant never hits the bucket
    assert reg.try_charge_tokens("free", 10 ** 9)


# -- fair queue --------------------------------------------------------------


def _item(tenant):
    return SimpleNamespace(tenant=tenant)


def test_fair_queue_weighted_round_robin_order():
    """Stride schedule over weights a=4, b=1: four a-pops per b-pop,
    FIFO within each tenant."""
    weights = {"a": 4.0, "b": 1.0}
    q = tenancy.FairQueue(weight_of=lambda t: weights.get(t, 1.0))
    a = [_item("a") for _ in range(8)]
    b = [_item("b") for _ in range(4)]
    for it in a:
        q.push(it)
    for it in b:
        q.push(it)
    assert len(q) == 12 and bool(q)
    order = []
    while q:
        head = q.peek()
        order.append(q.pop(head))
    assert order == [a[0], b[0], a[1], a[2], a[3], a[4], b[1],
                     a[5], a[6], a[7], b[2], b[3]]
    assert not q and len(q) == 0


def test_fair_queue_push_front_refunds_pass():
    """A requeue-at-head (block exhaustion) must not charge the tenant
    twice: after push_front, the same item is the next peek even though
    its pop already advanced the tenant's pass."""
    weights = {"a": 1.0, "b": 1.0}
    q = tenancy.FairQueue(weight_of=lambda t: weights[t])
    ia, ib = _item("a"), _item("b")
    q.push(ia), q.push(ib)
    head = q.peek()
    assert head is ia
    q.pop(ia)
    q.push_front(ia)  # admission failed: back at the head, pass refunded
    assert q.peek() is ia
    # popping something that is not its tenant's head is a bug upstream
    q2 = tenancy.FairQueue()
    x, y = _item("t"), _item("t")
    q2.push(x), q2.push(y)
    with pytest.raises(ValueError):
        q2.pop(y)


def test_fair_queue_idle_tenant_banks_no_credit():
    """A tenant idle through 8 pops of another re-enters at the current
    virtual time: it gets its fair share from NOW on, not a saved-up
    burst that would starve the incumbent."""
    q = tenancy.FairQueue()
    a = [_item("a") for _ in range(10)]
    for it in a:
        q.push(it)
    ib0 = _item("b")
    q.push(ib0)
    q.pop(q.peek())  # a0
    q.pop(q.peek())  # b0 (pass 0 < a's 1.0)
    assert not q.tenants_queued().get("b")
    for _ in range(8):  # b idle while a drains 8 more
        q.pop(q.peek())
    # b re-enters: ONE immediate turn at vtime, then strict alternation
    # — never a run of consecutive b-pops cashing in the idle stretch
    bs = [_item("b") for _ in range(3)]
    for it in bs:
        q.push(it)
    order = []
    while q:
        order.append(q.pop(q.peek()).tenant)
    assert order == ["b", "a", "b", "b"] or order == ["a", "b", "b", "b"]
    # the load-bearing claim: b's first pop is not followed by b,b while
    # a still waits
    assert order.count("a") == 1 and order.count("b") == 3
    assert order[:3].count("b") <= 2


def test_fair_queue_remove_iter_clear():
    q = tenancy.FairQueue()
    # distinct payloads: SimpleNamespace compares by value, and remove
    # must target THIS item, not an equal twin
    items = [SimpleNamespace(tenant="a", i=0),
             SimpleNamespace(tenant="b", i=1),
             SimpleNamespace(tenant="a", i=2)]
    for it in items:
        q.push(it)
    assert sorted(map(id, q)) == sorted(map(id, items))
    q.remove(items[2])  # mid-FIFO removal (deadline sweep)
    assert len(q) == 2
    with pytest.raises(ValueError):
        q.remove(items[2])
    assert q.tenants_queued() == {"a": 1, "b": 1}
    q.clear()
    assert not q


# -- fairness math -----------------------------------------------------------


def test_jain_index_properties():
    assert tenancy.jain_index([]) == 1.0
    assert tenancy.jain_index([0, 0]) == 1.0  # no traffic != unfair
    assert tenancy.jain_index([5]) == 1.0
    assert tenancy.jain_index([3, 3, 3]) == pytest.approx(1.0)
    # one tenant holds everything: 1/n
    assert tenancy.jain_index([9, 0, 0]) == pytest.approx(1.0)  # zeros drop
    assert tenancy.jain_index([400, 100]) == pytest.approx(
        500 ** 2 / (2 * (400 ** 2 + 100 ** 2)))


def test_fairness_window_is_weight_normalized():
    """A weight-2 tenant legitimately holding 2/3 of the tokens scores
    even with a weight-1 tenant holding 1/3 — Jain reads 1.0. With
    equal weights the same split reads 0.8."""
    t = [100.0]
    reg = tenancy.TenantRegistry(clock=lambda: t[0])
    reg.set_limits(tenancy.parse_limits({"big": {"weight": 2}}))
    reg.note_decode_tokens("big", 200)
    reg.note_decode_tokens("small", 100)
    f = reg.fairness()
    assert f["window_s"] == tenancy.FAIR_WINDOW_S
    assert f["active_tenants"] == 2
    assert f["jain_index"] == pytest.approx(1.0)
    assert f["share_max"] == pytest.approx(f["share_min"])
    # same split, equal weights: (0.75, 0.25) -> 1 / (2 * 0.625) = 0.8
    reg2 = tenancy.TenantRegistry(clock=lambda: t[0])
    reg2.note_decode_tokens("big", 300)
    reg2.note_decode_tokens("small", 100)
    assert reg2.fairness()["jain_index"] == pytest.approx(0.8)
    # the window slides: an hour later the shares are gone
    t[0] += 3600.0
    assert reg2.fairness()["active_tenants"] == 0
    assert reg2.fairness()["jain_index"] == 1.0


def test_publish_fairness_gauges():
    reg = tenancy.TenantRegistry()
    reg.note_decode_tokens("a", 10)
    reg.note_decode_tokens("b", 10)
    f = reg.publish_fairness()
    g = tm.registry()
    assert g.gauge(tm.TENANT_FAIRNESS_JAIN).value() == f["jain_index"]
    assert g.gauge(tm.TENANT_ACTIVE).value() == 2


# -- usage ledger ------------------------------------------------------------


def test_usage_ledger_interval_force_and_monotonic(tmp_path):
    t = [0.0]
    led = tenancy.UsageLedger(clock=lambda: t[0])
    reg = tenancy.TenantRegistry()
    path = tmp_path / "usage.jsonl"
    assert not led.enabled
    assert not led.maybe_write(reg)  # unconfigured: never writes
    led.configure(str(path), interval_s=10.0)
    assert led.enabled
    reg.note_decode_tokens("acme", 50)
    reg.note_prefill_tokens("acme", 5)
    t[0] = 15.0  # one interval past the (fresh) configure stamp
    assert led.maybe_write(reg)
    t[0] = 16.0
    assert not led.maybe_write(reg)      # interval not elapsed
    reg.note_decode_tokens("acme", 25)
    reg.note_shed("acme", "queue_full")
    assert led.maybe_write(reg, force=True)   # drain flush ignores it
    t[0] = 40.0
    reg.note_decode_tokens("zed", 10)
    assert led.maybe_write(reg)
    lines = [json.loads(ln) for ln in
             path.read_text().strip().splitlines()]
    assert [ln["seq"] for ln in lines] == [1, 2, 3]
    # cumulative + monotonic: a consumer may diff ANY two lines
    acme = [ln["tenants"]["acme"] for ln in lines]
    assert [a["decode_tokens"] for a in acme] == [50, 75, 75]
    assert acme[0]["prefill_tokens"] == 5
    assert [a["sheds"] for a in acme] == [0, 1, 1]
    for prev, cur in zip(acme, acme[1:]):
        for k in prev:
            assert cur[k] >= prev[k], k
    assert "zed" in lines[2]["tenants"]
    for ln in lines:
        assert ln["t_wall"] > 0 and ln["uptime_s"] >= 0
    # unconfigure: back to never writing
    led.configure(None)
    assert not led.enabled and not led.maybe_write(reg, force=True)


def test_snapshot_shape_and_metric_reconciliation():
    """Every note_* updates the in-process stats AND the matching
    dllama_tenant_* series with the same value in the same call."""
    reg = tenancy.registry()
    g = tm.registry()
    base_dec = g.counter(tm.TENANT_DECODE_TOKENS).total(tenant="acme")
    base_shed = g.counter(tm.TENANT_SHED).total(tenant="acme",
                                               reason="queue_full")
    reg.note_prefill_tokens("acme", 11)
    reg.note_decode_tokens("acme", 7)
    reg.note_admission("acme", 3.5)
    reg.note_ttft("acme", 42.0)
    reg.note_itl("acme", 9.0, n=6)
    reg.note_shed("acme", "queue_full")
    reg.note_timeout("acme")
    reg.note_spec("acme", drafted=8, accepted=5)
    reg.note_tick(2.0, {"acme": 3}, {"acme": 1})
    st = reg.snapshot()["tenants"]["acme"]
    assert st["prefill_tokens"] == 11
    assert st["decode_tokens"] == 7
    assert st["admissions"] == 1
    assert st["sheds"] == {"queue_full": 1}
    assert st["timeouts"] == 1
    assert st["kv_device_block_s"] == pytest.approx(6.0)
    assert st["kv_host_block_s"] == pytest.approx(2.0)
    assert st["spec_drafted"] == 8 and st["spec_accepted"] == 5
    assert st["queue_wait_ms"]["n"] == 1
    assert st["queue_wait_ms"]["sum"] == pytest.approx(3.5)
    assert st["ttft_ms"]["n"] == 1 and st["itl_ms"]["n"] == 6
    # the metric side carries the identical totals
    assert g.counter(tm.TENANT_DECODE_TOKENS).total(tenant="acme") \
        - base_dec == 7
    assert g.counter(tm.TENANT_SHED).total(
        tenant="acme", reason="queue_full") - base_shed == 1
    assert g.counter(tm.TENANT_KV_BLOCK_SECONDS).total(
        tenant="acme", tier="device") >= 6.0
    assert g.gauge(tm.TENANT_QUEUE_WAIT_MS).value(
        tenant="acme", q="p95") > 0


# -- scheduler integration ---------------------------------------------------


PATHS = {}


@pytest.fixture(scope="module")
def engine(tmp_path_factory):
    d = tmp_path_factory.mktemp("tenancy")
    mpath, tpath = d / "m.m", d / "t.t"
    rng = np.random.default_rng(23)
    write_tiny_model(mpath, tiny_header_params(vocab_size=268, seq_len=96),
                     rng)
    tfile.write_tfile(tpath, byte_vocab_tokenizer())
    PATHS["m"], PATHS["t"] = str(mpath), str(tpath)
    return InferenceEngine(str(mpath), str(tpath), tp=1)


def _enc(engine, p):
    return engine.tokenizer.encode(p, is_start=True)


def test_conservation_mixed_tenants(engine):
    """ISSUE-20 satellite: under mixed multi-tenant continuous batching
    the per-tenant decode/admission/queue-wait sums reconcile EXACTLY
    with the tenant-blind global counters — same site, same value."""
    g = tm.registry()
    base_batch = g.counter(tm.BATCH_TOKENS).total()
    base_adm = g.counter(tm.ADMISSIONS).total()
    base_wait_n = g.histogram(tm.QUEUE_WAIT_MS).count()
    plan = [("acme", "hello", 6), ("acme", " world", 4),
            ("zed", "hello world", 5), ("zed", "hell", 7),
            ("acme", "he", 3), (tenancy.ANON, " w", 6)]
    # the dllama_tenant_* series are process-global: earlier tests may
    # have used the same labels, so reconcile on deltas
    base_tdec = {t: g.counter(tm.TENANT_DECODE_TOKENS).total(tenant=t)
                 for t, _, _ in plan}
    sched = BatchScheduler(engine, n_slots=2)
    try:
        reqs = [sched.submit(_enc(engine, p), n, stop_on_eos=False,
                             tenant=t) for t, p, n in plan]
        for r in reqs:
            assert r.done.wait(timeout=300)
            assert r.error is None
    finally:
        sched.close()
    snap = tenancy.registry().snapshot()["tenants"]
    want_tokens = {}
    for (t, _, _), r in zip(plan, reqs):
        want_tokens[t] = want_tokens.get(t, 0) + len(r.tokens)
    # bit-exact conservation against the global counters
    assert sum(st["decode_tokens"] for st in snap.values()) \
        == g.counter(tm.BATCH_TOKENS).total() - base_batch
    assert sum(st["admissions"] for st in snap.values()) \
        == g.counter(tm.ADMISSIONS).total() - base_adm == len(plan)
    assert sum(st["queue_wait_ms"]["n"] for st in snap.values()) \
        == g.histogram(tm.QUEUE_WAIT_MS).count() - base_wait_n
    # per-tenant attribution matches what each request actually emitted
    for t, want in want_tokens.items():
        assert snap[t]["decode_tokens"] == want, t
        # ... and the metric series carries the identical number
        assert g.counter(tm.TENANT_DECODE_TOKENS).total(tenant=t) \
            - base_tdec[t] == want, t
    assert snap["acme"]["admissions"] == 3
    assert snap["zed"]["admissions"] == 2
    assert snap[tenancy.ANON]["admissions"] == 1


def test_queued_timeout_attributed_to_tenant(engine):
    g = tm.registry()
    base = g.counter(tm.REQUEST_TIMEOUTS).total()
    sched = BatchScheduler(engine, n_slots=1)
    try:
        long = sched.submit(_enc(engine, "hello world"), 40,
                            stop_on_eos=False, tenant="patient")
        hasty = sched.submit(_enc(engine, "hello"), 4, stop_on_eos=False,
                             timeout_s=0.05, tenant="hasty")
        assert hasty.done.wait(timeout=60)
        assert hasty.timed_out
        assert long.done.wait(timeout=300)
    finally:
        sched.close()
    snap = tenancy.registry().snapshot()["tenants"]
    assert snap["hasty"]["timeouts"] == 1
    assert snap.get("patient", {}).get("timeouts", 0) == 0
    assert g.counter(tm.REQUEST_TIMEOUTS).total() - base == 1
    assert g.counter(tm.TENANT_TIMEOUTS).total(tenant="hasty") == 1
    # the timeout decision in the flight ring names the tenant
    evs = [e for e in sched.flight.snapshot()["events"]
           if e["event"] == "timeout"]
    assert evs and evs[-1]["tenant"] == "hasty"


def test_rate_budget_sheds_only_that_tenant(engine):
    """A tenant over its --tenant-limits token budget gets a per-tenant
    429 (TenantOverBudgetError IS a QueueFullError — the api layer's
    backpressure shape is shared); other tenants are untouched."""
    g = tm.registry()
    base_shed = g.counter(tm.REQUESTS_SHED).total()
    sched = BatchScheduler(
        engine, n_slots=2,
        tenant_limits=tenancy.parse_limits(
            {"metered": {"tokens_per_s": 1.0}}))
    try:
        ids = _enc(engine, "hello")
        with pytest.raises(TenantOverBudgetError) as e:
            sched.submit(ids, 8, tenant="metered")
        assert isinstance(e.value, QueueFullError)  # the 429 contract
        assert "metered" in str(e.value)
        # the shed is attributed: registry + metric + flight decision
        snap = tenancy.registry().snapshot()["tenants"]["metered"]
        assert snap["sheds"] == {"tenant_rate_budget": 1}
        assert g.counter(tm.REQUESTS_SHED).total() - base_shed == 1
        assert g.counter(tm.TENANT_SHED).total(
            tenant="metered", reason="tenant_rate_budget") == 1
        evs = [e for e in sched.flight.snapshot()["events"]
               if e["event"] == "shed"]
        assert evs[-1]["reason"] == "tenant_rate_budget"
        assert evs[-1]["tenant"] == "metered"
        # an unlimited tenant sails through on the same scheduler
        ok = sched.submit(ids, 4, stop_on_eos=False, tenant="unmetered")
        assert ok.done.wait(timeout=300) and ok.error is None
    finally:
        sched.close()


def test_slot_cap_defers_without_blocking_others(engine):
    """A tenant at its max_slots cap is SKIPPED (defer decision with
    tenant + reason in the flight ring), not a barrier: other tenants
    keep admitting past it, and the capped tenant still finishes."""
    sched = BatchScheduler(
        engine, n_slots=2,
        tenant_limits=tenancy.parse_limits(
            {"capped": {"max_slots": 1}}))
    try:
        ids = _enc(engine, "hello")
        # staggered lengths: the free tenant's short requests retire
        # while the capped tenant's long one still runs, so its next
        # queue head is PROPOSED at the cap — the defer must fire
        capped = [sched.submit(ids, n, stop_on_eos=False, tenant="capped")
                  for n in (16, 6, 6)]
        free = [sched.submit(ids, 3, stop_on_eos=False, tenant="free")
                for _ in range(2)]
        for r in capped + free:
            assert r.done.wait(timeout=300)
            assert r.error is None
    finally:
        sched.close()
    evs = [e for e in sched.flight.snapshot()["events"]
           if e["event"] == "defer"
           and e.get("reason") == "tenant_slot_cap"]
    assert evs, "the slot-cap defer decision never hit the flight ring"
    assert all(e["tenant"] == "capped" for e in evs)
    # cap honored: "capped" never held both slots, so "free" always
    # had one available — its queue wait stays bounded by one request
    snap = tenancy.registry().snapshot()["tenants"]
    assert snap["capped"]["admissions"] == 3
    assert snap["free"]["admissions"] == 2


def _queue_p95(tenant):
    st = tenancy.registry().snapshot()["tenants"][tenant]
    return st["queue_wait_ms"]["p95"]


def test_contention_flooder_cannot_starve_light(engine, tmp_path):
    """THE acceptance scenario: a flooding tenant dumping a burst of
    requests cannot starve a light interactive tenant. Weighted
    round-robin keeps the light tenant's queue-wait p95 within 2x its
    solo baseline (plus a CPU-tier tick floor), Jain's index over the
    wave's decode tokens stays >= 0.8, every defer/shed decision in the
    flight ring is machine-attributed, the per-tenant totals reconcile
    bit-exactly with the global counter, and the usage ledger kept
    writing monotonic lines throughout."""
    limits = tenancy.parse_limits({"light": {"weight": 4.0},
                                   "flood": {"weight": 1.0}})
    ids_f = _enc(engine, "hello world")
    ids_l = _enc(engine, "hello")

    # solo baseline: the light tenant's staggered trickle, alone
    solo = BatchScheduler(engine, n_slots=2, tenant_limits=limits)
    try:
        rs = []
        for _ in range(6):
            rs.append(solo.submit(ids_l, 6, stop_on_eos=False,
                                  tenant="light"))
            time.sleep(0.03)
        for r in rs:
            assert r.done.wait(timeout=300) and r.error is None
        solo_p95 = _queue_p95("light")
    finally:
        solo.close()

    tenancy.reset()
    ledger_path = tmp_path / "usage.jsonl"
    tenancy.ledger().configure(str(ledger_path), interval_s=0.05)
    g = tm.registry()
    base_batch = g.counter(tm.BATCH_TOKENS).total()
    sched = BatchScheduler(engine, n_slots=2, tenant_limits=limits)
    try:
        flood = [sched.submit(ids_f, 6, stop_on_eos=False, tenant="flood")
                 for _ in range(12)]
        lights = []
        for _ in range(6):
            lights.append(sched.submit(ids_l, 6, stop_on_eos=False,
                                       tenant="light"))
            time.sleep(0.03)
        for r in flood + lights:
            assert r.done.wait(timeout=300)
            assert r.error is None
    finally:
        sched.close()

    snap = tenancy.registry().snapshot()["tenants"]
    # no starvation: the light tenant's waits stay near its solo run
    # (the floor absorbs CPU-tier tick jitter on the tiny model — a
    # FIFO queue behind 12 flooder requests would be far past it)
    light_p95 = snap["light"]["queue_wait_ms"]["p95"]
    assert light_p95 <= 2.0 * max(solo_p95, 250.0), \
        f"light p95 {light_p95:.0f}ms vs solo {solo_p95:.0f}ms"
    assert light_p95 <= snap["flood"]["queue_wait_ms"]["p95"] * 1.5 + 1.0
    # the wave was served fairly: 72 vs 36 demanded tokens -> 0.9
    jain = tenancy.jain_index([snap["flood"]["decode_tokens"],
                               snap["light"]["decode_tokens"]])
    assert jain >= 0.8, jain
    # bit-exact conservation under contention
    assert snap["flood"]["decode_tokens"] + snap["light"]["decode_tokens"] \
        == g.counter(tm.BATCH_TOKENS).total() - base_batch
    # every admission decision in the ring is machine-attributed
    for e in sched.flight.snapshot()["events"]:
        if e["event"] in ("defer", "shed", "requeue", "preempt"):
            assert e["reason"] in tenancy.ADMIT_REASONS, e
            assert e.get("tenant"), e
    # fairness gauges published from the tick loop
    assert 0.0 < g.gauge(tm.TENANT_FAIRNESS_JAIN).value() <= 1.0
    # the ledger kept its cadence and stayed monotonic; close() forced
    # a final drain line with the full totals
    lines = [json.loads(ln) for ln in
             ledger_path.read_text().strip().splitlines()]
    assert len(lines) >= 2
    assert [ln["seq"] for ln in lines] \
        == sorted(ln["seq"] for ln in lines)
    for prev, cur in zip(lines, lines[1:]):
        for t, st in prev["tenants"].items():
            for k, v in st.items():
                assert cur["tenants"][t][k] >= v, (t, k)
    final = lines[-1]["tenants"]
    assert final["flood"]["decode_tokens"] == snap["flood"]["decode_tokens"]
    assert final["light"]["decode_tokens"] == snap["light"]["decode_tokens"]
