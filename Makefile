# The machine-readable "this is how you run it" surface (the reference
# encodes the same contract in .github/workflows/main.yml:28-63: build the
# test binaries, run each on every platform).
#
#   make test       CPU tier: the full suite (incl. @slow macbeth-scale
#                   transcripts) on the 8-device virtual CPU mesh
#                   (tests/conftest.py forces the platform) — every
#                   sharding/collective path, no hardware needed.
#   make test-tpu   Hardware tier: @tpu-marked kernel/numerics tests on the
#                   real chip (compiles actual Pallas kernels).
#   make test-all   Both CPU tiers, then the TPU tier if a chip answers.
#   make native     Build the C++ host-runtime library (quant codecs, BPE).
#   make lint       The unified dlint static-analysis suite
#                   (python -m tools.dlint; catalog in LINTS.md): the
#                   trace-safety analyzer (closed-world jit entry through
#                   plan_scoped_jit / the shard_map shim, tracer-hazard
#                   detection in traced bodies, guarded-twin tripwire
#                   completeness), the thread-ownership analyzer
#                   (owner=loop/monitor/any call-graph checking,
#                   guarded-by lock discipline, lock-order cycles), and
#                   the six historical scanners (metric names, exception
#                   hygiene, route labels, failpoint sites, span phases,
#                   shard_map shim) consolidated as rules. One rule:
#                   python -m tools.dlint --only RULE; CI summary: --json.
#   make bench      The driver's benchmark: ONE JSON line on stdout.
#   make perf-check The perf-regression sentinel: run the bench and
#                   compare against the committed PERF_BASELINE.json
#                   (tools/perf_baseline.py). Exits nonzero naming any
#                   regressed metric; a no-hardware run is first-class
#                   "no evidence" and stays green. Re-record with
#                   `python bench.py --baseline update` after a
#                   deliberate perf change lands ON CHIP.
#   make quality-check  The quality-regression sentinel: the built-in
#                   fixture eval (deterministic tiny model over
#                   tests/goldens/eval_tiny.jsonl, every config in
#                   telemetry.EVAL_CONFIGS) checked against the
#                   committed QUALITY_BASELINE.json
#                   (tools/quality_baseline.py). Exits nonzero naming a
#                   perplexity regression beyond the documented
#                   tolerance or any bit-level parity drift between
#                   exact-parity configs. Re-record with
#                   `python tools/quality_baseline.py record` after a
#                   deliberate numerics change.
#   make graft      Compile-check the jittable entry + the 8-device
#                   multi-chip dry run (tp/pp/dp/sp/ep shardings).

PY ?= python

.PHONY: test test-tpu test-all native tsan bench perf-check quality-check graft lint clean

test:
	$(PY) -m pytest tests/ -q

test-tpu:
	DLLAMA_TESTS_TPU=1 $(PY) -m pytest tests/ -m tpu -q

test-all: test test-tpu

native:
	$(PY) -c 'from dllama_tpu import native; print(native.get_lib() or "native build unavailable (g++ missing?)")'

tsan:
	$(MAKE) -C dllama_tpu/native tsan
	TSAN_OPTIONS="halt_on_error=1 exitcode=66" ./dllama_tpu/native/tsan_stress

lint:
	$(PY) -m tools.dlint

bench:
	$(PY) bench.py

perf-check:
	$(PY) bench.py --baseline check

quality-check:
	JAX_PLATFORMS=cpu $(PY) tools/quality_baseline.py check

graft:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	    $(PY) __graft_entry__.py

clean:
	$(MAKE) -C dllama_tpu/native clean
	rm -rf build dist *.egg-info
