"""Turbo quant mode: the reference's integer-dot philosophy on the MXU.

The reference computes Q80 activations x Q40 weights with int8 multiplies
and per-block f32 scale epilogues (matmul_Q80_Q40_F32,
src/nn/nn-cpu-ops.cpp:229-447).  The round-4 on-chip profile showed this
repo's fast path (XLA-fused bf16 dequant) running VPU-limited: the
convert+scale work per code caps effective weight streaming at ~450-750
GB/s of the chip's 819.  Turbo mode removes the per-element dequant from
the hot loop the same way the reference does — integer dots, scales
applied at the output:

* at load, each Q40 plane requantizes to **per-column int8**
  (``w8[k, n] = round(dense[k, n] / scale[n])``, ``scale[n] =
  colmax/127``): same 1 B/weight HBM footprint, no per-element scale work
  left in the matmul;
* ``a8`` activations quantize per row to int8 (the Q80 idea at row
  granularity) and the dot runs s8 x s8 -> s32 on the MXU, with one
  ``sx * scale[n]`` f32 multiply per OUTPUT element;
* ``a16`` keeps bf16 activations (no activation quantization error): the
  dot still skips the scale multiply per element (one s8->bf16 convert
  remains), halving the VPU work of the fast path.

Numerics: per-column 8-bit requantization of 4-bit block codes adds
bounded drift (abs error <= colmax/254 per weight; tests bound the output
RMS drift) — turbo is OPT-IN via ``DLLAMA_TPU_QUANT_MODE=turbo`` (a8) /
``turbo16`` (a16) and never the default. Exact/fast modes are unaffected.
The a8/a16 choice is captured IN the weight at derivation time (pytree aux
data), so later env changes cannot silently flip serving numerics.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .linear import QuantizedWeight


@jax.tree_util.register_pytree_node_class
class TurboWeight:
    """Per-column-requantized int8 weight, K-major like QuantizedWeight.

    ``w8``: int8 ``[..., in, out]``; ``scale``: f32 ``[..., out]`` with
    ``dense[k, n] ~= w8[k, n] * scale[n]``; ``a8`` (static aux data):
    whether the matmul quantizes activations to int8 for an s8 x s8 MXU
    dot, fixed when the weight was derived."""

    def __init__(self, w8, scale, a8: bool):
        self.w8 = w8
        self.scale = scale
        self.a8 = bool(a8)

    def tree_flatten(self):
        return (self.w8, self.scale), self.a8

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux)

    @property
    def out_features(self) -> int:
        return self.w8.shape[-1]

    @property
    def in_features(self) -> int:
        return self.w8.shape[-2]

    def __repr__(self) -> str:  # debugging / test failure messages
        return (f"TurboWeight(w8={getattr(self.w8, 'shape', self.w8)}, "
                f"scale={getattr(self.scale, 'shape', self.scale)}, "
                f"a8={self.a8})")


def _derive_one(qw: QuantizedWeight):
    """One [K, N] plane -> per-column int8 (jittable; bf16/f32 scales ok)."""
    from .linear import dequantize_weight

    dense = dequantize_weight(qw, dtype=jnp.float32)  # [K, N]
    amax = jnp.max(jnp.abs(dense), axis=-2)  # [N]
    scale = jnp.where(amax > 0.0, amax / 127.0, 1.0)
    w8 = jnp.clip(jnp.round(dense / scale[None, :]), -127, 127).astype(jnp.int8)
    return w8, scale


def derive_turbo(qw: QuantizedWeight, a8: bool = True,
                 free_source: bool = False) -> TurboWeight:
    """Requantize a (possibly layer/expert-stacked) Q40 weight to TurboWeight.

    Stacked planes convert one (layer[, expert]) plane at a time
    (``lax.map`` over the flattened leading axes) so the dense f32
    intermediate is bounded by ONE plane, not the whole stack (an 8B stack
    would need ~30 GB dense).  ``free_source`` deletes the source plane
    buffers right after the derived arrays materialize, so a whole-tree
    conversion transiently holds at most one extra leaf, not a second copy
    of the model (runtime.hbm charges that bound)."""
    if qw.codes.ndim == 2:
        w8, scale = jax.jit(_derive_one)(qw)
    else:
        lead = qw.codes.shape[:-2]  # [L] or [L, E] (MoE expert stacks)

        def one(args):
            return _derive_one(QuantizedWeight(scales=args[0], codes=args[1]))

        def mapped(s, c):
            s = s.reshape((-1,) + s.shape[len(lead):])
            c = c.reshape((-1,) + c.shape[len(lead):])
            w8_f, scale_f = jax.lax.map(one, (s, c))
            return (w8_f.reshape(lead + w8_f.shape[1:]),
                    scale_f.reshape(lead + scale_f.shape[1:]))

        w8, scale = jax.jit(mapped)(qw.scales, qw.codes)
    if free_source:
        # fetch-forced sync, NOT block_until_ready: on the axon tunnel
        # block_until_ready returns without waiting for device execution
        # (bench.py round-4 finding), which would let tree_map enqueue the
        # next leaf's derivation while this one's dense f32 intermediate is
        # still in flight — breaking the one-extra-leaf transient HBM bound
        # runtime.hbm charges. device_get of a value that data-depends on
        # w8 cannot return until the derivation actually ran.
        jax.device_get(w8[(0,) * w8.ndim])
        qw.codes.delete()
        qw.scales.delete()
    else:
        jax.block_until_ready(w8)
    return TurboWeight(w8, scale, a8)


def turbo_params(params, a8: bool = True, free_source: bool = True):
    """Convert every QuantizedWeight leaf of a Params tree to TurboWeight.

    Leaves convert one at a time with their source buffers freed as soon as
    each derived leaf lands (see derive_turbo) — the caller must treat the
    INPUT tree as consumed."""
    return jax.tree_util.tree_map(
        lambda leaf: (derive_turbo(leaf, a8=a8, free_source=free_source)
                      if isinstance(leaf, QuantizedWeight) else leaf),
        params, is_leaf=lambda x: isinstance(x, QuantizedWeight))


def quantize_activations_a8(x: jax.Array):
    """Per-row int8 activation quantization (the Q80 idea at row
    granularity): returns ``(xq int8, sx f32[..., 1])`` with
    ``x ~= xq * sx``. The ONE implementation of the a8 prologue — both the
    dense turbo matmul and the MoE gather-regime dot share it."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    sx = jnp.where(amax > 0.0, amax / 127.0, 1.0)
    xq = jnp.clip(jnp.round(xf / sx), -127, 127).astype(jnp.int8)
    return xq, sx


def turbo_matmul(x: jax.Array, w: TurboWeight) -> jax.Array:
    """``y[..., N] = x[..., K] @ (w8 * scale)`` without per-element dequant.

    The a8/a16 choice rides ON the weight (aux data — a static under jit):
    a8 = row-quantized int8 activations + s8 x s8 -> s32 MXU dot (the
    reference's integer-dot shape); a16 = bf16 x s8->bf16 with the scale in
    the f32 epilogue."""
    out_dtype = x.dtype
    if w.a8:
        xq, sx = quantize_activations_a8(x)
        acc = jax.lax.dot_general(
            xq, w.w8,
            dimension_numbers=(((xq.ndim - 1,), (w.w8.ndim - 2,)), ((), ())),
            preferred_element_type=jnp.int32)
        out = acc.astype(jnp.float32) * sx * w.scale
    else:
        wd = w.w8.astype(jnp.bfloat16)
        acc = jax.lax.dot_general(
            x.astype(jnp.bfloat16), wd,
            dimension_numbers=(((x.ndim - 1,), (wd.ndim - 2,)), ((), ())),
            preferred_element_type=jnp.float32)
        out = acc * w.scale
    return out.astype(out_dtype)
