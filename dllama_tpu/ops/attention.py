"""Causal GQA attention over a preallocated KV cache.

Semantics match the reference's OP_MULTIHEAD_ATT (reference: multiheadAtt_F32,
src/nn/nn-cpu-ops.cpp:751-786): per head, scores ``q·k / sqrt(head_dim)`` over
cache positions ``0..pos``, float32 softmax, weighted V sum; GQA via the
``kv_mul`` head-group factor. The serial per-position loop becomes one batched
einsum pair so XLA maps it onto the MXU; masking replaces the loop bound.

This XLA implementation is the semantics oracle; the Pallas flash-attention
kernel in :mod:`dllama_tpu.ops.flash_attention` must match it bit-for-bit in
f32 (tested the way nn-vulkan-test.cpp checks GPU ops against expectations).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def attention(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
              positions: jax.Array, head_dim: int) -> jax.Array:
    """Attend ``q: [B, T, n_heads, head_dim]`` over cached
    ``k/v: [B, n_kv_heads, S, head_dim]`` (head-major, see runtime.kvcache).

    ``positions: [B, T]`` is the absolute position of each query row; cache
    entries at ``s <= position`` are visible (the reference's ``t <= pos`` loop
    bound), which assumes the cache holds keys for positions ``0..pos``.
    """
    B, T, n_heads, _ = q.shape
    n_kv = k_cache.shape[1]
    S = k_cache.shape[2]
    kv_mul = n_heads // n_kv

    qg = q.reshape(B, T, n_kv, kv_mul, head_dim)
    scores = jnp.einsum("btkmh,bksh->btkms", qg.astype(jnp.float32),
                        k_cache.astype(jnp.float32))
    scores = scores / jnp.sqrt(jnp.float32(head_dim))

    mask = jnp.arange(S)[None, None, :] <= positions[:, :, None]  # [B, T, S]
    scores = jnp.where(mask[:, :, None, None, :], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)

    out = jnp.einsum("btkms,bksh->btkmh", probs, v_cache.astype(jnp.float32))
    return out.reshape(B, T, n_heads, head_dim).astype(q.dtype)
