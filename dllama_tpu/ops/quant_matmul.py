"""Pallas TPU kernel: Q40 weight-dequantizing matmul.

The TPU replacement for the reference's Q80×Q40 integer-dot kernels
(reference: matmul_Q80_Q40_F32, src/nn/nn-cpu-ops.cpp:229-447, and the
llamafile sgemm prefill path, SURVEY.md §2 #7): weights stream from HBM in
their K-major plane layout (int8 codes ``[K, N]`` + f32 scales ``[K/32, N]``)
and are dequantized in VMEM right before hitting the MXU — the dense weight
never exists in HBM, so the matmul moves ~3.5× fewer bytes than a dense-f32
weight would.

Kernel shape: ``y[M, N] = x[M, K] @ dequant(codes, scales)``

Grid ``(N // BN, K // BK)``; each step:

1. expands the step's scale block to ``[BK, BN]`` via a tiny MXU matmul with a
   constant 0/1 sublane-expansion matrix ``E[BK, BK/32]`` (this Mosaic
   toolchain rejects reshape-broadcast and ``jnp.repeat`` lowerings, and
   ``pltpu.repeat`` has tile-repeat — not element-repeat — semantics);
2. dequantizes codes on the VPU (``codes * sexp``);
3. accumulates ``x_blk @ wd`` into the revisited f32 output tile.

Both dots run at ``Precision.HIGHEST`` — measured ~2e-5 absolute error vs the
exact host oracle on real hardware (default MXU precision loses ~3e-3).
K-major layout is what makes every operand block-indexable: the out-major
layout needed narrow f16/f32 scale blocks or in-kernel dynamic slices, both
of which this Mosaic build refuses to lower.

Falls back to the XLA dequant+dot path (ops.linear) when shapes don't fit the
tile grid; parity is tested in tests/test_quant_matmul.py the way
nn-vulkan-test.cpp checks GPU ops against the CPU reference.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..parallel.api import shard_map
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..formats.quants import Q40_BLOCK_SIZE
from .linear import QuantizedWeight

_HIGHEST = jax.lax.Precision.HIGHEST


def _kernel(x_ref, codes_ref, scales_ref, expand_ref, out_ref, *, fast: bool):
    """One (n, k) grid step: out[M, BN] += x[M, BK] @ dequant(W[BK, BN]).

    ``fast=False`` (exact/parity mode): f32 dequant, both dots at
    ``Precision.HIGHEST`` (~6 bf16 MXU passes per dot) — matches the host
    oracle to ~2e-5.  ``fast=True`` (serving mode): dequant lands in bf16 and
    the main dot runs ONE default-precision MXU pass with f32 accumulation —
    the TPU analogue of the reference's integer-dot philosophy (Q80×Q40
    int8-dot with f32 per-block scale epilogue, nn-cpu-ops.cpp:229-447):
    low-precision multiplies, full-precision accumulate, scales applied at
    block granularity.
    """
    k = pl.program_id(1)

    # element-repeat each scale 32× along K (sublanes) as a 0/1 matmul; each
    # output is a single selected scale (no accumulation), so HIGHEST here
    # costs little and keeps exact-mode scales bit-clean
    sexp = jax.lax.dot_general(
        expand_ref[:], scales_ref[:],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=_HIGHEST)

    if fast:
        wd = codes_ref[:].astype(jnp.bfloat16) * sexp.astype(jnp.bfloat16)
        partial = jax.lax.dot_general(
            x_ref[:], wd,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:
        wd = codes_ref[:].astype(jnp.float32) * sexp
        partial = jax.lax.dot_general(
            x_ref[:], wd,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_HIGHEST)

    @pl.when(k == 0)
    def _():
        out_ref[:] = partial

    @pl.when(k != 0)
    def _():
        out_ref[:] += partial


# default tile candidates, largest first (gemv_sweep picks these)
BN_CANDIDATES = (512, 256, 128)
BK_CANDIDATES = (512, 256, 128)


def _decode_kernel(x_ref, codes_ref, scales_ref, expand_ref, out_ref, wd_ref,
                   *, bk_e: int, fast: bool):
    """One n-column stripe of the DECODE-shaped fused dequant-GEMV.

    Unlike :func:`_kernel`'s (n, k) grid, the decode kernel keeps the whole
    K axis in one block: the grid walks N only, each step streams the full
    ``[K, bn]`` code stripe from HBM once, dequantizes it in-register into
    the ``wd`` VMEM scratch (chunked scale expansion — the ``[K, K/32]``
    expansion matrix of the full-K trick would itself be MBs), and runs ONE
    dot over the whole contraction. No revisited output tile, no k-step
    read-modify-write: the kernel is a single pass over the weight planes,
    which is exactly the decode regime's byte budget (weights dominate; the
    T<=16 activation rides along in VMEM).

    The single full-K dot is also what makes the kernel bit-parity with the
    XLA fused-dequant reference (ops.linear's dequant+dot fallback) instead
    of merely close: the blocked k-accumulation of :func:`_kernel` sums
    partials in a different order. Exact mode dequantizes at the activation
    dtype (the reference's rule) with a HIGHEST dot — BITWISE vs the
    reference on f32 activation graphs (the golden-parity configuration);
    a bf16 graph is drift-bounded instead, because XLA's in-jaxpr fusion
    may elide the bf16 dequant rounding on either side. Fast mode: bf16
    dequant, one default-precision MXU pass, f32 accumulation —
    drift-bounded for the same reason.
    """
    K = codes_ref.shape[0]
    g = bk_e // Q40_BLOCK_SIZE
    # chunked scale expansion: static python loop (K//bk_e is trace-time),
    # each chunk element-repeats its scale rows 32x via the 0/1 matmul and
    # lands the dequantized stripe in the wd scratch
    wd_dt = wd_ref.dtype  # bf16 in fast mode, the activation dtype in exact
    for i in range(K // bk_e):
        sexp = jax.lax.dot_general(
            expand_ref[:], scales_ref[i * g:(i + 1) * g, :],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_HIGHEST)
        codes = codes_ref[i * bk_e:(i + 1) * bk_e, :]
        wd_ref[i * bk_e:(i + 1) * bk_e, :] = (codes.astype(wd_dt)
                                              * sexp.astype(wd_dt))
    if fast:
        out_ref[:] = jax.lax.dot_general(
            x_ref[:], wd_ref[:],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:
        out_ref[:] = jax.lax.dot_general(
            x_ref[:], wd_ref[:],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_HIGHEST)


# Widest dispatch that counts as the decode regime for the fused kernel:
# single steps (T=1), fused-chunk scan bodies, speculative verifies
# (T=K+1, small) — the same rule as models.llama._OVERLAP_MAX_WIDTH.
FUSED_MAX_M = 16

# VMEM budget for the decode kernel's resident set: wd scratch + the
# double-buffered code stripe + the full-K activation block must leave
# room for Mosaic's own pipelining (~16MB/core total).
_FUSED_VMEM_BUDGET = 10 * 1024 * 1024


def _decode_blocks(M: int, K: int, N: int,
                   fast: bool) -> tuple[int, int] | None:
    """``(bn, bk_e)`` for the decode kernel, or None when the shape doesn't
    fit: bn is the largest 128-multiple (or whole-N, >=8-aligned) dividing N
    whose resident set fits the VMEM budget; bk_e the largest expansion
    chunk dividing K."""
    if not (0 < M <= FUSED_MAX_M) or K % Q40_BLOCK_SIZE:
        return None
    bk_e = next((c for c in (512, 256, 128, 64, 32) if K % c == 0), None)
    if bk_e is None:
        return None
    wd_bytes = 2 if fast else 4
    x_bytes = M * K * (2 if fast else 4)
    for bn in BN_CANDIDATES + ((N,) if N % 8 == 0 else ()):
        if N % bn:
            continue
        resident = K * bn * (wd_bytes + 2) + x_bytes  # wd + 2x codes + x
        if resident <= _FUSED_VMEM_BUDGET:
            return bn, bk_e
    return None


# dlint: static-fn (shape gate; w may carry ShapeDtypeStruct leaves)
def supports_decode(x_shape: tuple[int, ...], w: QuantizedWeight,
                    fast: bool = False) -> bool:
    """Whether the decode-shaped fused kernel covers these shapes."""
    K = x_shape[-1]
    M = 1
    for d in x_shape[:-1]:
        M *= d
    return (w.codes.ndim == 2 and w.in_features == K
            and _decode_blocks(M, K, w.out_features, fast) is not None)


def _decode_call(xf: jax.Array, w: QuantizedWeight, *, interpret: bool,
                 fast: bool) -> jax.Array:
    """Dispatch the decode kernel over ``xf [M, K]`` (already cast).

    Exact mode dequantizes at the ACTIVATION dtype — the same rule as the
    XLA reference (``dequantize_weight(w, dtype=x.dtype)``), so an
    exact-mode bf16 graph gets bf16 dequant on both paths instead of the
    kernel silently upgrading to f32 and breaking xla↔fused identity."""
    M, K = xf.shape
    N = w.out_features
    bn, bk_e = _decode_blocks(M, K, N, fast)
    wd_dtype = jnp.bfloat16 if fast else xf.dtype
    return pl.pallas_call(
        functools.partial(_decode_kernel, bk_e=bk_e, fast=fast),
        grid=(N // bn,),
        in_specs=[
            pl.BlockSpec((M, K), lambda n: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((K, bn), lambda n: (0, n), memory_space=pltpu.VMEM),
            pl.BlockSpec((K // Q40_BLOCK_SIZE, bn), lambda n: (0, n),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bk_e, bk_e // Q40_BLOCK_SIZE), lambda n: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((M, bn), lambda n: (0, n),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((M, N), jnp.float32),
        scratch_shapes=[pltpu.VMEM((K, bn), wd_dtype)],
        interpret=interpret,
    )(xf, w.codes, w.scales.astype(jnp.float32), _expansion_matrix(bk_e))


def _pick_block(dim: int, candidates: tuple[int, ...], min_align: int) -> int | None:
    """A 128-aligned block dividing ``dim``, or the whole dim (Mosaic allows a
    block equal to the array extent) when it at least meets ``min_align``."""
    for c in candidates:
        if dim % c == 0:
            return c
    if dim % min_align == 0:
        return dim
    return None


@functools.lru_cache(maxsize=8)
def _expansion_matrix(bk: int) -> np.ndarray:
    """0/1 matrix ``E[bk, bk/32]`` with ``E[32i:32(i+1), i] = 1``.

    Returns numpy (not jnp): this is called during traces, where caching a
    jnp constant would leak a tracer."""
    return np.kron(np.eye(bk // Q40_BLOCK_SIZE, dtype=np.float32),
                   np.ones((Q40_BLOCK_SIZE, 1), np.float32))


@functools.partial(jax.jit,
                   static_argnames=("interpret", "fast", "bn", "bk", "fused"))
def quant_matmul(x: jax.Array, w: QuantizedWeight, *, interpret: bool = False,
                 fast: bool = False, bn: int | None = None,
                 bk: int | None = None, fused: bool = False) -> jax.Array:
    """``y[..., N] = x[..., K] @ dequant(w)`` via the Pallas kernel.

    ``fast=False``: ``x`` is cast to f32 for the dequantized dot (parity with
    the XLA exact path). ``fast=True``: bf16 operands, one MXU pass, f32
    accumulation (see _kernel). Leading dims flatten into M.  ``bn``/``bk``
    override the tile picks (tools/gemv_sweep.py measures the candidates).
    ``fused=True`` prefers the decode-shaped full-K kernel
    (:func:`_decode_kernel` — bit-parity with the XLA fused-dequant
    reference) when :func:`supports_decode` holds, falling back to the
    (n, k)-tiled kernel otherwise, so a ``fused``-mode dispatch never
    fails on a prefill-wide shape.
    """
    *lead, K = x.shape
    N = w.out_features
    M = 1
    for d in lead:
        M *= d

    if fused and bn is None and bk is None \
            and _decode_blocks(M, K, N, fast) is not None:
        # fast casts to bf16; exact keeps the activation dtype (the XLA
        # reference dequantizes at x.dtype — see _decode_call)
        xf = x.reshape(M, K)
        if fast:
            xf = xf.astype(jnp.bfloat16)
        out = _decode_call(xf, w, interpret=interpret, fast=fast)
        return out.reshape(*lead, N).astype(x.dtype)

    bn = bn or _pick_block(N, BN_CANDIDATES, min_align=8)
    bk = bk or _pick_block(K, BK_CANDIDATES, min_align=Q40_BLOCK_SIZE)
    if bn is None or bk is None:
        raise ValueError(f"shapes N={N}, K={K} do not fit the tile grid")
    if N % bn or K % bk or bk % Q40_BLOCK_SIZE:
        # overrides included: a non-dividing block would truncate the grid
        # and return uninitialized output columns
        raise ValueError(f"blocks bn={bn}, bk={bk} do not tile N={N}, K={K}")

    xf = x.reshape(M, K).astype(jnp.bfloat16 if fast else jnp.float32)
    grid = (N // bn, K // bk)

    out = pl.pallas_call(
        functools.partial(_kernel, fast=fast),
        grid=grid,
        in_specs=[
            pl.BlockSpec((M, bk), lambda n, k: (0, k), memory_space=pltpu.VMEM),
            pl.BlockSpec((bk, bn), lambda n, k: (k, n), memory_space=pltpu.VMEM),
            pl.BlockSpec((bk // Q40_BLOCK_SIZE, bn), lambda n, k: (k, n),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bk, bk // Q40_BLOCK_SIZE), lambda n, k: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((M, bn), lambda n, k: (0, n), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((M, N), jnp.float32),
        interpret=interpret,
    )(xf, w.codes, w.scales.astype(jnp.float32), _expansion_matrix(bk))

    return out.reshape(*lead, N).astype(x.dtype)


def quant_matmul_sharded(plan, x: jax.Array, w: QuantizedWeight,
                         out_axis: str | None = None,
                         in_axis: str | None = None, *,
                         interpret: bool = False,
                         fast: bool = False,
                         fused: bool = False) -> jax.Array | None:
    """Tensor-parallel Pallas quant matmul: the kernel inside a shard_map.

    The auto-sharder cannot partition a ``pallas_call``, so under a mesh plan
    the kernel runs manual-SPMD (same pattern as
    ops.flash_attention.flash_attention_sharded). Two layouts, mirroring the
    reference's weight slicers:

    * **row-split** (``out_axis``; reference sliceRowMatmul,
      nn-core.cpp:207-217): the K-major planes shard their N axis; each device
      computes its slice of the output features, zero collectives.
    * **col-split** (``in_axis``; reference sliceColMatmul,
      nn-core.cpp:219-230): planes shard K, activations shard their feature
      axis, and a ``psum`` reduces the partial sums — the reference's
      SYNC_NODE_SLICES + OP_MERGE_ADD pair in one collective.

    When the named axis doesn't resolve on this mesh (or the dim isn't
    divisible — e.g. wk/wv under KV replication), the weight is replicated and
    every device runs the full kernel, matching what param_shardings did at
    load time. Returns ``None`` only when the *local* shapes don't fit the
    kernel's tile grid (caller falls back to the XLA dequant+dot path).
    """
    from jax.sharding import PartitionSpec as P

    assert x.ndim == 3 and w.codes.ndim == 2, (x.shape, w.codes.shape)
    assert (out_axis is None) or (in_axis is None)
    B, T, K = x.shape
    N = w.out_features

    def _axis_n(sz: int, logical: str | None):
        """Mesh axis for a logical name, or None when it can't divide ``sz``
        — MeshPlan.sharding_for's degradation rule, so the specs here always
        match the layout param_shardings chose at load time."""
        if logical is None:
            return None
        m = plan.resolve(logical)
        if m is None or sz % plan._axis_size(m) != 0:
            return None
        return m

    dp_ax = _axis_n(B, "batch")
    n_ax = _axis_n(N, out_axis)
    k_ax = _axis_n(K, in_axis) if n_ax is None else None

    def _sz(ax) -> int:
        return 1 if ax is None else plan._axis_size(ax)

    n_loc, k_loc = N // _sz(n_ax), K // _sz(k_ax)
    b_loc = B // _sz(dp_ax)
    local_w = QuantizedWeight(
        scales=jax.ShapeDtypeStruct((k_loc // Q40_BLOCK_SIZE, n_loc), jnp.float32),
        codes=jax.ShapeDtypeStruct((k_loc, n_loc), jnp.int8))
    if not (supports((b_loc, T, k_loc), local_w)
            or (fused
                and supports_decode((b_loc, T, k_loc), local_w, fast))):
        return None

    if k_ax is not None:
        from ..parallel.qcollectives import wire_psum

        def local(xl, sc, cd):
            # f32 partials so the cross-device reduction doesn't round in bf16
            # (fast mode keeps bf16 multiplies but its accumulator/output is
            # already f32, so the psum is f32 either way). wire_psum ships
            # Q80-quantized partials when --wire q80 is on (the reference's
            # quantized sync pipes; parallel/qcollectives.py).
            part = quant_matmul(xl.astype(jnp.float32),
                                QuantizedWeight(scales=sc, codes=cd),
                                interpret=interpret, fast=fast, fused=fused)
            return wire_psum(part, k_ax, plan._axis_size(k_ax))

        fn = shard_map(
            local, mesh=plan.mesh,
            in_specs=(P(dp_ax, None, k_ax), P(k_ax, None), P(k_ax, None)),
            out_specs=P(dp_ax, None, None), check_vma=False)
    else:
        def local(xl, sc, cd):
            return quant_matmul(xl, QuantizedWeight(scales=sc, codes=cd),
                                interpret=interpret, fast=fast, fused=fused)

        fn = shard_map(
            local, mesh=plan.mesh,
            in_specs=(P(dp_ax, None, None), P(None, n_ax), P(None, n_ax)),
            out_specs=P(dp_ax, None, n_ax), check_vma=False)
    return fn(x, w.scales, w.codes)


def pallas_mode_gate(fast: bool) -> dict | None:  # dlint: static-fn
    """The ONE mode/numerics gate for every Pallas kernel dispatch:
    ``DLLAMA_TPU_QUANT_KERNEL`` = ``auto`` (Pallas only for exact mode on
    TPU), ``pallas`` (force the tiled kernel; interpret mode off-TPU, the
    test path), ``fused`` (force the decode-shaped fused dequant-GEMV —
    the built-but-unpromoted serving candidate, à la turbo: never resolved
    from ``auto``), or ``xla`` (the fused-dequant XLA reference, also the
    kill switch for every kernel this gate guards). Returns the
    :func:`quant_matmul` kwargs (``interpret``, optionally ``fused``) or
    None. Consulted by ops.linear's single-device and sharded dispatch,
    the overlapped merge's :func:`pallas_local_choice`, the ragged paged
    attention entry (ops.paged_attention.kernel_choice), and the engine's
    wire pricing — one rule, so none of them can drift from what
    linear() dispatches (dlint rule ``pallas-gate`` machine-checks the
    routing)."""
    from .linear import _kernel_mode, _on_tpu  # lazy: linear imports us

    mode = _kernel_mode()
    if mode == "xla":
        return None
    if mode == "fused":
        return {"interpret": not _on_tpu(), "fused": True}
    if mode != "pallas" and (fast or not _on_tpu()):
        return None
    return {"interpret": mode == "pallas" and not _on_tpu()}


def wants_fused(kw: dict | None) -> bool:  # dlint: static-fn
    """Whether a :func:`pallas_mode_gate` result selects the decode-shaped
    fused kernel (trace-time env config, never a traced value)."""
    return kw is not None and kw.get("fused", False) is True


# dlint: static-fn (shape gate; w may carry ShapeDtypeStruct leaves)
def pallas_local_choice(x_shape: tuple[int, ...], w: QuantizedWeight,
                        fast: bool) -> dict | None:
    """:func:`pallas_mode_gate` + the shard-shape ``supports`` check —
    the per-shard kernel rule for the overlapped col-split merge
    (models.llama._overlapped_col_linear) and host-side pricing probes.
    ``w`` may carry ShapeDtypeStruct leaves."""
    kw = pallas_mode_gate(fast)
    if kw is None:
        return None
    if not (supports(tuple(x_shape), w)
            or (wants_fused(kw) and supports_decode(tuple(x_shape), w, fast))):
        return None
    return kw


# Largest M the un-tiled batch axis may take: x block + out block + dequant
# scratch must fit VMEM (~16MB) alongside double-buffered weight tiles.
MAX_M = 512


def supports(x_shape: tuple[int, ...], w: QuantizedWeight) -> bool:  # dlint: static-fn
    """Whether the kernel's tile grid covers these shapes."""
    K = x_shape[-1]
    M = 1
    for d in x_shape[:-1]:
        M *= d
    return (w.codes.ndim == 2
            and w.in_features == K
            and M <= MAX_M
            and _pick_block(w.out_features, BN_CANDIDATES, min_align=8) is not None
            and _pick_block(K, BK_CANDIDATES, min_align=Q40_BLOCK_SIZE) is not None)
