"""Pallas TPU kernel: Q40 weight-dequantizing matmul.

The TPU replacement for the reference's Q80×Q40 integer-dot kernels
(reference: matmul_Q80_Q40_F32, src/nn/nn-cpu-ops.cpp:229-447, and the
llamafile sgemm prefill path, SURVEY.md §2 #7): weights stream from HBM in
their K-major plane layout (int8 codes ``[K, N]`` + f32 scales ``[K/32, N]``)
and are dequantized in VMEM right before hitting the MXU — the dense weight
never exists in HBM, so the matmul moves ~3.5× fewer bytes than a dense-f32
weight would.

Kernel shape: ``y[M, N] = x[M, K] @ dequant(codes, scales)``

Grid ``(N // BN, K // BK)``; each step:

1. expands the step's scale block to ``[BK, BN]`` via a tiny MXU matmul with a
   constant 0/1 sublane-expansion matrix ``E[BK, BK/32]`` (this Mosaic
   toolchain rejects reshape-broadcast and ``jnp.repeat`` lowerings, and
   ``pltpu.repeat`` has tile-repeat — not element-repeat — semantics);
2. dequantizes codes on the VPU (``codes * sexp``);
3. accumulates ``x_blk @ wd`` into the revisited f32 output tile.

Both dots run at ``Precision.HIGHEST`` — measured ~2e-5 absolute error vs the
exact host oracle on real hardware (default MXU precision loses ~3e-3).
K-major layout is what makes every operand block-indexable: the out-major
layout needed narrow f16/f32 scale blocks or in-kernel dynamic slices, both
of which this Mosaic build refuses to lower.

Falls back to the XLA dequant+dot path (ops.linear) when shapes don't fit the
tile grid; parity is tested in tests/test_quant_matmul.py the way
nn-vulkan-test.cpp checks GPU ops against the CPU reference.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..parallel.api import shard_map
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..formats.quants import Q40_BLOCK_SIZE
from .linear import QuantizedWeight

_HIGHEST = jax.lax.Precision.HIGHEST


def _kernel(x_ref, codes_ref, scales_ref, expand_ref, out_ref, *, fast: bool):
    """One (n, k) grid step: out[M, BN] += x[M, BK] @ dequant(W[BK, BN]).

    ``fast=False`` (exact/parity mode): f32 dequant, both dots at
    ``Precision.HIGHEST`` (~6 bf16 MXU passes per dot) — matches the host
    oracle to ~2e-5.  ``fast=True`` (serving mode): dequant lands in bf16 and
    the main dot runs ONE default-precision MXU pass with f32 accumulation —
    the TPU analogue of the reference's integer-dot philosophy (Q80×Q40
    int8-dot with f32 per-block scale epilogue, nn-cpu-ops.cpp:229-447):
    low-precision multiplies, full-precision accumulate, scales applied at
    block granularity.
    """
    k = pl.program_id(1)

    # element-repeat each scale 32× along K (sublanes) as a 0/1 matmul; each
    # output is a single selected scale (no accumulation), so HIGHEST here
    # costs little and keeps exact-mode scales bit-clean
    sexp = jax.lax.dot_general(
        expand_ref[:], scales_ref[:],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32, precision=_HIGHEST)

    if fast:
        wd = codes_ref[:].astype(jnp.bfloat16) * sexp.astype(jnp.bfloat16)
        partial = jax.lax.dot_general(
            x_ref[:], wd,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:
        wd = codes_ref[:].astype(jnp.float32) * sexp
        partial = jax.lax.dot_general(
            x_ref[:], wd,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=_HIGHEST)

    @pl.when(k == 0)
    def _():
        out_ref[:] = partial

    @pl.when(k != 0)
    def _():
        out_ref[:] += partial


# default tile candidates, largest first (gemv_sweep picks these)
BN_CANDIDATES = (512, 256, 128)
BK_CANDIDATES = (512, 256, 128)


def _pick_block(dim: int, candidates: tuple[int, ...], min_align: int) -> int | None:
    """A 128-aligned block dividing ``dim``, or the whole dim (Mosaic allows a
    block equal to the array extent) when it at least meets ``min_align``."""
    for c in candidates:
        if dim % c == 0:
            return c
    if dim % min_align == 0:
        return dim
    return None


@functools.lru_cache(maxsize=8)
def _expansion_matrix(bk: int) -> np.ndarray:
    """0/1 matrix ``E[bk, bk/32]`` with ``E[32i:32(i+1), i] = 1``.

    Returns numpy (not jnp): this is called during traces, where caching a
    jnp constant would leak a tracer."""
    return np.kron(np.eye(bk // Q40_BLOCK_SIZE, dtype=np.float32),
                   np.ones((Q40_BLOCK_SIZE, 1), np.float32))


@functools.partial(jax.jit, static_argnames=("interpret", "fast", "bn", "bk"))
def quant_matmul(x: jax.Array, w: QuantizedWeight, *, interpret: bool = False,
                 fast: bool = False, bn: int | None = None,
                 bk: int | None = None) -> jax.Array:
    """``y[..., N] = x[..., K] @ dequant(w)`` via the Pallas kernel.

    ``fast=False``: ``x`` is cast to f32 for the dequantized dot (parity with
    the XLA exact path). ``fast=True``: bf16 operands, one MXU pass, f32
    accumulation (see _kernel). Leading dims flatten into M.  ``bn``/``bk``
    override the tile picks (tools/gemv_sweep.py measures the candidates).
    """
    *lead, K = x.shape
    N = w.out_features
    M = 1
    for d in lead:
        M *= d

    bn = bn or _pick_block(N, BN_CANDIDATES, min_align=8)
    bk = bk or _pick_block(K, BK_CANDIDATES, min_align=Q40_BLOCK_SIZE)
    if bn is None or bk is None:
        raise ValueError(f"shapes N={N}, K={K} do not fit the tile grid")
    if N % bn or K % bk or bk % Q40_BLOCK_SIZE:
        # overrides included: a non-dividing block would truncate the grid
        # and return uninitialized output columns
        raise ValueError(f"blocks bn={bn}, bk={bk} do not tile N={N}, K={K}")

    xf = x.reshape(M, K).astype(jnp.bfloat16 if fast else jnp.float32)
    grid = (N // bn, K // bk)

    out = pl.pallas_call(
        functools.partial(_kernel, fast=fast),
        grid=grid,
        in_specs=[
            pl.BlockSpec((M, bk), lambda n, k: (0, k), memory_space=pltpu.VMEM),
            pl.BlockSpec((bk, bn), lambda n, k: (k, n), memory_space=pltpu.VMEM),
            pl.BlockSpec((bk // Q40_BLOCK_SIZE, bn), lambda n, k: (k, n),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bk, bk // Q40_BLOCK_SIZE), lambda n, k: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((M, bn), lambda n, k: (0, n), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((M, N), jnp.float32),
        interpret=interpret,
    )(xf, w.codes, w.scales.astype(jnp.float32), _expansion_matrix(bk))

    return out.reshape(*lead, N).astype(x.dtype)


def quant_matmul_sharded(plan, x: jax.Array, w: QuantizedWeight,
                         out_axis: str | None = None,
                         in_axis: str | None = None, *,
                         interpret: bool = False,
                         fast: bool = False) -> jax.Array | None:
    """Tensor-parallel Pallas quant matmul: the kernel inside a shard_map.

    The auto-sharder cannot partition a ``pallas_call``, so under a mesh plan
    the kernel runs manual-SPMD (same pattern as
    ops.flash_attention.flash_attention_sharded). Two layouts, mirroring the
    reference's weight slicers:

    * **row-split** (``out_axis``; reference sliceRowMatmul,
      nn-core.cpp:207-217): the K-major planes shard their N axis; each device
      computes its slice of the output features, zero collectives.
    * **col-split** (``in_axis``; reference sliceColMatmul,
      nn-core.cpp:219-230): planes shard K, activations shard their feature
      axis, and a ``psum`` reduces the partial sums — the reference's
      SYNC_NODE_SLICES + OP_MERGE_ADD pair in one collective.

    When the named axis doesn't resolve on this mesh (or the dim isn't
    divisible — e.g. wk/wv under KV replication), the weight is replicated and
    every device runs the full kernel, matching what param_shardings did at
    load time. Returns ``None`` only when the *local* shapes don't fit the
    kernel's tile grid (caller falls back to the XLA dequant+dot path).
    """
    from jax.sharding import PartitionSpec as P

    assert x.ndim == 3 and w.codes.ndim == 2, (x.shape, w.codes.shape)
    assert (out_axis is None) or (in_axis is None)
    B, T, K = x.shape
    N = w.out_features

    def _axis_n(sz: int, logical: str | None):
        """Mesh axis for a logical name, or None when it can't divide ``sz``
        — MeshPlan.sharding_for's degradation rule, so the specs here always
        match the layout param_shardings chose at load time."""
        if logical is None:
            return None
        m = plan.resolve(logical)
        if m is None or sz % plan._axis_size(m) != 0:
            return None
        return m

    dp_ax = _axis_n(B, "batch")
    n_ax = _axis_n(N, out_axis)
    k_ax = _axis_n(K, in_axis) if n_ax is None else None

    def _sz(ax) -> int:
        return 1 if ax is None else plan._axis_size(ax)

    n_loc, k_loc = N // _sz(n_ax), K // _sz(k_ax)
    b_loc = B // _sz(dp_ax)
    local_w = QuantizedWeight(
        scales=jax.ShapeDtypeStruct((k_loc // Q40_BLOCK_SIZE, n_loc), jnp.float32),
        codes=jax.ShapeDtypeStruct((k_loc, n_loc), jnp.int8))
    if not supports((b_loc, T, k_loc), local_w):
        return None

    if k_ax is not None:
        from ..parallel.qcollectives import wire_psum

        def local(xl, sc, cd):
            # f32 partials so the cross-device reduction doesn't round in bf16
            # (fast mode keeps bf16 multiplies but its accumulator/output is
            # already f32, so the psum is f32 either way). wire_psum ships
            # Q80-quantized partials when --wire q80 is on (the reference's
            # quantized sync pipes; parallel/qcollectives.py).
            part = quant_matmul(xl.astype(jnp.float32),
                                QuantizedWeight(scales=sc, codes=cd),
                                interpret=interpret, fast=fast)
            return wire_psum(part, k_ax, plan._axis_size(k_ax))

        fn = shard_map(
            local, mesh=plan.mesh,
            in_specs=(P(dp_ax, None, k_ax), P(k_ax, None), P(k_ax, None)),
            out_specs=P(dp_ax, None, None), check_vma=False)
    else:
        def local(xl, sc, cd):
            return quant_matmul(xl, QuantizedWeight(scales=sc, codes=cd),
                                interpret=interpret, fast=fast)

        fn = shard_map(
            local, mesh=plan.mesh,
            in_specs=(P(dp_ax, None, None), P(None, n_ax), P(None, n_ax)),
            out_specs=P(dp_ax, None, n_ax), check_vma=False)
    return fn(x, w.scales, w.codes)


def pallas_mode_gate(fast: bool) -> dict | None:  # dlint: static-fn
    """The ONE mode/numerics gate for the sharded Pallas kernel: Pallas
    only for exact mode on TPU, or when forced
    (``DLLAMA_TPU_QUANT_KERNEL=pallas`` — interpret mode off-TPU, the
    test path). Returns the :func:`quant_matmul` kwargs (currently just
    ``interpret``) or None (XLA fused dequant+dot). Consulted by
    ops.linear._pallas_sharded, the overlapped merge's
    :func:`pallas_local_choice`, and the engine's wire pricing — one
    rule, so none of them can drift from what linear() dispatches."""
    from .linear import _kernel_mode, _on_tpu  # lazy: linear imports us

    mode = _kernel_mode()
    if mode == "xla":
        return None
    if mode != "pallas" and (fast or not _on_tpu()):
        return None
    return {"interpret": mode == "pallas" and not _on_tpu()}


# dlint: static-fn (shape gate; w may carry ShapeDtypeStruct leaves)
def pallas_local_choice(x_shape: tuple[int, ...], w: QuantizedWeight,
                        fast: bool) -> dict | None:
    """:func:`pallas_mode_gate` + the shard-shape ``supports`` check —
    the per-shard kernel rule for the overlapped col-split merge
    (models.llama._overlapped_col_linear) and host-side pricing probes.
    ``w`` may carry ShapeDtypeStruct leaves."""
    kw = pallas_mode_gate(fast)
    if kw is None or not supports(tuple(x_shape), w):
        return None
    return kw


# Largest M the un-tiled batch axis may take: x block + out block + dequant
# scratch must fit VMEM (~16MB) alongside double-buffered weight tiles.
MAX_M = 512


def supports(x_shape: tuple[int, ...], w: QuantizedWeight) -> bool:  # dlint: static-fn
    """Whether the kernel's tile grid covers these shapes."""
    K = x_shape[-1]
    M = 1
    for d in x_shape[:-1]:
        M *= d
    return (w.codes.ndim == 2
            and w.in_features == K
            and M <= MAX_M
            and _pick_block(w.out_features, BN_CANDIDATES, min_align=8) is not None
            and _pick_block(K, BK_CANDIDATES, min_align=Q40_BLOCK_SIZE) is not None)
