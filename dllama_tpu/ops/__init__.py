"""Compute ops: quantized linear, norms, attention, sampling.

This package is the TPU replacement for the reference's op-kernel surface
(reference: src/nn/nn-cpu-ops.cpp dispatch table, SURVEY.md §2.3): instead of
12 op codes × quant-variant function pointers, the ops are composable JAX
functions that XLA fuses, with Pallas kernels for the quantized matmul and
attention hot paths.
"""

from .linear import QuantizedWeight, linear, quantize_weight_q40, fake_quant_q80  # noqa: F401
from .norms import rms_norm, rms_norm_per_head  # noqa: F401
from .attention import attention  # noqa: F401
