"""Pallas TPU kernel: ragged paged attention over the block-table KV pool.

The TPU-native replacement for ``_paged_layer_step``'s gather+oracle pair
(models/llama.py): the XLA path materializes each row's full dense logical
cache per layer per step (``pool[tables]`` writes ``[B, M, n_kv, bs, hd]``
to HBM, then the oracle reads it straight back), so the paged program
family pays the KV bytes twice plus a scatter's worth of write bandwidth.
This kernel is the "Ragged Paged Attention" shape (PAPERS.md, arxiv
2604.15464): the block table rides in as a scalar-prefetch operand and the
kernel's *index maps* walk it directly — grid step ``(b, h, m)`` DMAs
physical block ``tables[b, m]`` of the pool straight into VMEM, so the
dense logical cache never exists in HBM at all.

Semantics are exactly the gather+oracle pair's, bit for bit:

* **ragged rows** — every batch row sits at its own depth; query row ``r``
  (GQA-folded, source position ``pos0[b] + r // kv_mul``) sees cache
  columns ``s <= pos0[b] + r // kv_mul``, the oracle's position mask;
* **partial tail block** — the row's newest block is masked per position,
  not per block, so a mid-block write point behaves identically;
* **null block 0** — unallocated table tail entries point at physical
  block 0 (runtime/kvblocks.py); its rows are gathered and then position-
  masked to zero weight, the same argument as the oracle's padded tails.

Per (b, h) instance the kernel stages per-block score stripes and f32
value rows into VMEM scratch and runs the oracle's own epilogue (scale →
mask → softmax → weighted sum) on the assembled arrays, so the math is
op-for-op the oracle's and interpret-mode parity is bitwise
(tests/test_paged_attention.py drives scrambled tables, CoW-redirects,
T=1/T=16 and non-128-aligned head dims against the dense reference).

Mode selection routes through :func:`quant_matmul.pallas_mode_gate` — the
ONE kernel gate (dlint rule ``pallas-gate``): ``auto`` enables the kernel
on TPU backends, ``DLLAMA_TPU_QUANT_KERNEL=pallas``/``fused`` force it
(interpret mode off-TPU, the test path), ``xla`` is the kill switch back
to the gather+oracle path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(tables_ref, pos_ref, q_ref, k_ref, v_ref, out_ref,
            kbuf_ref, vbuf_ref, *, bs: int, kv_mul: int, hd: int):
    """One (b, h, m) grid step over physical block ``tables[b, m]``.

    ``kbuf_ref`` / ``vbuf_ref [S, hd]`` assemble the (b, h) instance's f32
    logical K/V rows (S-major, so the per-block writes are sublane
    slices); the last block runs the oracle's own epilogue — score gemm at
    the oracle's ``(TQ, hd) x (hd, S)`` contraction shape, scale, position
    mask, softmax over S, value gemm — the same ops in the same order at
    the same shapes as ops.attention.attention, which is what makes the
    kernel bit-identical rather than merely close (an online-softmax
    rewrite, or even per-block score dots, reassociate the reductions and
    drift by ulps)."""
    b = pl.program_id(0)
    m = pl.program_id(2)
    nm = pl.num_programs(2)

    kbuf_ref[pl.ds(m * bs, bs), :] = k_ref[0, 0].astype(jnp.float32)
    vbuf_ref[pl.ds(m * bs, bs), :] = v_ref[0, 0].astype(jnp.float32)

    @pl.when(m == nm - 1)
    def _():
        s_total = nm * bs
        q = q_ref[0, 0].astype(jnp.float32)      # (TQ, hd)
        tq = q.shape[0]
        scores = jax.lax.dot_general(
            q, kbuf_ref[:], dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # (TQ, S)
        scores = scores / jnp.sqrt(jnp.float32(hd))
        # the oracle's position mask: column s visible to query row r iff
        # s <= pos0 + r // kv_mul (ragged depths, partial tail blocks and
        # null-block garbage all handled by this one rule)
        row_t = jax.lax.broadcasted_iota(jnp.int32, (tq, s_total), 0) // kv_mul
        col = jax.lax.broadcasted_iota(jnp.int32, (tq, s_total), 1)
        scores = jnp.where(col <= pos_ref[b] + row_t, scores, -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1)
        out_ref[0, 0] = jax.lax.dot_general(
            probs, vbuf_ref[:],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # (TQ, hd)


# VMEM budget for the assembled per-(b, h) resident set: K + V scratch
# [S, hd] plus the epilogue's score matrix [TQ, S], all f32.
_VMEM_BUDGET = 12 * 1024 * 1024

MAX_TQ = 512  # folded query rows per (b, h) instance


def supports(q_shape: tuple[int, ...], n_kv: int, n_blocks_seq: int,
             block_size: int) -> bool:  # dlint: static-fn
    """Whether the kernel covers this paged geometry (caller falls back to
    the gather+oracle path otherwise)."""
    B, T, n_heads, D = q_shape
    if n_heads % n_kv:
        return False
    tq = T * (n_heads // n_kv)
    s = n_blocks_seq * block_size
    scratch = 4 * s * (tq + 2 * D)
    return (D % 8 == 0 and block_size % 8 == 0 and 0 < tq <= MAX_TQ
            and scratch <= _VMEM_BUDGET)


def kernel_choice(q_shape: tuple[int, ...], n_kv: int, n_blocks_seq: int,
                  block_size: int) -> dict | None:  # dlint: static-fn
    """The paged-attention kernel gate: mode selection routes through
    :func:`quant_matmul.pallas_mode_gate` (the ONE gate; fast=False — the
    kernel is bit-identical, so there is no fast/exact numerics split to
    pick), plus the shape predicate and the plan-free requirement (the
    paged forward auto-shards under a mesh plan, and the auto-sharder
    cannot partition a ``pallas_call``). Returns
    :func:`paged_ragged_attention` kwargs or None (gather+oracle)."""
    from ..parallel.api import current_plan
    from .quant_matmul import pallas_mode_gate

    kw = pallas_mode_gate(False)
    if kw is None or current_plan() is not None:
        return None
    if not supports(q_shape, n_kv, n_blocks_seq, block_size):
        return None
    return {"interpret": kw["interpret"]}


@functools.partial(jax.jit, static_argnames=("head_dim", "interpret"))
def paged_ragged_attention(q: jax.Array, k_pool: jax.Array,
                           v_pool: jax.Array, tables: jax.Array,
                           positions: jax.Array, head_dim: int, *,
                           interpret: bool = False) -> jax.Array:
    """Causal GQA attention of ``q [B, T, n_heads, hd]`` over the paged
    pool ``k/v_pool [n_blocks, n_kv, bs, hd]`` through block ``tables
    [B, M]`` (0 = null block), with per-row absolute positions
    ``positions [B, T]`` (affine per row, the model's invariant).

    Value-identical (bitwise, in f32) to::

        gathered = pool[tables]           # the dense logical cache
        view = moveaxis(gathered, 2, 1).reshape(B, n_kv, M*bs, hd)
        attention(q, view_k, view_v, positions, head_dim)
    """
    B, T, n_heads, D = q.shape
    n_kv, bs = k_pool.shape[1], k_pool.shape[2]
    M = tables.shape[1]
    kv_mul = n_heads // n_kv
    tq = T * kv_mul

    q_g = (q.reshape(B, T, n_kv, kv_mul, D)
            .transpose(0, 2, 1, 3, 4)
            .reshape(B, n_kv, tq, D)
            .astype(jnp.float32))
    pos0 = jnp.asarray(positions, jnp.int32)[:, 0]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # tables, pos0
        grid=(B, n_kv, M),
        in_specs=[
            pl.BlockSpec((1, 1, tq, D),
                         lambda b, h, m, tbl, pos: (b, h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bs, D),
                         lambda b, h, m, tbl, pos: (tbl[b, m], h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bs, D),
                         lambda b, h, m, tbl, pos: (tbl[b, m], h, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, tq, D),
                               lambda b, h, m, tbl, pos: (b, h, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((M * bs, D), jnp.float32),   # assembled f32 keys
            pltpu.VMEM((M * bs, D), jnp.float32),   # assembled f32 values
        ],
    )
    out = pl.pallas_call(
        functools.partial(_kernel, bs=bs, kv_mul=kv_mul, hd=head_dim),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, n_kv, tq, D), jnp.float32),
        interpret=interpret,
    )(jnp.asarray(tables, jnp.int32), pos0, q_g, k_pool, v_pool)

    return (out.reshape(B, n_kv, T, kv_mul, D)
               .transpose(0, 2, 1, 3, 4)
               .reshape(B, T, n_heads, D)
               .astype(q.dtype))
