"""Linear/matmul ops over dense or Q40-quantized weights.

The quantized path replaces the reference's Q80×Q40 integer-dot kernels
(reference: matmul_Q80_Q40_F32, src/nn/nn-cpu-ops.cpp:229-447 and the
llamafile sgemm prefill path): weights stay in the Q40 block domain (separated
scale/code planes from :func:`dllama_tpu.formats.quants.unpack_q40`), and the
matmul dequantizes on the fly. On TPU the XLA path below lets the compiler
fuse dequantization into the MXU matmul; a hand-tiled Pallas kernel lives in
:mod:`dllama_tpu.ops.quant_matmul` for the cases XLA schedules poorly.

``fake_quant_q80`` mirrors the reference's activation-quantization ("sync
type" Q80 casts, llm.cpp:258-265): quantize-dequantize in-graph so the
numerical effect of the wire quantization is reproduced even though TPU
collectives move bf16/f32.
"""

from __future__ import annotations

from typing import NamedTuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..formats.quants import Q40_BLOCK_SIZE, Q80_BLOCK_SIZE


class QuantizedWeight(NamedTuple):
    """Q40 weight as TPU-friendly planes.

    ``scales``: float16 ``[out, in // 32]`` block scales.
    ``codes``: int8 ``[out, in]`` centered 4-bit codes in [-8, 7].

    Logical value: ``w[o, i] = codes[o, i] * scales[o, i // 32]``
    (reference block layout: NnBlockQ40, src/nn/nn-quants.hpp:64-67).
    """

    scales: jax.Array
    codes: jax.Array

    @property
    def out_features(self) -> int:
        return self.codes.shape[-2]

    @property
    def in_features(self) -> int:
        return self.codes.shape[-1]


Weight = Union[jax.Array, QuantizedWeight]


def quantize_weight_q40(w: np.ndarray) -> QuantizedWeight:
    """Quantize a dense ``[out, in]`` float32 weight to Q40 planes (host-side)."""
    from ..formats.quants import quantize_q40, unpack_q40

    out, in_ = w.shape
    buf = quantize_q40(np.ascontiguousarray(w, dtype=np.float32).reshape(-1))
    scales, codes = unpack_q40(buf, out * in_)
    return QuantizedWeight(
        scales=jnp.asarray(scales.reshape(out, in_ // Q40_BLOCK_SIZE)),
        codes=jnp.asarray(codes.reshape(out, in_)),
    )


def dequantize_weight(w: QuantizedWeight, dtype=jnp.float32) -> jax.Array:
    """Expand Q40 planes to a dense ``[..., out, in]`` array."""
    scales = jnp.repeat(w.scales.astype(dtype), Q40_BLOCK_SIZE, axis=-1)
    return w.codes.astype(dtype) * scales


def linear(x: jax.Array, w: Weight) -> jax.Array:
    """``y[..., out] = x[..., in] @ w.T`` with dense or Q40 weight.

    Weights use the reference's on-disk ``[out, in]`` orientation (row-major,
    llm.cpp matmul weights), so TP row/col split semantics stay auditable:
    row-split = shard ``out``, col-split = shard ``in``.
    """
    if isinstance(w, QuantizedWeight):
        wd = dequantize_weight(w, dtype=x.dtype)
    else:
        wd = w.astype(x.dtype)
    return jax.lax.dot_general(
        x, wd,
        dimension_numbers=(((x.ndim - 1,), (wd.ndim - 1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)


def fake_quant_q80(x: jax.Array) -> jax.Array:
    """In-graph Q80 quantize→dequantize of the trailing axis.

    Numerically mirrors the reference *runtime* path quantizeF32toQ80 +
    dequantizeQ80toF32 (src/nn/nn-quants.cpp:158-192 scalar): the int8 code is
    ``roundf(x / d)`` with the UNROUNDED f32 scale ``d = absmax/127`` (half
    away from zero), while the dequant multiply uses the f16-rounded stored
    scale. Used when the engine runs in "sync q80" parity mode so activations
    passing a sync point carry the same quantization the reference's wire
    format applies.
    """
    orig_shape = x.shape
    orig_dtype = x.dtype
    n = orig_shape[-1]
    assert n % Q80_BLOCK_SIZE == 0, n
    g = x.astype(jnp.float32).reshape(*orig_shape[:-1], n // Q80_BLOCK_SIZE, Q80_BLOCK_SIZE)
    amax = jnp.max(jnp.abs(g), axis=-1, keepdims=True)
    d = amax / 127.0
    inv = jnp.where(d != 0, 1.0 / jnp.where(d != 0, d, 1.0), 0.0)
    scaled = g * inv
    q = jnp.sign(scaled) * jnp.floor(jnp.abs(scaled) + 0.5)  # roundf semantics
    d16 = d.astype(jnp.float16).astype(jnp.float32)
    return (q * d16).reshape(orig_shape).astype(orig_dtype)
